"""Wall-clock block timing (with MFU accounting), jax.profiler traces, and
the shared latency-percentile formula."""

from __future__ import annotations

import contextlib
import math
import os
import threading
from typing import Optional, Sequence

import time


def nearest_rank_percentile(sorted_vals: Sequence[float],
                            q: float) -> Optional[float]:
    """Nearest-rank percentile over an ASCENDING sequence (None when empty).

    The ONE percentile formula for serving latency: the service's live
    `/stats`, the offline report's serve section, and `tools/loadgen.py`
    all route through it, so their p50/p95/p99 rows can never disagree on
    the same samples (same contract as `StepTimer.summary` for MFU)."""
    if not sorted_vals:
        return None
    idx = max(0, min(len(sorted_vals) - 1,
                     math.ceil(q * len(sorted_vals)) - 1))
    return float(sorted_vals[idx])


class StepTimer:
    """Wall-clock series over jitted blocks -> throughput summary."""

    def __init__(self, clock=time.perf_counter):
        self._clock = clock
        self._t0 = None
        self.block_seconds = []

    def start(self) -> None:
        self._t0 = self._clock()

    def stop(self) -> float:
        if self._t0 is None:
            raise RuntimeError("StepTimer.stop() before start()")
        dt = self._clock() - self._t0
        self._t0 = None
        self.block_seconds.append(dt)
        return dt

    def summary(self, steps_per_block: int, batch: int,
                flops_per_step: float = 0.0,
                peak_flops: float = 0.0) -> dict:
        """Throughput summary; pass `flops_per_step` (useful FLOPs of one
        optimization step — e.g. 3 x forward-FLOPs x EOT x batch from
        `jit(fwd).lower(...).compile().cost_analysis()["flops"]`) and the
        chip's `peak_flops` to get a defensible `mfu` row (SURVEY.md §6).
        This is the ONE MFU formula in the repo: `bench.py` and the offline
        report CLI (`observe/report.py`) both route through it."""
        total = float(sum(self.block_seconds))
        n_steps = steps_per_block * len(self.block_seconds)
        out = {
            "blocks": len(self.block_seconds),
            "total_seconds": round(total, 3),
            "steps_per_sec": round(n_steps / total, 3) if total else 0.0,
            "images_per_sec": round(n_steps * batch / total, 3) if total else 0.0,
        }
        if flops_per_step and peak_flops and total:
            out["achieved_tflops"] = round(
                n_steps * flops_per_step / total / 1e12, 2)
            out["mfu"] = round(n_steps * flops_per_step / total / peak_flops, 4)
        return out


@contextlib.contextmanager
def trace(trace_dir: Optional[str]):
    """`jax.profiler` trace scope; no-op when `trace_dir` is falsy.

    Produces a tensorboard-loadable trace of every XLA computation launched
    in the scope — the rebuild's answer to the reference's absent profiling
    (SURVEY.md §5)."""
    if not trace_dir:
        yield
        return
    import jax

    jax.profiler.start_trace(trace_dir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()


# One capture at a time per process: `jax.profiler.start_trace` is a
# process-global toggle, so overlapping captures would corrupt each other.
_PROFILE_LOCK = threading.Lock()


def capture_profile(out_dir: Optional[str], duration_s: float = 0.5,
                    max_duration_s: float = 10.0,
                    sleep=time.sleep) -> Optional[str]:
    """On-demand bounded-duration `jax.profiler` device-trace capture.

    Traces everything the process launches for (clamped) `duration_s`
    into ``<out_dir>/profile`` and emits a ``profile.captured`` event on
    the active EventLog. Serving keeps answering while the capture runs —
    this is the `POST /profile` / farm SIGUSR2 hook, not a pause button.
    Returns the trace dir, or None when `out_dir` is falsy or another
    capture is already in flight (the lock is never waited on: a second
    concurrent request is refused, not queued)."""
    if not out_dir:
        return None
    if not _PROFILE_LOCK.acquire(blocking=False):
        return None
    try:
        dur = max(0.05, min(float(duration_s), float(max_duration_s)))
        trace_dir = os.path.join(out_dir, "profile")
        t0 = time.perf_counter()
        with trace(trace_dir):
            sleep(dur)
        from dorpatch_tpu.observe import events

        events.record_event("profile.captured", dir=trace_dir,
                            duration_s=round(dur, 3),
                            wall_s=round(time.perf_counter() - t0, 3))
        return trace_dir
    finally:
        _PROFILE_LOCK.release()
