"""Victim models (flax, NHWC) + timm-checkpoint conversion."""

from dorpatch_tpu.models.registry import Victim, get_model, resolve_arch, checkpoint_path  # noqa: F401
