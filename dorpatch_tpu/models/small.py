"""Small CIFAR victim (ResNet-18 style) for the sweep benchmark config.

Used by BASELINE.md Config 4 (CIFAR-10 patch-size x sparsity sweep). This is
our own model (no timm-checkpoint contract): a CIFAR-style ResNet-18 with a
3x3 stem, GroupNorm instead of BatchNorm (no mutable batch stats inside the
jitted attack loop), NHWC.
"""

from __future__ import annotations

from typing import Sequence

import flax.linen as nn
import jax.numpy as jnp


class GroupNorm8(nn.Module):
    """GroupNorm(8) that keeps sub-f32 activations at their own dtype.

    float32 inputs go through flax's `nn.GroupNorm` verbatim (applied
    functionally against this module's own `scale`/`bias`, so the f32
    parameter tree and numerics are bit-identical to declaring it inline).
    Lower-precision inputs — the bf16 certify bank casts the victim's
    params and images down — use `fused_gn.gn_preserve_dtype`: f32
    statistics, input-dtype normalization. flax would materialize the
    whole normalize chain in f32, which both doubles the real HBM traffic
    of the big intermediates and prices the bf16 defense programs above
    their f32 twins in the DP301 baseline bank.
    """

    @nn.compact
    def __call__(self, x):
        c = x.shape[-1]
        scale = self.param("scale", nn.initializers.ones, (c,), jnp.float32)
        bias = self.param("bias", nn.initializers.zeros, (c,), jnp.float32)
        if x.dtype == jnp.float32:
            # parent=None keeps this an unbound functional apply; without
            # it flax registers a child module in GroupNorm8's own scope.
            return nn.GroupNorm(num_groups=8, parent=None).apply(
                {"params": {"scale": scale, "bias": bias}}, x)
        from dorpatch_tpu.ops import fused_gn
        return fused_gn.gn_preserve_dtype(x, scale, bias, 8, eps=1e-6)


class BasicBlock(nn.Module):
    features: int
    stride: int = 1

    @nn.compact
    def __call__(self, x):
        y = nn.Conv(self.features, (3, 3), (self.stride, self.stride), padding=1,
                    use_bias=False, name="conv1")(x)
        y = nn.relu(GroupNorm8(name="norm1")(y))
        y = nn.Conv(self.features, (3, 3), padding=1, use_bias=False, name="conv2")(y)
        y = GroupNorm8(name="norm2")(y)
        if x.shape[-1] != self.features or self.stride != 1:
            x = nn.Conv(self.features, (1, 1), (self.stride, self.stride),
                        use_bias=False, name="proj")(x)
            x = GroupNorm8(name="proj_norm")(x)
        return nn.relu(x + y)


class CifarResNet18(nn.Module):
    num_classes: int = 10
    stage_sizes: Sequence[int] = (2, 2, 2, 2)

    @nn.compact
    def __call__(self, x, mode: str = "full"):
        """mode="full": logits from images. mode="stem": only the bias-free
        stem conv's PRE-norm output (the linear cache of the masked-stem
        incremental certify path, `ops/stem_fold.py` — GroupNorm is a
        global nonlinearity, so the shareable-across-masks activation must
        be cut before it). mode="trunk": `x` is already a stem output; run
        everything after the stem conv. `full(x) == trunk(stem(x))`
        exactly, and the three modes share one parameter tree (a mode that
        skips a submodule simply leaves its params unread)."""
        if mode not in ("full", "stem", "trunk"):
            raise ValueError(f"mode={mode!r} (use 'full', 'stem' or 'trunk')")
        if mode != "trunk":
            x = nn.Conv(64, (3, 3), padding=1, use_bias=False, name="stem")(x)
            if mode == "stem":
                return x
        x = nn.relu(GroupNorm8(name="stem_norm")(x))
        features = 64
        for si, depth in enumerate(self.stage_sizes):
            for bi in range(depth):
                stride = 2 if (bi == 0 and si > 0) else 1
                x = BasicBlock(features, stride, name=f"stage{si}_block{bi}")(x)
            features *= 2
        x = jnp.mean(x, axis=(1, 2))
        return nn.Dense(self.num_classes, name="head")(x)
