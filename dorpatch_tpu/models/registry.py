"""Victim-model factory (the reference's model layer, `/root/reference/utils.py:47-78`).

Resolves an architecture name by substring against the supported timm model
names (as the reference does), loads + converts the PatchCleanser checkpoint
if present, and returns a jittable apply function operating on [0,1] NHWC
images with the mean/std=0.5 normalization folded in (the reference's
`NormModel` wrapper).
"""

from __future__ import annotations

import os
from typing import Any, Callable, NamedTuple

import jax
import jax.numpy as jnp

from dorpatch_tpu import observe
from dorpatch_tpu.config import NUM_CLASSES

# Supported timm model names, matched by substring as in the reference.
TIMM_MODELS = (
    "resnetv2_50x1_bit_distilled",
    "vit_base_patch16_224",
    "resmlp_24_distilled_224",
)


class Victim(NamedTuple):
    """A victim classifier: `logits = apply(params, images01)`.

    `apply` expects NHWC float images in [0,1] (normalization folded in) and
    is safe to jit/vmap/grad-through. `incremental` is the family's
    mask-aware incremental-inference engine (`models.vit.TokenPrunedViT`
    for the ViT families, `models.resmlp.MixerPrunedResMLP` for the
    ResMLP families, `ops.stem_fold.StemFoldEngine` for the conv
    families, None where no engine exists) — `defense.build_defenses`
    consumes it for `DefenseConfig.incremental`.
    """

    name: str
    apply: Callable[[Any, jax.Array], jax.Array]
    params: Any
    num_classes: int
    from_checkpoint: bool
    incremental: Any = None


def resolve_arch(arch: str) -> str:
    """Substring match against supported timm names (`utils.py:55-57`),
    plus the framework's own small CIFAR victim for sweep configs."""
    if arch in ("resnet18", "cifar_resnet18"):
        return "cifar_resnet18"
    if arch == "cifar_vit":
        return "cifar_vit"
    if arch == "cifar_resmlp":
        return "cifar_resmlp"
    for tm in TIMM_MODELS:
        if arch in tm:
            return tm
    raise ValueError(
        f"unknown architecture {arch!r}; supported: "
        f"{TIMM_MODELS + ('cifar_resnet18', 'cifar_vit', 'cifar_resmlp')}")


def checkpoint_path(model_dir: str, dataset: str, timm_name: str) -> str:
    """The PatchCleanser-release checkpoint naming contract (`utils.py:59-61`)."""
    return os.path.join(model_dir, dataset, f"{timm_name}_cutout2_128_{dataset}.pth")


def build_bare_model(timm_name: str, num_classes: int, gn_impl: str = "auto"):
    """Public bare-module builder (no normalization fold, no checkpoint):
    the flax module for a canonical arch name — used by `train.py`, which
    needs the raw module to init/train before exporting."""
    return _build_flax(timm_name, num_classes, gn_impl=gn_impl)


def _build_flax(timm_name: str, num_classes: int, gn_impl: str = "auto"):
    if timm_name == "resnetv2_50x1_bit_distilled":
        from dorpatch_tpu.models.resnetv2 import resnetv2_50x1

        return resnetv2_50x1(num_classes, gn_impl=gn_impl)
    if timm_name == "vit_base_patch16_224":
        from dorpatch_tpu.models.vit import vit_base_patch16

        return vit_base_patch16(num_classes)
    if timm_name == "resmlp_24_distilled_224":
        from dorpatch_tpu.models.resmlp import resmlp_24

        return resmlp_24(num_classes)
    if timm_name == "cifar_resnet18":
        from dorpatch_tpu.models.small import CifarResNet18

        return CifarResNet18(num_classes=num_classes)
    if timm_name == "cifar_vit":
        from dorpatch_tpu.models.vit import vit_cifar

        return vit_cifar(num_classes)
    if timm_name == "cifar_resmlp":
        from dorpatch_tpu.models.resmlp import resmlp_cifar

        return resmlp_cifar(num_classes)
    raise NotImplementedError(timm_name)


def init_program(timm_name: str, num_classes: int, img_size: int,
                 gn_impl: str = "auto", model=None):
    """The jitted parameter-init entry point plus abstract example args.

    One builder shared by `get_model` (which passes its already-built
    `model` and calls the program with a concrete key) and the program
    auditor (which traces it abstractly — `analysis/entrypoints.py`), so
    the audited initializer can never drift from the one production
    compiles."""
    if model is None:
        model = _build_flax(timm_name, num_classes, gn_impl=gn_impl)
    # jit the initializer: eager init dispatches hundreds of tiny ops,
    # which is pathologically slow over remote-tunneled TPU backends
    program = observe.timed_first_call(
        jax.jit(model.init), f"model.init.{timm_name}", recompile_budget=1)
    example_args = (jax.ShapeDtypeStruct((2,), jnp.uint32),
                    jax.ShapeDtypeStruct((1, img_size, img_size, 3),
                                         jnp.float32))
    return program, example_args


def _normalize(images01):
    """The folded victim normalization (`NormModel`, mean/std = 0.5)."""
    return (images01 - 0.5) / 0.5


#: d(normalized)/d(image01): the linear scale the masked-stem fold uses to
#: express the occlusion fill's delta in normalized space.
_NORM_SCALE = 2.0


def incremental_engine(timm_name: str, model, img_size: int):
    """The family's mask-aware incremental-inference engine, or None.

    ViT families get the token-pruned engine (clean KV cache + dirty-token
    recompute, `models/vit.py`); conv families get the exact masked-stem
    fold (`ops/stem_fold.py`); ResMLP gets the mixer-pruned engine
    (`models/resmlp.py`: cached block inputs + skinny dirty-row slice of
    the token-mixing matmul, margin-gated like the ViT engine).
    """
    if timm_name in ("vit_base_patch16_224", "cifar_vit"):
        from dorpatch_tpu.models.vit import TokenPrunedViT

        if img_size % model.patch_size:
            return None  # non-grid-aligned input: no token geometry
        return TokenPrunedViT(model, img_size, normalize=_normalize)
    if timm_name in ("resmlp_24_distilled_224", "cifar_resmlp"):
        from dorpatch_tpu.models.resmlp import MixerPrunedResMLP

        if img_size % model.patch_size:
            return None  # non-grid-aligned input: no token geometry
        return MixerPrunedResMLP(model, img_size, normalize=_normalize)
    if timm_name == "cifar_resnet18":
        from dorpatch_tpu.ops.stem_fold import StemFoldEngine

        return StemFoldEngine(
            model, img_size,
            kernel_fn=lambda p: p["params"]["stem"]["kernel"],
            kernel_hw=3, strides=(1, 1), pads=((1, 1), (1, 1)),
            normalize=_normalize, norm_scale=_NORM_SCALE)
    if timm_name == "resnetv2_50x1_bit_distilled":
        import jax.numpy as jnp_mod

        from dorpatch_tpu.ops.stem_fold import StemFoldEngine, same_pads

        def std_kernel(p, _eps=1e-8):
            # the StdConv weight standardization (models/resnetv2.py),
            # folded so the delta conv uses the same effective kernel
            kern = p["params"]["stem_conv"]["kernel"]
            mean = jnp_mod.mean(kern, axis=(0, 1, 2), keepdims=True)
            var = jnp_mod.var(kern, axis=(0, 1, 2), keepdims=True)
            return (kern - mean) * jax.lax.rsqrt(var + _eps)

        return StemFoldEngine(
            model, img_size, kernel_fn=std_kernel, kernel_hw=7,
            strides=(2, 2),
            pads=(same_pads(img_size, 7, 2), same_pads(img_size, 7, 2)),
            normalize=_normalize, norm_scale=_NORM_SCALE)
    return None


def _convert(timm_name: str, state_dict):
    if timm_name == "resnetv2_50x1_bit_distilled":
        from dorpatch_tpu.models.convert import convert_resnetv2

        return convert_resnetv2(state_dict)
    if timm_name == "vit_base_patch16_224":
        from dorpatch_tpu.models.convert import convert_vit

        return convert_vit(state_dict)
    if timm_name == "resmlp_24_distilled_224":
        from dorpatch_tpu.models.convert import convert_resmlp

        return convert_resmlp(state_dict)
    if timm_name == "cifar_resnet18":
        from dorpatch_tpu.models.convert import convert_cifar_resnet18

        return convert_cifar_resnet18(state_dict)
    if timm_name == "cifar_vit":
        from dorpatch_tpu.models.convert import convert_vit
        from dorpatch_tpu.models.vit import CIFAR_VIT

        return convert_vit(state_dict, depth=CIFAR_VIT["depth"],
                           num_heads=CIFAR_VIT["num_heads"])
    raise NotImplementedError(timm_name)


def get_model(
    dataset: str,
    arch: str = "resnetv2",
    model_dir: str = "pretrained_models/",
    img_size: int = 224,
    seed: int = 0,
    gn_impl: str = "auto",
) -> Victim:
    """Build the victim for a dataset (`utils.py:47-63` + `NormModel`).

    Loads + converts `<model_dir>/<dataset>/<timm>_cutout2_128_<dataset>.pth`
    when present; otherwise falls back to deterministic random initialization
    (for environments without the PatchCleanser checkpoints — synthetic mode,
    tests, benchmarks).

    gn_impl selects the GroupNorm+ReLU implementation for ResNetV2 victims
    (see `models.resnetv2.GroupNormRelu`); other architectures ignore it.
    """
    timm_name = resolve_arch(arch)
    num_classes = NUM_CLASSES[dataset]
    model = _build_flax(timm_name, num_classes, gn_impl=gn_impl)

    ckpt = checkpoint_path(model_dir, dataset, timm_name)
    if os.path.exists(ckpt):
        from dorpatch_tpu.models.convert import load_state_dict

        params = _convert(timm_name, load_state_dict(ckpt))
        from_checkpoint = True
    else:
        dummy = jnp.zeros((1, img_size, img_size, 3), jnp.float32)
        program, _ = init_program(timm_name, num_classes, img_size,
                                  gn_impl=gn_impl, model=model)
        params = program(jax.random.PRNGKey(seed), dummy)
        from_checkpoint = False

    def apply(params, images01):
        return model.apply(params, _normalize(images01))

    return Victim(
        name=timm_name,
        apply=apply,
        params=params,
        num_classes=num_classes,
        from_checkpoint=from_checkpoint,
        incremental=incremental_engine(timm_name, model, img_size),
    )
