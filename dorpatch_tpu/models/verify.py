"""Checkpoint conversion verifier: `python -m dorpatch_tpu.models.verify <ckpt.pth>`.

The reference's whole model layer is "timm model + PatchCleanser-release
checkpoint" (`/root/reference/utils.py:47-63`). Our parity story rests on
converting those exact `.pth` files to flax params (`models/convert.py`), so
this tool takes a real checkpoint file and reports, per fixed input, the
max |logit delta| between

  - the flax model with the converted params, and
  - the torch twin (`backends/torch_models.py`) loading the same state_dict
    (architecture contract identical to the timm model),

on a batch of seeded random images. A max delta within `--tol` (default
1e-3 — GroupNorm/LayerNorm accumulate ~1e-4 noise in f32 at RN50 depth)
exits 0; anything larger exits 1 and prints the per-image deltas.

`--keys-only` skips the logit comparison and instead diffs the checkpoint's
key/shape set against the vendored timm-0.6.7 contract
(`models/timm_keys.py`): missing, unexpected, and shape-drifted keys are
reported (exit 1 on any drift). This runs without building either model —
a fast naming-contract check for checkpoints AND a drift alarm if a future
timm re-pin renames modules.

Usage:
  python -m dorpatch_tpu.models.verify path/to/resnetv2_50x1_bit_distilled_cutout2_128_imagenet.pth
  python -m dorpatch_tpu.models.verify ckpt.pth --arch vit --dataset imagenet
  python -m dorpatch_tpu.models.verify ckpt.pth --keys-only
"""

from __future__ import annotations

import argparse
import os
import sys

import numpy as np

from dorpatch_tpu import observe


def _infer_arch(path: str) -> str:
    base = os.path.basename(path)
    # cifar_vit before vit: a "cifar_vit_*" filename contains both
    for arch in ("cifar_vit", "resnetv2", "vit", "resmlp", "resnet18"):
        if arch in base:
            return arch
    return "resnetv2"


def _infer_dataset(path: str) -> str:
    base = os.path.basename(path)
    for ds in ("imagenet", "cifar100", "cifar10"):
        if ds in base:
            return ds
    return "imagenet"


def verify_checkpoint(
    ckpt_path: str,
    arch: str,
    dataset: str,
    batch: int = 4,
    img_size: int = 224,
    seed: int = 0,
) -> dict:
    """Convert `ckpt_path`, run flax + torch side by side, return the report.

    Report keys: `max_abs_delta`, `per_image_delta`, `argmax_agree`,
    `n_params` (converted leaves), `arch`, `dataset`.
    """
    import jax.numpy as jnp
    import torch

    from dorpatch_tpu.backends.torch_models import Normalized, create_torch_model
    from dorpatch_tpu.config import NUM_CLASSES
    from dorpatch_tpu.models import registry
    from dorpatch_tpu.models.convert import load_state_dict

    timm_name = registry.resolve_arch(arch)
    n_classes = NUM_CLASSES[dataset]

    sd = load_state_dict(ckpt_path)
    params = registry._convert(timm_name, sd)
    flax_model = registry._build_flax(timm_name, n_classes)

    tm = create_torch_model(arch, n_classes)
    missing, unexpected = tm.load_state_dict(
        {k: torch.as_tensor(v) for k, v in sd.items()}, strict=False)
    if missing or unexpected:
        raise KeyError(
            f"torch twin state_dict mismatch: missing={list(missing)[:5]} "
            f"unexpected={list(unexpected)[:5]} (of "
            f"{len(missing)}/{len(unexpected)})")
    tm = Normalized(tm).eval()

    rng = np.random.default_rng(seed)
    x = rng.random((batch, img_size, img_size, 3), dtype=np.float32)

    logits_jax = np.asarray(
        flax_model.apply(params, (jnp.asarray(x) - 0.5) / 0.5))
    with torch.no_grad():
        logits_torch = tm(
            torch.from_numpy(x.transpose(0, 3, 1, 2))).numpy()

    delta = np.abs(logits_jax - logits_torch)
    n_leaves = len(jax_tree_leaves(params))
    return {
        "arch": timm_name,
        "dataset": dataset,
        "n_params": n_leaves,
        "max_abs_delta": float(delta.max()),
        "per_image_delta": [float(d) for d in delta.max(axis=1)],
        "argmax_agree": bool(
            (logits_jax.argmax(-1) == logits_torch.argmax(-1)).all()),
    }


def jax_tree_leaves(tree):
    import jax

    return jax.tree_util.tree_leaves(tree)


def verify_keys(ckpt_path: str, arch: str, dataset: str) -> dict:
    """Diff the checkpoint's keys/shapes against the vendored timm contract.

    Returns {"arch", "n_keys", "missing", "unexpected", "shape_drift"};
    clean iff the last three are empty."""
    from dorpatch_tpu.config import NUM_CLASSES
    from dorpatch_tpu.models import registry, timm_keys
    from dorpatch_tpu.models.convert import load_state_dict

    timm_name = registry.resolve_arch(arch)
    sd = load_state_dict(ckpt_path)
    report = timm_keys.diff_against_contract(
        sd.keys(), timm_name, NUM_CLASSES[dataset],
        sd_shapes={k: v.shape for k, v in sd.items()})
    report.update(arch=timm_name, n_keys=len(sd))
    return report


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        description="Verify a timm/PatchCleanser checkpoint converts to flax "
        "with logit parity against the torch twin")
    p.add_argument("checkpoint", help="path to the .pth file")
    p.add_argument("--arch", default=None,
                   choices=["resnetv2", "vit", "resmlp", "resnet18",
                            "cifar_vit"],
                   help="architecture (default: inferred from the filename)")
    p.add_argument("--dataset", default=None,
                   choices=["imagenet", "cifar10", "cifar100"],
                   help="dataset -> class count (default: inferred)")
    p.add_argument("--batch", type=int, default=4)
    p.add_argument("--img-size", type=int, default=0,
                   help="0 = by arch: 32 for the small trained-victim "
                        "families (fixed pos_embed for cifar_vit), 224 "
                        "for the timm models")
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--tol", type=float, default=1e-3)
    p.add_argument("--keys-only", action="store_true",
                   help="only diff the checkpoint's key/shape set against "
                   "the vendored timm-0.6.7 contract (models/timm_keys.py)")
    args = p.parse_args(argv)

    if not os.path.exists(args.checkpoint):
        observe.log(f"checkpoint not found: {args.checkpoint}", file=sys.stderr)
        return 2
    if args.keys_only:
        report = verify_keys(
            args.checkpoint,
            args.arch or _infer_arch(args.checkpoint),
            args.dataset or _infer_dataset(args.checkpoint),
        )
        drift = (report["missing"] or report["unexpected"]
                 or report["shape_drift"])
        verdict = "FAIL" if drift else "OK"
        observe.log(f"[{verdict}] {report['arch']}: {report['n_keys']} keys vs "
              f"vendored timm-0.6.7 contract — "
              f"{len(report['missing'])} missing, "
              f"{len(report['unexpected'])} unexpected, "
              f"{len(report['shape_drift'])} shape-drifted")
        for field in ("missing", "unexpected", "shape_drift"):
            for item in report[field][:20]:
                observe.log(f"  {field}: {item}")
        return 1 if drift else 0
    arch = args.arch or _infer_arch(args.checkpoint)
    img_size = args.img_size or (
        32 if arch in ("resnet18", "cifar_resnet18", "cifar_vit") else 224)
    report = verify_checkpoint(
        args.checkpoint,
        arch,
        args.dataset or _infer_dataset(args.checkpoint),
        args.batch, img_size, args.seed,
    )
    ok = report["max_abs_delta"] <= args.tol and report["argmax_agree"]
    verdict = "OK" if ok else "FAIL"
    observe.log(f"[{verdict}] {report['arch']} ({report['dataset']}): "
          f"max |logit delta| = {report['max_abs_delta']:.3e} "
          f"(tol {args.tol:g}), argmax agree = {report['argmax_agree']}, "
          f"{report['n_params']} converted param leaves")
    if not ok:
        observe.log(f"per-image max deltas: {report['per_image_delta']}")
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
