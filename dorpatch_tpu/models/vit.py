"""Flax ViT-B/16, NHWC, matching timm's `vit_base_patch16_224`, plus the
token-pruned incremental masked-inference engine.

Second victim family of the reference (`/root/reference/utils.py:51-52`).
timm contract: 16x16 conv patch embed (with bias), cls token, learned
197-token position embedding added after cls concat, pre-norm transformer
blocks (LayerNorm eps=1e-6, 12 heads, qkv bias, MLP ratio 4 with *exact*
erf GELU — torch nn.GELU default), final LayerNorm, linear head on the cls
token.

TPU notes: attention is batched matmuls on the MXU; sequence length 197 is
small, so no flash/ring attention is needed here — the EOT/mask axis is this
workload's scaling dimension (SURVEY.md §5) and is sharded at the batch level.

Incremental masked inference (`TokenPrunedViT`, ROADMAP item 1): a
PatchCleanser occlusion mask is a small contiguous window, so for most patch
tokens the masked image's pixels — and therefore the layer-0 token
embeddings — are bit-identical to the clean image's. The engine computes the
clean per-block input activations ONCE per image (`ViT.__call__` mode
="cache") and projects them through every block's key/value heads once
(`TokenPrunedViT._clean_kv` — the shared clean KV cache), then per mask
recomputes only the mask-touched patch tokens (plus the cls readout token):
queries, the dirty rows' K/V projections (scattered into the cached K/V),
and the MLP all run on the dirty rows alone, so per-mask cost scales with
`dirty_tokens / total_tokens` instead of 1.0 — projecting K/V from the
substituted *activations* instead would put 2/3 of the attention projection
cost back on every entry, which measurement showed erases the win.

Exactness contract: the dirty tokens' updates are exact *given their block
inputs* — in particular the final block's cls readout is computed exactly
from the (substituted) final-block KV — but untouched tokens keep their
clean activations at every depth, while in the true masked forward they
would drift from attending to dirty tokens from block 1 on. The resulting
logit drift is small (the mask touches a few tokens out of 65/197) but not
zero; the engine therefore returns top-2 logit *margins* alongside each
prediction, and `defense.py`'s "token-exact" mode re-runs any image whose
read table entries sit within the configured margin of the decision
boundary through the exhaustive program, making *verdicts* bit-identical
whenever the drift stays below that documented tolerance
(`DefenseConfig.incremental_margin`).
"""

from __future__ import annotations

from typing import Callable, NamedTuple, Optional, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np

from dorpatch_tpu import masks as masks_lib
from dorpatch_tpu.ops import _backend
from dorpatch_tpu.ops import masked_kv_attn


def _fast_ln(x, scale, bias, eps=1e-6):
    """flax `nn.LayerNorm` twin (fast-variance formula) at x.dtype: the
    per-token statistics accumulate in f32 (jnp.mean upcasts half-precision
    reductions internally and converts straight back), but the normalize
    chain's slab-sized tensors stay at the input dtype."""
    mean = jnp.mean(x, axis=-1, keepdims=True)
    mean2 = jnp.mean(x * x, axis=-1, keepdims=True)
    var = jnp.maximum(0.0, mean2 - mean * mean)
    y = (x - mean) * jax.lax.rsqrt(var + eps)
    return y * scale + bias


class LayerNormDT(nn.Module):
    """LayerNorm that keeps sub-f32 activations at their own dtype.

    float32 inputs go through flax's `nn.LayerNorm` verbatim (applied
    functionally against this module's own `scale`/`bias`, so the f32
    parameter tree and numerics are bit-identical to declaring it inline).
    Lower-precision inputs — the bf16 certify bank casts the victim's
    params and images down — use `_fast_ln`: flax's `_normalize`
    materializes the whole chain in f32 even under `dtype=bfloat16`,
    which is exactly the slab leak DP208 flags inside bf16 banks."""

    epsilon: float = 1e-6

    @nn.compact
    def __call__(self, x):
        c = x.shape[-1]
        scale = self.param("scale", nn.initializers.ones, (c,), jnp.float32)
        bias = self.param("bias", nn.initializers.zeros, (c,), jnp.float32)
        if x.dtype == jnp.float32:
            # parent=None keeps this an unbound functional apply; without
            # it flax registers a child module in LayerNormDT's own scope
            return nn.LayerNorm(epsilon=self.epsilon, parent=None).apply(
                {"params": {"scale": scale, "bias": bias}}, x)
        return _fast_ln(x, scale, bias, self.epsilon)


class ViTBlock(nn.Module):
    dim: int
    num_heads: int
    mlp_ratio: int = 4

    @nn.compact
    def __call__(self, x):
        y = LayerNormDT(name="norm1")(x)
        y = nn.MultiHeadDotProductAttention(
            num_heads=self.num_heads,
            qkv_features=self.dim,
            out_features=self.dim,
            use_bias=True,
            name="attn",
        )(y, y)
        x = x + y
        y = LayerNormDT(name="norm2")(x)
        y = nn.Dense(self.dim * self.mlp_ratio, name="mlp_fc1")(y)
        y = nn.gelu(y, approximate=False)
        y = nn.Dense(self.dim, name="mlp_fc2")(y)
        return x + y


class ViT(nn.Module):
    num_classes: int
    patch_size: int = 16
    dim: int = 768
    depth: int = 12
    num_heads: int = 12
    img_size: Tuple[int, int] = (224, 224)

    @nn.compact
    def __call__(self, x, mode: str = "full"):
        """mode="full": logits. mode="cache": the tuple of per-block INPUT
        activations `depth x [B, T+1, D]` — the incremental engine's clean
        KV cache. Cache mode stops before the final block executes (its
        output feeds only the head, which the cache does not include), so
        the traced program carries no dead compute."""
        if mode not in ("full", "cache"):
            raise ValueError(f"mode={mode!r} (use 'full' or 'cache')")
        B = x.shape[0]
        x = nn.Conv(
            self.dim,
            (self.patch_size, self.patch_size),
            strides=(self.patch_size, self.patch_size),
            padding="VALID",
            name="patch_embed",
        )(x)
        x = x.reshape(B, -1, self.dim)  # [B, 196, D] row-major patches
        cls = self.param("cls_token", nn.initializers.zeros, (1, 1, self.dim), jnp.float32)
        x = jnp.concatenate([jnp.broadcast_to(cls, (B, 1, self.dim)), x], axis=1)
        n_tokens = x.shape[1]
        pos = self.param(
            "pos_embed", nn.initializers.normal(0.02), (1, n_tokens, self.dim), jnp.float32
        )
        x = x + pos
        cache = []
        for i in range(self.depth):
            cache.append(x)
            if mode == "cache" and i == self.depth - 1:
                return tuple(cache)
            x = ViTBlock(self.dim, self.num_heads, name=f"block{i}")(x)
        x = LayerNormDT(name="norm")(x)
        return nn.Dense(self.num_classes, name="head")(x[:, 0])


def vit_base_patch16(num_classes: int) -> ViT:
    return ViT(num_classes=num_classes)


# The framework's own small transformer victim for 32x32 trained-victim
# protocol runs (the analog of `models.small.CifarResNet18` for the ViT
# family): 8x8 grid of 4x4 patches + cls = 65 tokens. The reference only
# consumes pretrained 224px victims (`/root/reference/utils.py:47-63`); this
# config exists so the trained-victim parity evidence (train.py ->
# torch-.pth export -> converter round-trip -> torch-oracle certified-ASR)
# covers a second, non-convolutional family offline.
CIFAR_VIT = dict(patch_size=4, dim=128, depth=6, num_heads=4,
                 img_size=(32, 32))


def vit_cifar(num_classes: int) -> ViT:
    return ViT(num_classes=num_classes, **CIFAR_VIT)


# ------------------------------------------- token-pruned incremental engine


def _default_normalize(x):
    """Fallback for directly-constructed engines; the factory
    (`models.registry.incremental_engine`) always passes its own
    `registry._normalize`, the single production definition."""
    return (x - 0.5) / 0.5


class _TokenTables(NamedTuple):
    """Static per-mask-family lookup tables, device-resident (closed-over
    DEVICE arrays are the params idiom the program auditor exempts from
    DP203; host numpy constants this size would be flagged)."""

    idx: jax.Array      # [N, S] int32 sequence positions (0 = cls, patch t -> t+1)
    keep: jax.Array     # [N, S-1, p, p, 1] f32 pixel keep-mask per dirty patch slot
    slot_bias: jax.Array  # [N, S] f32 additive attention bias: 0 for real
    #                       dirty slots, -1e9 for duplicate padding slots
    #                       (their K/V rows must not count twice)
    fe: np.ndarray      # [N] float64 forward equivalents: (dirty tokens + 1) / (T + 1)


def _build_tables(rects: np.ndarray, img_size: int, patch: int) -> _TokenTables:
    """Token sets + per-token pixel keep masks for one rectangle table.

    Slots beyond a mask's real coverage repeat slot 0 (same token, same
    keep mask), so the padded slots compute the identical dirty value and
    the KV scatter stays deterministic under duplicate indices."""
    rects = np.asarray(rects, np.int64)
    if rects.ndim == 2:
        rects = rects[:, None, :]
    grid = img_size // patch
    cov = masks_lib.rect_token_coverage(rects, img_size, patch)  # [N, T]
    n, t_total = cov.shape
    s_max = int(cov.sum(axis=1).max())
    idx = np.zeros((n, s_max + 1), np.int32)
    keep = np.ones((n, s_max, patch, patch, 1), np.float32)
    slot_bias = np.zeros((n, s_max + 1), np.float32)
    for i in range(n):
        toks = np.nonzero(cov[i])[0]
        padded = np.concatenate([toks, np.full(s_max - len(toks), toks[0])])
        idx[i, 1:] = padded + 1  # sequence position; slot 0 stays cls (0)
        slot_bias[i, 1 + len(toks):] = -1e9
        for s, tok in enumerate(padded):
            pr, pc = divmod(int(tok), grid)
            r_off, c_off = pr * patch, pc * patch
            for r0, r1, c0, c1 in rects[i]:
                rr0, rr1 = max(r0 - r_off, 0), min(r1 - r_off, patch)
                cc0, cc1 = max(c0 - c_off, 0), min(c1 - c_off, patch)
                if rr0 < rr1 and cc0 < cc1:
                    keep[i, s, rr0:rr1, cc0:cc1, 0] = 0.0
    fe = (cov.sum(axis=1) + 1.0) / float(t_total + 1)
    return _TokenTables(jnp.asarray(idx), jnp.asarray(keep),
                        jnp.asarray(slot_bias), fe)


class TokenViTFamily:
    """One mask family's incremental programs: the combined rectangle table
    `[singles; pairs]` of a certifier (`defense.PatchCleanser._rects`
    layout) compiled into three jit-friendly callables —

    - `phase1(params, imgs)`: the `[B, M]` first-round table,
    - `pairs(params, imgs)`: the `[B, P]` pair-audit table,
    - `rows(params, imgs_g, sets_idx)`: ragged second-round rows, one
      gathered image and one `[M2]` combined-table index row per entry

    — each returning `(preds int32, margins f32)` of identical shape, where
    the margin is the masked forward's top-1/top-2 logit gap (the
    token-exact escalation signal). Forward-equivalent weights per combined
    mask are in `.fe`; `fe_first`/`fe_pairs` are the per-image sums."""

    def __init__(self, engine: "TokenPrunedViT", rects: np.ndarray,
                 num_singles: int, chunk_size: int, fill: float,
                 use_pallas: str = "auto", mesh=None,
                 data_axis: str = "data", compute_dtype: str = "float32"):
        self.engine = engine
        self.num_singles = int(num_singles)
        self.chunk_size = max(1, int(chunk_size))
        self.fill = float(fill)
        self.use_pallas = use_pallas
        self.mesh = mesh
        self.data_axis = data_axis
        self.compute_dtype = jnp.dtype(compute_dtype)
        img, patch = engine.img_size, engine.patch
        self.first = _build_tables(rects[:num_singles], img, patch)
        self.pair_tables = _build_tables(rects[num_singles:], img, patch)
        self.combined = _build_tables(rects, img, patch)
        if self.compute_dtype != jnp.float32:
            # cast the static float tables once at family build: a bf16
            # activation times an f32 keep mask (or plus an f32 slot bias)
            # silently promotes the whole chain back to f32 — exactly the
            # leak DP208 flags — so the tables follow the sweep dtype.
            # (-1e9 bias sentinels are exactly representable in bf16: same
            # exponent range as f32.)
            def cast(t):
                return t._replace(
                    keep=t.keep.astype(self.compute_dtype),
                    slot_bias=t.slot_bias.astype(self.compute_dtype))

            self.first = cast(self.first)
            self.pair_tables = cast(self.pair_tables)
            self.combined = cast(self.combined)
        self.fe = self.combined.fe
        self.fe_first = float(self.fe[:num_singles].sum())
        self.fe_pairs = float(self.fe[num_singles:].sum())
        # per-invocation clean-cache cost in full-forward units: every
        # program run computes the clean activations once per image ("cache"
        # mode, ~(depth-1)/depth of a forward) plus the K/V projections
        # (~1/6 — 2 of the 12 D^2-matmuls per block). The defense's fe
        # accounting charges this on top of the per-mask fractions so
        # `forward_equivalents` reflects ALL dispatched work.
        depth = max(1, int(engine.module.depth))
        self.cache_fe = (depth - 1) / depth + 1.0 / 6.0

    # the three program bodies defense.py wraps in jax.jit ----------------

    def phase1(self, params, imgs):
        # program-boundary image cast (no-op at f32): callers hand f32
        # batches regardless of the bank's sweep dtype
        return self.engine._table(params, imgs.astype(self.compute_dtype),
                                  self.first,
                                  self.fill, self.chunk_size,
                                  self.use_pallas, self.mesh,
                                  self.data_axis)

    def pairs(self, params, imgs):
        return self.engine._table(params, imgs.astype(self.compute_dtype),
                                  self.pair_tables,
                                  self.fill, self.chunk_size,
                                  self.use_pallas, self.mesh,
                                  self.data_axis)

    def rows(self, params, imgs_g, sets_idx):
        return self.engine._rows(params, imgs_g.astype(self.compute_dtype),
                                 sets_idx, self.combined,
                                 self.fill, self.chunk_size,
                                 self.use_pallas, self.mesh,
                                 self.data_axis)


class TokenPrunedViT:
    """Token-pruned incremental masked inference for one ViT victim.

    Built by `models.registry.get_model` for the ViT families and handed to
    `defense.build_defenses(..., incremental=...)`; `build_family` is called
    once per certifier (mask radius) with its combined rectangle table."""

    kind = "token"

    def __init__(self, module: ViT, img_size: int,
                 normalize: Optional[Callable[[jax.Array], jax.Array]] = None):
        if img_size % module.patch_size:
            raise ValueError(
                f"img_size={img_size} not divisible by patch "
                f"{module.patch_size}")
        self.module = module
        self.img_size = int(img_size)
        self.patch = int(module.patch_size)
        self.grid = self.img_size // self.patch
        self.tokens = self.grid * self.grid
        self.normalize = normalize or _default_normalize

    def build_family(self, rects: np.ndarray, num_singles: int,
                     chunk_size: int, fill: float,
                     use_pallas: str = "auto", mesh=None,
                     data_axis: str = "data",
                     compute_dtype: str = "float32") -> TokenViTFamily:
        return TokenViTFamily(self, rects, num_singles, chunk_size, fill,
                              use_pallas=use_pallas, mesh=mesh,
                              data_axis=data_axis,
                              compute_dtype=compute_dtype)

    # ------------------------------------------------------------ internals

    def _patches(self, imgs: jax.Array) -> jax.Array:
        """[B, H, W, C] -> [B, T, p, p, C] row-major patches (the conv
        patch embed's token order)."""
        b, h, w, c = imgs.shape
        p, g = self.patch, self.grid
        x = imgs.reshape(b, g, p, g, p, c)
        return jnp.transpose(x, (0, 1, 3, 2, 4, 5)).reshape(b, g * g, p, p, c)

    def _embed(self, params, patches_g, keep, seq_pos, fill):
        """Dirty-token embeddings: occlude the gathered raw patches with the
        static keep masks, normalize, apply the patch-embed conv (a p-stride
        p-kernel conv == one einsum per token) and add the position rows."""
        p = params["params"]
        masked = patches_g * keep + fill * (1.0 - keep)
        xn = self.normalize(masked)
        emb = jnp.einsum("...hwc,hwcd->...d", xn,
                         p["patch_embed"]["kernel"]) + p["patch_embed"]["bias"]
        return emb + p["pos_embed"][0][seq_pos]

    @staticmethod
    def _ln(x, p, eps=1e-6):
        """flax `nn.LayerNorm` twin over params {scale, bias} — applied
        manually so the incremental blocks can run straight off the
        parameter tree (shared `_fast_ln` math, dtype-preserving)."""
        return _fast_ln(x, p["scale"], p["bias"], eps)

    def _clean_kv(self, params, cache):
        """Per-block clean KEY/VALUE projections of the cached activations
        — the shared KV cache proper. Computed once per image (2/12 of a
        forward per block); per masked entry only the few dirty rows'
        projections are recomputed and scattered in, so attention cost
        scales with dirty_tokens, not T."""
        p = params["params"]
        ks, vs = [], []
        for layer in range(self.module.depth):
            bp = p[f"block{layer}"]
            ln = self._ln(cache[layer], bp["norm1"])
            a = bp["attn"]
            ks.append(jnp.einsum("btd,dhf->bthf", ln, a["key"]["kernel"])
                      + a["key"]["bias"])
            vs.append(jnp.einsum("btd,dhf->bthf", ln, a["value"]["kernel"])
                      + a["value"]["bias"])
        return tuple(ks), tuple(vs)

    def _forward(self, params, d, kcs, vcs, idx, slot_bias, attn="off",
                 mesh=None, data_axis="data"):
        """Dirty tokens `d [B, C, S, D]` (C masks per image) through every
        block against the per-IMAGE clean KV caches (`kcs`/`vcs`:
        `depth x [B, T+1, H, hd]`). Attention concatenates two key/value
        groups per query: the shared clean cache — read IN PLACE, never
        copied per mask; the stale rows at the dirty positions are
        excluded with an additive -1e9 bias — and the S dirty rows'
        freshly projected K/V (duplicate padding slots masked by
        `slot_bias`). Queries, dirty K/V projections, and the MLP all
        run on the S dirty rows only, so per-entry cost scales with
        S/(T+1) in both FLOPs and memory traffic. Then the cls readout ->
        logits [B, C, num_classes]. Math mirrors flax
        `nn.MultiHeadDotProductAttention` (scaled q, per-head softmax;
        softmax is order-invariant, so regrouping the sequence cannot
        change the probabilities beyond summation order).

        `attn` is the RESOLVED kernel gate ("off" | "on" | "interpret"):
        off composes the attention read from einsums; otherwise the fused
        `ops.masked_kv_attn` kernel reads the cached K/V blocks in place
        with both biases folded into the logits on-chip (same math,
        regrouped reductions — allclose, margin-contracted verdicts). On a
        multi-device `mesh` the kernel runs per data-axis shard under
        `shard_map` (`masked_kv_attention_sharded`, the DP603-proved
        form)."""
        p = params["params"]
        t1 = kcs[0].shape[1]
        hd = self.module.dim // self.module.num_heads
        scale = 1.0 / float(np.sqrt(hd))
        # [B, C, T+1] additive bias masking the clean rows that sit at
        # dirty positions (their cached K/V is stale; the dirty group
        # carries the fresh rows). Mask geometry is layer-independent.
        stale = jnp.any(idx[..., None] == jnp.arange(t1), axis=-2)
        # explicit cast: jnp.where over python scalars yields a weak f32
        # array, and adding it to bf16 attention logits relies on weak-type
        # promotion staying bf16 — pin the bias to the sweep dtype instead
        stale_bias = jnp.where(stale, -1e9, 0.0).astype(d.dtype)
        clean_bias = stale_bias[..., None, None, :]
        dirty_bias = slot_bias[..., None, None, :]
        for layer in range(self.module.depth):
            bp = p[f"block{layer}"]
            a = bp["attn"]
            ln_d = self._ln(d, bp["norm1"])
            q = jnp.einsum("bcsd,dhf->bcshf", ln_d, a["query"]["kernel"]) \
                + a["query"]["bias"]
            q = q * scale
            kd = jnp.einsum("bcsd,dhf->bcshf", ln_d, a["key"]["kernel"]) \
                + a["key"]["bias"]
            vd = jnp.einsum("bcsd,dhf->bcshf", ln_d, a["value"]["kernel"]) \
                + a["value"]["bias"]
            if attn == "off":
                wc = jnp.einsum("bcshf,bthf->bchst", q, kcs[layer]) \
                    + clean_bias
                wd = jnp.einsum("bcshf,bcthf->bchst", q, kd) + dirty_bias
                w = jax.nn.softmax(jnp.concatenate([wc, wd], axis=-1),
                                   axis=-1)
                o = jnp.einsum("bchst,bthf->bcshf", w[..., :t1], vcs[layer]) \
                    + jnp.einsum("bchst,bcthf->bcshf", w[..., t1:], vd)
            elif mesh is not None and mesh.devices.size > 1:
                o = masked_kv_attn.masked_kv_attention_sharded(
                    q, kd, vd, kcs[layer], vcs[layer], stale_bias,
                    slot_bias, mesh, data_axis,
                    interpret=(attn == "interpret"))
            else:
                o = masked_kv_attn.masked_kv_attention(
                    q, kd, vd, kcs[layer], vcs[layer], stale_bias,
                    slot_bias, interpret=(attn == "interpret"))
            d = d + jnp.einsum("bcshf,hfd->bcsd", o, a["out"]["kernel"]) \
                + a["out"]["bias"]
            ln2 = self._ln(d, bp["norm2"])
            h = nn.gelu(ln2 @ bp["mlp_fc1"]["kernel"]
                        + bp["mlp_fc1"]["bias"], approximate=False)
            d = d + (h @ bp["mlp_fc2"]["kernel"] + bp["mlp_fc2"]["bias"])
        cls = self._ln(d[..., 0, :], p["norm"])
        return cls @ p["head"]["kernel"] + p["head"]["bias"]

    @staticmethod
    def _preds_margins(logits):
        from dorpatch_tpu.utils import preds_margins

        return preds_margins(logits)

    def _chunk(self, params, patches, cls0, kcs, vcs, idxc, keepc, biasc,
               fill, attn="off", mesh=None, data_axis="data"):
        """One mask chunk: [B images, c masks] dirty-token batch against
        the per-image clean KV caches (shared across the mask axis — the
        einsums read them in place). Tables are PER-IMAGE (`[B, c, ...]`):
        the phase-1/pair programs broadcast one shared mask chunk over the
        batch, the rows program passes each gathered image its own
        second-mask chunk."""
        b, c = idxc.shape[0], idxc.shape[1]
        dim = self.module.dim
        tok = idxc[..., 1:] - 1                                 # [B, c, S-1]
        pg = jax.vmap(lambda pp, ii: pp[ii])(patches, tok)      # [B, c, S-1, p, p, C]
        emb = self._embed(params, pg, keepc, idxc[..., 1:], fill)
        cls = jnp.broadcast_to(cls0[:, None], (b, c, 1, dim))
        d = jnp.concatenate([cls, emb], axis=2)                 # [B, c, S, D]
        logits = self._forward(params, d, kcs, vcs, idxc, biasc, attn,
                               mesh, data_axis)
        return self._preds_margins(logits)                      # [B, c] each

    @staticmethod
    def _resolve_attn(use_pallas, mesh, data_axis, leading):
        """The shared gate, with the mesh divisibility rule: a batch the
        data axis does not divide falls back to the XLA einsum path (the
        shard_map wrapper needs equal per-shard blocks)."""
        on_mesh = (mesh is not None
                   and getattr(mesh, "devices", None) is not None
                   and mesh.devices.size > 1)
        divisible = (not on_mesh) or leading % mesh.shape[data_axis] == 0
        return _backend.resolve_use_pallas(use_pallas, mesh=mesh,
                                           divisible=divisible)

    def _table(self, params, imgs, tables: _TokenTables, fill, chunk_size,
               use_pallas: str = "off", mesh=None, data_axis="data"):
        """All N masks of `tables` over the batch -> (preds, margins)
        `[B, N]`, scanning mask chunks of <= chunk_size (the same live-
        memory bound as `defense.masked_predictions`). Padding masks repeat
        entry 0 and are sliced off."""
        n = int(tables.idx.shape[0])
        c = min(max(1, int(chunk_size)), n) if n else 1
        n_chunks = -(-n // c) if n else 0
        pad = n_chunks * c - n
        def padded(t):
            return jnp.concatenate(
                [t, jnp.broadcast_to(t[:1], (pad,) + t.shape[1:])]
            ).reshape((n_chunks, c) + t.shape[1:])

        idx_p = padded(tables.idx)
        keep_p = padded(tables.keep)
        bias_p = padded(tables.slot_bias)
        cache = self.module.apply(params, self.normalize(imgs), "cache")
        kcs, vcs = self._clean_kv(params, cache)
        cls0 = cache[0][:, :1]
        patches = self._patches(imgs)
        b = imgs.shape[0]
        attn = self._resolve_attn(use_pallas, mesh, data_axis, b)

        def body(carry, xs):
            idxc, keepc, biasc = xs

            def bc(t):  # shared mask chunk -> per-image [B, c, ...]
                return jnp.broadcast_to(t[None], (b,) + t.shape)

            return carry, self._chunk(params, patches, cls0, kcs, vcs,
                                      bc(idxc), bc(keepc), bc(biasc), fill,
                                      attn, mesh, data_axis)

        _, (preds, margins) = jax.lax.scan(body, None,
                                           (idx_p, keep_p, bias_p))
        preds = jnp.moveaxis(preds, 0, 1).reshape(b, -1)[:, :n]
        margins = jnp.moveaxis(margins, 0, 1).reshape(b, -1)[:, :n]
        return preds, margins

    def _rows(self, params, imgs_g, sets_idx, combined: _TokenTables, fill,
              chunk_size, use_pallas: str = "off", mesh=None,
              data_axis="data"):
        """Ragged second-round rows: entry w = (gathered image, [M2] row of
        combined-table mask indices). The second-mask axis is processed in
        chunks of `max(1, chunk_size // W)` so each scan step is a
        [W, c]-shaped batch (the same `chunk_size` live-entry bound as the
        table programs) instead of M2 tiny per-mask steps — small-shape
        dispatch overhead would otherwise dominate the token path's FLOP
        savings."""
        w, m2 = int(sets_idx.shape[0]), int(sets_idx.shape[1])
        c = max(1, min(m2, int(chunk_size) // max(1, w)))
        n_chunks = -(-m2 // c)
        pad = n_chunks * c - m2
        sets_p = jnp.concatenate(
            [sets_idx, jnp.broadcast_to(sets_idx[:, :1], (w, pad))], axis=1)
        idx_all = combined.idx[sets_p]        # [W, M2p, S]
        keep_all = combined.keep[sets_p]      # [W, M2p, S-1, p, p, 1]
        bias_all = combined.slot_bias[sets_p]  # [W, M2p, S]
        cache = self.module.apply(params, self.normalize(imgs_g), "cache")
        kcs, vcs = self._clean_kv(params, cache)
        cls0 = cache[0][:, :1]
        patches = self._patches(imgs_g)
        attn = self._resolve_attn(use_pallas, mesh, data_axis, w)

        def chunked(t):  # [W, M2p, ...] -> scan xs [nc, W, c, ...]
            return jnp.moveaxis(
                t.reshape((w, n_chunks, c) + t.shape[2:]), 1, 0)

        def body(carry, xs):
            idxc, keepc, biasc = xs           # [W, c, ...]
            return carry, self._chunk(params, patches, cls0, kcs, vcs,
                                      idxc, keepc, biasc, fill, attn,
                                      mesh, data_axis)

        _, (preds, margins) = jax.lax.scan(
            body, None, (chunked(idx_all), chunked(keep_all),
                         chunked(bias_all)))
        # [nc, W, c] -> [W, nc*c] -> [:, :M2]
        preds = jnp.moveaxis(preds, 0, 1).reshape(w, -1)[:, :m2]
        margins = jnp.moveaxis(margins, 0, 1).reshape(w, -1)[:, :m2]
        return preds, margins
