"""Flax ViT-B/16, NHWC, matching timm's `vit_base_patch16_224`.

Second victim family of the reference (`/root/reference/utils.py:51-52`).
timm contract: 16x16 conv patch embed (with bias), cls token, learned
197-token position embedding added after cls concat, pre-norm transformer
blocks (LayerNorm eps=1e-6, 12 heads, qkv bias, MLP ratio 4 with *exact*
erf GELU — torch nn.GELU default), final LayerNorm, linear head on the cls
token.

TPU notes: attention is batched matmuls on the MXU; sequence length 197 is
small, so no flash/ring attention is needed here — the EOT/mask axis is this
workload's scaling dimension (SURVEY.md §5) and is sharded at the batch level.
"""

from __future__ import annotations

from typing import Tuple

import flax.linen as nn
import jax.numpy as jnp


class ViTBlock(nn.Module):
    dim: int
    num_heads: int
    mlp_ratio: int = 4

    @nn.compact
    def __call__(self, x):
        y = nn.LayerNorm(epsilon=1e-6, name="norm1")(x)
        y = nn.MultiHeadDotProductAttention(
            num_heads=self.num_heads,
            qkv_features=self.dim,
            out_features=self.dim,
            use_bias=True,
            name="attn",
        )(y, y)
        x = x + y
        y = nn.LayerNorm(epsilon=1e-6, name="norm2")(x)
        y = nn.Dense(self.dim * self.mlp_ratio, name="mlp_fc1")(y)
        y = nn.gelu(y, approximate=False)
        y = nn.Dense(self.dim, name="mlp_fc2")(y)
        return x + y


class ViT(nn.Module):
    num_classes: int
    patch_size: int = 16
    dim: int = 768
    depth: int = 12
    num_heads: int = 12
    img_size: Tuple[int, int] = (224, 224)

    @nn.compact
    def __call__(self, x):
        B = x.shape[0]
        x = nn.Conv(
            self.dim,
            (self.patch_size, self.patch_size),
            strides=(self.patch_size, self.patch_size),
            padding="VALID",
            name="patch_embed",
        )(x)
        x = x.reshape(B, -1, self.dim)  # [B, 196, D] row-major patches
        cls = self.param("cls_token", nn.initializers.zeros, (1, 1, self.dim), jnp.float32)
        x = jnp.concatenate([jnp.broadcast_to(cls, (B, 1, self.dim)), x], axis=1)
        n_tokens = x.shape[1]
        pos = self.param(
            "pos_embed", nn.initializers.normal(0.02), (1, n_tokens, self.dim), jnp.float32
        )
        x = x + pos
        for i in range(self.depth):
            x = ViTBlock(self.dim, self.num_heads, name=f"block{i}")(x)
        x = nn.LayerNorm(epsilon=1e-6, name="norm")(x)
        return nn.Dense(self.num_classes, name="head")(x[:, 0])


def vit_base_patch16(num_classes: int) -> ViT:
    return ViT(num_classes=num_classes)


# The framework's own small transformer victim for 32x32 trained-victim
# protocol runs (the analog of `models.small.CifarResNet18` for the ViT
# family): 8x8 grid of 4x4 patches + cls = 65 tokens. The reference only
# consumes pretrained 224px victims (`/root/reference/utils.py:47-63`); this
# config exists so the trained-victim parity evidence (train.py ->
# torch-.pth export -> converter round-trip -> torch-oracle certified-ASR)
# covers a second, non-convolutional family offline.
CIFAR_VIT = dict(patch_size=4, dim=128, depth=6, num_heads=4,
                 img_size=(32, 32))


def vit_cifar(num_classes: int) -> ViT:
    return ViT(num_classes=num_classes, **CIFAR_VIT)
