"""Vendored timm 0.6.7 state-dict key contracts for the reference victims.

The reference's model layer is "timm model + PatchCleanser checkpoint"
(`/root/reference/utils.py:47-63`, timm pin `/root/reference/requirements.txt`).
No timm nor real checkpoints exist in this build environment, so the
converter's key map (`models/convert.py`) would otherwise be validated only
against this repo's own torch twins — a naming-drift bug invisible to every
test. This module pins the contract instead: the exact state-dict keys (and
shapes) of the three timm 0.6.7 architectures, reconstructed from their
module trees:

- `timm/models/resnetv2.py` — ResNetV2(layers=(3,4,6,3)) of `ResNetStage`s
  of `PreActBottleneck`s (norm1..3 GroupNormAct, conv1..3 StdConv2d,
  `DownsampleConv(preact=True)` = bare conv on every stage's block 0), a
  bias-free stem conv, final GroupNormAct `norm`, and a
  `ClassifierHead(use_conv=True)` 1x1-conv `head.fc`.
- `timm/models/vision_transformer.py` — VisionTransformer(depth=12):
  cls_token/pos_embed, `patch_embed.proj` conv, per-block
  norm1 / attn.qkv / attn.proj / norm2 / mlp.fc1 / mlp.fc2, final `norm`,
  Linear `head` (no pre_logits/fc_norm/dist_token in this variant; ls1/ls2
  are Identity at init_values=None).
- `timm/models/mlp_mixer.py` — MlpMixer(block_layer=ResBlock, depth=24):
  the patch embed is named `stem` (NOT ViT's `patch_embed`), `Affine`
  alpha/beta are stored [1, 1, D], ls1/ls2 are bare [D] parameters, and
  blocks live in one flat `nn.Sequential` (`blocks.{i}.`).

Checkpoints are saved *after* `reset_classifier(num_classes)`
(`/root/reference/utils.py:52`), so head shapes take `num_classes`.

`state_dict_contract(timm_name, num_classes)` -> OrderedDict[key, shape].
Consumers: the convert.py exactness test (`tests/test_models.py`) and
`models/verify.py --keys-only` drift reporting.
"""

from __future__ import annotations

from collections import OrderedDict
from typing import Dict, Tuple

Shape = Tuple[int, ...]


def resnetv2_50x1_keys(num_classes: int) -> "OrderedDict[str, Shape]":
    """`resnetv2_50x1_bit_distilled`: layers (3,4,6,3), width factor 1."""
    keys: "OrderedDict[str, Shape]" = OrderedDict()
    keys["stem.conv.weight"] = (64, 3, 7, 7)
    layers = (3, 4, 6, 3)
    for s, depth in enumerate(layers):
        mid = 64 * (2 ** s)
        out = 4 * mid
        for b in range(depth):
            cin = (64 if s == 0 else 2 * mid) if b == 0 else out
            pre = f"stages.{s}.blocks.{b}."
            keys[pre + "norm1.weight"] = (cin,)
            keys[pre + "norm1.bias"] = (cin,)
            keys[pre + "conv1.weight"] = (mid, cin, 1, 1)
            keys[pre + "norm2.weight"] = (mid,)
            keys[pre + "norm2.bias"] = (mid,)
            keys[pre + "conv2.weight"] = (mid, mid, 3, 3)
            keys[pre + "norm3.weight"] = (mid,)
            keys[pre + "norm3.bias"] = (mid,)
            keys[pre + "conv3.weight"] = (out, mid, 1, 1)
            if b == 0:
                keys[pre + "downsample.conv.weight"] = (out, cin, 1, 1)
    keys["norm.weight"] = (2048,)
    keys["norm.bias"] = (2048,)
    keys["head.fc.weight"] = (num_classes, 2048, 1, 1)
    keys["head.fc.bias"] = (num_classes,)
    return keys


def vit_base_patch16_224_keys(num_classes: int) -> "OrderedDict[str, Shape]":
    return _vit_keys(num_classes, dim=768, depth=12, n_tokens=197, patch=16)


def cifar_vit_keys(num_classes: int) -> "OrderedDict[str, Shape]":
    """The framework's OWN small transformer-victim contract (not a timm
    model): `models/vit.py:CIFAR_VIT` as exported by `export_vit` — 8x8
    grid of 4px patches + cls = 65 tokens, dim 128, depth 6."""
    return _vit_keys(num_classes, dim=128, depth=6, n_tokens=65, patch=4)


def _vit_keys(num_classes: int, dim: int, depth: int, n_tokens: int,
              patch: int) -> "OrderedDict[str, Shape]":
    mlp = 4 * dim
    keys: "OrderedDict[str, Shape]" = OrderedDict()
    keys["cls_token"] = (1, 1, dim)
    keys["pos_embed"] = (1, n_tokens, dim)
    keys["patch_embed.proj.weight"] = (dim, 3, patch, patch)
    keys["patch_embed.proj.bias"] = (dim,)
    for i in range(depth):
        pre = f"blocks.{i}."
        keys[pre + "norm1.weight"] = (dim,)
        keys[pre + "norm1.bias"] = (dim,)
        keys[pre + "attn.qkv.weight"] = (3 * dim, dim)
        keys[pre + "attn.qkv.bias"] = (3 * dim,)
        keys[pre + "attn.proj.weight"] = (dim, dim)
        keys[pre + "attn.proj.bias"] = (dim,)
        keys[pre + "norm2.weight"] = (dim,)
        keys[pre + "norm2.bias"] = (dim,)
        keys[pre + "mlp.fc1.weight"] = (mlp, dim)
        keys[pre + "mlp.fc1.bias"] = (mlp,)
        keys[pre + "mlp.fc2.weight"] = (dim, mlp)
        keys[pre + "mlp.fc2.bias"] = (dim,)
    keys["norm.weight"] = (dim,)
    keys["norm.bias"] = (dim,)
    keys["head.weight"] = (num_classes, dim)
    keys["head.bias"] = (num_classes,)
    return keys


def resmlp_24_keys(num_classes: int) -> "OrderedDict[str, Shape]":
    dim, depth, seq, hidden = 384, 24, 196, 1536
    keys: "OrderedDict[str, Shape]" = OrderedDict()
    keys["stem.proj.weight"] = (dim, 3, 16, 16)
    keys["stem.proj.bias"] = (dim,)
    for i in range(depth):
        pre = f"blocks.{i}."
        keys[pre + "norm1.alpha"] = (1, 1, dim)
        keys[pre + "norm1.beta"] = (1, 1, dim)
        keys[pre + "linear_tokens.weight"] = (seq, seq)
        keys[pre + "linear_tokens.bias"] = (seq,)
        keys[pre + "norm2.alpha"] = (1, 1, dim)
        keys[pre + "norm2.beta"] = (1, 1, dim)
        keys[pre + "mlp_channels.fc1.weight"] = (hidden, dim)
        keys[pre + "mlp_channels.fc1.bias"] = (hidden,)
        keys[pre + "mlp_channels.fc2.weight"] = (dim, hidden)
        keys[pre + "mlp_channels.fc2.bias"] = (dim,)
        keys[pre + "ls1"] = (dim,)
        keys[pre + "ls2"] = (dim,)
    keys["norm.alpha"] = (1, 1, dim)
    keys["norm.beta"] = (1, 1, dim)
    keys["head.weight"] = (num_classes, dim)
    keys["head.bias"] = (num_classes,)
    return keys


def cifar_resnet18_keys(num_classes: int) -> "OrderedDict[str, Shape]":
    """The framework's OWN small-victim checkpoint contract (not a timm
    model): `backends/torch_models.py:CifarResNet18Torch`, the format
    `train.py` exports and `convert_cifar_resnet18` consumes. Pinned here
    so `verify.py --keys-only` covers trained-victim checkpoints too."""
    keys: "OrderedDict[str, Shape]" = OrderedDict()
    keys["stem.weight"] = (64, 3, 3, 3)
    keys["stem_norm.weight"] = (64,)
    keys["stem_norm.bias"] = (64,)
    in_ch, features, flat = 64, 64, 0
    for si, depth in enumerate((2, 2, 2, 2)):
        for bi in range(depth):
            pre = f"blocks.{flat}."
            keys[pre + "conv1.weight"] = (features, in_ch, 3, 3)
            keys[pre + "norm1.weight"] = (features,)
            keys[pre + "norm1.bias"] = (features,)
            keys[pre + "conv2.weight"] = (features, features, 3, 3)
            keys[pre + "norm2.weight"] = (features,)
            keys[pre + "norm2.bias"] = (features,)
            if bi == 0 and si > 0:   # stage transition: projection shortcut
                keys[pre + "proj.0.weight"] = (features, in_ch, 1, 1)
                keys[pre + "proj.1.weight"] = (features,)
                keys[pre + "proj.1.bias"] = (features,)
            in_ch = features
            flat += 1
        features *= 2
    keys["head.weight"] = (num_classes, in_ch)
    keys["head.bias"] = (num_classes,)
    return keys


_CONTRACTS = {
    "resnetv2_50x1_bit_distilled": resnetv2_50x1_keys,
    "vit_base_patch16_224": vit_base_patch16_224_keys,
    "resmlp_24_distilled_224": resmlp_24_keys,
    "cifar_resnet18": cifar_resnet18_keys,
    "cifar_vit": cifar_vit_keys,
}


def state_dict_contract(timm_name: str, num_classes: int) -> Dict[str, Shape]:
    """The pinned timm-0.6.7 state-dict (key -> shape) map for `timm_name`."""
    try:
        return _CONTRACTS[timm_name](num_classes)
    except KeyError:
        raise KeyError(
            f"no vendored key contract for {timm_name!r} "
            f"(have {sorted(_CONTRACTS)})") from None


def diff_against_contract(sd_keys, timm_name: str, num_classes: int,
                          sd_shapes=None) -> Dict[str, list]:
    """Drift report of a state_dict against the vendored contract.

    Returns {"missing": [...], "unexpected": [...], "shape_drift": [...]}
    — all empty iff the checkpoint matches timm 0.6.7 naming exactly.
    """
    contract = state_dict_contract(timm_name, num_classes)
    have = set(sd_keys)
    want = set(contract)
    report = {
        "missing": sorted(want - have),
        "unexpected": sorted(have - want),
        "shape_drift": [],
    }
    if sd_shapes is not None:
        for k in sorted(want & have):
            got = tuple(sd_shapes[k])
            if got != contract[k]:
                report["shape_drift"].append(
                    f"{k}: checkpoint {got} != contract {contract[k]}")
    return report
