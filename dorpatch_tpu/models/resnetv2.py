"""Flax ResNetV2-50x1 (BiT), NHWC, matching timm's `resnetv2_50x1_bit_distilled`.

This is the victim model the reference loads via timm
(`/root/reference/utils.py:47-63`). Architectural contract (timm resnetv2.py,
BiT variant):

- Weight-standardized convs (`StdConv2dSame`, eps=1e-8): per-output-channel
  (w - mean) / sqrt(biased_var + eps), TF-style dynamic SAME padding.
- Pre-activation bottlenecks with GroupNorm(32, eps=1e-5) + ReLU; the
  projection shortcut consumes the *pre-activated* input.
- "Fixed" stem: 7x7/2 std-conv (SAME), then ConstantPad2d(1, value=0) +
  3x3/2 VALID max-pool. The zero-valued pad (not -inf) is a timm quirk that
  must be reproduced exactly for checkpoint parity.
- Head: final GroupNorm+ReLU, global average pool, 1x1 conv classifier
  (converted here to a Dense).

Everything is NHWC and bfloat16-friendly; the MXU-heavy ops are the convs,
which XLA tiles directly.
"""

from __future__ import annotations

from typing import Sequence, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp
from jax.ad_checkpoint import checkpoint_name


class StdConv(nn.Module):
    """Weight-standardized conv, TF SAME padding (timm StdConv2dSame, eps=1e-8)."""

    features: int
    kernel_size: Tuple[int, int]
    strides: Tuple[int, int] = (1, 1)
    eps: float = 1e-8

    @nn.compact
    def __call__(self, x):
        kernel = self.param(
            "kernel",
            nn.initializers.he_normal(),
            (*self.kernel_size, x.shape[-1], self.features),
            jnp.float32,
        )
        mean = jnp.mean(kernel, axis=(0, 1, 2), keepdims=True)
        var = jnp.var(kernel, axis=(0, 1, 2), keepdims=True)
        kernel = (kernel - mean) * jax.lax.rsqrt(var + self.eps)
        out = jax.lax.conv_general_dilated(
            x,
            kernel.astype(x.dtype),
            window_strides=self.strides,
            padding="SAME",
            dimension_numbers=("NHWC", "HWIO", "NHWC"),
        )
        # no-op outside jax.checkpoint; under the "conv" remat policy
        # (attack.py:_grad_fwd) the tag makes conv outputs saveable so the
        # backward replays only the cheap chains between convs
        return checkpoint_name(out, "conv_out")


class _GNParams(nn.Module):
    """Shadow parameter holder for the fused GN path: declares `scale`/`bias`
    under the same module name ("GroupNorm_0") and shapes/dtypes/initializers
    as `nn.GroupNorm`, so both impls share one checkpoint-compatible tree."""

    features: int

    @nn.compact
    def __call__(self):
        scale = self.param("scale", nn.initializers.ones, (self.features,), jnp.float32)
        bias = self.param("bias", nn.initializers.zeros, (self.features,), jnp.float32)
        return scale, bias


class GroupNormRelu(nn.Module):
    """GroupNorm(32, eps=1e-5) + ReLU (timm GroupNormAct).

    Statistics are computed in float32 for numerical parity with the torch
    reference, but everything elementwise stays at the input dtype: under
    the attack's bfloat16 mixed precision (and the bf16 certify bank) the
    surrounding convs must see bf16 activations, or every conv after the
    first GN silently runs on f32 activations at 2x the HBM traffic
    (measured ~26 TFLOP/s vs ~60+ fixed) — and flax's own GroupNorm would
    additionally materialize the normalize chain itself in f32
    (`fused_gn.gn_preserve_dtype` keeps it at x.dtype).

    impl: "auto" (fused Pallas kernel on single-device TPU backends — XLA's
    GN *backward* costs ~23% of the attack step, see `ops/fused_gn.py` —
    flax GroupNorm elsewhere), "flax", "pallas", "interpret", "jnp"
    (the kernel's jnp twin; testing only).
    """

    num_groups: int = 32
    impl: str = "auto"

    @nn.compact
    def __call__(self, x):
        from dorpatch_tpu.ops import fused_gn

        impl = self.impl
        if impl == "auto":
            impl = "pallas" if fused_gn.auto_pallas(x.shape, x.dtype) else "flax"
        scale, bias = _GNParams(x.shape[-1], name="GroupNorm_0")()
        if impl == "flax":
            if x.dtype == jnp.float32:
                # parent=None: construct outside the compact scope, else
                # flax auto-registers a "GroupNorm_0" child colliding with
                # the _GNParams shadow holder above.
                y = nn.GroupNorm(
                    num_groups=self.num_groups, epsilon=1e-5,
                    dtype=jnp.float32, parent=None).apply(
                        {"params": {"scale": scale, "bias": bias}}, x)
                return nn.relu(y)
            # Sub-f32 activations (bf16 attack / certify banks): flax's
            # GroupNorm materializes the whole normalize chain in f32;
            # keep the big elementwise tensors at x.dtype instead.
            y = fused_gn.gn_preserve_dtype(
                x, scale, bias, self.num_groups, eps=1e-5)
            return nn.relu(y)
        return fused_gn.gn_relu(x, scale, bias, self.num_groups, impl=impl)


class PreActBottleneck(nn.Module):
    """Pre-activation bottleneck: GN/ReLU -> 1x1 -> GN/ReLU -> 3x3(stride)
    -> GN/ReLU -> 1x1, with the projection shortcut taken from the
    pre-activated input (timm PreActBottleneck)."""

    out_features: int
    stride: int = 1
    bottle_ratio: float = 0.25
    gn_impl: str = "auto"

    @nn.compact
    def __call__(self, x):
        mid = int(round(self.out_features * self.bottle_ratio))
        preact = GroupNormRelu(name="norm1", impl=self.gn_impl)(x)
        if x.shape[-1] != self.out_features or self.stride != 1:
            shortcut = StdConv(
                self.out_features, (1, 1), (self.stride, self.stride), name="downsample_conv"
            )(preact)
        else:
            shortcut = x
        y = StdConv(mid, (1, 1), name="conv1")(preact)
        y = GroupNormRelu(name="norm2", impl=self.gn_impl)(y)
        y = StdConv(mid, (3, 3), (self.stride, self.stride), name="conv2")(y)
        y = GroupNormRelu(name="norm3", impl=self.gn_impl)(y)
        y = StdConv(self.out_features, (1, 1), name="conv3")(y)
        return y + shortcut


class ResNetV2(nn.Module):
    """BiT ResNetV2 trunk. Defaults = 50x1 (layers 3-4-6-3, width 1)."""

    num_classes: int
    layers: Sequence[int] = (3, 4, 6, 3)
    width_factor: int = 1
    stem_features: int = 64
    gn_impl: str = "auto"

    @nn.compact
    def __call__(self, x, mode: str = "full"):
        """mode="full": logits from images. mode="stem": only the
        weight-standardized stem conv's output, BEFORE the pad+max-pool
        (the pool is nonlinear but local; the linear shareable cache of the
        masked-stem incremental certify path, `ops/stem_fold.py`, must stop
        at the conv). mode="trunk": `x` is a stem-conv output; run the
        pad+pool and everything after. `full(x) == trunk(stem(x))` exactly;
        all modes share one parameter tree."""
        if mode not in ("full", "stem", "trunk"):
            raise ValueError(f"mode={mode!r} (use 'full', 'stem' or 'trunk')")
        wf = self.width_factor
        if mode != "trunk":
            x = StdConv(self.stem_features * wf, (7, 7), (2, 2), name="stem_conv")(x)
            if mode == "stem":
                return x
        # timm "fixed" stem pool: ConstantPad2d(1, 0.) then VALID 3x3/2 pool.
        # Zero pad (not -inf) is deliberate — see module docstring.
        x = jnp.pad(x, ((0, 0), (1, 1), (1, 1), (0, 0)))
        x = nn.max_pool(x, (3, 3), strides=(2, 2), padding="VALID")

        features = 256
        for si, depth in enumerate(self.layers):
            for bi in range(depth):
                stride = 2 if (bi == 0 and si > 0) else 1
                x = PreActBottleneck(
                    features * wf, stride=stride, name=f"stage{si}_block{bi}",
                    gn_impl=self.gn_impl,
                )(x)
            features *= 2

        x = GroupNormRelu(name="norm", impl=self.gn_impl)(x)
        x = jnp.mean(x, axis=(1, 2))
        x = nn.Dense(self.num_classes, name="head")(x)
        return x


def resnetv2_50x1(num_classes: int, gn_impl: str = "auto") -> ResNetV2:
    return ResNetV2(num_classes=num_classes, gn_impl=gn_impl)
