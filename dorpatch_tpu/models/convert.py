"""timm/torch checkpoint -> flax params conversion.

The reference's model path loads timm checkpoints from the PatchCleanser
release (`/root/reference/utils.py:47-63`, `<model>_cutout2_128_<dataset>.pth`).
This module converts those torch state_dicts into the parameter pytrees of the
flax models in `dorpatch_tpu.models`, handling OIHW->HWIO conv layout,
conv1x1-head -> Dense, and GroupNorm weight/bias -> scale/bias renames.
"""

from __future__ import annotations

from typing import Dict, Mapping, Sequence

import numpy as np


def _np(t) -> np.ndarray:
    if hasattr(t, "detach"):
        t = t.detach().cpu().numpy()
    return np.asarray(t, dtype=np.float32)


def _conv_kernel(w) -> np.ndarray:
    """OIHW -> HWIO."""
    return _np(w).transpose(2, 3, 1, 0)


def load_state_dict(path: str) -> Dict[str, np.ndarray]:
    """Load a torch checkpoint file and return a flat numpy state_dict.

    Accepts either a bare state_dict or the reference checkpoints'
    `{'state_dict': ...}` wrapper; strips DataParallel `module.` prefixes.
    """
    import torch

    obj = torch.load(path, map_location="cpu", weights_only=True)
    if isinstance(obj, dict) and "state_dict" in obj:
        obj = obj["state_dict"]
    out = {}
    for k, v in obj.items():
        if k.startswith("module."):
            k = k[len("module."):]
        out[k] = _np(v)
    return out


def convert_resnetv2(
    sd: Mapping[str, np.ndarray], layers: Sequence[int] = (3, 4, 6, 3)
) -> Dict:
    """Convert a timm `resnetv2_*_bit*` state_dict to flax ResNetV2 params.

    Key map (timm -> flax):
      stem.conv.weight                      -> stem_conv/kernel
      stages.S.blocks.B.normK.{weight,bias} -> stageS_blockB/normK/GroupNorm_0/{scale,bias}
      stages.S.blocks.B.convK.weight        -> stageS_blockB/convK/kernel
      stages.S.blocks.B.downsample.conv.weight -> stageS_blockB/downsample_conv/kernel
      norm.{weight,bias}                    -> norm/GroupNorm_0/{scale,bias}
      head.fc.{weight,bias} (1x1 conv)      -> head/{kernel,bias} (Dense)
    """
    params: Dict = {"stem_conv": {"kernel": _conv_kernel(sd["stem.conv.weight"])}}
    for si, depth in enumerate(layers):
        for bi in range(depth):
            src = f"stages.{si}.blocks.{bi}."
            blk: Dict = {}
            for k in (1, 2, 3):
                blk[f"norm{k}"] = {
                    "GroupNorm_0": {
                        "scale": _np(sd[src + f"norm{k}.weight"]),
                        "bias": _np(sd[src + f"norm{k}.bias"]),
                    }
                }
                blk[f"conv{k}"] = {"kernel": _conv_kernel(sd[src + f"conv{k}.weight"])}
            if src + "downsample.conv.weight" in sd:
                blk["downsample_conv"] = {
                    "kernel": _conv_kernel(sd[src + "downsample.conv.weight"])
                }
            params[f"stage{si}_block{bi}"] = blk
    params["norm"] = {
        "GroupNorm_0": {"scale": _np(sd["norm.weight"]), "bias": _np(sd["norm.bias"])}
    }
    head_w = _np(sd["head.fc.weight"])  # [num_classes, C, 1, 1]
    params["head"] = {
        "kernel": head_w[:, :, 0, 0].T,
        "bias": _np(sd["head.fc.bias"]),
    }
    return {"params": params}


def _dense(sd, key):
    """torch Linear -> flax Dense: weight [out,in] -> kernel [in,out]."""
    return {"kernel": _np(sd[key + ".weight"]).T, "bias": _np(sd[key + ".bias"])}


def _layernorm(sd, key):
    return {"scale": _np(sd[key + ".weight"]), "bias": _np(sd[key + ".bias"])}


def convert_vit(sd: Mapping[str, np.ndarray], depth: int = 12, num_heads: int = 12) -> Dict:
    """Convert a timm `vit_base_patch16_224` state_dict to flax ViT params.

    The fused qkv Linear `[3D, D]` splits into flax attention's per-projection
    DenseGeneral kernels `[D, heads, head_dim]` (head-major, matching timm's
    `reshape(B,N,3,heads,hd)`); `attn.proj` becomes the `[heads, head_dim, D]`
    output kernel.
    """
    dim = _np(sd["cls_token"]).shape[-1]
    hd = dim // num_heads
    params: Dict = {
        "cls_token": _np(sd["cls_token"]),
        "pos_embed": _np(sd["pos_embed"]),
        "patch_embed": {
            "kernel": _conv_kernel(sd["patch_embed.proj.weight"]),
            "bias": _np(sd["patch_embed.proj.bias"]),
        },
        "norm": _layernorm(sd, "norm"),
        "head": _dense(sd, "head"),
    }
    for i in range(depth):
        src = f"blocks.{i}."
        qkv_w = _np(sd[src + "attn.qkv.weight"])  # [3D, D]
        qkv_b = _np(sd[src + "attn.qkv.bias"])
        attn = {}
        for j, name in enumerate(("query", "key", "value")):
            attn[name] = {
                "kernel": qkv_w[j * dim:(j + 1) * dim].T.reshape(dim, num_heads, hd),
                "bias": qkv_b[j * dim:(j + 1) * dim].reshape(num_heads, hd),
            }
        attn["out"] = {
            "kernel": _np(sd[src + "attn.proj.weight"]).T.reshape(num_heads, hd, dim),
            "bias": _np(sd[src + "attn.proj.bias"]),
        }
        params[f"block{i}"] = {
            "norm1": _layernorm(sd, src + "norm1"),
            "attn": attn,
            "norm2": _layernorm(sd, src + "norm2"),
            "mlp_fc1": _dense(sd, src + "mlp.fc1"),
            "mlp_fc2": _dense(sd, src + "mlp.fc2"),
        }
    return {"params": params}


def export_vit(params: Mapping) -> Dict[str, np.ndarray]:
    """Inverse of `convert_vit`: flax ViT params -> timm-shaped state_dict
    (per-projection DenseGeneral kernels re-fused into the `[3D, D]` qkv
    Linear, HWIO->OIHW patch embed, Dense kernels transposed). Depth/dim
    are inferred from the pytree, so the same exporter serves the small
    `vit_cifar` trained victims and any full-size ViT. Round-trip
    (export -> `ViTTorch.load_state_dict(strict=True)` -> `convert_vit`)
    is pinned by `tests/test_models.py`."""
    p = params["params"] if "params" in params else params

    def arr(a):
        return np.asarray(a, dtype=np.float32)

    dim = arr(p["cls_token"]).shape[-1]
    sd: Dict[str, np.ndarray] = {
        "cls_token": arr(p["cls_token"]),
        "pos_embed": arr(p["pos_embed"]),
        "patch_embed.proj.weight": arr(p["patch_embed"]["kernel"]).transpose(3, 2, 0, 1),
        "patch_embed.proj.bias": arr(p["patch_embed"]["bias"]),
        "norm.weight": arr(p["norm"]["scale"]),
        "norm.bias": arr(p["norm"]["bias"]),
        "head.weight": arr(p["head"]["kernel"]).T,
        "head.bias": arr(p["head"]["bias"]),
    }
    depth = sum(1 for k in p if k.startswith("block"))
    for i in range(depth):
        blk = p[f"block{i}"]
        dst = f"blocks.{i}."
        sd[dst + "norm1.weight"] = arr(blk["norm1"]["scale"])
        sd[dst + "norm1.bias"] = arr(blk["norm1"]["bias"])
        sd[dst + "norm2.weight"] = arr(blk["norm2"]["scale"])
        sd[dst + "norm2.bias"] = arr(blk["norm2"]["bias"])
        attn = blk["attn"]
        # [D, heads, hd] kernel -> [D_out, D_in] torch rows, stacked q/k/v
        sd[dst + "attn.qkv.weight"] = np.concatenate(
            [arr(attn[n]["kernel"]).reshape(dim, dim).T
             for n in ("query", "key", "value")], axis=0)
        sd[dst + "attn.qkv.bias"] = np.concatenate(
            [arr(attn[n]["bias"]).reshape(dim) for n in ("query", "key", "value")])
        sd[dst + "attn.proj.weight"] = arr(attn["out"]["kernel"]).reshape(dim, dim).T
        sd[dst + "attn.proj.bias"] = arr(attn["out"]["bias"])
        sd[dst + "mlp.fc1.weight"] = arr(blk["mlp_fc1"]["kernel"]).T
        sd[dst + "mlp.fc1.bias"] = arr(blk["mlp_fc1"]["bias"])
        sd[dst + "mlp.fc2.weight"] = arr(blk["mlp_fc2"]["kernel"]).T
        sd[dst + "mlp.fc2.bias"] = arr(blk["mlp_fc2"]["bias"])
    return sd


def convert_cifar_resnet18(
    sd: Mapping[str, np.ndarray], stage_sizes: Sequence[int] = (2, 2, 2, 2)
) -> Dict:
    """Convert a `backends.torch_models.CifarResNet18Torch` state_dict to the
    flax `models.small.CifarResNet18` params (used by backend-parity tests to
    run both backends with identical weights)."""

    def gn(prefix):
        return {"scale": _np(sd[prefix + ".weight"]), "bias": _np(sd[prefix + ".bias"])}

    params: Dict = {
        "stem": {"kernel": _conv_kernel(sd["stem.weight"])},
        "stem_norm": gn("stem_norm"),
        "head": _dense(sd, "head"),
    }
    bi_flat = 0
    for si, depth in enumerate(stage_sizes):
        for bi in range(depth):
            src = f"blocks.{bi_flat}."
            blk: Dict = {
                "conv1": {"kernel": _conv_kernel(sd[src + "conv1.weight"])},
                "norm1": gn(src + "norm1"),
                "conv2": {"kernel": _conv_kernel(sd[src + "conv2.weight"])},
                "norm2": gn(src + "norm2"),
            }
            if src + "proj.0.weight" in sd:
                blk["proj"] = {"kernel": _conv_kernel(sd[src + "proj.0.weight"])}
                blk["proj_norm"] = gn(src + "proj.1")
            params[f"stage{si}_block{bi}"] = blk
            bi_flat += 1
    return {"params": params}


def export_cifar_resnet18(
    params: Mapping, stage_sizes: Sequence[int] = (2, 2, 2, 2)
) -> Dict[str, np.ndarray]:
    """Inverse of `convert_cifar_resnet18`: flax params -> torch-twin
    state_dict (HWIO->OIHW, Dense kernel transposed). Lets `train.py` export
    a trained victim as a `.pth` the standard checkpoint path loads —
    round-trip pinned by `tests/test_models.py`."""
    p = params["params"] if "params" in params else params

    def arr(a):
        return np.asarray(a, dtype=np.float32)

    def conv(kernel):
        return arr(kernel).transpose(3, 2, 0, 1)

    sd: Dict[str, np.ndarray] = {
        "stem.weight": conv(p["stem"]["kernel"]),
        "stem_norm.weight": arr(p["stem_norm"]["scale"]),
        "stem_norm.bias": arr(p["stem_norm"]["bias"]),
        "head.weight": arr(p["head"]["kernel"]).T,
        "head.bias": arr(p["head"]["bias"]),
    }
    bi_flat = 0
    for si, depth in enumerate(stage_sizes):
        for bi in range(depth):
            blk = p[f"stage{si}_block{bi}"]
            dst = f"blocks.{bi_flat}."
            sd[dst + "conv1.weight"] = conv(blk["conv1"]["kernel"])
            sd[dst + "norm1.weight"] = arr(blk["norm1"]["scale"])
            sd[dst + "norm1.bias"] = arr(blk["norm1"]["bias"])
            sd[dst + "conv2.weight"] = conv(blk["conv2"]["kernel"])
            sd[dst + "norm2.weight"] = arr(blk["norm2"]["scale"])
            sd[dst + "norm2.bias"] = arr(blk["norm2"]["bias"])
            if "proj" in blk:
                sd[dst + "proj.0.weight"] = conv(blk["proj"]["kernel"])
                sd[dst + "proj.1.weight"] = arr(blk["proj_norm"]["scale"])
                sd[dst + "proj.1.bias"] = arr(blk["proj_norm"]["bias"])
            bi_flat += 1
    return sd


def convert_resmlp(sd: Mapping[str, np.ndarray], depth: int = 24) -> Dict:
    """Convert a timm `resmlp_24_distilled_224` state_dict to flax ResMLP params.

    timm `MlpMixer` naming (`timm_keys.py` fixture): the patch embed is
    `stem.proj` (not ViT's `patch_embed.proj`), and `Affine` alpha/beta are
    stored `[1, 1, D]` — flattened here to the flax `[D]` params.
    """

    def affine(key):
        return {"alpha": _np(sd[key + ".alpha"]).reshape(-1),
                "beta": _np(sd[key + ".beta"]).reshape(-1)}

    params: Dict = {
        "patch_embed": {
            "kernel": _conv_kernel(sd["stem.proj.weight"]),
            "bias": _np(sd["stem.proj.bias"]),
        },
        "norm": affine("norm"),
        "head": _dense(sd, "head"),
    }
    for i in range(depth):
        src = f"blocks.{i}."
        params[f"block{i}"] = {
            "ls1": _np(sd[src + "ls1"]),
            "ls2": _np(sd[src + "ls2"]),
            "norm1": affine(src + "norm1"),
            "linear_tokens": _dense(sd, src + "linear_tokens"),
            "norm2": affine(src + "norm2"),
            "mlp_fc1": _dense(sd, src + "mlp_channels.fc1"),
            "mlp_fc2": _dense(sd, src + "mlp_channels.fc2"),
        }
    return {"params": params}
