"""Flax ResMLP-24, NHWC, matching timm's `resmlp_24_distilled_224`, plus
the mixer-pruned incremental masked-inference engine.

Third victim family of the reference (`/root/reference/utils.py:51-52`).
timm contract (mlp_mixer.py ResBlock): 16x16 conv patch embed -> 196 tokens
of dim 384; 24 residual blocks of [Affine norm -> token-mixing Linear(196,196)
on the transposed sequence -> layerscale] and [Affine norm -> channel MLP
(ratio 4, exact GELU) -> layerscale]; final Affine; mean pool; linear head.

Incremental masked inference (`MixerPrunedResMLP`, ROADMAP item 3c): a
PatchCleanser occlusion mask touches a few patch tokens, and ResMLP's only
cross-token operator — the Affine -> token-mixing Linear path — is exactly
linear in its input. So per masked entry the engine tracks only the S
mask-touched (dirty) token rows: the clean per-block inputs, token-mix
outputs, and final pre-pool activations are cached once per image
(`ResMLP.__call__` mode="cache"), and each block propagates the dirty
rows' delta through the token mix as a skinny `[S, S]` slice of the
`[T, T]` mixing matmul (`K[idx][:, idx]` — the dirty rows' contribution to
the dirty outputs; contributions to clean rows are dropped, see below)
before the per-token channel MLP runs dense on the S dirty rows alone.
The mean-pool head is linear too, so the final logits are the cached
clean logits plus a rank-S pooled delta through the head matrix.

Exactness contract (the ViT token engine's, `models/vit.py`): dirty-row
updates are exact given their block inputs — the token-mix delta `z_dirty
= z_clean[idx] + K[idx, idx]^T (alpha1 * (d - x_clean[idx]))` is the
masked forward's exact mix value when all non-dirty tokens hold their
clean activations — but untouched tokens keep clean activations at every
depth, while the true masked forward would drift them through the mixing
matrix from block 1 on. Programs therefore return top-2 logit margins and
`defense.py`'s "mixer-exact" mode re-runs near-boundary images through
the exhaustive program, keeping verdicts bit-identical whenever the drift
stays below `DefenseConfig.incremental_margin`.
"""

from __future__ import annotations

from typing import Callable, NamedTuple, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np

from dorpatch_tpu import masks as masks_lib


class Affine(nn.Module):
    """Per-channel scale+shift (timm's Affine: alpha*x + beta)."""

    dim: int

    @nn.compact
    def __call__(self, x):
        alpha = self.param("alpha", nn.initializers.ones, (self.dim,), jnp.float32)
        beta = self.param("beta", nn.initializers.zeros, (self.dim,), jnp.float32)
        return alpha * x + beta


class ResMLPBlock(nn.Module):
    dim: int
    seq_len: int
    mlp_ratio: int = 4

    @nn.compact
    def __call__(self, x, return_mix: bool = False):
        ls1 = self.param("ls1", nn.initializers.ones, (self.dim,), jnp.float32)
        ls2 = self.param("ls2", nn.initializers.ones, (self.dim,), jnp.float32)
        y = Affine(self.dim, name="norm1")(x)
        y = nn.Dense(self.seq_len, name="linear_tokens")(y.transpose(0, 2, 1))
        z = y.transpose(0, 2, 1)  # [B, T, D] token-mix output (pre-layerscale)
        x = x + ls1 * z
        y = Affine(self.dim, name="norm2")(x)
        y = nn.Dense(self.dim * self.mlp_ratio, name="mlp_fc1")(y)
        y = nn.gelu(y, approximate=False)
        y = nn.Dense(self.dim, name="mlp_fc2")(y)
        x = x + ls2 * y
        return (x, z) if return_mix else x


class ResMLP(nn.Module):
    num_classes: int
    patch_size: int = 16
    dim: int = 384
    depth: int = 24
    img_size: int = 224

    @nn.compact
    def __call__(self, x, mode: str = "full"):
        """mode="full": logits. mode="cache": `(block_inputs, mix_outputs,
        final)` — the per-block INPUT activations `depth x [B, T, D]`, the
        per-block token-mix outputs `depth x [B, T, D]` (post-transpose,
        bias included, pre-layerscale — collected where the block computes
        them anyway, so cache mode costs one ordinary forward), and the
        final pre-Affine activations `[B, T, D]`. The mixer incremental
        engine's clean cache."""
        if mode not in ("full", "cache"):
            raise ValueError(f"mode={mode!r} (use 'full' or 'cache')")
        B = x.shape[0]
        x = nn.Conv(
            self.dim,
            (self.patch_size, self.patch_size),
            strides=(self.patch_size, self.patch_size),
            padding="VALID",
            name="patch_embed",
        )(x)
        x = x.reshape(B, -1, self.dim)
        seq_len = x.shape[1]
        xs, zs = [], []
        for i in range(self.depth):
            block = ResMLPBlock(self.dim, seq_len, name=f"block{i}")
            if mode == "cache":
                xs.append(x)
                x, z = block(x, return_mix=True)
                zs.append(z)
            else:
                x = block(x)
        if mode == "cache":
            return tuple(xs), tuple(zs), x
        x = Affine(self.dim, name="norm")(x)
        x = x.mean(axis=1)
        return nn.Dense(self.num_classes, name="head")(x)


def resmlp_24(num_classes: int) -> ResMLP:
    return ResMLP(num_classes=num_classes)


# CIFAR-scale ResMLP (the `vit_cifar` idiom): 4px patches on 32px images
# -> 64 tokens of dim 128, depth 6 — the third victim family at sweep /
# audit scale, small enough to trace and certify on CPU CI.
CIFAR_RESMLP = dict(patch_size=4, dim=128, depth=6, img_size=32)


def resmlp_cifar(num_classes: int) -> ResMLP:
    return ResMLP(num_classes=num_classes, **CIFAR_RESMLP)


# ------------------------------------------- mixer-pruned incremental engine


def _default_normalize(x):
    """Fallback for directly-constructed engines; the factory
    (`models.registry.incremental_engine`) always passes its own
    `registry._normalize`, the single production definition."""
    return (x - 0.5) / 0.5


class _MixerTables(NamedTuple):
    """Static per-mask-family lookup tables, device-resident (the
    `models.vit._TokenTables` idiom; no cls slot — ResMLP mean-pools)."""

    idx: jax.Array   # [N, S] int32 dirty token ids (pad repeats the first)
    keep: jax.Array  # [N, S, p, p, 1] f32 pixel keep-mask per dirty slot
    w: jax.Array     # [N, S] f32 slot weight: 1 real, 0 duplicate padding
    #                  (multiplicative — the mixer SUMS dirty contributions,
    #                  so duplicate slots must contribute zero, where the
    #                  ViT engine's additive -1e9 softmax bias lives)
    fe: np.ndarray   # [N] float64 forward equivalents: dirty tokens / T


def _build_mixer_tables(rects: np.ndarray, img_size: int,
                        patch: int) -> _MixerTables:
    """Token sets + per-token pixel keep masks for one rectangle table.
    Slots beyond a mask's real coverage repeat slot 0 (same token, same
    keep mask) and carry weight 0, so they compute the identical dirty
    value and contribute nothing to any linear sum."""
    rects = np.asarray(rects, np.int64)
    if rects.ndim == 2:
        rects = rects[:, None, :]
    grid = img_size // patch
    cov = masks_lib.rect_token_coverage(rects, img_size, patch)  # [N, T]
    n, t_total = cov.shape
    s_max = int(cov.sum(axis=1).max()) if n else 1
    idx = np.zeros((n, s_max), np.int32)
    keep = np.ones((n, s_max, patch, patch, 1), np.float32)
    w = np.zeros((n, s_max), np.float32)
    for i in range(n):
        toks = np.nonzero(cov[i])[0]
        padded = np.concatenate([toks, np.full(s_max - len(toks), toks[0])])
        idx[i] = padded
        w[i, :len(toks)] = 1.0
        for s, tok in enumerate(padded):
            pr, pc = divmod(int(tok), grid)
            r_off, c_off = pr * patch, pc * patch
            for r0, r1, c0, c1 in rects[i]:
                rr0, rr1 = max(r0 - r_off, 0), min(r1 - r_off, patch)
                cc0, cc1 = max(c0 - c_off, 0), min(c1 - c_off, patch)
                if rr0 < rr1 and cc0 < cc1:
                    keep[i, s, rr0:rr1, cc0:cc1, 0] = 0.0
    fe = cov.sum(axis=1) / float(t_total)
    return _MixerTables(jnp.asarray(idx), jnp.asarray(keep),
                        jnp.asarray(w), fe)


class ResMLPMixerFamily:
    """One mask family's incremental programs (the `models.vit.
    TokenViTFamily` contract): `phase1`/`pairs`/`rows`, each returning
    `(preds int32, margins f32)`, with forward-equivalent weights in
    `.fe` and the per-image clean-cache cost in `.cache_fe`."""

    def __init__(self, engine: "MixerPrunedResMLP", rects: np.ndarray,
                 num_singles: int, chunk_size: int, fill: float,
                 use_pallas: str = "auto", mesh=None,
                 data_axis: str = "data", compute_dtype: str = "float32"):
        self.engine = engine
        self.num_singles = int(num_singles)
        self.chunk_size = max(1, int(chunk_size))
        self.fill = float(fill)
        # accepted for build_family signature parity with the kernel-tier
        # engines; the mixer's skinny [S, S] mix slice + dense dirty-row
        # MLP is already plain matmuls XLA fuses — no Pallas tier (yet),
        # so the mesh rides plain GSPMD propagation and is recorded only
        self.use_pallas = use_pallas
        self.mesh = mesh
        self.data_axis = data_axis
        self.compute_dtype = jnp.dtype(compute_dtype)
        img, patch = engine.img_size, engine.patch
        self.first = _build_mixer_tables(rects[:num_singles], img, patch)
        self.pair_tables = _build_mixer_tables(rects[num_singles:], img,
                                               patch)
        self.combined = _build_mixer_tables(rects, img, patch)
        if self.compute_dtype != jnp.float32:
            # cast the static float tables once at family build: a bf16
            # activation times an f32 keep mask or slot weight silently
            # promotes the chain back to f32 (the DP208 leak)
            def cast(t):
                return t._replace(keep=t.keep.astype(self.compute_dtype),
                                  w=t.w.astype(self.compute_dtype))

            self.first = cast(self.first)
            self.pair_tables = cast(self.pair_tables)
            self.combined = cast(self.combined)
        self.fe = self.combined.fe
        self.fe_first = float(self.fe[:num_singles].sum())
        self.fe_pairs = float(self.fe[num_singles:].sum())
        # per-invocation clean-cache cost in full-forward units: "cache"
        # mode runs every block (collecting the mix outputs where the
        # block computes them anyway), i.e. one forward minus the head
        self.cache_fe = 1.0

    def phase1(self, params, imgs):
        # program-boundary image cast (no-op at f32): callers hand f32
        # batches regardless of the bank's sweep dtype
        return self.engine._table(params, imgs.astype(self.compute_dtype),
                                  self.first, self.fill, self.chunk_size)

    def pairs(self, params, imgs):
        return self.engine._table(params, imgs.astype(self.compute_dtype),
                                  self.pair_tables, self.fill,
                                  self.chunk_size)

    def rows(self, params, imgs_g, sets_idx):
        return self.engine._rows(params, imgs_g.astype(self.compute_dtype),
                                 sets_idx, self.combined,
                                 self.fill, self.chunk_size)


class MixerPrunedResMLP:
    """Mixer-pruned incremental masked inference for one ResMLP victim.

    Built by `models.registry.incremental_engine` for the ResMLP family
    and handed to `defense.build_defenses(..., incremental=...)`;
    `build_family` is called once per certifier (mask radius) with its
    combined rectangle table."""

    kind = "mixer"

    def __init__(self, module: ResMLP, img_size: int,
                 normalize: Optional[Callable[[jax.Array], jax.Array]] = None):
        if img_size % module.patch_size:
            raise ValueError(
                f"img_size={img_size} not divisible by patch "
                f"{module.patch_size}")
        self.module = module
        self.img_size = int(img_size)
        self.patch = int(module.patch_size)
        self.grid = self.img_size // self.patch
        self.tokens = self.grid * self.grid
        self.normalize = normalize or _default_normalize

    def build_family(self, rects: np.ndarray, num_singles: int,
                     chunk_size: int, fill: float,
                     use_pallas: str = "auto", mesh=None,
                     data_axis: str = "data",
                     compute_dtype: str = "float32") -> ResMLPMixerFamily:
        return ResMLPMixerFamily(self, rects, num_singles, chunk_size,
                                 fill, use_pallas=use_pallas, mesh=mesh,
                                 data_axis=data_axis,
                                 compute_dtype=compute_dtype)

    # ------------------------------------------------------------ internals

    def _patches(self, imgs: jax.Array) -> jax.Array:
        """[B, H, W, C] -> [B, T, p, p, C] row-major patches (the conv
        patch embed's token order)."""
        b, h, w, c = imgs.shape
        p, g = self.patch, self.grid
        x = imgs.reshape(b, g, p, g, p, c)
        return jnp.transpose(x, (0, 1, 3, 2, 4, 5)).reshape(b, g * g, p, p, c)

    def _embed(self, params, patches_g, keep, fill):
        """Dirty-token embeddings: occlude the gathered raw patches with
        the static keep masks, normalize, apply the patch-embed conv (a
        p-stride p-kernel conv == one einsum per token). No cls token, no
        position embedding — ResMLP has neither."""
        p = params["params"]
        masked = patches_g * keep + fill * (1.0 - keep)
        xn = self.normalize(masked)
        return jnp.einsum("...hwc,hwcd->...d", xn,
                          p["patch_embed"]["kernel"]) \
            + p["patch_embed"]["bias"]

    def _clean(self, params, imgs):
        """The per-image clean cache: block inputs, token-mix outputs,
        final pre-Affine activations — plus the clean logits (the Affine
        + mean pool + head are linear, so per entry only a pooled dirty
        delta rides through the head)."""
        xs, zs, xf = self.module.apply(params, self.normalize(imgs),
                                       "cache")
        p = params["params"]
        pooled = (p["norm"]["alpha"] * xf + p["norm"]["beta"]).mean(axis=1)
        logits0 = pooled @ p["head"]["kernel"] + p["head"]["bias"]
        return xs, zs, xf, logits0

    def _forward(self, params, d, xs, zs, xf, logits0, idxc, wc):
        """Dirty tokens `d [B, C, S, D]` (C masks per image) through every
        block against the per-image clean cache. Per block: the token-mix
        value at each dirty row is the cached clean mix `zs[l]` gathered
        at the dirty ids plus the skinny delta `K[idx][:, idx]^T (alpha1 *
        (d - x_clean[idx]))` — exactly the masked mix under the
        frozen-clean-rows approximation, because the mix is linear and
        only the dirty rows' inputs differ. Then the channel MLP runs
        dense on the S dirty rows. Finally the clean logits get the
        pooled dirty delta through the head. `wc [B, C, S]` zeroes
        duplicate padding slots out of every sum."""
        p = params["params"]
        t = self.tokens
        gather = jax.vmap(lambda a, i: a[i])   # [B,T,D], [B,C,S] -> [B,C,S,D]
        for layer in range(self.module.depth):
            bp = p[f"block{layer}"]
            k_mix = bp["linear_tokens"]["kernel"]        # [T, T]
            xg = gather(xs[layer], idxc)
            zg = gather(zs[layer], idxc)
            a1 = bp["norm1"]["alpha"] * (d - xg) * wc[..., None]
            # [B, C, S, S] dirty->dirty slice of the [T, T] mixing matmul
            kss = k_mix[idxc[..., :, None], idxc[..., None, :]]
            dz = jnp.einsum("bcsd,bcst->bctd", a1, kss)
            d = d + bp["ls1"] * (zg + dz)
            y = bp["norm2"]["alpha"] * d + bp["norm2"]["beta"]
            h = nn.gelu(y @ bp["mlp_fc1"]["kernel"]
                        + bp["mlp_fc1"]["bias"], approximate=False)
            d = d + bp["ls2"] * (h @ bp["mlp_fc2"]["kernel"]
                                 + bp["mlp_fc2"]["bias"])
        xgf = gather(xf, idxc)
        pooled_d = ((d - xgf) * wc[..., None]).sum(axis=2) / float(t)
        return logits0[:, None] \
            + (p["norm"]["alpha"] * pooled_d) @ p["head"]["kernel"]

    @staticmethod
    def _preds_margins(logits):
        from dorpatch_tpu.utils import preds_margins

        return preds_margins(logits)

    def _chunk(self, params, patches, clean, idxc, keepc, wc, fill):
        """One mask chunk: [B images, c masks] dirty-token batch against
        the per-image clean cache (shared across the mask axis). Tables
        are PER-IMAGE (`[B, c, ...]`), the `models.vit` chunk contract."""
        pg = jax.vmap(lambda pp, ii: pp[ii])(patches, idxc)  # [B,c,S,p,p,C]
        d = self._embed(params, pg, keepc, fill)             # [B, c, S, D]
        logits = self._forward(params, d, *clean, idxc, wc)
        return self._preds_margins(logits)                   # [B, c] each

    def _table(self, params, imgs, tables: _MixerTables, fill, chunk_size):
        """All N masks of `tables` over the batch -> (preds, margins)
        `[B, N]`, scanning mask chunks of <= chunk_size. Padding masks
        repeat entry 0 and are sliced off."""
        n = int(tables.idx.shape[0])
        c = min(max(1, int(chunk_size)), n) if n else 1
        n_chunks = -(-n // c) if n else 0
        pad = n_chunks * c - n

        def padded(t):
            return jnp.concatenate(
                [t, jnp.broadcast_to(t[:1], (pad,) + t.shape[1:])]
            ).reshape((n_chunks, c) + t.shape[1:])

        idx_p = padded(tables.idx)
        keep_p = padded(tables.keep)
        w_p = padded(tables.w)
        clean = self._clean(params, imgs)
        patches = self._patches(imgs)
        b = imgs.shape[0]

        def body(carry, xs_):
            idxc, keepc, wc = xs_

            def bc(t):  # shared mask chunk -> per-image [B, c, ...]
                return jnp.broadcast_to(t[None], (b,) + t.shape)

            return carry, self._chunk(params, patches, clean, bc(idxc),
                                      bc(keepc), bc(wc), fill)

        _, (preds, margins) = jax.lax.scan(body, None, (idx_p, keep_p, w_p))
        preds = jnp.moveaxis(preds, 0, 1).reshape(b, -1)[:, :n]
        margins = jnp.moveaxis(margins, 0, 1).reshape(b, -1)[:, :n]
        return preds, margins

    def _rows(self, params, imgs_g, sets_idx, combined: _MixerTables, fill,
              chunk_size):
        """Ragged second-round rows: entry w = (gathered image, [M2] row
        of combined-table mask indices), chunked exactly like
        `models.vit.TokenPrunedViT._rows`."""
        w, m2 = int(sets_idx.shape[0]), int(sets_idx.shape[1])
        c = max(1, min(m2, int(chunk_size) // max(1, w)))
        n_chunks = -(-m2 // c)
        pad = n_chunks * c - m2
        sets_p = jnp.concatenate(
            [sets_idx, jnp.broadcast_to(sets_idx[:, :1], (w, pad))], axis=1)
        idx_all = combined.idx[sets_p]        # [W, M2p, S]
        keep_all = combined.keep[sets_p]      # [W, M2p, S, p, p, 1]
        w_all = combined.w[sets_p]            # [W, M2p, S]
        clean = self._clean(params, imgs_g)
        patches = self._patches(imgs_g)

        def chunked(t):  # [W, M2p, ...] -> scan xs [nc, W, c, ...]
            return jnp.moveaxis(
                t.reshape((w, n_chunks, c) + t.shape[2:]), 1, 0)

        def body(carry, xs_):
            idxc, keepc, wc = xs_             # [W, c, ...]
            return carry, self._chunk(params, patches, clean, idxc, keepc,
                                      wc, fill)

        _, (preds, margins) = jax.lax.scan(
            body, None, (chunked(idx_all), chunked(keep_all),
                         chunked(w_all)))
        preds = jnp.moveaxis(preds, 0, 1).reshape(w, -1)[:, :m2]
        margins = jnp.moveaxis(margins, 0, 1).reshape(w, -1)[:, :m2]
        return preds, margins
