"""Flax ResMLP-24, NHWC, matching timm's `resmlp_24_distilled_224`.

Third victim family of the reference (`/root/reference/utils.py:51-52`).
timm contract (mlp_mixer.py ResBlock): 16x16 conv patch embed -> 196 tokens
of dim 384; 24 residual blocks of [Affine norm -> token-mixing Linear(196,196)
on the transposed sequence -> layerscale] and [Affine norm -> channel MLP
(ratio 4, exact GELU) -> layerscale]; final Affine; mean pool; linear head.
"""

from __future__ import annotations

import flax.linen as nn
import jax.numpy as jnp


class Affine(nn.Module):
    """Per-channel scale+shift (timm's Affine: alpha*x + beta)."""

    dim: int

    @nn.compact
    def __call__(self, x):
        alpha = self.param("alpha", nn.initializers.ones, (self.dim,), jnp.float32)
        beta = self.param("beta", nn.initializers.zeros, (self.dim,), jnp.float32)
        return alpha * x + beta


class ResMLPBlock(nn.Module):
    dim: int
    seq_len: int
    mlp_ratio: int = 4

    @nn.compact
    def __call__(self, x):
        ls1 = self.param("ls1", nn.initializers.ones, (self.dim,), jnp.float32)
        ls2 = self.param("ls2", nn.initializers.ones, (self.dim,), jnp.float32)
        y = Affine(self.dim, name="norm1")(x)
        y = nn.Dense(self.seq_len, name="linear_tokens")(y.transpose(0, 2, 1))
        x = x + ls1 * y.transpose(0, 2, 1)
        y = Affine(self.dim, name="norm2")(x)
        y = nn.Dense(self.dim * self.mlp_ratio, name="mlp_fc1")(y)
        y = nn.gelu(y, approximate=False)
        y = nn.Dense(self.dim, name="mlp_fc2")(y)
        return x + ls2 * y


class ResMLP(nn.Module):
    num_classes: int
    patch_size: int = 16
    dim: int = 384
    depth: int = 24
    img_size: int = 224

    @nn.compact
    def __call__(self, x):
        B = x.shape[0]
        x = nn.Conv(
            self.dim,
            (self.patch_size, self.patch_size),
            strides=(self.patch_size, self.patch_size),
            padding="VALID",
            name="patch_embed",
        )(x)
        x = x.reshape(B, -1, self.dim)
        seq_len = x.shape[1]
        for i in range(self.depth):
            x = ResMLPBlock(self.dim, seq_len, name=f"block{i}")(x)
        x = Affine(self.dim, name="norm")(x)
        x = x.mean(axis=1)
        return nn.Dense(self.num_classes, name="head")(x)


def resmlp_24(num_classes: int) -> ResMLP:
    return ResMLP(num_classes=num_classes)
