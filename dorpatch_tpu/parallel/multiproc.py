"""Multi-process experiment-driver support (BASELINE config 5 end-to-end).

The reference's driver is strictly single-process (`torch.nn.DataParallel`
inside one python, `/root/reference/main.py:53`); multi-host was out of its
reach entirely. This module makes `pipeline.run_experiment` runnable under
`jax.distributed` with N processes, the TPU-native way:

**Design: SPMD with replicated host logic.** Every process executes the
identical host program on identical host values — data generation is
deterministic from the config seed, target draws use the same seeded rng,
and per-image driver state (the B<=batch_size images, masks, patterns,
predictions) is REPLICATED over the global mesh. The wide masked-image
batch — where all the FLOPs are — still shards over the whole mesh through
`shard_apply_fn`, so compute scales exactly like the single-process sharded
path (the data axis participates in sharding the flat masked batch; with
driver batches of <=8 images, sharding the image axis itself buys nothing).
Because every host value the driver branches on is identical across
processes, every process enters every jitted collective in the same order —
the SPMD contract — and every np.asarray() materializes a fully-addressable
replicated array.

**Artifact IO is process-0-only, with reads broadcast.** A resume decision
taken from the filesystem must not diverge (process 0 sees the cache file,
process 1 doesn't → different jit call sequences → a collective mismatch
hang). `Process0Store` wraps `ArtifactStore`: writes happen only on process
0; cache reads happen on process 0 and are broadcast (flag + shapes +
values) via `multihost_utils.broadcast_one_to_all`, so all processes take
the same branch with the same data. The pickled PatchCleanser record cache
is the one exception: records are python objects, so multi-process runs
recompute certification (cheap next to the attack) while process 0 still
saves records for later single-process reuse.
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import numpy as np
from jax.experimental import multihost_utils

from dorpatch_tpu import observe


def is_main() -> bool:
    """True on the process that owns artifact writes and logging."""
    return jax.process_index() == 0


def _bcast(tree):
    """Broadcast process 0's pytree of numpy arrays to all processes.

    Telemetry: each broadcast is a span on the active EventLog — a
    collective-mismatch hang wedges every process exactly here, and the
    span's unclosed `begin` record (plus the heartbeat phase
    `.../artifact_io/bcast`) is what makes that diagnosable post-mortem
    (see `observe/heartbeat.py`)."""
    with observe.span("bcast"):
        return multihost_utils.broadcast_one_to_all(tree)


def shared_run_id(run_id: str) -> str:
    """Adopt process 0's per-attempt run_id on every process. One run must
    carry ONE attempt id across all of its telemetry files (events_N,
    heartbeat_N, run.json): the report CLI groups by run_id, so
    independently drawn per-process ids would read as separate attempts and
    drop proc>0 records from the attempt-filtered accounting."""
    buf = np.frombuffer(run_id.encode("ascii")[:16].ljust(16, b" "),
                        np.uint8).copy()
    return bytes(_bcast(buf)).decode("ascii").strip()


def _bcast_optional_arrays(values: Optional[Tuple[np.ndarray, ...]],
                           dtypes) -> Optional[Tuple[np.ndarray, ...]]:
    """Broadcast a maybe-absent tuple of arrays from process 0.

    Presence and shapes are broadcast first (receivers cannot know either —
    the cache file only exists on process 0's filesystem), then the values.
    `dtypes` fixes the dtype per element; receivers allocate zeros."""
    n = len(dtypes)
    if is_main() and values is not None:
        values = tuple(np.asarray(v) for v in values)
        ranks = [v.ndim for v in values]
        header = [1] + ranks
        flat_shapes = [d for v in values for d in v.shape]
    else:
        values = None
        header = [0] + [0] * n
        flat_shapes = []
    header = _bcast(np.asarray(header, np.int64))
    if int(header[0]) == 0:
        return None
    ranks = [int(r) for r in header[1:]]
    # ship shapes padded to a fixed rank-sum so receivers match structure
    total = sum(ranks)
    pad = np.zeros(total, np.int64)
    if flat_shapes:
        pad[:len(flat_shapes)] = flat_shapes
    shapes_flat = _bcast(pad)
    shapes, off = [], 0
    for r in ranks:
        shapes.append(tuple(int(d) for d in shapes_flat[off:off + r]))
        off += r
    if values is None:
        values = tuple(np.zeros(s, dt) for s, dt in zip(shapes, dtypes))
    return tuple(np.asarray(v) for v in _bcast(values))


class Process0Store:
    """`ArtifactStore` adapter for multi-process runs (see module docstring:
    writes on process 0 only, cache reads broadcast so resume decisions are
    identical on every process)."""

    def __init__(self, store):
        self.store = store
        self.result_dir = store.result_dir

    # -- per-batch final patches --

    def load_patch(self, batch_id: int):
        got = self.store.load_patch(batch_id) if is_main() else None
        return _bcast_optional_arrays(got, (np.float32, np.float32))

    def save_patch(self, batch_id: int, mask, pattern):
        if is_main():
            self.store.save_patch(batch_id, mask, pattern)

    # -- stage-0 artifacts (read inside attack.generate) --

    def load_stage0(self, batch_id: int):
        got = self.store.load_stage0(batch_id) if is_main() else None
        return _bcast_optional_arrays(got, (np.float32, np.float32))

    def save_stage0(self, batch_id: int, mask, pattern):
        if is_main():
            self.store.save_stage0(batch_id, mask, pattern)

    # -- recorded attack targets --

    def load_targets(self, batch_id: int):
        got = self.store.load_targets(batch_id) if is_main() else None
        # canonical int32 on BOTH sides of the broadcast: the recorded
        # targets are int32 (jax default int; result.y), and a collective
        # whose sender and receivers disagree on dtype/byte-width hangs
        got = _bcast_optional_arrays(
            None if got is None else (np.asarray(got, np.int32),),
            (np.int32,))
        return None if got is None else got[0]

    def save_targets(self, batch_id: int, targets) -> None:
        if is_main():
            self.store.save_targets(batch_id, targets)

    def resolve_targets(self, batch_id: int, rederive):
        """Same contract as `ArtifactStore.resolve_targets`, with broadcast
        reads. When neither the recorded targets nor stage-0 artifacts
        exist, the rederivation closure (a jitted forward on replicated
        arrays) runs identically on every process."""
        t = self.load_targets(batch_id)
        if t is not None:
            return np.asarray(t)
        s0 = self.load_stage0(batch_id)
        if s0 is None:
            raise FileNotFoundError(
                f"targeted resume for batch {batch_id} needs the recorded "
                "targets or the shared stage-0 artifacts on process 0")
        return np.asarray(rederive(s0))

    # -- PatchCleanser record cache: recompute under multi-process --

    def load_pc_records(self, batch_id: int):
        return None  # python objects; recomputing keeps all processes SPMD

    def save_pc_records(self, batch_id: int, records) -> None:
        if is_main():
            self.store.save_pc_records(batch_id, records)


__all__ = [
    "is_main",
    "shared_run_id",
    "Process0Store",
]
