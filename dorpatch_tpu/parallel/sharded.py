"""Sharded attack/defense factories: the multi-chip execution path.

The reference parallelizes one thing: the masked-image forward, via
`torch.nn.DataParallel` (`/root/reference/main.py:53`). Here the same
decision — *shard the flat masked batch, replicate everything else* — is
expressed once, as input placements + a sharding constraint on the victim
forward, and GSPMD compiles the whole jitted step (sample -> rasterize ->
forward -> losses -> backward -> signed update -> bookkeeping) into an SPMD
program with ICI all-reduces where the mask axis contracts.

Scaling story (BASELINE.md configs):
- single chip: ``make_mesh(1, 1)`` degenerates to the unsharded path;
- v4-8, EOT 32-128: ``make_mesh(1, n)`` — mask-axis sharding over ICI;
- v4-32 multi-host: ``make_mesh(n_hosts, chips_per_host)`` — the data axis
  crosses DCN (each host feeds its local image shard through
  ``jax.make_array_from_process_local_data``), the mask axis stays on ICI.
"""

from __future__ import annotations

from typing import Any, Callable, List

import jax

from dorpatch_tpu.attack import DorPatch
from dorpatch_tpu.config import AttackConfig, DefenseConfig
from dorpatch_tpu.defense import PatchCleanser, build_defenses
from dorpatch_tpu.parallel.mesh import Mesh, place_replicated, shard_apply_fn


def make_sharded_attack(
    apply_fn: Callable[[Any, jax.Array], jax.Array],
    params: Any,
    num_classes: int,
    config: AttackConfig,
    mesh: Mesh,
    **kwargs,
) -> DorPatch:
    """A DorPatch whose EOT forward+backward shards over `mesh`.

    Params are replicated (classifier weights are small next to the 128-way
    activation batch); callers place the image batch with
    ``parallel.place_batch(mesh, x, y)`` so per-image state initializes
    sharded over the data axis.
    """
    return DorPatch(
        apply_fn=shard_apply_fn(apply_fn, mesh),
        params=place_replicated(mesh, params),
        num_classes=num_classes,
        config=config,
        mesh=mesh,   # keeps the fused Pallas mask-fill via its shard_map wrapper
        **kwargs,
    )


def make_sharded_defenses(
    apply_fn: Callable[[Any, jax.Array], jax.Array],
    img_size: int,
    mesh: Mesh,
    config: DefenseConfig = DefenseConfig(),
    recompile_budget=None,
    incremental=None,
) -> List[PatchCleanser]:
    """The 4-radius defense bank with certification sweeps sharded over the
    mesh (chunk axis splits across chips; the per-chunk forward is the unit
    of scatter, as in the attack). The two-phase pruned schedule runs here
    too: phase 1 shards over the whole mesh, phase-2 worklists are planned
    shard-locally and dispatched as `[S * bucket]` SPMD waves (see
    `defense._PrunedPending._schedule_mesh`). `incremental` is the victim
    family's incremental-inference engine (`models.Victim.incremental`);
    its programs ride the same shard-local schedule."""
    return build_defenses(shard_apply_fn(apply_fn, mesh), img_size, config,
                          mesh=mesh, recompile_budget=recompile_budget,
                          incremental=incremental)


__all__ = [
    "make_sharded_attack",
    "make_sharded_defenses",
]
