"""Mesh parallelism: the TPU-native replacement for the reference's
`torch.nn.DataParallel` layer (`/root/reference/main.py:53`). See mesh.py for
the (data, mask) axis design and sharded.py for the attack/defense factories."""

from dorpatch_tpu.parallel.mesh import (
    DATA_AXIS,
    MASK_AXIS,
    data_sharding,
    flat_batch_sharding,
    make_mesh,
    place_batch,
    place_batch_auto,
    place_batch_multihost,
    place_replicated,
    replicated,
    shard_apply_fn,
)
from dorpatch_tpu.parallel.sharded import (
    make_sharded_attack,
    make_sharded_defenses,
)
from dorpatch_tpu.parallel import multiproc

__all__ = [
    "DATA_AXIS",
    "MASK_AXIS",
    "multiproc",
    "data_sharding",
    "flat_batch_sharding",
    "make_mesh",
    "make_sharded_attack",
    "make_sharded_defenses",
    "place_batch",
    "place_batch_auto",
    "place_batch_multihost",
    "place_replicated",
    "replicated",
    "shard_apply_fn",
]
