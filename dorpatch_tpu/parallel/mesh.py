"""Device-mesh construction and sharding placements.

The reference's only parallelism is single-process `torch.nn.DataParallel`
(`/root/reference/main.py:53`): replicate the model, scatter the 128-image
masked EOT batch over visible GPUs, gather logits. The TPU-native equivalent
is a GSPMD mesh with two logical axes:

- ``data``  — images (the reference's outer batch). Across hosts this axis
  rides DCN; within a slice it is ordinary data parallelism.
- ``mask``  — the EOT/occlusion/defense-mask axis, this workload's "long
  dimension" (128 sampled masks per attack step, 2520-mask failure sweeps,
  666-mask certification). Sharding it over ICI is the moral equivalent of
  sequence/context parallelism for classifiers (SURVEY.md §5).

Everything rides XLA collectives implicitly: the per-step loss/grad reduction
over the mask axis becomes an ICI all-reduce inserted by GSPMD — no NCCL-style
explicit communication code, which is the idiomatic replacement for the
reference's DataParallel scatter/gather.

Mesh construction uses ``jax.experimental.mesh_utils`` so the (data, mask)
axes map onto the physical ICI torus contiguously; on multi-host slices the
``data`` axis is laid out across hosts (DCN) and ``mask`` stays inside the
slice (ICI), per ``create_hybrid_device_mesh``.
"""

from __future__ import annotations

from typing import Any, Callable, Optional, Sequence

import jax
import numpy as np
from jax.experimental import mesh_utils
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

DATA_AXIS = "data"
MASK_AXIS = "mask"


def make_mesh(
    data: int = 1,
    mask: int = -1,
    devices: Optional[Sequence[jax.Device]] = None,
) -> Mesh:
    """Build a ``(data, mask)`` mesh over ``data*mask`` devices.

    ``mask=-1`` absorbs all remaining devices — the right default for this
    workload, where the outer image batch is small (the reference runs B=1,
    `/root/reference/main.py:27-28`) and the mask/EOT axis is wide.
    """
    if devices is None:
        devices = jax.devices()
    if mask == -1:
        if len(devices) % data:
            raise ValueError(f"{len(devices)} devices not divisible by data={data}")
        mask = len(devices) // data
    n = data * mask
    if n > len(devices):
        raise ValueError(f"mesh {data}x{mask} needs {n} devices, have {len(devices)}")
    if n == len(devices):
        n_slices = len({getattr(d, "slice_index", 0) for d in devices})
        if n_slices > 1:
            if data % n_slices:
                raise ValueError(
                    f"{n_slices} DCN-connected slices: the data axis must be a"
                    f" multiple of the slice count (got data={data}) so the"
                    f" mask axis stays on ICI; use"
                    f" make_mesh(data={n_slices}*images_per_slice_groups, ...)"
                )
            # Multi-slice: pin the data axis across DCN granules and keep the
            # mask axis inside each slice's ICI torus, so the per-step
            # mask-axis loss/grad all-reduce never crosses DCN.
            arr = mesh_utils.create_hybrid_device_mesh(
                (data // n_slices, mask), (n_slices, 1), devices=devices
            )
        else:
            arr = mesh_utils.create_device_mesh((data, mask), devices=devices)
    else:
        arr = np.asarray(devices[:n]).reshape(data, mask)
    return Mesh(arr, (DATA_AXIS, MASK_AXIS))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def _axis_sharding(mesh: Mesh, ndim: int, axis_spec, axis: int = 0) -> NamedSharding:
    if ndim < 1 or not (0 <= axis < ndim):
        raise ValueError(f"cannot shard axis {axis} of a rank-{ndim} array")
    spec = [None] * ndim
    spec[axis] = axis_spec
    return NamedSharding(mesh, P(*spec))


def data_sharding(mesh: Mesh, ndim: int, axis: int = 0) -> NamedSharding:
    """Shard dimension `axis` of an ndim-array over the data axis."""
    return _axis_sharding(mesh, ndim, DATA_AXIS, axis)


def flat_batch_sharding(mesh: Mesh, ndim: int = 4) -> NamedSharding:
    """Sharding for a flattened ``[B*S, ...]`` model batch: the leading axis
    split over *both* mesh axes — every chip gets an equal slice of the
    masked-image batch, exactly DataParallel's scatter but compiled."""
    return _axis_sharding(mesh, ndim, (DATA_AXIS, MASK_AXIS))


def shard_apply_fn(
    apply_fn: Callable[[Any, jax.Array], jax.Array], mesh: Mesh
) -> Callable[[Any, jax.Array], jax.Array]:
    """Wrap a victim ``apply(params, images)`` so its (flattened) image batch
    is constrained to shard over the whole mesh.

    This one wrapper parallelizes every hot path in the framework — the
    attack's 128-way EOT forward+backward (`/root/reference/attack.py:222`),
    the 2520-mask failure sweeps (`attack.py:384-406`), and the defense's
    666-mask certification (`PatchCleanser.py:70-112`) — because all of them
    funnel through the victim forward on a flat ``[B*S, H, W, C]`` batch.
    GSPMD propagates the constraint outward (the rasterized masks shard the
    same way) and inserts the ICI all-reduce for the loss/grad reduction.
    """

    n_devices = mesh.devices.size

    def wrapped(params, images):
        # Shapes are static under trace: constrain only batches the mesh
        # divides (small eager calls — e.g. the label-inference forward on
        # B images — stay unconstrained rather than forcing padding).
        if images.shape[0] % n_devices == 0:
            images = jax.lax.with_sharding_constraint(
                images, flat_batch_sharding(mesh, images.ndim)
            )
        return apply_fn(params, images)

    return wrapped


def place_replicated(mesh: Mesh, tree):
    """Replicate a pytree (model params, mask universe) over the mesh.

    On a multi-process mesh `jax.device_put` cannot target non-addressable
    devices ("cross-host reshard"); `make_array_from_process_local_data`
    with a fully-replicated sharding is the multi-controller form — every
    process contributes its (identical) full copy."""
    sh = replicated(mesh)
    me = jax.process_index()
    if all(d.process_index == me for d in mesh.devices.flat):
        return jax.device_put(tree, sh)
    return jax.tree_util.tree_map(
        lambda a: jax.make_array_from_process_local_data(sh, np.asarray(a)),
        tree)


def place_batch(mesh: Mesh, x: jax.Array, *per_image):
    """Place an image batch (and aligned per-image arrays) sharded over the
    data axis. The data-axis size must divide the batch. Single-process form;
    multi-host jobs feed per-process shards via `place_batch_multihost`."""
    n_data = mesh.shape[DATA_AXIS]
    if x.shape[0] % n_data:
        raise ValueError(
            f"batch {x.shape[0]} not divisible by data axis size {n_data}")
    out = [jax.device_put(x, data_sharding(mesh, np.ndim(x)))]
    for pos, a in enumerate(per_image):
        if np.ndim(a) < 1 or np.shape(a)[0] != x.shape[0]:
            raise ValueError(
                f"per_image arg {pos} must have leading dim {x.shape[0]}, "
                f"got shape {np.shape(a)}")
        out.append(jax.device_put(a, data_sharding(mesh, np.ndim(a))))
    return out[0] if not per_image else tuple(out)


def place_batch_auto(mesh: Mesh, x: jax.Array) -> jax.Array:
    """`place_batch` when the data axis divides the batch, replicated
    otherwise — the certify/attack input-placement rule: a correctness
    filter (or a ragged final batch) makes the surviving batch size
    dynamic, and per-image state is tiny next to the masked activation
    batch, so replication is the right fallback. jit cache keys include
    input shardings: warmup paths (`PatchCleanser.warm_pruned`) apply the
    same rule so warm placements match live traffic."""
    if x.shape[0] % mesh.shape[DATA_AXIS] == 0:
        return place_batch(mesh, x)
    return jax.device_put(x, replicated(mesh))


def place_batch_multihost(mesh: Mesh, x_local, *per_image_local):
    """Multi-host feeding (BASELINE config 5, the v4-32 row): every process
    passes only ITS shard of the global batch; the result is a global
    `jax.Array` sharded over the data axis, assembled with
    `jax.make_array_from_process_local_data` — no host ever materializes the
    full batch, the TPU-native replacement for the reference's single-process
    `DataParallel` scatter (`/root/reference/main.py:53`).

    The data axis must be laid out so each process's addressable devices hold
    a contiguous slice (what `make_mesh` produces via
    `create_hybrid_device_mesh`: data across DCN granules, mask inside the
    slice). Local batches must be equal-sized across processes; the global
    leading dim is `process_count * local_batch`. On a single process this
    degenerates to `place_batch` semantics (same sharding, same values).
    """
    out = []
    for pos, a in enumerate((x_local,) + per_image_local):
        a = np.asarray(a)
        if pos and a.shape[0] != np.shape(x_local)[0]:
            raise ValueError(
                f"per_image arg {pos - 1} must have leading dim "
                f"{np.shape(x_local)[0]}, got shape {a.shape}")
        out.append(jax.make_array_from_process_local_data(
            data_sharding(mesh, a.ndim), a))
    return out[0] if not per_image_local else tuple(out)
