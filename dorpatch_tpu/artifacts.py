"""Results-directory contract + artifact store (resume/caching layer).

Replicates the reference's persistence interfaces exactly so torch- and
jax-produced artifacts interchange:

- the config-derived results path (`/root/reference/utils.py:24-44`):
  `results/<k=v config string>/<num_patch=.._patch_budget=..>`, with the
  uninformative keys dropped;
- per-batch patch artifacts `adv_mask_%d.pt` / `adv_pattern_%d.pt`
  (`/root/reference/main.py:101-106,135-138`), stored as torch NCHW tensors;
- stage-0 artifacts in the *parent* directory, shared across patch budgets
  (`/root/reference/attack.py:102-103,134-141,348-356`);
- pickled PatchCleanser records `adv_PC_%d.pt` (`/root/reference/main.py:144-153`).
"""

from __future__ import annotations

import os
import pickle
from typing import Optional, Sequence, Tuple

import numpy as np

from dorpatch_tpu.config import ExperimentConfig


def results_path(cfg: ExperimentConfig) -> str:
    """Config -> nested results dir, byte-compatible with the reference's
    `generate_saving_path` for the shared keys (key order and formatting
    follow the reference's argparse-dict order)."""
    keys = [
        ("dataset", cfg.dataset),
        ("base_arch", cfg.base_arch),
        ("targeted", cfg.attack.targeted),
        ("attack", cfg.attack_name),
        ("dropout", cfg.attack.dropout),
        ("density", cfg.attack.density),
        ("structured", cfg.attack.structured),
    ]
    # TPU-extension knobs that change what the cached artifacts MEAN are
    # path keys too — only when non-default, so default runs stay
    # byte-compatible with the reference contract. Without these, a warm
    # results tree would serve non-dual patches to a --dual run and
    # n_patch=1 certification records to a --defense-n-patch 2 run.
    if cfg.attack.dual:
        keys.append(("dual", True))
    if cfg.defense.n_patch != 1:
        keys.append(("defense_n_patch", cfg.defense.n_patch))
    top = "_".join(f"{k}={v}" for k, v in keys)
    sub = f"num_patch={cfg.attack.num_patch}_patch_budget={cfg.attack.patch_budget}"
    return os.path.join(cfg.results_root, top, sub)


def write_config_record(cfg: ExperimentConfig, result_dir: str) -> None:
    """Persist the exact experiment config beside the artifacts
    (`config.json`), so a results tree is self-describing — `results_path`
    encodes only a few attack knobs; sampling_size / max_iterations / seed
    would otherwise be unrecoverable — and the parity tool can reconstruct
    the torch-oracle config from the jax run it is scoring."""
    import json

    from dorpatch_tpu.config import config_to_dict

    try:
        with open(os.path.join(result_dir, "config.json"), "w") as fh:
            json.dump(config_to_dict(cfg), fh, indent=1, default=float)
    except OSError:
        pass  # read-only results dir: artifacts still work without it


def _to_torch_nchw(arr: np.ndarray):
    import torch

    # copy: the source may be a non-writable jax buffer
    return torch.from_numpy(np.array(np.moveaxis(arr, -1, 1), copy=True))


def _from_torch_nchw(t) -> np.ndarray:
    return np.moveaxis(np.asarray(t.detach().cpu(), dtype=np.float32), 1, -1)


class ArtifactStore:
    """Filesystem store rooted at the config's results dir.

    NHWC<->NCHW conversion happens at the boundary: files hold torch NCHW
    tensors (the reference's on-disk format), the framework works in NHWC.
    """

    def __init__(self, result_dir: str):
        self.result_dir = result_dir
        self.parent_dir = os.path.dirname(result_dir)
        os.makedirs(result_dir, exist_ok=True)

    # -- per-batch final patches (`main.py:101-106,135-138`) --

    def _patch_paths(self, batch_id: int, root: str) -> Tuple[str, str]:
        return (
            os.path.join(root, f"adv_mask_{batch_id}.pt"),
            os.path.join(root, f"adv_pattern_{batch_id}.pt"),
        )

    def load_patch(self, batch_id: int) -> Optional[Tuple[np.ndarray, np.ndarray]]:
        return self._load_pair(*self._patch_paths(batch_id, self.result_dir))

    def save_patch(self, batch_id: int, mask: np.ndarray, pattern: np.ndarray):
        self._save_pair(self._patch_paths(batch_id, self.result_dir), mask, pattern)

    # -- stage-0 artifacts in the parent dir, shared across budgets --

    def load_stage0(self, batch_id: int) -> Optional[Tuple[np.ndarray, np.ndarray]]:
        return self._load_pair(*self._patch_paths(batch_id, self.parent_dir))

    def save_stage0(self, batch_id: int, mask: np.ndarray, pattern: np.ndarray):
        os.makedirs(self.parent_dir, exist_ok=True)
        self._save_pair(self._patch_paths(batch_id, self.parent_dir), mask, pattern)

    def _load_pair(self, mask_path, pattern_path):
        import torch

        if not (os.path.exists(mask_path) and os.path.exists(pattern_path)):
            return None
        mask = torch.load(mask_path, map_location="cpu", weights_only=True)
        pattern = torch.load(pattern_path, map_location="cpu", weights_only=True)
        return _from_torch_nchw(mask), _from_torch_nchw(pattern)

    def _save_pair(self, paths, mask, pattern):
        import torch

        torch.save(_to_torch_nchw(np.asarray(mask)), paths[0])
        torch.save(_to_torch_nchw(np.asarray(pattern)), paths[1])

    # -- recorded attack targets (framework extension) --
    #
    # The reference recovers targets on resume by re-running the cached
    # stage-0 patch through the model (`main.py:108-118`), which only equals
    # the optimized target when stage 0 fully succeeded — so the certified-
    # ASR of identical artifacts could change between the generating run and
    # a cached re-run. Persisting the actual targets removes that ambiguity;
    # the re-derivation stays as the fallback for artifact dirs produced by
    # the reference itself (which never wrote this file).

    def _targets_path(self, batch_id: int) -> str:
        return os.path.join(self.result_dir, f"targets_{batch_id}.pt")

    def load_targets(self, batch_id: int) -> Optional[np.ndarray]:
        import torch

        path = self._targets_path(batch_id)
        if not os.path.exists(path):
            return None
        return np.asarray(torch.load(path, map_location="cpu", weights_only=True))

    def save_targets(self, batch_id: int, targets: np.ndarray) -> None:
        import torch

        # copy: the source may be a non-writable jax buffer (same reason as
        # _to_torch_nchw), which torch.as_tensor would alias with a warning
        torch.save(torch.from_numpy(np.array(targets, copy=True)),
                   self._targets_path(batch_id))

    def resolve_targets(self, batch_id: int, rederive) -> np.ndarray:
        """Targets for a cached patch: the recorded file when present, else
        the reference's re-derivation from the shared stage-0 artifacts
        (`/root/reference/main.py:108-118`) via `rederive((mask, pattern))`
        — the backend-specific model forward. Shared by both pipelines so
        the resume contract cannot drift between them."""
        t = self.load_targets(batch_id)
        if t is not None:
            return np.asarray(t)
        s0 = self.load_stage0(batch_id)
        if s0 is None:
            raise FileNotFoundError(
                f"targeted resume for batch {batch_id} needs the recorded "
                f"targets or the shared stage-0 artifacts in "
                f"{self.parent_dir}; they were removed — delete the "
                "per-budget patch files too to regenerate")
        return np.asarray(rederive(s0))

    # -- PatchCleanser record cache (`main.py:144-153`) --

    def _pc_path(self, batch_id: int) -> str:
        return os.path.join(self.result_dir, f"adv_PC_{batch_id}.pt")

    def load_pc_records(self, batch_id: int):
        path = self._pc_path(batch_id)
        if not os.path.exists(path):
            return None
        with open(path, "rb") as f:
            return pickle.load(f)

    def save_pc_records(self, batch_id: int, records: Sequence):
        with open(self._pc_path(batch_id), "wb") as f:
            pickle.dump(records, f)
