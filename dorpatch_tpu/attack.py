"""The DorPatch optimizer as jitted XLA programs with on-device carry state.

Reimplements the reference's two-stage attack (`/root/reference/attack.py:51-406`)
TPU-first. The reference's hot loop interleaves CUDA compute with host-side
numpy bookkeeping every iteration (best-checkpointing, failure-set surgery,
lr/coefficient schedules — `attack.py:249-342`); here the *entire* adaptive
state lives in a `TrainState` pytree on device, one optimization step is a
single fused jit program (sample masks -> rasterize -> masked forward -> CW +
TV + density + group-lasso losses -> signed-grad update -> bookkeeping as
`where` selects), and steps run in `lax.scan` blocks of `sweep_interval`
between full-universe failure sweeps. Host work per block: one scalar sync.

Stage 0 learns a continuous importance map under group-lasso/density
regularization; stage 1 freezes the top-k hard mask (`patch_selection`) and
refines the pattern under EOT over the occlusion universe.

Deliberate repairs of reference latent bugs (SURVEY.md §4), preserved in
spirit but made well-defined:
- true batched semantics over B images (the reference hard-assumes B=1:
  `attack.py:98,120,313,344`);
- the iteration-500 switch keeps per-image targets; images with no
  misclassified EOT sample keep their label (the reference would set an
  inconsistent targeted-toward-truth state, `attack.py:106-122,169-176`);
- the redundant second sweep at the switch iteration is dropped (the
  reference's `attack.py:181` result is immediately overwritten at
  `attack.py:189`);
- `dual=False` dead branch (`attack.py:208-218`) is a live config option.
"""

from __future__ import annotations

import dataclasses
from functools import partial
from typing import Any, Callable, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from dorpatch_tpu import losses
from dorpatch_tpu import masks as masks_lib
from dorpatch_tpu import observe
from dorpatch_tpu import ops
from dorpatch_tpu import utils
from dorpatch_tpu.config import AttackConfig
from dorpatch_tpu.defense import masked_predictions


class TrainState(NamedTuple):
    """Everything the reference keeps on the host (`attack.py:59-98,129-132`),
    as a single on-device pytree."""

    step: jax.Array            # i32 scalar, iteration within the stage
    rng: jax.Array             # PRNG key
    adv_mask: jax.Array        # [B,H,W,1] continuous (stage 0) / frozen hard (stage 1)
    adv_pattern: jax.Array     # [B,H,W,3]
    best_mask: jax.Array       # [B,H,W,1]
    best_pattern: jax.Array    # [B,H,W,3]
    loss_best: jax.Array       # [B] best target-loss so far (inf = none)
    lr: jax.Array              # [B] per-image signed-grad step size
    not_decay: jax.Array       # [B] i32 patience counters
    num_failure: jax.Array     # i32 scalar, failure count of the best checkpoint
    failed: jax.Array          # [n_mask] bool, current failure set
    coeff_gl: jax.Array        # f32 scalar, adaptive group-lasso coefficient
    coeff_struct: jax.Array    # f32 scalar, adaptive structural coefficient
    coeff_density: jax.Array   # f32 scalar, density coefficient (traced so a
                               # sweep varies it without recompiling the block)
    targeted: jax.Array        # [B] bool, per-image attack mode
    y: jax.Array               # [B] labels (ground truth or targets)
    last_preds: jax.Array      # [B,S] predictions of the last sampled forward
    stopped: jax.Array         # bool scalar, all-lr early stop latched
    metrics: jax.Array         # [8] f32: loss, adv, struc, gl, density, acc, l2, n_failed


class AttackResult(NamedTuple):
    adv_mask: jax.Array        # [B,H,W,1]
    adv_pattern: jax.Array     # [B,H,W,3]
    y: np.ndarray              # [B] final labels (targets if switched)
    targeted: np.ndarray       # [B] bool, per-image mode after switching
    stage0_mask: jax.Array
    stage0_pattern: jax.Array


# Config fields that never enter the compiled step/sweep graphs: they only
# shape the eager stage transition (`patch_budget`, `num_patch`) or initialize
# traced carry scalars (`density`/`structured`/`lr`, plus `targeted`). Two
# configs equal outside this set compile to byte-identical programs, which is
# what lets a hyperparameter sweep share one set of compiled blocks.
BLOCK_IRRELEVANT_FIELDS = frozenset(
    {"patch_budget", "num_patch", "density", "structured", "lr", "targeted"}
)


def block_signature(cfg: AttackConfig) -> tuple:
    """Hashable fingerprint of every config field baked into the jitted
    step/sweep programs (the complement of `BLOCK_IRRELEVANT_FIELDS`)."""
    d = dataclasses.asdict(cfg)
    return tuple(sorted(
        (k, v) for k, v in d.items() if k not in BLOCK_IRRELEVANT_FIELDS))


def patch_selection(
    mask: jax.Array, patch_budget: float, basic_unit: int = 7
) -> jax.Array:
    """Importance map -> hard patch mask (`/root/reference/attack.py:363-382`).

    Window-sum the continuous mask over basic_unit cells, take the top
    `floor(H*W*budget/unit^2)` cells with positive mass, upsample to pixels.
    mask `[B,H,W,1]` -> binary `[B,H,W,1]`.
    """
    b, h, w, _ = mask.shape
    cells = losses.window_sum(mask, basic_unit)[..., 0]  # [B, h', w']
    hp, wp = cells.shape[1:]
    flat = cells.reshape(b, -1)
    k = int(np.floor(h * w * patch_budget / basic_unit**2))
    vals, idxs = jax.lax.top_k(flat, k)
    sel = jnp.zeros_like(flat)
    updates = (vals > 0).astype(mask.dtype)
    sel = jax.vmap(lambda s, i, u: s.at[i].set(u))(sel, idxs, updates)
    sel = sel.reshape(b, hp, wp)
    sel = jnp.repeat(jnp.repeat(sel, basic_unit, axis=1), basic_unit, axis=2)
    # zero-pad edge pixels not covered by a full cell (when basic_unit does
    # not divide H/W; at the reference's 224/7 geometry this is empty)
    sel = jnp.pad(sel, ((0, 0), (0, h - sel.shape[1]), (0, w - sel.shape[2])))
    return sel[..., None]


def majority_incorrect_label(preds: jax.Array, y: jax.Array, num_classes: int):
    """Per-image mode of misclassified predictions (`attack.py:106-122`):
    the easiest target for label-consistent certification evasion.

    preds `[B,S]`, y `[B]`. Returns `(labels, has_target)`: images with no
    misclassified prediction keep their label and report False — they must
    *stay untargeted* (the reference would flip its global flag and start
    optimizing toward the true label for them; see module docstring)."""
    incorrect = preds != y[:, None]
    counts = jnp.sum(
        jax.nn.one_hot(preds, num_classes, dtype=jnp.int32) * incorrect[..., None], axis=1
    )  # [B, C]
    has_any = jnp.any(incorrect, axis=1)
    mode = jnp.argmax(counts, axis=-1).astype(y.dtype)  # smallest label on ties
    return jnp.where(has_any, mode, y), has_any


@dataclasses.dataclass
class DorPatch:
    """Two-stage distributed occlusion-robust patch attack
    (`/root/reference/attack.py:51-361`), jitted end-to-end per stage."""

    apply_fn: Callable[[Any, jax.Array], jax.Array]
    params: Any
    num_classes: int
    config: AttackConfig = dataclasses.field(default_factory=AttackConfig)
    # None -> follow config.remat ("auto" remats only when the masked batch
    # exceeds config.remat_threshold); True/False force it
    remat: Optional[bool] = None
    on_block_end: Optional[Callable[[int, int, dict], None]] = None
    # optional CarryCheckpointer: mid-stage crash recovery (checkpoint.py)
    checkpointer: Optional[Any] = None
    # optional (data, mask) jax.sharding.Mesh: keeps the fused Pallas
    # mask-fill on multi-chip meshes via its shard_map wrapper
    # (`ops.masked_fill`); see parallel.make_sharded_attack
    mesh: Optional[Any] = None
    # declared trace budget per jitted entry point: distinct image-batch
    # sizes are the only legitimate shape buckets (the driver's correctness
    # filter makes B dynamic), so the pipeline declares cfg.batch_size.
    # Inert unless the runtime sanitizer is armed (analysis/sanitize.py).
    recompile_budget: Optional[int] = None

    def __post_init__(self):
        cfg = self.config
        fwd = self.apply_fn
        if cfg.compute_dtype == "bfloat16":
            # mixed precision, TPU-style: the EOT forward+backward (where all
            # the FLOPs and bandwidth are) runs in bfloat16 on the MXU, while
            # the patch iterates, losses, and all adaptive carry state stay
            # float32 ("master" precision). The signed-grad update only
            # consumes grad signs, so reduced-precision gradients are well
            # tolerated. Params are cast once (keeps device placement and
            # any mesh sharding).
            params16 = jax.tree_util.tree_map(
                lambda a: a.astype(jnp.bfloat16)
                if jnp.issubdtype(jnp.asarray(a).dtype, jnp.floating) else a,
                self.params,
            )
            base = self.apply_fn

            def fwd(_params, x):
                return base(params16, x.astype(jnp.bfloat16)).astype(jnp.float32)

        elif cfg.compute_dtype != "float32":
            raise ValueError(f"compute_dtype={cfg.compute_dtype!r}")
        if cfg.remat not in ("auto", "on", "off"):
            raise ValueError(f"remat={cfg.remat!r}")
        if cfg.remat_policy not in ("full", "conv", "dots"):
            raise ValueError(f"remat_policy={cfg.remat_policy!r}")
        self._fwd = fwd
        self._sampling_size = cfg.sampling_size
        # jitted program cache: (stage, img_size, n_steps) -> block fn, plus
        # the "sweep" key. Shared *by reference* via `adopt_compiled`, so
        # programs compiled through any sharing instance serve all of them.
        self._programs = {}

    def adopt_compiled(self, other: "DorPatch") -> None:
        """Share `other`'s compiled step/sweep programs (zero recompiles).

        Legal iff both attacks wrap the same victim (`apply_fn`/`params`
        identity) with the same `remat` policy and agree on every config
        field that is baked into the compiled graphs (`block_signature`).
        Fields in `BLOCK_IRRELEVANT_FIELDS` may differ — they live in the
        traced carry or the eager stage transition, which is exactly what a
        hyperparameter sweep varies (`sweep.run_sweep`)."""
        if self.apply_fn is not other.apply_fn or self.params is not other.params:
            raise ValueError("adopt_compiled requires the identical victim "
                             "(same apply_fn and params objects)")
        if self.num_classes != other.num_classes:
            raise ValueError("adopt_compiled: num_classes differs (baked into "
                             "the compiled label-switch logic)")
        if self.mesh is not other.mesh:
            raise ValueError("adopt_compiled: mesh differs (baked into the "
                             "compiled masked_fill dispatch)")
        if self.remat != other.remat:
            raise ValueError("adopt_compiled: remat policy differs")
        if block_signature(self.config) != block_signature(other.config):
            raise ValueError(
                "adopt_compiled: configs differ in compiled-graph fields: "
                f"{block_signature(self.config)} vs {block_signature(other.config)}")
        self._programs = other._programs

    def _grad_fwd(self, n_masked: int):
        """The forward used under `jax.grad`, with the remat policy applied.

        Rematerialization re-runs forward work during the backward to avoid
        storing activations; it only pays when the masked batch would not
        fit HBM. `remat=None` follows `config.remat`: "on"/"off" force it,
        "auto" remats when `n_masked` (images x EOT samples) exceeds
        `config.remat_threshold`. `config.remat_policy` picks what is
        recomputed: "full" replays the whole forward (~25% extra step time,
        minimum memory), "conv" keeps the conv outputs and replays only the
        normalize/elementwise chains between them (a few-percent tax;
        memory ~= conv outputs), "dots" keeps matmul outputs (the ViT /
        ResMLP analog). The failure sweeps and certification never
        differentiate, so they always use the plain forward."""
        if self.remat is not None:
            use = self.remat
        else:
            cfg = self.config
            use = cfg.remat == "on" or (
                cfg.remat == "auto" and n_masked > cfg.remat_threshold)
        if not use:
            return self._fwd
        policy = {
            "full": None,  # jax.checkpoint's default: save nothing
            "conv": jax.checkpoint_policies.save_only_these_names("conv_out"),
            "dots": jax.checkpoint_policies.dots_saveable,
        }[self.config.remat_policy]
        return jax.checkpoint(self._fwd, policy=policy)

    # ---------- mask sampling (static shapes) ----------

    def _sample_indices(self, rng, failed, step):
        """Failure-biased EOT sampling (`attack.py:192-204`) with static
        shapes: up to half the batch from the failure set (after
        `failure_sampling_start`), the rest uniform from the universe, both
        without replacement within their draw, via Gumbel top-k."""
        cfg = self.config
        n_mask = failed.shape[0]
        # the reference clamps the EOT batch to the universe size
        # (`attack.py:92-94`); also keeps the dropout=0 identity universe legal
        s = min(self._sampling_size, n_mask)
        half = s // 2
        k1, k2 = jax.random.split(rng)

        g_uni = jax.random.gumbel(k2, (n_mask,))
        uni_top = jax.lax.top_k(g_uni, s)[1]
        pos = jnp.arange(s)
        if half == 0:
            return uni_top, jnp.zeros((s,), bool)

        n_failed = jnp.sum(failed.astype(jnp.int32))
        n_from_fail = jnp.where(
            step >= cfg.failure_sampling_start, jnp.minimum(n_failed, half), 0
        )
        g_fail = jax.random.gumbel(k1, (n_mask,)) + jnp.where(failed, 0.0, -jnp.inf)
        fail_top = jax.lax.top_k(g_fail, half)[1]

        from_fail = pos < n_from_fail
        idx = jnp.where(
            from_fail,
            fail_top[jnp.clip(pos, 0, half - 1)],
            uni_top[jnp.clip(pos - n_from_fail, 0, s - 1)],
        )
        return idx, from_fail

    # ---------- one optimization step ----------

    def _loss_and_aux(self, adv_mask, adv_pattern, x, local_var_x, rects, state, stage):
        cfg = self.config
        b = x.shape[0]
        s = rects.shape[0]  # effective EOT batch (clamped to universe size)
        delta = losses.l2_project(adv_mask, adv_pattern, x, cfg.eps)
        adv_x = x + delta
        # fused rasterize+fill (Pallas on TPU): the [S,H,W] mask tensor is
        # never materialized; gradients flow to adv_x through the kept pixels
        masked = ops.masked_fill(adv_x, rects, cfg.mask_fill, cfg.use_pallas,
                                 mesh=self.mesh)
        logits = self._grad_fwd(b * s)(
            self.params, masked.reshape((-1,) + x.shape[1:]))
        y_rep = jnp.repeat(state.y, s)
        targeted_rep = jnp.repeat(state.targeted, s)
        loss_adv = losses.cw_margin_switchable(
            logits, y_rep, self.num_classes, targeted_rep, cfg.confidence
        ).reshape(b, s)

        loss_struc = losses.structural_loss(adv_x, local_var_x)
        loss = jnp.mean(loss_adv, axis=1)
        # regularization coefficients are traced carry scalars (not Python
        # constants baked into the graph), so a hyperparameter sweep varies
        # them without recompiling the step block; a 0 coefficient is a
        # mathematical no-op, same as the reference's `if != 0` guards
        loss = loss + state.coeff_struct * loss_struc
        gl = jnp.zeros(b)
        dens = jnp.zeros(b)
        if stage == 0:
            dens = losses.density_loss(adv_mask, x.shape[1] // 8)
            loss = loss + state.coeff_density * dens
            gl = losses.group_lasso(adv_mask, cfg.basic_unit)
            loss = loss + state.coeff_gl * gl
        preds = jnp.argmax(logits, axis=-1).reshape(b, s)
        aux = dict(
            loss=loss, loss_adv=loss_adv, loss_struc=loss_struc,
            group_lasso=gl, density=dens, preds=preds, delta=delta,
        )
        return jnp.sum(loss), aux

    def _step(self, state: TrainState, x, local_var_x, universe, stage: int) -> TrainState:
        cfg = self.config
        b = x.shape[0]
        rng, k_samp, k_dual = jax.random.split(state.rng, 3)

        idx, from_fail = self._sample_indices(k_samp, state.failed, state.step)
        rects = universe[idx]
        if cfg.dual:
            # second independent occlusion layer (`attack.py:208-218`): the
            # union of both rectangle sets, as extra rows on the K axis
            idx2, _ = self._sample_indices(k_dual, state.failed, state.step)
            rects = jnp.concatenate([rects, universe[idx2]], axis=1)

        grad_fn = jax.grad(self._loss_and_aux, argnums=(0, 1), has_aux=True)
        (g_mask, g_pattern), aux = grad_fn(
            state.adv_mask, state.adv_pattern, x, local_var_x, rects, state, stage
        )

        # ---- bookkeeping (`attack.py:249-342`), all as selects ----
        loss_adv = aux["loss_adv"]
        attack_success_bs = loss_adv < cfg.success_threshold     # [B,S]
        mask_success = jnp.all(attack_success_bs, axis=0)        # [S]

        # failure-set surgery (`attack.py:259-267`): successes drawn from the
        # failure set leave it; failures drawn from the universe enter it.
        # Non-matching positions scatter to an out-of-bounds marker + drop.
        n_mask = state.failed.shape[0]
        remove = jnp.where(from_fail & mask_success, idx, n_mask)
        add = jnp.where((~from_fail) & (~mask_success), idx, n_mask)
        failed = state.failed.at[remove].set(False, mode="drop")
        failed = failed.at[add].set(True, mode="drop")
        n_failed = jnp.sum(failed.astype(jnp.int32))

        attack_success = jnp.all(attack_success_bs)              # scalar (all B, all S)
        certifiable = n_failed == 0

        loss_target = aux["group_lasso"] if stage == 0 else aux["loss_struc"]
        loss_best = jnp.where(n_failed < state.num_failure, jnp.inf, state.loss_best)
        certify_better = n_failed <= state.num_failure
        loss_decay = certify_better & ((loss_target - loss_best) < -cfg.loss_decay_margin)

        any_save = jnp.any(loss_decay)
        num_failure = jnp.where(any_save, n_failed, state.num_failure)
        loss_best = jnp.where(loss_decay, loss_target, loss_best)
        sel = loss_decay[:, None, None, None]
        best_mask = jnp.where(sel, state.adv_mask, state.best_mask) if stage == 0 else state.best_mask
        best_pattern = jnp.where(sel, state.adv_pattern, state.best_pattern)
        not_decay = jnp.where(loss_decay, 0, state.not_decay + 1)

        # adaptive coefficient schedule (`attack.py:294-303`): stage 0 past
        # adapt_start scales the group-lasso coefficient, every other step
        # (including early stage 0) scales the structural coefficient.
        grow = attack_success & certifiable
        factor = jnp.where(grow, cfg.scale_up, 1.0 / cfg.scale_down)
        if stage == 0:
            gl_adapts = state.step > cfg.adapt_start
        else:
            gl_adapts = jnp.asarray(False)
        coeff_gl = jnp.where(gl_adapts, state.coeff_gl * factor, state.coeff_gl)
        coeff_struct = jnp.where(gl_adapts, state.coeff_struct, state.coeff_struct * factor)

        # patience lr decay (`attack.py:292,305-316`)
        early = not_decay > cfg.patience
        lr = jnp.where(early, state.lr * cfg.lr_decay, state.lr)
        lr = jnp.maximum(lr, cfg.lr_floor)
        not_decay = jnp.where(early, 0, not_decay)
        stopped = jnp.all(lr < cfg.lr_stop)

        # signed-gradient updates (`attack.py:332-342`); mask only in stage 0.
        # The reference breaks *before* the update once every lr has decayed
        # below lr_stop (`attack.py:311-316` precede `:332-342`), so the
        # stopping step keeps its bookkeeping but applies no update.
        lr_b = lr[:, None, None, None]
        new_pattern = jnp.where(
            stopped,
            state.adv_pattern,
            jnp.clip(state.adv_pattern - lr_b * jnp.sign(g_pattern),
                     cfg.clip_min, cfg.clip_max),
        )
        if stage == 0:
            new_mask = jnp.where(
                stopped,
                state.adv_mask,
                jnp.clip(state.adv_mask - lr_b * jnp.sign(g_mask),
                         cfg.clip_min, cfg.clip_max),
            )
        else:
            new_mask = state.adv_mask

        acc = jnp.mean((aux["preds"] == state.y[:, None]).astype(jnp.float32))
        l2 = jnp.sqrt(jnp.sum(aux["delta"] ** 2, axis=(1, 2, 3))).mean()
        metrics = jnp.stack(
            [
                aux["loss"].mean(), loss_adv.mean(), aux["loss_struc"].mean(),
                aux["group_lasso"].mean(), aux["density"].mean(), acc, l2,
                n_failed.astype(jnp.float32),
            ]
        )

        new = TrainState(
            step=state.step + 1, rng=rng, adv_mask=new_mask, adv_pattern=new_pattern,
            best_mask=best_mask, best_pattern=best_pattern, loss_best=loss_best,
            lr=lr, not_decay=not_decay, num_failure=num_failure, failed=failed,
            coeff_gl=coeff_gl, coeff_struct=coeff_struct,
            coeff_density=state.coeff_density, targeted=state.targeted,
            y=state.y, last_preds=aux["preds"], stopped=state.stopped | stopped,
            metrics=metrics,
        )
        # latched early stop: once stopped, the state passes through unchanged
        return jax.tree_util.tree_map(
            lambda old, upd: jnp.where(state.stopped, old, upd), state, new
        )

    # ---------- jitted block + sweep ----------

    def _out_replicated(self):
        """out_shardings pin for the per-image program outputs when a mesh
        is present: the compiler is otherwise free to leave carry fields
        sharded over the data axis, which a multi-PROCESS driver cannot
        np.asarray (non-addressable shards). Per-image state is tiny next
        to the masked batch, so the closing gather is noise — and the HLO
        guard's no-big-all-gather bound still holds."""
        if self.mesh is None:
            return None
        from jax.sharding import NamedSharding, PartitionSpec

        return NamedSharding(self.mesh, PartitionSpec())

    def _get_block(self, stage: int, img_size: int, n_steps: int):
        key = (stage, img_size, n_steps)
        if key not in self._programs:

            @partial(jax.jit, static_argnums=(),
                     out_shardings=self._out_replicated())
            def run_block(state, x, local_var_x, universe):
                def body(s, _):
                    return self._step(s, x, local_var_x, universe, stage), None

                state, _ = jax.lax.scan(body, state, None, length=n_steps)
                return state

            # telemetry: the first call pays trace+XLA compile; record it as
            # a `compile` event on whatever EventLog the driver activated
            self._programs[key] = observe.timed_first_call(
                run_block, f"attack.block.stage{stage}.steps{n_steps}",
                recompile_budget=self.recompile_budget)
        return self._programs[key]

    def _get_sweep(self):
        """The jitted full-universe sweep program, built (not executed) on
        first use — split from `sweep_failures` so the program auditor can
        enumerate it abstractly (`analysis/entrypoints.py`)."""
        if "sweep" not in self._programs:

            @partial(jax.jit, out_shardings=self._out_replicated())
            def sweep(adv_mask, adv_pattern, x, y, targeted, universe):
                delta = losses.l2_project(adv_mask, adv_pattern, x, self.config.eps)
                adv_x = x + delta
                preds = masked_predictions(
                    self._fwd, self.params, adv_x, universe,
                    min(self._sampling_size, universe.shape[0]),
                    self.config.mask_fill, self.config.use_pallas,
                    mesh=self.mesh,
                )  # [B, n_mask]
                hit = preds == y[:, None]
                fail_per_img = jnp.where(targeted[:, None], ~hit, hit)
                return jnp.any(fail_per_img, axis=0)

            self._programs["sweep"] = observe.timed_first_call(
                sweep, "attack.sweep",
                recompile_budget=self.recompile_budget)
        return self._programs["sweep"]

    def sweep_failures(self, adv_mask, adv_pattern, x, y, targeted, universe) -> jax.Array:
        """Full-universe failure sweep (`attack.py:384-406`): a mask index
        fails if any image's goal is violated under it. Returns bool [n_mask]."""
        return self._get_sweep()(adv_mask, adv_pattern, x, y, targeted, universe)

    # ---------- host orchestration ----------

    def _init_state(self, key, x, y, targeted, universe_size) -> TrainState:
        cfg = self.config
        b, h, w, _ = x.shape
        k_mask, k_pat, k_run = jax.random.split(key, 3)
        return TrainState(
            step=jnp.asarray(0, jnp.int32),
            rng=k_run,
            adv_mask=jax.random.uniform(k_mask, (b, h, w, 1)),
            adv_pattern=jax.random.uniform(k_pat, (b, h, w, 3)),
            best_mask=jnp.zeros((b, h, w, 1)),
            best_pattern=jnp.zeros((b, h, w, 3)),
            # explicit dtype: a weak-typed init (python-float inf) retraces
            # every block program once when the strong-typed carry comes
            # back around — caught by the recompile watchdog (--sanitize)
            loss_best=jnp.full((b,), jnp.inf, jnp.float32),
            lr=jnp.full((b,), cfg.lr, jnp.float32),
            not_decay=jnp.zeros((b,), jnp.int32),
            num_failure=jnp.asarray(universe_size + 1, jnp.int32),
            failed=jnp.zeros((universe_size,), bool),
            coeff_gl=jnp.asarray(cfg.coeff_group_lasso, jnp.float32),
            coeff_struct=jnp.asarray(cfg.structured, jnp.float32),
            coeff_density=jnp.asarray(cfg.density, jnp.float32),
            targeted=jnp.broadcast_to(jnp.asarray(targeted, bool), (b,)).copy(),
            y=jnp.asarray(y, jnp.int32),
            last_preds=jnp.zeros((b, min(self._sampling_size, universe_size)), jnp.int32),
            stopped=jnp.asarray(False),
            metrics=jnp.zeros((8,)),
        )

    def _reset_schedules(self, state: TrainState, universe_size: int) -> TrainState:
        """lr/best/patience reset at the targeted switch (`attack.py:177-180`)."""
        cfg = self.config
        b = state.lr.shape[0]
        return state._replace(
            lr=jnp.full((b,), cfg.lr, jnp.float32),
            loss_best=jnp.full((b,), jnp.inf, jnp.float32),  # strong: see _init_state
            not_decay=jnp.zeros((b,), jnp.int32),
            num_failure=jnp.asarray(universe_size + 1, jnp.int32),
        )

    def _finalize_best(self, state: TrainState) -> Tuple[jax.Array, jax.Array]:
        """Images that never checkpointed fall back to their current iterate
        (`attack.py:311-316,344-346`), per image (batched repair)."""
        never = jnp.isinf(state.loss_best)[:, None, None, None]
        best_mask = jnp.where(never, state.adv_mask, state.best_mask)
        best_pattern = jnp.where(never, state.adv_pattern, state.best_pattern)
        return best_mask, best_pattern

    def _run_stage(
        self, stage: int, state: TrainState, x, local_var_x, universe,
        start_iter: int = 0, stage0_artifacts=None,
    ) -> TrainState:
        cfg = self.config
        img_size = x.shape[1]
        n_universe = universe.shape[0]
        interval = cfg.sweep_interval
        total = cfg.max_iterations
        block = self._get_block(stage, img_size, interval)

        if bool(state.stopped):
            return state  # e.g. resumed from a snapshot taken at early stop

        i = start_iter
        with observe.span(f"attack.stage{stage}", start_iter=start_iter) as sp:
            while i < total:
                # full failure sweep at every sweep_interval boundary
                # (incl. i=0, `attack.py:187-190`)
                failed = self.sweep_failures(
                    state.adv_mask, state.adv_pattern, x, state.y,
                    state.targeted, universe
                )
                state = state._replace(failed=failed)

                n_steps = min(interval, total - i)
                if n_steps != interval:
                    block = self._get_block(stage, img_size, n_steps)
                state = block(state, x, local_var_x, universe)
                i += n_steps

                # untargeted -> targeted switch at the boundary after
                # switch_iteration steps (stage 0, `attack.py:169-182`)
                if (
                    stage == 0
                    and i >= cfg.switch_iteration
                    and i - n_steps < cfg.switch_iteration
                    and not bool(jnp.all(state.targeted))
                ):
                    y_new, has_target = majority_incorrect_label(
                        state.last_preds, state.y, self.num_classes
                    )
                    switch = has_target & (~state.targeted)
                    state = state._replace(
                        targeted=state.targeted | switch,
                        y=jnp.where(switch, y_new, state.y),
                    )
                    state = self._reset_schedules(state, n_universe)

                # snapshot before the user callback, so a crash anywhere
                # after the block computation resumes from this block
                if self.checkpointer is not None:
                    s0 = stage0_artifacts or (None, None)
                    self.checkpointer.save(stage, i, state, s0[0], s0[1])
                if self.on_block_end is not None:
                    self.on_block_end(stage, i, {
                        "metrics": np.asarray(state.metrics),
                        "stopped": bool(state.stopped),
                        "n_failed": int(np.asarray(state.metrics)[7]),
                    })
                if bool(state.stopped):
                    break
            sp["end_iter"] = i
        return state

    def generate(
        self,
        x: jax.Array,
        y: Optional[jax.Array] = None,
        targeted: bool = False,
        key: Optional[jax.Array] = None,
        store=None,
        batch_id: int = 0,
    ) -> AttackResult:
        """Run the full two-stage attack on a batch of images
        (`/root/reference/attack.py:51-361`).

        x: `[B,H,W,C]` in [0,1]. y: labels (targets when `targeted`); when
        None, the model's own predictions are used (`attack.py:67-69`).
        `store` (optional) provides stage-0 artifact sharing across budgets:
        `store.load_stage0(batch_id) -> (mask, pattern) | None` and
        `store.save_stage0(batch_id, mask, pattern)`
        (`attack.py:102-103,134-141,348-356`).
        """
        cfg = self.config
        if key is None:
            # derive from the configured seed (utils.set_global_seed), not a
            # hard-coded PRNGKey literal — rule DP104
            key = utils.global_key()
        img_size = x.shape[1]
        universe = jnp.asarray(
            masks_lib.dropout_universe(img_size, cfg.dropout, cfg.dropout_sizes)
        )
        if y is None:
            y = jnp.argmax(self.apply_fn(self.params, x), axis=-1)
        local_var_x = jnp.mean(losses.local_variance(x)[0], axis=-1)

        k0, k1 = jax.random.split(key)
        state = self._init_state(k0, x, y, targeted, universe.shape[0])

        # mid-stage crash recovery: restore the latest carry snapshot, if any
        resume = None
        if self.checkpointer is not None:
            resume = self.checkpointer.restore(
                state, (state.adv_mask, state.adv_pattern))

        # ---- stage 0: importance map (resumable from the shared parent dir) ----
        cached = store.load_stage0(batch_id) if store is not None else None
        if resume is not None and resume.stage == 1:
            # carry snapshot is already past stage 0; y/targeted/coefficients
            # all live inside the restored state
            stage0_mask = jnp.asarray(resume.stage0_mask)
            stage0_pattern = jnp.asarray(resume.stage0_pattern)
        elif cached is not None:
            stage0_mask, stage0_pattern = (jnp.asarray(cached[0]), jnp.asarray(cached[1]))
            targeted_now = targeted
            coeff_struct_carry = jnp.asarray(cfg.structured, jnp.float32)
        else:
            start0 = 0
            if resume is not None and resume.stage == 0:
                state, start0 = resume.state, resume.iteration
            state = self._run_stage(0, state, x, local_var_x, universe,
                                    start_iter=start0)
            stage0_mask, stage0_pattern = self._finalize_best(state)
            targeted_now = state.targeted  # [B] per-image flags after stage 0
            # the reference mutates `structured` in place, so stage 1 inherits
            # the stage-0-adapted value (`attack.py:299-303`)
            coeff_struct_carry = state.coeff_struct
            if store is not None:
                store.save_stage0(batch_id, np.asarray(stage0_mask), np.asarray(stage0_pattern))

        # ---- stage 1 init (`attack.py:143-165`) ----
        if resume is not None and resume.stage == 1:
            state, start1 = resume.state, resume.iteration
        else:
            start1 = 0
            delta = losses.l2_project(stage0_mask, stage0_pattern, x, cfg.eps)
            adv_x = x + delta
            targeted_vec = jnp.broadcast_to(jnp.asarray(targeted_now, bool), (x.shape[0],))
            targeted_vec = targeted_vec | state.targeted
            preds = jnp.argmax(self.apply_fn(self.params, adv_x), axis=-1)
            newly = (~targeted_vec) & (preds != state.y)
            y_cur = jnp.where(newly, preds, state.y)
            targeted_vec = targeted_vec | newly

            hard_mask = patch_selection(stage0_mask, cfg.patch_budget, cfg.basic_unit)
            state = self._init_state(k1, x, y_cur, False, universe.shape[0])
            state = state._replace(
                adv_mask=hard_mask,
                adv_pattern=adv_x,
                best_mask=hard_mask,
                y=jnp.asarray(y_cur, jnp.int32),
                targeted=targeted_vec,
                coeff_struct=coeff_struct_carry,
            )
        state = self._run_stage(1, state, x, local_var_x, universe,
                                start_iter=start1,
                                stage0_artifacts=(stage0_mask, stage0_pattern))
        best_mask, best_pattern = self._finalize_best(state)

        return AttackResult(
            adv_mask=best_mask,
            adv_pattern=best_pattern,
            y=np.asarray(state.y),
            targeted=np.asarray(state.targeted),
            stage0_mask=stage0_mask,
            stage0_pattern=stage0_pattern,
        )
