"""Shared exponential-backoff/jitter math.

One formula for every retry loop in the system — farm job retries
(`farm/queue.py`), serve replica restarts (`serve/pool.py`), and the load
generator's 503 retry loop (`tools/loadgen.py`) all call `retry_delay` so
their accounting is comparable and their tests deterministic.
"""

from __future__ import annotations

import hashlib


def retry_delay(job_id: str, attempt: int, base: float = 2.0,
                cap: float = 300.0, jitter: float = 0.25) -> float:
    """Exponential backoff with *deterministic* jitter seeded from the job
    id and attempt number: retries are exactly reproducible (no flaky
    recovery tests), while a burst of simultaneous failures still spreads
    its retries instead of thundering back in lockstep."""
    delay = min(float(cap), float(base) * (2.0 ** max(0, attempt - 1)))
    seed = int.from_bytes(
        hashlib.sha256(f"{job_id}:{attempt}".encode()).digest()[:4], "big")
    return delay * (1.0 + float(jitter) * (seed / 2.0 ** 32))
