"""Crash-resumable recert scheduler: grid generations on the farm.

One *generation* = one (model x defense x attack) grid submitted to a
private farm directory (``<recert_dir>/gen_NNNN``), drained by ordinary
farm workers, harvested into per-cell robust-accuracy measurements, and
checked against the checked-in `robustness_baseline.json` (DP400-DP402).

Crash discipline mirrors the farm queue it drives:

- `recert_state.json` is the scheduler's only mutable state and every
  transition is one `checkpoint.atomic_write_json` — a reader never sees
  a half-written generation counter.
- The in-flight record is committed BEFORE `submit_spec`, so a SIGKILL
  anywhere in the cycle leaves either (a) an inflight record whose farm
  dir resumes exactly where the workers left it (`submit_spec` is
  idempotent — resubmission tops up missing jobs, never resets live
  ones), or (b) a completed generation whose `recert_complete.json`
  marker already landed. Resume therefore finishes the SAME generation
  instead of starting a new one.
- A torn `recert_state.json` (corrupt/truncated) is recovered by scanning
  the generation dirs themselves: each completed generation carries an
  atomic completion marker, so the dirs are the ground truth and the
  state file is merely a cache of them.

A generation *completes* even when cells are missing: `JobQueue.drained`
counts quarantined/exhausted jobs as terminal, so a farm worker
quarantined mid-grid leaves a hole that harvests as DP402 — the scheduler
reports it rather than hanging on it.

Host-only orchestration: nothing here touches a jax backend; the model
stack runs inside the farm workers.
"""

from __future__ import annotations

import os
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

from dorpatch_tpu import observe
from dorpatch_tpu.checkpoint import atomic_write_json, load_json
from dorpatch_tpu.farm.queue import FARM_NAME, JobQueue
from dorpatch_tpu.farm.report import read_result_rows
from dorpatch_tpu.recert import baseline as rbase

STATE_NAME = "recert_state.json"
COMPLETE_NAME = "recert_complete.json"
VERDICT_NAME = "recert_verdict.json"
GEN_PREFIX = "gen_"


class RecertError(RuntimeError):
    """Typed scheduler-level refusal (no completed generation to check,
    baseline update would drop entries without --allow-remove, ...)."""


def is_recert_dir(path: str) -> bool:
    return os.path.exists(os.path.join(path, STATE_NAME))


def _gen_number(name: str) -> Optional[int]:
    if not name.startswith(GEN_PREFIX):
        return None
    try:
        return int(name[len(GEN_PREFIX):])
    except ValueError:
        return None


class RecertScheduler:
    """All reads/writes of one recert directory's generation state."""

    def __init__(self, recert_dir: str, baseline_file: str = "",
                 clock=time.time, chaos=None):
        self.recert_dir = os.path.abspath(recert_dir)
        os.makedirs(self.recert_dir, exist_ok=True)
        self.baseline_file = baseline_file or str(rbase.baseline_path())
        self._clock = clock
        self.chaos = chaos
        # generation timing/outcome series; snapshotted to
        # <recert_dir>/metrics.json at every completion so the fleet
        # report reads recert the same way it reads serve and farm dirs
        self.metrics = observe.MetricRegistry()
        # one shared log handle, opened lazily on the first record: a fresh
        # EventLog per record would restart its seq at 0 every time, and
        # two threads recording at once (drain poller + canary gate) would
        # interleave half-open handles on the same file
        self._lock = threading.Lock()
        self._elog = None  # guarded-by: self._lock

    def _record(self, name: str, **fields) -> None:
        """Append one event to the recert dir's own events.jsonl (the
        scheduler runs outside any job's event log, but its generation
        begin/complete records must land somewhere the fleet report can
        join on trace id)."""
        with self._lock:
            if self._elog is None:
                self._elog = observe.EventLog(
                    os.path.join(self.recert_dir,
                                 observe.events_filename(0)))
            self._elog.event(name, **fields)

    # ---------------- state ----------------

    @property
    def state_path(self) -> str:
        return os.path.join(self.recert_dir, STATE_NAME)

    @property
    def verdict_path(self) -> str:
        return os.path.join(self.recert_dir, VERDICT_NAME)

    def gen_dir(self, generation: int) -> str:
        return os.path.join(self.recert_dir,
                            f"{GEN_PREFIX}{int(generation):04d}")

    def gen_numbers(self) -> List[int]:
        try:
            names = os.listdir(self.recert_dir)
        except OSError:
            return []
        out = []
        for name in names:
            g = _gen_number(name)
            if g is not None and os.path.isdir(
                    os.path.join(self.recert_dir, name)):
                out.append(g)
        return sorted(out)

    def _gen_complete(self, generation: int) -> bool:
        return os.path.exists(
            os.path.join(self.gen_dir(generation), COMPLETE_NAME))

    def load_state(self) -> Dict[str, Any]:
        """The scheduler state, recovered from the generation dirs when the
        state file is missing or torn."""
        state = load_json(self.state_path)
        if (isinstance(state, dict) and "generation" in state
                and "inflight" in state):
            return state
        return self._recover_state()

    def _recover_state(self) -> Dict[str, Any]:
        """Rebuild `recert_state.json` from the ground truth: completed
        generations have an atomic `recert_complete.json` marker; a gen dir
        holding a farm spec but no marker is the in-flight generation."""
        completed = 0
        inflight: Optional[Dict[str, Any]] = None
        for g in self.gen_numbers():
            gdir = self.gen_dir(g)
            if self._gen_complete(g):
                completed = max(completed, g)
            elif os.path.exists(os.path.join(gdir, FARM_NAME)):
                farm = load_json(os.path.join(gdir, FARM_NAME), {})
                inflight = {"generation": g,
                            "farm_dir": os.path.basename(gdir),
                            "spec": farm.get("spec", {})}
        if inflight is not None and inflight["generation"] <= completed:
            inflight = None
        state = {"version": 1, "generation": completed, "inflight": inflight}
        atomic_write_json(self.state_path, state)
        return state

    # ---------------- generations ----------------

    def begin_generation(self, spec: Optional[Dict[str, Any]] = None
                         ) -> Tuple[int, str]:
        """Start the next generation — or resume the in-flight one.

        The inflight record (including the spec) is committed to the state
        file BEFORE jobs are submitted; `submit_spec` is idempotent, so a
        crash at any point between the two leaves a resumable farm, never a
        duplicated one. Returns (generation, farm_dir)."""
        state = self.load_state()
        inflight = state.get("inflight")
        if inflight:
            generation = int(inflight["generation"])
            spec = inflight.get("spec") or spec
        else:
            if spec is None:
                raise RecertError(
                    "no in-flight generation to resume and no spec given")
            generation = int(state.get("generation", 0)) + 1
            inflight = {"generation": generation,
                        "farm_dir": f"{GEN_PREFIX}{generation:04d}",
                        "spec": spec,
                        # generation wall-clock start + the cross-process
                        # correlation id minted at THIS ingress: both ride
                        # the inflight record so a crash/resume keeps the
                        # original start and trace, not a fresh pair
                        "began_ts": round(self._clock(), 3),
                        "trace": observe.new_trace_id()}
            atomic_write_json(self.state_path, {
                "version": 1, "generation": state.get("generation", 0),
                "inflight": inflight})
            self._record("recert.generation.begin", generation=generation,
                         trace=inflight["trace"], opens_trace=True)
        farm_dir = os.path.join(self.recert_dir, inflight["farm_dir"])
        if spec is None:
            raise RecertError(
                f"in-flight generation {generation} has no recorded spec")
        JobQueue(farm_dir, clock=self._clock).submit_spec(spec)
        if self.chaos is not None:
            self.chaos.on_recert("submitted", state_path=self.state_path)
        return generation, farm_dir

    def counts(self, farm_dir: str) -> Dict[str, int]:
        return JobQueue(farm_dir, clock=self._clock).counts()

    def drained(self, farm_dir: str) -> bool:
        return JobQueue(farm_dir, clock=self._clock).drained()

    def wait_drained(self, farm_dir: str, poll_interval: float = 0.5,
                     timeout: Optional[float] = None,
                     sleep=time.sleep) -> bool:
        """Poll until every job in the generation's farm is terminal.
        Quarantined/exhausted jobs count as terminal — a generation with
        holes completes (and reports them) instead of hanging."""
        # the timeout is a local bound on OUR waiting, not a cross-process
        # protocol — monotonic, so an NTP step mid-drain cannot expire it
        # early or stretch it
        deadline = (None if timeout is None
                    else time.monotonic() + timeout)
        while True:
            if self.drained(farm_dir):
                return True
            if deadline is not None and time.monotonic() >= deadline:
                return False
            sleep(poll_interval)

    # ---------------- harvest ----------------

    def harvest(self, farm_dir: str
                ) -> Tuple[Dict[str, Dict[str, Any]], List[str]]:
        """(measured, holes) for one drained generation: done jobs' rows
        become per-cell measurements; every expected cell a job failed to
        produce (quarantined, exhausted, or a torn rows file) is a hole."""
        jq = JobQueue(farm_dir, clock=self._clock)
        measured: Dict[str, Dict[str, Any]] = {}
        holes: set = set()
        for job_id in jq.job_ids():
            job = jq.read_job(job_id)
            if job is None:
                continue  # unreadable job.json: cells not even enumerable
            expected = rbase.job_cells(job)
            if job.get("state") == "done":
                seen = set()
                rows = read_result_rows(
                    os.path.join(jq.job_dir(job_id), "results"))
                for row in rows:
                    key = rbase.cell_key(job, row)
                    measured[key] = rbase.row_measurement(row, job_id)
                    seen.add(key)
                holes.update(k for k in expected if k not in seen)
            else:
                holes.update(expected)
        holes -= set(measured)  # a sibling job may have covered the cell
        return measured, sorted(holes)

    # ---------------- completion / checking ----------------

    def _write_baseline(self, data: Dict[str, Any]) -> None:
        """Deterministic text + atomic replace: the baseline file carries
        no timestamps, so an interrupted-and-resumed generation commits a
        byte-identical file to an uninterrupted one."""
        text = rbase.dump_baseline(data)
        tmp = f"{self.baseline_file}.tmp.{os.getpid()}"
        with open(tmp, "w", encoding="utf-8") as fh:
            fh.write(text)
            fh.flush()
            os.fsync(fh.fileno())
        os.replace(tmp, self.baseline_file)

    def complete_generation(self, generation: int, farm_dir: str,
                            update_baseline: bool = False,
                            allow: Optional[Dict[str, Dict[str, str]]] = None
                            ) -> Dict[str, Any]:
        """Harvest a drained generation, check it against the baseline,
        publish the verdict, and commit the generation as done — in that
        order, each step atomic, so a crash replays idempotently."""
        measured, holes = self.harvest(farm_dir)
        data = rbase.load_baseline(self.baseline_file)
        if update_baseline:
            data = rbase.fold_measurements(data, measured, generation)
            self._write_baseline(data)
        findings = rbase.check_measurements(
            measured, holes, data, generation,
            baseline_file=self.baseline_file, allow=allow)
        verdict = rbase.build_verdict(measured, holes, data, generation,
                                      findings,
                                      baseline_file=self.baseline_file)
        atomic_write_json(self.verdict_path, verdict)
        # generation timing + trace come off the inflight record (absent
        # after a state recovery — seconds then reads null, never wrong)
        inflight = self.load_state().get("inflight") or {}
        began_ts = inflight.get("began_ts")
        trace = inflight.get("trace", "")
        seconds = (round(self._clock() - float(began_ts), 3)
                   if began_ts is not None else None)
        marker = {
            "generation": int(generation),
            "measured": len(measured),
            "holes": holes,
            "status": verdict["status"],
        }
        if seconds is not None:
            marker["seconds"] = seconds
        if trace:
            marker["trace"] = trace
        atomic_write_json(os.path.join(farm_dir, COMPLETE_NAME), marker)
        atomic_write_json(self.state_path, {
            "version": 1, "generation": int(generation), "inflight": None})
        self.metrics.counter(
            "recert_generations_total",
            help="completed generations by verdict status",
        ).inc(status=str(verdict["status"]))
        if seconds is not None:
            self.metrics.histogram(
                "recert_generation_seconds",
                help="submit-to-verdict wall seconds per generation",
            ).observe(seconds)
        fields = {"generation": int(generation), "status": verdict["status"],
                  "measured": len(measured), "holes": len(holes)}
        if seconds is not None:
            fields["seconds"] = seconds
        if trace:
            fields["trace"] = trace
        self._record("recert.generation", **fields)
        self.metrics.dump(os.path.join(self.recert_dir, "metrics.json"))
        return verdict

    def latest_completed(self) -> Tuple[int, str]:
        """(generation, farm_dir) of the newest completed generation."""
        done = [g for g in self.gen_numbers() if self._gen_complete(g)]
        if not done:
            raise RecertError(
                f"no completed generation under {self.recert_dir} — run "
                "`python -m dorpatch_tpu.recert run` first")
        g = max(done)
        return g, self.gen_dir(g)

    def check_latest(self, allow: Optional[Dict[str, Dict[str, str]]] = None,
                     select=None) -> Tuple[int, List, Dict[str, Any]]:
        """Re-harvest the newest completed generation and diff it against
        the CURRENT baseline file (which may have changed since the
        generation completed); rewrites the published verdict. Returns
        (generation, findings, verdict)."""
        generation, farm_dir = self.latest_completed()
        measured, holes = self.harvest(farm_dir)
        data = rbase.load_baseline(self.baseline_file)
        findings = rbase.check_measurements(
            measured, holes, data, generation,
            baseline_file=self.baseline_file, allow=allow, select=select)
        verdict = rbase.build_verdict(measured, holes, data, generation,
                                      findings,
                                      baseline_file=self.baseline_file)
        atomic_write_json(self.verdict_path, verdict)
        return generation, findings, verdict

    def update_from_latest(self, allow_remove: bool = False
                           ) -> Dict[str, Any]:
        """Fold the newest completed generation's measurements into the
        baseline file. Entries that are neither measured nor holes (the
        grid shrank) are only dropped under `allow_remove`; without it the
        update REFUSES rather than silently losing entries — the same
        contract `analysis --baseline update` enforces."""
        generation, farm_dir = self.latest_completed()
        measured, holes = self.harvest(farm_dir)
        data = rbase.load_baseline(self.baseline_file) \
            or rbase.empty_baseline()
        old = set(data.get("entries", {}))
        removed = sorted(old - set(measured) - set(holes))
        if removed and not allow_remove:
            raise RecertError(
                f"update would drop {len(removed)} baseline entr(ies) no "
                "longer in the grid: "
                + ", ".join(removed[:4])
                + (" ..." if len(removed) > 4 else "")
                + " — pass --allow-remove to accept the shrink")
        new = rbase.fold_measurements(data, measured, generation)
        if allow_remove and removed:
            entries = dict(new["entries"])
            for key in removed:
                entries.pop(key, None)
            new["entries"] = entries
        self._write_baseline(new)
        findings = rbase.check_measurements(
            measured, holes, new, generation,
            baseline_file=self.baseline_file)
        verdict = rbase.build_verdict(measured, holes, new, generation,
                                      findings,
                                      baseline_file=self.baseline_file)
        atomic_write_json(self.verdict_path, verdict)
        return {"generation": generation, "baseline_file": self.baseline_file,
                "entries": len(new["entries"]), "folded": len(measured),
                "removed": removed, "holes": holes,
                "status": verdict["status"]}

    # ---------------- status ----------------

    def status(self) -> Dict[str, Any]:
        state = self.load_state()
        out: Dict[str, Any] = {
            "recert_dir": self.recert_dir,
            "baseline_file": self.baseline_file,
            "generation": int(state.get("generation", 0)),
            "inflight": None,
        }
        inflight = state.get("inflight")
        if inflight:
            farm_dir = os.path.join(self.recert_dir, inflight["farm_dir"])
            out["inflight"] = {
                "generation": int(inflight["generation"]),
                "farm_dir": farm_dir,
                "counts": self.counts(farm_dir),
            }
        verdict = load_json(self.verdict_path)
        if isinstance(verdict, dict):
            out["verdict"] = {
                "generation": verdict.get("generation"),
                "status": verdict.get("status"),
                "worst_margin": verdict.get("worst_margin"),
                "findings_by_rule": verdict.get("findings_by_rule", {}),
            }
        return out
