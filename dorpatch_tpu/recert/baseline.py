"""Adversarial regression baseline: robust-accuracy drift gate (DP4xx).

The analysis tier's `baselines.json` pins what the *programs* compute; this
module pins what the *attack* achieves against them. A recert generation
(one (model x defense x attack) grid run through the farm) produces one
robust-accuracy measurement per grid cell; the checked-in
`robustness_baseline.json` records the accepted reference value per cell
with a per-cell ABSOLUTE tolerance (percentage points of robust accuracy —
adversarial numbers are noisy in absolute terms, not relative ones).

Rules (emitted through `analysis.engine.Finding`, so `--format json`,
allowlists, and the 0/1/2 exit-code contract carry over):

- **DP400 robust-accuracy-regression** — a cell's fresh measurement fell
  below its baseline robust accuracy by more than the cell's tolerance
  (or its certified attack-success rate rose past it): the defense got
  weaker against the standing red team. The paper's whole point is that
  this happens silently; this gate makes it a CI failure.
- **DP401 grid-cell-drift** — a cell was measured that the baseline does
  not know, or a baseline cell is no longer in the submitted grid at all:
  the coverage contract changed shape without a baseline update.
- **DP402 stale-cell** — a baseline cell got NO fresh measurement from the
  generation (the owning job quarantined/exhausted — the generation
  completes on the remaining cells and reports the hole instead of
  hanging), or the baseline itself has never been seeded/updated: serving
  from it would mean serving silently-uncertified.

Suppression: `ALLOWLIST` (fnmatch glob over the cell key ->
{rule: reason}) — the noqa analog for measurements, which have no source
line to annotate. Shipped entries must carry their reason.

The baseline file is the adversarial sibling of `analysis/baselines.json`:
shipped inside the package (`recert/robustness_baseline.json`), regenerated
deterministically (`python -m dorpatch_tpu.recert update` — sorted keys,
normalized floats, update-twice byte-identical via the analysis dump), and
reviewed like any other checked-in contract.
"""

from __future__ import annotations

import fnmatch
import json
import pathlib
from typing import Any, Dict, Iterable, List, Mapping, Optional, Sequence, Tuple

from dorpatch_tpu.analysis.baseline import dump_baseline as _dump
from dorpatch_tpu.analysis.engine import Finding

#: The checked-in default baseline, shipped inside the package.
BASELINE_FILENAME = "robustness_baseline.json"

#: DP400 default tolerance, in absolute percentage points of robust
#: accuracy (and of certified ASR): a tiny CI batch quantizes accuracy in
#: steps of 100/images, so the default leaves room for one image flipping
#: on re-measurement noise while still catching real regressions.
DEFAULT_TOLERANCE = 2.0

#: Cell-key glob -> {rule_id: reason} — intentional drift with no source
#: line to own it. Shipped entries must carry their reason.
ALLOWLIST: Dict[str, Dict[str, str]] = {}

#: (id, name, description) rows for `--list-rules`-style help.
RECERT_RULE_ROWS: Tuple[Tuple[str, str, str], ...] = (
    ("DP400", "robust-accuracy-regression",
     "a grid cell's fresh robust-accuracy measurement regressed past its "
     "absolute tolerance vs recert/robustness_baseline.json (or certified "
     "ASR rose past it) — the defense got weaker against the standing "
     "red team"),
    ("DP401", "grid-cell-drift",
     "a measured cell is missing from the baseline, or a baseline cell is "
     "no longer in the submitted grid — the coverage contract changed "
     "shape; regenerate with `python -m dorpatch_tpu.recert update`"),
    ("DP402", "stale-cell",
     "a baseline cell got no fresh measurement this generation (owning "
     "job quarantined/exhausted, or the baseline was never seeded) — "
     "serving on it would be serving silently-uncertified"),
)

RECERT_RULE_IDS: Tuple[str, ...] = tuple(r[0] for r in RECERT_RULE_ROWS)

#: Grid-cell hyperparameters (mirrors `sweep.GRID_KEYS`; kept literal so
#: this host-only module never imports the model stack).
GRID_KEYS = ("patch_budget", "density", "structured")


def baseline_path() -> pathlib.Path:
    """The checked-in default baseline file (inside the package)."""
    return pathlib.Path(__file__).with_name(BASELINE_FILENAME)


# ------------------------------------------------------------- cell keys

def _fmt(v: Any) -> str:
    """JSON-roundtrip-stable rendering of a cell-key value: rows come back
    from `rows.jsonl` with json's float formatting, so the key built from a
    spec float and the key built from its recorded row must agree."""
    if isinstance(v, bool) or not isinstance(v, (int, float)):
        return str(v)
    return json.dumps(json.loads(json.dumps(float(v))))


def cell_key(job: Mapping[str, Any], row: Mapping[str, Any]) -> str:
    """Stable identity of one (model, defense, attack) grid cell.

    Built from the job's base config (model identity), its sweep dict
    (defense ratio), its non-grid axis params (e.g. `attack.dropout` axes
    that the row keys alone cannot distinguish), and the row's grid-point
    hyperparameters."""
    base = job.get("base", {}) or {}
    sweep = job.get("sweep", {}) or {}
    model = (f"{base.get('base_arch', '?')}@{base.get('dataset', '?')}"
             f"/{base.get('img_size', '?')}")
    defense = f"pc:r{_fmt(sweep.get('defense_ratio', 0.06))}"
    extras = ",".join(
        f"{k.split('.')[-1]}={_fmt(v)}"
        for k, v in sorted((job.get("params", {}) or {}).items())
        if k.split(".")[-1] not in GRID_KEYS)
    point = ",".join(f"{k}={_fmt(row.get(k, '?'))}" for k in GRID_KEYS)
    attack = f"{extras},{point}" if extras else point
    return f"{model}|{defense}|{attack}"


def job_cells(job: Mapping[str, Any]) -> List[str]:
    """Every cell the job's grid slice is expected to produce — computable
    WITHOUT rows, so a quarantined job's holes are still enumerable."""
    from dorpatch_tpu.farm.worker import job_config  # lazy: pulls config

    cfg = job_config(dict(job))
    sw = dict(job.get("sweep", {}) or {})
    budgets = sw.get("patch_budgets", [cfg.attack.patch_budget])
    densities = sw.get("densities", [cfg.attack.density])
    structureds = sw.get("structureds", [cfg.attack.structured])
    return [cell_key(job, {"patch_budget": b, "density": d, "structured": s})
            for b in budgets for d in densities for s in structureds]


def row_measurement(row: Mapping[str, Any], job_id: str) -> Dict[str, Any]:
    """One row -> the measurement record a baseline entry is built from."""
    return {
        "robust_accuracy": float(row.get("robust_accuracy", 0.0)),
        "certified_asr_pc": float(row.get("certified_asr_pc", 0.0)),
        "images": int(row.get("images", 0) or 0),
        "job": job_id,
    }


# ------------------------------------------------------------- file I/O

def empty_baseline() -> Dict[str, Any]:
    return {"version": 1, "generation": 0,
            "tolerance_default": DEFAULT_TOLERANCE, "entries": {}}


def load_baseline(path: Optional[pathlib.Path] = None
                  ) -> Optional[Dict[str, Any]]:
    p = pathlib.Path(path) if path is not None else baseline_path()
    try:
        return json.loads(p.read_text(encoding="utf-8"))
    except (OSError, ValueError):
        return None


def dump_baseline(data: Mapping[str, Any]) -> str:
    """Deterministic serialization (sorted keys, normalized floats, one
    trailing newline) — same dump discipline as `analysis/baselines.json`,
    so `update` twice is byte-identical and diffs review cleanly."""
    return _dump(data)


def fold_measurements(data: Optional[Mapping[str, Any]],
                      measured: Mapping[str, Mapping[str, Any]],
                      generation: int) -> Dict[str, Any]:
    """A new baseline with every freshly measured cell's reference values
    set to its measurement (stamped with the generation). Cells the
    generation did not measure keep their old entries untouched — `check`
    (not `update`) is what complains about them."""
    out: Dict[str, Any] = dict(data) if data else empty_baseline()
    entries = dict(out.get("entries", {}))
    for key in sorted(measured):
        m = measured[key]
        prev = dict(entries.get(key, {}))
        prev.update({
            "robust_accuracy": float(m["robust_accuracy"]),
            "certified_asr_pc": float(m["certified_asr_pc"]),
            "images": int(m.get("images", 0)),
            "generation": int(generation),
        })
        entries[key] = prev
    out["entries"] = entries
    out["generation"] = int(generation)
    return out


# --------------------------------------------------------------- checking

def allowed(key: str, rule_id: str,
            allow: Optional[Dict[str, Dict[str, str]]] = None) -> bool:
    for table in (ALLOWLIST, allow or {}):
        for pattern, rules in table.items():
            if fnmatch.fnmatchcase(key, pattern) and rule_id in rules:
                return True
    return False


def tolerance_for(key: str, entry: Mapping[str, Any],
                  data: Mapping[str, Any]) -> float:
    if "tolerance" in entry:
        return float(entry["tolerance"])
    return float(data.get("tolerance_default", DEFAULT_TOLERANCE))


def check_measurements(
        measured: Mapping[str, Mapping[str, Any]],
        holes: Iterable[str],
        data: Optional[Mapping[str, Any]],
        generation: int,
        baseline_file: str = "<baseline>",
        allow: Optional[Dict[str, Dict[str, str]]] = None,
        select: Optional[Sequence[str]] = None) -> List[Finding]:
    """Diff one completed generation's measurements against the baseline:
    DP400 (regression past tolerance), DP401 (cell added/removed), DP402
    (baseline cell with no fresh measurement / unseeded baseline).

    `holes` are cells the generation's grid COVERED but could not measure
    (owning job quarantined or retry-exhausted) — they report as DP402,
    not DP401: the grid did not change shape, the measurement is missing.
    """
    findings: List[Finding] = []

    def add(rule: str, key: str, message: str) -> None:
        findings.append(Finding(path=baseline_file, line=1, col=1,
                                rule_id=rule, message=f"[{key}] {message}"))

    holes = sorted(set(holes))
    if not data or not data.get("entries"):
        add("DP402", "<unseeded>",
            f"baseline has no entries — generation {generation} measured "
            f"{len(measured)} cell(s) but nothing is committed as the "
            "reference; seed it with `python -m dorpatch_tpu.recert "
            "update` and check the file in")
        data = data or empty_baseline()
    entries: Mapping[str, Any] = data.get("entries", {})

    for key in sorted(set(measured) - set(entries)):
        add("DP401", key,
            "cell measured but absent from the baseline — the grid grew; "
            "accept it with `python -m dorpatch_tpu.recert update`")
    for key in sorted(set(entries) - set(measured) - set(holes)):
        add("DP401", key,
            "baseline cell is no longer in the submitted grid — the grid "
            "shrank; accept the removal with `python -m dorpatch_tpu.recert "
            "update --allow-remove`")
    for key in holes:
        if key in entries:
            age = int(generation) - int(entries[key].get("generation", 0))
            add("DP402", key,
                f"no fresh measurement in generation {generation} (owning "
                "farm job quarantined or retry-exhausted; baseline entry is "
                f"{age} generation(s) old) — the cell is a hole, not a pass")
        else:
            add("DP401", key,
                "cell entered the grid but produced no measurement (owning "
                "job failed) and has no baseline entry")

    for key in sorted(set(measured) & set(entries)):
        m, e = measured[key], entries[key]
        tol = tolerance_for(key, e, data)
        ra, ra0 = float(m["robust_accuracy"]), float(e["robust_accuracy"])
        if ra < ra0 - tol:
            add("DP400", key,
                f"robust accuracy regressed {ra0:.2f}% -> {ra:.2f}% "
                f"(drop {ra0 - ra:.2f} > tolerance {tol:.2f} percentage "
                "points) — the attack got stronger or the defense weaker; "
                "investigate before accepting with `recert update`")
            continue
        asr, asr0 = (float(m.get("certified_asr_pc", 0.0)),
                     float(e.get("certified_asr_pc", 0.0)))
        if asr > asr0 + tol:
            add("DP400", key,
                f"certified attack success rose {asr0:.2f}% -> {asr:.2f}% "
                f"(rise {asr - asr0:.2f} > tolerance {tol:.2f} percentage "
                "points) with robust accuracy inside tolerance — the "
                "certificate itself is eroding")

    out = []
    for f in sorted(findings):
        if select is not None and f.rule_id not in select:
            continue
        key = f.message.split("]", 1)[0].lstrip("[")
        if allowed(key, f.rule_id, allow):
            continue
        out.append(f)
    return out


# ---------------------------------------------------------------- verdict

def build_verdict(measured: Mapping[str, Mapping[str, Any]],
                  holes: Iterable[str],
                  data: Optional[Mapping[str, Any]],
                  generation: int,
                  findings: Sequence[Finding],
                  baseline_file: str = "") -> Dict[str, Any]:
    """The machine-readable per-generation verdict the serve boot gate and
    `GET /robustness` consume: per-cell status + margin (percentage points
    above the tolerance floor; negative = failing), worst margin, and the
    findings that produced it."""
    data = data or empty_baseline()
    entries: Mapping[str, Any] = data.get("entries", {})
    holes = set(holes)
    cells: Dict[str, Any] = {}
    failing = {f.message.split("]", 1)[0].lstrip("[")
               for f in findings if f.rule_id == "DP400"}
    worst: Optional[float] = None
    for key in sorted(set(measured) | set(entries) | holes):
        m, e = measured.get(key), entries.get(key)
        cell: Dict[str, Any] = {}
        if m is not None:
            cell["measured"] = float(m["robust_accuracy"])
        if e is not None:
            cell["baseline"] = float(e["robust_accuracy"])
            cell["tolerance"] = tolerance_for(key, e, data)
        if m is not None and e is not None:
            margin = (float(m["robust_accuracy"])
                      - (float(e["robust_accuracy"]) - cell["tolerance"]))
            cell["margin"] = round(margin, 4)
            worst = margin if worst is None else min(worst, margin)
            cell["status"] = "regressed" if key in failing else "ok"
        elif key in holes:
            cell["status"] = "stale"
        elif m is not None:
            cell["status"] = "added"
        else:
            cell["status"] = "removed"
        cells[key] = cell
    by_rule: Dict[str, int] = {}
    for f in findings:
        by_rule[f.rule_id] = by_rule.get(f.rule_id, 0) + 1
    seeded = bool(entries)
    if by_rule.get("DP400") or by_rule.get("DP401"):
        status = "failing"
    elif by_rule.get("DP402") or not seeded:
        status = "stale"
    else:
        status = "ok"
    return {
        "version": 1,
        "generation": int(generation),
        "baseline_file": str(baseline_file),
        "baseline_generation": int(data.get("generation", 0)),
        "seeded": seeded,
        "clean": not findings,
        "status": status,
        "worst_margin": None if worst is None else round(worst, 4),
        "findings_by_rule": dict(sorted(by_rule.items())),
        "findings": [{"rule": f.rule_id,
                      "cell": f.message.split("]", 1)[0].lstrip("["),
                      "message": f.message} for f in findings],
        "cells": cells,
    }
