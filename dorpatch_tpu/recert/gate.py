"""Serve-boot recert gate: refuse to serve silently-uncertified.

The serve worker consults the scheduler's published verdict
(`recert_verdict.json`) at boot, mirroring the AOT strict-boot contract
(`--aot strict` refuses on a cache miss; `--require-recert strict`
refuses on a failing or stale robustness verdict):

- ``off``    — no gate; the snapshot is still loaded for `GET /robustness`
  when a recert dir is configured.
- ``warn``   — boot proceeds on any verdict (including a missing one) and
  the degraded status is carried in the snapshot for `/robustness` and
  the boot log.
- ``strict`` — the pool refuses to reach serving-ready unless the verdict
  exists and its status is ``ok``: a DP400/DP401 finding (``failing``), a
  DP402 hole or unseeded baseline (``stale``), or no verdict at all
  (``absent``) each raise the typed `RecertGateError` instead of serving.

Host-only: reads one JSON file, never touches a jax backend.
"""

from __future__ import annotations

import os
from typing import Any, Dict, Optional

from dorpatch_tpu.checkpoint import load_json

REQUIRE_MODES = ("off", "warn", "strict")


class RecertGateError(RuntimeError):
    """Strict recert gate refused the boot: the robustness verdict is
    failing, stale, or absent — serving would be silently-uncertified."""


def load_verdict(recert_dir: str) -> Optional[Dict[str, Any]]:
    from dorpatch_tpu.recert.scheduler import VERDICT_NAME
    v = load_json(os.path.join(recert_dir, VERDICT_NAME))
    return v if isinstance(v, dict) else None


def snapshot(recert_dir: str, require: str = "off") -> Dict[str, Any]:
    """The robustness snapshot a serve worker carries: gate mode, verdict
    status (``absent`` when none is published), and the per-cell verdict
    body for `GET /robustness`."""
    verdict = load_verdict(recert_dir) if recert_dir else None
    status = verdict.get("status", "failing") if verdict else "absent"
    out: Dict[str, Any] = {
        "require": require,
        "recert_dir": os.path.abspath(recert_dir) if recert_dir else "",
        "status": status,
    }
    if verdict is not None:
        out["generation"] = verdict.get("generation")
        out["worst_margin"] = verdict.get("worst_margin")
        out["findings_by_rule"] = verdict.get("findings_by_rule", {})
        out["cells"] = verdict.get("cells", {})
    return out


def boot_gate(recert_dir: str, require: str = "off"
              ) -> Optional[Dict[str, Any]]:
    """Evaluate the gate at serve boot. Returns the snapshot (None when no
    recert dir is configured and the gate is off); raises `RecertGateError`
    under ``strict`` unless the verdict status is ``ok``."""
    if require not in REQUIRE_MODES:
        raise ValueError(
            f"require_recert must be one of {REQUIRE_MODES}, got {require!r}")
    if not recert_dir and require == "off":
        return None
    if not recert_dir:
        raise RecertGateError(
            f"--require-recert {require} needs a recert dir "
            "(--recert-dir) to read the verdict from")
    snap = snapshot(recert_dir, require)
    if require == "strict" and snap["status"] != "ok":
        failing = [c for c, cell in snap.get("cells", {}).items()
                   if cell.get("status") not in ("ok", "added")]
        raise RecertGateError(
            f"recert verdict is '{snap['status']}' "
            f"(generation {snap.get('generation', '?')}) under "
            f"--require-recert strict — refusing serving-ready; "
            f"cells: {', '.join(failing[:3]) or '(no verdict published)'}"
            + (" ..." if len(failing) > 3 else ""))
    return snap
