"""`python -m dorpatch_tpu.recert` — the re-certification operator surface.

- ``schedule <dir> --spec spec.json``  begin (or resume) a generation:
  commit the inflight record, submit the grid to the generation's farm
  dir, return — external farm workers drain it
  (``python -m dorpatch_tpu.farm work <dir>/gen_NNNN``)
- ``run <dir> [--spec ...]``           one full cycle in-process: begin or
  resume, drain (in-process worker by default, or poll for external
  workers with ``--external-workers``), harvest, check, publish verdict
- ``check <dir>``                      re-check the newest completed
  generation against the current baseline (exit 1 on DP400-DP402)
- ``update <dir> [--allow-remove]``    fold the newest completed
  generation into the baseline file (refuses to drop entries without
  ``--allow-remove``)
- ``status <dir>``                     one JSON line of scheduler state

Findings go to stdout (`--format json` for one JSON object per line),
the human summary to stderr, exit 0/1/2 — the same contract as
`python -m dorpatch_tpu.analysis`.
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import List, Optional, Sequence

from dorpatch_tpu import observe
from dorpatch_tpu.analysis.cli import emit
from dorpatch_tpu.recert.baseline import RECERT_RULE_IDS, RECERT_RULE_ROWS
from dorpatch_tpu.recert.scheduler import RecertError, RecertScheduler


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="python -m dorpatch_tpu.recert",
        description="Continuous re-certification: grid generations on the "
                    "farm, checked against the adversarial regression "
                    "baseline (DP400-DP402)")
    sub = p.add_subparsers(dest="cmd", required=True)

    def common(sp):
        sp.add_argument("recert_dir")
        sp.add_argument("--baseline-file", default="",
                        help="baseline file override (default: the "
                             "package's recert/robustness_baseline.json)")

    ps = sub.add_parser("schedule",
                        help="begin or resume a generation (submit only)")
    common(ps)
    ps.add_argument("--spec", default="",
                    help="JSON file: the farm grid spec {base, axes, sweep, "
                         "max_attempts} (optional when resuming)")

    pr = sub.add_parser("run", help="one full cycle: begin/resume, drain, "
                                    "harvest, check, publish verdict")
    common(pr)
    pr.add_argument("--spec", default="")
    pr.add_argument("--cycles", type=int, default=1,
                    help="run N back-to-back generations (default 1)")
    pr.add_argument("--update-baseline", action="store_true",
                    help="fold each completed generation's measurements "
                         "into the baseline file before checking")
    pr.add_argument("--external-workers", action="store_true",
                    help="do not run an in-process worker; poll until "
                         "external farm workers drain the generation")
    pr.add_argument("--poll-interval", type=float, default=0.5)
    pr.add_argument("--timeout", type=float, default=None,
                    help="give up waiting for drain after this many "
                         "seconds (exit 2)")
    pr.add_argument("--worker-id", default="recert-w0")
    pr.add_argument("--lease-ttl", type=float, default=30.0)
    pr.add_argument("--chaos", default="",
                    help="comma-joined scheduler faults: recert_kill_cycle "
                         "(SIGKILL after submit), recert_torn_state (tear "
                         "recert_state.json after submit)")
    pr.add_argument("--worker-chaos", default="",
                    help="comma-joined farm faults for the in-process "
                         "worker (crash_block, ckpt_raise, ...)")
    pr.add_argument("--crash-mode", choices=["kill", "raise"],
                    default="kill")
    pr.add_argument("--format", choices=("human", "json"), default="human")

    pc = sub.add_parser("check", help="re-check the newest completed "
                                      "generation against the baseline")
    common(pc)
    pc.add_argument("--select", default="",
                    help="comma-separated rule IDs (DP400,DP401,DP402)")
    pc.add_argument("--format", choices=("human", "json"), default="human")

    pu = sub.add_parser("update", help="fold the newest completed "
                                       "generation into the baseline")
    common(pu)
    pu.add_argument("--allow-remove", action="store_true",
                    help="accept dropping baseline entries that are no "
                         "longer in the grid (refused otherwise)")

    pst = sub.add_parser("status", help="scheduler state as one JSON line")
    common(pst)

    p.add_argument("--list-rules", action="store_true",
                   help="print the DP4xx rule table and exit")
    return p


def _scheduler(args, chaos=None) -> RecertScheduler:
    return RecertScheduler(args.recert_dir,
                           baseline_file=args.baseline_file, chaos=chaos)


def _parse_select(raw: str) -> Optional[List[str]]:
    if not raw:
        return None
    select = [s.strip().upper() for s in raw.split(",") if s.strip()]
    bad = set(select) - set(RECERT_RULE_IDS)
    if bad:
        sys.stderr.write(
            f"rule id(s) not in the recert wing: {sorted(bad)} "
            f"(have {', '.join(RECERT_RULE_IDS)})\n")
        return ["<usage-error>"]
    return select


def _load_spec(path: str) -> Optional[dict]:
    if not path:
        return None
    with open(path) as fh:
        return json.load(fh)


def _finish(findings, verdict, generation: int, fmt: str) -> int:
    emit(findings, fmt)
    if findings:
        sys.stderr.write(
            f"{len(findings)} recert finding(s) for generation "
            f"{generation} (status: {verdict['status']}). Accept an "
            "intentional shift with `python -m dorpatch_tpu.recert "
            "update`, or a reasoned recert.ALLOWLIST entry.\n")
        return 1
    sys.stderr.write(
        f"recert check: generation {generation} clean against "
        f"{verdict['baseline_file']} "
        f"({len(verdict.get('cells', {}))} cell(s))\n")
    return 0


def _run_cycles(args) -> int:
    chaos = None
    if args.chaos:
        from dorpatch_tpu.chaos import Chaos, parse_faults

        chaos = Chaos(parse_faults(args.chaos), job_id="recert",
                      state_dir=args.recert_dir, crash_mode=args.crash_mode)
    sched = _scheduler(args, chaos=chaos)
    rc = 0
    for _ in range(max(1, args.cycles)):
        generation, farm_dir = sched.begin_generation(_load_spec(args.spec))
        observe.log(json.dumps({"recert": "begin", "generation": generation,
                                "farm_dir": farm_dir}))
        if args.external_workers:
            if not sched.wait_drained(farm_dir,
                                      poll_interval=args.poll_interval,
                                      timeout=args.timeout):
                sys.stderr.write(
                    f"generation {generation} not drained within "
                    f"{args.timeout}s\n")
                return 2
        else:
            from dorpatch_tpu.farm.worker import FarmWorker  # lazy: models

            FarmWorker(farm_dir, worker_id=args.worker_id,
                       lease_ttl=args.lease_ttl,
                       poll_interval=args.poll_interval,
                       chaos=args.worker_chaos,
                       crash_mode=args.crash_mode).run()
            if not sched.drained(farm_dir):
                sys.stderr.write(
                    f"generation {generation} worker exited but farm not "
                    "drained\n")
                return 2
        verdict = sched.complete_generation(
            generation, farm_dir, update_baseline=args.update_baseline)
        findings_n = len(verdict.get("findings", []))
        observe.log(json.dumps({
            "recert": "complete", "generation": generation,
            "status": verdict["status"],
            "worst_margin": verdict.get("worst_margin"),
            "findings": findings_n}))
        if findings_n:
            rc = 1
    return rc


def main(argv: Optional[Sequence[str]] = None) -> int:
    parser = build_parser()
    argv = list(argv) if argv is not None else sys.argv[1:]
    if "--list-rules" in argv:
        for rid, name, desc in RECERT_RULE_ROWS:
            sys.stdout.write(f"{rid}  {name}: {desc}\n")
        return 0
    args = parser.parse_args(argv)
    try:
        if args.cmd == "schedule":
            sched = _scheduler(args)
            generation, farm_dir = sched.begin_generation(
                _load_spec(args.spec))
            observe.log(json.dumps({
                "recert": "scheduled", "generation": generation,
                "farm_dir": farm_dir, "counts": sched.counts(farm_dir)}))
            return 0
        if args.cmd == "run":
            return _run_cycles(args)
        if args.cmd == "check":
            select = _parse_select(args.select)
            if select == ["<usage-error>"]:
                return 2
            generation, findings, verdict = _scheduler(args).check_latest(
                select=select)
            return _finish(findings, verdict, generation, args.format)
        if args.cmd == "update":
            summary = _scheduler(args).update_from_latest(
                allow_remove=args.allow_remove)
            observe.log(json.dumps(summary))
            sys.stderr.write(
                f"recert update: folded {summary['folded']} cell(s) from "
                f"generation {summary['generation']} -> "
                f"{summary['baseline_file']} "
                f"({summary['entries']} entr(ies)"
                + (f", removed {len(summary['removed'])}"
                   if summary["removed"] else "")
                + ")\n")
            return 0
        # status
        observe.log(json.dumps(_scheduler(args).status(), sort_keys=True))
        return 0
    except RecertError as e:
        sys.stderr.write(f"recert {args.cmd}: {e}\n")
        return 2 if args.cmd in ("schedule", "run", "check", "status") else 1


if __name__ == "__main__":
    raise SystemExit(main())
