"""Continuous re-certification: the standing red team as a subsystem.

The paper's thesis is that certified accuracy is a moving target — a
deployment that certifies once and serves forever is exactly the failure
mode DorPatch documents. This package closes the loop the farm (PR 9) and
the supervised serve pool (PR 11) left open:

- `scheduler`  — crash-resumable generation state machine: expands
  (model x defense x attack) grids into farm jobs, survives SIGKILL
  mid-cycle by resuming the in-flight generation, recovers a torn
  `recert_state.json` from the generation dirs themselves.
- `baseline`   — the adversarial sibling of `analysis/baselines.json`:
  checked-in per-cell robust-accuracy references with absolute
  tolerances, diffed as DP400 (regression), DP401 (grid drift), DP402
  (stale cell / unseeded baseline) through `analysis.engine.Finding`.
- `gate`       — the serve-boot gate (`--require-recert strict|warn|off`):
  strict refuses serving-ready on a failing/stale/absent verdict with a
  typed `RecertGateError`, mirroring AOT strict boot.
- `__main__`   — `python -m dorpatch_tpu.recert
  schedule|run|check|update|status`.

Host-only throughout: the model stack runs inside the farm workers the
scheduler drives, never in the scheduler itself.
"""

from dorpatch_tpu.recert.baseline import (  # noqa: F401 (public surface)
    ALLOWLIST,
    RECERT_RULE_IDS,
    RECERT_RULE_ROWS,
    baseline_path,
    check_measurements,
    load_baseline,
)
from dorpatch_tpu.recert.gate import (  # noqa: F401 (public surface)
    RecertGateError,
    boot_gate,
)
from dorpatch_tpu.recert.scheduler import (  # noqa: F401 (public surface)
    RecertError,
    RecertScheduler,
    is_recert_dir,
)
