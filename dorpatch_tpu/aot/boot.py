"""Warm-boot loader: install serialized executables instead of tracing.

Three pieces:

* `AotDispatcher` — a drop-in replacement for a jitted program, swapped into
  `observe._FirstCallTimer.__wrapped__`. It routes each call by a structural
  signature (pytree shape + per-leaf shape/dtype, static leaves by repr) to a
  pre-loaded executable; unknown signatures fall through to the original jit
  (one `aot.miss` event per signature, the armed recompile watchdog still
  accounts the trace). `_cache_size()` delegates to the fallback jit, so
  `trace_counts()` reads 0 on a fully warm path — the zero-trace proof is
  mechanical, not asserted.

* `warm_boot(programs, aot_cfg)` — eager loader for serve: for every
  (name, timer, abstract_args) triple from `trace_entrypoints()`, trace
  abstractly (no compile), fingerprint via the PR 8 baseline canonicalizer,
  look the record up in the store, and deserialize-and-install. Misses under
  `strict` raise `AotBootError` (deploy mode: boot fails rather than
  compiling); under `auto` they compile-and-rewrite the store entry — a
  fingerprint/topology/corrupt mismatch is never served stale.

* `FirstCallAotResolver` — the lazy, read-only variant for farm workers,
  installed through `observe.set_aot_resolver`: on a timer's first call it
  substitutes a store-backed dispatcher that resolves executables by
  fingerprint on demand and never writes (no multi-worker write races).

Serialization uses `jax.experimental.serialize_executable`; only the payload
bytes are persisted — in/out pytree defs are rebuilt from a fresh abstract
trace at load time, which is exactly the fingerprint check's trace, so a hit
costs one trace-cache-free abstract trace + deserialize.
"""

from __future__ import annotations

import time
import types
from typing import Any, Dict, Iterable, List, Optional, Tuple

from dorpatch_tpu import observe
from dorpatch_tpu.aot.store import ExecutableStore


class AotBootError(RuntimeError):
    """Strict-mode warm boot failed: a program missed the store."""


def call_signature(args: tuple, kwargs: dict) -> Tuple[str, tuple]:
    """Structural dispatch key, identical for abstract example args
    (ShapeDtypeStruct) and the concrete arrays of a live call: pytree
    structure, per-array (shape, dtype), non-array leaves by repr (a static
    value that changes the program changes the key — a different static can
    miss, it can never hit the wrong executable)."""
    import jax

    leaves, treedef = jax.tree_util.tree_flatten((tuple(args), dict(kwargs)))
    sig = []
    for leaf in leaves:
        if hasattr(leaf, "shape") and hasattr(leaf, "dtype"):
            sig.append(
                ("a", tuple(int(d) for d in leaf.shape), str(leaf.dtype))
            )
        else:
            sig.append(("s", repr(leaf)))
    return (str(treedef), tuple(sig))


def _interface_sha(traced) -> str:
    from dorpatch_tpu.analysis import baseline as baseline_mod

    ctx = types.SimpleNamespace(jaxpr=traced.jaxpr, args_info=traced.args_info)
    return baseline_mod.interface_record(ctx)["sha"]


def _static_positions(args: tuple, kwargs: dict, traced) -> Optional[tuple]:
    """Which positional args were declared static (dropped from args_info)?
    Validated structurally: dropping the candidates must reproduce the traced
    args_info pytree exactly, else the program is refused as unsupported
    (never guess which inputs an executable expects)."""
    import jax

    target = jax.tree_util.tree_structure(traced.args_info)
    if jax.tree_util.tree_structure((tuple(args), dict(kwargs))) == target:
        return ()
    cand = tuple(
        i
        for i, a in enumerate(args)
        if jax.tree_util.tree_leaves(a)
        and not any(
            hasattr(l, "shape") and hasattr(l, "dtype")
            for l in jax.tree_util.tree_leaves(a)
        )
    )
    dropped = tuple(a for i, a in enumerate(args) if i not in cand)
    if jax.tree_util.tree_structure((dropped, dict(kwargs))) == target:
        return cand
    return None


def _materialize(store: ExecutableStore, entry: Dict[str, Any],
                 payload: bytes, traced):
    """Turn a store hit into a callable: deserialize the blob, or for
    persistent_cache entries AOT-compile against the store's XLA disk cache
    (a cache hit inside XLA; the jit's own trace cache stays untouched)."""
    if entry.get("method") == "persistent_cache":
        from dorpatch_tpu import utils

        utils.enable_compilation_cache(store.xla_cache_dir)
        return traced.lower().compile()
    import jax
    from jax.experimental.serialize_executable import deserialize_and_load

    in_tree = jax.tree_util.tree_flatten(traced.args_info)[1]
    out_tree = jax.tree_util.tree_structure(traced.out_info)
    return deserialize_and_load(payload, in_tree, out_tree)


def _serialize_payload(compiled) -> Tuple[str, bytes]:
    """(method, payload): serialized bytes when the backend supports
    executable serialization, else the persistent_cache marker."""
    try:
        from jax.experimental.serialize_executable import serialize

        return "serialized", serialize(compiled)[0]
    except Exception:
        return "persistent_cache", b""


class AotDispatcher:
    """Stands in for a jitted program behind a `_FirstCallTimer`.

    Holds {call signature: (executable, static_positions)}. Known signatures
    run the pre-loaded executable (statics dropped, as AOT executables take
    only the dynamic operands); unknown ones optionally try a lazy read-only
    store load (farm path), then fall back to the original jit with a single
    `aot.miss` event per signature. All other attributes — `_cache_size`,
    `trace`, `lower` — delegate to the fallback jit, so watchdog and
    trace-count accounting see straight through to the real trace cache.
    """

    def __init__(self, fallback, name: str,
                 store: Optional[ExecutableStore] = None,
                 stats: Optional[Dict[str, Any]] = None):
        self.fallback = fallback
        self._name = name
        self._store = store
        self._stats = stats
        self._table: Dict[Tuple[str, tuple], Tuple[Any, tuple]] = {}
        self._missed: set = set()

    def install(self, sig, executable, static_pos: tuple) -> None:
        self._table[sig] = (executable, static_pos)

    def installed(self) -> int:
        return len(self._table)

    def __call__(self, *args, **kwargs):
        sig = call_signature(args, kwargs)
        entry = self._table.get(sig)
        if entry is None:
            if sig not in self._missed:
                reason = "signature"
                if self._store is not None:
                    entry, reason = self._lazy_load(sig, args, kwargs)
                if entry is None:
                    self._missed.add(sig)
                    observe.record_event(
                        "aot.miss", program=self._name, reason=reason
                    )
                    if self._stats is not None:
                        self._stats["misses"] = (
                            self._stats.get("misses", 0) + 1
                        )
                        self._stats.setdefault("programs", {}).setdefault(
                            self._name, f"miss:{reason}"
                        )
            if entry is None:
                return self.fallback(*args, **kwargs)
        executable, static_pos = entry
        if static_pos:
            args = tuple(a for i, a in enumerate(args) if i not in static_pos)
        return executable(*args, **kwargs)

    def _lazy_load(self, sig, args, kwargs):
        """Read-only on-demand resolution (farm): trace abstractly, look up
        by fingerprint, install on hit. Returns (table entry | None, miss
        reason); never raises into the caller — the caller records the miss
        exactly once per signature."""
        try:
            from dorpatch_tpu.analysis import baseline as baseline_mod

            t0 = time.perf_counter()
            traced = self.fallback.trace(*args, **kwargs)
            fp = baseline_mod.fingerprint(traced.jaxpr)
            payload, entry, reason = self._store.lookup_by_fingerprint(
                fp, _interface_sha(traced)
            )
            if reason is not None:
                return None, reason
            static_pos = _static_positions(args, kwargs, traced)
            if static_pos is None:
                return None, "unsupported"
            executable = _materialize(self._store, entry, payload, traced)
            self._table[sig] = (executable, static_pos)
            load_s = round(time.perf_counter() - t0, 6)
            if self._stats is not None:
                self._stats["hits"] = self._stats.get("hits", 0) + 1
                self._stats["load_s"] = round(
                    self._stats.get("load_s", 0.0) + load_s, 6
                )
                self._stats.setdefault("programs", {})[self._name] = "hit"
            observe.record_event(
                "aot.load", program=self._name,
                method=entry.get("method", "serialized"), load_s=load_s,
                lazy=True,
            )
            return self._table[sig], None
        except Exception as e:
            return None, f"error:{type(e).__name__}"

    def __getattr__(self, item):
        fallback = self.__dict__.get("fallback")
        if fallback is None:
            raise AttributeError(item)
        return getattr(fallback, item)


def _note_miss(stats: Dict[str, Any], name: str, reason: str,
               mode: str) -> None:
    stats["misses"] += 1
    stats["miss_reasons"][reason] = stats["miss_reasons"].get(reason, 0) + 1
    stats["programs"][name] = f"miss:{reason}"
    observe.record_event("aot.miss", program=name, reason=reason)
    if mode == "strict":
        raise AotBootError(
            f"[{name}] AOT store miss ({reason}) under strict warm boot — "
            "rebuild the store (`python -m dorpatch_tpu.aot build`) or boot "
            "with --aot auto"
        )


def _boot_one(store: ExecutableStore, disp: AotDispatcher, fallback,
              name: str, args: tuple, mode: str, stats: Dict[str, Any],
              clock) -> bool:
    """Load-or-rebuild one program; returns True when the store was
    mutated (auto-mode rewrite)."""
    from dorpatch_tpu.analysis import baseline as baseline_mod

    try:
        traced = fallback.trace(*args)
        fp = baseline_mod.fingerprint(traced.jaxpr)
        iface = _interface_sha(traced)
        static_pos = _static_positions(args, {}, traced)
        sig = call_signature(args, {})
    except AotBootError:
        raise
    except Exception as e:
        _note_miss(stats, name, f"untraceable:{type(e).__name__}", mode)
        return False
    if static_pos is None:
        _note_miss(stats, name, "unsupported", mode)
        return False
    payload, entry, reason = store.lookup(name, fp, iface)
    if reason is None:
        t0 = clock()
        try:
            executable = _materialize(store, entry, payload, traced)
        except Exception:
            reason = "corrupt"
        else:
            load_s = clock() - t0
            disp.install(sig, executable, static_pos)
            saved = max(
                0.0, float(entry.get("build_compile_s", 0.0)) - load_s
            )
            stats["hits"] += 1
            stats["load_s"] = round(stats["load_s"] + load_s, 6)
            stats["saved_s"] = round(stats["saved_s"] + saved, 6)
            stats["programs"][name] = "hit"
            observe.record_event(
                "aot.load", program=name,
                method=entry.get("method", "serialized"),
                load_s=round(load_s, 6), saved_s=round(saved, 3),
            )
            return False
    _note_miss(stats, name, reason, mode)  # raises under strict
    t0 = clock()
    compiled = traced.lower().compile()
    compile_s = clock() - t0
    method, payload_out = _serialize_payload(compiled)
    if method == "persistent_cache":
        from dorpatch_tpu import utils

        utils.enable_compilation_cache(store.xla_cache_dir)
        traced.lower().compile()
    store.put(name, fp, iface, method, payload_out, compile_s)
    disp.install(sig, compiled, static_pos)
    stats["builds"] += 1
    stats["build_s"] = round(stats["build_s"] + compile_s, 6)
    stats["programs"][name] = f"rebuilt:{reason}"
    observe.record_event(
        "aot.build", program=name, reason=reason,
        compile_s=round(compile_s, 6),
    )
    return True


def warm_boot(programs: Iterable[Tuple[str, Any, tuple]], aot_cfg,
              store: Optional[ExecutableStore] = None,
              clock=time.perf_counter) -> Dict[str, Any]:
    """Eagerly install executables for every (name, timer, abstract_args)
    triple (the `trace_entrypoints()` shape). Bucket variants sharing one
    timer share one dispatcher. Returns the boot stats dict and emits the
    `aot.boot` summary event; raises `AotBootError` on any miss when
    aot_cfg.mode == "strict"."""
    from dorpatch_tpu.observe.events import _FirstCallTimer

    mode = getattr(aot_cfg, "mode", "auto")
    if store is None:
        store = ExecutableStore(getattr(aot_cfg, "cache_dir", ""))
    t0_boot = clock()
    stats: Dict[str, Any] = {
        "mode": mode, "store": store.store_dir, "hits": 0, "misses": 0,
        "builds": 0, "load_s": 0.0, "build_s": 0.0, "saved_s": 0.0,
        "miss_reasons": {}, "programs": {},
    }
    groups: Dict[int, Tuple[Any, List[Tuple[str, tuple]]]] = {}
    order: List[int] = []
    for name, fn, args in programs:
        key = id(fn)
        if key not in groups:
            groups[key] = (fn, [])
            order.append(key)
        groups[key][1].append((name, tuple(args)))
    dirty = False
    try:
        for key in order:
            timer, items = groups[key]
            if not isinstance(timer, _FirstCallTimer):
                for name, _ in items:
                    _note_miss(stats, name, "unsupported", mode)
                continue
            fallback = timer.__wrapped__
            if isinstance(fallback, AotDispatcher):
                fallback = fallback.fallback
            if not hasattr(fallback, "trace"):
                for name, _ in items:
                    _note_miss(stats, name, "unsupported", mode)
                continue
            disp = AotDispatcher(fallback, timer._name, store=store)
            for name, args in items:
                dirty |= _boot_one(
                    store, disp, fallback, name, args, mode, stats, clock
                )
            timer.__wrapped__ = disp
    finally:
        if dirty:
            store.save()
    stats["boot_s"] = round(clock() - t0_boot, 3)
    observe.record_event(
        "aot.boot", mode=mode, hits=stats["hits"], misses=stats["misses"],
        builds=stats["builds"], boot_s=stats["boot_s"],
        saved_s=round(stats["saved_s"], 3),
    )
    return stats


class FirstCallAotResolver:
    """`observe.set_aot_resolver` hook for farm workers: on a timer's first
    call, substitute a read-only store-backed dispatcher so a reclaimed
    job's resume does not re-pay compile. Never writes the store and never
    raises into the host — any internal failure means "no substitution"."""

    def __init__(self, store: ExecutableStore):
        self.store = store
        self.stats: Dict[str, Any] = {
            "store": store.store_dir, "hits": 0, "misses": 0,
            "load_s": 0.0, "programs": {},
        }

    def before_first_call(self, name: str, wrapped, args, kwargs):
        try:
            if isinstance(wrapped, AotDispatcher) or not hasattr(
                wrapped, "trace"
            ):
                return None
            return AotDispatcher(
                wrapped, name, store=self.store, stats=self.stats
            )
        except Exception:
            return None


__all__ = [
    "AotBootError",
    "AotDispatcher",
    "FirstCallAotResolver",
    "call_signature",
    "warm_boot",
]
