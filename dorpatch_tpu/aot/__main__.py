"""`python -m dorpatch_tpu.aot` — build|verify|ls|gc for the executable
store. Exit codes: 0 clean, 1 findings/refusal, 2 usage."""

import sys

from dorpatch_tpu.aot.build import main

if __name__ == "__main__":
    sys.exit(main())
