"""Content-addressed on-disk store for AOT-compiled executables.

Layout under ``store_dir``:

    manifest.json          deterministic index (atomic tmp+replace writes)
    blobs/<name>-<sha>.bin serialized executables (sha256-verified on read)
    xla_cache/             XLA persistent compilation cache, used for entries
                           whose backend refused `serialize_executable`
                           (method "persistent_cache")

An entry's key is the PR 8 baseline record — the program's canonical-jaxpr
body fingerprint plus its interface hash — combined with the environment the
store was built under (jax version, backend, device-topology hash). A lookup
misses with a typed reason rather than ever returning bytes that could
install a stale or foreign executable: ``absent``, ``fingerprint``,
``interface``, ``topology``, ``corrupt``.

The manifest is read tolerantly: a torn or garbage file behaves as an empty
store (every lookup misses ``absent``), mirroring `checkpoint.load_json` —
boot falls back to compiling, never crashes on bad persisted state.
"""

from __future__ import annotations

import fnmatch
import hashlib
import os
import re
from typing import Any, Dict, List, Optional, Tuple

from dorpatch_tpu.checkpoint import atomic_write_json, load_json

MANIFEST = "manifest.json"
STORE_VERSION = 1

# env keys that must match for any entry in the store to be servable; a
# mismatch in any of them is reported as the single miss reason "topology"
# (the executable was compiled for a different world, whichever axis moved).
_ENV_KEYS = ("jax", "backend", "topology")


def topology_hash() -> str:
    """16-hex digest over the visible device mesh: platform/device-kind per
    device plus device and process counts. Two hosts with the same digest can
    exchange serialized executables; anything else must recompile."""
    import jax

    devs = sorted(
        (d.platform, str(getattr(d, "device_kind", ""))) for d in jax.devices()
    )
    txt = "|".join(
        [str(jax.device_count()), str(jax.process_count())]
        + [f"{p}:{k}" for p, k in devs]
    )
    return hashlib.sha256(txt.encode()).hexdigest()[:16]


def current_env() -> Dict[str, Any]:
    import jax

    return {
        "jax": jax.__version__,
        "backend": jax.default_backend(),
        "topology": topology_hash(),
        "device_count": jax.device_count(),
        "process_count": jax.process_count(),
    }


def _sha(data: bytes) -> str:
    return hashlib.sha256(data).hexdigest()[:16]


def _slug(name: str) -> str:
    return re.sub(r"[^A-Za-z0-9._-]", "_", name)


class ExecutableStore:
    """Open (or create) an executable store rooted at ``store_dir``.

    ``check_env=True`` compares the manifest's recorded build environment
    against the live one at open; on mismatch every lookup misses with
    reason ``topology`` until the first `put` resets the store to the live
    environment (a rewrite under a new topology starts from a clean slate —
    entries built for the old mesh are unservable here by definition).
    """

    def __init__(self, store_dir: str, check_env: bool = True):
        self.store_dir = os.path.abspath(store_dir)
        self.blob_dir = os.path.join(self.store_dir, "blobs")
        self.xla_cache_dir = os.path.join(self.store_dir, "xla_cache")
        self.manifest_path = os.path.join(self.store_dir, MANIFEST)
        raw = load_json(self.manifest_path, default=None)
        if not isinstance(raw, dict) or not isinstance(
            raw.get("entries"), dict
        ):
            raw = {"version": STORE_VERSION, "env": None, "entries": {}}
        self.manifest: Dict[str, Any] = raw
        self.env_reason: Optional[str] = None
        if check_env and self.manifest.get("entries"):
            env = self.manifest.get("env") or {}
            live = current_env()
            if any(env.get(k) != live.get(k) for k in _ENV_KEYS):
                self.env_reason = "topology"

    # ------------------------------------------------------------- entries

    def entries(self) -> Dict[str, Dict[str, Any]]:
        return self.manifest.get("entries", {})

    def _blob_path(self, entry: Dict[str, Any]) -> str:
        return os.path.join(self.store_dir, entry.get("payload", ""))

    def _read_payload(
        self, entry: Dict[str, Any]
    ) -> Tuple[Optional[bytes], Optional[str]]:
        if entry.get("method") == "persistent_cache":
            # no blob: the executable lives in xla_cache/, re-materialized by
            # an AOT compile with the persistent cache enabled (still zero
            # traces on the jit's own cache).
            return b"", None
        try:
            with open(self._blob_path(entry), "rb") as fh:
                payload = fh.read()
        except OSError:
            return None, "corrupt"
        if _sha(payload) != entry.get("payload_sha"):
            return None, "corrupt"
        return payload, None

    def lookup(
        self, name: str, fingerprint: str, interface_sha: str = ""
    ) -> Tuple[Optional[bytes], Optional[Dict[str, Any]], Optional[str]]:
        """Return (payload, entry, miss_reason); miss_reason None on hit."""
        if self.env_reason:
            return None, None, self.env_reason
        entry = self.entries().get(name)
        if entry is None:
            return None, None, "absent"
        if entry.get("fingerprint") != fingerprint:
            return None, entry, "fingerprint"
        if (
            interface_sha
            and entry.get("interface_sha")
            and entry.get("interface_sha") != interface_sha
        ):
            return None, entry, "interface"
        payload, reason = self._read_payload(entry)
        if reason is not None:
            return None, entry, reason
        return payload, entry, None

    def lookup_by_fingerprint(
        self, fingerprint: str, interface_sha: str = ""
    ) -> Tuple[Optional[bytes], Optional[Dict[str, Any]], Optional[str]]:
        """Content-addressed lookup ignoring the entry name — used by the
        farm's lazy first-call resolver, where the live timer name need not
        match the entrypoint-registry name the store was built under."""
        if self.env_reason:
            return None, None, self.env_reason
        for name in sorted(self.entries()):
            entry = self.entries()[name]
            if entry.get("fingerprint") != fingerprint:
                continue
            if (
                interface_sha
                and entry.get("interface_sha")
                and entry.get("interface_sha") != interface_sha
            ):
                continue
            payload, reason = self._read_payload(entry)
            if reason is None:
                return payload, entry, None
        return None, None, "absent"

    def put(
        self,
        name: str,
        fingerprint: str,
        interface_sha: str,
        method: str,
        payload: bytes,
        build_compile_s: float,
    ) -> Dict[str, Any]:
        """Write/overwrite one entry (blob immediately, manifest on save())."""
        if self.env_reason:
            # rebuilding under a different environment: the old entries are
            # unservable here, so start clean under the live env.
            self.manifest = {
                "version": STORE_VERSION,
                "env": None,
                "entries": {},
            }
            self.env_reason = None
        os.makedirs(self.blob_dir, exist_ok=True)
        entry: Dict[str, Any] = {
            "fingerprint": fingerprint,
            "interface_sha": interface_sha,
            "method": method,
            "payload": "",
            "payload_sha": "",
            "payload_bytes": 0,
            "build_compile_s": round(float(build_compile_s), 6),
        }
        if method != "persistent_cache":
            rel = os.path.join(
                "blobs", f"{_slug(name)}-{_sha(name.encode())[:10]}.bin"
            )
            path = os.path.join(self.store_dir, rel)
            tmp = f"{path}.{os.getpid()}.tmp"
            with open(tmp, "wb") as fh:
                fh.write(payload)
            os.replace(tmp, path)
            entry.update(
                payload=rel,
                payload_sha=_sha(payload),
                payload_bytes=len(payload),
            )
        self.manifest.setdefault("entries", {})[name] = entry
        return entry

    def remove(self, name: str) -> bool:
        entry = self.entries().pop(name, None)
        if entry is None:
            return False
        if entry.get("payload"):
            try:
                os.remove(self._blob_path(entry))
            except OSError:
                pass
        return True

    def save(self) -> None:
        """Atomically persist the manifest, stamping the live environment."""
        os.makedirs(self.store_dir, exist_ok=True)
        if self.manifest.get("env") is None:
            self.manifest["env"] = current_env()
        self.manifest["version"] = STORE_VERSION
        self.manifest["entries"] = {
            k: self.manifest["entries"][k]
            for k in sorted(self.manifest.get("entries", {}))
        }
        atomic_write_json(self.manifest_path, self.manifest)

    # ------------------------------------------------------------ lifecycle

    def state_hash(self) -> str:
        """16-hex digest over (name, fingerprint, payload_sha) triples — the
        store identity BENCH boot rows stamp next to the program_set hash."""
        lines = [
            f"{n}:{e.get('fingerprint')}:{e.get('payload_sha')}"
            for n, e in sorted(self.entries().items())
        ]
        return hashlib.sha256("\n".join(lines).encode()).hexdigest()[:16]

    def gc(self, baseline_entries: Dict[str, Any]) -> List[str]:
        """Drop entries whose name or fingerprint has left baselines.json,
        plus orphaned blobs; returns the removed entry names."""
        removed = []
        for name in sorted(self.entries()):
            entry = self.entries()[name]
            base = baseline_entries.get(name)
            if base is None or base.get("fingerprint") != entry.get(
                "fingerprint"
            ):
                self.remove(name)
                removed.append(name)
        live_blobs = {
            e.get("payload")
            for e in self.entries().values()
            if e.get("payload")
        }
        if os.path.isdir(self.blob_dir):
            for fn in os.listdir(self.blob_dir):
                rel = os.path.join("blobs", fn)
                if rel not in live_blobs and fn.endswith(".bin"):
                    try:
                        os.remove(os.path.join(self.blob_dir, fn))
                    except OSError:
                        pass
        return removed

    def stamp_baseline(self, fingerprint_set: str, path) -> None:
        self.manifest["baseline"] = {
            "fingerprint_set": fingerprint_set,
            "file": str(path),  # may arrive as a pathlib.Path
        }

    # -------------------------------------------------------------- verify

    def verify_against(
        self,
        baseline_data: Dict[str, Any],
        allow: Optional[Dict[str, Dict[str, str]]] = None,
        check_env: bool = True,
    ) -> List[Any]:
        """DP305 findings wherever the store disagrees with baselines.json:
        stale entries (fingerprint/interface drift or no longer baselined),
        missing entries, corrupt blobs, and a build-env/topology mismatch
        against the live process. Suppression goes through the same
        allowlist channel as the other DP3xx rules (`# noqa` has no source
        line to live on inside manifest.json, so the allowlist is the
        sanctioned override — see README)."""
        from dorpatch_tpu.analysis import baseline as baseline_mod
        from dorpatch_tpu.analysis.engine import Finding

        findings: List[Any] = []

        def add(name: str, msg: str) -> None:
            if baseline_mod.allowed(name, "DP305", allow):
                return
            findings.append(
                Finding(
                    path=self.manifest_path,
                    line=1,
                    col=0,
                    rule_id="DP305",
                    message=f"[{name}] {msg}",
                )
            )

        base_entries = baseline_data.get("entries", {})
        if check_env and self.entries():
            env = self.manifest.get("env") or {}
            live = current_env()
            bad = [k for k in _ENV_KEYS if env.get(k) != live.get(k)]
            if bad:
                add(
                    "<store>",
                    "store built under a different environment ("
                    + ", ".join(
                        f"{k}: {env.get(k)!r} != {live.get(k)!r}" for k in bad
                    )
                    + ") — its executables cannot serve here; rebuild with "
                    "`python -m dorpatch_tpu.aot build`",
                )
        for name in sorted(self.entries()):
            entry = self.entries()[name]
            base = base_entries.get(name)
            if base is None:
                add(
                    name,
                    "store entry is no longer in baselines.json — stale; "
                    "run `python -m dorpatch_tpu.aot gc`",
                )
                continue
            if base.get("fingerprint") != entry.get("fingerprint"):
                add(
                    name,
                    "store fingerprint "
                    f"{entry.get('fingerprint')} != baseline "
                    f"{base.get('fingerprint')} — stale executable; rebuild",
                )
            base_iface = (base.get("interface") or {}).get("sha")
            if (
                base_iface
                and entry.get("interface_sha")
                and base_iface != entry.get("interface_sha")
            ):
                add(
                    name,
                    "store interface hash disagrees with baseline "
                    "(DP304-style drift) — rebuild",
                )
            _, reason = self._read_payload(entry)
            if reason is not None:
                add(name, f"executable payload unreadable ({reason}) — rebuild")
        for name in sorted(base_entries):
            if name not in self.entries():
                add(
                    name,
                    "baselined program has no store entry — warm boot will "
                    "miss; run `python -m dorpatch_tpu.aot build`",
                )
        return findings

    def select(self, globs) -> List[str]:
        names = sorted(self.entries())
        if not globs:
            return names
        return [
            n for n in names if any(fnmatch.fnmatch(n, g) for g in globs)
        ]


def open_readonly(store_dir: str) -> Optional[ExecutableStore]:
    """Best-effort open for boot paths that must never fail: returns None on
    any error (missing dir, unreadable manifest handled inside; this guards
    the truly unexpected)."""
    try:
        return ExecutableStore(store_dir)
    except Exception:
        return None


def find_manifest(store_dir: str) -> str:
    return os.path.join(os.path.abspath(store_dir), MANIFEST)


__all__ = [
    "ExecutableStore",
    "current_env",
    "topology_hash",
    "open_readonly",
    "find_manifest",
    "MANIFEST",
]
