"""AOT executable store: zero-trace warm boot for serve and farm.

Deploy-time `python -m dorpatch_tpu.aot build` compiles and serializes every
production program keyed by its PR 8 baseline fingerprint + interface hash
and the build environment (jax version, backend, device-topology hash);
boot-time `warm_boot` / `FirstCallAotResolver` deserialize-and-install
instead of tracing. See README "AOT executable store".
"""

from dorpatch_tpu.aot.boot import (  # noqa: F401
    AotBootError,
    AotDispatcher,
    FirstCallAotResolver,
    call_signature,
    warm_boot,
)
from dorpatch_tpu.aot.store import (  # noqa: F401
    ExecutableStore,
    current_env,
    open_readonly,
    topology_hash,
)

__all__ = [
    "AotBootError",
    "AotDispatcher",
    "ExecutableStore",
    "FirstCallAotResolver",
    "call_signature",
    "current_env",
    "open_readonly",
    "topology_hash",
    "warm_boot",
]
