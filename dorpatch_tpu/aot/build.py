"""Build/verify/ls/gc logic behind `python -m dorpatch_tpu.aot`.

`build` is the deploy-time half of the warm-boot story: enumerate
`production_entrypoints()`, gate on the PR 8 baseline check (a store built
from a drifted tree must not exist — the refusal happens before any compile),
then AOT-compile and serialize every program into the store. Entries whose
backend refuses executable serialization fall back to the XLA persistent
compilation cache under the store (method "persistent_cache", recorded per
entry, so boot knows to re-lower against the disk cache instead of
deserializing).

Exit codes mirror `dorpatch_tpu.analysis`: 0 clean, 1 findings/refusal,
2 usage error.
"""

from __future__ import annotations

import fnmatch
import sys
import time
from typing import List, Optional

from dorpatch_tpu.aot.store import ExecutableStore


def _err(msg: str) -> None:
    sys.stderr.write(msg + "\n")


def _load_baseline(baseline_file: str):
    from dorpatch_tpu.analysis import baseline as baseline_mod

    path = baseline_file or baseline_mod.baseline_path()
    data = baseline_mod.load_baseline(path)
    if data is None:
        _err(
            f"aot: cannot read baseline file {path!r} — run "
            "`python -m dorpatch_tpu.analysis --baseline update` first"
        )
    return path, data


def build_store(store_dir: str, baseline_file: str = "",
                only: Optional[List[str]] = None, fmt: str = "human",
                entrypoints_spec: str = "") -> int:
    """Compile+serialize every production program into ``store_dir``."""
    from dorpatch_tpu.analysis import baseline as baseline_mod
    from dorpatch_tpu.analysis.cli import _load_entrypoints, emit

    path, data = _load_baseline(baseline_file)
    if data is None:
        return 2
    loaded = _load_entrypoints(entrypoints_spec)
    if loaded is None:
        return 2
    eps, budgets, ladders, _uncovered = loaded
    # Refusal gate: the live tree must match the baseline before a single
    # executable is written (estimate-cost mode — fingerprints and
    # interfaces are exact there; compiled cost deltas are not the build
    # gate's business).
    findings = baseline_mod.check_entrypoints(
        eps, data, budgets, ladders, compiled=False
    )
    if findings:
        emit(findings, fmt)
        _err(
            f"aot build REFUSED: --baseline check failed with "
            f"{len(findings)} finding(s) against {path}; nothing written. "
            "Fix the drift or regenerate the baseline, then rebuild."
        )
        return 1

    store = ExecutableStore(store_dir, check_env=False)
    store.manifest = {"version": 1, "env": None, "entries": {}}
    store.env_reason = None
    built, skipped = 0, []
    for ep in eps:
        if only and not any(fnmatch.fnmatch(ep.name, g) for g in only):
            continue
        fn = ep.fn
        if not hasattr(fn, "trace"):
            skipped.append(ep.name)
            continue
        traced = fn.trace(*ep.args, **ep.kwargs)
        fp = baseline_mod.fingerprint(traced.jaxpr)
        from dorpatch_tpu.aot.boot import (
            _interface_sha,
            _serialize_payload,
        )

        iface = _interface_sha(traced)
        t0 = time.perf_counter()
        compiled = traced.lower().compile()
        compile_s = time.perf_counter() - t0
        method, payload = _serialize_payload(compiled)
        if method == "persistent_cache":
            from dorpatch_tpu import utils

            utils.enable_compilation_cache(store.xla_cache_dir)
            traced.lower().compile()
        store.put(ep.name, fp, iface, method, payload, compile_s)
        built += 1
    store.stamp_baseline(
        baseline_mod.fingerprint_set_hash(data.get("entries", {})), path
    )
    store.save()
    _err(
        f"aot build: {built} executable(s) -> {store.store_dir} "
        f"(state {store.state_hash()})"
        + (f"; skipped non-jit: {', '.join(skipped)}" if skipped else "")
    )
    return 0


def verify_store(store_dir: str, baseline_file: str = "",
                 fmt: str = "human") -> int:
    path, data = _load_baseline(baseline_file)
    if data is None:
        return 2
    from dorpatch_tpu.analysis.cli import emit

    store = ExecutableStore(store_dir, check_env=False)
    findings = store.verify_against(data)
    if findings:
        emit(findings, fmt)
        _err(f"aot verify: {len(findings)} DP305 finding(s) against {path}")
        return 1
    _err(
        f"aot verify: OK — {len(store.entries())} entr(ies) consistent "
        f"with {path} (state {store.state_hash()})"
    )
    return 0


def ls_store(store_dir: str, as_json: bool = False) -> int:
    import json

    store = ExecutableStore(store_dir, check_env=False)
    if as_json:
        sys.stdout.write(
            json.dumps(
                {
                    "store": store.store_dir,
                    "state": store.state_hash(),
                    "env": store.manifest.get("env"),
                    "entries": store.entries(),
                },
                indent=2,
                sort_keys=True,
            )
            + "\n"
        )
        return 0
    env = store.manifest.get("env") or {}
    sys.stdout.write(
        f"store {store.store_dir}  state {store.state_hash()}  "
        f"env jax={env.get('jax')} backend={env.get('backend')} "
        f"topology={env.get('topology')}\n"
    )
    for name, e in sorted(store.entries().items()):
        sys.stdout.write(
            f"  {name}  fp={e.get('fingerprint')}  "
            f"iface={e.get('interface_sha')}  method={e.get('method')}  "
            f"{e.get('payload_bytes', 0)}B  "
            f"compile={e.get('build_compile_s', 0.0):.3f}s\n"
        )
    return 0


def gc_store(store_dir: str, baseline_file: str = "") -> int:
    path, data = _load_baseline(baseline_file)
    if data is None:
        return 2
    store = ExecutableStore(store_dir, check_env=False)
    removed = store.gc(data.get("entries", {}))
    store.save()
    _err(
        f"aot gc: removed {len(removed)} entr(ies) whose fingerprint left "
        f"{path}" + (f": {', '.join(removed)}" if removed else "")
    )
    return 0


def main(argv: Optional[List[str]] = None) -> int:
    import argparse

    parser = argparse.ArgumentParser(
        prog="python -m dorpatch_tpu.aot",
        description=(
            "AOT executable store: build at deploy time, warm-boot at "
            "serve time (see README 'AOT executable store')."
        ),
    )
    sub = parser.add_subparsers(dest="cmd", required=True)

    def common(p):
        p.add_argument("--store", required=True,
                       help="store directory (manifest.json + blobs/)")

    pb = sub.add_parser("build", help="compile+serialize every production "
                        "program (refuses on baseline drift)")
    common(pb)
    pb.add_argument("--baseline-file", default="",
                    help="override analysis/baselines.json")
    pb.add_argument("--only", action="append", default=[],
                    help="fnmatch glob over entry names (repeatable)")
    pb.add_argument("--entrypoints", default="",
                    help="module:callable override for the registry loader")
    pb.add_argument("--format", choices=("human", "json"), default="human")

    pv = sub.add_parser("verify", help="DP305 drift check: store manifest "
                        "vs analysis/baselines.json")
    common(pv)
    pv.add_argument("--baseline-file", default="")
    pv.add_argument("--format", choices=("human", "json"), default="human")

    pl = sub.add_parser("ls", help="list store entries")
    common(pl)
    pl.add_argument("--json", action="store_true")

    pg = sub.add_parser("gc", help="drop entries whose fingerprint left "
                        "baselines.json")
    common(pg)
    pg.add_argument("--baseline-file", default="")

    args = parser.parse_args(argv)
    if args.cmd == "build":
        return build_store(args.store, args.baseline_file, args.only,
                           args.format, args.entrypoints)
    if args.cmd == "verify":
        return verify_store(args.store, args.baseline_file, args.format)
    if args.cmd == "ls":
        return ls_store(args.store, args.json)
    if args.cmd == "gc":
        return gc_store(args.store, args.baseline_file)
    return 2


__all__ = ["build_store", "verify_store", "ls_store", "gc_store", "main"]
