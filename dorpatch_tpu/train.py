"""Victim training: give the flagship protocol a victim that actually
classifies.

The reference consumes *pretrained* victims (PatchCleanser-release
checkpoints, `/root/reference/utils.py:47-63`); it has no training code. This
environment ships neither those checkpoints nor any dataset, so round 3's
flagship ran against a randomly-initialized victim — mechanically complete
but scientifically weak (round-3 verdict #5). This module closes that gap
the only way possible offline: train `CifarResNet18` on the procedural
labeled task (`data.procedural_arrays`) to real held-out accuracy, export
the weights as a torch-style checkpoint under the reference's naming
contract, and let the standard pipeline load it through the existing
converter path (`models/registry.get_model` -> `convert_cifar_resnet18`).

TPU-first design: one jitted `train_step` (fwd+bwd+adamw update) with the
augmentation (pad-4 random crop + horizontal flip) inside the jit as pure
`jax.random` ops on static shapes; the epoch loop is a host loop over
device-resident uint8 data (casts per batch). Runs unchanged on CPU or a
single TPU chip.

Usage:
  python -m dorpatch_tpu.train --out pretrained_models/ --epochs 12
"""

from __future__ import annotations

import argparse
import contextlib
import dataclasses
import os
import sys
import time
from typing import Optional, Tuple

import numpy as np

from dorpatch_tpu import observe


@dataclasses.dataclass(frozen=True)
class TrainConfig:
    dataset: str = "cifar10"
    # trainable victim families: "cifar_resnet18" (conv) and "cifar_vit"
    # (transformer) — both 32px, both exported under the reference's
    # checkpoint naming so `models/registry.get_model` loads them
    arch: str = "cifar_resnet18"
    img_size: int = 32
    n_per_class_train: int = 1500
    n_per_class_test: int = 200
    batch_size: int = 128
    epochs: int = 12
    lr: float = 3e-3
    weight_decay: float = 1e-4
    warmup_steps: int = 100
    label_smoothing: float = 0.1
    seed: int = 0
    # "procedural" (offline generated task) or "disk" (real CIFAR batches,
    # the reference's train=True path) — see data.training_arrays
    data_source: str = "procedural"
    data_dir: str = "data/"


def _augment(key, imgs):
    """Pad-4 random crop + horizontal flip, batched, static shapes."""
    import jax
    import jax.numpy as jnp

    n, h, w, c = imgs.shape
    kc, kf = jax.random.split(key)
    padded = jnp.pad(imgs, ((0, 0), (4, 4), (4, 4), (0, 0)), mode="reflect")
    off = jax.random.randint(kc, (n, 2), 0, 9)

    def crop(img, o):
        return jax.lax.dynamic_slice(img, (o[0], o[1], 0), (h, w, c))

    imgs = jax.vmap(crop)(padded, off)
    flip = jax.random.bernoulli(kf, 0.5, (n, 1, 1, 1))
    return jnp.where(flip, imgs[:, :, ::-1, :], imgs)


def _trainable_arch(arch: str) -> str:
    """Canonical registry name of a trainable victim family. Only the small
    32px victims are trainable offline (the 224px families would need real
    data at a scale the procedural task cannot provide)."""
    from dorpatch_tpu.models import registry

    name = registry.resolve_arch(arch)
    if name not in ("cifar_resnet18", "cifar_vit"):
        raise ValueError(
            f"arch {arch!r} is not trainable offline; use cifar_resnet18 "
            "or cifar_vit (the 224px timm families need real datasets)")
    return name


def _make_optimizer(cfg: TrainConfig, total_steps: int):
    import optax

    sched = optax.warmup_cosine_decay_schedule(
        0.0, cfg.lr, cfg.warmup_steps, max(total_steps, cfg.warmup_steps + 1))
    return optax.adamw(sched, weight_decay=cfg.weight_decay)


def make_train_programs(cfg: TrainConfig, model, tx, n_classes: int):
    """The three jitted training entry points — init / train step / eval
    step — wrapped per the DP105 telemetry contract. One builder shared by
    the real training loop and the program auditor's enumeration
    (`analysis/entrypoints.py`), so the audited programs cannot drift from
    the ones production compiles.

    Budgets: the train batch shape never changes (1 bucket); eval runs
    full 500-image chunks plus at most one remainder chunk (2 buckets)."""
    import jax
    import jax.numpy as jnp
    import optax

    init = observe.timed_first_call(
        jax.jit(model.init), "train.init", recompile_budget=1)

    # model normalization contract: victims see [0,1] images shifted by the
    # pipeline's (x-0.5)/0.5 (registry.get_model) — train in the same frame
    def loss_fn(params, key, x01, y):
        x = _augment(key, x01)
        logits = model.apply(params, (x - 0.5) / 0.5)
        labels = optax.smooth_labels(
            jax.nn.one_hot(y, n_classes), cfg.label_smoothing)
        loss = optax.softmax_cross_entropy(logits, labels).mean()
        return loss, (logits.argmax(-1) == y).mean()

    @jax.jit
    def train_step(params, opt_state, key, x_u8, y):
        x01 = x_u8.astype(jnp.float32) / 255.0
        (loss, acc), grads = jax.value_and_grad(loss_fn, has_aux=True)(
            params, key, x01, y)
        updates, opt_state = tx.update(grads, opt_state, params)
        return optax.apply_updates(params, updates), opt_state, loss, acc

    train_step = observe.timed_first_call(
        train_step, "train.step", recompile_budget=1)

    @jax.jit
    def eval_step(params, x_u8, y):
        logits = model.apply(
            params, (x_u8.astype(jnp.float32) / 255.0 - 0.5) / 0.5)
        return (logits.argmax(-1) == y).sum()

    eval_step = observe.timed_first_call(
        eval_step, "train.eval_step", recompile_budget=2)
    return init, train_step, eval_step


def trace_entrypoints(cfg: Optional[TrainConfig] = None, n_classes: int = 10):
    """(program, abstract example args) for every training entry point —
    the auditor's enumeration hook. Everything is `jax.eval_shape`-derived:
    no data is loaded, no parameter is materialized, no step executes."""
    import jax
    import jax.numpy as jnp

    from dorpatch_tpu.models import registry

    cfg = cfg or TrainConfig()
    model = registry.build_bare_model(_trainable_arch(cfg.arch), n_classes)
    tx = _make_optimizer(cfg, total_steps=100)
    init, train_step, eval_step = make_train_programs(cfg, model, tx,
                                                      n_classes)
    key = jax.ShapeDtypeStruct((2,), jnp.uint32)
    dummy = jax.ShapeDtypeStruct((1, cfg.img_size, cfg.img_size, 3),
                                 jnp.float32)
    params = jax.eval_shape(model.init, key, dummy)
    opt_state = jax.eval_shape(tx.init, params)
    x_u8 = jax.ShapeDtypeStruct(
        (cfg.batch_size, cfg.img_size, cfg.img_size, 3), jnp.uint8)
    y = jax.ShapeDtypeStruct((cfg.batch_size,), jnp.int32)
    x_eval = jax.ShapeDtypeStruct((500, cfg.img_size, cfg.img_size, 3),
                                  jnp.uint8)
    y_eval = jax.ShapeDtypeStruct((500,), jnp.int32)
    return [(init, (key, dummy)),
            (train_step, (params, opt_state, key, x_u8, y)),
            (eval_step, (params, x_eval, y_eval))]


def train_victim(cfg: TrainConfig = TrainConfig(), log=observe.log,
                 telemetry_dir: Optional[str] = None) -> Tuple[dict, dict]:
    """Train the cfg.arch victim (cifar_resnet18 or cifar_vit) on the
    procedural task; returns (params, report).

    report: {"test_acc", "train_acc", "steps", "seconds", "backend"}.
    `telemetry_dir` (optional) gets the same run-telemetry contract as an
    experiment results dir — run.json, events.jsonl spans per epoch, a
    heartbeat file — readable by `python -m dorpatch_tpu.observe.report`.
    """
    arch_name = _trainable_arch(cfg.arch)  # fail fast, before any telemetry

    telemetry = contextlib.ExitStack()
    if telemetry_dir:
        run_id = observe.new_run_id()
        observe.write_run_manifest(
            telemetry_dir, cfg, run_id=run_id,
            extra=observe.jax_environment())
        elog = telemetry.enter_context(observe.EventLog(
            os.path.join(telemetry_dir, observe.events_filename()),
            run_id=run_id))
        telemetry.enter_context(observe.active(elog))
        telemetry.enter_context(observe.Heartbeat(
            os.path.join(telemetry_dir, observe.heartbeat_filename()),
            get_phase=elog.current_path, run_id=run_id))
        telemetry.enter_context(observe.span("run"))
    # `with` (not happy-path close): exceptions must unwind the heartbeat
    # daemon and the process-global active EventLog, or a later run in the
    # same process writes spans into this stale telemetry dir. A genuine
    # hang never unwinds anyway, so the unclosed-span post-mortem signature
    # (observe/events.py docstring) is preserved.
    with telemetry:
        return _train_victim_impl(cfg, arch_name, log)


def _train_victim_impl(cfg: TrainConfig, arch_name: str,
                       log) -> Tuple[dict, dict]:
    import jax
    import jax.numpy as jnp

    from dorpatch_tpu import data as data_lib
    from dorpatch_tpu import utils

    from dorpatch_tpu.models import registry

    utils.enable_compilation_cache()

    tr_x, tr_y = data_lib.training_arrays(
        cfg.dataset, cfg.data_source, cfg.data_dir,
        n_per_class=cfg.n_per_class_train, img_size=cfg.img_size,
        seed=1234, split="train")
    te_x, te_y = data_lib.training_arrays(
        cfg.dataset, cfg.data_source, cfg.data_dir,
        n_per_class=cfg.n_per_class_test, img_size=cfg.img_size,
        seed=1234, split="test")
    n_classes = int(tr_y.max()) + 1

    model = registry.build_bare_model(arch_name, n_classes)
    key = jax.random.PRNGKey(cfg.seed)

    steps_per_epoch = len(tr_x) // cfg.batch_size
    if steps_per_epoch == 0:
        # reachable via --data-source disk with a partial download (one
        # small data_batch_* file): fail with the cause, not an empty-stack
        # error deep in the epoch loop
        raise ValueError(
            f"{len(tr_x)} training images < batch_size {cfg.batch_size}: "
            "not enough data for one step (partial dataset?)")
    total_steps = steps_per_epoch * cfg.epochs
    tx = _make_optimizer(cfg, total_steps)
    init, train_step, eval_step = make_train_programs(cfg, model, tx,
                                                      n_classes)
    params = init(key, jnp.zeros((1, cfg.img_size, cfg.img_size, 3)))
    opt_state = tx.init(params)

    # uint8 on device: 4x less HBM/L2 traffic than f32, cast inside the jit
    dev_tr_x = jax.device_put((tr_x * 255).astype(np.uint8))
    dev_tr_y = jax.device_put(tr_y)
    dev_te_x = jax.device_put((te_x * 255).astype(np.uint8))
    dev_te_y = jax.device_put(te_y)

    def test_acc(params) -> float:
        hits = 0
        for i in range(0, len(te_x), 500):
            hits += int(eval_step(params, dev_te_x[i:i + 500],
                                  dev_te_y[i:i + 500]))
        return hits / len(te_x)

    rng = np.random.default_rng(cfg.seed)
    t0 = time.perf_counter()
    step = 0
    train_acc = 0.0
    for epoch in range(cfg.epochs):
        with observe.span("train.epoch", epoch=epoch + 1) as sp:
            order = rng.permutation(len(tr_x))
            accs = []
            for i in range(steps_per_epoch):
                sel = jnp.asarray(
                    order[i * cfg.batch_size:(i + 1) * cfg.batch_size])
                key, sub = jax.random.split(key)
                params, opt_state, loss, acc = train_step(
                    params, opt_state, sub, dev_tr_x[sel], dev_tr_y[sel])
                accs.append(acc)
                step += 1
            train_acc = float(jnp.mean(jnp.stack(accs)))
            sp["train_acc"] = round(train_acc, 4)
        log(f"epoch {epoch + 1}/{cfg.epochs}: train_acc={train_acc:.3f} "
            f"({time.perf_counter() - t0:.0f}s)")
    with observe.span("train.eval"):
        acc = test_acc(params)
    report = {
        "test_acc": acc,
        "train_acc": train_acc,
        "steps": step,
        "seconds": round(time.perf_counter() - t0, 1),
        "backend": jax.default_backend(),
        "n_train": len(tr_x),
        "n_test": len(te_x),
    }
    log(f"done: held-out acc={acc:.3f} ({report['seconds']}s on "
        f"{report['backend']})")
    return params, report


def save_victim_checkpoint(params, out_dir: str, dataset: str = "cifar10",
                           arch: str = "cifar_resnet18") -> str:
    """Export trained flax params as a torch `.pth` under the reference's
    checkpoint naming contract, loadable by `models/registry.get_model`."""
    from dorpatch_tpu.models import registry
    from dorpatch_tpu.models.convert import export_cifar_resnet18, export_vit

    name = _trainable_arch(arch)  # clear error for non-trainable families
    exporter = {"cifar_resnet18": export_cifar_resnet18,
                "cifar_vit": export_vit}[name]
    path = registry.checkpoint_path(out_dir, dataset, name)
    os.makedirs(os.path.dirname(path), exist_ok=True)
    import torch

    sd = {k: torch.as_tensor(v) for k, v in exporter(params).items()}
    torch.save({"state_dict": sd}, path)
    return path


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--out", default="pretrained_models/",
                   help="model dir to export the checkpoint into")
    p.add_argument("--dataset", default="cifar10")
    p.add_argument("--arch", default="cifar_resnet18",
                   choices=("cifar_resnet18", "resnet18", "cifar_vit"),
                   help="victim family to train (32px offline victims)")
    p.add_argument("--epochs", type=int, default=12)
    p.add_argument("--batch-size", type=int, default=128)
    p.add_argument("--n-per-class", type=int, default=1500)
    p.add_argument("--lr", type=float, default=3e-3)
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--data-source", default="procedural",
                   choices=("procedural", "disk"),
                   help="disk = real CIFAR train batches under --data-dir")
    p.add_argument("--data-dir", default="data/")
    p.add_argument("--telemetry-dir", default="",
                   help="write run telemetry (run.json, events.jsonl, "
                        "heartbeat) here; readable by "
                        "`python -m dorpatch_tpu.observe.report`")
    args = p.parse_args(argv)

    cfg = TrainConfig(dataset=args.dataset, arch=args.arch, epochs=args.epochs,
                      batch_size=args.batch_size, lr=args.lr, seed=args.seed,
                      n_per_class_train=args.n_per_class,
                      data_source=args.data_source, data_dir=args.data_dir)
    params, report = train_victim(cfg, telemetry_dir=args.telemetry_dir)
    path = save_victim_checkpoint(params, args.out, args.dataset, args.arch)
    observe.log(f"saved {path}; report={report}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
