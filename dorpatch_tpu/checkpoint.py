"""Mid-optimization checkpointing of the attack carry (crash recovery).

The reference's only resume granularity is whole artifacts: a crash loses an
entire 5000-iteration stage (`/root/reference/main.py:101-118`,
`attack.py:134-141` — artifact files exist only *after* a stage completes;
SURVEY.md §5). Here the jitted optimizer's full carry — the `TrainState`
pytree holding the iterates, best-so-far checkpoints, failure set, adaptive
coefficients, lr/patience schedules and PRNG key — is snapshotted with orbax
at sweep-block boundaries, together with the stage id, iteration count, and
the stage-0 artifacts stage 1 depends on. Restoring reproduces the exact
on-device state, so a killed run continues from the last block instead of
the last stage.

Orbax is the TPU-native choice: async-capable, atomic renames, works with
sharded jax.Arrays on meshes, and the restore takes a concrete template so
arrays come back with the template's sharding/placement.
"""

from __future__ import annotations

import json
import os
from typing import Any, NamedTuple, Optional

import jax


def atomic_write_json(path: str, obj: Any) -> None:
    """Crash-safe JSON write: same-directory `*.tmp` + `os.replace`.

    A reader never observes a half-written file — a crash mid-write leaves
    either the previous content or the new one (plus at worst a stray tmp
    file). The tmp name carries the pid so concurrent writers on the same
    path never clobber each other's in-flight tmp; the `os.replace` itself
    is the single atomic commit point. This is the persistence primitive
    for every mutable host-side JSON record that must survive SIGKILL
    (`farm/queue.py`'s job/lease state, the sweep's resume bookkeeping)."""
    path = os.path.abspath(path)
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w") as fh:
        json.dump(obj, fh, indent=1, sort_keys=True, default=float)
        fh.flush()
        os.fsync(fh.fileno())
    os.replace(tmp, path)


def load_json(path: str, default: Any = None) -> Any:
    """Read a JSON file, returning `default` when it is missing, unreadable,
    or truncated/corrupt — the tolerant counterpart of `atomic_write_json`
    (crash-recovery readers must classify bad state, never die on it)."""
    try:
        with open(path) as fh:
            return json.load(fh)
    except (OSError, ValueError):
        return default


class CarryCheckpoint(NamedTuple):
    """A restored mid-stage snapshot."""

    stage: int
    iteration: int
    state: Any                    # TrainState pytree
    stage0_mask: Optional[jax.Array]     # None while still in stage 0
    stage0_pattern: Optional[jax.Array]


class CarryCheckpointer:
    """Orbax CheckpointManager wrapper for the attack carry.

    One instance per attack invocation (e.g. per experiment batch). `save`
    keeps the newest `max_to_keep` snapshots; `restore` returns the latest
    or None. `clear` removes all snapshots (call after a successful
    generate so stale carries never leak into the next run).

    `fingerprint` (any JSON-serializable dict — seed, batch id, attack
    config hash, ...) is stored in the snapshot meta. On construction,
    snapshots whose stored fingerprint differs from this instance's are
    *deleted* (with a warning): a stale run's carry can never be restored by
    this run, and orbax silently refuses saves at steps below the latest
    existing one — a leftover stage-1 snapshot would otherwise both shadow
    restores and block every new save. A re-run with e.g. a different seed
    therefore regenerates instead of silently restoring a carry trained on
    different images/targets.
    """

    def __init__(self, directory: str, max_to_keep: int = 2,
                 fingerprint: Optional[dict] = None):
        import orbax.checkpoint as ocp

        self._ocp = ocp
        self.fingerprint = fingerprint
        self.directory = os.path.abspath(directory)
        self._mgr = ocp.CheckpointManager(
            self.directory,
            options=ocp.CheckpointManagerOptions(
                max_to_keep=max_to_keep,
                enable_async_checkpointing=False,  # blocks are seconds apart
            ),
        )
        if fingerprint is not None:
            for step in list(self._mgr.all_steps()):
                meta = self._read_meta(step)
                if meta is None or meta.get("fingerprint") != fingerprint:
                    import warnings

                    got = None if meta is None else meta.get("fingerprint")
                    warnings.warn(
                        f"carry snapshot {step} in {self.directory} has "
                        f"fingerprint {got!r} != this "
                        f"run's {fingerprint!r}; deleting it (it could "
                        "shadow restores and block saves)")
                    self._delete_quietly(step)

    def _read_meta(self, step: int) -> Optional[dict]:
        """The snapshot's meta record, or None when the snapshot is
        truncated/corrupt (a crash mid-write must not kill the reader)."""
        ocp = self._ocp
        try:
            return self._mgr.restore(
                step, args=ocp.args.Composite(meta=ocp.args.JsonRestore())
            )["meta"]
        except Exception:
            return None

    def _delete_quietly(self, step: int) -> None:
        """Remove a snapshot we have decided is unusable. Deletion (not mere
        skipping) matters: orbax refuses saves at steps at or below the
        latest existing one, so a corrupt high-step snapshot would otherwise
        block every post-resume save."""
        try:
            self._mgr.delete(step)
        except Exception:
            pass  # already half-gone; restore() skips it either way

    def save(self, stage: int, iteration: int, state: Any,
             stage0_mask=None, stage0_pattern=None) -> None:
        """Snapshot the carry at a block boundary. The write is atomic at
        the snapshot granularity: orbax stages the step into a tmp directory
        and commits it with a single rename, so a crash mid-save leaves
        either no step-dir or a complete one — and `restore` deletes any
        half-committed leftovers and falls back to the previous snapshot."""
        ocp = self._ocp
        step = int(iteration)
        payload = {"state": state}
        if stage0_mask is not None:
            payload["stage0"] = {"mask": stage0_mask, "pattern": stage0_pattern}
        meta = {"stage": int(stage), "iteration": step}
        if self.fingerprint is not None:
            meta["fingerprint"] = self.fingerprint
        self._mgr.save(
            stage * 10_000_000 + step,
            args=ocp.args.Composite(
                carry=ocp.args.StandardSave(payload),
                meta=ocp.args.JsonSave(meta),
            ),
        )

    def restore(self, state_template: Any, stage0_template=None
                ) -> Optional[CarryCheckpoint]:
        """Newest *readable* snapshot whose fingerprint matches this run's,
        arrays placed like the (concrete) templates.

        Two classes of snapshot are passed over, falling back to the next
        older one instead of crashing mid-resume:

        - fingerprint mismatch (skipped with a warning) — a stale run's
          high-step snapshot in the same directory must not shadow this
          run's own valid ones;
        - truncated/corrupt payload (a crash or ENOSPC mid-save) — the
          snapshot is deleted (it would block future saves at lower steps)
          and the previous good one is restored instead."""
        ocp = self._ocp
        import warnings

        for step in sorted(self._mgr.all_steps(), reverse=True):
            meta = self._read_meta(step)
            if meta is None:
                warnings.warn(
                    f"carry snapshot {step} in {self.directory} is "
                    "truncated/corrupt (unreadable meta); deleting it and "
                    "falling back to the previous snapshot")
                self._delete_quietly(step)
                continue
            if (self.fingerprint is not None
                    and meta.get("fingerprint") != self.fingerprint):
                warnings.warn(
                    f"carry snapshot {step} in {self.directory} has "
                    f"fingerprint {meta.get('fingerprint')!r} != this run's "
                    f"{self.fingerprint!r}; skipping it")
                continue
            payload_t = {"state": state_template}
            if meta["stage"] == 1:
                if stage0_template is None:
                    raise ValueError(
                        "stage-1 checkpoint needs a stage-0 template")
                payload_t["stage0"] = {
                    "mask": stage0_template[0], "pattern": stage0_template[1]}
            try:
                restored = self._mgr.restore(
                    step, args=ocp.args.Composite(
                        carry=ocp.args.StandardRestore(payload_t))
                )["carry"]
            except Exception as e:
                warnings.warn(
                    f"carry snapshot {step} in {self.directory} failed to "
                    f"restore ({type(e).__name__}: {e}); deleting it and "
                    "falling back to the previous snapshot")
                self._delete_quietly(step)
                continue
            s0 = restored.get("stage0")
            return CarryCheckpoint(
                stage=int(meta["stage"]),
                iteration=int(meta["iteration"]),
                state=restored["state"],
                stage0_mask=None if s0 is None else s0["mask"],
                stage0_pattern=None if s0 is None else s0["pattern"],
            )
        return None

    def latest_step_info(self) -> Optional[CarryCheckpoint]:
        """Meta of the newest matching snapshot as a `CarryCheckpoint` with
        `state`/arrays set to None — a host-only peek (no array restore) for
        resume accounting ("did this attempt start from a checkpoint, and
        how far along?"). Returns None when nothing restorable exists."""
        for step in sorted(self._mgr.all_steps(), reverse=True):
            meta = self._read_meta(step)
            if meta is None:
                continue
            if (self.fingerprint is not None
                    and meta.get("fingerprint") != self.fingerprint):
                continue
            return CarryCheckpoint(
                stage=int(meta["stage"]), iteration=int(meta["iteration"]),
                state=None, stage0_mask=None, stage0_pattern=None)
        return None

    def clear(self) -> None:
        for step in list(self._mgr.all_steps()):
            self._mgr.delete(step)

    def close(self) -> None:
        self._mgr.wait_until_finished()
        self._mgr.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
