"""Mid-optimization checkpointing of the attack carry (crash recovery).

The reference's only resume granularity is whole artifacts: a crash loses an
entire 5000-iteration stage (`/root/reference/main.py:101-118`,
`attack.py:134-141` — artifact files exist only *after* a stage completes;
SURVEY.md §5). Here the jitted optimizer's full carry — the `TrainState`
pytree holding the iterates, best-so-far checkpoints, failure set, adaptive
coefficients, lr/patience schedules and PRNG key — is snapshotted with orbax
at sweep-block boundaries, together with the stage id, iteration count, and
the stage-0 artifacts stage 1 depends on. Restoring reproduces the exact
on-device state, so a killed run continues from the last block instead of
the last stage.

Orbax is the TPU-native choice: async-capable, atomic renames, works with
sharded jax.Arrays on meshes, and the restore takes a concrete template so
arrays come back with the template's sharding/placement.
"""

from __future__ import annotations

import os
from typing import Any, NamedTuple, Optional

import jax


class CarryCheckpoint(NamedTuple):
    """A restored mid-stage snapshot."""

    stage: int
    iteration: int
    state: Any                    # TrainState pytree
    stage0_mask: Optional[jax.Array]     # None while still in stage 0
    stage0_pattern: Optional[jax.Array]


class CarryCheckpointer:
    """Orbax CheckpointManager wrapper for the attack carry.

    One instance per attack invocation (e.g. per experiment batch). `save`
    keeps the newest `max_to_keep` snapshots; `restore` returns the latest
    or None. `clear` removes all snapshots (call after a successful
    generate so stale carries never leak into the next run).

    `fingerprint` (any JSON-serializable dict — seed, batch id, attack
    config hash, ...) is stored in the snapshot meta. On construction,
    snapshots whose stored fingerprint differs from this instance's are
    *deleted* (with a warning): a stale run's carry can never be restored by
    this run, and orbax silently refuses saves at steps below the latest
    existing one — a leftover stage-1 snapshot would otherwise both shadow
    restores and block every new save. A re-run with e.g. a different seed
    therefore regenerates instead of silently restoring a carry trained on
    different images/targets.
    """

    def __init__(self, directory: str, max_to_keep: int = 2,
                 fingerprint: Optional[dict] = None):
        import orbax.checkpoint as ocp

        self._ocp = ocp
        self.fingerprint = fingerprint
        self.directory = os.path.abspath(directory)
        self._mgr = ocp.CheckpointManager(
            self.directory,
            options=ocp.CheckpointManagerOptions(
                max_to_keep=max_to_keep,
                enable_async_checkpointing=False,  # blocks are seconds apart
            ),
        )
        if fingerprint is not None:
            for step in list(self._mgr.all_steps()):
                meta = self._mgr.restore(
                    step, args=ocp.args.Composite(meta=ocp.args.JsonRestore())
                )["meta"]
                if meta.get("fingerprint") != fingerprint:
                    import warnings

                    warnings.warn(
                        f"carry snapshot {step} in {self.directory} has "
                        f"fingerprint {meta.get('fingerprint')!r} != this "
                        f"run's {fingerprint!r}; deleting it (it could "
                        "shadow restores and block saves)")
                    self._mgr.delete(step)

    def save(self, stage: int, iteration: int, state: Any,
             stage0_mask=None, stage0_pattern=None) -> None:
        ocp = self._ocp
        step = int(iteration)
        payload = {"state": state}
        if stage0_mask is not None:
            payload["stage0"] = {"mask": stage0_mask, "pattern": stage0_pattern}
        meta = {"stage": int(stage), "iteration": step}
        if self.fingerprint is not None:
            meta["fingerprint"] = self.fingerprint
        self._mgr.save(
            stage * 10_000_000 + step,
            args=ocp.args.Composite(
                carry=ocp.args.StandardSave(payload),
                meta=ocp.args.JsonSave(meta),
            ),
        )

    def restore(self, state_template: Any, stage0_template=None
                ) -> Optional[CarryCheckpoint]:
        """Newest snapshot whose fingerprint matches this run's, arrays
        placed like the (concrete) templates.

        Mismatching snapshots are skipped (with a warning), not merely
        rejected at the latest step: a stale run's high-step snapshot in the
        same directory must not shadow this run's own valid ones."""
        ocp = self._ocp
        steps = sorted(self._mgr.all_steps(), reverse=True)
        meta = latest = None
        for step in steps:
            m = self._mgr.restore(
                step, args=ocp.args.Composite(meta=ocp.args.JsonRestore())
            )["meta"]
            if self.fingerprint is not None and m.get("fingerprint") != self.fingerprint:
                import warnings

                warnings.warn(
                    f"carry snapshot {step} in {self.directory} has "
                    f"fingerprint {m.get('fingerprint')!r} != this run's "
                    f"{self.fingerprint!r}; skipping it")
                continue
            meta, latest = m, step
            break
        if latest is None:
            return None
        payload_t = {"state": state_template}
        if meta["stage"] == 1:
            if stage0_template is None:
                raise ValueError("stage-1 checkpoint needs a stage-0 template")
            payload_t["stage0"] = {
                "mask": stage0_template[0], "pattern": stage0_template[1]}
        restored = self._mgr.restore(
            latest, args=ocp.args.Composite(carry=ocp.args.StandardRestore(payload_t))
        )["carry"]
        s0 = restored.get("stage0")
        return CarryCheckpoint(
            stage=int(meta["stage"]),
            iteration=int(meta["iteration"]),
            state=restored["state"],
            stage0_mask=None if s0 is None else s0["mask"],
            stage0_pattern=None if s0 is None else s0["pattern"],
        )

    def clear(self) -> None:
        for step in list(self._mgr.all_steps()):
            self._mgr.delete(step)

    def close(self) -> None:
        self._mgr.wait_until_finished()
        self._mgr.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
