"""Deterministic fault injection: every recovery path, provable.

Grown out of the farm's chaos harness (PR 9) and now shared with the serve
replica pool: both subsystems prove their failover stories against the same
exactly-once marker protocol. Each fault is seeded from the *job id*, fires
*exactly once* per (job, fault) — the fired-marker is an O_EXCL file in the
state directory created BEFORE the fault is injected, so not even a SIGKILL
fault can fire twice across process restarts — and is injected at a
declared site.

Farm faults (wired by `farm/worker.py`):

- ``crash_block``     — die at a seeded attack-block boundary. Injected
  inside the `on_block_end` callback, which `DorPatch._run_stage` invokes
  AFTER the carry snapshot for that block is saved: the job provably has a
  checkpoint to resume from. ``crash_mode="kill"`` SIGKILLs the process
  (the real thing — no cleanup, no finally blocks); ``"raise"`` raises
  `SimulatedPreemption` for in-process tests.
- ``ckpt_raise``      — a checkpointer proxy whose `save` raises
  ENOSPC at a seeded save ordinal (transient IO failure -> retry path).
- ``wedge_heartbeat`` — stop the worker's heartbeat thread without the
  exit beat (`Heartbeat.wedge`): the process stays alive but its leases go
  stale, exercising reclaim-from-a-live-zombie.
- ``enospc_events``   — the event log's file handle starts raising ENOSPC
  after a seeded number of writes; `EventLog._write` must degrade to its
  tracking-only sink and the job must still complete (telemetry loss is
  never fatal).

Serve faults (wired by `serve/pool.py` at the replica batch boundary,
`on_serve_batch` — called with a batch in flight, outside the per-batch
exception guard):

- ``wedge_dispatch``   — the target replica's worker thread blocks forever
  mid-batch (requests assigned, none resolved): the supervisor must detect
  the stale heartbeat, re-dispatch the in-flight requests, and quarantine.
- ``raise_in_worker``  — an exception escapes the worker loop entirely and
  the thread dies, exercising the dead-thread classification + restart.
- ``wedge_heartbeat``  — (shared name) the replica keeps serving but its
  heartbeat stops: staleness-based detection must fire even though the
  thread is alive.
- ``kill_backend``     — SIGKILL the whole serve *process* mid-batch (the
  fleet-gateway smoke's backend-loss story). Fires at a seeded batch
  ordinal; before dying it invokes the bound ``metrics_flush`` callback so
  the victim's committed counters reach metrics.json — the in-flight batch
  is counted NOWHERE (the gateway retries it on a survivor), keeping the
  fleet books exact. Always SIGKILLs regardless of ``crash_mode``: a raise
  would be caught by the pool's failover and never leave the process.

Serve faults always target replica 0 — the smoke's assertions need a known
victim, and determinism beats configurability here.

Gateway faults (wired by `gateway/` at its probe and deploy sites):

- ``wedge_probe``     — the membership prober's next probes of a seeded
  backend fail artificially (no socket touched), exercising the
  consecutive-failure ejection path; the count is sized to cross the
  ejection threshold, after which real probes resume and re-admission
  hysteresis takes over.
- ``poison_canary``   — the rolling deploy's robustness evaluation of the
  canary is replaced ONCE with a failing DP400 verdict, proving the
  automatic rollback + typed `gateway.rollback` event without needing a
  genuinely-regressed model.

Recert faults (wired by `recert/scheduler.py` at the cycle's one
crash-interesting boundary, `on_recert` — called right after the
generation's grid is submitted, with the inflight record already
committed to `recert_state.json`):

- ``recert_kill_cycle`` — SIGKILL the scheduler mid-generation: jobs
  submitted, nothing harvested. Resume must pick up the SAME in-flight
  generation (never resubmit a new one) and complete it with a baseline
  byte-identical to an uninterrupted run.
- ``recert_torn_state`` — truncate `recert_state.json` mid-byte (a torn
  write): the scheduler must recover the generation counter and the
  in-flight record from the generation dirs' completion markers.

The harness holds no global state: construct one `Chaos` per job attempt
(or per serve run), `bind` the worker's heartbeat, and wire the sites.
"""

from __future__ import annotations

import errno
import hashlib
import os
import signal
import threading
from typing import IO, Optional, Sequence

FARM_FAULTS = ("crash_block", "ckpt_raise", "wedge_heartbeat",
               "enospc_events")
SERVE_FAULTS = ("wedge_dispatch", "raise_in_worker", "wedge_heartbeat",
                "kill_backend")
RECERT_FAULTS = ("recert_kill_cycle", "recert_torn_state")
GATEWAY_FAULTS = ("wedge_probe", "poison_canary")
FAULTS = (FARM_FAULTS + ("wedge_dispatch", "raise_in_worker", "kill_backend")
          + RECERT_FAULTS + GATEWAY_FAULTS)

# The replica every serve fault is aimed at (see module docstring).
SERVE_TARGET_REPLICA = 0


class SimulatedPreemption(RuntimeError):
    """A chaos-injected crash in ``crash_mode="raise"`` — classified by the
    worker as a transient preemption, same as the SIGKILL it stands in for."""


def fault_seed(job_id: str, fault: str) -> int:
    """Deterministic 32-bit seed per (job, fault): the same job always
    crashes at the same block / fails the same save, so recovery tests are
    exactly reproducible."""
    digest = hashlib.sha256(f"{job_id}:{fault}".encode()).digest()
    return int.from_bytes(digest[:4], "big")


def parse_faults(spec: str) -> Sequence[str]:
    """Comma-joined fault list (the `--chaos` flag); unknown names are a
    configuration error, not a silent no-op."""
    faults = tuple(f.strip() for f in spec.split(",") if f.strip())
    unknown = [f for f in faults if f not in FAULTS]
    if unknown:
        raise ValueError(
            f"unknown chaos fault(s) {unknown}; known: {list(FAULTS)}")
    return faults


class Chaos:
    def __init__(self, faults: Sequence[str], job_id: str, state_dir: str,
                 crash_mode: str = "kill"):
        unknown = [f for f in faults if f not in FAULTS]
        if unknown:
            raise ValueError(
                f"unknown chaos fault(s) {unknown}; known: {list(FAULTS)}")
        if crash_mode not in ("kill", "raise"):
            raise ValueError(f"crash_mode must be kill|raise, got {crash_mode!r}")
        self.faults = tuple(faults)
        self.job_id = job_id
        self.state_dir = os.path.abspath(state_dir)
        self.crash_mode = crash_mode
        self._block_counter = 0
        self._serve_batch_counter = 0
        self._probe_counter = {}
        self._heartbeat = None
        self._metrics_flush = None

    def bind(self, heartbeat=None, metrics_flush=None) -> "Chaos":
        """Wire process-level collaborators: `heartbeat` for the wedge
        faults, `metrics_flush` (a zero-arg callable dumping the serve
        metric registry to metrics.json) for `kill_backend`'s
        flush-before-SIGKILL contract."""
        if heartbeat is not None:
            self._heartbeat = heartbeat
        if metrics_flush is not None:
            self._metrics_flush = metrics_flush
        return self

    # ---------------- fired-marker bookkeeping ----------------

    def marker_path(self, fault: str) -> str:
        return os.path.join(self.state_dir, f"chaos_{fault}.fired")

    def fired(self, fault: str) -> bool:
        return os.path.exists(self.marker_path(fault))

    def fire_once(self, fault: str) -> bool:
        """True exactly once per (job, fault) across all attempts and
        processes: the marker is committed via O_EXCL *before* the fault is
        injected, so a recovery attempt sees the marker even when the fault
        was a SIGKILL."""
        if fault not in self.faults:
            return False
        os.makedirs(self.state_dir, exist_ok=True)
        try:
            fd = os.open(self.marker_path(fault),
                         os.O_CREAT | os.O_EXCL | os.O_WRONLY, 0o644)
        except FileExistsError:
            return False
        os.close(fd)
        return True

    # ---------------- farm injection sites ----------------

    def crash_block_ordinal(self) -> int:
        """Which block boundary (0 or 1, counted per attempt) the crash
        targets — always early enough to land mid-job on the tiny CI grids."""
        return fault_seed(self.job_id, "crash_block") % 2

    def events_write_budget(self) -> int:
        """How many event writes succeed before the injected ENOSPC."""
        return 1 + fault_seed(self.job_id, "enospc_events") % 5

    def ckpt_raise_ordinal(self) -> int:
        """Which checkpoint save (per attempt) raises."""
        return fault_seed(self.job_id, "ckpt_raise") % 2

    def on_block(self, stage: int, iteration: int,
                 info: Optional[dict] = None) -> None:
        """Block-boundary site — wire into the job's `on_block_end` chain.
        Runs after the block's checkpoint save, before lease renewal."""
        n = self._block_counter
        self._block_counter += 1
        if (self._heartbeat is not None
                and self.fire_once("wedge_heartbeat")):
            self._heartbeat.wedge()
        if ("crash_block" in self.faults
                and n >= self.crash_block_ordinal()
                and self.fire_once("crash_block")):
            if self.crash_mode == "kill":
                os.kill(os.getpid(), signal.SIGKILL)
            raise SimulatedPreemption(
                f"chaos: simulated preemption at block {n} "
                f"(stage {stage}, iteration {iteration})")

    def wrap_checkpointer(self, checkpointer):
        if "ckpt_raise" not in self.faults or self.fired("ckpt_raise"):
            return checkpointer
        return _CheckpointRaiseProxy(checkpointer, self)

    def wrap_event_log(self, event_log) -> None:
        """Swap the EventLog's live file handle for one that starts raising
        ENOSPC after a seeded number of writes. Reaches into `_fh`
        deliberately: the fault must hit the exact handle `_write`'s
        OSError-degradation path guards, not a lookalike."""
        if "enospc_events" not in self.faults or event_log._fh is None:
            return
        if not self.fire_once("enospc_events"):
            return
        event_log._fh = _ENOSPCFile(event_log._fh,
                                    self.events_write_budget())

    # ---------------- recert injection site ----------------

    def on_recert(self, phase: str, state_path: str = "") -> None:
        """Scheduler cycle-boundary site — called by
        `RecertScheduler.begin_generation` right after `submit_spec`, with
        the inflight record already committed. The one place a crash is
        interesting: earlier there is nothing to resume, later the farm's
        own crash story covers it."""
        if phase != "submitted":
            return
        if ("recert_torn_state" in self.faults and state_path
                and self.fire_once("recert_torn_state")):
            # Tear the state file the way a crashed non-atomic writer
            # would: keep the first half of the bytes, drop the rest.
            try:
                with open(state_path, "rb") as fh:
                    raw = fh.read()
                with open(state_path, "wb") as fh:
                    fh.write(raw[:max(1, len(raw) // 2)])
            except OSError:
                pass
        if ("recert_kill_cycle" in self.faults
                and self.fire_once("recert_kill_cycle")):
            if self.crash_mode == "kill":
                os.kill(os.getpid(), signal.SIGKILL)
            raise SimulatedPreemption(
                "chaos: simulated scheduler preemption mid-generation "
                "(grid submitted, nothing harvested)")

    # ---------------- serve injection site ----------------

    def on_serve_batch(self, replica_id: int, heartbeat=None) -> None:
        """Replica batch-boundary site — the pool's worker loop calls this
        with a batch in flight (requests assigned to the replica, none
        resolved yet) and OUTSIDE its per-batch exception guard, so
        `raise_in_worker` escapes the loop and kills the thread. Only
        replica `SERVE_TARGET_REPLICA` is ever hit; each fault fires on the
        first in-flight batch that replica picks up (deterministic, and
        warmup never routes through the worker loop)."""
        if replica_id != SERVE_TARGET_REPLICA:
            return
        n = self._serve_batch_counter
        self._serve_batch_counter += 1
        if ("kill_backend" in self.faults
                and n >= self.kill_backend_ordinal()
                and self.fire_once("kill_backend")):
            # Flush the committed counters first — this batch is still
            # uncounted (the site runs before resolution), so the books on
            # disk are exact and the gateway's retry keeps them exact.
            if self._metrics_flush is not None:
                try:
                    self._metrics_flush()
                except Exception:
                    pass
            # Always SIGKILL: crash_mode="raise" would be swallowed by the
            # pool's failover and the process would keep serving.
            os.kill(os.getpid(), signal.SIGKILL)
        if (heartbeat is not None and "wedge_heartbeat" in self.faults
                and self.fire_once("wedge_heartbeat")):
            heartbeat.wedge()
        if ("raise_in_worker" in self.faults
                and self.fire_once("raise_in_worker")):
            raise SimulatedPreemption(
                f"chaos: injected worker exception (replica {replica_id})")
        if ("wedge_dispatch" in self.faults
                and self.fire_once("wedge_dispatch")):
            # Freeze forever mid-batch: the daemon thread is abandoned and
            # the supervisor's staleness detection takes over.
            threading.Event().wait()

    def kill_backend_ordinal(self) -> int:
        """Which in-flight batch (counted per process) the SIGKILL targets
        — never the first, so the backend provably answered something
        before dying (the smoke asserts per-backend attribution)."""
        return 1 + fault_seed(self.job_id, "kill_backend") % 2

    # ---------------- gateway injection sites ----------------

    def wedge_probe_failures(self) -> int:
        """How many consecutive probes of the target backend fail
        artificially — sized past any sane ejection threshold so the
        wedge provably ejects, then real probes resume."""
        return 4 + fault_seed(self.job_id, "wedge_probe") % 3

    def on_gateway_probe(self, backend_index: int, backend_name: str) -> bool:
        """Membership-prober site — called once per probe cycle per
        backend BEFORE any socket is touched. Returns True when the probe
        must be treated as failed. Targets backend index 0 (the smoke's
        known victim, mirroring SERVE_TARGET_REPLICA); the first call
        commits the fired-marker, then the next `wedge_probe_failures()`
        probes of that backend fail."""
        if "wedge_probe" not in self.faults or backend_index != 0:
            return False
        if self.fire_once("wedge_probe"):
            self._probe_counter[backend_name] = self.wedge_probe_failures()
        left = self._probe_counter.get(backend_name, 0)
        if left <= 0:
            return False
        self._probe_counter[backend_name] = left - 1
        return True

    def poison_canary(self, verdict: dict) -> dict:
        """Deploy-evaluation site — called by the rolling deploy with the
        canary's real robustness verdict. Fires once, replacing it with a
        failing DP400 (robustness regression) verdict; every later
        evaluation passes the real verdict through."""
        if ("poison_canary" in self.faults
                and self.fire_once("poison_canary")):
            return {"status": "failed",
                    "findings_by_rule": {"DP400": [
                        "chaos: injected robustness regression "
                        "(certified-accuracy margin below baseline)"]},
                    "worst_margin": -1.0,
                    "poisoned": True}
        return verdict


class _CheckpointRaiseProxy:
    """`save` raises ENOSPC exactly once at the seeded ordinal; every other
    attribute (restore, latest_step_info, clear, close) delegates."""

    def __init__(self, checkpointer, chaos: Chaos):
        self._checkpointer = checkpointer
        self._chaos = chaos
        self._saves = 0

    def save(self, *args, **kwargs):
        n = self._saves
        self._saves += 1
        if (n >= self._chaos.ckpt_raise_ordinal()
                and self._chaos.fire_once("ckpt_raise")):
            raise OSError(errno.ENOSPC,
                          "chaos: injected checkpoint-write failure")
        return self._checkpointer.save(*args, **kwargs)

    def __getattr__(self, item):
        return getattr(self._checkpointer, item)


class _ENOSPCFile:
    """File-object shim whose `write` raises ENOSPC once its budget runs
    out — the mid-run disk-full signature `EventLog._write` degrades on."""

    def __init__(self, fh: IO[str], budget: int):
        self._fh = fh
        self._budget = int(budget)

    def write(self, data: str):
        if self._budget <= 0:
            raise OSError(errno.ENOSPC, "chaos: injected event-write failure")
        self._budget -= 1
        return self._fh.write(data)

    def close(self) -> None:
        self._fh.close()

    def __getattr__(self, item):
        return getattr(self._fh, item)
