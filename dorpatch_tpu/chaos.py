"""Deterministic fault injection: every recovery path, provable.

Grown out of the farm's chaos harness (PR 9) and now shared with the serve
replica pool: both subsystems prove their failover stories against the same
exactly-once marker protocol. Each fault is seeded from the *job id*, fires
*exactly once* per (job, fault) — the fired-marker is an O_EXCL file in the
state directory created BEFORE the fault is injected, so not even a SIGKILL
fault can fire twice across process restarts — and is injected at a
declared site.

Farm faults (wired by `farm/worker.py`):

- ``crash_block``     — die at a seeded attack-block boundary. Injected
  inside the `on_block_end` callback, which `DorPatch._run_stage` invokes
  AFTER the carry snapshot for that block is saved: the job provably has a
  checkpoint to resume from. ``crash_mode="kill"`` SIGKILLs the process
  (the real thing — no cleanup, no finally blocks); ``"raise"`` raises
  `SimulatedPreemption` for in-process tests.
- ``ckpt_raise``      — a checkpointer proxy whose `save` raises
  ENOSPC at a seeded save ordinal (transient IO failure -> retry path).
- ``wedge_heartbeat`` — stop the worker's heartbeat thread without the
  exit beat (`Heartbeat.wedge`): the process stays alive but its leases go
  stale, exercising reclaim-from-a-live-zombie.
- ``enospc_events``   — the event log's file handle starts raising ENOSPC
  after a seeded number of writes; `EventLog._write` must degrade to its
  tracking-only sink and the job must still complete (telemetry loss is
  never fatal).

Serve faults (wired by `serve/pool.py` at the replica batch boundary,
`on_serve_batch` — called with a batch in flight, outside the per-batch
exception guard):

- ``wedge_dispatch``   — the target replica's worker thread blocks forever
  mid-batch (requests assigned, none resolved): the supervisor must detect
  the stale heartbeat, re-dispatch the in-flight requests, and quarantine.
- ``raise_in_worker``  — an exception escapes the worker loop entirely and
  the thread dies, exercising the dead-thread classification + restart.
- ``wedge_heartbeat``  — (shared name) the replica keeps serving but its
  heartbeat stops: staleness-based detection must fire even though the
  thread is alive.

Serve faults always target replica 0 — the smoke's assertions need a known
victim, and determinism beats configurability here.

Recert faults (wired by `recert/scheduler.py` at the cycle's one
crash-interesting boundary, `on_recert` — called right after the
generation's grid is submitted, with the inflight record already
committed to `recert_state.json`):

- ``recert_kill_cycle`` — SIGKILL the scheduler mid-generation: jobs
  submitted, nothing harvested. Resume must pick up the SAME in-flight
  generation (never resubmit a new one) and complete it with a baseline
  byte-identical to an uninterrupted run.
- ``recert_torn_state`` — truncate `recert_state.json` mid-byte (a torn
  write): the scheduler must recover the generation counter and the
  in-flight record from the generation dirs' completion markers.

The harness holds no global state: construct one `Chaos` per job attempt
(or per serve run), `bind` the worker's heartbeat, and wire the sites.
"""

from __future__ import annotations

import errno
import hashlib
import os
import signal
import threading
from typing import IO, Optional, Sequence

FARM_FAULTS = ("crash_block", "ckpt_raise", "wedge_heartbeat",
               "enospc_events")
SERVE_FAULTS = ("wedge_dispatch", "raise_in_worker", "wedge_heartbeat")
RECERT_FAULTS = ("recert_kill_cycle", "recert_torn_state")
FAULTS = (FARM_FAULTS + ("wedge_dispatch", "raise_in_worker")
          + RECERT_FAULTS)

# The replica every serve fault is aimed at (see module docstring).
SERVE_TARGET_REPLICA = 0


class SimulatedPreemption(RuntimeError):
    """A chaos-injected crash in ``crash_mode="raise"`` — classified by the
    worker as a transient preemption, same as the SIGKILL it stands in for."""


def fault_seed(job_id: str, fault: str) -> int:
    """Deterministic 32-bit seed per (job, fault): the same job always
    crashes at the same block / fails the same save, so recovery tests are
    exactly reproducible."""
    digest = hashlib.sha256(f"{job_id}:{fault}".encode()).digest()
    return int.from_bytes(digest[:4], "big")


def parse_faults(spec: str) -> Sequence[str]:
    """Comma-joined fault list (the `--chaos` flag); unknown names are a
    configuration error, not a silent no-op."""
    faults = tuple(f.strip() for f in spec.split(",") if f.strip())
    unknown = [f for f in faults if f not in FAULTS]
    if unknown:
        raise ValueError(
            f"unknown chaos fault(s) {unknown}; known: {list(FAULTS)}")
    return faults


class Chaos:
    def __init__(self, faults: Sequence[str], job_id: str, state_dir: str,
                 crash_mode: str = "kill"):
        unknown = [f for f in faults if f not in FAULTS]
        if unknown:
            raise ValueError(
                f"unknown chaos fault(s) {unknown}; known: {list(FAULTS)}")
        if crash_mode not in ("kill", "raise"):
            raise ValueError(f"crash_mode must be kill|raise, got {crash_mode!r}")
        self.faults = tuple(faults)
        self.job_id = job_id
        self.state_dir = os.path.abspath(state_dir)
        self.crash_mode = crash_mode
        self._block_counter = 0
        self._heartbeat = None

    def bind(self, heartbeat=None) -> "Chaos":
        self._heartbeat = heartbeat
        return self

    # ---------------- fired-marker bookkeeping ----------------

    def marker_path(self, fault: str) -> str:
        return os.path.join(self.state_dir, f"chaos_{fault}.fired")

    def fired(self, fault: str) -> bool:
        return os.path.exists(self.marker_path(fault))

    def fire_once(self, fault: str) -> bool:
        """True exactly once per (job, fault) across all attempts and
        processes: the marker is committed via O_EXCL *before* the fault is
        injected, so a recovery attempt sees the marker even when the fault
        was a SIGKILL."""
        if fault not in self.faults:
            return False
        os.makedirs(self.state_dir, exist_ok=True)
        try:
            fd = os.open(self.marker_path(fault),
                         os.O_CREAT | os.O_EXCL | os.O_WRONLY, 0o644)
        except FileExistsError:
            return False
        os.close(fd)
        return True

    # ---------------- farm injection sites ----------------

    def crash_block_ordinal(self) -> int:
        """Which block boundary (0 or 1, counted per attempt) the crash
        targets — always early enough to land mid-job on the tiny CI grids."""
        return fault_seed(self.job_id, "crash_block") % 2

    def events_write_budget(self) -> int:
        """How many event writes succeed before the injected ENOSPC."""
        return 1 + fault_seed(self.job_id, "enospc_events") % 5

    def ckpt_raise_ordinal(self) -> int:
        """Which checkpoint save (per attempt) raises."""
        return fault_seed(self.job_id, "ckpt_raise") % 2

    def on_block(self, stage: int, iteration: int,
                 info: Optional[dict] = None) -> None:
        """Block-boundary site — wire into the job's `on_block_end` chain.
        Runs after the block's checkpoint save, before lease renewal."""
        n = self._block_counter
        self._block_counter += 1
        if (self._heartbeat is not None
                and self.fire_once("wedge_heartbeat")):
            self._heartbeat.wedge()
        if ("crash_block" in self.faults
                and n >= self.crash_block_ordinal()
                and self.fire_once("crash_block")):
            if self.crash_mode == "kill":
                os.kill(os.getpid(), signal.SIGKILL)
            raise SimulatedPreemption(
                f"chaos: simulated preemption at block {n} "
                f"(stage {stage}, iteration {iteration})")

    def wrap_checkpointer(self, checkpointer):
        if "ckpt_raise" not in self.faults or self.fired("ckpt_raise"):
            return checkpointer
        return _CheckpointRaiseProxy(checkpointer, self)

    def wrap_event_log(self, event_log) -> None:
        """Swap the EventLog's live file handle for one that starts raising
        ENOSPC after a seeded number of writes. Reaches into `_fh`
        deliberately: the fault must hit the exact handle `_write`'s
        OSError-degradation path guards, not a lookalike."""
        if "enospc_events" not in self.faults or event_log._fh is None:
            return
        if not self.fire_once("enospc_events"):
            return
        event_log._fh = _ENOSPCFile(event_log._fh,
                                    self.events_write_budget())

    # ---------------- recert injection site ----------------

    def on_recert(self, phase: str, state_path: str = "") -> None:
        """Scheduler cycle-boundary site — called by
        `RecertScheduler.begin_generation` right after `submit_spec`, with
        the inflight record already committed. The one place a crash is
        interesting: earlier there is nothing to resume, later the farm's
        own crash story covers it."""
        if phase != "submitted":
            return
        if ("recert_torn_state" in self.faults and state_path
                and self.fire_once("recert_torn_state")):
            # Tear the state file the way a crashed non-atomic writer
            # would: keep the first half of the bytes, drop the rest.
            try:
                with open(state_path, "rb") as fh:
                    raw = fh.read()
                with open(state_path, "wb") as fh:
                    fh.write(raw[:max(1, len(raw) // 2)])
            except OSError:
                pass
        if ("recert_kill_cycle" in self.faults
                and self.fire_once("recert_kill_cycle")):
            if self.crash_mode == "kill":
                os.kill(os.getpid(), signal.SIGKILL)
            raise SimulatedPreemption(
                "chaos: simulated scheduler preemption mid-generation "
                "(grid submitted, nothing harvested)")

    # ---------------- serve injection site ----------------

    def on_serve_batch(self, replica_id: int, heartbeat=None) -> None:
        """Replica batch-boundary site — the pool's worker loop calls this
        with a batch in flight (requests assigned to the replica, none
        resolved yet) and OUTSIDE its per-batch exception guard, so
        `raise_in_worker` escapes the loop and kills the thread. Only
        replica `SERVE_TARGET_REPLICA` is ever hit; each fault fires on the
        first in-flight batch that replica picks up (deterministic, and
        warmup never routes through the worker loop)."""
        if replica_id != SERVE_TARGET_REPLICA:
            return
        if (heartbeat is not None and "wedge_heartbeat" in self.faults
                and self.fire_once("wedge_heartbeat")):
            heartbeat.wedge()
        if ("raise_in_worker" in self.faults
                and self.fire_once("raise_in_worker")):
            raise SimulatedPreemption(
                f"chaos: injected worker exception (replica {replica_id})")
        if ("wedge_dispatch" in self.faults
                and self.fire_once("wedge_dispatch")):
            # Freeze forever mid-batch: the daemon thread is abandoned and
            # the supervisor's staleness detection takes over.
            threading.Event().wait()


class _CheckpointRaiseProxy:
    """`save` raises ENOSPC exactly once at the seeded ordinal; every other
    attribute (restore, latest_step_info, clear, close) delegates."""

    def __init__(self, checkpointer, chaos: Chaos):
        self._checkpointer = checkpointer
        self._chaos = chaos
        self._saves = 0

    def save(self, *args, **kwargs):
        n = self._saves
        self._saves += 1
        if (n >= self._chaos.ckpt_raise_ordinal()
                and self._chaos.fire_once("ckpt_raise")):
            raise OSError(errno.ENOSPC,
                          "chaos: injected checkpoint-write failure")
        return self._checkpointer.save(*args, **kwargs)

    def __getattr__(self, item):
        return getattr(self._checkpointer, item)


class _ENOSPCFile:
    """File-object shim whose `write` raises ENOSPC once its budget runs
    out — the mid-run disk-full signature `EventLog._write` degrades on."""

    def __init__(self, fh: IO[str], budget: int):
        self._fh = fh
        self._budget = int(budget)

    def write(self, data: str):
        if self._budget <= 0:
            raise OSError(errno.ENOSPC, "chaos: injected event-write failure")
        self._budget -= 1
        return self._fh.write(data)

    def close(self) -> None:
        self._fh.close()

    def __getattr__(self, item):
        return getattr(self._fh, item)
