"""dorpatch-tpu: a TPU-native framework for distributed, occlusion-robust
adversarial patches and certified-defense evaluation.

Rebuilds the capabilities of CGCL-codes/DorPatch (NDSS 2024) as idiomatic
JAX/XLA: jit+vmap'd EOT optimization, batched-scan PatchCleanser certification,
on-device adaptive optimizer state, and mesh-sharded mask/EOT axes.
"""

__version__ = "0.1.0"

from dorpatch_tpu.config import AttackConfig, DefenseConfig, ExperimentConfig, NUM_CLASSES  # noqa: F401
