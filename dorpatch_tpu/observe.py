"""Observability: structured attack metrics, step timing, and device traces.

The reference's only observability is tqdm plus a print of the loss breakdown
every 20 iterations (`/root/reference/attack.py:318-330`) and per-run result
prints (`main.py:186-187`). Here that becomes a subsystem:

- `AttackMetricsLogger` — consumes the attack's on-device metrics vector at
  every jitted block boundary (`DorPatch.on_block_end`) and appends JSONL
  records (one file per experiment, under the results dir), with an optional
  console mirror of the reference's periodic loss-breakdown line. Metrics
  stay on device between block boundaries — logging cost is one [8]-vector
  transfer per block, not per step.
- `StepTimer` — wall-clock per block -> steps/sec and images/sec series.
- `trace` — context manager around `jax.profiler` for on-demand TPU traces
  (tensorboard-viewable), gated so it is a no-op when no trace dir is given.
"""

from __future__ import annotations

import contextlib
import json
import os
import time
from typing import IO, Optional

import numpy as np

# Layout of `TrainState.metrics` (see `attack.DorPatch._step`).
METRIC_NAMES = (
    "loss",         # total per-image objective, batch mean
    "loss_adv",     # CW margin over sampled masks, mean
    "loss_struc",   # structural TV ratio, mean
    "group_lasso",  # stage-0 group-lasso, mean
    "density",      # stage-0 density variance, mean
    "masked_acc",   # fraction of masked EOT samples predicted as state.y.
                    # Untargeted (y = true label): 1.0 = attack losing.
                    # After the targeted switch (y = target): 1.0 = winning.
    "l2",           # ||delta||_2 batch mean
    "n_failed",     # failure-set size (masks the attack currently loses to)
)


class AttackMetricsLogger:
    """JSONL metrics sink for `DorPatch.on_block_end`.

    Each record: `{"ts": ..., "batch": ..., "stage": 0|1, "step": ...,
    "stopped": ..., <METRIC_NAMES>...}`. Use as
    `attack.on_block_end = logger.on_block_end` (optionally after
    `logger.set_batch(i)`), or chain from an existing callback.
    """

    def __init__(
        self,
        path: Optional[str] = None,
        echo_every: int = 0,
        clock=time.time,
    ):
        self.path = path
        self.echo_every = echo_every
        self._clock = clock
        self._batch = 0
        self._fh: Optional[IO[str]] = None
        self.history = []
        if path:
            os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
            self._fh = open(path, "a", buffering=1)

    def set_batch(self, batch_id: int) -> None:
        self._batch = batch_id

    def on_block_end(self, stage: int, step: int, info: dict) -> None:
        vals = np.asarray(info["metrics"], dtype=np.float64)
        rec = {
            "ts": round(self._clock(), 3),
            "batch": self._batch,
            "stage": int(stage),
            "step": int(step),
            "stopped": bool(info.get("stopped", False)),
        }
        rec.update({k: float(v) for k, v in zip(METRIC_NAMES, vals)})
        self.history.append(rec)
        if self._fh is not None:
            self._fh.write(json.dumps(rec) + "\n")
        if self.echo_every and (step % self.echo_every == 0 or rec["stopped"]):
            # the reference's periodic loss breakdown (`attack.py:318-330`)
            print(
                f"[batch {self._batch} stage {stage} iter {step}] "
                f"loss {rec['loss']:.4f} (adv {rec['loss_adv']:.4f}, "
                f"struct {rec['loss_struc']:.4f}, gl {rec['group_lasso']:.5f}, "
                f"density {rec['density']:.5f}) l2 {rec['l2']:.2f} "
                f"masked-acc {rec['masked_acc']:.2f} "
                f"failures {rec['n_failed']:.0f}",
                flush=True,
            )

    def close(self) -> None:
        if self._fh is not None:
            self._fh.close()
            self._fh = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


class StepTimer:
    """Wall-clock series over jitted blocks -> throughput summary."""

    def __init__(self, clock=time.perf_counter):
        self._clock = clock
        self._t0 = None
        self.block_seconds = []

    def start(self) -> None:
        self._t0 = self._clock()

    def stop(self) -> float:
        if self._t0 is None:
            raise RuntimeError("StepTimer.stop() before start()")
        dt = self._clock() - self._t0
        self._t0 = None
        self.block_seconds.append(dt)
        return dt

    def summary(self, steps_per_block: int, batch: int,
                flops_per_step: float = 0.0,
                peak_flops: float = 0.0) -> dict:
        """Throughput summary; pass `flops_per_step` (useful FLOPs of one
        optimization step — e.g. 3 x forward-FLOPs x EOT x batch from
        `jit(fwd).lower(...).compile().cost_analysis()["flops"]`) and the
        chip's `peak_flops` to get a defensible `mfu` row (SURVEY.md §6)."""
        total = float(sum(self.block_seconds))
        n_steps = steps_per_block * len(self.block_seconds)
        out = {
            "blocks": len(self.block_seconds),
            "total_seconds": round(total, 3),
            "steps_per_sec": round(n_steps / total, 3) if total else 0.0,
            "images_per_sec": round(n_steps * batch / total, 3) if total else 0.0,
        }
        if flops_per_step and peak_flops and total:
            out["achieved_tflops"] = round(
                n_steps * flops_per_step / total / 1e12, 2)
            out["mfu"] = round(n_steps * flops_per_step / total / peak_flops, 4)
        return out


@contextlib.contextmanager
def trace(trace_dir: Optional[str]):
    """`jax.profiler` trace scope; no-op when `trace_dir` is falsy.

    Produces a tensorboard-loadable trace of every XLA computation launched
    in the scope — the rebuild's answer to the reference's absent profiling
    (SURVEY.md §5)."""
    if not trace_dir:
        yield
        return
    import jax

    jax.profiler.start_trace(trace_dir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()
