"""Loss functions and projections for the DorPatch optimizer, as pure jnp.

Everything here is NHWC (TPU-native layout): images `[B, H, W, C]`, patch
masks `[B, H, W, 1]`. Reference semantics are reproduced exactly — including
the *asymmetric* gradient flow of the reference's total-variation variant
(`/root/reference/attack.py:33-45`), where gradients reach only the shifted
operand of each finite difference — but expressed with `stop_gradient` and
`lax.reduce_window` instead of in-place tensor surgery and all-ones conv
modules.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax


def cw_margin_switchable(
    logits: jax.Array,
    labels: jax.Array,
    num_classes: int,
    targeted: jax.Array,
    confidence: float = 0.0,
) -> jax.Array:
    """Carlini-Wagner margin loss with a (possibly traced) `targeted` flag
    (`/root/reference/attack.py:10-23`).

    logits `[N, C]`, labels `[N]` int. Untargeted: hinge on
    `conf + logit_label - max_other`; targeted: `conf + max_other - logit_label`.
    The max over "others" zeroes the label logit and pushes its slot to
    exactly -1e4, matching the reference's `(1-onehot)*logits - onehot*1e4`.
    The flag may be a traced boolean: the attack flips untargeted -> targeted
    mid-run (`attack.py:169-176`), so the flag lives in the jitted carry and
    selects via `where` instead of Python control flow. Returns `[N]`.
    """
    onehot = jax.nn.one_hot(labels, num_classes, dtype=logits.dtype)
    real = jnp.sum(logits * onehot, axis=-1)
    other = jnp.max((1.0 - onehot) * logits - onehot * 1e4, axis=-1)
    margin = jnp.where(targeted, other - real, real - other)
    return jnp.maximum(confidence + margin, 0.0)


def cw_margin(
    logits: jax.Array,
    labels: jax.Array,
    num_classes: int,
    targeted: bool,
    confidence: float = 0.0,
) -> jax.Array:
    """`cw_margin_switchable` with a static Python `targeted` flag."""
    return cw_margin_switchable(logits, labels, num_classes, jnp.asarray(targeted), confidence)


def local_variance(x: jax.Array):
    """Directional absolute differences with one-sided gradients
    (`/root/reference/attack.py:33-39`).

    x: `[B, H, W, C]`. Returns `(lv, grad_lr, grad_ud)`, each `[B, H, W, C]`.

    The reference builds each directional term by in-place subtracting the
    shifted image from a *detached* clone, so autograd reaches only the
    shifted operand: `grad_lr[..., w] = |sg(x[..., w]) - x[..., w+1]|` for
    w < W-1, and the last column is `sg(x[..., W-1])` untouched (outside the
    abs). Same for rows. We reproduce that flow with stop_gradient.
    """
    sg = lax.stop_gradient(x)
    diff_lr = jnp.abs(sg[:, :, :-1, :] - x[:, :, 1:, :])
    grad_lr = jnp.concatenate([diff_lr, sg[:, :, -1:, :]], axis=2)
    diff_ud = jnp.abs(sg[:, :-1, :, :] - x[:, 1:, :, :])
    grad_ud = jnp.concatenate([diff_ud, sg[:, -1:, :, :]], axis=1)
    return grad_lr + grad_ud, grad_lr, grad_ud


def min_var_weighted_variance(x: jax.Array) -> jax.Array:
    """TV weighted by the smaller directional gradient
    (`/root/reference/attack.py:41-45`): smoothness penalty aligned with image
    structure. Gradients flow through both the sum and the selected weight
    (the selection itself is non-differentiable), matching `torch.where`.
    """
    lv, grad_lr, grad_ud = local_variance(x)
    return lv * jnp.where(grad_lr > grad_ud, grad_ud, grad_lr)


def structural_loss(adv_x: jax.Array, local_var_x: jax.Array) -> jax.Array:
    """Per-image structural loss (`/root/reference/attack.py:227-228`):
    channel-mean weighted TV of adv_x, normalized by the clean image's local
    variance (+1e-5), averaged over pixels. adv_x `[B,H,W,C]`,
    local_var_x `[B,H,W]` precomputed from the clean image. Returns `[B]`.
    """
    mv = jnp.mean(min_var_weighted_variance(adv_x), axis=-1)
    return jnp.mean(mv / (local_var_x + 1e-5), axis=(1, 2))


def window_sum(x: jax.Array, window: int) -> jax.Array:
    """Non-overlapping window sums: `[B, H, W, 1] -> [B, H/w, W/w, 1]`.

    TPU-native replacement for the reference's all-ones stride-w conv modules
    (`/root/reference/attack.py:72-80`): a `lax.reduce_window` add-reduction,
    which XLA lowers to an efficient pooling op instead of a dense conv.
    """
    return lax.reduce_window(
        x, 0.0, lax.add, (1, window, window, 1), (1, window, window, 1), "VALID"
    )


def group_lasso(adv_mask: jax.Array, basic_unit: int) -> jax.Array:
    """Group-wise sparsity over basic_unit x basic_unit cells
    (`/root/reference/attack.py:242-245`): `unit * sum_g sqrt(sum_cell m^2)`.
    adv_mask `[B, H, W, 1]`. Returns `[B]`.
    """
    g = window_sum(adv_mask**2, basic_unit)
    return basic_unit * jnp.sum(jnp.sqrt(g), axis=(1, 2, 3))


def density_loss(adv_mask: jax.Array, window: int) -> jax.Array:
    """Variance of coarse-cell mask mass (`/root/reference/attack.py:76-80,237`):
    low variance spreads patch mass across the image (the "distributed"
    property). Uses the unbiased (ddof=1) variance to match `torch.var`.
    adv_mask `[B, H, W, 1]`, window = H // 8. Returns `[B]`.
    """
    cells = window_sum(adv_mask, window)
    flat = cells.reshape(cells.shape[0], -1)
    return jnp.var(flat, axis=1, ddof=1)


def l2_project(mask: jax.Array, pattern: jax.Array, x: jax.Array, eps: float) -> jax.Array:
    """Soft L2 projection of the patch delta (`/root/reference/utils.py:105-110`).

    delta = mask * (pattern - x); scaled so ||delta||_2 <= eps per image, with
    the norm detached (gradients see the scale as a constant), exactly as the
    reference. mask `[B,H,W,1]`, pattern/x `[B,H,W,C]`. Returns delta `[B,H,W,C]`.
    """
    delta = mask * (pattern - x)
    norm = lax.stop_gradient(jnp.sqrt(jnp.sum(delta**2, axis=(1, 2, 3))))
    scale = jnp.minimum(eps / norm, 1.0)
    return delta * scale[:, None, None, None]
