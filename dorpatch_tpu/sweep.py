"""Hyperparameter sweep driver (BASELINE.md config 4: CIFAR-10 + ResNet-18,
patch-budget x regularization grid).

The reference has no sweep tooling — grids were run by hand via the CLI, one
process per point (`/root/reference/main.py:8-41`). Here a grid is one
process: every point attacks the *same* fixed evaluation batch with one
victim, one mask universe, and one defense bank, then scores robust accuracy
and certified attack success on-device.

Compile-cache note: the whole grid shares ONE set of compiled step blocks.
The swept hyperparameters never enter the compiled graphs — the
regularization coefficients are traced carry scalars
(`attack.BLOCK_IRRELEVANT_FIELDS`) and `patch_budget` only shapes the eager
stage-0→1 top-k — so each grid point's `DorPatch` adopts the first point's
programs (`DorPatch.adopt_compiled`) and runs with zero recompiles. The
summary JSON reports `block_programs` (compiled step/sweep programs for the
entire grid) and per-row wall seconds, where the first row carries all of
the compile time and later rows demonstrate the drop.
"""

from __future__ import annotations

import argparse
import dataclasses
import itertools
import json
import os
import time
from typing import Callable, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from dorpatch_tpu import losses, metrics, observe
from dorpatch_tpu.attack import DorPatch
from dorpatch_tpu.config import (AttackConfig, DefenseConfig, ExperimentConfig,
                                  resolved_data_source)
from dorpatch_tpu.data import dataset_batches, streaming_batches
from dorpatch_tpu.defense import build_defenses
from dorpatch_tpu.models import get_model

ROWS_NAME = "rows.jsonl"
GRID_KEYS = ("patch_budget", "density", "structured")


def row_key(patch_budget: float, density: float, structured: float) -> Tuple:
    """Hashable identity of one grid point, stable across a JSON round-trip
    (recorded rows come back from `rows.jsonl` with json's float formatting,
    so the in-memory key must go through the same representation)."""
    return tuple(json.loads(json.dumps(
        [float(patch_budget), float(density), float(structured)])))


def load_recorded_rows(result_dir: str) -> Dict[Tuple, Dict]:
    """{grid-point key: row} already recorded in `result_dir`'s rows.jsonl.

    Tolerant of a truncated final line — the file is appended row-by-row and
    the previous sweep may have been killed mid-write; a partial row is
    simply not recorded, so that point re-runs."""
    out: Dict[Tuple, Dict] = {}
    try:
        with open(os.path.join(result_dir, ROWS_NAME), errors="replace") as fh:
            for line in fh:
                line = line.strip()
                if not line:
                    continue
                try:
                    row = json.loads(line)
                except ValueError:
                    continue
                if all(k in row for k in GRID_KEYS):
                    out[row_key(*(row[k] for k in GRID_KEYS))] = row
    except OSError:
        pass
    return out


def append_row(result_dir: str, row: Dict) -> None:
    """Flush one completed grid row to `rows.jsonl` (append-mode,
    line-buffered — the same pattern as metrics.jsonl): a killed sweep keeps
    every finished row and loses at most the one in flight."""
    os.makedirs(result_dir, exist_ok=True)
    with open(os.path.join(result_dir, ROWS_NAME), "a", buffering=1) as fh:
        fh.write(json.dumps(row, default=float) + "\n")


def run_sweep(
    cfg: ExperimentConfig,
    patch_budgets: Sequence[float] = (0.06, 0.12),
    densities: Sequence[float] = (0.0, 1e-3),
    structureds: Sequence[float] = (1e-3,),
    defense_ratio: float = 0.06,
    verbose: bool = True,
    result_dir: Optional[str] = None,
    checkpointer_factory: Optional[Callable[[int, Dict], object]] = None,
    on_block_end: Optional[Callable[[int, int, dict], None]] = None,
) -> List[Dict]:
    """Grid-attack one evaluation batch; one result row per grid point.

    Row: the point's hyperparameters, robust accuracy (victim still correct
    under the patch), certified-ASR at `defense_ratio`, mean patch L2, and
    wall seconds.

    With `result_dir`, each completed row is appended to `rows.jsonl` as it
    finishes and the final patch artifacts are saved per point — and on
    re-invocation over the same directory, already-recorded grid points are
    skipped (their recorded rows are returned in place), so a killed sweep
    resumes instead of restarting. `checkpointer_factory(point_index,
    point_params)` (returning a `CarryCheckpointer` or None) additionally
    checkpoints the attack carry at block boundaries, so even the
    interrupted point resumes mid-stage; `on_block_end` is forwarded to
    every point's `DorPatch` (the farm's lease-renewal/chaos hook)."""
    victim = get_model(cfg.dataset, cfg.base_arch, cfg.model_dir, cfg.img_size,
                       gn_impl=cfg.gn_impl)
    data_source = resolved_data_source(cfg)
    if cfg.stream_depth > 0:
        # the streaming input path (background reads + device prefetch):
        # the sweep consumes one batch, so this mainly buys the overlapped
        # decode+transfer while the victim's first forward compiles
        batch_iter = streaming_batches(
            cfg.dataset, cfg.data_dir, cfg.batch_size, cfg.img_size,
            cfg.seed, source=data_source, depth=cfg.stream_depth)
    else:
        batch_iter = iter(dataset_batches(
            cfg.dataset, cfg.data_dir, cfg.batch_size, cfg.img_size,
            cfg.seed, source=data_source,
        ))
    x_np, y_np = next(batch_iter)
    close = getattr(batch_iter, "close", None)
    if close is not None:
        close()
    x = jnp.asarray(x_np)
    preds = jnp.argmax(victim.apply(victim.params, x), -1)
    if data_source == "synthetic":
        y_np = np.asarray(preds)  # random labels -> score the model's own preds
    keep = np.asarray(preds) == y_np
    if not keep.any():
        raise RuntimeError("no correctly-classified images in the sweep batch")
    x, y_np = x[jnp.asarray(keep)], y_np[keep]

    defense = build_defenses(
        victim.apply, cfg.img_size,
        dataclasses.replace(cfg.defense, ratios=(defense_ratio,)),
        incremental=victim.incremental)[0]

    rows: List[Dict] = []
    grid = list(itertools.product(patch_budgets, densities, structureds))
    recorded = load_recorded_rows(result_dir) if result_dir else {}
    if result_dir:
        os.makedirs(result_dir, exist_ok=True)
    proto: Optional[DorPatch] = None
    for gi, (budget, density, structured) in enumerate(grid):
        prior = recorded.get(row_key(budget, density, structured))
        if prior is not None:
            # already completed by an earlier (killed) invocation of this
            # sweep: keep its recorded metrics, spend nothing re-attacking
            rows.append(prior)
            if verbose:
                observe.log(json.dumps({"sweep_resume_skip": gi,
                                        "patch_budget": budget,
                                        "density": density,
                                        "structured": structured}))
            continue
        acfg = dataclasses.replace(
            cfg.attack, patch_budget=budget, density=density,
            structured=structured)
        attack = DorPatch(victim.apply, victim.params, victim.num_classes,
                          acfg, on_block_end=on_block_end)
        if proto is None:
            proto = attack
        else:
            attack.adopt_compiled(proto)  # zero recompiles across the grid
        point_params = {"patch_budget": budget, "density": density,
                        "structured": structured}
        ck = (checkpointer_factory(gi, point_params)
              if checkpointer_factory is not None else None)
        resumed = ck.latest_step_info() if ck is not None else None
        if ck is not None:
            attack.checkpointer = ck
        try:
            timer = observe.StepTimer()
            timer.start()
            # same key for every grid point (the reference protocol: one
            # process per point, same --seed) so row deltas isolate the
            # hyperparameters
            with observe.span("sweep.point", point=gi, patch_budget=budget,
                              density=density, structured=structured):
                res = attack.generate(x, key=jax.random.PRNGKey(cfg.seed))
                jax.block_until_ready(res.adv_pattern)
            seconds = timer.stop()

            delta = losses.l2_project(res.adv_mask, res.adv_pattern, x, acfg.eps)
            adv_x = x + delta
            preds_adv = np.asarray(jnp.argmax(victim.apply(victim.params, adv_x), -1))
            with observe.span("certify", point=gi, images=int(x.shape[0])):
                recs = defense.robust_predict(
                    victim.params, adv_x, victim.num_classes)
            defense.collect(recs)  # one metric definition (metrics.compute_metrics)
            m = metrics.compute_metrics(
                np.asarray(y_np), y_np, preds_adv, [defense.result])
            row = {
                "point": gi,
                "patch_budget": budget,
                "density": density,
                "structured": structured,
                "robust_accuracy": m["robust_accuracy"],
                "asr": round(100.0 - m["robust_accuracy"], 4),
                "certified_asr_pc": m["certified_asr_pc"][0],
                "mean_l2": float(jnp.sqrt(jnp.sum(delta**2, axis=(1, 2, 3))).mean()),
                "images": int(x.shape[0]),
                "seconds": round(seconds, 2),
            }
            if resumed is not None:
                # provable crash-recovery accounting: this attempt started
                # from a carry snapshot, not from iteration zero
                row["resumed_from_stage"] = resumed.stage
                row["resumed_from_iteration"] = resumed.iteration
            if result_dir:
                np.save(os.path.join(result_dir, f"point_{gi:03d}_mask.npy"),
                        np.asarray(res.adv_mask))
                np.save(os.path.join(result_dir, f"point_{gi:03d}_pattern.npy"),
                        np.asarray(res.adv_pattern))
                append_row(result_dir, row)
            if ck is not None:
                ck.clear()  # row recorded: a stale carry must never leak
        finally:
            if ck is not None:
                ck.close()
        rows.append(row)
        if verbose:
            observe.log(json.dumps(row))
    if verbose and proto is not None:
        observe.log(json.dumps({
            "block_programs": len(proto._programs),
            "grid_points": len(grid),
        }))
    return rows


def main(argv: Optional[Sequence[str]] = None):
    p = argparse.ArgumentParser(description="DorPatch hyperparameter sweep")
    p.add_argument("--dataset", default="cifar10",
                   choices=["cifar10", "imagenet", "cifar100"])
    p.add_argument("--data_dir", default="/home/data/data")
    p.add_argument("--model_dir", default="pretrained_models/")
    p.add_argument("--base_arch", default="resnet18")
    p.add_argument("--img-size", type=int, default=32)
    p.add_argument("-b", "--batch-size", type=int, default=8)
    p.add_argument("--synthetic", action="store_true")
    p.add_argument("--seed", type=int, default=1234)
    p.add_argument("--max-iterations", type=int, default=400)
    p.add_argument("--sampling-size", type=int, default=32)
    p.add_argument("--basic-unit", type=int, default=4)
    p.add_argument("--dropout", type=int, default=1, choices=[0, 1, 2])
    p.add_argument("--patch-budgets", type=float, nargs="+", default=[0.06, 0.12])
    p.add_argument("--densities", type=float, nargs="+", default=[0.0, 1e-3])
    p.add_argument("--structureds", type=float, nargs="+", default=[1e-3])
    p.add_argument("--defense-ratio", type=float, default=0.06)
    p.add_argument("--result-dir", default="",
                   help="persist rows.jsonl + per-point patch artifacts here;"
                        " re-running over the same dir skips finished rows")
    args = p.parse_args(argv)

    attack = AttackConfig(
        max_iterations=args.max_iterations,
        sampling_size=args.sampling_size,
        basic_unit=args.basic_unit,
        dropout=args.dropout,
        switch_iteration=min(500, args.max_iterations // 2),
        sweep_interval=min(100, max(1, args.max_iterations // 4)),
    )
    cfg = ExperimentConfig(
        dataset=args.dataset, data_dir=args.data_dir, model_dir=args.model_dir,
        base_arch=args.base_arch, img_size=args.img_size,
        batch_size=args.batch_size, seed=args.seed,
        synthetic_data=args.synthetic, attack=attack, defense=DefenseConfig(),
    )
    t0 = time.time()
    rows = run_sweep(cfg, args.patch_budgets, args.densities, args.structureds,
                     args.defense_ratio, result_dir=args.result_dir or None)
    observe.log(json.dumps({"sweep_points": len(rows),
                            "total_seconds": round(time.time() - t0, 1)}))
    return rows


if __name__ == "__main__":
    main()
