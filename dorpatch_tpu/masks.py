"""R-covering occlusion-mask geometry, TPU-first.

Reimplements the PatchCleanser mask-set construction
(`/root/reference/defenses/PatchCleanser.py:6-59`) with a representation chosen
for XLA rather than materialized boolean tensors: every mask is a small set of
occlusion *rectangles* `[K, 4]` (row0, row1, col0, col1 half-open), and a
jit-friendly rasterizer turns gathered rectangle sets into boolean masks
on-device. This keeps the 2520-mask attack universe at `2520*2*4` int32
(~80 KB) instead of 126 MB of booleans, shards trivially along the mask axis,
and lets the hot loop rasterize only the 128 sampled masks per step.

Mask convention matches the reference: **True = pixel kept, False = occluded**.
A mask with K rectangles rasterizes to `AND_k (outside rect_k)`, which is
exactly the reference's elementwise product of single-rectangle masks
(`PatchCleanser.py:23-24`).
"""

from __future__ import annotations

import math
from typing import NamedTuple, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np


# The ratio schedule and R-covering axis count live in config.py (the
# jax-free layer) and are re-exported here for the geometry code and its
# many historical importers — see the note beside their definition.
from dorpatch_tpu.config import (  # noqa: F401  (re-export)
    DEFAULT_RATIOS,
    NUM_MASKS_PER_AXIS,
)


class MaskSpec(NamedTuple):
    """Geometry of one R-covering mask family (`PatchCleanser.py:8-17`)."""

    img_size: int
    patch_ratio: float
    n_patch: int
    mask_size: int
    stride: int
    window_size: int
    num_mask_per_axis: int


def geometry(
    img_size: int,
    patch_ratio: float = 0.03,
    n_patch: int = 1,
    num_mask_per_axis: int = NUM_MASKS_PER_AXIS,
) -> MaskSpec:
    """Compute mask/stride/window sizes (`PatchCleanser.py:11-17`).

    mask_size = floor(sqrt(img^2 * ratio / n_patch));
    stride = ceil((img - mask + 1) / num_mask_per_axis);
    window = mask + stride - 1.
    """
    mask_size = math.floor(math.sqrt(img_size**2 * patch_ratio / n_patch))
    stride = math.ceil((img_size - mask_size + 1) / num_mask_per_axis)
    window_size = mask_size + stride - 1
    return MaskSpec(
        img_size=img_size,
        patch_ratio=patch_ratio,
        n_patch=n_patch,
        mask_size=mask_size,
        stride=stride,
        window_size=window_size,
        num_mask_per_axis=num_mask_per_axis,
    )


def first_order_rects(spec: MaskSpec) -> np.ndarray:
    """The `num^2` single occlusion windows (`PatchCleanser.py:44-59`).

    Returns int32 `[num^2, 4]` rows of (r0, r1, c0, c1), half-open, clipped to
    the image; enumeration order is row-major `i * num + j` as in the reference.
    """
    num = spec.num_mask_per_axis
    rects = np.zeros((num * num, 4), dtype=np.int32)
    for i in range(num):
        for j in range(num):
            r0 = spec.stride * i
            r1 = min(spec.img_size, r0 + spec.window_size)
            c0 = spec.stride * j
            c1 = min(spec.img_size, c0 + spec.window_size)
            rects[i * num + j] = (r0, r1, c0, c1)
    return rects


def pair_rects(rects: np.ndarray) -> np.ndarray:
    """Upper-triangular (i<j) pairs of rectangles, `[C(M,2), 2, 4]`.

    Row-major pair order matches the reference's `triu(...,diagonal=1)`
    selection of the MxM product (`PatchCleanser.py:23-29`):
    (0,1),(0,2),...,(0,M-1),(1,2),...
    """
    n = rects.shape[0]
    ii, jj = np.triu_indices(n, k=1)
    return np.stack([rects[ii], rects[jj]], axis=1).astype(np.int32)


def pair_index(n: int, i: np.ndarray, j: np.ndarray) -> np.ndarray:
    """Index into `pair_rects` output for pair (i<j) of an n-mask family."""
    i = np.asarray(i)
    j = np.asarray(j)
    return (i * (2 * n - i - 1)) // 2 + (j - i - 1)


def second_round_table_indices(n: int) -> np.ndarray:
    """`grid[i, j]` = index of the {i, j} double-mask into the COMBINED
    `[singles; pairs]` rectangle table (`defense.PatchCleanser._rects`
    layout: n single masks first, then the C(n,2) pairs).

    The diagonal maps to the single mask itself — masking is idempotent
    (`mask_i(mask_i(x)) == mask_i(x)`), so row i of this grid is exactly the
    mask set of PatchCleanser's second round for first-round mask i. The
    pruned certifier's ragged row program gathers its per-entry mask sets
    through this grid, and `defense._second_round_index_grid` derives its
    pair-table view from it (one pair-layout source of truth)."""
    ii, jj = np.meshgrid(np.arange(n), np.arange(n), indexing="ij")
    grid = n + pair_index(n, np.minimum(ii, jj), np.maximum(ii, jj))
    grid[np.eye(n, dtype=bool)] = np.arange(n)
    return grid.astype(np.int32)


def mask_sets(spec: MaskSpec) -> Tuple[np.ndarray, np.ndarray]:
    """(mask_set, double_mask_set) as rectangle sets.

    n_patch=1: singles `[M,1,4]` and pairs `[C(M,2),2,4]`.
    n_patch=2: pairs and basic-major triples `[M*C(M,2),3,4]`
    (`PatchCleanser.py:31-39`; the reference's n_patch=2 triple order is
    `basic[:,None] * combined[None,:]`, i.e. basic-major).
    """
    basic = first_order_rects(spec)
    pairs = pair_rects(basic)
    if spec.n_patch == 1:
        return basic[:, None, :], pairs
    if spec.n_patch == 2:
        n_basic, n_pairs = basic.shape[0], pairs.shape[0]
        triples = np.concatenate(
            [
                np.broadcast_to(basic[:, None, None, :], (n_basic, n_pairs, 1, 4)),
                np.broadcast_to(pairs[None, :, :, :], (n_basic, n_pairs, 2, 4)),
            ],
            axis=2,
        ).reshape(n_basic * n_pairs, 3, 4)
        return pairs, triples.astype(np.int32)
    raise NotImplementedError(f"n_patch={spec.n_patch}")


def pad_rects(rects: np.ndarray, k: int) -> np.ndarray:
    """Pad the rectangle axis to K entries with empty (0,0,0,0) rectangles.

    Empty rectangles rasterize to 'occlude nothing', so padding is a no-op on
    the resulting mask; it gives heterogeneous mask families a uniform shape.
    """
    n, cur, _ = rects.shape
    if cur > k:
        raise ValueError(f"cannot pad {cur} rectangles down to K={k}")
    if cur == k:
        return rects
    pad = np.zeros((n, k - cur, 4), dtype=np.int32)
    return np.concatenate([rects, pad], axis=1)


def dropout_universe(
    img_size: int,
    dropout: int = 2,
    dropout_sizes: Sequence[float] = DEFAULT_RATIOS,
    num_mask_per_axis: int = NUM_MASKS_PER_AXIS,
) -> np.ndarray:
    """The attack's occlusion universe (`/root/reference/attack.py:25-31,83-85`).

    Concatenation over dropout ratios of the n_patch=1 mask family:
    dropout=1 -> single masks (`[len(sizes)*M, 1, 4]`),
    dropout=2 -> double masks (`[len(sizes)*C(M,2), 2, 4]`).
    dropout=0 -> one empty mask (occlusion EOT disabled; the reference's
    dropout=0 branch is unreachable/broken — `attack.py:54-55,84-85` would
    concat None — so this is the deliberate repair: a single identity mask).
    """
    if dropout == 0:
        return np.zeros((1, 1, 4), dtype=np.int32)
    if dropout not in (1, 2):
        raise ValueError(f"dropout={dropout} (supported: 0, 1, 2)")
    parts = []
    for ratio in dropout_sizes:
        spec = geometry(img_size, ratio, n_patch=1, num_mask_per_axis=num_mask_per_axis)
        singles, doubles = mask_sets(spec)
        parts.append(singles if dropout == 1 else doubles)
    return np.concatenate(parts, axis=0)


def rect_token_coverage(rects: np.ndarray, img_size: int,
                        patch_px: int) -> np.ndarray:
    """Boolean coverage of a ViT patch-token grid by rectangle sets.

    rects `[N, K, 4]` (r0, r1, c0, c1 half-open, empty (0,0,0,0) rows
    allowed) -> `[N, T]` bool with `T = (img_size // patch_px) ** 2`
    row-major patch tokens: entry (n, t) is True iff any rectangle of mask
    n overlaps token t's `patch_px x patch_px` pixel window. A rectangle
    whose edge straddles a patch boundary covers BOTH straddled tokens
    (interval overlap, not containment) — the incremental ViT path must
    recompute every token whose pixels the mask touches at all.
    """
    rects = np.asarray(rects, dtype=np.int64)
    if rects.ndim == 2:
        rects = rects[:, None, :]
    grid = img_size // patch_px
    t0 = np.arange(grid) * patch_px          # token window starts
    r0, r1 = rects[..., 0:1], rects[..., 1:2]  # [N, K, 1]
    c0, c1 = rects[..., 2:3], rects[..., 3:4]
    # half-open interval overlap per axis: [a0, a1) meets [t, t+patch_px)
    rows = (r0 < t0[None, None] + patch_px) & (r1 > t0[None, None])  # [N,K,G]
    cols = (c0 < t0[None, None] + patch_px) & (c1 > t0[None, None])
    cover = rows[..., :, None] & cols[..., None, :]  # [N, K, G, G]
    return cover.any(axis=1).reshape(rects.shape[0], grid * grid)


def token_coverage(spec: MaskSpec, patch_px: int) -> np.ndarray:
    """`[M, T]` bool: first-round mask i touches ViT patch token t.

    The union coverage of a mask *pair* {i, j} is `cov[i] | cov[j]` — the
    combined-table rows `rect_token_coverage(mask_sets(...)[1], ...)`
    produces directly. Consumed by the token-pruned incremental certify
    path (`models/vit.py:TokenPrunedViT`)."""
    if spec.img_size % patch_px:
        raise ValueError(
            f"patch_px={patch_px} does not divide img_size={spec.img_size}")
    return rect_token_coverage(first_order_rects(spec)[:, None, :],
                               spec.img_size, patch_px)


def rasterize(rects: jax.Array, img_size: int) -> jax.Array:
    """Rasterize rectangle sets `[..., K, 4]` to boolean masks `[..., H, W]`.

    True = keep, False = occluded (reference convention,
    `PatchCleanser.py:49-58`). Pure jnp; safe inside jit/vmap/scan and under
    sharding along the leading mask axis.
    """
    rects = jnp.asarray(rects, dtype=jnp.int32)
    rows = jax.lax.broadcasted_iota(jnp.int32, (img_size, img_size), 0)
    cols = jax.lax.broadcasted_iota(jnp.int32, (img_size, img_size), 1)
    r0 = rects[..., 0][..., None, None]
    r1 = rects[..., 1][..., None, None]
    c0 = rects[..., 2][..., None, None]
    c1 = rects[..., 3][..., None, None]
    occluded = (rows >= r0) & (rows < r1) & (cols >= c0) & (cols < c1)
    return ~jnp.any(occluded, axis=-3)


def apply_masks(imgs: jax.Array, masks: jax.Array, fill: float = 0.5) -> jax.Array:
    """Occlude images with a set of masks: `img*m + fill*(1-m)`.

    imgs: `[B, H, W, C]` (NHWC, TPU-native); masks: `[N, H, W]` boolean.
    Returns `[B, N, H, W, C]`. Mirrors `PatchCleanser.mask`
    (`PatchCleanser.py:99-100`) and the attack's fused mask-apply
    (`attack.py:206`).
    """
    m = masks[None, :, :, :, None].astype(imgs.dtype)
    return imgs[:, None] * m + fill * (1.0 - m)
