"""Data pipeline (the reference's loader layer, `/root/reference/utils.py:81-102`).

Replicates Resize(img/0.875) -> CenterCrop(img) -> [0,1] float, shuffled with
a seed, yielding NHWC numpy batches — without torchvision (absent in this
environment). Sources:

- `synthetic`: deterministic random images + labels, so every pipeline stage
  runs without datasets on disk (tests, benchmarks);
- `cifar10` / `cifar100`: the standard python-pickle batch files under
  `<data_dir>/<name>/cifar-10-batches-py` / `cifar-100-python` (32px images
  are resized like the reference does);
- `imagenet`: `<data_dir>/imagenet/val/<wnid>/*.JPEG` folder layout via PIL.
"""

from __future__ import annotations

import os
import pickle
import time
from typing import Iterator, Optional, Sequence, Tuple

import numpy as np

from dorpatch_tpu.config import NUM_CLASSES


def batch_buckets(max_batch: int) -> Tuple[int, ...]:
    """Fixed batch-size buckets up to (and including) `max_batch`:
    1, 8, 32, 128, ... (x4 past 8), e.g. max_batch=32 -> (1, 8, 32).

    Shared by the batching layers (`defense.PatchCleanser.robust_predict`,
    `serve/`): a jitted program compiled per *bucket* instead of per exact
    batch size compiles O(log B) times total and never retraces on the
    ragged final batch a data stream or a correctness filter produces. The
    cost is padded rows (wasted forwards) bounded by the bucket spacing —
    at most 7/8 of a batch in the worst case, typically far less."""
    if max_batch < 1:
        raise ValueError(f"max_batch must be >= 1, got {max_batch}")
    out = {1, int(max_batch)}
    b = 8
    while b < max_batch:
        out.add(b)
        b *= 4
    return tuple(sorted(out))


def bucket_batch(n: int, buckets: Sequence[int]) -> int:
    """Smallest bucket >= n (the padded batch size a ragged batch rounds up
    to). `n` must not exceed the largest bucket."""
    for b in sorted(buckets):
        if b >= n:
            return int(b)
    raise ValueError(f"batch of {n} exceeds the largest bucket "
                     f"{max(buckets)} (buckets {tuple(sorted(buckets))})")


def bucket_plan(n: int, buckets: Sequence[int]):
    """Greedy cover of `n` items by bucket-shaped program calls:
    `[(offset, count, bucket), ...]` — full buckets largest-first, then one
    padded tail call (`count <= bucket`).

    `bucket_batch` alone rounds a whole ragged batch up to ONE bucket,
    which is the right trade for the primary image batch (one dispatch)
    but pathological for worklists that may far exceed the next bucket
    boundary — e.g. 34 ragged second-round rows would pad straight to a
    128-bucket (3.7x wasted forwards), while this plan covers them as
    32 + 8 (6 padded slots). The smallest rung is never used for greedy
    decomposition below the largest bucket (that would shred remainders
    into batch-1 dispatches); once no LARGER bucket fits, the remainder
    goes out as one padded tail, so waste is bounded by the bucket the
    tail rounds up to — independent of `n`. Unlike `bucket_batch`, `n`
    may exceed the largest bucket (the plan just emits more full-bucket
    calls)."""
    bs = sorted(int(b) for b in buckets)
    plan = []
    pos = 0
    while n - pos >= bs[-1]:
        plan.append((pos, bs[-1], bs[-1]))
        pos += bs[-1]
    rem = n - pos
    if not rem:
        return plan
    greedy, gpos, grem = [], pos, rem
    while grem:
        full = [b for b in bs if b <= grem and b > bs[0]]
        if full:
            b = max(full)
            greedy.append((gpos, b, b))
            gpos, grem = gpos + b, grem - b
        else:
            greedy.append((gpos, grem, bucket_batch(grem, bs)))
            grem = 0
    # a single padded call wins on slot ties (one dispatch beats several):
    # e.g. 31 over (1, 8, 32) ships as one 32-bucket, not four 8s
    single_bucket = bucket_batch(rem, bs)
    if single_bucket <= sum(b for _, _, b in greedy):
        plan.append((pos, rem, single_bucket))
    else:
        plan.extend(greedy)
    return plan


def shard_bucket_plan(counts: Sequence[int], buckets: Sequence[int]):
    """Per-shard greedy bucket cover for S parallel worklists dispatched as
    ONE `[S * bucket]` program call per wave:
    `[(offset, per_shard_counts, bucket), ...]`.

    The meshed two-phase certify scheduler plans each shard's phase-2
    worklist shard-locally but must dispatch a single SPMD program per
    wave (every shard participates in every call). The wave ladder is
    therefore `bucket_plan` applied to the LONGEST worklist — the wave
    loop runs until the busiest shard drains — and every other shard
    contributes `min(bucket, its remaining entries)` real rows to the same
    wave, its leftover slots being padding the caller fills with a
    replicated owned row. Wave shapes depend only on the ladder and the
    max count, never on the per-shard skew: a worklist where one shard
    holds every entry and the rest hold none compiles exactly the same
    programs as the balanced case."""
    counts = [int(c) for c in counts]
    if not counts:
        return []
    return [(off, tuple(max(0, min(cnt, c - off)) for c in counts), bucket)
            for off, cnt, bucket in bucket_plan(max(counts), buckets)]


def pad_to_bucket(arr, bucket: int):
    """Pad axis 0 up to `bucket` rows by repeating the first row. Every
    consumer's verdict is a pure per-row function of its tables, so padded
    rows cannot perturb real rows — callers slice them back out."""
    n = int(arr.shape[0])
    if n >= bucket:
        return arr
    if isinstance(arr, np.ndarray):
        fill = np.broadcast_to(arr[:1], (bucket - n,) + arr.shape[1:])
        return np.concatenate([arr, fill], axis=0)
    import jax.numpy as jnp
    fill = jnp.broadcast_to(arr[:1], (bucket - n,) + tuple(arr.shape[1:]))
    return jnp.concatenate([arr, fill], axis=0)


# ------------------------------------------------------ streaming input path
#
# The production-224 input path: at 224px a disk batch costs real wall time
# (PIL decode + bilinear resize per image), and the synchronous loop above
# serializes that behind the accelerator — the certify sweep idles while the
# host decodes, then the host idles while the sweep runs. The two pieces
# below overlap the three stages (disk/decode, host->device transfer,
# compute) with bounded memory:
#
#   `stream_batches`     — chunked background reader: the underlying batch
#                          iterator runs on a worker thread into a bounded
#                          queue (order-preserving, clean shutdown), so
#                          decode of batch N+1 overlaps compute of batch N;
#   `prefetch_to_device` — double-buffered device prefetch: issues the
#                          (asynchronous) `jax.device_put` / per-shard
#                          `place_batch_auto` for batch N+1 before yielding
#                          batch N, so the PCIe transfer also overlaps
#                          compute.
#
# `streaming_batches` composes both over `dataset_batches`; consumed by the
# pipeline's eval loop, serve warmup and the farm sweeps (gate:
# `ExperimentConfig.stream_depth`). Spans/events land in events.jsonl:
# `data.prefetch` marks each ahead-of-compute placement (its `batch` attr
# runs ahead of the consumer's `batch` span), `data.stream.wait` events
# record how long the consumer actually blocked on the loader thread.


def stream_batches(batches, depth: int = 2):
    """Run a host batch iterator on a daemon worker thread feeding a
    bounded queue (`depth` batches of lookahead); yields items in order.

    Errors raised by the underlying iterator surface on the consumer side.
    Closing the generator (or exhausting it) stops the worker promptly:
    the worker's blocked `put` polls a stop event, and shutdown drains the
    queue so the join cannot deadlock on a full queue."""
    import queue
    import threading

    from dorpatch_tpu import observe

    q: "queue.Queue" = queue.Queue(maxsize=max(1, int(depth)))
    stop = threading.Event()

    def put(item) -> bool:
        while not stop.is_set():
            try:
                q.put(item, timeout=0.05)
                return True
            except queue.Full:
                continue
        return False

    def worker():
        try:
            for item in batches:
                if not put(("batch", item)):
                    return
            put(("done", None))
        except BaseException as e:  # loader errors re-raise at the consumer
            put(("error", e))

    t = threading.Thread(target=worker, name="dorpatch-data-stream",
                         daemon=True)
    t.start()
    try:
        i = 0
        while True:
            t0 = time.perf_counter()
            kind, payload = q.get()
            wait = time.perf_counter() - t0
            if kind == "done":
                return
            if kind == "error":
                raise payload
            # near-zero wait = the worker kept ahead of compute (the
            # overlap evidence the report's streaming section reads)
            observe.record_event("data.stream.wait", batch=i,
                                 wait_s=round(wait, 6))
            i += 1
            yield payload
    finally:
        stop.set()
        while True:  # unblock a worker stuck on a full queue
            try:
                q.get_nowait()
            except queue.Empty:
                break
        t.join(timeout=5.0)


def prefetch_to_device(batches, depth: int = 2, mesh=None):
    """Double-buffered host->device prefetch over `(images, labels)` host
    batches: dispatches the asynchronous device placement for batch N+1
    before yielding batch N, so the transfer overlaps the consumer's
    compute. Yields `(images_on_device, labels_host)`.

    Placement matches the certify input-placement rule: `jax.device_put`
    single-chip, `parallel.place_batch_auto` on a mesh (per-shard when the
    data axis divides the batch, replicated otherwise) — so downstream
    re-placements are no-ops and jit cache keys match the warmed shapes."""
    import collections

    import jax
    import jax.numpy as jnp

    from dorpatch_tpu import observe

    def place(x_np):
        x = jnp.asarray(x_np)
        if mesh is not None:
            from dorpatch_tpu import parallel

            return parallel.place_batch_auto(mesh, x)
        return jax.device_put(x)

    depth = max(1, int(depth))
    buf: "collections.deque" = collections.deque()
    it = iter(batches)
    try:
        n = 0
        for x_np, y_np in it:
            # dispatch-only: device_put returns immediately, the copy
            # proceeds while the consumer computes on earlier batches
            with observe.span("data.prefetch", batch=n,
                              images=int(np.shape(x_np)[0]),
                              ahead=len(buf)):
                buf.append((place(x_np), y_np))
            n += 1
            if len(buf) >= depth:
                yield buf.popleft()
        while buf:
            yield buf.popleft()
    finally:
        buf.clear()
        close = getattr(it, "close", None)
        if close is not None:
            close()


def streaming_batches(
    dataset: str,
    data_dir: str,
    batch_size: int,
    img_size: int = 224,
    seed: int = 1234,
    source: Optional[str] = None,
    depth: int = 2,
    mesh=None,
) -> Iterator[Tuple["object", np.ndarray]]:
    """`dataset_batches` behind the streaming input path: background
    chunked reads + double-buffered device prefetch, `depth` batches of
    lookahead at each stage. Images arrive device-resident; labels stay
    host numpy."""
    return prefetch_to_device(
        stream_batches(
            dataset_batches(dataset, data_dir, batch_size, img_size, seed,
                            source=source),
            depth=depth),
        depth=depth, mesh=mesh)


def _resize_center_crop(img: "np.ndarray", size: int) -> np.ndarray:
    """PIL bilinear resize of the short side to size/0.875, center crop."""
    from PIL import Image

    resize_to = int(size / 0.875)
    pil = Image.fromarray(img)
    w, h = pil.size
    scale = resize_to / min(w, h)
    pil = pil.resize((max(1, round(w * scale)), max(1, round(h * scale))), Image.BILINEAR)
    w, h = pil.size
    left, top = (w - size) // 2, (h - size) // 2
    pil = pil.crop((left, top, left + size, top + size))
    return np.asarray(pil, dtype=np.float32) / 255.0


def synthetic_batches(
    dataset: str, batch_size: int, img_size: int, seed: int = 1234
) -> Iterator[Tuple[np.ndarray, np.ndarray]]:
    """Deterministic synthetic stream: smooth random images (so TV losses see
    structure) with random labels."""
    rng = np.random.default_rng(seed)
    n_classes = NUM_CLASSES[dataset]
    while True:
        low = rng.uniform(0, 1, (batch_size, 8, 8, 3)).astype(np.float32)
        imgs = np.stack([
            _resize_center_crop((lo * 255).astype(np.uint8), img_size) for lo in low
        ])
        labels = rng.integers(0, n_classes, batch_size)
        yield imgs, labels.astype(np.int64)


def procedural_arrays(
    dataset: str,
    n_per_class: int,
    img_size: int = 32,
    seed: int = 1234,
    split: str = "train",
) -> Tuple[np.ndarray, np.ndarray]:
    """Deterministic procedurally-generated labeled images (NHWC [0,1], int64).

    Purpose: this environment ships no datasets and has no network, so
    "train the victim to real accuracy" (round-3 verdict) cannot use real
    CIFAR-10. This generator produces a *learnable but nontrivial* 10-way
    task instead: class = (orientation bucket x {linear, radial}) of a
    sinusoidal grating, with per-sample random phase, frequency, center,
    per-channel gain/base colors, a smooth background gradient, and additive
    Gaussian noise. An untrained net scores ~chance; a trained net has real
    decision boundaries — which is what makes attacking and certifying
    against it scientifically meaningful.

    Splits draw from disjoint seed streams; labels are genuine (generative),
    unlike `synthetic_batches`' random labels.
    """
    n_classes = NUM_CLASSES[dataset]
    # class geometry: n_orient orientation buckets x {linear, radial}. The
    # buckets must stay separated well past the per-sample angle jitter
    # (sd 0.06 rad); past ~20 classes neighboring orientations overlap and
    # the "genuine labels" premise silently breaks — refuse rather than
    # generate an unlearnable task (imagenet's 1000 classes would also
    # allocate ~60 GB here).
    if n_classes > 20:
        raise ValueError(
            f"procedural task supports <= 20 classes, got {n_classes} "
            f"({dataset!r}); use dataset='cifar10'")
    n_orient = (n_classes + 1) // 2
    rng = np.random.default_rng([seed, {"train": 0, "test": 1}[split]])
    n = n_classes * n_per_class
    labels = np.repeat(np.arange(n_classes), n_per_class).astype(np.int64)
    rng.shuffle(labels)

    u, v = np.meshgrid(
        np.linspace(0.0, 1.0, img_size, dtype=np.float32),
        np.linspace(0.0, 1.0, img_size, dtype=np.float32),
        indexing="xy",
    )
    imgs = np.empty((n, img_size, img_size, 3), np.float32)
    for i0 in range(0, n, 512):
        lab = labels[i0:i0 + 512]
        m = lab.shape[0]
        theta = (lab % n_orient) * (np.pi / n_orient) + rng.normal(0.0, 0.06, m)
        ct, st = np.cos(theta)[:, None, None], np.sin(theta)[:, None, None]
        radial = (lab >= n_orient)[:, None, None]
        cx = rng.uniform(0.3, 0.7, (m, 1, 1)).astype(np.float32)
        cy = rng.uniform(0.3, 0.7, (m, 1, 1)).astype(np.float32)
        du, dv = u[None] - cx, v[None] - cy
        s_lin = u[None] * ct + v[None] * st
        s_rad = np.sqrt((du * ct + dv * st) ** 2
                        + 3.0 * (-du * st + dv * ct) ** 2)
        s = np.where(radial, s_rad, s_lin)
        freq = rng.uniform(3.5, 6.5, (m, 1, 1))
        phase = rng.uniform(0.0, 2 * np.pi, (m, 1, 1))
        t = np.cos(2 * np.pi * freq * s + phase)[..., None]       # [m,H,W,1]
        gain = rng.uniform(0.25, 0.45, (m, 1, 1, 3))
        base = rng.uniform(0.35, 0.65, (m, 1, 1, 3))
        w = rng.normal(0.0, 1.0, (m, 2, 1, 1, 1))
        bg = 0.08 * (w[:, 0] * u[None, ..., None] + w[:, 1] * v[None, ..., None])
        out = base + gain * t + bg + rng.normal(0.0, 0.07, t.shape)
        imgs[i0:i0 + 512] = np.clip(out, 0.0, 1.0)
    return imgs, labels


def procedural_batches(
    dataset: str,
    batch_size: int,
    img_size: int,
    seed: int = 1234,
    split: str = "test",
    n_per_class: int = 100,
) -> Iterator[Tuple[np.ndarray, np.ndarray]]:
    """Shuffled batches of the procedural task's held-out split (the eval
    stream the trained-victim flagship protocol attacks)."""
    imgs, labels = procedural_arrays(dataset, n_per_class, img_size, seed, split)
    rng = np.random.default_rng(seed)
    order = rng.permutation(len(imgs))
    for i in range(0, len(order), batch_size):
        sel = order[i:i + batch_size]
        yield imgs[sel], labels[sel]


def training_arrays(
    dataset: str,
    source: str,
    data_dir: str = "data/",
    n_per_class: int = 1500,
    img_size: int = 32,
    seed: int = 1234,
    split: str = "train",
) -> Tuple[np.ndarray, np.ndarray]:
    """Whole-split arrays for victim training: NHWC float32 [0,1] + int64.

    source="procedural" generates the labeled task offline; source="disk"
    loads real CIFAR batches (train split = `data_batch_1..5` / `train`,
    the reference's `train=True` path, `/root/reference/utils.py:81-102`).
    CIFAR images stay at their native 32px here — `train.py` trains at
    img_size 32, so no eval-style resize/crop is applied."""
    if source == "procedural":
        return procedural_arrays(dataset, n_per_class, img_size, seed, split)
    if source == "disk":
        if dataset not in ("cifar10", "cifar100"):
            raise ValueError(
                f"disk training data supports cifar only, got {dataset!r}")
        if img_size != 32:
            raise ValueError("disk cifar training is native-32px only")
        imgs, labels = _load_cifar(data_dir, dataset, split)
        return imgs.astype(np.float32) / 255.0, labels
    raise ValueError(f"unknown training data source {source!r}")


def _load_cifar(data_dir: str, name: str, split: str = "test"):
    """uint8 NHWC images + int64 labels from the standard pickle batches.

    split="train" reads the training batches (`data_batch_1..5` for cifar10,
    `train` for cifar100), mirroring the reference's `train=True` loaders
    (`/root/reference/utils.py:81-102`); missing cifar10 train batches are
    skipped so a partial download still loads (at least one must exist)."""
    if name == "cifar10":
        base = os.path.join(data_dir, name, "cifar-10-batches-py")
        if split == "train":
            paths = [p for i in range(1, 6)
                     if os.path.exists(p := os.path.join(base, f"data_batch_{i}"))]
            if not paths:
                raise FileNotFoundError(f"no data_batch_* under {base}")
        else:
            paths = [os.path.join(base, "test_batch")]
        label_key = b"labels"
    else:
        base = os.path.join(data_dir, name, "cifar-100-python")
        paths = [os.path.join(base, "train" if split == "train" else "test")]
        label_key = b"fine_labels"
    imgs, labels = [], []
    for p in paths:
        with open(p, "rb") as f:
            d = pickle.load(f, encoding="bytes")
        imgs.append(d[b"data"].reshape(-1, 3, 32, 32).transpose(0, 2, 3, 1))
        labels.extend(d[label_key])
    return np.concatenate(imgs), np.asarray(labels, np.int64)


def _imagenet_val_entries(data_dir: str):
    root = os.path.join(data_dir, "imagenet", "val")
    classes = sorted(d for d in os.listdir(root) if os.path.isdir(os.path.join(root, d)))
    entries = []
    for ci, cname in enumerate(classes):
        cdir = os.path.join(root, cname)
        for fname in sorted(os.listdir(cdir)):
            entries.append((os.path.join(cdir, fname), ci))
    return entries


def dataset_batches(
    dataset: str,
    data_dir: str,
    batch_size: int,
    img_size: int = 224,
    seed: int = 1234,
    synthetic: bool = False,
    source: Optional[str] = None,
) -> Iterator[Tuple[np.ndarray, np.ndarray]]:
    """Shuffled eval-split batches, NHWC float32 in [0,1] (the reference's
    `get_dataset` with shuffle=True and the eval transform).

    source: "disk" | "synthetic" | "procedural" (None = disk unless
    `synthetic`). "procedural" yields the generated task's held-out split
    with genuine labels (see `procedural_arrays`) — fixed at 100 images
    per class (a 1000-image eval split for cifar10); callers needing a
    different size use `procedural_batches` directly. Train-split loading
    for victim training goes through `training_arrays`, not this stream."""
    source = source or ("synthetic" if synthetic else "disk")
    if source == "synthetic":
        yield from synthetic_batches(dataset, batch_size, img_size, seed)
        return
    if source == "procedural":
        yield from procedural_batches(dataset, batch_size, img_size, seed,
                                      split="test")
        return
    if source != "disk":
        raise ValueError(f"unknown data source {source!r}")

    rng = np.random.default_rng(seed)
    if dataset in ("cifar10", "cifar100"):
        imgs, labels = _load_cifar(data_dir, dataset)
        order = rng.permutation(len(imgs))
        for i in range(0, len(order), batch_size):
            sel = order[i:i + batch_size]
            batch = np.stack([_resize_center_crop(imgs[j], img_size) for j in sel])
            yield batch, labels[sel]
    elif dataset == "imagenet":
        from PIL import Image

        entries = _imagenet_val_entries(data_dir)
        order = rng.permutation(len(entries))
        for i in range(0, len(order), batch_size):
            sel = order[i:i + batch_size]
            batch, labs = [], []
            for j in sel:
                path, lab = entries[j]
                img = np.asarray(Image.open(path).convert("RGB"))
                batch.append(_resize_center_crop(img, img_size))
                labs.append(lab)
            yield np.stack(batch), np.asarray(labs, np.int64)
    else:
        raise ValueError(f"unknown dataset {dataset!r}")
