"""Data pipeline (the reference's loader layer, `/root/reference/utils.py:81-102`).

Replicates Resize(img/0.875) -> CenterCrop(img) -> [0,1] float, shuffled with
a seed, yielding NHWC numpy batches — without torchvision (absent in this
environment). Sources:

- `synthetic`: deterministic random images + labels, so every pipeline stage
  runs without datasets on disk (tests, benchmarks);
- `cifar10` / `cifar100`: the standard python-pickle batch files under
  `<data_dir>/<name>/cifar-10-batches-py` / `cifar-100-python` (32px images
  are resized like the reference does);
- `imagenet`: `<data_dir>/imagenet/val/<wnid>/*.JPEG` folder layout via PIL.
"""

from __future__ import annotations

import os
import pickle
from typing import Iterator, Tuple

import numpy as np

from dorpatch_tpu.config import NUM_CLASSES


def _resize_center_crop(img: "np.ndarray", size: int) -> np.ndarray:
    """PIL bilinear resize of the short side to size/0.875, center crop."""
    from PIL import Image

    resize_to = int(size / 0.875)
    pil = Image.fromarray(img)
    w, h = pil.size
    scale = resize_to / min(w, h)
    pil = pil.resize((max(1, round(w * scale)), max(1, round(h * scale))), Image.BILINEAR)
    w, h = pil.size
    left, top = (w - size) // 2, (h - size) // 2
    pil = pil.crop((left, top, left + size, top + size))
    return np.asarray(pil, dtype=np.float32) / 255.0


def synthetic_batches(
    dataset: str, batch_size: int, img_size: int, seed: int = 1234
) -> Iterator[Tuple[np.ndarray, np.ndarray]]:
    """Deterministic synthetic stream: smooth random images (so TV losses see
    structure) with random labels."""
    rng = np.random.default_rng(seed)
    n_classes = NUM_CLASSES[dataset]
    while True:
        low = rng.uniform(0, 1, (batch_size, 8, 8, 3)).astype(np.float32)
        imgs = np.stack([
            _resize_center_crop((lo * 255).astype(np.uint8), img_size) for lo in low
        ])
        labels = rng.integers(0, n_classes, batch_size)
        yield imgs, labels.astype(np.int64)


def _load_cifar(data_dir: str, name: str):
    if name == "cifar10":
        base = os.path.join(data_dir, name, "cifar-10-batches-py")
        paths = [os.path.join(base, "test_batch")]
        label_key = b"labels"
    else:
        base = os.path.join(data_dir, name, "cifar-100-python")
        paths = [os.path.join(base, "test")]
        label_key = b"fine_labels"
    imgs, labels = [], []
    for p in paths:
        with open(p, "rb") as f:
            d = pickle.load(f, encoding="bytes")
        imgs.append(d[b"data"].reshape(-1, 3, 32, 32).transpose(0, 2, 3, 1))
        labels.extend(d[label_key])
    return np.concatenate(imgs), np.asarray(labels, np.int64)


def _imagenet_val_entries(data_dir: str):
    root = os.path.join(data_dir, "imagenet", "val")
    classes = sorted(d for d in os.listdir(root) if os.path.isdir(os.path.join(root, d)))
    entries = []
    for ci, cname in enumerate(classes):
        cdir = os.path.join(root, cname)
        for fname in sorted(os.listdir(cdir)):
            entries.append((os.path.join(cdir, fname), ci))
    return entries


def dataset_batches(
    dataset: str,
    data_dir: str,
    batch_size: int,
    img_size: int = 224,
    seed: int = 1234,
    synthetic: bool = False,
) -> Iterator[Tuple[np.ndarray, np.ndarray]]:
    """Shuffled eval-split batches, NHWC float32 in [0,1] (the reference's
    `get_dataset` with shuffle=True and the eval transform)."""
    if synthetic:
        yield from synthetic_batches(dataset, batch_size, img_size, seed)
        return

    rng = np.random.default_rng(seed)
    if dataset in ("cifar10", "cifar100"):
        imgs, labels = _load_cifar(data_dir, dataset)
        order = rng.permutation(len(imgs))
        for i in range(0, len(order), batch_size):
            sel = order[i:i + batch_size]
            batch = np.stack([_resize_center_crop(imgs[j], img_size) for j in sel])
            yield batch, labels[sel]
    elif dataset == "imagenet":
        from PIL import Image

        entries = _imagenet_val_entries(data_dir)
        order = rng.permutation(len(entries))
        for i in range(0, len(order), batch_size):
            sel = order[i:i + batch_size]
            batch, labs = [], []
            for j in sel:
                path, lab = entries[j]
                img = np.asarray(Image.open(path).convert("RGB"))
                batch.append(_resize_center_crop(img, img_size))
                labs.append(lab)
            yield np.stack(batch), np.asarray(labs, np.int64)
    else:
        raise ValueError(f"unknown dataset {dataset!r}")
