"""Hand-fused TPU ops (Pallas) for the framework's hot inner-loop primitives."""

from dorpatch_tpu.ops.fused_gn import gn_relu, gn_relu_reference
from dorpatch_tpu.ops.masked_fill import masked_fill, masked_fill_reference

__all__ = ["gn_relu", "gn_relu_reference", "masked_fill", "masked_fill_reference"]
