"""Hand-fused TPU ops (Pallas) for the framework's hot inner-loop primitives."""

from dorpatch_tpu.ops.masked_fill import masked_fill, masked_fill_reference

__all__ = ["masked_fill", "masked_fill_reference"]
