"""Shared backend detection for the Pallas dispatch heuristics."""

from __future__ import annotations

import jax


def is_tpu_backend() -> bool:
    """True when the default backend compiles Pallas Mosaic kernels
    ("tpu" proper, or the remote-tunneled "axon" TPU platform)."""
    try:
        return jax.default_backend() in ("tpu", "axon")
    except Exception:
        return False
