"""Shared backend detection + `use_pallas` gate resolution.

Every Pallas op in the package (`masked_fill`, `fused_gn`, the stem
delta-conv and masked-KV attention kernels) dispatches behind the same
four-valued gate — `"auto" | "on" | "off" | "interpret"` — and the "auto"
heuristic is identical across them: Mosaic kernels only lower on TPU
backends, and a raw `pallas_call` is a custom call GSPMD cannot partition,
so on multi-device platforms "auto" engages Pallas only when the caller
brings an explicit mesh (the op's `shard_map` path). This module is the
single implementation of that rule; per-op feasibility checks (VMEM plans,
mesh divisibility) layer on top via the `divisible` argument.
"""

from __future__ import annotations

import jax


def is_tpu_backend() -> bool:
    """True when the default backend compiles Pallas Mosaic kernels
    ("tpu" proper, or the remote-tunneled "axon" TPU platform)."""
    try:
        return jax.default_backend() in ("tpu", "axon")
    except Exception:
        return False


def single_device_tpu() -> bool:
    """True when a bare (shard_map-free) `pallas_call` is safe AND lowers:
    a TPU backend with exactly one device, so GSPMD never has to partition
    the custom call."""
    return is_tpu_backend() and jax.device_count() == 1


def get_shard_map():
    """The `shard_map` entry point plus the kwargs that disable its
    replication checker, across the jax versions this package supports.

    Every sharded Pallas wrapper in the package (`masked_fill`, the
    stem-fold and masked-KV attention kernels) builds its per-shard body
    the same way; this is the one place the version split lives. Returns
    `(shard_map, kwargs)` — splat `kwargs` into every call."""
    try:
        # jax >= 0.6: public API; the replication check kwarg is check_vma
        from jax import shard_map
        return shard_map, {"check_vma": False}
    except ImportError:
        # jax 0.4.x: experimental API, same semantics, kwarg is check_rep
        from jax.experimental.shard_map import shard_map
        return shard_map, {"check_rep": False}


def resolve_use_pallas(use_pallas: str = "auto", *, mesh=None,
                       divisible: bool = True) -> str:
    """Resolve the shared `use_pallas` gate to `"on" | "off" | "interpret"`.

    - "auto" -> "on" iff the backend is a TPU and either the caller passed
      a multi-device `mesh` (the op runs its Pallas kernel per shard under
      `shard_map`) or the platform has a single device (a bare
      `pallas_call` cannot block sharding propagation there). "off"
      otherwise — CPU tests and virtual meshes take the XLA reference
      path.
    - Any non-"off" request on a multi-device mesh whose shapes the op
      cannot shard (`divisible=False`, e.g. `masked_fill._mesh_divides`)
      falls back to "off": the partitionable XLA path beats a replicated
      custom call.
    - "on"/"off"/"interpret" pass through (modulo the divisibility
      fallback); anything else raises.
    """
    on_mesh = (mesh is not None
               and getattr(mesh, "devices", None) is not None
               and mesh.devices.size > 1)
    if use_pallas == "auto":
        single = jax.device_count() == 1
        use_pallas = ("on" if is_tpu_backend() and (on_mesh or single)
                      else "off")
    if use_pallas not in ("on", "off", "interpret"):
        raise ValueError(f"use_pallas={use_pallas!r}")
    if use_pallas != "off" and on_mesh and not divisible:
        use_pallas = "off"
    return use_pallas
