"""Fused rasterize + occlusion-fill: the framework's hottest memory-bound op.

Every hot path funnels images through "occlude with mask m, gray-fill the
rest": the attack's EOT forward applies 128 sampled occlusion masks per step
(`/root/reference/attack.py:206`), the failure sweep applies all 2520
(`attack.py:384-406`), and PatchCleanser applies its 36+630 certification
masks (`defenses/PatchCleanser.py:99-100`). The reference materializes the
boolean mask tensors once on the GPU and broadcasts; our jnp path
(`masks.rasterize` + `masks.apply_masks`) rasterizes coordinate rectangles
on device and lets XLA fuse.

This module goes one step further on TPU: a Pallas kernel that rasterizes
each rectangle set *inside* the kernel from its (r0, r1, c0, c1) corners
(scalar-prefetched to SMEM) and writes the occluded image directly — the
[S,H,W] mask tensor never exists in HBM, and the op reads `imgs` once and
writes the [B,S,H,W,C] output once, the bandwidth lower bound. Images are
viewed as [B, H, W*C] so the lane axis is W*C (672 @224, a 5.25×128-lane
tile — fine for the VPU); the rasterizer compares row/lane iotas against
the corners, with lane→column mapping `w = lane // C`.

A `jax.custom_vjp` makes the op differentiable w.r.t. `imgs` (the attack
backprops through the fill to the patch): the backward kernel accumulates
`g * keep_mask` over the mask axis per image, again without materializing
masks. Rectangle coordinates and the fill value are non-differentiable.

`masked_fill` dispatches: Pallas on TPU backends, the jnp reference path
elsewhere (CPU tests, virtual meshes) — numerically identical (pure
select, no arithmetic on the kept pixels).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from dorpatch_tpu import masks as masks_lib
from dorpatch_tpu.ops import _backend


def masked_fill_reference(imgs: jax.Array, rects: jax.Array, fill: float) -> jax.Array:
    """jnp reference: `[B,H,W,C] x [S,K,4] -> [B,S,H,W,C]`."""
    m = masks_lib.rasterize(rects, imgs.shape[1])
    return masks_lib.apply_masks(imgs, m, fill)


# ---------------------------------------------------------------- kernels


def _fwd_kernel(n_rect: int, channels: int, fill: float, rects_ref, img_ref, out_ref):
    h, wc = img_ref.shape[1], img_ref.shape[2]
    s = pl.program_id(1)
    rows = jax.lax.broadcasted_iota(jnp.int32, (1, h, wc), 1)
    cols = jax.lax.broadcasted_iota(jnp.int32, (1, h, wc), 2) // channels
    occluded = jnp.zeros((1, h, wc), jnp.bool_)
    for k in range(n_rect):  # K is tiny (1-3): unrolled
        r0, r1 = rects_ref[s, k, 0], rects_ref[s, k, 1]
        c0, c1 = rects_ref[s, k, 2], rects_ref[s, k, 3]
        occluded |= (rows >= r0) & (rows < r1) & (cols >= c0) & (cols < c1)
    out_ref[0] = jnp.where(occluded, jnp.asarray(fill, img_ref.dtype), img_ref[...])


def _bwd_kernel(n_rect: int, channels: int, rects_ref, g_ref, out_ref):
    h, wc = g_ref.shape[2], g_ref.shape[3]
    s = pl.program_id(1)
    rows = jax.lax.broadcasted_iota(jnp.int32, (1, h, wc), 1)
    cols = jax.lax.broadcasted_iota(jnp.int32, (1, h, wc), 2) // channels
    occluded = jnp.zeros((1, h, wc), jnp.bool_)
    for k in range(n_rect):
        r0, r1 = rects_ref[s, k, 0], rects_ref[s, k, 1]
        c0, c1 = rects_ref[s, k, 2], rects_ref[s, k, 3]
        occluded |= (rows >= r0) & (rows < r1) & (cols >= c0) & (cols < c1)
    contrib = jnp.where(occluded, jnp.zeros((), g_ref.dtype), g_ref[0])

    @pl.when(s == 0)
    def _():
        out_ref[...] = contrib

    @pl.when(s != 0)
    def _():
        out_ref[...] = out_ref[...] + contrib


def _pallas_fwd(imgs: jax.Array, rects: jax.Array, fill: float, interpret: bool) -> jax.Array:
    b, h, w, c = imgs.shape
    n_mask, n_rect = rects.shape[0], rects.shape[1]
    flat = imgs.reshape(b, h, w * c)
    out = pl.pallas_call(
        functools.partial(_fwd_kernel, n_rect, c, fill),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=(b, n_mask),
            in_specs=[
                pl.BlockSpec((1, h, w * c), lambda i, s, rects: (i, 0, 0)),
            ],
            out_specs=pl.BlockSpec((1, 1, h, w * c), lambda i, s, rects: (i, s, 0, 0)),
        ),
        out_shape=jax.ShapeDtypeStruct((b, n_mask, h, w * c), imgs.dtype),
        interpret=interpret,
    )(jnp.asarray(rects, jnp.int32), flat)
    return out.reshape(b, n_mask, h, w, c)


def _pallas_bwd(rects: jax.Array, g: jax.Array, interpret: bool) -> jax.Array:
    b, n_mask, h, w, c = g.shape
    n_rect = rects.shape[1]
    flat = g.reshape(b, n_mask, h, w * c)
    out = pl.pallas_call(
        functools.partial(_bwd_kernel, n_rect, c),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            # mask axis iterates minor-to-major last → sequential per image,
            # making the out-block accumulation across s well-defined
            grid=(b, n_mask),
            in_specs=[
                pl.BlockSpec((1, 1, h, w * c), lambda i, s, rects: (i, s, 0, 0)),
            ],
            out_specs=pl.BlockSpec((1, h, w * c), lambda i, s, rects: (i, 0, 0)),
        ),
        out_shape=jax.ShapeDtypeStruct((b, h, w * c), g.dtype),
        interpret=interpret,
    )(jnp.asarray(rects, jnp.int32), flat)
    return out.reshape(b, h, w, c)


# ------------------------------------------------------------- custom vjp


@functools.partial(jax.custom_vjp, nondiff_argnums=(2, 3))
def _masked_fill_pallas(imgs, rects, fill: float, interpret: bool):
    return _pallas_fwd(imgs, rects, fill, interpret)


def _vjp_fwd(imgs, rects, fill: float, interpret: bool):
    return _pallas_fwd(imgs, rects, fill, interpret), rects


def _vjp_bwd(fill: float, interpret: bool, rects, g):
    return _pallas_bwd(rects, g, interpret), None


_masked_fill_pallas.defvjp(_vjp_fwd, _vjp_bwd)


# --------------------------------------------------------- shard_map wrapper


@functools.lru_cache(maxsize=None)
def _sharded_masked_fill_fn(fill: float, interpret: bool, mesh,
                            data_axis: str, mask_axis: str):
    """A mesh-partitioned `masked_fill`: the Pallas kernel runs per shard
    under `shard_map`, so a multi-chip mesh keeps the fused op instead of
    falling back to the XLA rasterize path.

    A raw `pallas_call` is a Mosaic custom call that GSPMD cannot partition —
    it would stop mask-axis sharding propagation and replicate the step's
    largest tensor per chip. `shard_map` sidesteps GSPMD entirely: images
    shard over the data axis (replicated over mask), rectangle sets shard
    over the mask axis, each device rasterizes+fills only its `[B/d, S/m]`
    block, and the output carries the `(data, mask)` sharding the EOT
    forward wants. The backward kernel accumulates per-shard image cotangents
    and `psum`s them over the mask axis — the one collective this op needs.
    """
    shard_map, sm_kwargs = _backend.get_shard_map()
    from jax.sharding import PartitionSpec as P

    im_spec = P(data_axis)             # [B,H,W,C]: data-sharded, mask-replicated
    rc_spec = P(mask_axis)             # [S,K,4]: mask-sharded
    out_spec = P(data_axis, mask_axis)  # [B,S,H,W,C]

    fwd_sm = shard_map(
        lambda im, rc: _pallas_fwd(im, rc, fill, interpret),
        mesh=mesh, in_specs=(im_spec, rc_spec), out_specs=out_spec,
        **sm_kwargs,
    )
    bwd_sm = shard_map(
        lambda rc, g: jax.lax.psum(_pallas_bwd(rc, g, interpret), mask_axis),
        mesh=mesh, in_specs=(rc_spec, out_spec), out_specs=im_spec,
        **sm_kwargs,
    )

    @jax.custom_vjp
    def f(imgs, rects):
        return fwd_sm(imgs, rects)

    def f_fwd(imgs, rects):
        return fwd_sm(imgs, rects), rects

    def f_bwd(rects, g):
        return bwd_sm(rects, g), None

    f.defvjp(f_fwd, f_bwd)
    return f


def _mesh_divides(imgs, rects, mesh, data_axis: str, mask_axis: str) -> bool:
    return (imgs.shape[0] % mesh.shape[data_axis] == 0
            and rects.shape[0] % mesh.shape[mask_axis] == 0)


def masked_fill(
    imgs: jax.Array,
    rects: jax.Array,
    fill: float = 0.5,
    use_pallas: str = "auto",
    mesh=None,
    data_axis: str = "data",
    mask_axis: str = "mask",
) -> jax.Array:
    """Occlude `imgs` with every rectangle set in `rects`, filling with `fill`.

    imgs `[B,H,W,C]`, rects `[S,K,4]` int32 rows `(r0, r1, c0, c1)`
    (half-open; zero-area rows are no-ops, matching `masks.pad_rects`).
    Returns `[B,S,H,W,C]`. Differentiable w.r.t. `imgs`.

    use_pallas: "auto" (Pallas on TPU backends, XLA elsewhere), "on", "off",
    "interpret" (Pallas in interpreter mode — for CPU tests).

    mesh: a `jax.sharding.Mesh` with `(data_axis, mask_axis)` axes. On a
    multi-device mesh the Pallas path runs under `shard_map`
    (`_sharded_masked_fill_fn`); shapes the mesh does not divide fall back
    to the partitionable XLA path.
    """
    on_mesh = mesh is not None and mesh.devices.size > 1
    use_pallas = _backend.resolve_use_pallas(
        use_pallas, mesh=mesh,
        divisible=(not on_mesh) or _mesh_divides(imgs, rects, mesh,
                                                 data_axis, mask_axis))
    if use_pallas == "off":
        return masked_fill_reference(imgs, rects, fill)
    if on_mesh:
        f = _sharded_masked_fill_fn(
            float(fill), use_pallas == "interpret", mesh, data_axis, mask_axis)
        return f(imgs, rects)
    return _masked_fill_pallas(imgs, rects, float(fill), use_pallas == "interpret")
