"""Fused GroupNorm(32)+ReLU with a hand-written backward: the victim's
bandwidth sink.

ResNetV2-50 BiT runs 49 GroupNorm+ReLU pairs between its convs
(`models/resnetv2.py`, timm `GroupNormAct`). On-chip attribution
(`tools/profile_gn.py`, PERF.md round 3) shows XLA's GN costs only ~11% of
the victim *forward* but ~23% of the attack's fwd+bwd step — the autodiff
backward of the f32 normalize chain materializes several full-activation
float32 intermediates in HBM. The arithmetic is trivial; the traffic is not.

This module implements the pair as one Pallas kernel per direction with a
`jax.custom_vjp`:

- forward: one read of `x`, one write of `y`; per-(sample, group) statistics
  computed in VMEM (f32, fast-variance `E[x^2]-E[x]^2` clipped at 0 —
  exactly flax's `_compute_stats`), plus tiny `[N, G]` mean/rstd residual
  outputs.
- backward: reads `x`/`dy`, writes `dx` (the HBM lower bound), recomputing
  `xhat` and the ReLU gate from the saved statistics in-register. Parameter
  cotangents come out as per-sample `[N, C]` partials, summed outside the
  kernel (they are two orders of magnitude smaller than the slabs).

Group statistics never touch HBM mid-kernel: per-channel sums are reduced to
per-group sums with a tiny `[C, G]` one-hot matmul (MXU-friendly; lane axis
stays C), and broadcast back with its transpose.

Both directions take the whole [HW, C] slab per grid step when it fits
VMEM, and fall back to an HW-tiled two-pass variant when it does not
(`_pallas_fwd_tiled`: tiled group sums then tiled normalize;
`_pallas_bwd_tiled`: tiled stats accumulation then tiled dx — one extra
read of the streamed inputs as the price of fit). Admission is decided
per-shape and per-dtype by `auto_pallas`/`_fwd_plan`/`_bwd_plan` with
conservative double-buffered estimates; only slabs with no sublane-
aligned tiling (pathological HW factorizations) route to XLA.

`gn_relu` dispatches like `ops.masked_fill`: "auto" uses Pallas on a
single-device TPU backend and the jnp reference elsewhere. Under a
multi-device mesh the jnp path is kept deliberately: a raw `pallas_call` is
opaque to GSPMD and would block batch-sharding propagation through the
victim forward (see `ops/masked_fill.py` for the shard_map treatment the
EOT fill needed; the GN sits *inside* the model, where XLA's own fusion is
the partitionable choice).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl


def gn_relu_reference(x: jax.Array, scale: jax.Array, bias: jax.Array,
                      num_groups: int, eps: float = 1e-5) -> jax.Array:
    """jnp reference: flax `GroupNorm(dtype=f32)` + ReLU + cast to x.dtype.

    Mirrors flax's op ordering (`_normalize`): fast variance clipped at 0,
    `y = (x - mean) * (rsqrt(var + eps) * scale) + bias`.
    """
    dt = x.dtype
    n, h, w, c = x.shape
    g = num_groups
    gs = c // g
    xf = x.astype(jnp.float32).reshape(n, h * w, g, gs)
    mean = jnp.mean(xf, axis=(1, 3), keepdims=True)          # [N,1,G,1]
    msq = jnp.mean(xf * xf, axis=(1, 3), keepdims=True)
    var = jnp.maximum(msq - mean * mean, 0.0)
    mul = jax.lax.rsqrt(var + eps) * scale.astype(jnp.float32).reshape(1, 1, g, gs)
    y = (xf - mean) * mul + bias.astype(jnp.float32).reshape(1, 1, g, gs)
    return jax.nn.relu(y).reshape(n, h, w, c).astype(dt)


def gn_preserve_dtype(x: jax.Array, scale: jax.Array, bias: jax.Array,
                      num_groups: int, eps: float = 1e-5) -> jax.Array:
    """GroupNorm with float32 statistics but input-dtype elementwise math.

    flax's `nn.GroupNorm` materializes the whole normalization chain in
    float32 regardless of its `dtype=` argument. Inside a declared-bf16
    certify program that leaves the four largest per-GN intermediates at
    4 bytes/element, enough to push a conv victim's bf16 phase-1 bank
    *above* its f32 twin on `analysis.baseline.estimate_cost` bytes. Here
    only the statistics run in f32 (one upcast of `x` plus its square,
    both consumed by reductions XLA fuses away); the normalization
    `(x - mean) * mul + bias` stays at `x.dtype`. For float32 inputs this
    is exactly `gn_relu_reference`'s op ordering minus the ReLU, but
    model code keeps flax's own GroupNorm on the f32 path anyway so the
    f32 banks stay bit-identical to the seed (see `models/small.py`).
    """
    dt = x.dtype
    n, h, w, c = x.shape
    g = num_groups
    gs = c // g
    xg = x.reshape(n, h * w, g, gs)
    xf = xg.astype(jnp.float32)
    mean = jnp.mean(xf, axis=(1, 3), keepdims=True)
    msq = jnp.mean(xf * xf, axis=(1, 3), keepdims=True)
    var = jnp.maximum(msq - mean * mean, 0.0)
    mul = jax.lax.rsqrt(var + eps) * scale.astype(jnp.float32).reshape(1, 1, g, gs)
    bias_f = bias.astype(jnp.float32).reshape(1, 1, g, gs)
    y = (xg - mean.astype(dt)) * mul.astype(dt) + bias_f.astype(dt)
    return y.reshape(n, h, w, c)


# ---------------------------------------------------------------- kernels


def _group_matrices(c: int, g: int):
    """`gm [C, G]` one-hot channel->group; built from iotas in-kernel."""
    ch = jax.lax.broadcasted_iota(jnp.int32, (c, g), 0)
    gr = jax.lax.broadcasted_iota(jnp.int32, (c, g), 1)
    return (ch // (c // g) == gr).astype(jnp.float32)


def _fwd_kernel(g: int, eps: float, x_ref, s_ref, b_ref,
                y_ref, mean_ref, rstd_ref):
    xf = x_ref[0].astype(jnp.float32)                        # [HW, C]
    hw, c = xf.shape
    cnt = float(hw * (c // g))
    gm = _group_matrices(c, g)                               # [C, G]
    s1 = jnp.sum(xf, axis=0, keepdims=True)                  # [1, C]
    s2 = jnp.sum(xf * xf, axis=0, keepdims=True)
    mean_g = jnp.dot(s1, gm, preferred_element_type=jnp.float32) / cnt
    msq_g = jnp.dot(s2, gm, preferred_element_type=jnp.float32) / cnt
    var_g = jnp.maximum(msq_g - mean_g * mean_g, 0.0)
    rstd_g = jax.lax.rsqrt(var_g + eps)                      # [1, G]
    mean_c = jnp.dot(mean_g, gm.T, preferred_element_type=jnp.float32)
    mul_c = jnp.dot(rstd_g, gm.T, preferred_element_type=jnp.float32) * s_ref[...]
    y = (xf - mean_c) * mul_c + b_ref[...]
    y_ref[0] = jnp.maximum(y, 0.0).astype(y_ref.dtype)
    mean_ref[0] = mean_g
    rstd_ref[0] = rstd_g


def _bwd_kernel(g: int, x_ref, dy_ref, s_ref, b_ref, mean_ref, rstd_ref,
                dx_ref, ds_ref, db_ref):
    xf = x_ref[0].astype(jnp.float32)                        # [HW, C]
    hw, c = xf.shape
    cnt = float(hw * (c // g))
    gm = _group_matrices(c, g)
    mean_c = jnp.dot(mean_ref[0], gm.T, preferred_element_type=jnp.float32)
    rstd_c = jnp.dot(rstd_ref[0], gm.T, preferred_element_type=jnp.float32)
    xhat = (xf - mean_c) * rstd_c
    gate = xhat * s_ref[...] + b_ref[...] > 0.0
    dyr = jnp.where(gate, dy_ref[0].astype(jnp.float32), 0.0)
    db_c = jnp.sum(dyr, axis=0, keepdims=True)               # [1, C]
    ds_c = jnp.sum(dyr * xhat, axis=0, keepdims=True)
    # per-group sums of dxhat (= dyr * scale) and dxhat * xhat
    a_g = jnp.dot(db_c * s_ref[...], gm, preferred_element_type=jnp.float32)
    b_g = jnp.dot(ds_c * s_ref[...], gm, preferred_element_type=jnp.float32)
    a_c = jnp.dot(a_g, gm.T, preferred_element_type=jnp.float32)
    b_c = jnp.dot(b_g, gm.T, preferred_element_type=jnp.float32)
    dx = rstd_c * (dyr * s_ref[...] - (a_c + xhat * b_c) / cnt)
    dx_ref[0] = dx.astype(dx_ref.dtype)
    ds_ref[0] = ds_c
    db_ref[0] = db_c


def _fwd_stats_kernel(g: int, x_ref, s1_ref, s2_ref):
    """Tiled fwd phase 1: per-(sample, group) raw sums over HW tiles
    (same accumulator pattern as `_bwd_stats_kernel`); mean/rstd are
    finished by a tiny [N, G] jnp epilogue outside the kernel."""
    t = pl.program_id(1)
    xf = x_ref[0].astype(jnp.float32)                        # [HW/T, C]
    c = xf.shape[1]
    gm = _group_matrices(c, g)
    s1 = jnp.sum(xf, axis=0, keepdims=True)                  # [1, C]
    s2 = jnp.sum(xf * xf, axis=0, keepdims=True)

    @pl.when(t == 0)
    def _init():
        s1_ref[0] = jnp.zeros_like(s1_ref[0])
        s2_ref[0] = jnp.zeros_like(s2_ref[0])

    s1_ref[0] += jnp.dot(s1, gm, preferred_element_type=jnp.float32)
    s2_ref[0] += jnp.dot(s2, gm, preferred_element_type=jnp.float32)


def _fwd_apply_kernel(g: int, x_ref, s_ref, b_ref, mean_ref, rstd_ref, y_ref):
    """Tiled fwd phase 2: normalize+affine+ReLU per HW tile."""
    xf = x_ref[0].astype(jnp.float32)
    c = xf.shape[1]
    gm = _group_matrices(c, g)
    mean_c = jnp.dot(mean_ref[0], gm.T, preferred_element_type=jnp.float32)
    mul_c = jnp.dot(rstd_ref[0], gm.T,
                    preferred_element_type=jnp.float32) * s_ref[...]
    y = (xf - mean_c) * mul_c + b_ref[...]
    y_ref[0] = jnp.maximum(y, 0.0).astype(y_ref.dtype)


def _pallas_fwd_tiled(x, scale, bias, g: int, eps: float, tiles: int,
                      interpret: bool):
    """Two-pass tiled forward: raw group sums per tile, mean/rstd finished
    in jnp, then a tiled normalize pass. One extra read of x vs the
    whole-slab kernel — the price of VMEM fit on oversize slabs."""
    n, h, w, c = x.shape
    hw = h * w
    th = hw // tiles
    xr = x.reshape(n, hw, c)
    per_sample = lambda i, t: (i, 0, 0)  # noqa: E731 - accumulator blocks
    s1, s2 = pl.pallas_call(
        functools.partial(_fwd_stats_kernel, g),
        grid=(n, tiles),
        in_specs=[pl.BlockSpec((1, th, c), lambda i, t: (i, t, 0))],
        out_specs=[
            pl.BlockSpec((1, 1, g), per_sample),
            pl.BlockSpec((1, 1, g), per_sample),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((n, 1, g), jnp.float32),
            jax.ShapeDtypeStruct((n, 1, g), jnp.float32),
        ],
        interpret=interpret,
    )(xr)
    cnt = float(hw * (c // g))
    mean = s1 / cnt                                          # [n, 1, g]
    var = jnp.maximum(s2 / cnt - mean * mean, 0.0)
    rstd = jax.lax.rsqrt(var + eps)
    y = pl.pallas_call(
        functools.partial(_fwd_apply_kernel, g),
        grid=(n, tiles),
        in_specs=[
            pl.BlockSpec((1, th, c), lambda i, t: (i, t, 0)),
            pl.BlockSpec((1, c), lambda i, t: (0, 0)),
            pl.BlockSpec((1, c), lambda i, t: (0, 0)),
            pl.BlockSpec((1, 1, g), per_sample),
            pl.BlockSpec((1, 1, g), per_sample),
        ],
        out_specs=pl.BlockSpec((1, th, c), lambda i, t: (i, t, 0)),
        out_shape=jax.ShapeDtypeStruct((n, hw, c), x.dtype),
        interpret=interpret,
    )(xr, scale.astype(jnp.float32).reshape(1, c),
      bias.astype(jnp.float32).reshape(1, c), mean, rstd)
    return y.reshape(n, h, w, c), mean, rstd


def _pallas_fwd(x, scale, bias, g: int, eps: float, interpret: bool):
    n, h, w, c = x.shape
    hw = h * w
    tiles = _fwd_plan(hw, c, jnp.dtype(x.dtype).itemsize)
    if tiles is None:
        if interpret:
            tiles = 1  # the interpreter has no VMEM constraint
        else:
            raise ValueError(
                f"no VMEM-feasible forward plan for slab ({hw},{c}) "
                f"{x.dtype}; auto_pallas should have routed this to XLA")
    if tiles > 1:
        return _pallas_fwd_tiled(x, scale, bias, g, eps, tiles, interpret)
    y, mean, rstd = pl.pallas_call(
        functools.partial(_fwd_kernel, g, eps),
        grid=(n,),
        in_specs=[
            pl.BlockSpec((1, hw, c), lambda i: (i, 0, 0)),
            pl.BlockSpec((1, c), lambda i: (0, 0)),
            pl.BlockSpec((1, c), lambda i: (0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, hw, c), lambda i: (i, 0, 0)),
            # stats are [N, 1, G]: Mosaic requires the last two block dims to
            # divide (8, 128) or equal the array dims — the singleton middle
            # axis makes the (1, G) tail exact
            pl.BlockSpec((1, 1, g), lambda i: (i, 0, 0)),
            pl.BlockSpec((1, 1, g), lambda i: (i, 0, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((n, hw, c), x.dtype),
            jax.ShapeDtypeStruct((n, 1, g), jnp.float32),
            jax.ShapeDtypeStruct((n, 1, g), jnp.float32),
        ],
        interpret=interpret,
    )(x.reshape(n, hw, c),
      scale.astype(jnp.float32).reshape(1, c),
      bias.astype(jnp.float32).reshape(1, c))
    return y.reshape(n, h, w, c), mean, rstd


def _bwd_stats_kernel(g: int, x_ref, dy_ref, s_ref, b_ref, mean_ref, rstd_ref,
                      ag_ref, bg_ref, ds_ref, db_ref):
    """Tiled phase 1: per-(sample, group) sums over HW tiles.

    Grid (n, T); the four outputs' block index depends only on the sample, so
    they stay VMEM-resident across a sample's tiles and accumulate (zeroed at
    the sample's first tile). All sums are linear, so per-tile partial
    contributions add exactly."""
    t = pl.program_id(1)
    xf = x_ref[0].astype(jnp.float32)                        # [HW/T, C]
    c = xf.shape[1]
    gm = _group_matrices(c, g)
    mean_c = jnp.dot(mean_ref[0], gm.T, preferred_element_type=jnp.float32)
    rstd_c = jnp.dot(rstd_ref[0], gm.T, preferred_element_type=jnp.float32)
    xhat = (xf - mean_c) * rstd_c
    gate = xhat * s_ref[...] + b_ref[...] > 0.0
    dyr = jnp.where(gate, dy_ref[0].astype(jnp.float32), 0.0)
    db_t = jnp.sum(dyr, axis=0, keepdims=True)               # [1, C]
    ds_t = jnp.sum(dyr * xhat, axis=0, keepdims=True)

    @pl.when(t == 0)
    def _init():
        ag_ref[0] = jnp.zeros_like(ag_ref[0])
        bg_ref[0] = jnp.zeros_like(bg_ref[0])
        ds_ref[0] = jnp.zeros_like(ds_ref[0])
        db_ref[0] = jnp.zeros_like(db_ref[0])

    ag_ref[0] += jnp.dot(db_t * s_ref[...], gm,
                         preferred_element_type=jnp.float32)
    bg_ref[0] += jnp.dot(ds_t * s_ref[...], gm,
                         preferred_element_type=jnp.float32)
    ds_ref[0] += ds_t
    db_ref[0] += db_t


def _bwd_dx_kernel(g: int, cnt: float, x_ref, dy_ref, s_ref, b_ref, mean_ref,
                   rstd_ref, ag_ref, bg_ref, dx_ref):
    """Tiled phase 2: dx per HW tile from the phase-1 group sums."""
    xf = x_ref[0].astype(jnp.float32)
    c = xf.shape[1]
    gm = _group_matrices(c, g)
    mean_c = jnp.dot(mean_ref[0], gm.T, preferred_element_type=jnp.float32)
    rstd_c = jnp.dot(rstd_ref[0], gm.T, preferred_element_type=jnp.float32)
    xhat = (xf - mean_c) * rstd_c
    gate = xhat * s_ref[...] + b_ref[...] > 0.0
    dyr = jnp.where(gate, dy_ref[0].astype(jnp.float32), 0.0)
    a_c = jnp.dot(ag_ref[0], gm.T, preferred_element_type=jnp.float32)
    b_c = jnp.dot(bg_ref[0], gm.T, preferred_element_type=jnp.float32)
    dx = rstd_c * (dyr * s_ref[...] - (a_c + xhat * b_c) / cnt)
    dx_ref[0] = dx.astype(dx_ref.dtype)


def _pallas_bwd_tiled(x, dy, scale, bias, mean, rstd, g: int, tiles: int,
                      interpret: bool):
    """Two-pass tiled backward: same math as `_bwd_kernel`, but x/dy/dx are
    streamed in HW tiles so VMEM holds one tile (not the whole slab) per
    step. Costs one extra read of x and dy vs the untiled kernel — the
    price of admitting slabs whose untiled live set busts VMEM."""
    n, h, w, c = x.shape
    hw = h * w
    th = hw // tiles
    xr = x.reshape(n, hw, c)
    dyr = dy.reshape(n, hw, c)
    s2 = scale.astype(jnp.float32).reshape(1, c)
    b2 = bias.astype(jnp.float32).reshape(1, c)

    tile_specs = [
        pl.BlockSpec((1, th, c), lambda i, t: (i, t, 0)),
        pl.BlockSpec((1, th, c), lambda i, t: (i, t, 0)),
        pl.BlockSpec((1, c), lambda i, t: (0, 0)),
        pl.BlockSpec((1, c), lambda i, t: (0, 0)),
        pl.BlockSpec((1, 1, g), lambda i, t: (i, 0, 0)),
        pl.BlockSpec((1, 1, g), lambda i, t: (i, 0, 0)),
    ]
    per_sample = lambda i, t: (i, 0, 0)  # noqa: E731 - accumulator blocks
    ag, bg, ds_p, db_p = pl.pallas_call(
        functools.partial(_bwd_stats_kernel, g),
        grid=(n, tiles),
        in_specs=tile_specs,
        out_specs=[
            pl.BlockSpec((1, 1, g), per_sample),
            pl.BlockSpec((1, 1, g), per_sample),
            pl.BlockSpec((1, 1, c), per_sample),
            pl.BlockSpec((1, 1, c), per_sample),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((n, 1, g), jnp.float32),
            jax.ShapeDtypeStruct((n, 1, g), jnp.float32),
            jax.ShapeDtypeStruct((n, 1, c), jnp.float32),
            jax.ShapeDtypeStruct((n, 1, c), jnp.float32),
        ],
        interpret=interpret,
    )(xr, dyr, s2, b2, mean, rstd)

    dx = pl.pallas_call(
        functools.partial(_bwd_dx_kernel, g, float(hw * (c // g))),
        grid=(n, tiles),
        in_specs=tile_specs + [
            pl.BlockSpec((1, 1, g), per_sample),
            pl.BlockSpec((1, 1, g), per_sample),
        ],
        out_specs=pl.BlockSpec((1, th, c), lambda i, t: (i, t, 0)),
        out_shape=jax.ShapeDtypeStruct((n, hw, c), x.dtype),
        interpret=interpret,
    )(xr, dyr, s2, b2, mean, rstd, ag, bg)
    return (dx.reshape(n, h, w, c),
            jnp.sum(ds_p, axis=(0, 1)), jnp.sum(db_p, axis=(0, 1)))


def _pallas_bwd(x, dy, scale, bias, mean, rstd, g: int, interpret: bool):
    n, h, w, c = x.shape
    hw = h * w
    tiles = _bwd_plan(hw, c, jnp.dtype(x.dtype).itemsize)
    if tiles is None:
        if interpret:
            tiles = 1  # the interpreter has no VMEM constraint
        else:
            raise ValueError(
                f"no VMEM-feasible backward plan for slab ({hw},{c}) "
                f"{x.dtype}; auto_pallas should have routed this to XLA")
    if tiles > 1:
        return _pallas_bwd_tiled(x, dy, scale, bias, mean, rstd, g, tiles,
                                 interpret)
    dx, ds_p, db_p = pl.pallas_call(
        functools.partial(_bwd_kernel, g),
        grid=(n,),
        in_specs=[
            pl.BlockSpec((1, hw, c), lambda i: (i, 0, 0)),
            pl.BlockSpec((1, hw, c), lambda i: (i, 0, 0)),
            pl.BlockSpec((1, c), lambda i: (0, 0)),
            pl.BlockSpec((1, c), lambda i: (0, 0)),
            pl.BlockSpec((1, 1, g), lambda i: (i, 0, 0)),
            pl.BlockSpec((1, 1, g), lambda i: (i, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, hw, c), lambda i: (i, 0, 0)),
            pl.BlockSpec((1, 1, c), lambda i: (i, 0, 0)),
            pl.BlockSpec((1, 1, c), lambda i: (i, 0, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((n, hw, c), x.dtype),
            jax.ShapeDtypeStruct((n, 1, c), jnp.float32),
            jax.ShapeDtypeStruct((n, 1, c), jnp.float32),
        ],
        interpret=interpret,
    )(x.reshape(n, hw, c), dy.reshape(n, hw, c),
      scale.astype(jnp.float32).reshape(1, c),
      bias.astype(jnp.float32).reshape(1, c), mean, rstd)
    return (dx.reshape(n, h, w, c),
            jnp.sum(ds_p, axis=(0, 1)), jnp.sum(db_p, axis=(0, 1)))


# ------------------------------------------------------------- custom vjp


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def _gn_relu_pallas(x, scale, bias, g: int, eps: float, interpret: bool):
    return _pallas_fwd(x, scale, bias, g, eps, interpret)[0]


def _vjp_fwd(x, scale, bias, g: int, eps: float, interpret: bool):
    y, mean, rstd = _pallas_fwd(x, scale, bias, g, eps, interpret)
    return y, (x, scale, bias, mean, rstd)


def _vjp_bwd(g: int, eps: float, interpret: bool, res, dy):
    x, scale, bias, mean, rstd = res
    dx, ds, db = _pallas_bwd(x, dy, scale, bias, mean, rstd, g, interpret)
    return dx, ds.astype(scale.dtype), db.astype(bias.dtype)


_gn_relu_pallas.defvjp(_vjp_fwd, _vjp_bwd)


# VMEM admission (ADVICE r03: budget ALL live backward operands, not one
# slab). Estimates assume pallas_call's default double-buffered pipelining
# on every streamed block and that Mosaic fuses none of the f32 in-kernel
# slab temporaries (xf, dyr, xhat, and the pre-cast result) — conservative
# by construction, since none of this has compiled on silicon yet. Budget
# 14 MB of the ~16 MB/core (v5e), leaving headroom for the tiny
# stats/affine blocks and kernel bookkeeping.
_VMEM_BUDGET_BYTES = 14 * 1024 * 1024
_MAX_TILES = 256  # caps the search in _tile_plan (forward AND backward)


def _fwd_vmem_bytes(slab_elems: int, itemsize: int) -> int:
    """One `_fwd_kernel` grid step: x in + y out double-buffered, ~2 f32
    slab temporaries (xf and the pre-ReLU result)."""
    return 2 * 2 * slab_elems * itemsize + 2 * slab_elems * 4


def _bwd_vmem_bytes(tile_elems: int, itemsize: int) -> int:
    """One backward grid step over a [HW/T, C] tile (T=1 = the untiled
    `_bwd_kernel`): x, dy in + dx out double-buffered, ~4 f32 tile
    temporaries."""
    return 2 * 3 * tile_elems * itemsize + 4 * tile_elems * 4


def _tile_plan(hw: int, c: int, itemsize: int, vmem_fn):
    """1 = whole-slab kernel fits, T > 1 = T HW-tiles, None = no feasible
    plan (route to XLA). Tiles must divide HW on a Mosaic-aligned row
    boundary (sublane multiple: 16 rows at bf16, 8 at f32). Only exact
    divisors of HW are considered — no padded tiles — so an unfriendly
    factorization (e.g. HW = 2p for a large prime p) falls to XLA even
    when a padded tiling would fit; RN50 slabs are all power-of-two HW."""
    if vmem_fn(hw * c, itemsize) <= _VMEM_BUDGET_BYTES:
        return 1
    align = 16 if itemsize == 2 else 8
    for t in range(2, min(hw, _MAX_TILES) + 1):
        if hw % t or (hw // t) % align:
            continue
        if vmem_fn((hw // t) * c, itemsize) <= _VMEM_BUDGET_BYTES:
            return t
    return None


def _fwd_plan(hw: int, c: int, itemsize: int):
    return _tile_plan(hw, c, itemsize, _fwd_vmem_bytes)


def _bwd_plan(hw: int, c: int, itemsize: int):
    return _tile_plan(hw, c, itemsize, _bwd_vmem_bytes)


def auto_pallas(x_shape=None, x_dtype=None) -> bool:
    """Dispatch predicate for impl="auto": the Pallas kernel on a
    single-device TPU backend (and, when `x_shape` [N,H,W,C] is given, only
    when feasible forward AND backward plans exist — whole-slab or HW-tiled,
    dtype-aware since bf16 slabs stream at half the f32 rate); the
    GSPMD-partitionable path elsewhere."""
    from dorpatch_tpu.ops._backend import single_device_tpu

    try:
        ok = single_device_tpu()
    except Exception:
        return False
    if ok and x_shape is not None:
        n, h, w, c = x_shape
        itemsize = jnp.dtype(x_dtype).itemsize if x_dtype is not None else 4
        ok = (_fwd_plan(h * w, c, itemsize) is not None
              and _bwd_plan(h * w, c, itemsize) is not None)
    return ok


def gn_relu(x: jax.Array, scale: jax.Array, bias: jax.Array,
            num_groups: int = 32, eps: float = 1e-5,
            impl: str = "auto") -> jax.Array:
    """GroupNorm(num_groups, eps)+ReLU on NHWC `x`, output in `x.dtype`.

    f32 statistics regardless of `x.dtype` (flax/timm parity). `scale`/`bias`
    are the `[C]` affine parameters. Differentiable w.r.t. all three.

    impl: "auto" (Pallas on single-device TPU backends, jnp elsewhere),
    "pallas", "interpret" (Pallas interpreter — CPU tests), "jnp".
    """
    if x.shape[-1] % num_groups:
        raise ValueError(f"C={x.shape[-1]} not divisible by {num_groups} groups")
    if impl == "auto":
        impl = "pallas" if auto_pallas(x.shape, x.dtype) else "jnp"
    if impl == "jnp":
        return gn_relu_reference(x, scale, bias, num_groups, eps)
    if impl not in ("pallas", "interpret"):
        raise ValueError(f"impl={impl!r}")
    return _gn_relu_pallas(x, scale, bias, num_groups, float(eps),
                           impl == "interpret")
