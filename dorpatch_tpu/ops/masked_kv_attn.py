"""In-place masked-KV attention: the token-pruned engines' fused hot loop.

The token-pruned ViT rows/table programs (`models.vit.TokenPrunedViT`) are
bandwidth-bound on the clean KV cache: per mask entry, the XLA einsum chain
(`bcshf,bthf->bchst` logits, concat-softmax, two weighted-value einsums)
streams the whole `[B, T+1, H, hd]` clean K and V tensors from HBM per
block with materialized `[B, C, H, S, T]` logit/probability intermediates
in between — the gap ROADMAP item 3(b) names. This kernel fuses one
block's whole attention read: for each (image, mask) grid step it loads
the S fresh-query rows, reads the clean K/V blocks IN PLACE (the clean
cache blocks index only on the image axis, so with the mask axis
minor-most they load into VMEM once per image and serve every mask), adds
the additive staleness/pad biases to the logits inside the kernel, runs
the numerically-stable two-group softmax, and writes only the `[S, H, hd]`
attention output — no logit or probability tensor ever exists in HBM.

Exactness contract: identical math to the einsum composition (scaled
queries, additive -1e9 staleness/duplicate-slot biases, max-subtracted
softmax over the concatenated clean+dirty key axis), reassociated — the
two groups' max/sum/weighted-value reductions are computed separately and
combined, so outputs are allclose at tight f32 tolerance rather than
bit-equal (`tests/test_ops.py`); the verdict-level contract stays the
margin-gated escalation of `defense.py`'s "token-exact"/"mixer-exact"
modes, unchanged.

Gate: callers resolve `use_pallas` through `ops._backend.resolve_use_pallas`
and pass `interpret=True` on CPU ("interpret" mode, the parity-test path).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from dorpatch_tpu.ops import _backend


def masked_kv_attention_reference(q, kd, vd, kc, vc, clean_bias, dirty_bias):
    """The einsum composition the kernel replaces (q pre-scaled):
    `q/kd/vd [B, C, S, H, f]`, `kc/vc [B, T, H, f]`, `clean_bias [B, C, T]`,
    `dirty_bias [B, C, S]` -> `[B, C, S, H, f]`."""
    t = kc.shape[1]
    wc = jnp.einsum("bcshf,bthf->bchst", q, kc) \
        + clean_bias[:, :, None, None, :]
    wd = jnp.einsum("bcshf,bcthf->bchst", q, kd) \
        + dirty_bias[:, :, None, None, :]
    w = jax.nn.softmax(jnp.concatenate([wc, wd], axis=-1), axis=-1)
    o = jnp.einsum("bchst,bthf->bcshf", w[..., :t], vc) \
        + jnp.einsum("bchst,bcthf->bcshf", w[..., t:], vd)
    return o


def _attn_kernel(num_heads: int, q_ref, kd_ref, vd_ref, kc_ref, vc_ref,
                 cb_ref, db_ref, out_ref):
    cb = cb_ref[0, 0][None, :]                      # [1, T]
    db = db_ref[0, 0][None, :]                      # [1, S]
    for h in range(num_heads):                      # tiny H: unrolled
        qh = q_ref[0, 0, :, h, :]                   # [S, f]
        wc = jax.lax.dot_general(                   # [S, T]
            qh, kc_ref[0, :, h, :], (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) + cb
        wd = jax.lax.dot_general(                   # [S, S]
            qh, kd_ref[0, 0, :, h, :], (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) + db
        m = jnp.maximum(jnp.max(wc, axis=-1), jnp.max(wd, axis=-1))
        ec = jnp.exp(wc - m[:, None])
        ed = jnp.exp(wd - m[:, None])
        denom = jnp.sum(ec, axis=-1) + jnp.sum(ed, axis=-1)
        o = (jax.lax.dot_general(
                 ec, vc_ref[0, :, h, :], (((1,), (0,)), ((), ())),
                 preferred_element_type=jnp.float32)
             + jax.lax.dot_general(
                 ed, vd_ref[0, 0, :, h, :], (((1,), (0,)), ((), ())),
                 preferred_element_type=jnp.float32)) / denom[:, None]
        out_ref[0, 0, :, h, :] = o.astype(out_ref.dtype)


def masked_kv_attention(q, kd, vd, kc, vc, clean_bias, dirty_bias,
                        interpret: bool = False):
    """Pallas twin of `masked_kv_attention_reference` (same signature plus
    `interpret`). Grid (B, C) with the mask axis minor-most: the clean
    K/V blocks depend only on the image index and stay VMEM-resident
    across each image's whole mask sweep; every step writes its
    `[S, H, f]` output directly."""
    b, c, s, h, f = q.shape
    t = kc.shape[1]
    img = lambda i, j: (i, 0, 0, 0)
    ent = lambda i, j: (i, j, 0, 0, 0)
    return pl.pallas_call(
        functools.partial(_attn_kernel, h),
        grid=(b, c),
        in_specs=[
            pl.BlockSpec((1, 1, s, h, f), ent),
            pl.BlockSpec((1, 1, s, h, f), ent),
            pl.BlockSpec((1, 1, s, h, f), ent),
            pl.BlockSpec((1, t, h, f), img),
            pl.BlockSpec((1, t, h, f), img),
            pl.BlockSpec((1, 1, t), lambda i, j: (i, j, 0)),
            pl.BlockSpec((1, 1, s), lambda i, j: (i, j, 0)),
        ],
        out_specs=pl.BlockSpec((1, 1, s, h, f), ent),
        out_shape=jax.ShapeDtypeStruct((b, c, s, h, f), q.dtype),
        interpret=interpret,
    )(q, kd, vd, kc, vc, clean_bias, dirty_bias)


def masked_kv_attention_sharded(q, kd, vd, kc, vc, clean_bias, dirty_bias,
                                mesh, data_axis: str = "data",
                                interpret: bool = False):
    """`masked_kv_attention` under `shard_map` over the data axis — the
    mesh-safe form the DP603 audit proves. Every operand carries the image
    batch on its leading axis, so all seven shard `P(data)` together, the
    per-shard grid is ([B/d], C) — shard-local in both dimensions — and
    the body contains no collectives (attention entries are per-image;
    nothing crosses shards). The output keeps the data-axis sharding the
    surrounding block arithmetic propagates."""
    shard_map, sm_kwargs = _backend.get_shard_map()
    from jax.sharding import PartitionSpec as P

    sm = shard_map(
        functools.partial(masked_kv_attention, interpret=interpret),
        mesh=mesh,
        in_specs=(P(data_axis),) * 7,
        out_specs=P(data_axis), **sm_kwargs)
    return sm(q, kd, vd, kc, vc, clean_bias, dirty_bias)
