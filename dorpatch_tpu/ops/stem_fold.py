"""Masked-stem fold: occlusion masks applied in post-stem activation space.

The conv families' certification sweep spends its first layer doing
redundant work: every one of the 36 first-round masks re-runs the stem conv
on an image that is bit-identical to the clean one outside a small
occlusion window, after first materializing the full `[B, 36, H, W, C]`
masked-image tensor in HBM (`masks.apply_masks` / `ops.masked_fill`). The
stem conv is LINEAR (bias-free in both supported families; ResNetV2's
weight standardization is a function of the weights only), so

    stem(norm(img * m + fill * (1-m)))
      = stem(norm(img)) + stem_nb(norm_scale * (fill - img) * occ)

where `occ = 1 - m` is supported only on the mask rectangles. This module
computes the clean stem activation ONCE per image and, per mask, only the
delta term — a small conv over the mask window's receptive field, scattered
into a broadcast of the shared clean cache. The masked-image tensor never
exists; the 36-mask round reads the image once and the stem runs once
(`ROADMAP` item 1's conv leg, built on the same static-rectangle geometry
the Pallas `ops.masked_fill` kernel rasterizes from — here the rectangles
are known at trace time, so the windows, paddings and scatter offsets are
all compile-time constants and XLA fuses the per-mask chain directly).

Exactness: the fold is algebraically exact; the only deviation from the
`apply_masks` + forward oracle is float summation order inside the stem
conv, so verdicts are bit-stable in practice (asserted by the parity
fixtures in `tests/test_defense.py`).

The fold applies to the mandatory first-round (phase-1) sweep, where every
window is small. Pair masks' joint receptive fields approach the full
image (two far-apart rectangles), so phase-2 keeps the standard path —
see `defense.PatchCleanser._build_pruned_programs`.
"""

from __future__ import annotations

from typing import Callable, List, NamedTuple, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np


class _Window(NamedTuple):
    """Static per-mask fold geometry, all in PADDED input coordinates."""

    o0: int      # affected output rows [o0, o1)
    o1: int
    oc0: int     # affected output cols [oc0, oc1)
    oc1: int
    i0: int      # input window rows [i0, i1) feeding those outputs
    i1: int
    ic0: int
    ic1: int
    occ: np.ndarray  # [i1-i0, ic1-ic0, 1] f32 union occlusion indicator


def same_pads(size: int, k: int, s: int) -> Tuple[int, int]:
    """TF/XLA 'SAME' padding split for one spatial axis (low, high)."""
    out = -(-size // s)
    total = max((out - 1) * s + k - size, 0)
    return total // 2, total - total // 2


def _axis_window(r0: int, r1: int, pad_lo: int, k: int, s: int,
                 out_size: int) -> Tuple[int, int, int, int]:
    """Outputs [o0, o1) whose receptive field meets input rows [r0, r1)
    (original coords), plus the padded-coord input window [i0, i1) that
    produces exactly those outputs under a VALID conv."""
    a0, a1 = r0 + pad_lo, r1 + pad_lo   # padded coords
    o0 = max(0, -(-(a0 - k + 1) // s))
    o1 = min(out_size, (a1 - 1) // s + 1)
    return o0, o1, o0 * s, (o1 - 1) * s + k


def plan_windows(rects: np.ndarray, img_size: int, k: int, s: int,
                 pads: Tuple[Tuple[int, int], Tuple[int, int]]) -> List[_Window]:
    """Static fold plan for a rectangle table `[N, K, 4]` (empty (0,0,0,0)
    rows ignored): per mask, the union bounding box's affected output
    region, its input window, and the occlusion indicator restricted to
    that window (built host-side — the rectangles are trace-time
    constants)."""
    (pr0, pr1), (pc0, pc1) = pads
    h_out = (img_size + pr0 + pr1 - k) // s + 1
    w_out = (img_size + pc0 + pc1 - k) // s + 1
    rects = np.asarray(rects, np.int64)
    if rects.ndim == 2:
        rects = rects[:, None, :]
    plan: List[_Window] = []
    for n in range(rects.shape[0]):
        live = [r for r in rects[n] if r[1] > r[0] and r[3] > r[2]]
        if not live:
            raise ValueError(f"mask {n} has no non-empty rectangle")
        r0 = min(int(r[0]) for r in live)
        r1 = max(int(r[1]) for r in live)
        c0 = min(int(r[2]) for r in live)
        c1 = max(int(r[3]) for r in live)
        o0, o1, i0, i1 = _axis_window(r0, r1, pr0, k, s, h_out)
        oc0, oc1, ic0, ic1 = _axis_window(c0, c1, pc0, k, s, w_out)
        occ = np.zeros((i1 - i0, ic1 - ic0, 1), np.float32)
        for rr0, rr1, cc0, cc1 in live:
            occ[max(rr0 + pr0 - i0, 0):max(rr1 + pr0 - i0, 0),
                max(cc0 + pc0 - ic0, 0):max(cc1 + pc0 - ic0, 0)] = 1.0
        plan.append(_Window(o0, o1, oc0, oc1, i0, i1, ic0, ic1, occ))
    return plan


def fold_masked_stem(kernel: jax.Array, clean: jax.Array, u: jax.Array,
                     plan: Sequence[_Window], strides: Tuple[int, int],
                     pads) -> jax.Array:
    """`[B, h, w, c]` clean stem cache + `[B, H, W, C]` fill-delta input
    `u = norm_scale * (fill - img)` -> `[B, N, h, w, c]` masked stem
    activations: one small VALID delta-conv per mask, scattered into the
    broadcast clean cache. Everything about each mask is static, so the
    whole fold compiles into one fused program."""
    (pr0, pr1), (pc0, pc1) = pads
    up = jnp.pad(u, ((0, 0), (pr0, pr1), (pc0, pc1), (0, 0)))
    b = clean.shape[0]
    out = jnp.broadcast_to(clean[:, None], (b, len(plan)) + clean.shape[1:])
    for n, w in enumerate(plan):
        win = up[:, w.i0:w.i1, w.ic0:w.ic1, :] * jnp.asarray(w.occ)
        d = jax.lax.conv_general_dilated(
            win, kernel, window_strides=strides, padding="VALID",
            dimension_numbers=("NHWC", "HWIO", "NHWC"))
        out = out.at[:, n, w.o0:w.o1, w.oc0:w.oc1, :].add(d)
    return out


def _preds_margins(logits):
    from dorpatch_tpu.utils import preds_margins

    return preds_margins(logits)


class StemFoldFamily:
    """One mask family's stem-folded first-round program: `phase1(params,
    imgs)` -> `(preds [B, M], margins [B, M])`, numerically the
    `apply_masks` + full-forward table up to conv summation order.
    `fe`/`fe_first` mirror the token engine's forward-equivalents contract;
    the stem fold saves the per-mask stem recompute and the masked-image
    HBM materialization but still runs the full trunk per mask, so it is
    conservatively credited a full forward per entry."""

    def __init__(self, engine: "StemFoldEngine", rects: np.ndarray,
                 num_singles: int, chunk_size: int, fill: float):
        self.engine = engine
        self.num_singles = int(num_singles)
        self.chunk_size = max(1, int(chunk_size))
        self.fill = float(fill)
        self.plan = plan_windows(rects[:num_singles], engine.img_size,
                                 engine.kernel_hw, engine.strides[0],
                                 engine.pads)
        self.fe = np.ones((np.asarray(rects).shape[0],), np.float64)
        self.fe_first = float(num_singles)
        self.fe_pairs = float(self.fe[num_singles:].sum())
        # already conservative: every folded entry is credited a FULL
        # forward although its stem never ran, which over-covers the one
        # clean stem pass (a small fraction of a forward) per image
        self.cache_fe = 0.0

    def phase1(self, params, imgs):
        eng = self.engine
        b, h, w, ci = imgs.shape
        n = len(self.plan)
        xn = eng.normalize(imgs)
        clean = eng.module.apply(params, xn, "stem")
        u = eng.norm_scale * (self.fill - imgs)
        kernel = eng.kernel_fn(params)
        # fold AND trunk per mask chunk so the live folded-stem tensor
        # stays within the chunk_size memory contract: a stem map is
        # (h'*w'*C')/(H*W*C) times an input image (e.g. ~21x for the CIFAR
        # 3x3/1 64-channel stem), so the mask-chunk width shrinks by that
        # inflation — never materializing all N stem maps at once. Chunks
        # are a Python-unrolled loop in ONE jitted program (static ragged
        # tail, no padding, no retrace).
        inflation = float(np.prod(clean.shape[1:])) / float(h * w * ci)
        c = max(1, min(n, int(self.chunk_size / max(1.0, inflation))))
        preds, margins = [], []
        for off in range(0, n, c):
            part = self.plan[off:off + c]
            folded = fold_masked_stem(kernel, clean, u, part,
                                      eng.strides, eng.pads)  # [B, c', ...]
            logits = eng.module.apply(
                params, folded.reshape((-1,) + folded.shape[2:]), "trunk")
            p, m = _preds_margins(logits)
            preds.append(p.reshape(b, len(part)))
            margins.append(m.reshape(b, len(part)))
        return (jnp.concatenate(preds, axis=1),
                jnp.concatenate(margins, axis=1))


class StemFoldEngine:
    """Masked-stem incremental inference for one conv victim.

    `module.apply(params, x, "stem")` must yield the bias-free linear stem
    conv output and `"trunk"` must complete the forward from it (see
    `models.small.CifarResNet18` / `models.resnetv2.ResNetV2`);
    `kernel_fn(params)` returns the EFFECTIVE HWIO stem kernel with any
    weight transform (ResNetV2 standardization) folded in. Built by
    `models.registry.get_model`; consumed by `defense.build_defenses`."""

    kind = "stem"

    def __init__(self, module, img_size: int,
                 kernel_fn: Callable, kernel_hw: int,
                 strides: Tuple[int, int],
                 pads,
                 normalize: Optional[Callable] = None,
                 norm_scale: float = 2.0):
        self.module = module
        self.img_size = int(img_size)
        self.kernel_fn = kernel_fn
        self.kernel_hw = int(kernel_hw)
        self.strides = tuple(strides)
        self.pads = (tuple(pads[0]), tuple(pads[1]))
        self.normalize = normalize or (lambda x: (x - 0.5) / 0.5)
        self.norm_scale = float(norm_scale)

    def build_family(self, rects: np.ndarray, num_singles: int,
                     chunk_size: int, fill: float) -> StemFoldFamily:
        return StemFoldFamily(self, rects, num_singles, chunk_size, fill)
