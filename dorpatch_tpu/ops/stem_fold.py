"""Masked-stem fold: occlusion masks applied in post-stem activation space.

The conv families' certification sweep spends its first layer doing
redundant work: every one of the 36 first-round masks re-runs the stem conv
on an image that is bit-identical to the clean one outside a small
occlusion window, after first materializing the full `[B, 36, H, W, C]`
masked-image tensor in HBM (`masks.apply_masks` / `ops.masked_fill`). The
stem conv is LINEAR (bias-free in both supported families; ResNetV2's
weight standardization is a function of the weights only), so

    stem(norm(img * m + fill * (1-m)))
      = stem(norm(img)) + stem_nb(norm_scale * (fill - img) * occ)

where `occ = 1 - m` is supported only on the mask rectangles. This module
computes the clean stem activation ONCE per image and, per mask, only the
delta term — a small conv over the mask window's receptive field, scattered
into a broadcast of the shared clean cache. The masked-image tensor never
exists; the 36-mask round reads the image once and the stem runs once
(`ROADMAP` item 1's conv leg, built on the same static-rectangle geometry
the Pallas `ops.masked_fill` kernel rasterizes from — here the rectangles
are known at trace time, so the windows, paddings and scatter offsets are
all compile-time constants and XLA fuses the per-mask chain directly).

Exactness: the fold is algebraically exact; the only deviation from the
`apply_masks` + forward oracle is float summation order inside the stem
conv, so verdicts are bit-stable in practice (asserted by the parity
fixtures in `tests/test_defense.py`).

The fold applies to the mandatory first-round (phase-1) sweep, where every
window is small. Pair masks' joint receptive fields approach the full
image (two far-apart rectangles), so phase-2 keeps the standard path —
see `defense.PatchCleanser._build_pruned_programs`.

Pallas tier (`use_pallas`, same gate as `ops.masked_fill`): the XLA fold
still materializes each mask's windowed input and delta tensor in HBM
between ops. `fold_masked_stem_kernel` fuses the whole per-mask chain —
window gather, occlusion mask, VALID delta-conv, scatter into the
broadcast clean cache — into one kernel whose grid iterates masks
minor-most per image, so the padded input and the clean cache stay
VMEM-resident across the 36-mask sweep and the per-mask delta never
round-trips through HBM. Mask windows are enlarged to the family-uniform
`[OH, OW]` bound (start offsets clamped to stay in range; the occlusion
indicator is laid out in absolute window coordinates, so every output in
the enlargement but outside the true affected region receives an exactly
zero delta). The delta-conv runs as the k*k unrolled strided-slice
matmul accumulation `_delta_conv` — the SAME composition the XLA fold
uses, in the same order, so kernel and fold outputs are bit-identical on
f32 (asserted by `tests/test_ops.py`).
"""

from __future__ import annotations

import functools
from typing import Callable, List, NamedTuple, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from dorpatch_tpu.ops import _backend


class _Window(NamedTuple):
    """Static per-mask fold geometry, all in PADDED input coordinates."""

    o0: int      # affected output rows [o0, o1)
    o1: int
    oc0: int     # affected output cols [oc0, oc1)
    oc1: int
    i0: int      # input window rows [i0, i1) feeding those outputs
    i1: int
    ic0: int
    ic1: int
    occ: np.ndarray  # [i1-i0, ic1-ic0, 1] f32 union occlusion indicator


def same_pads(size: int, k: int, s: int) -> Tuple[int, int]:
    """TF/XLA 'SAME' padding split for one spatial axis (low, high)."""
    out = -(-size // s)
    total = max((out - 1) * s + k - size, 0)
    return total // 2, total - total // 2


def _axis_window(r0: int, r1: int, pad_lo: int, k: int, s: int,
                 out_size: int) -> Tuple[int, int, int, int]:
    """Outputs [o0, o1) whose receptive field meets input rows [r0, r1)
    (original coords), plus the padded-coord input window [i0, i1) that
    produces exactly those outputs under a VALID conv."""
    a0, a1 = r0 + pad_lo, r1 + pad_lo   # padded coords
    o0 = max(0, -(-(a0 - k + 1) // s))
    o1 = min(out_size, (a1 - 1) // s + 1)
    return o0, o1, o0 * s, (o1 - 1) * s + k


def plan_windows(rects: np.ndarray, img_size: int, k: int, s: int,
                 pads: Tuple[Tuple[int, int], Tuple[int, int]]) -> List[_Window]:
    """Static fold plan for a rectangle table `[N, K, 4]` (empty (0,0,0,0)
    rows ignored): per mask, the union bounding box's affected output
    region, its input window, and the occlusion indicator restricted to
    that window (built host-side — the rectangles are trace-time
    constants)."""
    (pr0, pr1), (pc0, pc1) = pads
    h_out = (img_size + pr0 + pr1 - k) // s + 1
    w_out = (img_size + pc0 + pc1 - k) // s + 1
    rects = np.asarray(rects, np.int64)
    if rects.ndim == 2:
        rects = rects[:, None, :]
    plan: List[_Window] = []
    for n in range(rects.shape[0]):
        live = [r for r in rects[n] if r[1] > r[0] and r[3] > r[2]]
        if not live:
            raise ValueError(f"mask {n} has no non-empty rectangle")
        r0 = min(int(r[0]) for r in live)
        r1 = max(int(r[1]) for r in live)
        c0 = min(int(r[2]) for r in live)
        c1 = max(int(r[3]) for r in live)
        o0, o1, i0, i1 = _axis_window(r0, r1, pr0, k, s, h_out)
        oc0, oc1, ic0, ic1 = _axis_window(c0, c1, pc0, k, s, w_out)
        occ = np.zeros((i1 - i0, ic1 - ic0, 1), np.float32)
        for rr0, rr1, cc0, cc1 in live:
            occ[max(rr0 + pr0 - i0, 0):max(rr1 + pr0 - i0, 0),
                max(cc0 + pc0 - ic0, 0):max(cc1 + pc0 - ic0, 0)] = 1.0
        plan.append(_Window(o0, o1, oc0, oc1, i0, i1, ic0, ic1, occ))
    return plan


def _delta_conv(win: jax.Array, kernel: jax.Array, s: int) -> jax.Array:
    """VALID conv `[B, IH, IW, Cin] x [k, k, Cin, Cout] -> [B, OH, OW,
    Cout]` (stride `s`, f32 accumulation) as the k*k unrolled
    strided-slice matmul chain. THE delta-conv arithmetic of both the XLA
    fold and the Pallas kernel — one composition, one summation order
    (dr-major, dc, then Cin inside each dot), which is what makes the
    kernel/fold parity contract bit-exact instead of merely allclose. The
    strided row/col select is a reshape (`[OH, s, ...][:, 0]`), which
    needs `OH*s + k - 1` rows; windows at the natural VALID size are
    zero-padded up (the pad rows feed no selected output)."""
    k = int(kernel.shape[0])
    b, ih, iw, cin = win.shape
    oh, ow = (ih - k) // s + 1, (iw - k) // s + 1
    cout = kernel.shape[-1]
    eh, ew = oh * s + k - 1, ow * s + k - 1
    if (eh, ew) != (ih, iw):
        win = jnp.pad(win, ((0, 0), (0, eh - ih), (0, ew - iw), (0, 0)))
    acc = jnp.zeros((b, oh * ow, cout), jnp.float32)
    for dr in range(k):
        rows = win[:, dr:dr + oh * s].reshape(b, oh, s, ew, cin)[:, :, 0]
        for dc in range(k):
            cols = rows[:, :, dc:dc + ow * s] \
                .reshape(b, oh, ow, s, cin)[:, :, :, 0]
            acc = acc + jax.lax.dot_general(
                cols.reshape(b, oh * ow, cin), kernel[dr, dc],
                (((2,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)
    return acc.reshape(b, oh, ow, cout)


def fold_masked_stem(kernel: jax.Array, clean: jax.Array, u: jax.Array,
                     plan: Sequence[_Window], strides: Tuple[int, int],
                     pads) -> jax.Array:
    """`[B, h, w, c]` clean stem cache + `[B, H, W, C]` fill-delta input
    `u = norm_scale * (fill - img)` -> `[B, N, h, w, c]` masked stem
    activations: one small VALID delta-conv per mask, scattered into the
    broadcast clean cache. Everything about each mask is static, so the
    whole fold compiles into one fused program. The delta-conv is the
    shared `_delta_conv` composition (bit-identical to the Pallas
    kernel's)."""
    (pr0, pr1), (pc0, pc1) = pads
    up = jnp.pad(u, ((0, 0), (pr0, pr1), (pc0, pc1), (0, 0)))
    b = clean.shape[0]
    out = jnp.broadcast_to(clean[:, None], (b, len(plan)) + clean.shape[1:])
    for n, w in enumerate(plan):
        # occ is stored f32 host-side; match the input dtype so the bf16
        # certify bank's window product does not silently upcast (DP208)
        win = up[:, w.i0:w.i1, w.ic0:w.ic1, :] \
            * jnp.asarray(w.occ, dtype=up.dtype)
        d = _delta_conv(win, kernel, int(strides[0]))
        out = out.at[:, n, w.o0:w.o1, w.oc0:w.oc1, :].add(
            d.astype(out.dtype))
    return out


# ------------------------------------------------------------ Pallas kernel


def _uniform_plan(plan: Sequence[_Window], h_out: int, w_out: int,
                  k: int, s: int):
    """Family-uniform kernel geometry. Every mask's affected-output window
    is enlarged to the family max `[OH, OW]` with the start clamped
    in-range (`o0' = min(o0, h_out - OH)` keeps the input start
    `o0' * s`-aligned), and the occlusion indicator is re-laid-out in
    absolute window coordinates over the enlarged `[IH, IW]` input window
    (`IH = OH*s + k - 1`, sized for `_delta_conv`'s reshape select).
    Outputs inside the enlargement but outside the mask's true affected
    region have receptive fields that see only occ=0 input, so their
    delta is exactly zero — the kernel scatters the whole `[OH, OW]`
    block without any output masking. Returns `(OH, OW, geo [N, 4] int32
    rows (o0, oc0, i0, ic0), occ [N, IH, IW] f32)`."""
    oh = max(w.o1 - w.o0 for w in plan)
    ow = max(w.oc1 - w.oc0 for w in plan)
    ih, iw = oh * s + k - 1, ow * s + k - 1
    geo = np.zeros((len(plan), 4), np.int32)
    occ = np.zeros((len(plan), ih, iw), np.float32)
    for n, w in enumerate(plan):
        o0 = min(w.o0, h_out - oh)
        oc0 = min(w.oc0, w_out - ow)
        i0, ic0 = o0 * s, oc0 * s
        geo[n] = (o0, oc0, i0, ic0)
        occ[n, w.i0 - i0:w.i1 - i0, w.ic0 - ic0:w.ic1 - ic0] = w.occ[:, :, 0]
    return oh, ow, geo, occ


def _fold_kernel(oh: int, ow: int, k: int, s: int, geo_ref, up_ref,
                 occ_ref, clean_ref, kern_ref, out_ref):
    n = pl.program_id(1)
    ih, iw = occ_ref.shape[1], occ_ref.shape[2]
    win = up_ref[0, pl.ds(geo_ref[n, 2], ih), pl.ds(geo_ref[n, 3], iw), :] \
        * occ_ref[0][:, :, None]
    d = _delta_conv(win[None], kern_ref[...], s)[0]
    scattered = jax.lax.dynamic_update_slice(
        jnp.zeros(out_ref.shape[2:], out_ref.dtype),
        d.astype(out_ref.dtype), (geo_ref[n, 0], geo_ref[n, 1], 0))
    out_ref[0, 0] = clean_ref[0] + scattered


def fold_masked_stem_kernel(kernel: jax.Array, clean: jax.Array,
                            u: jax.Array, plan: Sequence[_Window],
                            strides: Tuple[int, int], pads,
                            interpret: bool = False) -> jax.Array:
    """Pallas twin of `fold_masked_stem`, bit-identical output: one fused
    window-gather + occlusion + delta-conv + scatter kernel. The grid
    iterates masks minor-most per image, so the padded fill-delta input
    and the clean stem cache load into VMEM once per image and serve the
    whole mask sweep; each grid step writes its `[h, w, c]` masked
    activation directly — the per-mask windowed input and delta tensors
    never exist in HBM."""
    (pr0, pr1), (pc0, pc1) = pads
    s = int(strides[0])
    k = int(kernel.shape[0])
    b, h, w, c = clean.shape
    n = len(plan)
    cin = u.shape[-1]
    # s-1 extra zero rows/cols keep the clamped uniform windows (and the
    # reshape select's overhang) in bounds; they feed no real output
    up = jnp.pad(u, ((0, 0), (pr0, pr1 + s - 1), (pc0, pc1 + s - 1),
                     (0, 0)))
    hp, wp = int(up.shape[1]), int(up.shape[2])
    oh, ow, geo, occ = _uniform_plan(plan, h, w, k, s)
    return pl.pallas_call(
        functools.partial(_fold_kernel, oh, ow, k, s),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            # mask axis minor-most: the up/clean blocks index only on the
            # image axis, so they stay resident across each image's sweep
            grid=(b, n),
            in_specs=[
                pl.BlockSpec((1, hp, wp, cin), lambda i, m, geo: (i, 0, 0, 0)),
                pl.BlockSpec((1,) + occ.shape[1:], lambda i, m, geo: (m, 0, 0)),
                pl.BlockSpec((1, h, w, c), lambda i, m, geo: (i, 0, 0, 0)),
                pl.BlockSpec(tuple(kernel.shape), lambda i, m, geo: (0, 0, 0, 0)),
            ],
            out_specs=pl.BlockSpec(
                (1, 1, h, w, c), lambda i, m, geo: (i, m, 0, 0, 0)),
        ),
        out_shape=jax.ShapeDtypeStruct((b, n, h, w, c), clean.dtype),
        interpret=interpret,
    )(jnp.asarray(geo), up, jnp.asarray(occ, dtype=up.dtype), clean, kernel)


def fold_masked_stem_sharded(kernel: jax.Array, clean: jax.Array,
                             u: jax.Array, plan: Sequence[_Window],
                             strides: Tuple[int, int], pads, mesh,
                             data_axis: str = "data",
                             interpret: bool = False) -> jax.Array:
    """`fold_masked_stem_kernel` under `shard_map` over the data axis — the
    mesh-safe form the DP603 audit proves: the stem kernel's grid iterates
    (per-shard images) x (masks), both shard-local, and the body contains
    no collectives at all (phase-1 entries are per-image, nothing crosses
    shards). The effective stem kernel and the static plan are replicated;
    the clean cache and fill-delta input shard with the image batch, so
    each device folds only its `[B/d, N, h, w, c]` block."""
    shard_map, sm_kwargs = _backend.get_shard_map()
    from jax.sharding import PartitionSpec as P

    body = functools.partial(fold_masked_stem_kernel, plan=plan,
                             strides=strides, pads=pads,
                             interpret=interpret)
    sm = shard_map(lambda kern, cl, uu: body(kern, cl, uu),
                   mesh=mesh,
                   in_specs=(P(), P(data_axis), P(data_axis)),
                   out_specs=P(data_axis), **sm_kwargs)
    return sm(kernel, clean, u)


def _preds_margins(logits):
    from dorpatch_tpu.utils import preds_margins

    return preds_margins(logits)


class StemFoldFamily:
    """One mask family's stem-folded first-round program: `phase1(params,
    imgs)` -> `(preds [B, M], margins [B, M])`, numerically the
    `apply_masks` + full-forward table up to conv summation order.
    `fe`/`fe_first` mirror the token engine's forward-equivalents contract;
    the stem fold saves the per-mask stem recompute and the masked-image
    HBM materialization but still runs the full trunk per mask, so it is
    conservatively credited a full forward per entry."""

    def __init__(self, engine: "StemFoldEngine", rects: np.ndarray,
                 num_singles: int, chunk_size: int, fill: float,
                 use_pallas: str = "auto", mesh=None,
                 data_axis: str = "data",
                 compute_dtype: str = "float32"):
        self.engine = engine
        self.compute_dtype = jnp.dtype(compute_dtype)
        self.num_singles = int(num_singles)
        self.chunk_size = max(1, int(chunk_size))
        self.fill = float(fill)
        self.use_pallas = use_pallas
        self.mesh = mesh
        self.data_axis = data_axis
        self.plan = plan_windows(rects[:num_singles], engine.img_size,
                                 engine.kernel_hw, engine.strides[0],
                                 engine.pads)
        self.fe = np.ones((np.asarray(rects).shape[0],), np.float64)
        self.fe_first = float(num_singles)
        self.fe_pairs = float(self.fe[num_singles:].sum())
        # already conservative: every folded entry is credited a FULL
        # forward although its stem never ran, which over-covers the one
        # clean stem pass (a small fraction of a forward) per image
        self.cache_fe = 0.0

    def phase1(self, params, imgs):
        eng = self.engine
        b, h, w, ci = imgs.shape
        n = len(self.plan)
        # program-boundary cast: callers keep f32 batches (stable jit cache
        # keys); under the bf16 certify bank the image, the fill-delta `u`
        # and the occ windows all flow at compute_dtype, with f32
        # accumulation inside `_delta_conv` and f32 margin readout
        if imgs.dtype != self.compute_dtype:
            imgs = imgs.astype(self.compute_dtype)
        xn = eng.normalize(imgs)
        clean = eng.module.apply(params, xn, "stem")
        u = eng.norm_scale * (self.fill - imgs)
        kernel = eng.kernel_fn(params)
        # fold AND trunk per mask chunk so the live folded-stem tensor
        # stays within the chunk_size memory contract: a stem map is
        # (h'*w'*C')/(H*W*C) times an input image (e.g. ~21x for the CIFAR
        # 3x3/1 64-channel stem), so the mask-chunk width shrinks by that
        # inflation — never materializing all N stem maps at once. Chunks
        # are a Python-unrolled loop in ONE jitted program (static ragged
        # tail, no padding, no retrace).
        inflation = float(np.prod(clean.shape[1:])) / float(h * w * ci)
        c = max(1, min(n, int(self.chunk_size / max(1.0, inflation))))
        # kernel tier: resolved at trace time by the shared gate. On a
        # multi-device mesh the kernel runs per shard under shard_map
        # (`fold_masked_stem_sharded` — the DP603-proved form); batches the
        # data axis does not divide fall back to the partitionable XLA fold.
        mesh = self.mesh
        on_mesh = (mesh is not None
                   and getattr(mesh, "devices", None) is not None
                   and mesh.devices.size > 1)
        divisible = (not on_mesh) or b % mesh.shape[self.data_axis] == 0
        mode = _backend.resolve_use_pallas(self.use_pallas, mesh=mesh,
                                           divisible=divisible)
        preds, margins = [], []
        for off in range(0, n, c):
            part = self.plan[off:off + c]
            if mode == "off":
                folded = fold_masked_stem(kernel, clean, u, part,
                                          eng.strides, eng.pads)
            elif on_mesh:
                folded = fold_masked_stem_sharded(
                    kernel, clean, u, part, eng.strides, eng.pads,
                    mesh, self.data_axis,
                    interpret=(mode == "interpret"))  # [B, c', ...]
            else:
                folded = fold_masked_stem_kernel(
                    kernel, clean, u, part, eng.strides, eng.pads,
                    interpret=(mode == "interpret"))  # [B, c', ...]
            logits = eng.module.apply(
                params, folded.reshape((-1,) + folded.shape[2:]), "trunk")
            p, m = _preds_margins(logits)
            preds.append(p.reshape(b, len(part)))
            margins.append(m.reshape(b, len(part)))
        return (jnp.concatenate(preds, axis=1),
                jnp.concatenate(margins, axis=1))


class StemFoldEngine:
    """Masked-stem incremental inference for one conv victim.

    `module.apply(params, x, "stem")` must yield the bias-free linear stem
    conv output and `"trunk"` must complete the forward from it (see
    `models.small.CifarResNet18` / `models.resnetv2.ResNetV2`);
    `kernel_fn(params)` returns the EFFECTIVE HWIO stem kernel with any
    weight transform (ResNetV2 standardization) folded in. Built by
    `models.registry.get_model`; consumed by `defense.build_defenses`."""

    kind = "stem"

    def __init__(self, module, img_size: int,
                 kernel_fn: Callable, kernel_hw: int,
                 strides: Tuple[int, int],
                 pads,
                 normalize: Optional[Callable] = None,
                 norm_scale: float = 2.0):
        self.module = module
        self.img_size = int(img_size)
        self.kernel_fn = kernel_fn
        self.kernel_hw = int(kernel_hw)
        self.strides = tuple(strides)
        self.pads = (tuple(pads[0]), tuple(pads[1]))
        self.normalize = normalize or (lambda x: (x - 0.5) / 0.5)
        self.norm_scale = float(norm_scale)

    def build_family(self, rects: np.ndarray, num_singles: int,
                     chunk_size: int, fill: float,
                     use_pallas: str = "auto", mesh=None,
                     data_axis: str = "data",
                     compute_dtype: str = "float32") -> StemFoldFamily:
        return StemFoldFamily(self, rects, num_singles, chunk_size, fill,
                              use_pallas=use_pallas, mesh=mesh,
                              data_axis=data_axis,
                              compute_dtype=compute_dtype)
