#!/usr/bin/env python
"""Headline benchmark: patch-optimization throughput of the jitted DorPatch
stage-1 step (EOT=128 occlusion samples — the reference's sampling_size,
`/root/reference/attack.py:53` — ResNetV2-50x1 BiT @224) vs the torch CPU
reference path (BASELINE.json config 1: single image, EOT=1).

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": "images/sec", "vs_baseline": N}

"images/sec" counts images receiving one full optimization iteration — the
fused (mask-sample -> rasterize -> mask-apply -> EOT-way fwd+bwd -> CW/TV/
density losses -> signed-grad update -> on-device bookkeeping) step of
`dorpatch_tpu.attack.DorPatch._step`, which replaces the reference's inner
loop (`/root/reference/attack.py:184-342`). The baseline row is measured
in-process: one fwd+bwd of the torch ResNetV2-50 oracle on CPU per image
(EOT=1), the reference's per-iteration unit cost at sampling_size=1.

Architecture: the orchestrator (this process) never imports jax/torch; each
measurement runs in a child process with a hard deadline, because
remote-tunneled TPU backends can hang indefinitely (device claim or remote
compile) and a wedged child must not take the benchmark down. If the
accelerator child misses its deadline, the benchmark reruns on CPU with the
small CIFAR victim (axon tunnel stripped from PYTHONPATH) so the driver
always gets its JSON line — tagged `"fallback": "cpu"`.

MFU accounting: the victim's forward FLOPs come from XLA's own cost model
(`jit(fwd).lower().compile().cost_analysis()["flops"]`), useful work per
step = 3x forward (fwd+bwd) x EOT x batch, divided by measured step time and
the chip's peak bf16 FLOP/s (BENCH_PEAK_TFLOPS overrides; default 197 for
TPU v5e/"v5 lite", 275 for v4). The division itself is the shared
`observe.StepTimer.summary` formula — the same accounting the offline
telemetry report uses — so the bench's MFU row cannot drift from the
framework's. Rematerialization (off by default here;
re-enabled automatically on OOM) re-executes the forward, so its extra FLOPs
are real but not "useful" — MFU is reported on the 3x count either way.

Env overrides: BENCH_MODE ("attack" default; "certify" times the
PatchCleanser 666-mask certification path instead — see `_certify_bench`;
"boot" measures cold vs AOT-warm serve boot wall-clock against a throwaway
executable store — see `child_boot`; "recert" measures one full
re-certification generation — grid submit -> in-process farm drain ->
harvest/fold/verdict — on the tiny synthetic victim, see `child_recert`),
BENCH_BATCH (default 4), BENCH_EOT (128 — the reference sampling_size;
r03 measured batch 4 x EOT 128 fitting v5e HBM without remat), BENCH_BLOCK (8 steps
per jitted block), BENCH_REPS (3 timed blocks), BENCH_WARMUP (3 untimed
steady-state warm-up calls after compile — see the warm-up note in
`child_jax`), BENCH_TORCH_ITERS (3), BENCH_ARCH / BENCH_DATASET / BENCH_IMG
(model selection), BENCH_REMAT (0/1, default 0 = no remat, auto-falls-back
to 1 on OOM), BENCH_REMAT_POLICY (full|conv|dots — what an active remat
recomputes, see AttackConfig.remat_policy), BENCH_GN (GroupNorm impl for
ResNetV2 victims: "auto" = fused Pallas kernel on single-chip TPU, "flax" =
XLA path — see ops/fused_gn.py), BENCH_PEAK_TFLOPS, BENCH_JAX_TIMEOUT (seconds, default 1800 —
first-time Mosaic kernel compiles through the remote tunnel can add many
minutes),
BENCH_INCR (certify mode: off|on|ab — mask-aware incremental forwards; "ab"
times the incremental engine vs the PR 5 pruned-only path on the same batch,
asserts parity per the family's exactness contract, and prints incr_speedup
plus forward_equivalents_per_image — see `_certify_bench`),
BENCH_KERNEL (certify mode: on|off|ab, default "on" — the Pallas kernel
tier's use_pallas gate; "ab" times the engine-backed certify with kernels
off vs the production gate, asserts the kernels' exactness contracts, and
prints kernel_speedup plus each side's static bytes-accessed and
flops/byte — see `_certify_bench`),
BENCH_TORCH_TIMEOUT (default 600), BENCH_TOTAL_BUDGET (seconds, default
3000 — a hard wall budget across ALL children; every child's timeout is
clipped so the orchestrator always prints its JSON line before an outer
driver timeout can kill it; see `_Deadline`).
"""

from __future__ import annotations

import json
import os
import signal
import subprocess
import sys
import time


def log(msg: str) -> None:
    print(msg, file=sys.stderr, flush=True)


# ---------------------------------------------------------------- children


def child_torch() -> None:
    """Config-1 oracle: single-image EOT=1 fwd+bwd steps/sec on CPU.

    In certify mode (BENCH_MODE=certify): the per-image cost of one
    PatchCleanser radius = 666 masked forwards (`PatchCleanser.py:70-112`),
    extrapolated from one timed 36-mask chunk (the full sweep measures
    ~230 s/image on an idle CPU — same arithmetic, 18.5x the chunk)."""
    import torch

    from dorpatch_tpu.backends.torch_models import create_torch_model

    arch = os.environ.get("BENCH_ARCH", "resnetv2")
    img = int(os.environ.get("BENCH_IMG", "224"))
    n_classes = {"imagenet": 1000, "cifar10": 10, "cifar100": 100}[
        os.environ.get("BENCH_DATASET", "imagenet")]
    iters = int(os.environ.get("BENCH_TORCH_ITERS", "3"))

    torch.manual_seed(0)
    model = create_torch_model(arch, n_classes).eval()

    if os.environ.get("BENCH_MODE") == "certify":
        import numpy as np

        from dorpatch_tpu import masks as masks_lib
        from dorpatch_tpu.backends import torch_attack as ta

        spec = masks_lib.geometry(img, 0.06, 1, 6)
        singles, doubles = masks_lib.mask_sets(spec)
        n_total = singles.shape[0] + doubles.shape[0]
        x = torch.rand(1, 3, img, img)
        with torch.no_grad():
            keep = ta.rects_to_masks(np.asarray(singles), img)
            # warm up at the SAME batch shape as the timed call: the first
            # forward at a new shape pays allocation + thread-pool ramp-up
            model(ta.apply_masks(x, keep, 0.5))
            t0 = time.perf_counter()
            model(ta.apply_masks(x, keep, 0.5))
            chunk_dt = time.perf_counter() - t0
        per_image = chunk_dt * (n_total / singles.shape[0])
        print(json.dumps({"ips": 1.0 / per_image,
                          "extrapolated_from_masks": int(singles.shape[0]),
                          "masks_per_image": int(n_total)}))
        return

    x = torch.rand(1, 3, img, img)
    pattern = torch.rand(1, 3, img, img, requires_grad=True)

    def step():
        logits = model(x * 0.5 + pattern * 0.5)
        loss = logits.square().mean()  # stand-in scalar loss; cost ~= CW margin
        loss.backward()
        pattern.grad = None

    step()  # warm-up
    t0 = time.perf_counter()
    for _ in range(iters):
        step()
    dt = time.perf_counter() - t0
    print(json.dumps({"ips": iters / dt}))


def _peak_tflops(devices) -> float:
    """Best-effort peak bf16 TFLOP/s of the attached chip (overridable)."""
    env = os.environ.get("BENCH_PEAK_TFLOPS")
    if env:
        return float(env)
    kind = " ".join(str(getattr(d, "device_kind", d)) for d in devices[:1]).lower()
    for tag, peak in (("v5 lite", 197.0), ("v5e", 197.0), ("v5p", 459.0),
                      ("v4", 275.0), ("v6", 918.0)):
        if tag in kind:
            return peak
    # unrecognized device (e.g. the CPU fallback): no defensible peak ->
    # report no MFU rather than a bogus one
    return 0.0


def child_jax() -> None:
    """Timed jitted stage-1 attack blocks; prints
    {"ips": ..., "batch": ..., "mfu": ..., "remat": ...}."""
    import jax
    import jax.numpy as jnp

    from dorpatch_tpu import losses, utils
    from dorpatch_tpu import masks as masks_lib
    from dorpatch_tpu.attack import DorPatch
    from dorpatch_tpu.config import AttackConfig
    from dorpatch_tpu.models import get_model

    # repeated bench children recompile the same programs; through the
    # tunnel that is minutes each — share one persistent XLA cache
    utils.enable_compilation_cache()

    dataset = os.environ.get("BENCH_DATASET", "imagenet")
    arch = os.environ.get("BENCH_ARCH", "resnetv2")
    img = int(os.environ.get("BENCH_IMG", "224"))
    batch = int(os.environ.get("BENCH_BATCH", "4"))
    eot = int(os.environ.get("BENCH_EOT", "128"))
    block_steps = int(os.environ.get("BENCH_BLOCK", "8"))
    reps = int(os.environ.get("BENCH_REPS", "3"))
    remat = os.environ.get("BENCH_REMAT", "0") == "1"
    # bf16 EOT fwd+bwd is the TPU-native default for the throughput metric;
    # the torch fp32 baseline measures the reference design, not ours. If
    # this child silently landed on the CPU backend (no accelerator), bf16
    # would be emulated and *slower* — default to f32 there instead.
    dtype = os.environ.get("BENCH_DTYPE")
    if dtype is None:
        dtype = "float32" if jax.default_backend() == "cpu" else "bfloat16"
    # short aliases (the certify precision surface): f32|bf16|ab
    dtype = {"f32": "float32", "bf16": "bfloat16"}.get(dtype, dtype)

    log(f"jax devices: {jax.devices()} dtype: {dtype}")

    if os.environ.get("BENCH_MODE") == "certify":
        _certify_bench(dataset, arch, img, batch, dtype, reps)
        return

    def fwd_flops(victim, params) -> float:
        """XLA's cost model for one EOT-batch forward (per masked image)."""
        n = batch * eot
        shaped = jax.ShapeDtypeStruct(
            (n, img, img, 3),
            jnp.bfloat16 if dtype == "bfloat16" else jnp.float32)
        try:
            compiled = jax.jit(victim.apply).lower(params, shaped).compile()
            analysis = compiled.cost_analysis()
            if isinstance(analysis, list):  # older jax returns per-device list
                analysis = analysis[0]
            return float(analysis["flops"]) / n
        except Exception as e:
            log(f"cost_analysis unavailable ({e}); mfu omitted")
            return 0.0

    def run(batch: int, remat: bool) -> dict:
        victim = get_model(dataset, arch, img_size=img,
                           gn_impl=os.environ.get("BENCH_GN") or "auto")
        cfg = AttackConfig(sampling_size=eot, compute_dtype=dtype,
                           remat_policy=os.environ.get(
                               "BENCH_REMAT_POLICY") or "full")
        attack = DorPatch(victim.apply, victim.params, victim.num_classes, cfg,
                          remat=remat)
        universe = jnp.asarray(
            masks_lib.dropout_universe(img, cfg.dropout, cfg.dropout_sizes))
        key = jax.random.PRNGKey(0)
        x = jax.random.uniform(key, (batch, img, img, 3))
        y = jnp.zeros((batch,), jnp.int32)
        local_var_x = jnp.mean(losses.local_variance(x)[0], axis=-1)
        state = attack._init_state(key, x, y, False, universe.shape[0])

        block = attack._get_block(1, img, block_steps)
        t0 = time.perf_counter()
        state = block(state, x, local_var_x, universe)
        jax.block_until_ready(state.adv_pattern)
        log(f"compile+first block: {time.perf_counter() - t0:.1f}s")

        # Warm-up: the first few invocations of a freshly compiled executable
        # through the remote tunnel run orders of magnitude slower than
        # steady state (measured: 10-20s/call decaying to <1s/call with no
        # change in args). Time only the steady state the production pipeline
        # (thousands of block calls per image) actually runs at.
        warmup = int(os.environ.get("BENCH_WARMUP", "3"))
        for i in range(warmup):
            t0 = time.perf_counter()
            state = block(state, x, local_var_x, universe)
            jax.device_get(state.metrics)
            log(f"warmup call {i}: {time.perf_counter() - t0:.2f}s")

        # the timed region ends with a genuine device->host transfer of a
        # small output (not just block_until_ready, which this backend has
        # been observed resolving early on warm executables — PERF.md traps)
        from dorpatch_tpu import observe

        timer = observe.StepTimer()
        timer.start()
        for _ in range(reps):
            state = block(state, x, local_var_x, universe)
        jax.device_get(state.metrics)
        dt = timer.stop()
        step_seconds = dt / (block_steps * reps)

        # MFU through the ONE shared formula (observe.StepTimer.summary):
        # useful FLOPs per step = fwd+bwd = 3x fwd (remat recompute
        # excluded), forward count from XLA's own cost model of the compiled
        # victim, over the chip's peak.
        f_fwd = fwd_flops(victim, victim.params)
        peak = _peak_tflops(jax.devices()) * 1e12
        s = timer.summary(steps_per_block=block_steps * reps, batch=batch,
                          flops_per_step=3.0 * f_fwd * batch * eot,
                          peak_flops=peak)
        return {
            "ips": batch / step_seconds,
            "batch": batch,
            "backend": jax.default_backend(),
            # the EOT fwd+bwd precision this row measured (short form; the
            # certify child stamps its DefenseConfig.compute_dtype here)
            "compute_dtype": "bf16" if dtype == "bfloat16" else "f32",
            "remat": remat,
            "mfu": s.get("mfu"),
            "step_seconds": round(step_seconds, 4),
            "fwd_gflops_per_image": round(f_fwd / 1e9, 2) if f_fwd else None,
            # per-masked-sample throughput: the EOT batch is `eot` fwd+bwd
            # passes per image-iteration, the unit the torch baseline row
            # (EOT=1) pays once — the apples-to-apples per-sample speedup
            "masked_images_per_sec": round(batch * eot / step_seconds, 1),
        }

    while True:
        try:
            res = run(batch, remat)
            break
        except Exception as e:
            if "RESOURCE_EXHAUSTED" not in str(e):
                raise
            if not remat:  # first OOM: trade FLOPs for memory before batch
                log("OOM without remat; retrying with remat")
                remat = True
            elif batch > 1:
                log(f"batch={batch} OOM; retrying with {batch // 2}")
                batch //= 2
            else:
                raise
    print(json.dumps(res))


def _certify_bench(dataset, arch, img, batch, dtype, reps) -> None:
    """PatchCleanser certification throughput (BASELINE config 3): one
    radius (0.06) = 36 single + 630 double masked forwards per image, the
    reference's per-image certification cost (`PatchCleanser.py:70-112`),
    batched and jitted. Prints {"ips": certified images/sec, ...}.

    BENCH_PRUNE selects the double-masking schedule ("exact" default,
    "off", "consensus" — see DefenseConfig.prune) or "ab", which times the
    pruned AND the exhaustive path on the same images, asserts verdict
    parity, and reports the measured speedup — a real number on any
    backend. Half the bench batch carries a planted high-contrast square
    so masked predictions disagree, as they do on the eval pipeline's
    adversarial inputs (the workload pruning targets); the other half
    stays benign. Per-image executed forwards and the prune rate come
    from the records' own accounting.

    BENCH_INCR selects the mask-aware incremental forwards
    (DefenseConfig.incremental; "off" default): "on" runs the family's
    resolved engine (token-pruned ViT / mixer-pruned ResMLP / masked-stem
    conv); "ab" times the incremental path vs the PR 5 pruned-only path on
    the same batch, asserts parity — bit-exact for the exact-contract
    families (stem); for the tolerance-contracted token/mixer families
    every verdict mismatch must have been margin-flagged (its min
    evaluated top-2 logit gap below DefenseConfig.incremental_margin,
    i.e. the escalation signal the "-exact" modes act on caught it) —
    and prints `incr_speedup` plus
    `forward_equivalents_per_image`, the mandatory first-round sweep's
    per-image cost in full-forward units (36.0 un-pruned; every certified
    image pays this floor). `forward_equivalents_total_per_image` is the
    whole certify's fractional cost, and MFU credits fractional forwards.

    BENCH_DTYPE selects the certify bank precision
    (DefenseConfig.compute_dtype): "f32"/"float32", "bf16"/"bfloat16"
    (engine families included — the bank casts params once and escalates
    small-margin images through the f32 exhaustive program), or "ab",
    which times BOTH banks on the same batch, asserts
    verdict-parity-or-margin-flagged, and reports `dtype_speedup` plus
    `dtype_bytes_ratio` (predicted phase-1 HBM bytes, bf16/f32 — strictly
    < 1 by the baseline cost model's itemsize pricing). Every certify row
    stamps the precision it ran as `compute_dtype`.

    BENCH_STREAM ("1" default at BENCH_IMG >= 128) sources the bench
    batch through `data.streaming_batches` — the chunked background
    loader + double-buffered device prefetcher the pipeline's 224-input
    eval loop rides — instead of on-device RNG (BENCH_SOURCE picks the
    stream: "synthetic" default, "procedural" for the learnable task).
    BENCH_EVENTS=<dir> writes the run's events.jsonl there — the streaming
    loader's prefetch/wait telemetry included, so `observe.report` renders
    overlap for a bench-only run. The timed reps always execute under the
    ARMED recompile watchdog with trace counts frozen after warmup: a
    retrace fails the row instead of silently timing compilation.

    BENCH_KERNEL gates the Pallas kernel tier (DefenseConfig.use_pallas;
    "on" default = the production "auto" gate): "off" pins the XLA tier,
    "ab" times the engine-backed pruned certify with kernels off vs the
    production gate on the same batch — verdict parity asserted per the
    kernels' exactness contracts (stem bit-exact at CPU f32, attention
    margin-contracted) — and prints `kernel_speedup` plus each side's
    static bytes-accessed and flops/byte (`kernel_roofline`, from the
    baseline cost model over the phase-1 jaxpr; on CPU without a mesh the
    gate resolves off so the timed sides match and the row is a
    no-regression floor — under BENCH_MESH on CPU the on-side runs
    "interpret" instead, so the A/B exercises the real shard_map kernel
    wrappers with the usual parity assertion).

    BENCH_MESH="DxM" (e.g. "4x2") runs the whole certify on a (data=D,
    mask=M) device mesh: the exhaustive sweep shards as before, the pruned
    path plans its phase-2 worklists shard-locally (defense._schedule_mesh)
    — so BENCH_PRUNE=ab on a mesh A/Bs sharded-pruned vs sharded-exhaustive
    with the same parity contract, and the BENCH row carries the mesh shape
    plus the predicted DP600 comm vector (`comm_bytes`, explicit-collective
    bytes of the phase-1 program) next to the measured wall-clock.
    BENCH_KERNEL=ab composes with BENCH_MESH: both engine kernels run
    through their shard_map wrappers and each roofline side stamps its
    comm_bytes (zero IS the shard-local claim)."""
    import jax
    import jax.numpy as jnp

    from dorpatch_tpu import data as data_lib, parallel
    from dorpatch_tpu.config import DefenseConfig
    from dorpatch_tpu.defense import build_defenses
    from dorpatch_tpu.models import get_model

    prune = os.environ.get("BENCH_PRUNE") or "exact"
    incr = os.environ.get("BENCH_INCR") or "off"
    kern = os.environ.get("BENCH_KERNEL") or "on"
    # "on" is the production gate (DefenseConfig default "auto": kernels
    # resolve on where the backend supports them); "off" forces the XLA
    # tier everywhere, so timed rows can pin either side of the A/B.
    kern_gate = {"on": "auto", "off": "off"}.get(kern, "auto")
    mesh = None
    mesh_env = os.environ.get("BENCH_MESH") or ""
    if mesh_env:
        d_ax, m_ax = (int(v) for v in mesh_env.split("x"))
        if jax.device_count() < d_ax * m_ax:
            raise AssertionError(
                f"BENCH_MESH={mesh_env} needs {d_ax * m_ax} devices, have "
                f"{jax.device_count()} (on CPU: XLA_FLAGS="
                f"--xla_force_host_platform_device_count={d_ax * m_ax})")
        mesh = parallel.make_mesh(d_ax, m_ax)
    victim = get_model(dataset, arch, img_size=img,
                       gn_impl=os.environ.get("BENCH_GN") or "auto")
    apply_fn = victim.apply
    # certify precision is a first-class defense axis
    # (DefenseConfig.compute_dtype): the certifier builds its own bf16
    # program bank — engine families included — under the margin-escalation
    # contract, so no apply_fn wrapper and no f32 fallback for BENCH_INCR.
    # BENCH_DTYPE=ab times BOTH banks on the same batch (below).
    if dtype not in ("float32", "bfloat16", "ab"):
        raise AssertionError(
            f"BENCH_DTYPE={dtype!r} (certify mode takes f32|bf16|ab)")
    cdt = "bfloat16" if dtype == "bfloat16" else "float32"

    def make_defense(mode, incremental="off", use_pallas=None,
                     compute_dtype=None):
        cfg = DefenseConfig(ratios=(0.06,), chunk_size=128, prune=mode,
                            incremental=incremental,
                            use_pallas=use_pallas or kern_gate,
                            compute_dtype=compute_dtype or cdt)
        engine = victim.incremental if incremental != "off" else None
        if mesh is not None:
            return parallel.make_sharded_defenses(
                apply_fn, img, mesh, cfg, incremental=engine)[0]
        return build_defenses(apply_fn, img, cfg, incremental=engine)[0]

    key = jax.random.PRNGKey(0)
    from dorpatch_tpu import observe

    elog = None
    ev_dir = os.environ.get("BENCH_EVENTS")
    if ev_dir:
        # drop this run's events.jsonl into BENCH_EVENTS: the streaming
        # loader's `data.prefetch` spans and `data.stream.wait` events land
        # there, so `observe.report` shows prefetch overlap for a
        # bench-only run exactly like a pipeline results dir
        os.makedirs(ev_dir, exist_ok=True)
        elog = observe.EventLog(os.path.join(ev_dir, "events.jsonl"),
                                run_id="bench-certify")
        elog.__enter__()
    stream = os.environ.get("BENCH_STREAM",
                            "1" if img >= 128 else "0") == "1"
    if stream:
        # the production-224 input path: the bench batch arrives through
        # the chunked background loader + double-buffered device
        # prefetcher (data.streaming_batches) instead of on-device RNG —
        # the same path the pipeline's eval loop and serve warmup ride
        src = os.environ.get("BENCH_SOURCE") or "synthetic"
        batch_iter = data_lib.streaming_batches(
            dataset, os.environ.get("BENCH_DATA_DIR") or "data/", batch,
            img_size=img, seed=0, source=src, depth=2, mesh=mesh)
        x_np, _ = next(batch_iter)
        batch_iter.close()
        x = jnp.asarray(x_np)
        log(f"bench batch streamed ({src}, img={img})")
    else:
        x = jax.random.uniform(key, (batch, img, img, 3))
    q = max(4, img // 8)
    # the disagreement inducer, interleaved rather than a contiguous block:
    # single-chip scheduling is order-blind, but the meshed pruned path
    # plans worklists per contiguous data-axis shard — a sorted batch would
    # pile every dense-route image onto one shard (all-padding waves
    # elsewhere), which benchmarks ownership skew, not the schedule. Real
    # eval batches arrive shuffled.
    x = x.at[1::2, :q, :q, :].set(1.0)
    # power-of-two ladder (denser than the serving default): the measured
    # quantity is the certify schedule, not bucket-padding luck — with the
    # sparse (1, 8) ladder a 2-image pair worklist pads 4x while a 1-image
    # one rides bucket 1, and that noise can dwarf the path under test.
    # Both A/B sides use the same ladder, so comparisons stay fair.
    buckets = tuple(sorted({2 ** i for i in range(max(1, batch.bit_length() - 1))}
                           | {1, batch}))
    if mesh is not None:
        # meshed certifiers keep the exact batch (begin_pruned never pads
        # on a mesh; phase 2 buckets at its own [S * bucket] wave shapes)
        buckets = None

    from dorpatch_tpu import observe

    warmup = int(os.environ.get("BENCH_WARMUP", "3"))

    def time_mode(mode, xx, incremental="off", use_pallas=None,
                  compute_dtype=None):
        d = make_defense(mode, incremental, use_pallas, compute_dtype)
        if mesh is not None:
            # sharded over the data axis when it divides the batch; the
            # eager refresh arithmetic below preserves the placement
            xx = parallel.place_batch_auto(mesh, xx)
        t0 = time.perf_counter()
        d.robust_predict(victim.params, xx, victim.num_classes,
                         bucket_sizes=buckets)
        log(f"[{mode}/incr={incremental}] compile+first certify: "
            f"{time.perf_counter() - t0:.1f}s")
        for i in range(warmup):
            t0 = time.perf_counter()
            # fresh buffers: defeat same-args memoization
            xx = xx * 0.999 + 0.0005
            d.robust_predict(victim.params, xx, victim.num_classes,
                             bucket_sizes=buckets)
            log(f"[{mode}/incr={incremental}] warmup call {i}: "
                f"{time.perf_counter() - t0:.2f}s")
        timer = observe.StepTimer()
        recs = None
        # the timed reps must not retrace: freeze the programs' trace
        # counts after warmup and run the reps under the ARMED recompile
        # watchdog, so a shape leak fails the bench loudly instead of
        # quietly timing compilation
        from dorpatch_tpu.analysis.sanitize import Sanitizer

        warm_traces = d.pruned_trace_counts()
        with Sanitizer(debug_nans=False, log_compiles=False):
            for _ in range(reps):
                xx = xx * 0.999 + 0.0005
                timer.start()
                recs = d.robust_predict(victim.params, xx,
                                        victim.num_classes,
                                        bucket_sizes=buckets)
                # robust_predict materializes records: a real transfer
                timer.stop()
        if d.pruned_trace_counts() != warm_traces:
            raise AssertionError(
                f"[{mode}/incr={incremental}] recompiled during timed "
                f"reps: {warm_traces} -> {d.pruned_trace_counts()}")
        return d, xx, sum(timer.block_seconds) / reps, recs

    prune_stats = {"prune": prune}
    if mesh is not None:
        prune_stats["mesh"] = f"{d_ax}x{m_ax}"
    if dtype == "ab":
        # precision A/B: the SAME pruned schedule on the SAME batch at
        # both certify banks, differing only in
        # DefenseConfig.compute_dtype. The timed headline is the bf16
        # side (the new bank); dtype_speedup is its win over f32. Parity
        # is verdict-identical-or-margin-flagged: the bf16 bank already
        # re-certifies every small-margin image through the f32 exhaustive
        # program (defense._PrunedPending._escalate), so any REMAINING
        # mismatch must itself sit below the escalation margin (an argmax
        # boundary ULP flip between the two padded program shapes) —
        # a mismatch at a comfortable margin is a bank bug: hard fail.
        base_prune = "exact" if prune == "off" else prune
        incr_mode_req = "auto" if incr == "on" else "off"
        d32, x_final, dt32, recs32 = time_mode(
            base_prune, x, incremental=incr_mode_req,
            compute_dtype="float32")
        d, _, dt, recs = time_mode(
            base_prune, x, incremental=incr_mode_req,
            compute_dtype="bfloat16")
        mism = [i for i, (a, b) in enumerate(zip(recs32, recs))
                if (a.prediction, a.certification) != (b.prediction,
                                                       b.certification)]
        tol = d.config.incremental_margin
        unflagged = [i for i in mism
                     if d.last_min_margin is None
                     or d.last_min_margin[i] >= tol]
        if unflagged:
            raise AssertionError(
                f"bf16 bank flipped {len(unflagged)} verdict(s) at "
                f"margins >= {tol} — the escalation contract should have "
                "caught them")
        prune_stats.update({
            "incr": d.resolved_incremental(incr_mode_req),
            "ips_f32": round(batch / dt32, 4),
            "dtype_speedup": round(dt32 / dt, 3),
            "parity": not mism,
            "parity_mismatches": len(mism),
        })
        # predicted HBM traffic of the dominant program under each bank
        # (the baseline cost model prices bytes by aval itemsize, so the
        # bf16 phase-1 program must come out strictly lighter — the same
        # invariant tools/certify_bf16_smoke.py gates on the checked-in
        # baselines). Estimate-only: failure just omits the numbers.
        try:
            from dorpatch_tpu.analysis import baseline as baseline_lib
            from dorpatch_tpu.analysis.entrypoints import _unwrap

            bytes_by = {}
            for dd, tagd in ((d32, "f32"), (d, "bf16")):
                jaxpr = jax.make_jaxpr(_unwrap(dd._phase1))(
                    dd._cast_params(victim.params), x_final)
                bytes_by[tagd] = baseline_lib.estimate_cost(
                    jaxpr)["est_bytes"]
            if bytes_by.get("f32"):
                prune_stats["dtype_bytes_ratio"] = round(
                    bytes_by["bf16"] / bytes_by["f32"], 3)
        except Exception as e:  # noqa: BLE001 - reporting axis only
            log(f"dtype bytes-ratio estimate unavailable ({e})")
    elif prune == "ab":
        d, x_final, dt_ex, recs_ex = time_mode("off", x)
        _, _, dt, recs = time_mode("exact", x)
        mismatches = sum(
            1 for a, b in zip(recs_ex, recs)
            if (a.prediction, a.certification) != (b.prediction,
                                                   b.certification))
        # the skipped-entry argument guarantees parity only for identical
        # numerics; phase-1/pair programs compile at different shapes than
        # the one-program sweep, so on accelerators (and in bf16) a masked
        # logit sitting on the argmax boundary may flip between paths in
        # ULPs. Hard-fail only where the comparison is meaningful (CPU
        # f32, the CI smoke case); elsewhere report the count.
        if mismatches and jax.default_backend() == "cpu" \
                and dtype == "float32":
            raise AssertionError(
                f"pruned/exhaustive verdict parity broke on {mismatches} "
                f"image(s) at f32 on cpu — a scheduling bug, not numerics")
        prune_stats.update({
            "ips_exhaustive": round(batch / dt_ex, 4),
            "prune_speedup": round(dt_ex / dt, 3),
            "parity": mismatches == 0,
            "parity_mismatches": mismatches,
        })
    elif kern == "ab":
        # kernel-tier A/B: the SAME engine-backed pruned schedule on both
        # sides, differing only in DefenseConfig.use_pallas ("off" pins
        # the XLA tier, "auto" is the production gate — kernels on where
        # the backend supports them, so on CPU both sides lower
        # identically and the row is a no-regression floor). Parity uses
        # the engines' own exactness contracts: the stem kernel shares
        # its delta-conv with the fold (bit-exact, hard-fail at CPU f32),
        # the attention kernel is tolerance-contracted like the engine
        # itself (mismatches must be margin-flagged).
        kind = getattr(victim.incremental, "kind", None)
        if kind is None:
            raise AssertionError(
                f"BENCH_KERNEL=ab but arch {arch!r} resolved no "
                "incremental engine — pick a ViT/ResMLP/conv family")
        raw_mode = {"token": "token", "mixer": "mixer"}.get(kind, "auto")
        base_prune = "exact" if prune == "off" else prune
        on_gate = "auto"
        if mesh is not None and jax.default_backend() == "cpu":
            # on a CPU mesh "auto" resolves off and the A/B would be
            # vacuous; "interpret" runs the real shard_map kernel wrappers
            # (parity + recompile accounting on the exact meshed programs —
            # the speedup column then measures schedule overhead, not
            # kernel speed; on TPU "auto" stays the production gate)
            on_gate = "interpret"
        d_off, x_final, dt_off, recs_off = time_mode(
            base_prune, x, incremental=raw_mode, use_pallas="off")
        d, _, dt, recs = time_mode(
            base_prune, x, incremental=raw_mode, use_pallas=on_gate)
        incr_mode = d.resolved_incremental(raw_mode)
        mism = [i for i, (a, b) in enumerate(zip(recs_off, recs))
                if (a.prediction, a.certification) != (b.prediction,
                                                       b.certification)]
        if incr_mode == "stem":
            if mism and jax.default_backend() == "cpu" \
                    and dtype == "float32":
                raise AssertionError(
                    f"stem kernel verdict parity broke on {len(mism)} "
                    f"image(s) at f32 on cpu — a kernel bug, not numerics")
        else:
            tol = d.config.incremental_margin
            unflagged = [i for i in mism
                         if d.last_min_margin[i] >= tol]
            if unflagged:
                raise AssertionError(
                    f"kernel tier flipped {len(unflagged)} verdict(s) at "
                    f"margins >= {tol} — tolerance contract violated")
        prune_stats.update({
            "incr": incr_mode,
            "kernel": "ab",
            "kernel_speedup": round(dt_off / dt, 3),
            "parity": not mism,
            "parity_mismatches": len(mism),
        })
        # roofline axis for the A/B: static bytes-accessed + arithmetic
        # intensity of the engine's phase-1 program under each gate
        # ("interpret" keeps the pallas_call eqns in the jaxpr so the
        # fused-cost model prices them even on CPU; "off" is the XLA
        # lowering). Estimate-only — failure just omits the numbers.
        try:
            import numpy as np

            from dorpatch_tpu import masks as masks_lib
            from dorpatch_tpu.analysis import baseline as baseline_lib

            spec = masks_lib.geometry(img, 0.06)
            singles, doubles = masks_lib.mask_sets(spec)
            kk = max(singles.shape[1], doubles.shape[1])
            rects = np.concatenate([masks_lib.pad_rects(singles, kk),
                                    masks_lib.pad_rects(doubles, kk)])
            from dorpatch_tpu.analysis import comms as comms_lib

            ai = {}
            for gate, tag in (("interpret", "kernel"), ("off", "xla")):
                fam = victim.incremental.build_family(
                    rects, singles.shape[0], 128, 0.5, use_pallas=gate,
                    mesh=mesh)
                jaxpr = jax.make_jaxpr(fam.phase1)(victim.params, x)
                cost = baseline_lib.estimate_cost(jaxpr)
                ai[tag] = {"est_bytes": round(cost["est_bytes"], 1),
                           "flops_per_byte": round(cost["est_ai"], 3)}
                if mesh is not None:
                    # the DP600 comm vector of the meshed phase-1 program:
                    # zero explicit-collective bytes IS the shard-local
                    # wrapper claim, stamped next to the measured row
                    ai[tag]["comm_bytes"] = round(
                        comms_lib.comm_cost(jaxpr)["comm_bytes"], 1)
            prune_stats["kernel_roofline"] = ai
        except Exception as e:  # noqa: BLE001 - reporting axis only
            log(f"kernel roofline estimate unavailable ({e})")
    elif incr == "ab":
        # incremental A/B rides the production pruned schedule on both
        # sides: PR 5's pruned-only path vs the same schedule with the
        # family engine's incremental forwards. For the row-set engines
        # (ViT "token", ResMLP "mixer") the timed side is the RAW engine
        # mode — the production default ("token-exact"/"mixer-exact")
        # adds margin-gated escalation whose cost depends on the victim's
        # margin distribution (the bench's random-init victim is the
        # documented escalate-everything worst case), and its exactness
        # mechanism is covered by the margin-flag assertion below plus
        # the -exact parity fixtures in tests.
        base_prune = "exact" if prune == "off" else prune
        kind = getattr(victim.incremental, "kind", None)
        if kind is None:
            raise AssertionError(
                f"BENCH_INCR=ab but arch {arch!r} resolved no incremental "
                "engine — pick a ViT/ResMLP/conv family")
        raw_mode = {"token": "token", "mixer": "mixer"}.get(kind, "auto")
        d_off, x_final, dt_off, recs_off = time_mode(base_prune, x)
        d, _, dt, recs = time_mode(base_prune, x, incremental=raw_mode)
        incr_mode = d.resolved_incremental(raw_mode)
        mism = [i for i, (a, b) in enumerate(zip(recs_off, recs))
                if (a.prediction, a.certification) != (b.prediction,
                                                       b.certification)]
        if incr_mode == "stem":
            # the stem fold is algebraically exact: same hard-fail terms
            # as the prune A/B
            if mism and jax.default_backend() == "cpu" \
                    and dtype == "float32":
                raise AssertionError(
                    f"stem-fold verdict parity broke on {len(mism)} "
                    f"image(s) at f32 on cpu — a fold bug, not numerics")
        else:
            # row-set (token/mixer) parity is tolerance-contracted: every
            # mismatch must have been margin-flagged (min evaluated top-2
            # logit gap below incremental_margin — the signal the "-exact"
            # modes use to escalate; read off the timed run's own
            # pending). A high-margin mismatch means drift exceeded the
            # documented tolerance: fail.
            tol = d.config.incremental_margin
            unflagged = [i for i in mism
                         if d.last_min_margin[i] >= tol]
            if unflagged:
                raise AssertionError(
                    f"{incr_mode} drift flipped {len(unflagged)} "
                    f"verdict(s) at margins >= {tol} — tolerance contract "
                    "violated")
        prune_stats.update({
            "incr": incr_mode,
            "ips_pruned_only": round(batch / dt_off, 4),
            "incr_speedup": round(dt_off / dt, 3),
            "parity": not mism,
            "parity_mismatches": len(mism),
        })
    elif incr == "on":
        d, x_final, dt, recs = time_mode(prune, x, incremental="auto")
        prune_stats["incr"] = d.resolved_incremental()
    else:
        d, x_final, dt, recs = time_mode(prune, x)
    fwd = [max(0, r.forwards) for r in recs]
    fe = [max(0.0, r.forward_equivalents) for r in recs]
    prune_stats.update({
        "forwards_per_image": round(sum(fwd) / len(fwd), 1),
        # the mandatory first-round sweep's per-image cost in full-forward
        # units (the acceptance headline: 36.0 un-pruned, the token
        # engine's fraction of that under BENCH_INCR) and the whole
        # certify's fractional cost
        "forward_equivalents_per_image": round(
            d.first_round_forward_equivalents, 2),
        "forward_equivalents_total_per_image": round(sum(fe) / len(fe), 2),
        "prune_rate": round(
            1.0 - sum(fwd) / (len(fwd) * d.num_forwards_exhaustive), 4),
    })

    if mesh is not None:
        # every meshed certify row stamps the predicted DP600 comm vector
        # of its dominant program (the first-round phase-1 sweep) next to
        # the measured wall-clock: explicit-collective bytes only, so the
        # shard_map'd fills/kernels price exactly and a comm regression
        # shows in the BENCH history like a flop regression. Estimate-only
        # — failure just omits the numbers.
        try:
            from dorpatch_tpu.analysis import comms as comms_lib
            from dorpatch_tpu.analysis.entrypoints import _unwrap

            phase1 = getattr(d, "_phase1", None) or getattr(
                d, "_predict", None)
            jaxpr = jax.make_jaxpr(_unwrap(phase1))(victim.params, x_final)
            cv = comms_lib.comm_cost(jaxpr)
            prune_stats["comm_bytes"] = round(cv["comm_bytes"], 1)
            if cv["by_collective"]:
                prune_stats["comm_by_collective"] = {
                    k: round(v, 1) for k, v in cv["by_collective"].items()}
        except Exception as e:  # noqa: BLE001 - reporting axis only
            log(f"comm vector estimate unavailable ({e})")

    # certify-mode MFU through the shared observe.StepTimer.summary formula:
    # forward-only FLOPs (XLA's own count at the chunked sweep's batch
    # shape) x EXECUTED masked-forward rate over the chip peak — pruned
    # runs are credited only the forwards they dispatched, incremental
    # runs only the FRACTION of each forward they recomputed
    # (forward_equivalents); same guard as the attack child — unavailable
    # cost model just omits it
    n_masks = d.num_forwards_exhaustive
    executed = sum(fe)
    mfu = None
    try:
        chunk = min(d.config.chunk_size, n_masks)
        shaped = jax.ShapeDtypeStruct(
            (chunk, img, img, 3),
            # the timed side of BENCH_DTYPE=ab is the bf16 bank
            jnp.bfloat16 if dtype in ("bfloat16", "ab") else jnp.float32)
        compiled = jax.jit(victim.apply).lower(victim.params, shaped).compile()
        analysis = compiled.cost_analysis()
        if isinstance(analysis, list):
            analysis = analysis[0]
        f_fwd = float(analysis["flops"]) / chunk
        peak = _peak_tflops(jax.devices()) * 1e12
        t = observe.StepTimer()
        t.block_seconds = [dt]
        mfu = t.summary(steps_per_block=1, batch=batch,
                        flops_per_step=f_fwd * executed,
                        peak_flops=peak).get("mfu")
    except Exception as e:
        log(f"certify cost_analysis unavailable ({e}); mfu omitted")
    print(json.dumps({
        "ips": batch / dt,
        "batch": batch,
        "backend": jax.default_backend(),
        # certify sweep precision of the timed row (short form): the
        # DefenseConfig.compute_dtype bank that produced these numbers,
        # "ab" when both banks ran (dtype_speedup carries the ratio)
        "compute_dtype": {"float32": "f32", "bfloat16": "bf16",
                          "ab": "ab"}[dtype],
        "masks_per_image": int(n_masks),
        "masked_fwd_per_sec": round(executed / dt, 1),
        "seconds_per_batch": round(dt, 4),
        "mfu": mfu,
        **prune_stats,
    }))
    if elog is not None:
        elog.__exit__(None, None, None)


# ------------------------------------------------------------ orchestrator


class _Deadline:
    """Hard wall budget across all child processes.

    Round-3 failure mode (BENCH_r03.json): the jax child died fast on a
    dead tunnel, the retry + CPU-fallback children were still queued behind
    full default timeouts (2x1800s + 600s) when the *driver's* budget
    expired -> rc=124 and no JSON line at all. Every child timeout is now
    sliced from one shared budget, with reservations for the children that
    must still run afterwards, so the orchestrator always reaches its
    print() before any sane outer timeout."""

    def __init__(self, total_s: float, clock=time.monotonic):
        self._clock = clock
        self._deadline = clock() + total_s

    def remaining(self) -> float:
        return max(0.0, self._deadline - self._clock())

    def slice(self, want_s: float, reserve_s: float = 0.0) -> int:
        """Timeout for the next child: at most `want_s`, leaving at least
        `reserve_s` for children that must still run. 0 = don't spawn."""
        return int(max(0.0, min(want_s, self.remaining() - reserve_s)))


# Child-stderr signatures. Backend-init failures (dead/unreachable
# accelerator tunnel) make ANY accelerator retry pointless -- skip straight
# to the CPU fallback. Kernel signatures justify exactly one retry with the
# always-partitionable flax GN (ADVICE r03: don't burn a full extra timeout
# re-running an unrelated crash, e.g. HBM OOM or a dataset error).
_BACKEND_INIT_SIGNATURES = (
    "unable to initialize backend",
    "unavailable:",
    "failed to connect",
    "no tpu devices",
    "backend unavailable",
)
# NB deliberately no bare "memory space": XLA's HBM-OOM text ("Ran out of
# memory in memory space hbm") is NOT a kernel failure — the flax-GN retry
# would hit the same OOM. VMEM exhaustion ("memory space vmem") still
# matches via "vmem". Crash-type signal deaths (segfault/abort/illegal
# instruction — how a miscompiled kernel dies, leaving no traceback) are
# kernel-suspect via the marker run_child appends; SIGKILL is NOT listed
# (host OOM-killer — a retry would meet the same fate).
_KERNEL_SIGNATURES = ("mosaic", "pallas", "vmem",
                      "terminated by signal 11]",   # SIGSEGV
                      "terminated by signal 6]",    # SIGABRT
                      "terminated by signal 4]",    # SIGILL
                      "terminated by signal 7]")    # SIGBUS


def classify_failure(why: str, err_tail: str) -> str:
    """-> 'timeout' | 'backend-init' | 'kernel' | 'budget' | 'other'.

    'timeout': accelerator wedged; no software path can help.
    'backend-init': the jax runtime never came up (dead tunnel; the
      UNAVAILABLE / Unable-to-initialize text is in the child tail).
    'kernel': Mosaic/Pallas/VMEM signature -- a GN-kernel regression the
      flax-GN retry exists for.
    'budget': the child was never spawned (BENCH_TOTAL_BUDGET left too
      little after reserves) -- the accelerator was not even attempted.
    'other': unrelated child crash; retrying the same accelerator path
      with a different GN impl would meet the same fate."""
    if why in ("timeout", "budget"):
        return why
    tail = (err_tail or "").lower()
    if any(s in tail for s in _BACKEND_INIT_SIGNATURES):
        return "backend-init"
    if any(s in tail for s in _KERNEL_SIGNATURES):
        return "kernel"
    return "other"


def run_child(role: str, timeout_s: int, env_extra: dict):
    """-> (parsed JSON dict | None, reason, stderr_tail). reason is None on
    success, else "timeout" (accelerator wedged -- retrying a different
    software path cannot help) vs "crash"/"no-json" (child-side failure --
    see classify_failure for what the stderr tail distinguishes)."""
    env = dict(os.environ)
    env["BENCH_ROLE"] = role
    env.update(env_extra)
    # start_new_session so a timeout can kill the whole process group —
    # a wedged TPU plugin may fork helpers that would otherwise hold the
    # output pipes open past the child's own SIGKILL
    proc = subprocess.Popen(
        [sys.executable, os.path.abspath(__file__)],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
        cwd=os.path.dirname(os.path.abspath(__file__)),
        start_new_session=True,
    )
    try:
        out, err = proc.communicate(timeout=timeout_s)
    except subprocess.TimeoutExpired:
        log(f"{role} child timed out after {timeout_s}s")
        try:
            os.killpg(proc.pid, signal.SIGKILL)
        except (ProcessLookupError, PermissionError):
            proc.kill()
        try:
            _, err = proc.communicate(timeout=10)
        except subprocess.TimeoutExpired:
            err = ""
        return None, "timeout", (err or "")[-4000:]
    for line in err.splitlines():
        if "WARNING" not in line:
            log(f"[{role}] {line}")
    if proc.returncode != 0:
        log(f"{role} child failed (rc={proc.returncode})")
        tail = err[-4000:]
        if proc.returncode < 0:
            # signal deaths leave no traceback: record the signal so
            # classify_failure can treat crash-type signals (a miscompiled
            # kernel segfaulting) as kernel-suspect
            tail += f"\n[child terminated by signal {-proc.returncode}]"
        return None, "crash", tail
    try:
        return json.loads(out.strip().splitlines()[-1]), None, err[-4000:]
    except Exception:
        log(f"{role} child produced no JSON: {out[-300:]!r}")
        return None, "no-json", err[-4000:]


def child_boot() -> None:
    """BENCH_MODE=boot child: cold vs AOT-warm serve boot wall-clock.

    Boots the certified-inference service three times against one
    throwaway store with a tiny stub victim: (1) cold — no store, every
    serving program traces and compiles in-process; (2) build — mode
    "auto" against the empty store compiles once more and populates it;
    (3) warm — a FRESH service in mode "strict" boots purely from the
    store (any miss raises AotBootError, so a finishing warm boot is the
    proof). The printed line carries the warm service's total trace count:
    0 is the zero-trace contract the serve smoke also asserts."""
    import shutil
    import tempfile

    import jax
    import jax.numpy as jnp

    from dorpatch_tpu.aot.store import ExecutableStore
    from dorpatch_tpu.config import AotConfig, DefenseConfig, ServeConfig
    from dorpatch_tpu.serve.service import CertifiedInferenceService

    # a FRESH stub closure per service: jax.jit shares its trace cache
    # across wrappers of the same function object, so one shared apply_fn
    # would leak the cold boot's trace counts into the warm service's
    # zero-trace accounting
    def make_apply():
        def apply_fn(params, x):
            s = x.mean(axis=(1, 2, 3))
            return jax.nn.one_hot((s * 7.0).astype(jnp.int32) % 5, 5)
        return apply_fn

    # replicas pinned to 1: the boot benchmark measures one bank's
    # cold-vs-warm compile wall clock, not pool spin-up
    serve_cfg = ServeConfig(max_batch=4, bucket_sizes=(1, 4), replicas=1)
    defense_cfg = DefenseConfig(ratios=(0.06,), chunk_size=64)

    def make(aot_cfg):
        return CertifiedInferenceService(
            make_apply(), None, 5, 32, serve_cfg=serve_cfg,
            defense_cfg=defense_cfg, aot_cfg=aot_cfg)

    store_dir = tempfile.mkdtemp(prefix="bench-aot-")
    try:
        svc = make(None)
        t0 = time.perf_counter()
        svc.start()
        cold_s = time.perf_counter() - t0
        svc.stop()

        builder = make(AotConfig(cache_dir=store_dir, mode="auto"))
        builder.start()
        builder.stop()

        svcw = make(AotConfig(cache_dir=store_dir, mode="strict"))
        t0 = time.perf_counter()
        svcw.start()
        warm_s = time.perf_counter() - t0
        stats = dict(svcw._aot_stats or {})
        warm_traces = sum(svcw.trace_counts().values())
        # drive a few predictions through the warm bank and report the
        # request counts straight from the service's metric registry —
        # the same books /metrics and /stats serve
        import numpy as np

        from dorpatch_tpu.observe import labeled_values

        rng = np.random.default_rng(3)
        for image in rng.uniform(0.0, 1.0, (3, 32, 32, 3)).astype(np.float32):
            svcw.predict(image, deadline_ms=15000.0)
        served = {k: int(v) for k, v in sorted(labeled_values(
            svcw.metrics.snapshot(), "serve_requests_total",
            "status").items())}
        svcw.stop()
        print(json.dumps({
            "cold_s": round(cold_s, 3),
            "warm_s": round(warm_s, 3),
            "aot": {"hits": int(stats.get("hits", 0)),
                    "misses": int(stats.get("misses", 0)),
                    "builds": int(stats.get("builds", 0)),
                    "store_state": ExecutableStore(store_dir).state_hash()},
            "warm_trace_count": int(warm_traces),
            "warm_requests_by_status": served,
        }))
    finally:
        shutil.rmtree(store_dir, ignore_errors=True)


def child_recert() -> None:
    """BENCH_MODE=recert child: wall-clock of ONE full re-certification
    generation — grid submit, an in-process farm worker draining the real
    sweep jobs, rows harvest, baseline fold, verdict — on the tiny
    synthetic victim. Prints {"generation_s": ..., "jobs": ..., ...}."""
    import shutil
    import tempfile

    from dorpatch_tpu.farm.worker import FarmWorker
    from dorpatch_tpu.recert.scheduler import RecertScheduler

    attack = {"sampling_size": 4, "max_iterations": 4, "sweep_interval": 2,
              "switch_iteration": 2, "dropout": 1, "dropout_sizes": [0.06],
              "basic_unit": 4}
    spec = {
        "base": {"dataset": "cifar10", "base_arch": "resnet18",
                 "img_size": 32, "batch_size": 2, "synthetic_data": True,
                 "attack": attack},
        "axes": {"attack.patch_budget": [0.06, 0.12]},
        "sweep": {"densities": [0.0], "structureds": [1e-3],
                  "defense_ratio": 0.06},
        "max_attempts": 2,
    }
    workdir = tempfile.mkdtemp(prefix="bench-recert-")
    try:
        sched = RecertScheduler(
            os.path.join(workdir, "recert"),
            baseline_file=os.path.join(workdir, "robustness_baseline.json"))
        t0 = time.perf_counter()
        gen, farm_dir = sched.begin_generation(spec)
        FarmWorker(farm_dir, worker_id="bench", lease_ttl=30.0,
                   poll_interval=0.1, heartbeat_interval=0.5).run()
        verdict = sched.complete_generation(gen, farm_dir,
                                            update_baseline=True)
        gen_s = time.perf_counter() - t0
        jobs = int(sched.counts(farm_dir)["done"])
        print(json.dumps({
            "generation_s": round(gen_s, 3),
            "jobs": jobs,
            "jobs_per_hour": round(jobs * 3600.0 / gen_s, 1) if gen_s else 0.0,
            "cells": len(verdict.get("cells", {})),
            "status": verdict.get("status"),
        }))
    finally:
        shutil.rmtree(workdir, ignore_errors=True)


def no_axon_env() -> dict:
    """Env that forces plain CPU jax: axon plugin off the path, cpu platform."""
    pp = [p for p in os.environ.get("PYTHONPATH", "").split(os.pathsep)
          if p and "axon" not in p]
    return {
        "PYTHONPATH": os.pathsep.join(pp),
        "JAX_PLATFORMS": "cpu",
        "PALLAS_AXON_POOL_IPS": "",
    }


def main() -> None:
    # empty string = unset (the same convention as PALLAS_AXON_POOL_IPS)
    mode = os.environ.get("BENCH_MODE") or "attack"
    if mode not in ("attack", "certify", "boot", "recert"):
        print(json.dumps({"metric": "patch-opt images/sec", "value": 0.0,
                          "unit": "images/sec", "vs_baseline": 0.0,
                          "error": f"unknown BENCH_MODE={mode!r} (use "
                                   "'attack', 'certify', 'boot' or "
                                   "'recert')"}))
        return
    if mode == "recert":
        # One full re-certification generation end to end. One CPU child
        # (the scheduler/farm layer is host-side; CPU keeps the row
        # reproducible and independent of tunnel health), no torch baseline
        # — the row's payload is the generation wall-clock and jobs/hour.
        recert_metric = ("recert generation seconds (2-job grid, tiny "
                         "synthetic victim, in-process worker)")
        res, why, _tail = run_child(
            "recert", int(os.environ.get("BENCH_JAX_TIMEOUT", "1800")),
            no_axon_env())
        if res is None:
            print(json.dumps({"metric": recert_metric, "value": 0.0,
                              "unit": "seconds", "vs_baseline": 0.0,
                              "error": f"recert child failed ({why})"}))
            return
        out = {
            "metric": recert_metric,
            "value": res["generation_s"],
            "unit": "seconds",
            "vs_baseline": 0.0,
            "jobs": res.get("jobs"),
            "jobs_per_hour": res.get("jobs_per_hour"),
            "cells": res.get("cells"),
            "status": res.get("status"),
        }
        try:
            from dorpatch_tpu.analysis.baseline import program_set_stamp

            stamp = program_set_stamp()
            if stamp is not None:
                out["program_set"] = stamp
        except Exception:
            pass
        print(json.dumps(out))
        return
    if mode == "boot":
        # Cold vs AOT-warm serve boot on one throwaway store. One CPU child
        # (serialized executables are backend-specific; CPU keeps the row
        # reproducible and independent of tunnel health), no torch baseline
        # — vs_baseline is the cold/warm speedup of the SAME boot.
        boot_metric = "serve warm-boot seconds (AOT executable store, stub victim)"
        res, why, _tail = run_child(
            "boot", int(os.environ.get("BENCH_JAX_TIMEOUT", "1800")),
            no_axon_env())
        if res is None:
            print(json.dumps({"metric": boot_metric, "value": 0.0,
                              "unit": "seconds", "vs_baseline": 0.0,
                              "error": f"boot child failed ({why})"}))
            return
        out = {
            "metric": boot_metric,
            "value": res["warm_s"],
            "unit": "seconds",
            # >1.0 = warm boot reached serving-ready faster than cold
            "vs_baseline": (round(res["cold_s"] / res["warm_s"], 2)
                            if res.get("warm_s") else 0.0),
            "cold_s": res["cold_s"],
            "warm_s": res["warm_s"],
            # store hit/miss/build counts + content hash, next to the
            # program_set stamp below: the row names both the executables
            # it loaded and the jit programs they were compiled from
            "aot": res.get("aot"),
            "warm_trace_count": res.get("warm_trace_count"),
        }
        try:
            from dorpatch_tpu.analysis.baseline import program_set_stamp

            stamp = program_set_stamp()
            if stamp is not None:
                out["program_set"] = stamp
        except Exception:
            pass
        print(json.dumps(out))
        return
    # mode is validated: label misconfiguration rows with the right series
    err_metric = ("PatchCleanser certifications/sec" if mode == "certify"
                  else "patch-opt images/sec")
    rp = os.environ.get("BENCH_REMAT_POLICY") or "full"
    if rp not in ("full", "conv", "dots"):
        print(json.dumps({"metric": err_metric, "value": 0.0,
                          "unit": "images/sec", "vs_baseline": 0.0,
                          "error": f"unknown BENCH_REMAT_POLICY={rp!r} "
                                   "(use 'full', 'conv' or 'dots')"}))
        return
    gn = os.environ.get("BENCH_GN") or "auto"
    if gn not in ("auto", "flax", "pallas", "interpret", "jnp"):
        print(json.dumps({"metric": err_metric, "value": 0.0,
                          "unit": "images/sec", "vs_baseline": 0.0,
                          "error": f"unknown BENCH_GN={gn!r} (use 'auto', "
                                   "'flax', 'pallas', 'interpret' or 'jnp')"}))
        return
    bp = os.environ.get("BENCH_PRUNE") or "exact"
    if bp not in ("off", "exact", "consensus", "ab"):
        print(json.dumps({"metric": err_metric, "value": 0.0,
                          "unit": "images/sec", "vs_baseline": 0.0,
                          "error": f"unknown BENCH_PRUNE={bp!r} (use 'off', "
                                   "'exact', 'consensus' or 'ab')"}))
        return
    bi = os.environ.get("BENCH_INCR") or "off"
    if bi not in ("off", "on", "ab"):
        print(json.dumps({"metric": err_metric, "value": 0.0,
                          "unit": "images/sec", "vs_baseline": 0.0,
                          "error": f"unknown BENCH_INCR={bi!r} (use 'off', "
                                   "'on' or 'ab')"}))
        return
    bk = os.environ.get("BENCH_KERNEL") or "on"
    if bk not in ("off", "on", "ab"):
        print(json.dumps({"metric": err_metric, "value": 0.0,
                          "unit": "images/sec", "vs_baseline": 0.0,
                          "error": f"unknown BENCH_KERNEL={bk!r} (use "
                                   "'off', 'on' or 'ab')"}))
        return
    if bk == "ab" and (bp == "ab" or bi != "off"):
        # the kernel A/B fixes the schedule (engine-backed pruned certify)
        # and varies only the use_pallas gate; stacking a second A/B axis
        # would make the reported speedup unattributable
        print(json.dumps({"metric": err_metric, "value": 0.0,
                          "unit": "images/sec", "vs_baseline": 0.0,
                          "error": "BENCH_KERNEL=ab measures the kernel-"
                                   "tier axis alone; set BENCH_INCR=off "
                                   "and drop BENCH_PRUNE=ab"}))
        return
    bd = {"f32": "float32", "bf16": "bfloat16"}.get(
        os.environ.get("BENCH_DTYPE") or "", os.environ.get("BENCH_DTYPE"))
    if bd == "ab":
        if mode != "certify":
            print(json.dumps({"metric": err_metric, "value": 0.0,
                              "unit": "images/sec", "vs_baseline": 0.0,
                              "error": "BENCH_DTYPE=ab only applies to "
                                       "BENCH_MODE=certify"}))
            return
        if bp == "ab" or bi == "ab" or bk == "ab":
            # one A/B axis per row: the precision A/B fixes schedule,
            # engine and kernel gate, varying only compute_dtype
            print(json.dumps({"metric": err_metric, "value": 0.0,
                              "unit": "images/sec", "vs_baseline": 0.0,
                              "error": "BENCH_DTYPE=ab measures the "
                                       "precision axis alone; drop the "
                                       "other =ab knobs"}))
            return
    bm = os.environ.get("BENCH_MESH") or ""
    if bm:
        parts = bm.split("x")
        if (os.environ.get("BENCH_MODE") or "attack") != "certify":
            print(json.dumps({"metric": err_metric, "value": 0.0,
                              "unit": "images/sec", "vs_baseline": 0.0,
                              "error": "BENCH_MESH only applies to "
                                       "BENCH_MODE=certify"}))
            return
        if len(parts) != 2 or not all(p.isdigit() and int(p) > 0
                                      for p in parts):
            print(json.dumps({"metric": err_metric, "value": 0.0,
                              "unit": "images/sec", "vs_baseline": 0.0,
                              "error": f"malformed BENCH_MESH={bm!r} (use "
                                       "'DATAxMASK', e.g. '4x2')"}))
            return
    if bp == "ab" and bi != "off":
        # the prune A/B branch runs both sides with the incremental engine
        # off; accepting BENCH_INCR=on|ab here would silently report
        # pruned-only numbers as if the engine were active
        print(json.dumps({"metric": err_metric, "value": 0.0,
                          "unit": "images/sec", "vs_baseline": 0.0,
                          "error": "BENCH_PRUNE=ab measures the pruned-vs-"
                                   "exhaustive axis with the incremental "
                                   "engine off; set BENCH_INCR=off (or "
                                   "drop BENCH_PRUNE=ab to measure "
                                   "BENCH_INCR)"}))
        return
    eot = int(os.environ.get("BENCH_EOT", "128"))
    jax_timeout = int(os.environ.get("BENCH_JAX_TIMEOUT", "1800"))
    torch_timeout = int(os.environ.get("BENCH_TORCH_TIMEOUT", "600"))
    arch = os.environ.get("BENCH_ARCH", "resnetv2")
    img = int(os.environ.get("BENCH_IMG", "224"))

    # One shared wall budget across every child (see _Deadline). Reserves
    # guarantee the later, cheaper children still get a slot even when an
    # earlier child eats its whole slice: the CPU fallback needs compile +
    # a few steps of the small victim (~2-3 min observed), the torch
    # baseline a model build + 3 steps.
    budget = _Deadline(float(os.environ.get("BENCH_TOTAL_BUDGET", "3000")))
    cpu_reserve, torch_reserve = 480, 180
    floor = 20  # below this a child can't even finish imports: don't spawn

    def spawn(role, want_s, reserve_s, env_extra):
        t = budget.slice(want_s, reserve_s)
        if t < floor:
            log(f"skipping {role} child: {t}s left of BENCH_TOTAL_BUDGET "
                f"after {reserve_s}s reserve")
            return None, "budget", ""
        return run_child(role, t, env_extra)

    fallback = None
    gn_fallback = None
    res, why, tail = spawn("jax", jax_timeout,
                           cpu_reserve + torch_reserve, {})
    failure = None if res is not None else classify_failure(why, tail)
    if (res is None and gn == "auto" and arch == "resnetv2"
            and failure == "kernel"):
        # The auto path selects the fused Pallas GN kernel on single-chip
        # TPU backends; a crash with a Mosaic/Pallas/VMEM signature in the
        # child tail means a kernel regression on this chip generation —
        # fall back to the always-partitionable flax GN before abandoning
        # the accelerator. Any other failure skips this retry: a timeout
        # means the accelerator is wedged, a backend-init failure (dead
        # tunnel, the r03 outage) means no accelerator child can succeed,
        # and an unrelated crash would meet the same fate again.
        log("jax child hit a kernel-signature crash with BENCH_GN=auto; "
            "retrying with flax GN")
        res, why, tail = spawn("jax", jax_timeout,
                               cpu_reserve + torch_reserve,
                               {"BENCH_GN": "flax"})
        if res is not None:
            gn_fallback = "flax"
        else:
            # the retry can fail for a DIFFERENT reason (e.g. the
            # accelerator wedged between children): fallback_cause must
            # name what actually killed the last attempt, not the first
            failure = classify_failure(why, tail)
    if res is None:
        # Accelerator unreachable/wedged: CPU + small victim, so the driver
        # still gets a self-consistent (same-model) ratio row.
        log(f"accelerator path abandoned ({failure}); CPU fallback")
        fallback = {"BENCH_DATASET": "cifar10", "BENCH_ARCH": "resnet18",
                    "BENCH_IMG": "32", "BENCH_BATCH": "2", "BENCH_EOT": "8",
                    # XLA-CPU emulates bf16 (slower than f32): keep the
                    # fallback row honest
                    "BENCH_DTYPE": "float32", **no_axon_env()}
        # relabel EVERY config knob the fallback rewrote, not just arch/img:
        # the r04 row was labeled EOT=128 while the child ran EOT=8
        arch, img, eot = "resnet18", 32, 8
        # the fallback child's cost (compile + a few small-victim steps) is
        # independent of BENCH_JAX_TIMEOUT: an operator lowering that to
        # fail fast on a dead tunnel must not starve the fallback
        res, _, _ = spawn("jax", max(jax_timeout, cpu_reserve),
                          torch_reserve, fallback)
    if res is None:
        print(json.dumps({"metric": err_metric, "value": 0.0,
                          "unit": "images/sec", "vs_baseline": 0.0,
                          "error": "benchmark could not run"}))
        return

    tres, _, _ = spawn("torch", torch_timeout, 0, fallback or {})
    torch_ips = tres["ips"] if tres else None
    log(f"jax: {res['ips']:.3f} images/sec; torch baseline: {torch_ips}")

    model_tag = "RN50-BiT@224" if (arch, img) == ("resnetv2", 224) else f"{arch}@{img}"
    if mode == "certify":
        metric = (f"PatchCleanser certifications/sec "
                  f"({model_tag}, 666-mask radius 0.06, jit)")
    else:
        metric = f"patch-opt images/sec (EOT={eot}, {model_tag}, jit stage-1 step)"
    out = {
        "metric": metric,
        "value": round(res["ips"], 3),
        "unit": "images/sec",
        "vs_baseline": round(res["ips"] / torch_ips, 2) if torch_ips else 0.0,
    }
    if gn_fallback:
        # make a benchmarked kernel regression visible in the recorded row
        # (same convention as the CPU fallback's "fallback" field)
        out["gn_fallback"] = gn_fallback
    if res.get("mfu") is not None:
        out["mfu"] = res["mfu"]
    for k in ("remat", "step_seconds", "fwd_gflops_per_image", "batch",
              "masked_images_per_sec", "masks_per_image", "masked_fwd_per_sec",
              "seconds_per_batch", "backend", "compute_dtype", "prune",
              "forwards_per_image",
              "prune_rate", "ips_exhaustive", "prune_speedup", "parity",
              "parity_mismatches", "incr", "incr_speedup", "ips_pruned_only",
              "ips_f32", "dtype_speedup", "dtype_bytes_ratio",
              "forward_equivalents_per_image",
              "forward_equivalents_total_per_image", "mesh",
              "kernel", "kernel_speedup", "kernel_roofline",
              "comm_bytes", "comm_by_collective"):
        if res.get(k) is not None:
            out[k] = res[k]
    # every BENCH row names its compute precision next to program_set;
    # rows from children predating the stamp ran f32
    out.setdefault("compute_dtype", "f32")
    if fallback is not None:
        # A fallback row is a liveness proof, not a framework measurement:
        # jax-CPU f32 on the small victim vs torch-CPU on the same config.
        # Mark it non-comparable so a bench-history reader can't mistake
        # "0.08x baseline" for a TPU regression (r04 lesson).
        out["fallback"] = "cpu"
        out["comparable"] = False
        out["fallback_cause"] = failure
        out["note"] = ("cpu-fallback: accelerator unavailable; value is "
                       "jax-CPU float32, not a TPU measurement")
    # record what the denominator actually ran, so vs_baseline is
    # self-describing even when the row is read in isolation
    if torch_ips:
        out["baseline"] = {"impl": "torch-cpu-fp32", "arch": arch,
                           "img": img, "mode": mode}
    # stamp the checked-in program-set identity (analysis/baselines.json
    # fingerprints) so a bench row is attributable to the exact jit
    # programs it measured; absent on pre-baseline checkouts
    try:
        from dorpatch_tpu.analysis.baseline import program_set_stamp

        stamp = program_set_stamp()
        if stamp is not None:
            out["program_set"] = stamp
    except Exception:
        pass
    print(json.dumps(out))


if __name__ == "__main__":
    role = os.environ.get("BENCH_ROLE")
    if role == "jax":
        child_jax()
    elif role == "torch":
        child_torch()
    elif role == "boot":
        child_boot()
    elif role == "recert":
        child_recert()
    else:
        main()
