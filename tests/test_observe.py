"""Observability subsystem: metrics JSONL sink, step timer, trace no-op."""

import json

import numpy as np

from dorpatch_tpu import observe


def _info(vals, stopped=False):
    return {"metrics": np.asarray(vals, np.float32), "stopped": stopped}


def test_metrics_logger_writes_jsonl(tmp_path):
    path = tmp_path / "m" / "metrics.jsonl"
    clock = iter([100.0, 101.0]).__next__
    with observe.AttackMetricsLogger(str(path), clock=clock) as logger:
        logger.set_batch(3)
        logger.on_block_end(0, 20, _info(range(8)))
        logger.on_block_end(1, 40, _info(range(8, 16), stopped=True))
    lines = [json.loads(l) for l in path.read_text().splitlines()]
    assert len(lines) == 2
    assert lines[0]["batch"] == 3 and lines[0]["stage"] == 0
    assert lines[0]["loss"] == 0.0 and lines[0]["n_failed"] == 7.0
    assert lines[1]["stage"] == 1 and lines[1]["stopped"] is True
    assert lines[1]["masked_acc"] == 13.0
    assert lines[0]["ts"] == 100.0


def test_metrics_logger_echo_cadence(capsys):
    logger = observe.AttackMetricsLogger(path=None, echo_every=40)
    logger.on_block_end(0, 20, _info(range(8)))       # not on cadence
    logger.on_block_end(0, 40, _info(range(8)))       # on cadence
    logger.on_block_end(0, 50, _info(range(8), True)) # stopped -> always
    out = capsys.readouterr().out
    assert out.count("iter") == 2
    assert len(logger.history) == 3


def test_metrics_logger_appends_across_instances(tmp_path):
    path = str(tmp_path / "metrics.jsonl")
    with observe.AttackMetricsLogger(path) as a:
        a.on_block_end(0, 1, _info(range(8)))
    with observe.AttackMetricsLogger(path) as b:
        b.on_block_end(0, 2, _info(range(8)))
    assert len(open(path).read().splitlines()) == 2


def test_step_timer_summary():
    times = iter([0.0, 2.0, 2.0, 4.0]).__next__
    t = observe.StepTimer(clock=times)
    t.start(); t.stop()
    t.start(); t.stop()
    s = t.summary(steps_per_block=10, batch=4)
    assert s == {"blocks": 2, "total_seconds": 4.0,
                 "steps_per_sec": 5.0, "images_per_sec": 20.0}


def test_trace_noop_without_dir():
    with observe.trace(""):
        pass
    with observe.trace(None):
        pass


def test_metric_names_match_attack_vector_width():
    from dorpatch_tpu.attack import DorPatch  # noqa: F401 (import side-check)

    assert len(observe.METRIC_NAMES) == 8


def test_step_timer_summary_reports_mfu():
    """SURVEY §6 / VERDICT r2 ask #2: summary carries a defensible MFU row
    when given useful FLOPs per step and the chip peak."""
    times = iter([0.0, 2.0, 2.0, 4.0]).__next__
    t = observe.StepTimer(clock=times)
    t.start(); t.stop()
    t.start(); t.stop()
    # 2 blocks x 5 steps, 1e12 useful FLOPs/step, 4s total -> 2.5 TFLOP/s
    s = t.summary(steps_per_block=5, batch=2, flops_per_step=1e12,
                  peak_flops=10e12)
    assert s["achieved_tflops"] == 2.5
    assert s["mfu"] == 0.25
    assert s["images_per_sec"] == 5.0
    # without flops/peak the mfu keys are absent (no bogus utilization rows)
    s2 = t.summary(steps_per_block=5, batch=2)
    assert "mfu" not in s2 and "achieved_tflops" not in s2
