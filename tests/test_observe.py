"""Observability subsystem: metrics JSONL sink, step timer, trace no-op,
span/event log, heartbeats + watchdog, run manifest."""

import json

import numpy as np

from dorpatch_tpu import observe


def _info(vals, stopped=False):
    return {"metrics": np.asarray(vals, np.float32), "stopped": stopped}


def test_metrics_logger_writes_jsonl(tmp_path):
    path = tmp_path / "m" / "metrics.jsonl"
    clock = iter([100.0, 101.0]).__next__
    with observe.AttackMetricsLogger(str(path), clock=clock) as logger:
        logger.set_batch(3)
        logger.on_block_end(0, 20, _info(range(8)))
        logger.on_block_end(1, 40, _info(range(8, 16), stopped=True))
    lines = [json.loads(l) for l in path.read_text().splitlines()]
    assert len(lines) == 2
    assert lines[0]["batch"] == 3 and lines[0]["stage"] == 0
    assert lines[0]["loss"] == 0.0 and lines[0]["n_failed"] == 7.0
    assert lines[1]["stage"] == 1 and lines[1]["stopped"] is True
    assert lines[1]["masked_acc"] == 13.0
    assert lines[0]["ts"] == 100.0


def test_metrics_logger_echo_cadence(capsys):
    logger = observe.AttackMetricsLogger(path=None, echo_every=40)
    logger.on_block_end(0, 20, _info(range(8)))       # not on cadence
    logger.on_block_end(0, 40, _info(range(8)))       # on cadence
    logger.on_block_end(0, 50, _info(range(8), True)) # stopped -> always
    out = capsys.readouterr().out
    assert out.count("iter") == 2
    assert len(logger.history) == 3


def test_metrics_logger_appends_across_instances(tmp_path):
    path = str(tmp_path / "metrics.jsonl")
    with observe.AttackMetricsLogger(path) as a:
        a.on_block_end(0, 1, _info(range(8)))
    with observe.AttackMetricsLogger(path) as b:
        b.on_block_end(0, 2, _info(range(8)))
    assert len(open(path).read().splitlines()) == 2


def test_step_timer_summary():
    times = iter([0.0, 2.0, 2.0, 4.0]).__next__
    t = observe.StepTimer(clock=times)
    t.start(); t.stop()
    t.start(); t.stop()
    s = t.summary(steps_per_block=10, batch=4)
    assert s == {"blocks": 2, "total_seconds": 4.0,
                 "steps_per_sec": 5.0, "images_per_sec": 20.0}


def test_trace_noop_without_dir():
    with observe.trace(""):
        pass
    with observe.trace(None):
        pass


def test_metric_names_match_attack_vector_width():
    from dorpatch_tpu.attack import DorPatch  # noqa: F401 (import side-check)

    assert len(observe.METRIC_NAMES) == 8


def test_step_timer_summary_reports_mfu():
    """SURVEY §6 / VERDICT r2 ask #2: summary carries a defensible MFU row
    when given useful FLOPs per step and the chip peak."""
    times = iter([0.0, 2.0, 2.0, 4.0]).__next__
    t = observe.StepTimer(clock=times)
    t.start(); t.stop()
    t.start(); t.stop()
    # 2 blocks x 5 steps, 1e12 useful FLOPs/step, 4s total -> 2.5 TFLOP/s
    s = t.summary(steps_per_block=5, batch=2, flops_per_step=1e12,
                  peak_flops=10e12)
    assert s["achieved_tflops"] == 2.5
    assert s["mfu"] == 0.25
    assert s["images_per_sec"] == 5.0
    # without flops/peak the mfu keys are absent (no bogus utilization rows)
    s2 = t.summary(steps_per_block=5, batch=2)
    assert "mfu" not in s2 and "achieved_tflops" not in s2


# ---------------- run_id stamping (resume disambiguation) ----------------


def test_metrics_logger_stamps_run_id(tmp_path):
    """Append-mode resume fix: every record carries the attempt's run_id so
    interleaved duplicate (batch, stage, step) records are groupable."""
    path = str(tmp_path / "metrics.jsonl")
    with observe.AttackMetricsLogger(path, run_id="attempt1") as a:
        a.on_block_end(0, 5, _info(range(8)))
    with observe.AttackMetricsLogger(path, run_id="attempt2") as b:
        b.on_block_end(0, 5, _info(range(8)))
    lines = [json.loads(l) for l in open(path)]
    assert [l["run_id"] for l in lines] == ["attempt1", "attempt2"]
    assert lines[0]["step"] == lines[1]["step"] == 5  # same key, two attempts
    # no run_id given -> no key (unstamped legacy shape preserved)
    with observe.AttackMetricsLogger(path) as c:
        c.on_block_end(0, 5, _info(range(8)))
    assert "run_id" not in json.loads(open(path).read().splitlines()[-1])


# ---------------- span/event log ----------------


def test_event_log_span_nesting_and_ordering(tmp_path):
    """Spans nest (path/depth), seq strictly increases, durations come from
    the injected perf clock, and late-added attrs land on the close record."""
    path = str(tmp_path / "events.jsonl")
    perf = iter([float(i) for i in range(100)]).__next__
    clock = iter([1000.0 + i for i in range(100)]).__next__
    elog = observe.EventLog(path, run_id="r1", process_index=3,
                            clock=clock, perf=perf)
    with elog:
        with elog.span("run"):
            with elog.span("batch", batch=0) as sp:
                sp["images"] = 2
            elog.event("note", detail="x")
    recs = [json.loads(l) for l in open(path)]
    assert [r["seq"] for r in recs] == list(range(len(recs)))
    assert all(r["proc"] == 3 and r["run_id"] == "r1" for r in recs)
    kinds = [(r["kind"], r.get("name")) for r in recs]
    assert kinds == [("begin", "run"), ("begin", "batch"),
                     ("span", "batch"), ("event", "note"), ("span", "run")]
    batch = recs[2]
    assert batch["path"] == "run/batch" and batch["depth"] == 1
    assert batch["images"] == 2 and batch["batch"] == 0
    assert batch["dur_s"] > 0
    run = recs[4]
    assert run["path"] == "run" and run["depth"] == 0
    assert run["dur_s"] > batch["dur_s"]  # outer span encloses inner


def test_event_log_current_path_and_activity(tmp_path):
    ticks = iter([float(i) for i in range(100)]).__next__
    elog = observe.EventLog(str(tmp_path / "e.jsonl"), perf=ticks)
    assert elog.current_path() == "idle"
    with elog.span("run"):
        with elog.span("certify"):
            assert elog.current_path() == "run/certify"
        assert elog.current_path() == "run"
    assert elog.current_path() == "idle"
    assert elog.seconds_since_activity() >= 0.0


def test_module_span_noops_without_active_log(tmp_path):
    """The attack/defense layers call observe.span unconditionally; with no
    active EventLog it must be a free no-op that still yields an attrs dict."""
    with observe.span("anything", x=1) as sp:
        sp["y"] = 2  # must not raise
    assert observe.active_event_log() is None
    elog = observe.EventLog(str(tmp_path / "e.jsonl"), run_id="r")
    with elog, observe.active(elog):
        assert observe.active_event_log() is elog
        with observe.span("inner"):
            pass
        observe.record_event("evt", k=1)
        observe.record_compile("prog", 1.5)
    assert observe.active_event_log() is None
    kinds = [json.loads(l)["kind"] for l in open(str(tmp_path / "e.jsonl"))]
    assert kinds == ["begin", "span", "event", "compile"]


def test_event_log_enter_installs_and_restores_active(tmp_path):
    """`with EventLog(...) as el:` alone must wire up the module-level
    helpers — the historical footgun was an __enter__ that only returned
    self, so telemetry emitted through observe.span/record_event silently
    went nowhere unless the caller also remembered observe.active(el)."""
    assert observe.active_event_log() is None
    elog = observe.EventLog(str(tmp_path / "e.jsonl"), run_id="r")
    with elog:
        assert observe.active_event_log() is elog
        observe.record_event("solo", k=1)
        # the old belt-and-braces pattern stays legal (and redundant)
        with observe.active(elog):
            assert observe.active_event_log() is elog
        assert observe.active_event_log() is elog
    assert observe.active_event_log() is None
    kinds = [json.loads(l)["kind"] for l in open(str(tmp_path / "e.jsonl"))]
    assert kinds == ["event"]

    # nesting two logs restores the OUTER one on inner exit
    outer = observe.EventLog(None)
    inner = observe.EventLog(None)
    with outer:
        with inner:
            assert observe.active_event_log() is inner
        assert observe.active_event_log() is outer
    assert observe.active_event_log() is None


def test_timed_first_call_records_compile_once(tmp_path):
    calls = []
    clock = iter([10.0, 12.5, 20.0, 20.1]).__next__
    fn = observe.timed_first_call(lambda v: calls.append(v) or v,
                                  "prog.x", clock=clock)
    elog = observe.EventLog(str(tmp_path / "e.jsonl"))
    with elog, observe.active(elog):
        assert fn(1) == 1 and fn(2) == 2
    recs = [json.loads(l) for l in open(str(tmp_path / "e.jsonl"))]
    compiles = [r for r in recs if r["kind"] == "compile"]
    assert len(compiles) == 1  # only the first call is timed
    assert compiles[0]["name"] == "prog.x"
    assert compiles[0]["dur_s"] == 2.5
    assert calls == [1, 2]  # pass-through untouched


def test_event_log_block_boundary_and_unwritable_dir(tmp_path):
    elog = observe.EventLog(str(tmp_path / "e.jsonl"))
    elog.block_boundary(1, 100, {"stopped": True})
    rec = json.loads(open(str(tmp_path / "e.jsonl")).read())
    assert rec["kind"] == "block" and rec["stage"] == 1 and rec["step"] == 100
    assert rec["stopped"] is True and rec["dur_s"] >= 0
    # memory sampling never raises on hosts without allocator stats
    assert observe.device_memory_stats() is None or isinstance(
        observe.device_memory_stats(), list)
    # unwritable path degrades to a tracking-only sink, not an exception
    ro = tmp_path / "ro"
    ro.mkdir()
    ro.chmod(0o500)
    try:
        silent = observe.EventLog(str(ro / "sub" / "e.jsonl"))
        with silent.span("run"):
            assert silent.current_path() == "run"
    finally:
        ro.chmod(0o700)


# ---------------- heartbeats + watchdog ----------------


def test_heartbeat_writes_beats_and_read_back(tmp_path):
    import time as _time

    path = str(tmp_path / "heartbeat_0.jsonl")
    phases = iter(["run/setup"] + ["run/batch"] * 50).__next__
    with observe.Heartbeat(path, get_phase=phases, interval=0.01,
                           process_index=0, run_id="r1"):
        _time.sleep(0.08)
    beats = observe.read_heartbeats(str(tmp_path))["heartbeat_0.jsonl"]
    assert len(beats) >= 3  # immediate first beat + periodic + exit beat
    assert [b["seq"] for b in beats] == list(range(len(beats)))
    assert beats[0]["phase"] == "run/setup"
    assert beats[-1]["phase"] == "exit"  # clean shutdown marker
    assert all(b["run_id"] == "r1" and b["proc"] == 0 for b in beats)


def test_heartbeat_gap_stall_detection(tmp_path):
    """The report's stall rule: a gap far beyond the median beat interval
    within ONE attempt flags a stall; resume boundaries don't."""
    beats = ([{"ts": 10.0 + i, "seq": i, "phase": "a", "run_id": "r1"}
              for i in range(5)]
             + [{"ts": 25.0, "seq": 5, "phase": "b", "run_id": "r1"}]  # 11s gap
             + [{"ts": 100.0, "seq": 0, "phase": "c", "run_id": "r2"}])
    with open(tmp_path / "heartbeat_0.jsonl", "w") as fh:
        for b in beats:
            fh.write(json.dumps(b) + "\n")
    gaps = observe.heartbeat_gaps(beats)
    assert max(gaps) == 11.0
    assert len(gaps) == 5  # the r1->r2 resume boundary is not a gap
    rows = observe.summarize_heartbeats(str(tmp_path))
    assert rows[0]["stalled"] is True and rows[0]["max_gap_s"] == 11.0
    # steady beats are not stalled
    with open(tmp_path / "heartbeat_1.jsonl", "w") as fh:
        for i in range(6):
            fh.write(json.dumps({"ts": 10.0 + i, "seq": i, "phase": "a",
                                 "run_id": "r1"}) + "\n")
    rows = observe.summarize_heartbeats(str(tmp_path))
    assert [r["stalled"] for r in rows] == [True, False]


def test_watchdog_fires_on_stalled_event_log(tmp_path):
    """--hang-timeout semantics: no EventLog progress past the timeout ->
    print every process's last-known phase and abort."""
    perf_now = [0.0]
    elog = observe.EventLog(str(tmp_path / "events.jsonl"),
                            perf=lambda: perf_now[0])
    with open(tmp_path / "heartbeat_1.jsonl", "w") as fh:
        fh.write(json.dumps({"ts": 5.0, "seq": 7, "proc": 1,
                             "phase": "run/batch/artifact_io/bcast"}) + "\n")
    aborted = []
    echoed = []
    dog = observe.Watchdog(
        str(tmp_path), elog, timeout_s=10.0,
        on_abort=lambda: aborted.append(True),
        echo=lambda msg, **kw: echoed.append(msg), clock=lambda: 30.0)
    elog.event("progress")          # activity at perf 0
    perf_now[0] = 5.0
    assert dog.check() is False     # under the timeout: no fire
    perf_now[0] = 11.0
    assert dog.check() is True      # 11s idle > 10s timeout
    assert aborted == [True]
    joined = "\n".join(echoed)
    assert "WATCHDOG" in joined
    assert "run/batch/artifact_io/bcast" in joined  # last-known phase shown


# ---------------- run manifest ----------------


def test_run_manifest_contents_and_attempt_chain(tmp_path):
    cfg = __import__("dorpatch_tpu.config", fromlist=["x"]).ExperimentConfig()
    p = observe.write_run_manifest(str(tmp_path), cfg, run_id="aaa")
    m = json.load(open(p))
    assert m["run_id"] == "aaa"
    assert m["config"]["dataset"] == "imagenet"
    assert m["hostname"] and m["python"] and m["pid"]
    assert "previous_run_ids" not in m
    # a resumed attempt chains the prior id
    observe.write_run_manifest(str(tmp_path), cfg, run_id="bbb",
                               extra={"backend": "cpu"})
    m2 = json.load(open(str(tmp_path / "run.json")))
    assert m2["run_id"] == "bbb" and m2["previous_run_ids"] == ["aaa"]
    assert m2["backend"] == "cpu"
    assert observe.new_run_id() != observe.new_run_id()


def test_shared_run_id_single_process():
    """Multi-process runs adopt process 0's attempt id (broadcast); in a
    single-process world the broadcast is the identity."""
    from dorpatch_tpu.parallel import multiproc

    assert multiproc.shared_run_id("abc123def456") == "abc123def456"


def test_last_beat_ts_tolerates_truncated_final_line(tmp_path):
    """`last_beat_ts` is the farm's lease-liveness primitive: it must return
    the newest *parseable* beat even when the final line was torn by the
    very crash it exists to detect."""
    path = tmp_path / "heartbeat_0.jsonl"
    assert observe.last_beat_ts(str(path)) is None  # missing file
    path.write_text("")
    assert observe.last_beat_ts(str(path)) is None  # empty file
    beats = [{"ts": 100.0, "seq": 0, "phase": "a"},
             {"ts": 101.5, "seq": 1, "phase": "b"}]
    path.write_text("".join(json.dumps(b) + "\n" for b in beats))
    assert observe.last_beat_ts(str(path)) == 101.5
    with open(path, "a") as fh:
        fh.write('{"ts": 999.0, "se')  # SIGKILL mid-write
    assert observe.last_beat_ts(str(path)) == 101.5
    with open(path, "ab") as fh:  # torn mid-multibyte-char: must not raise
        fh.write("\n".encode() + "é".encode()[:1])
    assert observe.last_beat_ts(str(path)) == 101.5


def test_read_heartbeats_skips_partial_lines(tmp_path):
    beats = [{"ts": 1.0, "seq": 0, "phase": "x", "proc": 0}]
    path = tmp_path / "heartbeat_0.jsonl"
    with open(path, "wb") as fh:
        for b in beats:
            fh.write((json.dumps(b) + "\n").encode())
        fh.write(b'{"ts": 2.0, "seq": 1, "ph')
        fh.write("é".encode()[:1])  # truncated multibyte tail
    got = observe.read_heartbeats(str(tmp_path))
    assert [b["seq"] for b in got["heartbeat_0.jsonl"]] == [0]


def test_heartbeat_wedge_freezes_file_without_exit_beat(tmp_path):
    """After `wedge()` the file must look exactly like a process stuck in a
    device call: no further beats, and no clean `exit` beat on close."""
    path = str(tmp_path / "heartbeat_0.jsonl")
    with observe.Heartbeat(path, get_phase=lambda: "busy",
                           interval=0.01) as hb:
        hb.beat()
        hb.wedge()
        frozen = observe.last_beat_ts(path)
    beats = observe.read_heartbeats(str(tmp_path))["heartbeat_0.jsonl"]
    assert observe.last_beat_ts(path) == frozen
    assert all(b["phase"] != "exit" for b in beats)
