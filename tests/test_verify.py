"""Checkpoint-conversion verifier (`python -m dorpatch_tpu.models.verify`).

The reference's parity contract is "timm model + PatchCleanser checkpoint"
(`/root/reference/utils.py:47-63`); the verifier takes a real `.pth` file
through `models/convert.py` and gates flax-vs-torch logit parity. Tested
against a synthetically saved full-size RN50 checkpoint file (the reference
checkpoints themselves are not on disk in this environment).
"""

import numpy as np
import pytest
import torch

from dorpatch_tpu.models import verify


@pytest.fixture(scope="module")
def rn50_ckpt(tmp_path_factory):
    from dorpatch_tpu.backends.torch_models import create_torch_model

    torch.manual_seed(7)
    model = create_torch_model("resnetv2", 1000)
    path = tmp_path_factory.mktemp("ckpt") / (
        "resnetv2_50x1_bit_distilled_cutout2_128_imagenet.pth")
    # the reference checkpoints wrap the weights (`utils.py:59-62`)
    torch.save({"state_dict": model.state_dict()}, path)
    return str(path)


@pytest.mark.slow
def test_verify_full_rn50_checkpoint(rn50_ckpt):
    report = verify.verify_checkpoint(
        rn50_ckpt, "resnetv2", "imagenet", batch=2, img_size=224)
    assert report["arch"] == "resnetv2_50x1_bit_distilled"
    assert report["max_abs_delta"] <= 1e-3
    assert report["argmax_agree"]
    assert report["n_params"] > 100  # full RN50, not a stub


@pytest.mark.slow
def test_verify_cli_infers_arch_and_passes(rn50_ckpt, capsys):
    rc = verify.main([rn50_ckpt, "--batch", "1"])
    out = capsys.readouterr().out
    assert rc == 0
    assert "[OK]" in out and "resnetv2_50x1_bit_distilled" in out
    assert "imagenet" in out  # dataset inferred from the filename


def test_verify_cli_missing_file():
    assert verify.main(["/nonexistent/ckpt.pth"]) == 2


def test_verify_rejects_wrong_arch_keys(tmp_path):
    """A checkpoint whose keys don't match the twin must fail loudly, not
    silently verify a partial load."""
    path = tmp_path / "resnetv2_50x1_bit_distilled_cutout2_128_imagenet.pth"
    torch.save({"state_dict": {"bogus.weight": torch.zeros(3)}}, path)
    with pytest.raises(KeyError):
        verify.verify_checkpoint(str(path), "resnetv2", "imagenet", batch=1,
                                 img_size=32)


def test_infer_helpers():
    assert verify._infer_arch("x/vit_base_patch16_224_cutout2_128_cifar10.pth") == "vit"
    # cifar_vit must win over the "vit" substring
    assert verify._infer_arch("x/cifar_vit_cutout2_128_cifar10.pth") == "cifar_vit"
    assert verify._infer_dataset("vit_base_patch16_224_cutout2_128_cifar100.pth") == "cifar100"
    assert verify._infer_dataset("resmlp_24_distilled_224_imagenet.pth") == "imagenet"
    assert np.isfinite(1.0)  # keep numpy import honest
