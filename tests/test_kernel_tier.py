"""CPU-interpreter parity for the Pallas kernel tier and its gate.

Runs the kernels in interpreter mode (tests force the CPU platform, see
conftest.py); the lowered TPU path shares the same kernel bodies. The two
kernels carry different exactness contracts, asserted here at their full
strength: the stem delta-conv kernel shares `_delta_conv` (one composition,
one summation order) with the XLA fold, so its output is BIT-exact at f32;
the masked-KV attention kernel re-derives the softmax as a two-group
running max, so its contract is allclose at f32 ULP scale — the engine
layers margin gating on top (tests/test_defense.py). The mixer engine has
no kernel; its frozen-clean-rows exactness is asserted against a dense
reference here, verdict-level contracts in tests/test_defense.py.
"""

import types

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dorpatch_tpu.ops import _backend
from dorpatch_tpu.ops.masked_kv_attn import (masked_kv_attention,
                                             masked_kv_attention_reference)
from dorpatch_tpu.ops.stem_fold import (fold_masked_stem,
                                        fold_masked_stem_kernel,
                                        plan_windows, same_pads)

IMG = 16


def _rect_table():
    # corner / interior / far-corner / edge boxes: distinct window shapes,
    # so the uniform-plan enlargement + start clamping all get exercised
    return np.array([[[0, 5, 0, 5]], [[3, 9, 2, 8]],
                     [[10, 16, 11, 16]], [[0, 4, 12, 16]]], np.int64)


@pytest.mark.parametrize("k,s,pad", [(3, 1, ((1, 1), (1, 1))),
                                     (3, 2, "same"), (5, 2, "same")])
def test_stem_kernel_bit_exact(k, s, pad):
    """The CifarResNet18 (3x3/s1) and BiT-stem-like (strided SAME)
    geometries: kernel output must equal the fold EXACTLY, not allclose."""
    if pad == "same":
        pad = (same_pads(IMG, k, s), same_pads(IMG, k, s))
    plan = plan_windows(_rect_table(), IMG, k, s, pad)
    (pr0, pr1), _ = pad
    h = (IMG + pr0 + pr1 - k) // s + 1
    kern = jax.random.normal(jax.random.PRNGKey(0), (k, k, 3, 8))
    clean = jax.random.normal(jax.random.PRNGKey(1), (2, h, h, 8))
    u = jax.random.normal(jax.random.PRNGKey(2), (2, IMG, IMG, 3))
    ref = fold_masked_stem(kern, clean, u, plan, (s, s), pad)
    got = fold_masked_stem_kernel(kern, clean, u, plan, (s, s), pad,
                                  interpret=True)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(ref))


def test_attention_kernel_matches_reference():
    """Two-group softmax vs the einsum/concat composition, with real
    −1e9 bias patterns (clean columns masked where dirty, dup dirty
    slots masked) — f32 ULP scale, far inside the engine's margin gate."""
    b, c, s, h, f, t = 2, 3, 4, 2, 8, 9
    ks = jax.random.split(jax.random.PRNGKey(3), 7)
    q = jax.random.normal(ks[0], (b, c, s, h, f))
    kd = jax.random.normal(ks[1], (b, c, s, h, f))
    vd = jax.random.normal(ks[2], (b, c, s, h, f))
    kc = jax.random.normal(ks[3], (b, t, h, f))
    vc = jax.random.normal(ks[4], (b, t, h, f))
    clean_bias = jnp.where(jax.random.bernoulli(ks[5], 0.2, (b, c, t)),
                           -1e9, 0.0)
    dirty_bias = jnp.where(jax.random.bernoulli(ks[6], 0.25, (b, c, s)),
                           -1e9, 0.0)
    # the engine never masks every dirty slot of an entry; keep slot 0 live
    dirty_bias = dirty_bias.at[:, :, 0].set(0.0)
    ref = masked_kv_attention_reference(q, kd, vd, kc, vc,
                                        clean_bias, dirty_bias)
    got = masked_kv_attention(q, kd, vd, kc, vc, clean_bias, dirty_bias,
                              interpret=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               atol=2e-6, rtol=2e-6)


@pytest.mark.parametrize("k,s,pad", [(3, 1, ((1, 1), (1, 1))),
                                     (5, 2, "same")])
def test_stem_kernel_bf16(k, s, pad):
    """The bf16 certify bank runs the stem kernel on bf16 operands. Two
    contracts: kernel-vs-fold stays BIT-exact at bf16 (they share
    `_delta_conv`, one composition, one summation order — the dtype does
    not change that), and the bf16 result tracks the f32 fold at bf16
    resolution (the escalation margin the engine layers on top)."""
    if pad == "same":
        pad = (same_pads(IMG, k, s), same_pads(IMG, k, s))
    plan = plan_windows(_rect_table(), IMG, k, s, pad)
    (pr0, pr1), _ = pad
    h = (IMG + pr0 + pr1 - k) // s + 1
    kern = jax.random.normal(jax.random.PRNGKey(0), (k, k, 3, 8))
    clean = jax.random.normal(jax.random.PRNGKey(1), (2, h, h, 8))
    u = jax.random.normal(jax.random.PRNGKey(2), (2, IMG, IMG, 3))
    ref32 = fold_masked_stem(kern, clean, u, plan, (s, s), pad)
    kb, cb, ub = (a.astype(jnp.bfloat16) for a in (kern, clean, u))
    refb = fold_masked_stem(kb, cb, ub, plan, (s, s), pad)
    got = fold_masked_stem_kernel(kb, cb, ub, plan, (s, s), pad,
                                  interpret=True)
    assert got.dtype == jnp.bfloat16
    np.testing.assert_array_equal(np.asarray(got, np.float32),
                                  np.asarray(refb, np.float32))
    np.testing.assert_allclose(np.asarray(got, np.float32),
                               np.asarray(ref32), atol=0.5, rtol=0.1)


def test_attention_kernel_bf16():
    """bf16 q/k/v through the two-group softmax kernel vs the f32
    reference: within bf16 resolution of the exact answer, on both the
    tiny irregular geometry and a lane-edge head dim (f=128, the Mosaic
    tile width — the shape the TPU lowering actually sees)."""
    for (b, c, s, h, f, t), seed in (((2, 3, 4, 2, 8, 9), 3),
                                     ((1, 2, 4, 1, 128, 17), 5)):
        ks = jax.random.split(jax.random.PRNGKey(seed), 7)
        q = jax.random.normal(ks[0], (b, c, s, h, f))
        kd = jax.random.normal(ks[1], (b, c, s, h, f))
        vd = jax.random.normal(ks[2], (b, c, s, h, f))
        kc = jax.random.normal(ks[3], (b, t, h, f))
        vc = jax.random.normal(ks[4], (b, t, h, f))
        clean_bias = jnp.where(jax.random.bernoulli(ks[5], 0.2, (b, c, t)),
                               -1e9, 0.0)
        dirty_bias = jnp.where(jax.random.bernoulli(ks[6], 0.25, (b, c, s)),
                               -1e9, 0.0)
        dirty_bias = dirty_bias.at[:, :, 0].set(0.0)
        ref = masked_kv_attention_reference(q, kd, vd, kc, vc,
                                            clean_bias, dirty_bias)
        qb, kdb, vdb, kcb, vcb = (a.astype(jnp.bfloat16)
                                  for a in (q, kd, vd, kc, vc))
        got = masked_kv_attention(qb, kdb, vdb, kcb, vcb,
                                  clean_bias, dirty_bias, interpret=True)
        assert got.dtype == jnp.bfloat16
        np.testing.assert_allclose(np.asarray(got, np.float32),
                                   np.asarray(ref), atol=0.06,
                                   err_msg=f"shape {(b, c, s, h, f, t)}")


def test_resolve_use_pallas_gate():
    """The shared gate: "auto" stays off on CPU hosts (the tests' own
    platform), explicit modes pass through, multi-device meshes fall back
    to XLA when the op can't shard."""
    assert _backend.resolve_use_pallas("auto") == "off"
    assert _backend.resolve_use_pallas("on") == "on"
    assert _backend.resolve_use_pallas("off") == "off"
    assert _backend.resolve_use_pallas("interpret") == "interpret"
    with pytest.raises(ValueError):
        _backend.resolve_use_pallas("maybe")
    fake_mesh = types.SimpleNamespace(devices=np.empty((2, 1), object))
    assert _backend.resolve_use_pallas("on", mesh=fake_mesh,
                                       divisible=False) == "off"
    assert _backend.resolve_use_pallas("interpret", mesh=fake_mesh,
                                       divisible=True) == "interpret"


# ---------------------------------------------------------- mixer engine


def test_mixer_engine_matches_frozen_clean_reference():
    """phase1/rows vs a dense frozen-clean-rows reference: per block,
    overwrite the cached clean activations' dirty rows with the engine's
    current values, run the FULL block, re-extract the dirty rows — the
    exactness contract the mixer engine documents (clean rows frozen at
    cache values; everything else exact)."""
    from dorpatch_tpu import masks as masks_lib
    from dorpatch_tpu.models.resmlp import MixerPrunedResMLP, ResMLP

    patch, dim, depth, nc = 4, 32, 3, 10
    mod = ResMLP(num_classes=nc, patch_size=patch, dim=dim, depth=depth,
                 img_size=IMG)
    params = mod.init(jax.random.PRNGKey(0), jnp.zeros((1, IMG, IMG, 3)))
    imgs = jax.random.uniform(jax.random.PRNGKey(1), (2, IMG, IMG, 3))
    rects = _rect_table()
    eng = MixerPrunedResMLP(mod, IMG)
    assert eng.kind == "mixer"
    fam = eng.build_family(rects, num_singles=len(rects), chunk_size=3,
                           fill=0.3)
    preds, margins = jax.jit(fam.phase1)(params, imgs)

    p = params["params"]

    def block_fwd(bp, x):
        y = bp["norm1"]["alpha"] * x + bp["norm1"]["beta"]
        z = jnp.einsum("btd,tu->bud", y, bp["linear_tokens"]["kernel"]) \
            + bp["linear_tokens"]["bias"][None, :, None]
        x = x + bp["ls1"] * z
        y = bp["norm2"]["alpha"] * x + bp["norm2"]["beta"]
        h = jax.nn.gelu(y @ bp["mlp_fc1"]["kernel"] + bp["mlp_fc1"]["bias"],
                        approximate=False)
        return x + bp["ls2"] * (h @ bp["mlp_fc2"]["kernel"]
                                + bp["mlp_fc2"]["bias"])

    def embed(x):
        pt = eng._patches(x)
        return jnp.einsum("bthwc,hwcd->btd", eng.normalize(pt),
                          p["patch_embed"]["kernel"]) + p["patch_embed"]["bias"]

    xs, _zs, xf = mod.apply(params, eng.normalize(imgs), "cache")
    cov = masks_lib.rect_token_coverage(rects, IMG, patch)
    ref_logits = np.zeros((2, len(rects), nc), np.float32)
    for n in range(len(rects)):
        r0, r1, c0, c1 = rects[n, 0]
        m = imgs.at[:, r0:r1, c0:c1, :].set(0.3)
        dirty = np.nonzero(cov[n])[0]
        d = embed(m)[:, dirty]
        for layer in range(depth):
            d = block_fwd(p[f"block{layer}"],
                          xs[layer].at[:, dirty].set(d))[:, dirty]
        xfin = xf.at[:, dirty].set(d)
        pooled = (p["norm"]["alpha"] * xfin + p["norm"]["beta"]).mean(axis=1)
        ref_logits[:, n] = np.asarray(pooled @ p["head"]["kernel"]
                                      + p["head"]["bias"])

    order = np.argsort(ref_logits, axis=-1)
    ref_preds = order[..., -1]
    ref_m = np.take_along_axis(ref_logits, order[..., -1:], -1)[..., 0] \
        - np.take_along_axis(ref_logits, order[..., -2:-1], -1)[..., 0]
    np.testing.assert_array_equal(np.asarray(preds), ref_preds)
    np.testing.assert_allclose(np.asarray(margins), ref_m,
                               atol=1e-5, rtol=1e-5)

    # the ragged rows program reuses the combined tables: same contract
    sets_idx = jnp.asarray([[0, 2], [1, 3]], jnp.int32)
    pr, mr = jax.jit(fam.rows)(params, imgs, sets_idx)
    for wrow in range(2):
        for j in range(2):
            n = int(sets_idx[wrow, j])
            assert int(pr[wrow, j]) == ref_preds[wrow, n]
            assert abs(float(mr[wrow, j]) - ref_m[wrow, n]) < 1e-4


# ------------------------------------------------- shard_map wrappers
# The meshed kernel tier (the programs the DP603 shard-local audit
# certifies): each kernel under its shard_map wrapper over the data axis
# of the tests' 8-device (4, 2) virtual mesh must reproduce its
# single-chip contract EXACTLY — the wrappers shard the batch, they must
# not perturb a single bit beyond each kernel's existing tolerance.


def _mesh42():
    from jax.sharding import Mesh

    return Mesh(np.asarray(jax.devices()).reshape(-1, 2), ("data", "mask"))


@pytest.mark.skipif(jax.device_count() % 2 or jax.device_count() < 2,
                    reason="needs an even multi-device mesh")
def test_stem_kernel_mesh_wrapper_bit_exact():
    from dorpatch_tpu.ops.stem_fold import fold_masked_stem_sharded

    mesh = _mesh42()
    b = int(dict(mesh.shape)["data"])
    k, s, pad = 3, 1, ((1, 1), (1, 1))
    plan = plan_windows(_rect_table(), IMG, k, s, pad)
    h = (IMG + 2 - k) // s + 1
    kern = jax.random.normal(jax.random.PRNGKey(0), (k, k, 3, 8))
    clean = jax.random.normal(jax.random.PRNGKey(1), (b, h, h, 8))
    u = jax.random.normal(jax.random.PRNGKey(2), (b, IMG, IMG, 3))
    ref = fold_masked_stem(kern, clean, u, plan, (s, s), pad)
    got = fold_masked_stem_sharded(kern, clean, u, plan, (s, s), pad,
                                   mesh, interpret=True)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(ref))


@pytest.mark.skipif(jax.device_count() % 2 or jax.device_count() < 2,
                    reason="needs an even multi-device mesh")
def test_attention_kernel_mesh_wrapper_matches_reference():
    from dorpatch_tpu.ops.masked_kv_attn import masked_kv_attention_sharded

    mesh = _mesh42()
    b = int(dict(mesh.shape)["data"])
    c, s, h, f, t = 3, 4, 2, 8, 9
    ks = jax.random.split(jax.random.PRNGKey(3), 7)
    q = jax.random.normal(ks[0], (b, c, s, h, f))
    kd = jax.random.normal(ks[1], (b, c, s, h, f))
    vd = jax.random.normal(ks[2], (b, c, s, h, f))
    kc = jax.random.normal(ks[3], (b, t, h, f))
    vc = jax.random.normal(ks[4], (b, t, h, f))
    clean_bias = jnp.where(jax.random.bernoulli(ks[5], 0.2, (b, c, t)),
                           -1e9, 0.0)
    dirty_bias = jnp.where(jax.random.bernoulli(ks[6], 0.25, (b, c, s)),
                           -1e9, 0.0)
    dirty_bias = dirty_bias.at[:, :, 0].set(0.0)
    ref = masked_kv_attention_reference(q, kd, vd, kc, vc,
                                        clean_bias, dirty_bias)
    got = masked_kv_attention_sharded(q, kd, vd, kc, vc, clean_bias,
                                      dirty_bias, mesh, interpret=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               atol=2e-6, rtol=2e-6)


def test_mixer_engine_registry_resolution():
    """Both ResMLP names resolve the mixer engine; non-grid-aligned input
    resolves none (no token geometry)."""
    from dorpatch_tpu.models import registry

    for arch, img in (("cifar_resmlp", 32), ("resmlp_24_distilled_224", 224)):
        mod = registry.build_bare_model(arch, 10)
        eng = registry.incremental_engine(arch, mod, img)
        assert eng is not None and eng.kind == "mixer", arch
    mod = registry.build_bare_model("cifar_resmlp", 10)
    assert registry.incremental_engine("cifar_resmlp", mod, 33) is None
