"""Loss-semantics tests: values and *gradients* cross-checked against
independent torch oracles implementing the documented reference semantics
(SURVEY.md §2: CW margin, asymmetric TV, group lasso, density, L2 clip)."""

import numpy as np
import pytest
import torch

import jax
import jax.numpy as jnp

from dorpatch_tpu import losses

RNG = np.random.default_rng(0)


def _rand(*shape):
    return RNG.uniform(0, 1, size=shape).astype(np.float32)


# ---------- torch oracles (NCHW), written to the documented semantics ----------

def torch_cw(logits, y, num_classes, targeted, confidence):
    oh = torch.nn.functional.one_hot(y, num_classes).float()
    real = (logits * oh).sum(1)
    other = ((1.0 - oh) * logits - oh * 1e4).max(1).values
    m = other - real if targeted else real - other
    return torch.clamp(confidence + m, min=0.0)


def torch_directional_tv(x):
    """One-sided-gradient directional diffs: detached base minus live shift."""
    base = x.detach().clone()
    lr = torch.cat(
        [(base[..., :, :-1] - x[..., :, 1:]).abs(), base[..., :, -1:]], dim=-1)
    ud = torch.cat(
        [(base[..., :-1, :] - x[..., 1:, :]).abs(), base[..., -1:, :]], dim=-2)
    return lr + ud, lr, ud


def torch_mvwv(x):
    lv, lr, ud = torch_directional_tv(x)
    return lv * torch.where(lr > ud, ud, lr)


# ---------- CW margin ----------

def test_cw_values_match_torch():
    logits = _rand(7, 10) * 10
    y = RNG.integers(0, 10, 7)
    for targeted in (False, True):
        got = np.asarray(
            losses.cw_margin(jnp.asarray(logits), jnp.asarray(y), 10, targeted, 0.1))
        want = torch_cw(torch.tensor(logits), torch.tensor(y), 10, targeted, 0.1).numpy()
        np.testing.assert_allclose(got, want, rtol=1e-6)


def test_cw_hand_computed():
    logits = jnp.asarray([[2.0, 5.0, 1.0]])
    y = jnp.asarray([1])
    # untargeted: conf + real - other = 0.1 + 5 - 2 = 3.1
    assert float(losses.cw_margin(logits, y, 3, False, 0.1)[0]) == pytest.approx(3.1)
    # targeted: conf + other - real = 0.1 + 2 - 5 = -2.9 -> clamp 0
    assert float(losses.cw_margin(logits, y, 3, True, 0.1)[0]) == 0.0


def test_cw_switchable_matches_static():
    logits = jnp.asarray(_rand(5, 4))
    y = jnp.asarray(RNG.integers(0, 4, 5))
    for t in (False, True):
        np.testing.assert_allclose(
            np.asarray(losses.cw_margin_switchable(logits, y, 4, jnp.asarray(t), 0.1)),
            np.asarray(losses.cw_margin(logits, y, 4, t, 0.1)),
            rtol=1e-6,
        )


# ---------- TV variants: values AND gradients ----------

def test_tv_values_and_asymmetric_gradients_match_torch():
    x_np = _rand(2, 3, 6, 5)  # NCHW for torch

    xt = torch.tensor(x_np, requires_grad=True)
    out_t = torch_mvwv(xt)
    out_t.sum().backward()

    xj = jnp.asarray(np.transpose(x_np, (0, 2, 3, 1)))  # NHWC

    def f(x):
        return losses.min_var_weighted_variance(x).sum()

    val_j, grad_j = jax.value_and_grad(f)(xj)
    val_t = float(out_t.sum())
    assert float(val_j) == pytest.approx(val_t, rel=1e-5)
    grad_j_nchw = np.transpose(np.asarray(grad_j), (0, 3, 1, 2))
    np.testing.assert_allclose(grad_j_nchw, xt.grad.numpy(), rtol=1e-5, atol=1e-6)


def test_local_variance_last_row_col_is_passthrough():
    x = jnp.asarray(_rand(1, 4, 4, 1))
    lv, lr, ud = losses.local_variance(x)
    np.testing.assert_allclose(np.asarray(lr[:, :, -1, :]), np.asarray(x[:, :, -1, :]))
    np.testing.assert_allclose(np.asarray(ud[:, -1, :, :]), np.asarray(x[:, -1, :, :]))


# ---------- window sums / group lasso / density ----------

def test_window_sum_matches_torch_conv():
    x_np = _rand(2, 1, 14, 14)
    conv = torch.nn.Conv2d(1, 1, 7, stride=7, bias=False)
    with torch.no_grad():
        conv.weight.fill_(1.0)
    want = conv(torch.tensor(x_np)).detach().numpy()
    got = np.asarray(losses.window_sum(jnp.asarray(np.transpose(x_np, (0, 2, 3, 1))), 7))
    np.testing.assert_allclose(np.transpose(got, (0, 3, 1, 2)), want, rtol=1e-5)


def test_group_lasso_value_and_grad():
    m_np = _rand(2, 1, 14, 14)

    mt = torch.tensor(m_np, requires_grad=True)
    conv = torch.nn.Conv2d(1, 1, 7, stride=7, bias=False)
    with torch.no_grad():
        conv.weight.fill_(1.0)
    gl_t = 7 * conv(mt**2).sqrt().sum((1, 2, 3))
    gl_t.sum().backward()

    mj = jnp.asarray(np.transpose(m_np, (0, 2, 3, 1)))
    val, grad = jax.value_and_grad(lambda m: losses.group_lasso(m, 7).sum())(mj)
    np.testing.assert_allclose(float(val), float(gl_t.sum()), rtol=1e-5)
    np.testing.assert_allclose(
        np.transpose(np.asarray(grad), (0, 3, 1, 2)),
        mt.grad.numpy(), rtol=1e-4, atol=1e-6)


def test_density_uses_unbiased_variance():
    m_np = _rand(3, 1, 16, 16)
    conv = torch.nn.Conv2d(1, 1, 2, stride=2, bias=False)
    with torch.no_grad():
        conv.weight.fill_(1.0)
    want = conv(torch.tensor(m_np)).reshape(3, -1).var(1).detach().numpy()  # torch: ddof=1
    got = np.asarray(losses.density_loss(
        jnp.asarray(np.transpose(m_np, (0, 2, 3, 1))), 2))
    np.testing.assert_allclose(got, want, rtol=1e-5)


# ---------- L2 projection ----------

def test_l2_project_scales_to_eps():
    mask = jnp.ones((1, 8, 8, 1))
    pattern = jnp.ones((1, 8, 8, 3))
    x = jnp.zeros((1, 8, 8, 3))
    delta = losses.l2_project(mask, pattern, x, eps=4.0)
    norm = float(jnp.sqrt((delta**2).sum()))
    assert norm == pytest.approx(4.0, rel=1e-5)


def test_l2_project_noop_inside_ball_and_detached_norm_grad():
    m_np = _rand(2, 1, 8, 8)
    p_np = _rand(2, 3, 8, 8)
    x_np = _rand(2, 3, 8, 8)

    # torch oracle with detached norm
    mt = torch.tensor(m_np, requires_grad=True)
    pt = torch.tensor(p_np, requires_grad=True)
    xt = torch.tensor(x_np)
    d = mt * (pt - xt)
    n = torch.norm(d, p=2, dim=(1, 2, 3)).detach()
    scale = (4.0 / n).clip(max=1.0).view(-1, 1, 1, 1)
    (d * scale).sum().backward()

    to_nhwc = lambda a: jnp.asarray(np.transpose(a, (0, 2, 3, 1)))

    def f(m, p):
        return losses.l2_project(m, p, to_nhwc(x_np), 4.0).sum()

    gm, gp = jax.grad(f, argnums=(0, 1))(to_nhwc(m_np), to_nhwc(p_np))
    np.testing.assert_allclose(
        np.transpose(np.asarray(gm), (0, 3, 1, 2)), mt.grad.numpy(), rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(
        np.transpose(np.asarray(gp), (0, 3, 1, 2)), pt.grad.numpy(), rtol=1e-5, atol=1e-6)

    # inside the ball: delta unchanged
    small = losses.l2_project(jnp.full((1, 4, 4, 1), 0.01), jnp.full((1, 4, 4, 3), 0.5),
                              jnp.full((1, 4, 4, 3), 0.4), eps=4.0)
    np.testing.assert_allclose(np.asarray(small), 0.01 * 0.1, rtol=1e-5)


def test_structural_loss_shape_and_clean_image_normalization():
    x = jnp.asarray(_rand(2, 8, 8, 3))
    lv_clean = jnp.mean(losses.local_variance(x)[0], axis=-1)
    out = losses.structural_loss(x, lv_clean)
    assert out.shape == (2,)
    assert np.all(np.isfinite(np.asarray(out)))
