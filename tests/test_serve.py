"""Serving subsystem tests: shared batch-size bucketing, micro-batcher
flush/backpressure semantics, typed responses, the HTTP front-end, and the
tier-1 acceptance e2e — after startup warmup, 50+ mixed-size requests
complete with ZERO recompiles (recompile-watchdog-armed, trace counts
checked) and verdicts identical to a direct `defense.robust_predict` call
on the same images, with the report CLI rendering the serve section."""

import json
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from dorpatch_tpu import data as data_lib
from dorpatch_tpu import masks as masks_lib
from dorpatch_tpu.config import DefenseConfig, ServeConfig
from dorpatch_tpu.defense import PatchCleanser
from dorpatch_tpu.observe import report
from dorpatch_tpu.serve import (
    CertifiedInferenceService,
    DeadlineExceeded,
    HttpFrontend,
    MicroBatcher,
    Overloaded,
    PendingRequest,
    PredictResult,
    ServeError,
)

IMG = 32
N_CLASSES = 5


def stub_apply(params, x):
    """Weightless, occlusion-sensitive classifier: class = brightness
    bucket, so masking (gray fill) genuinely moves predictions."""
    s = x.mean(axis=(1, 2, 3))
    return jax.nn.one_hot((s * 7).astype(jnp.int32) % N_CLASSES, N_CLASSES)


def make_images(n, seed=0):
    rng = np.random.default_rng(seed)
    base = rng.uniform(0.0, 1.0, (n, 4, 4, 3)).astype(np.float32)
    return np.kron(base, np.ones((1, IMG // 4, IMG // 4, 1), np.float32))


def make_service(tmp_path=None, **serve_kw):
    kw = dict(max_batch=4, bucket_sizes=(1, 2, 4), deadline_ms=4000.0,
              max_queue_depth=64)
    kw.update(serve_kw)
    return CertifiedInferenceService(
        stub_apply, None, num_classes=N_CLASSES, img_size=IMG,
        serve_cfg=ServeConfig(**kw),
        defense_cfg=DefenseConfig(ratios=(0.1,), chunk_size=64),
        result_dir=str(tmp_path / "serve") if tmp_path is not None else None)


# ---------- shared bucketing helpers (data.py) ----------

def test_batch_buckets_ladder():
    assert data_lib.batch_buckets(1) == (1,)
    assert data_lib.batch_buckets(4) == (1, 4)
    assert data_lib.batch_buckets(8) == (1, 8)
    assert data_lib.batch_buckets(32) == (1, 8, 32)
    assert data_lib.batch_buckets(100) == (1, 8, 32, 100)
    with pytest.raises(ValueError):
        data_lib.batch_buckets(0)


def test_bucket_batch_rounds_up():
    buckets = (1, 8, 32)
    assert data_lib.bucket_batch(1, buckets) == 1
    assert data_lib.bucket_batch(2, buckets) == 8
    assert data_lib.bucket_batch(8, buckets) == 8
    assert data_lib.bucket_batch(9, buckets) == 32
    with pytest.raises(ValueError):
        data_lib.bucket_batch(33, buckets)


def test_bucket_plan_greedy_cover():
    """Greedy decomposition for ragged worklists (the pruned certifier's
    phase-2 dispatch): full buckets largest-first, padding confined to one
    tail call, `n` beyond the largest bucket allowed."""
    assert data_lib.bucket_plan(0, (1, 8, 32)) == []
    assert data_lib.bucket_plan(8, (1, 8, 32)) == [(0, 8, 8)]
    # the motivating case: 34 rows run as 32 + a padded 8, not one 128
    assert data_lib.bucket_plan(34, (1, 8, 32, 128)) == \
        [(0, 32, 32), (32, 2, 8)]
    assert data_lib.bucket_plan(34, (8, 32)) == [(0, 32, 32), (32, 2, 8)]
    assert data_lib.bucket_plan(5, (8, 32)) == [(0, 5, 8)]
    # the smallest rung never shreds a remainder into batch-1 dispatches:
    # below the next rung the remainder ships as ONE padded tail call
    assert data_lib.bucket_plan(7, (1, 8, 32)) == [(0, 7, 8)]
    assert data_lib.bucket_plan(9, (1, 8, 32)) == [(0, 8, 8), (8, 1, 1)]
    # slot ties go to the single padded call: one dispatch beats four
    assert data_lib.bucket_plan(31, (1, 8, 32)) == [(0, 31, 32)]
    # n > max(buckets): more full-bucket calls, unlike bucket_batch
    assert data_lib.bucket_plan(96, (8, 32)) == \
        [(0, 32, 32), (32, 32, 32), (64, 32, 32)]


def test_bucket_plan_properties_randomized():
    rng = np.random.default_rng(11)
    ladders = [(1, 8, 32), (8, 32), (4,), (1, 2, 4, 128)]
    for _ in range(200):
        buckets = ladders[rng.integers(0, len(ladders))]
        n = int(rng.integers(0, 300))
        plan = data_lib.bucket_plan(n, buckets)
        assert sum(c for _, c, _ in plan) == n          # exact coverage
        pos = 0
        for off, cnt, bucket in plan:
            assert off == pos and 0 < cnt <= bucket     # contiguous
            assert bucket in buckets                    # compiled shapes only
            pos += cnt
        # padding only in the final call, bounded by its bucket
        assert all(c == b for _, c, b in plan[:-1])


# ---------- defense.robust_predict bucketing (satellite) ----------

@pytest.fixture(scope="module")
def stub_certifier():
    return PatchCleanser(stub_apply, masks_lib.geometry(IMG, 0.1))


def test_bucketed_robust_predict_padding_isolation(stub_certifier):
    """Padded rows must never perturb real rows' verdicts, and ragged
    batches inside one bucket must share ONE compiled program."""
    pc = stub_certifier
    imgs = jnp.asarray(make_images(3, seed=3))
    want = pc.robust_predict(None, imgs, N_CLASSES)          # exact batch 3
    got = pc.robust_predict(None, imgs, N_CLASSES, bucket_sizes=(1, 4))
    assert len(got) == 3
    for w, g in zip(want, got):
        assert g.prediction == w.prediction
        assert g.certification == w.certification
        np.testing.assert_array_equal(g.preds_1, w.preds_1)
        np.testing.assert_array_equal(g.preds_2, w.preds_2)


def test_bucketed_robust_predict_shares_traces():
    pc = PatchCleanser(stub_apply, masks_lib.geometry(IMG, 0.1))
    for b in (2, 3, 4):  # all round up to the same bucket of 4
        recs = pc.robust_predict(None, jnp.asarray(make_images(b, seed=b)),
                                 N_CLASSES, bucket_sizes=(4, 8),
                                 prune="off")
        assert len(recs) == b
    assert int(pc._predict._cache_size()) == 1
    # the pruned schedule (the default) buckets every phase the same way:
    # ragged batches inside one bucket share ONE phase-1 program
    for b in (2, 3, 4):
        recs = pc.robust_predict(None, jnp.asarray(make_images(b, seed=b)),
                                 N_CLASSES, bucket_sizes=(4, 8))
        assert len(recs) == b
    assert int(pc._phase1._cache_size()) == 1


# ---------- micro-batcher flush semantics ----------

def _req(budget_s=10.0, image=None):
    now = time.perf_counter()
    return PendingRequest(image if image is not None else make_images(1)[0],
                          enqueued=now, deadline=now + budget_s)


def test_size_triggered_flush():
    b = MicroBatcher((1, 2, 4), max_queue_depth=16)
    for _ in range(5):
        assert b.submit(_req(budget_s=30.0))
    t0 = time.perf_counter()
    batch = b.next_batch()
    # a full top bucket flushes immediately — long before the 15 s
    # half-budget deadline trigger — and leaves the 5th request queued
    assert time.perf_counter() - t0 < 5.0
    assert len(batch) == 4
    assert b.qsize() == 1


def test_deadline_triggered_flush():
    b = MicroBatcher((1, 2, 4), max_queue_depth=16)
    assert b.submit(_req(budget_s=0.6))
    t0 = time.perf_counter()
    batch = b.next_batch()
    elapsed = time.perf_counter() - t0
    # a lone request flushes once HALF its budget is spent: well before
    # the deadline itself, but not immediately
    assert len(batch) == 1
    assert 0.1 <= elapsed < 0.55, elapsed


def test_deadline_flush_not_head_of_line_blocked():
    """A short-deadline request queued behind a long-deadline head must
    flush within ITS OWN budget — the flush instant is the min over every
    pending request, not the head's (head-of-line starvation regression)."""
    b = MicroBatcher((1, 2, 8), max_queue_depth=16)
    assert b.submit(_req(budget_s=60.0))   # head alone would flush at +30s
    assert b.submit(_req(budget_s=0.4))    # tail forces a flush at +0.2s
    t0 = time.perf_counter()
    batch = b.next_batch()
    elapsed = time.perf_counter() - t0
    assert len(batch) == 2
    assert elapsed < 1.0, elapsed


def test_backpressure_reject_and_close_drain():
    b = MicroBatcher((1, 2), max_queue_depth=2)
    assert b.submit(_req())
    assert b.submit(_req())
    assert not b.submit(_req())          # typed reject, nothing queued
    assert b.qsize() == 2
    b.close()
    assert not b.submit(_req())          # closed: no admission
    assert len(b.next_batch()) == 2      # drain flushes immediately
    assert b.next_batch() is None        # drained + closed -> worker exits


# ---------- service-level typed responses ----------

def test_service_rejects_bad_shape_and_overload(tmp_path):
    svc = make_service(max_batch=4, bucket_sizes=(4,), max_queue_depth=3,
                       deadline_ms=30000.0, flush_fraction=1.0)
    with svc:
        bad = svc.predict(np.zeros((8, 8, 3), np.float32))
        assert isinstance(bad, ServeError) and "shape" in bad.reason
        ragged = svc.predict([[1.0, [2.0]]])  # does not even parse
        assert isinstance(ragged, ServeError) and ragged.status == "error"
        # fill the bounded queue below the flush threshold (bucket of 4
        # never fills, budgets are long), then overflow it
        for _ in range(3):
            assert svc.batcher.submit(_req(budget_s=30.0))
        resp = svc.predict(make_images(1)[0])
        assert isinstance(resp, Overloaded)
        assert resp.limit == 3 and resp.queue_depth == 3
        assert resp.to_dict()["status"] == "overloaded"
    # stop() drains: the queued requests were answered, not dropped
    s = svc.stats()
    assert s["rejected"] == 1 and s["errors"] == 2  # bad shape + ragged


def test_nonfinite_deadline_rejected_service_survives():
    """Infinity/NaN are legal JSON floats; they must come back as typed
    errors and never reach the batcher's flush arithmetic (a single bad
    request previously wedged or killed the worker thread)."""
    svc = make_service()
    with svc:
        for bad in (float("inf"), float("nan"), -5.0, 0.0):
            r = svc.predict(make_images(1)[0], deadline_ms=bad)
            assert isinstance(r, ServeError), bad
            assert "deadline_ms" in r.reason
        ok = svc.predict(make_images(1)[0], deadline_ms=30000.0)
        assert isinstance(ok, PredictResult)
    assert svc.stats()["errors"] == 4 and svc.stats()["completed"] == 1


def test_service_restarts_after_stop():
    """stop() closes the batcher; a subsequent start() must serve again
    instead of rejecting everything as Overloaded (regression)."""
    svc = make_service()
    img = make_images(1)[0]
    for _ in range(2):
        with svc:
            r = svc.predict(img, deadline_ms=30000.0)
            assert isinstance(r, PredictResult)
    assert svc.stats()["completed"] == 2


def test_start_failure_unwinds_global_state(tmp_path, monkeypatch):
    """A failed start (warmup OOM / budget trip) must restore the active
    EventLog, the run span, and the recompile guard — the next run in this
    process must not inherit serving globals."""
    from dorpatch_tpu import observe

    def boom(self):
        raise RuntimeError("warmup boom")

    monkeypatch.setattr(CertifiedInferenceService, "warmup", boom)
    svc = make_service(tmp_path)
    with pytest.raises(RuntimeError, match="warmup boom"):
        svc.start()
    assert observe.active_event_log() is None
    assert observe.recompile_guard() is None
    svc.stop()  # idempotent no-op after a failed start


def test_service_deadline_exceeded(tmp_path):
    svc = make_service(deadline_ms=0.001)
    with svc:
        resp = svc.predict(make_images(1)[0])
    assert isinstance(resp, DeadlineExceeded)
    assert resp.to_dict()["status"] == "deadline_exceeded"
    assert svc.stats()["deadline_exceeded"] == 1


# ---------- tier-1 acceptance e2e ----------

def _fire(svc, images, concurrency):
    """Closed-loop burst: `concurrency` in-flight callers over `images`."""
    results = [None] * len(images)
    nxt = {"i": 0}
    lock = threading.Lock()

    def worker():
        while True:
            with lock:
                i = nxt["i"]
                if i >= len(images):
                    return
                nxt["i"] = i + 1
            results[i] = svc.predict(images[i])

    threads = [threading.Thread(target=worker) for _ in range(concurrency)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    return results


def test_serve_e2e_zero_recompile_correct_verdicts_reported(tmp_path, capsys):
    """ISSUE acceptance: warmup, then 50+ mixed-size requests -> all ok,
    ZERO recompiles (watchdog-armed + trace-count-verified), verdicts equal
    a direct defense.robust_predict on the same images, and the report CLI
    renders the serve section with latency percentiles + reject rate."""
    images = make_images(52, seed=7)
    svc = make_service(tmp_path)
    with svc:
        assert svc.prune == "exact"
        warm = svc.trace_counts()
        # one program per shape bucket, compiled at warmup: the clean
        # forward plus the pruned certifier's phase-1/pair programs per
        # image bucket and its row program per row bucket (the exhaustive
        # program exists but never compiles under a pruned schedule)
        nb = len(svc.bucket_sizes)
        assert warm["serve.clean_predict"] == nb
        for d in svc.defenses:
            r = d.spec.patch_ratio
            assert warm[f"defense.predict.r{r}"] == 0
            assert warm[f"defense.phase1.r{r}"] == nb
            assert warm[f"defense.pairs.r{r}"] == nb
            assert warm[f"defense.rows.r{r}"] == len(d.row_bucket_sizes)

        results = []
        # mixed batch sizes: lone requests (bucket 1), small bursts
        # (padded buckets), saturating bursts (full buckets)
        results += _fire(svc, images[:2], concurrency=1)
        results += _fire(svc, images[2:12], concurrency=3)
        results += _fire(svc, images[12:], concurrency=8)

        after = svc.trace_counts()
        stats = svc.stats()
    assert all(isinstance(r, PredictResult) for r in results)
    assert after == warm, f"hot path retraced: {warm} -> {after}"

    # verdict parity vs a direct certifier on the same images (fresh
    # programs, so this cannot mask a serving-side retrace)
    ref = PatchCleanser(stub_apply, masks_lib.geometry(IMG, 0.1))
    want = ref.robust_predict(None, jnp.asarray(images), N_CLASSES)
    clean_want = np.asarray(
        jnp.argmax(stub_apply(None, jnp.asarray(images)), -1))
    for i, (r, w) in enumerate(zip(results, want)):
        assert r.prediction == w.prediction, f"request {i}"
        assert r.certified == w.certification, f"request {i}"
        assert r.clean_prediction == int(clean_want[i]), f"request {i}"
        assert r.verdicts[0].ratio == 0.1
        assert r.latency_ms >= 0.0 and r.bucket in svc.bucket_sizes
        # per-request certify cost is reported and never exceeds the
        # exhaustive sweep; it matches the direct pruned certifier's count
        assert r.certify_forwards == w.forwards, f"request {i}"
        assert 0 < r.certify_forwards <= ref.num_forwards_exhaustive

    assert stats["completed"] == 52 and stats["rejected"] == 0
    assert stats["latency_ms"]["p50"] is not None
    assert 0.0 < stats["occupancy"] <= 1.0
    cf = stats["certify_forwards"]
    assert stats["prune"] == "exact"
    assert cf["total"] == sum(w.forwards for w in want)
    assert cf["per_request"] > 0
    assert cf["prune_rate"] is not None and 0.0 <= cf["prune_rate"] < 1.0

    # the results dir carries the standard telemetry contract
    rd = str(tmp_path / "serve")
    manifest = json.load(open(f"{rd}/run.json"))
    assert manifest["service"] == "serve" and manifest["run_id"]

    assert report.main([rd]) == 0
    out = capsys.readouterr().out
    assert "-- serve --" in out
    assert "p50" in out and "p95" in out
    assert "reject rate 0.0%" in out
    assert "run span never closed" not in out  # clean shutdown

    assert report.main([rd, "--json"]) == 0
    s = json.loads(capsys.readouterr().out)
    assert s["serve"]["requests"] == 52
    assert s["serve"]["by_status"] == {"ok": 52}
    assert s["serve"]["latency_ms"]["count"] == 52
    assert s["serve"]["reject_rate"] == 0.0
    assert s["serve"]["occupancy"] and 0.0 < s["serve"]["occupancy"] <= 1.0
    assert s["serve"]["throughput_rps"] > 0


# ---------- load generator (tools/loadgen.py) ----------

def test_loadgen_inprocess_stub(tmp_path, capsys):
    """The CI smoke's exact path: in-process stub service, BENCH-style JSON
    line, zero-recompile contract reported, telemetry dir renders."""
    import importlib.util
    import os

    spec = importlib.util.spec_from_file_location(
        "loadgen", os.path.join(os.path.dirname(__file__), "..", "tools",
                                "loadgen.py"))
    loadgen = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(loadgen)

    out = tmp_path / "loadgen.json"
    rd = tmp_path / "serve"
    rc = loadgen.main(["--requests", "12", "--stub-victim",
                       "--results-dir", str(rd), "--out", str(out),
                       "--concurrency", "3"])
    assert rc == 0
    rep = json.loads(out.read_text())
    assert rep["metric"] == "serve_load" and rep["mode"] == "closed"
    assert rep["by_status"] == {"ok": 12}
    assert rep["zero_recompile"] is True
    assert rep["latency_ms"]["p50"] is not None
    capsys.readouterr()
    assert report.main([str(rd)]) == 0
    assert "-- serve --" in capsys.readouterr().out


# ---------- HTTP front-end ----------

def test_http_front_end(tmp_path):
    svc = make_service()
    with svc, HttpFrontend(svc, port=0) as fe:
        base = f"http://127.0.0.1:{fe.port}"
        with urllib.request.urlopen(f"{base}/healthz", timeout=30) as r:
            h = json.loads(r.read())
        assert h["status"] == "ok" and h["warm"] is True

        body = json.dumps({"image": make_images(1)[0].tolist()}).encode()
        req = urllib.request.Request(
            f"{base}/predict", data=body,
            headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=60) as r:
            assert r.status == 200
            p = json.loads(r.read())
        assert p["status"] == "ok"
        assert isinstance(p["prediction"], int)
        assert isinstance(p["certified"], bool)
        assert p["verdicts"][0]["ratio"] == 0.1

        with urllib.request.urlopen(f"{base}/stats", timeout=30) as r:
            st = json.loads(r.read())
        assert st["completed"] == 1 and st["trace_counts"]

        # malformed body -> 400 with a typed error payload
        bad = urllib.request.Request(
            f"{base}/predict", data=b"not json",
            headers={"Content-Type": "application/json"})
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(bad, timeout=30)
        assert ei.value.code == 400
        assert json.loads(ei.value.read())["status"] == "error"


# ---------- supervised replica pool (pool.py) ----------

def test_retry_delay_shared_and_deterministic():
    """One backoff formula for farm retries, replica restarts, and the
    loadgen 503 loop: deterministic per (id, attempt), doubling, capped."""
    from dorpatch_tpu.backoff import retry_delay
    from dorpatch_tpu.farm.queue import retry_delay as farm_retry_delay

    assert farm_retry_delay is retry_delay  # the farm re-exports, not forks
    a = retry_delay("serve-r0", 1, base=0.5, cap=30.0)
    assert a == retry_delay("serve-r0", 1, base=0.5, cap=30.0)
    assert 0.5 <= a <= 0.5 * 1.25
    assert retry_delay("serve-r0", 2, base=0.5, cap=30.0) >= a
    assert retry_delay("serve-r0", 50, base=0.5, cap=30.0) <= 30.0 * 1.25
    assert retry_delay("x", 1, base=0.5, cap=30.0) != \
        retry_delay("y", 1, base=0.5, cap=30.0)  # id-seeded jitter


def test_pending_request_claim_exactly_once():
    """The failover arbiter: N racing resolvers, exactly one wins."""
    req = _req(budget_s=10.0)
    wins = []
    barrier = threading.Barrier(8)

    def racer(i):
        barrier.wait()
        if req.resolve(i):
            wins.append(i)

    threads = [threading.Thread(target=racer, args=(i,)) for i in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert len(wins) == 1
    assert req.done.is_set() and req.result == wins[0]
    assert not req.claim()  # late duplicate: shed, never double-answered


def test_batcher_requeue_front_and_idle_tick():
    b = MicroBatcher((1, 2, 4), max_queue_depth=2)
    # idle tick: with a timeout, an empty queue yields [] (the worker's
    # heartbeat cadence), not a block
    t0 = time.perf_counter()
    assert b.next_batch(timeout=0.1) == []
    assert 0.05 < time.perf_counter() - t0 < 2.0
    old = [_req(budget_s=30.0), _req(budget_s=30.0)]
    new = [_req(budget_s=30.0), _req(budget_s=30.0)]
    for r in new:
        assert b.submit(r)
    # failover requeue: jumps the FIFO (those requests already burned
    # queue time) in original order, exempt from the depth bound
    assert b.requeue(old)
    assert b.qsize() == 4
    assert b.next_batch() == old + new
    b.close()
    assert not b.requeue(old)  # closed: caller resolves them as errors


def test_wedged_replica_fails_over_restarts_and_reports(tmp_path, capsys):
    """The tentpole drill: 2 replicas, chaos wedges replica 0 mid-batch
    with requests in flight. Every admitted request is answered ok exactly
    once (failover re-dispatch inside the original deadline, duplicates
    shed not double-answered); the supervisor classifies the wedge via
    missed beats, quarantines, and restarts through a fresh program bank;
    the report renders the `-- replicas --` accounting."""
    svc = make_service(tmp_path, max_batch=2, bucket_sizes=(1, 2),
                       deadline_ms=10000.0, replicas=2, max_restarts=2,
                       restart_backoff_base=0.2, restart_backoff_cap=1.0,
                       replica_stale_s=0.4, chaos="wedge_dispatch")
    images = make_images(6, seed=9)
    with svc:
        results = _fire(svc, images, concurrency=6)
        assert all(isinstance(r, PredictResult) for r in results), \
            [getattr(r, "status", r) for r in results]
        st = svc.stats()
        assert st["failover"]["redispatched"] >= 1
        deadline = time.time() + 60.0
        while time.time() < deadline:
            snap = {r["replica"]: r for r in svc.stats()["replicas"]}
            if snap[0]["state"] == "healthy" and snap[0]["generation"] == 1:
                break
            time.sleep(0.2)
        snap = {r["replica"]: r for r in svc.stats()["replicas"]}
        assert snap[0]["state"] == "healthy" and snap[0]["generation"] == 1
        assert snap[0]["restarts"] == 1
        assert snap[1]["state"] == "healthy" and snap[1]["restarts"] == 0
        assert snap[1]["completed"] == 6  # the healthy replica took it all
        h = svc.healthz()
        assert h["status"] == "ok" and h["replicas"]["healthy"] == 2
        post = svc.predict(images[0], deadline_ms=10000.0)
        assert isinstance(post, PredictResult)
    assert svc.stats()["completed"] == 7

    rd = str(tmp_path / "serve")
    events = [json.loads(line) for line in open(f"{rd}/events.jsonl")]
    names = [e.get("name") for e in events]
    sick = [e for e in events if e.get("name") == "serve.replica.sick"]
    assert sick and sick[0]["cause"] == "wedged" and sick[0]["replica"] == 0
    assert "serve.replica.quarantine" in names
    assert "serve.replica.restart" in names
    assert report.main([rd]) == 0
    out = capsys.readouterr().out
    assert "-- replicas --" in out
    assert "1 restart(s)" in out
    assert report.main([rd, "--json"]) == 0
    s = json.loads(capsys.readouterr().out)
    assert s["replicas"]["restarts"] == 1 and s["replicas"]["retired"] == 0
    assert s["replicas"]["failed_over"] >= 1


def test_raised_worker_restarts_and_redispatches(tmp_path):
    """chaos `raise_in_worker` kills the only replica's thread with a
    request in flight: the supervisor classifies it `raised`, re-dispatches
    the request, restarts the replica after backoff, and the SAME request
    is answered ok by the fresh generation — at most one re-dispatch."""
    svc = make_service(tmp_path, max_batch=1, bucket_sizes=(1,),
                       deadline_ms=30000.0, replicas=1, max_restarts=2,
                       restart_backoff_base=0.2, restart_backoff_cap=1.0,
                       replica_stale_s=0.4, chaos="raise_in_worker")
    with svc:
        r = svc.predict(make_images(1)[0], deadline_ms=30000.0)
        assert isinstance(r, PredictResult), getattr(r, "reason", r)
        st = svc.stats()
        assert st["failover"]["redispatched"] == 1
        snap = st["replicas"][0]
        assert snap["generation"] == 1 and snap["restarts"] == 1
    events = [json.loads(line)
              for line in open(f"{tmp_path}/serve/events.jsonl")]
    sick = [e for e in events if e.get("name") == "serve.replica.sick"]
    assert sick[0]["cause"] == "raised" and sick[0]["inflight"] == 1


def test_wedge_heartbeat_detected_while_thread_lives(tmp_path):
    """chaos `wedge_heartbeat` freezes only the BEATS: the thread keeps
    serving, yet the supervisor must still declare it wedged (missed-beat
    staleness, not thread liveness) and cycle it through a restart."""
    svc = make_service(tmp_path, max_batch=1, bucket_sizes=(1,),
                       deadline_ms=15000.0, replicas=1, max_restarts=2,
                       restart_backoff_base=0.2, restart_backoff_cap=1.0,
                       replica_stale_s=0.4, chaos="wedge_heartbeat")
    with svc:
        first = svc.predict(make_images(1)[0], deadline_ms=15000.0)
        assert isinstance(first, PredictResult)  # frozen beats still serve
        deadline = time.time() + 60.0
        while time.time() < deadline:
            snap = svc.stats()["replicas"][0]
            if snap["state"] == "healthy" and snap["generation"] == 1:
                break
            time.sleep(0.2)
        snap = svc.stats()["replicas"][0]
        assert snap["generation"] == 1 and snap["restarts"] == 1
        second = svc.predict(make_images(1)[0], deadline_ms=15000.0)
        assert isinstance(second, PredictResult)
    events = [json.loads(line)
              for line in open(f"{tmp_path}/serve/events.jsonl")]
    sick = [e for e in events if e.get("name") == "serve.replica.sick"]
    assert sick[0]["cause"] == "wedged"


def test_exhausted_restarts_retire_degrade_not_hang(tmp_path):
    """A replica past max_restarts retires; with nothing left the pool
    degrades: admission shrinks to zero (`Overloaded` immediately), the
    queue drains typed, and nothing ever hangs (satellite: dead worker)."""
    svc = make_service(tmp_path, max_batch=1, bucket_sizes=(1,),
                       deadline_ms=10000.0, replicas=1, max_restarts=0,
                       replica_stale_s=0.3, chaos="raise_in_worker")
    with svc, HttpFrontend(svc, port=0) as fe:
        base = f"http://127.0.0.1:{fe.port}"
        body = json.dumps({"image": make_images(1)[0].tolist(),
                           "deadline_ms": 10000.0}).encode()
        req = urllib.request.Request(
            f"{base}/predict", data=body,
            headers={"Content-Type": "application/json"})
        # the in-flight request dies with the only replica -> typed 500,
        # answered promptly (never hangs out its deadline)
        t0 = time.time()
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(req, timeout=60)
        assert ei.value.code == 500
        assert json.loads(ei.value.read())["status"] == "internal_error"
        assert time.time() - t0 < 8.0
        # retired pool: health says dead worker, /predict is a typed 503
        deadline = time.time() + 30.0
        while time.time() < deadline:
            snap = svc.stats()["replicas"][0]
            if snap["state"] == "retired" and not snap["thread_alive"]:
                break
            time.sleep(0.1)
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(f"{base}/healthz", timeout=30)
        assert ei.value.code == 503
        h = json.loads(ei.value.read())
        assert h["status"] == "unhealthy" and h["worker_alive"] is False
        assert h["replicas"] == {"total": 1, "healthy": 0, "retired": 1}
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(req, timeout=30)
        assert ei.value.code == 503
        assert json.loads(ei.value.read())["status"] == "overloaded"
    events = [json.loads(line)
              for line in open(f"{tmp_path}/serve/events.jsonl")]
    retire = [e for e in events if e.get("name") == "serve.replica.retire"]
    assert retire and retire[0]["max_queue_depth"] == 0


# ---------- incremental certify on the serve hot path ----------

# ~85 s on the single CI core; the serve loadgen smoke in run_tests.sh
# gates the same zero-recompile hot path on every CI run.
@pytest.mark.slow
def test_serve_incremental_zero_recompile_e2e(tmp_path):
    """Acceptance: with the token incremental engine enabled, warmup
    compiles the engine-backed programs once per shape bucket and mixed
    live traffic (ragged batches, every verdict class) never retraces —
    trace counts identical before/after under the ARMED recompile watchdog
    (enforce_budgets=True arms it for the worker). Responses carry the
    fractional `certify_forward_equivalents`, /stats aggregates it, and
    verdicts match a direct robust_predict on the same images."""
    from dorpatch_tpu.models.registry import incremental_engine
    from dorpatch_tpu.models.vit import ViT

    module = ViT(num_classes=N_CLASSES, patch_size=4, dim=32, depth=2,
                 num_heads=2, img_size=(IMG, IMG))
    params = module.init(jax.random.PRNGKey(3),
                         jnp.zeros((1, IMG, IMG, 3)))

    def apply_fn(p, x):
        return module.apply(p, (x - 0.5) / 0.5)

    engine = incremental_engine("cifar_vit", module, IMG)
    # explicit "token" (the default "auto" resolves to "token-exact",
    # which on this random-init victim would escalate nearly every image
    # through the exhaustive program and break the fe < forwards law this
    # test checks; token-exact's own laws are covered in test_defense)
    dcfg = DefenseConfig(ratios=(0.1,), chunk_size=64,
                         num_mask_per_axis=3, incremental="token")
    svc = CertifiedInferenceService(
        apply_fn, params, num_classes=N_CLASSES, img_size=IMG,
        serve_cfg=ServeConfig(max_batch=2, bucket_sizes=(1, 2),
                              deadline_ms=60000.0),
        defense_cfg=dcfg,
        result_dir=str(tmp_path / "serve"),
        incremental_engine=engine)
    assert svc.incremental == "token"
    imgs = make_images(6, seed=4)
    imgs[1] = 0.5  # a provably-unanimous frame among the mix
    with svc:
        warm = svc.trace_counts()
        assert "defense.phase1.token.r0.1" in warm
        # a burst (bucket-2 batches) plus sequential singles (bucket 1)
        results = [None] * 4
        def worker(i):
            results[i] = svc.predict(imgs[i])
        threads = [threading.Thread(target=worker, args=(i,))
                   for i in range(4)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        results.extend(svc.predict(imgs[i]) for i in (4, 5))
        assert all(isinstance(r, PredictResult) for r in results)
        for r in results:
            assert r.certify_forward_equivalents is not None
            assert 0 < r.certify_forward_equivalents < r.certify_forwards
        stats = svc.stats()
        assert svc.trace_counts() == warm, "serve hot path retraced"
    assert stats["incremental"] == "token"
    cf = stats["certify_forwards"]
    assert cf["forward_equivalents_per_request"] is not None
    assert cf["forward_equivalents_per_request"] < cf["per_request"]
    # verdict parity vs the direct defense call on the same images
    d = svc.defenses[0]
    direct = d.robust_predict(params, jnp.asarray(imgs),
                              N_CLASSES, bucket_sizes=(1, 2, 8))
    for r, rec in zip(results, direct):
        assert r.prediction == rec.prediction
        assert r.verdicts[0].certified == rec.certification
