"""The program-baseline tier (analysis/baseline.py, DP300-DP304):
fingerprint stability/sensitivity, the interface-vs-body split, the static
cost model and the planted-regression gate, deterministic/idempotent
`--baseline update`, suppression semantics (noqa + ALLOWLIST), the shipped
tree checking clean against the shipped baselines.json, and the CLI exit
contract in-process and via subprocess."""

import json
import pathlib
import subprocess
import sys
import textwrap

import pytest

import jax.numpy as jnp

from dorpatch_tpu.analysis import baseline
from dorpatch_tpu.analysis import entrypoints as ep_mod
from dorpatch_tpu.analysis.cli import main as cli_main
from dorpatch_tpu.analysis.entrypoints import (
    EntryPoint,
    abstractify,
    clear_entrypoints,
    register_bucket_ladder,
)

REPO = pathlib.Path(__file__).resolve().parents[1]
FIXTURES = REPO / "tests" / "fixtures" / "analysis"
sys.path.insert(0, str(FIXTURES))

import baseline_programs  # noqa: E402  (fixture module, see path insert)


def rule_ids(findings):
    return sorted({f.rule_id for f in findings})


def snap(ep, compiled=False):
    entry, errs = baseline.snapshot_entrypoint(ep, compiled=compiled)
    assert entry is not None, errs
    return entry


# ---------- fingerprint stability / sensitivity ----------

def test_fingerprint_stable_across_reenumeration():
    """The same program registered twice through a full registry
    clear+re-enumerate cycle (a fresh process, as far as the registry is
    concerned) fingerprints identically — jit object identity, wrapper
    state, and registration order must not leak into the hash."""
    prints = []
    for _ in range(2):
        clear_entrypoints()
        eps = baseline_programs.clean_entrypoints()
        data, errs = baseline.build_baseline(eps, compiled=False)
        assert not errs
        prints.append(baseline.dump_baseline(data))
    clear_entrypoints()
    assert prints[0] == prints[1]


def test_fingerprint_ignores_local_rename():
    a = snap(baseline_programs.ref_entrypoint())
    b = snap(baseline_programs.renamed_entrypoint())
    assert a["fingerprint"] == b["fingerprint"]


def test_fingerprint_changes_on_literal():
    a = snap(baseline_programs.ref_entrypoint())
    b = snap(baseline_programs.literal_entrypoint())
    assert a["fingerprint"] != b["fingerprint"]


def test_fingerprint_changes_on_eqn():
    a = snap(baseline_programs.ref_entrypoint())
    b = snap(baseline_programs.regressed_entrypoint())
    assert a["fingerprint"] != b["fingerprint"]


def test_interface_split_from_body():
    """Donation is interface, not body: flipping donate_argnums keeps the
    fingerprint and changes only the interface sha — the split that makes
    DP304 (interface drift with unchanged fingerprint) reachable."""
    a = snap(baseline_programs.carry_entrypoint())
    b = snap(baseline_programs.carry_donated_entrypoint())
    assert a["fingerprint"] == b["fingerprint"]
    assert a["interface"]["sha"] != b["interface"]["sha"]
    assert b["interface"]["donated"] == [0]


# ---------- cost model ----------

def test_estimator_counts_matmul_flops():
    ctx_cost = snap(baseline_programs.ref_entrypoint())["cost"]
    # (8,16)@(16,16): 2*8*16*16 = 4096 matmul flops dominate
    assert ctx_cost["est_flops"] >= 4096
    prims = snap(baseline_programs.ref_entrypoint())["primitives"]
    assert max(prims, key=prims.get) == "dot_general"


def test_compiled_cost_available():
    entry = snap(baseline_programs.ref_entrypoint(), compiled=True)
    assert entry["cost"]["flops"] > 0
    assert entry["cost"]["bytes"] > 0
    assert "temp_bytes" in entry["cost"]


# ---------- the drift rules ----------

def _clean_baseline():
    data, errs = baseline.build_baseline(
        baseline_programs.clean_entrypoints(), compiled=False)
    assert not errs
    return data


def test_dp301_planted_regression_names_primitive():
    """Acceptance: the planted extra matmul trips DP301, naming the entry
    point and the dominant regressing primitive."""
    findings = baseline.check_entrypoints(
        baseline_programs.regressed_entrypoints(), _clean_baseline(),
        compiled=False)
    dp301 = [f for f in findings if f.rule_id == "DP301"]
    assert len(dp301) == 1
    assert "[fx.base.ref]" in dp301[0].message
    assert "dot_general" in dp301[0].message


def test_dp304_donation_flip():
    findings = baseline.check_entrypoints(
        baseline_programs.regressed_entrypoints(), _clean_baseline(),
        compiled=False)
    dp304 = [f for f in findings if f.rule_id == "DP304"]
    assert len(dp304) == 1
    assert "[fx.base.carry]" in dp304[0].message
    assert "donated" in dp304[0].message


def test_dp300_fires_once_per_drifted_program():
    live = [baseline_programs.literal_entrypoint(),
            baseline_programs.carry_entrypoint()]
    findings = baseline.check_entrypoints(live, _clean_baseline(),
                                          compiled=False)
    assert [f.rule_id for f in findings if f.rule_id == "DP300"] == ["DP300"]


def test_dp302_added_and_removed():
    data = _clean_baseline()
    live = [baseline_programs.ref_entrypoint(),
            baseline_programs.carry_entrypoint(name="fx.base.carry2")]
    findings = baseline.check_entrypoints(live, data, compiled=False)
    msgs = {f.rule_id: f.message for f in findings}
    assert rule_ids(findings) == ["DP302"]
    assert sum(1 for f in findings if f.rule_id == "DP302") == 2
    assert "fx.base.carry2" in str(msgs)


def test_dp303_budget_vs_variants_and_ladder():
    eps = [baseline_programs.ref_entrypoint(name="fx.rows[w4]"),
           baseline_programs.ref_entrypoint(name="fx.rows[w8]")]
    data, errs = baseline.build_baseline(eps, compiled=False)
    assert not errs
    # variant count (2) vs declared budget: mismatch fires, match is quiet
    bad = baseline.check_entrypoints(eps, data, budgets={"fx.rows": 3},
                                     compiled=False)
    assert rule_ids(bad) == ["DP303"]
    assert "implies 2 bucket(s)" in bad[0].message
    assert not baseline.check_entrypoints(eps, data, budgets={"fx.rows": 2},
                                          compiled=False)
    # an explicit ladder outranks the variant count
    assert not baseline.check_entrypoints(
        eps, data, budgets={"fx.rows": 3}, ladders={"fx.rows": 3},
        compiled=False)
    # undeclared budget / unbucketed name: nothing to check
    assert not baseline.check_entrypoints(
        eps, data, budgets={"fx.rows": None, "fx.orphan": 5}, compiled=False)


def test_bucket_ladder_registry_round_trip():
    clear_entrypoints()
    try:
        register_bucket_ladder("fx.rows", (4, 8, 16))
        assert ep_mod.bucket_ladders() == {"fx.rows": 3}
    finally:
        clear_entrypoints()


def test_on_budget_hook_captures_declared_budget():
    from dorpatch_tpu import observe

    clear_entrypoints()
    try:
        with ep_mod.capture_entrypoints():
            observe.timed_first_call(baseline_programs._ref, "fx.budgeted",
                                     recompile_budget=7)
        assert ep_mod.declared_budgets() == {"fx.budgeted": 7}
    finally:
        clear_entrypoints()


# ---------- suppression: allowlist + source noqa ----------

def test_allowlist_overlay_suppresses_dp301():
    findings = baseline.check_entrypoints(
        baseline_programs.regressed_entrypoints(), _clean_baseline(),
        compiled=False,
        allow={"fx.base.*": {"DP301": "fixture", "DP304": "fixture"}})
    assert "DP301" not in rule_ids(findings)
    assert "DP304" not in rule_ids(findings)


def test_noqa_on_def_line_suppresses_dp300(tmp_path):
    mod = tmp_path / "noqa_base_prog.py"
    mod.write_text(textwrap.dedent("""\
        import jax
        import jax.numpy as jnp

        @jax.jit
        def prog(x):  # noqa: DP300 — fixture: drift here is deliberate
            return jnp.tanh(x) * 3.0
    """), encoding="utf-8")
    sys.path.insert(0, str(tmp_path))
    try:
        import noqa_base_prog
        args = (abstractify(jnp.zeros((4,), jnp.float32)),)
        ep = EntryPoint(name="fx.noqa", fn=noqa_base_prog.prog, args=args)
        data, _ = baseline.build_baseline([ep], compiled=False)
        data["entries"]["fx.noqa"]["fingerprint"] = "0" * 16  # plant drift
        findings = baseline.check_entrypoints([ep], data, compiled=False)
        assert "DP300" not in rule_ids(findings)
    finally:
        sys.path.remove(str(tmp_path))
        sys.modules.pop("noqa_base_prog", None)


def test_select_filters_rules():
    findings = baseline.check_entrypoints(
        baseline_programs.regressed_entrypoints(), _clean_baseline(),
        compiled=False, select=["DP304"])
    assert rule_ids(findings) == ["DP304"]


# ---------- update determinism / idempotency ----------

def test_update_idempotent_byte_identical(tmp_path):
    path = tmp_path / "baselines.json"
    argv = ["--baseline", "update", "--baseline-file", str(path),
            "--baseline-cost", "estimate",
            "--entrypoints", "baseline_programs:clean_entrypoints"]
    assert cli_main(list(argv)) == 0
    first = path.read_bytes()
    assert cli_main(list(argv)) == 0
    assert path.read_bytes() == first
    data = json.loads(first)
    assert sorted(data["entries"]) == ["fx.base.carry", "fx.base.ref"]
    assert data["version"] == 1


def test_update_refuses_silent_entry_removal(tmp_path, capsys):
    """A regeneration that would DROP baselined entry points (the classic
    cause: running without the 8-device virtual mesh, losing every .mesh
    entry) must refuse and leave the file untouched unless --allow-remove
    says the shrink is intentional."""
    path = tmp_path / "baselines.json"
    assert cli_main(["--baseline", "update", "--baseline-file", str(path),
                     "--baseline-cost", "estimate",
                     "--entrypoints",
                     "baseline_programs:clean_entrypoints"]) == 0
    before = path.read_bytes()
    capsys.readouterr()

    rc = cli_main(["--baseline", "update", "--baseline-file", str(path),
                   "--baseline-cost", "estimate",
                   "--entrypoints", "baseline_programs:shrunk_entrypoints"])
    assert rc == 1
    err = capsys.readouterr().err
    assert "fx.base.carry" in err and "--allow-remove" in err
    assert "NOT written" in err
    assert path.read_bytes() == before, "refusal must leave the file alone"

    assert cli_main(["--baseline", "update", "--baseline-file", str(path),
                     "--baseline-cost", "estimate", "--allow-remove",
                     "--entrypoints",
                     "baseline_programs:shrunk_entrypoints"]) == 0
    data = json.loads(path.read_bytes())
    assert sorted(data["entries"]) == ["fx.base.ref"]


def test_update_refuses_untraceable(tmp_path):
    import trace_programs

    path = tmp_path / "baselines.json"
    assert cli_main(["--baseline", "update", "--baseline-file", str(path),
                     "--baseline-cost", "estimate",
                     "--entrypoints", "trace_programs:bad_entrypoints"]) == 1
    assert not path.exists(), "a holed baseline must never be written"


# ---------- CLI exit contract ----------

def test_cli_check_exit_codes(tmp_path, capsys):
    path = tmp_path / "baselines.json"
    assert cli_main(["--baseline", "update", "--baseline-file", str(path),
                     "--baseline-cost", "estimate",
                     "--entrypoints",
                     "baseline_programs:clean_entrypoints"]) == 0
    capsys.readouterr()
    assert cli_main(["--baseline", "check", "--baseline-file", str(path),
                     "--baseline-cost", "estimate",
                     "--entrypoints",
                     "baseline_programs:clean_entrypoints"]) == 0
    capsys.readouterr()
    rc = cli_main(["--baseline", "check", "--baseline-file", str(path),
                   "--baseline-cost", "estimate", "--format", "json",
                   "--entrypoints",
                   "baseline_programs:regressed_entrypoints"])
    assert rc == 1
    out = capsys.readouterr().out.strip().splitlines()
    objs = [json.loads(line) for line in out if line]
    # the planted extra matmul is also a program change, so the fingerprint
    # drift (DP300) rides along with the cost regression (DP301)
    assert {o["rule"] for o in objs} == {"DP300", "DP301", "DP304"}
    assert all("message" in o and "path" in o for o in objs)


def test_cli_check_select_and_usage_errors(tmp_path, capsys):
    path = tmp_path / "baselines.json"
    assert cli_main(["--baseline", "update", "--baseline-file", str(path),
                     "--baseline-cost", "estimate",
                     "--entrypoints",
                     "baseline_programs:clean_entrypoints"]) == 0
    capsys.readouterr()
    rc = cli_main(["--baseline", "check", "--baseline-file", str(path),
                   "--baseline-cost", "estimate", "--select", "DP304",
                   "--entrypoints",
                   "baseline_programs:regressed_entrypoints"])
    assert rc == 1
    out = capsys.readouterr().out
    assert "DP304" in out and "DP301" not in out
    # cross-wing select is a usage error, not a vacuous pass
    assert cli_main(["--baseline", "check", "--select", "DP201"]) == 2
    assert cli_main(["--trace", "--select", "DP300"]) == 2
    # missing baseline file is a usage error
    assert cli_main(["--baseline", "check", "--baseline-file",
                     str(tmp_path / "nope.json"), "--baseline-cost",
                     "estimate", "--entrypoints",
                     "baseline_programs:clean_entrypoints"]) == 2
    # bad loader spec
    assert cli_main(["--baseline", "check",
                     "--entrypoints", "no.such.module:x"]) == 2


def test_cli_baseline_outranks_trace_flag(tmp_path, capsys):
    """`dorpatch-audit --baseline ...` prepends --trace; the baseline mode
    must win so the console script reaches the baseline tier."""
    from dorpatch_tpu.analysis.cli import audit_main

    path = tmp_path / "baselines.json"
    assert audit_main(["--baseline", "update", "--baseline-file", str(path),
                       "--baseline-cost", "estimate",
                       "--entrypoints",
                       "baseline_programs:clean_entrypoints"]) == 0
    assert path.exists()


def test_cli_baseline_report_written(tmp_path, capsys):
    path = tmp_path / "baselines.json"
    rd = tmp_path / "results"
    assert cli_main(["--baseline", "update", "--baseline-file", str(path),
                     "--baseline-cost", "estimate",
                     "--entrypoints",
                     "baseline_programs:clean_entrypoints"]) == 0
    rc = cli_main(["--baseline", "check", "--baseline-file", str(path),
                   "--baseline-cost", "estimate",
                   "--baseline-report", str(rd),
                   "--entrypoints",
                   "baseline_programs:regressed_entrypoints"])
    assert rc == 1
    summary = json.loads((rd / "baseline_check.json").read_text())
    assert summary["clean"] is False
    assert summary["findings_by_rule"] == {"DP300": 1, "DP301": 1,
                                           "DP304": 1}
    assert summary["fingerprint_set"]
    # the offline telemetry report renders the section
    from dorpatch_tpu.observe import report as report_mod

    s = report_mod.summarize(str(rd))
    assert s["baseline"]["clean"] is False
    text = report_mod.format_report(s)
    assert "-- program baseline --" in text
    assert "DRIFTED" in text and "DP301" in text


def test_list_rules_includes_baseline_rows(capsys):
    assert cli_main(["--list-rules"]) == 0
    out = capsys.readouterr().out
    for rid in ("DP300", "DP301", "DP302", "DP303", "DP304"):
        assert rid in out


# ---------- the shipped tree vs the shipped baseline ----------

def test_shipped_baseline_covers_all_entry_points():
    """Acceptance: the checked-in baselines.json names exactly the
    registered production set — zero uncovered names either way —
    and the live fingerprints match (estimate mode: compile-free)."""
    data = baseline.load_baseline()
    assert data is not None, "analysis/baselines.json missing"
    eps = ep_mod.production_entrypoints()
    assert sorted(data["entries"]) == sorted(e.name for e in eps)
    findings = baseline.check_entrypoints(
        eps, data, budgets=ep_mod.declared_budgets(),
        ladders=ep_mod.bucket_ladders(), compiled=False)
    assert not findings, "\n".join(f.render() for f in findings)


def test_program_set_stamp_matches_shipped_baseline():
    stamp = baseline.program_set_stamp()
    assert stamp is not None
    data = baseline.load_baseline()
    assert stamp["entries"] == len(data["entries"])
    assert stamp["hash"] == baseline.fingerprint_set_hash(data["entries"])
    assert stamp["file"] == "analysis/baselines.json"


@pytest.mark.slow
def test_cli_baseline_check_production_subprocess(tmp_path):
    """The run_tests.sh gate end-to-end: `--baseline check` audits the
    production registry against the shipped baseline in a fresh process
    (compiled-cost mode, XLA cost analysis per program) and exits 0."""
    proc = subprocess.run(
        [sys.executable, "-m", "dorpatch_tpu.analysis", "--baseline",
         "check"],
        cwd=REPO, capture_output=True, text=True, timeout=420,
        env={"PATH": "/usr/bin:/bin:/usr/local/bin",
             "JAX_PLATFORMS": "cpu",
             "XLA_FLAGS": "--xla_force_host_platform_device_count=8",
             "HOME": str(tmp_path)})
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "baseline check" in proc.stderr
