"""DorPatch attack engine tests: patch selection, target switching, sampling
shape-invariants, and a smoke end-to-end attack on a tiny victim."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from dorpatch_tpu.attack import (
    AttackResult,
    DorPatch,
    TrainState,
    majority_incorrect_label,
    patch_selection,
)
from dorpatch_tpu.config import AttackConfig


# ---------- patch_selection ----------

def test_patch_selection_topk_groups():
    h = w = 16
    unit = 4
    mask = np.zeros((1, h, w, 1), np.float32)
    # plant mass in three cells: (0,0) heavy, (2,1) medium, (3,3) light
    mask[0, 0:4, 0:4, 0] = 1.0
    mask[0, 8:12, 4:8, 0] = 0.5
    mask[0, 12:16, 12:16, 0] = 0.1
    # budget for exactly 2 cells: k = floor(16*16*b/16) = 2 -> b = 2*16/256
    out = np.asarray(patch_selection(jnp.asarray(mask), 2 * 16 / 256, unit))
    assert set(np.unique(out)) <= {0.0, 1.0}
    assert out[0, 0:4, 0:4, 0].all()
    assert out[0, 8:12, 4:8, 0].all()
    assert out.sum() == 2 * unit * unit


def test_patch_selection_skips_empty_groups():
    """Cells with zero mass are not selected even when k allows more."""
    mask = np.zeros((1, 16, 16, 1), np.float32)
    mask[0, 0:4, 0:4, 0] = 1.0
    out = np.asarray(patch_selection(jnp.asarray(mask), 3 * 16 / 256, 4))
    assert out.sum() == 16  # only the one positive cell


def test_patch_selection_batched():
    mask = np.random.default_rng(0).uniform(size=(3, 16, 16, 1)).astype(np.float32)
    out = np.asarray(patch_selection(jnp.asarray(mask), 0.25, 4))
    assert out.shape == (3, 16, 16, 1)
    k = int(np.floor(16 * 16 * 0.25 / 16))
    for b in range(3):
        assert out[b].sum() == k * 16  # all cells positive -> exactly k groups


# ---------- target selection ----------

@pytest.mark.slow
@pytest.mark.parametrize("family", ["vit", "resmlp"])
def test_attack_and_certify_on_transformer_families(family):
    """End-to-end smoke across model families (SURVEY §2 rows 4/21): the
    whole stack — two-stage DorPatch, failure sweep, PatchCleanser
    certification — is model-agnostic; the transformer/MLP victims trace
    through the same jitted programs the conv victims do (their conversion
    parity is covered in test_models; this pins the attack+defense path)."""
    from dorpatch_tpu.attack import DorPatch
    from dorpatch_tpu.config import AttackConfig, DefenseConfig
    from dorpatch_tpu.defense import build_defenses

    img = 32
    if family == "vit":
        from dorpatch_tpu.models.vit import ViT

        model = ViT(num_classes=5, patch_size=8, dim=32, depth=2,
                    num_heads=4, img_size=(img, img))
    else:
        from dorpatch_tpu.models.resmlp import ResMLP

        model = ResMLP(num_classes=5, patch_size=8, dim=48, depth=3,
                       img_size=img)
    params = jax.jit(model.init)(jax.random.PRNGKey(0),
                                 jnp.zeros((1, img, img, 3)))

    cfg = AttackConfig(sampling_size=4, max_iterations=2, sweep_interval=2,
                       switch_iteration=2, dropout=1, dropout_sizes=(0.06,),
                       basic_unit=4)
    attack = DorPatch(model.apply, params, 5, cfg)
    x = jax.random.uniform(jax.random.PRNGKey(1), (2, img, img, 3))
    result = attack.generate(x, key=jax.random.PRNGKey(2))
    assert result.adv_mask.shape == (2, img, img, 1)
    assert np.isfinite(np.asarray(result.adv_pattern)).all()

    d = build_defenses(model.apply, img,
                       DefenseConfig(ratios=(0.06,), num_mask_per_axis=2,
                                     chunk_size=8))[0]
    adv_x = x * (1.0 - result.adv_mask) + result.adv_pattern * result.adv_mask
    records = d.robust_predict(params, adv_x, 5)
    assert len(records) == 2
    assert all(0 <= r.prediction < 5 for r in records)


def test_majority_incorrect_label():
    y = jnp.asarray([3, 1])
    preds = jnp.asarray([
        [3, 3, 5, 5, 5, 2],   # misclassified: 5,5,5,2 -> mode 5
        [1, 1, 1, 1, 1, 1],   # none misclassified -> keep label
    ])
    out, has = majority_incorrect_label(preds, y, 8)
    assert np.asarray(out).tolist() == [5, 1]
    assert np.asarray(has).tolist() == [True, False]


def test_majority_incorrect_tie_takes_smallest():
    y = jnp.asarray([0])
    preds = jnp.asarray([[4, 4, 2, 2, 0, 0]])  # ties 4 vs 2 -> smallest (2)
    assert int(majority_incorrect_label(preds, y, 8)[0][0]) == 2


# ---------- sampling invariants ----------

def _tiny_attack(cfg=None, num_classes=4, **kw):
    def apply_fn(params, x):
        # cheap "model": class scores from pooled pixel stats
        s = x.mean(axis=(1, 2))  # [B,3]
        logits = jnp.stack(
            [s[:, 0], s[:, 1], s[:, 2], s.sum(-1) / 3.0], axis=-1)
        return logits * 10
    cfg = cfg or AttackConfig()
    kw.setdefault("remat", False)  # pass remat=None to follow cfg.remat
    return DorPatch(apply_fn, None, num_classes, cfg, **kw)


def test_sample_indices_static_and_biased():
    cfg = AttackConfig(sampling_size=8, failure_sampling_start=0)
    atk = _tiny_attack(cfg)
    failed = jnp.zeros(50, bool).at[jnp.asarray([4, 9, 11])].set(True)
    idx, from_fail = atk._sample_indices(jax.random.PRNGKey(0), failed, jnp.asarray(5))
    idx, from_fail = np.asarray(idx), np.asarray(from_fail)
    assert idx.shape == (8,)
    assert from_fail.sum() == 3  # min(n_failed=3, half=4)
    assert set(idx[from_fail]) <= {4, 9, 11}
    assert len(set(idx[from_fail])) == from_fail.sum()  # without replacement
    # universe part without replacement within itself
    uni = idx[~from_fail]
    assert len(set(uni)) == len(uni)


def test_sample_indices_before_start_ignores_failures():
    cfg = AttackConfig(sampling_size=8, failure_sampling_start=1000)
    atk = _tiny_attack(cfg)
    failed = jnp.ones(50, bool)
    _, from_fail = atk._sample_indices(jax.random.PRNGKey(1), failed, jnp.asarray(999))
    assert np.asarray(from_fail).sum() == 0


# ---------- mixed precision ----------

def test_bfloat16_compute_keeps_f32_carry():
    """bf16 EOT forward: carry state and metrics stay float32 and finite,
    and the first-step trajectory tracks the f32 path."""
    def run(dtype):
        cfg = AttackConfig(sampling_size=4, dropout=1, dropout_sizes=(0.06,),
                           basic_unit=4, compute_dtype=dtype)
        atk = _tiny_attack(cfg)
        from dorpatch_tpu import masks as masks_lib
        x = jax.random.uniform(jax.random.PRNGKey(11), (1, 16, 16, 3))
        universe = jnp.asarray(masks_lib.dropout_universe(16, 1, (0.06,)))
        state = atk._init_state(jax.random.PRNGKey(12), x,
                                jnp.zeros((1,), jnp.int32), False,
                                universe.shape[0])
        lv = jnp.zeros((1, 16, 16))
        return state, atk._get_block(1, 16, 2)(state, x, lv, universe)

    init16, s16 = run("bfloat16")
    init32, s32 = run("float32")
    assert s16.adv_pattern.dtype == jnp.float32
    assert s16.metrics.dtype == jnp.float32
    assert np.isfinite(np.asarray(s16.metrics)).all()
    # signed-grad updates: the *movement direction from the shared init*
    # must agree between precisions for nearly all pixels
    d16 = np.sign(np.asarray(s16.adv_pattern) - np.asarray(init16.adv_pattern))
    d32 = np.sign(np.asarray(s32.adv_pattern) - np.asarray(init32.adv_pattern))
    assert (d16 == d32).mean() > 0.9


def test_bad_compute_dtype_rejected():
    with pytest.raises(ValueError):
        _tiny_attack(AttackConfig(compute_dtype="float16"))


# ---------- end-to-end smoke attack ----------

@pytest.mark.slow
def test_generate_smoke():
    cfg = AttackConfig(
        sampling_size=8,
        max_iterations=30,
        sweep_interval=10,
        switch_iteration=10,
        failure_sampling_start=20,
        dropout=1,
        patch_budget=0.15,
        basic_unit=4,
        lr=0.05,
    )
    atk = _tiny_attack(cfg)
    x = jax.random.uniform(jax.random.PRNGKey(2), (2, 32, 32, 3)) * 0.2
    blocks = []
    atk.on_block_end = lambda stage, i, info: blocks.append((stage, i, info["n_failed"]))

    res = atk.generate(x, key=jax.random.PRNGKey(3))
    assert isinstance(res, AttackResult)
    assert res.adv_mask.shape == (2, 32, 32, 1)
    assert res.adv_pattern.shape == (2, 32, 32, 3)
    # stage-1 mask is the frozen hard selection
    vals = np.unique(np.asarray(res.adv_mask))
    assert set(vals) <= {0.0, 1.0}
    assert np.asarray(res.adv_pattern).min() >= 0.0
    assert np.asarray(res.adv_pattern).max() <= 1.0
    assert np.asarray(res.targeted).any()  # switch happened (switch_iteration=10 < 30)
    assert len(blocks) >= 2 and blocks[0][0] == 0 and blocks[-1][0] == 1
    assert all(np.isfinite(b[2]) for b in blocks)


@pytest.mark.slow
def test_generate_stage0_store_roundtrip(tmp_path):
    class Store:
        def __init__(self):
            self.saved = {}

        def load_stage0(self, batch_id):
            return self.saved.get(batch_id)

        def save_stage0(self, batch_id, mask, pattern):
            self.saved[batch_id] = (mask, pattern)

    cfg = AttackConfig(
        sampling_size=4, max_iterations=10, sweep_interval=5,
        switch_iteration=5, dropout=1, basic_unit=4, patch_budget=0.15,
    )
    atk = _tiny_attack(cfg)
    x = jax.random.uniform(jax.random.PRNGKey(4), (1, 32, 32, 3)) * 0.3
    store = Store()
    res1 = atk.generate(x, key=jax.random.PRNGKey(5), store=store, batch_id=0)
    assert 0 in store.saved
    # second run resumes stage 0 from the store
    res2 = atk.generate(x, key=jax.random.PRNGKey(5), store=store, batch_id=0)
    np.testing.assert_array_equal(
        np.asarray(res1.stage0_mask), np.asarray(res2.stage0_mask))


# ---------- compiled-program sharing (sweep de-recompile) ----------

@pytest.mark.slow
def test_adopt_compiled_matches_fresh_instance():
    """A DorPatch that adopts another's compiled programs (differing only in
    carry-initialized hyperparameters) must produce bit-identical results to
    a fresh instance — the sweep's zero-recompile contract."""
    def apply_fn(params, x):
        s = x.mean(axis=(1, 2))
        return jnp.stack([s[:, 0], s[:, 1], s[:, 2], s.sum(-1) / 3.0], -1) * 10

    base = dict(sampling_size=4, max_iterations=6, sweep_interval=3,
                switch_iteration=3, dropout=1, dropout_sizes=(0.06,),
                basic_unit=4)
    cfg_a = AttackConfig(patch_budget=0.15, density=0.0, structured=1e-3, **base)
    cfg_b = AttackConfig(patch_budget=0.3, density=5e-3, structured=2e-3, **base)

    proto = DorPatch(apply_fn, None, 4, cfg_a, remat=False)
    x = jax.random.uniform(jax.random.PRNGKey(6), (1, 16, 16, 3)) * 0.4
    proto.generate(x, key=jax.random.PRNGKey(7))
    n_programs = len(proto._programs)
    assert n_programs >= 2  # at least one block + the sweep

    adopted = DorPatch(apply_fn, None, 4, cfg_b, remat=False)
    adopted.adopt_compiled(proto)
    res_adopted = adopted.generate(x, key=jax.random.PRNGKey(7))
    # the b-config introduced no new programs: blocks/sweep all shared
    assert adopted._programs is proto._programs
    assert len(proto._programs) == n_programs

    fresh = DorPatch(apply_fn, None, 4, cfg_b, remat=False)
    res_fresh = fresh.generate(x, key=jax.random.PRNGKey(7))
    np.testing.assert_array_equal(
        np.asarray(res_adopted.adv_mask), np.asarray(res_fresh.adv_mask))
    np.testing.assert_allclose(
        np.asarray(res_adopted.adv_pattern), np.asarray(res_fresh.adv_pattern),
        atol=0)


def test_adopt_compiled_rejects_graph_relevant_mismatch():
    def apply_fn(params, x):
        s = x.mean(axis=(1, 2))
        return jnp.stack([s[:, 0], s[:, 1], s[:, 2], s.sum(-1) / 3.0], -1) * 10

    a = DorPatch(apply_fn, None, 4, AttackConfig(sampling_size=4), remat=False)
    b = DorPatch(apply_fn, None, 4, AttackConfig(sampling_size=8), remat=False)
    with pytest.raises(ValueError):
        b.adopt_compiled(a)
    # differing victim objects are rejected even with equal configs
    c = DorPatch(lambda p, x: apply_fn(p, x), None, 4,
                 AttackConfig(sampling_size=4), remat=False)
    with pytest.raises(ValueError):
        c.adopt_compiled(a)
    # remat mismatch is a different compiled graph
    d = DorPatch(apply_fn, None, 4, AttackConfig(sampling_size=4), remat=True)
    with pytest.raises(ValueError):
        d.adopt_compiled(a)


# ---------- dual occlusion layer (jax) ----------

@pytest.mark.slow
def test_generate_dual_smoke():
    """`dual=True` end-to-end: the second occlusion layer doubles the K axis
    of the sampled rectangle sets and the attack still optimizes/clips."""
    cfg = AttackConfig(
        sampling_size=4, max_iterations=6, sweep_interval=3,
        switch_iteration=3, dropout=1, dropout_sizes=(0.06,), basic_unit=4,
        patch_budget=0.15, dual=True,
    )
    atk = _tiny_attack(cfg)
    x = jax.random.uniform(jax.random.PRNGKey(21), (1, 16, 16, 3)) * 0.3
    res = atk.generate(x, key=jax.random.PRNGKey(22))
    assert res.adv_pattern.shape == (1, 16, 16, 3)
    assert np.asarray(res.adv_pattern).min() >= 0.0
    assert np.asarray(res.adv_pattern).max() <= 1.0
    assert set(np.unique(np.asarray(res.adv_mask))) <= {0.0, 1.0}


# ---------- remat policy ----------

def test_remat_policy_resolution():
    """`remat=None` follows config ("auto" keys off the masked-batch size);
    True/False force; bad config strings are rejected."""
    import dataclasses as dc

    def apply_fn(params, x):
        return x.mean(axis=(1, 2))

    cfg = AttackConfig(remat="auto", remat_threshold=16)
    atk = DorPatch(apply_fn, None, 3, cfg)           # remat=None -> config
    assert atk._grad_fwd(16) is atk._fwd             # at threshold: plain
    assert atk._grad_fwd(17) is not atk._fwd         # above: checkpointed

    on = DorPatch(apply_fn, None, 3, dc.replace(cfg, remat="on"))
    assert on._grad_fwd(1) is not on._fwd
    off = DorPatch(apply_fn, None, 3, dc.replace(cfg, remat="off"))
    assert off._grad_fwd(10**6) is off._fwd

    forced = DorPatch(apply_fn, None, 3, cfg, remat=False)
    assert forced._grad_fwd(10**6) is forced._fwd

    with pytest.raises(ValueError):
        DorPatch(apply_fn, None, 3, dc.replace(cfg, remat="maybe"))


def test_remat_on_off_same_results():
    """Remat changes scheduling, not math: one jitted step must produce the
    same state either way."""
    import dataclasses as dc
    from dorpatch_tpu import masks as masks_lib

    cfg = AttackConfig(sampling_size=4, dropout=1, dropout_sizes=(0.06,),
                       basic_unit=4)
    x = jax.random.uniform(jax.random.PRNGKey(0), (1, 16, 16, 3))
    universe = jnp.asarray(masks_lib.dropout_universe(16, 1, (0.06,)))
    lv = jnp.zeros((1, 16, 16))

    outs = []
    for mode in ("on", "off"):
        atk = _tiny_attack(dc.replace(cfg, remat=mode), remat=None)
        state = atk._init_state(jax.random.PRNGKey(1), x,
                                jnp.zeros((1,), jnp.int32), False,
                                universe.shape[0])
        outs.append(atk._get_block(1, 16, 2)(state, x, lv, universe))
    np.testing.assert_allclose(np.asarray(outs[0].adv_pattern),
                               np.asarray(outs[1].adv_pattern), atol=1e-6)


# ---------------- selective remat policy ----------------

def test_remat_policy_conv_grads_match_plain():
    """The "conv" remat policy (save `checkpoint_name("conv_out")` tags from
    StdConv, replay only the normalize chains) must be gradient-identical to
    the un-rematerialized forward."""
    from dorpatch_tpu.models.resnetv2 import ResNetV2

    model = ResNetV2(num_classes=5, layers=(1, 1), gn_impl="flax")
    x = jax.random.uniform(jax.random.PRNGKey(0), (2, 32, 32, 3))
    params = model.init(jax.random.PRNGKey(1), x)

    def loss(fn):
        return lambda x: jnp.sum(fn(x) ** 2)

    plain = jax.grad(loss(lambda x: model.apply(params, x)))(x)
    for policy in (None,
                   jax.checkpoint_policies.save_only_these_names("conv_out"),
                   jax.checkpoint_policies.dots_saveable):
        ck = (jax.checkpoint(lambda x: model.apply(params, x))
              if policy is None else
              jax.checkpoint(lambda x: model.apply(params, x), policy=policy))
        g = jax.grad(loss(ck))(x)
        np.testing.assert_allclose(np.asarray(g), np.asarray(plain),
                                   rtol=1e-5, atol=1e-5)


def test_remat_policy_validated():
    cfg = AttackConfig(sampling_size=4, remat_policy="bogus")
    apply_fn = lambda p, x: jnp.zeros((x.shape[0], 4))
    with pytest.raises(ValueError, match="remat_policy"):
        DorPatch(apply_fn, None, 4, cfg)


def test_grad_fwd_applies_policy():
    """_grad_fwd returns a checkpointed forward for each policy without
    tracing errors, and the policies produce identical step gradients."""
    from dorpatch_tpu.models.resnetv2 import ResNetV2

    model = ResNetV2(num_classes=4, layers=(1,), gn_impl="flax")
    xs = jax.random.uniform(jax.random.PRNGKey(2), (6, 16, 16, 3))
    params = model.init(jax.random.PRNGKey(3), xs)
    apply_fn = lambda p, x: model.apply(params, x)

    grads = []
    for policy in ("full", "conv", "dots"):
        cfg = AttackConfig(sampling_size=4, remat="on", remat_policy=policy)
        atk = DorPatch(apply_fn, None, 4, cfg)  # remat=None -> follow cfg
        fwd = atk._grad_fwd(n_masked=6)
        g = jax.grad(lambda x: jnp.sum(fwd(None, x) ** 2))(xs)
        grads.append(np.asarray(g))
    np.testing.assert_allclose(grads[1], grads[0], rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(grads[2], grads[0], rtol=1e-5, atol=1e-5)


def test_conv_policy_skips_conv_recompute_in_hlo():
    """Static proof of the "conv" policy's FLOP savings: the compiled
    grad graph under full remat re-runs the forward convolutions, while
    save_only_these_names("conv_out") keeps the conv count at the
    un-rematerialized graph's level (only the cheap chains are replayed)."""
    from dorpatch_tpu.models.resnetv2 import ResNetV2

    model = ResNetV2(num_classes=5, layers=(1, 1), gn_impl="flax")
    x = jnp.zeros((2, 32, 32, 3))
    params = model.init(jax.random.PRNGKey(0), x)
    fwd = lambda x: model.apply(params, x)

    def conv_count(fn):
        txt = jax.jit(jax.grad(
            lambda x: jnp.sum(fn(x) ** 2))).lower(x).compile().as_text()
        return txt.count(" convolution(")

    plain = conv_count(fwd)
    full = conv_count(jax.checkpoint(fwd))
    conv = conv_count(jax.checkpoint(
        fwd, policy=jax.checkpoint_policies.save_only_these_names("conv_out")))
    assert full > plain          # full remat recomputes forward convs
    assert conv == plain         # conv policy does not
