"""Test bootstrap: force a virtual 8-device CPU platform before jax imports.

This is the scale-free distributed-testing strategy from SURVEY.md §4: every
sharding/mesh test runs against 8 virtual CPU devices, no TPU required.
"""

import os

# Force-override: the environment may pin JAX_PLATFORMS to a TPU platform
# globally; tests always run on the virtual CPU mesh.
os.environ["JAX_PLATFORMS"] = "cpu"
_flags = [
    f
    for f in os.environ.get("XLA_FLAGS", "").split()
    if "xla_force_host_platform_device_count" not in f
]
_flags.append("--xla_force_host_platform_device_count=8")
os.environ["XLA_FLAGS"] = " ".join(_flags)

# A sitecustomize-registered accelerator plugin may programmatically set
# jax_platforms before this conftest runs, which beats the env var. Re-force
# CPU through the config API — this wins as long as no backend has been
# initialized yet (no jax.devices()/jit call has happened).
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

# The suite is compile-dominated on this one-core machine (~40 min cold):
# share the persistent XLA cache so re-runs only pay for changed programs.
# Tests that would be perturbed by caching (none known — keys are HLO-exact)
# can override with their own config.
from dorpatch_tpu.utils import enable_compilation_cache  # noqa: E402

enable_compilation_cache()
