"""Pipeline-layer tests: results-path contract, artifact store roundtrips,
metrics math, CLI parsing, and a tiny synthetic end-to-end experiment."""

import os

import numpy as np
import pytest

from dorpatch_tpu import metrics
from dorpatch_tpu.artifacts import ArtifactStore, results_path
from dorpatch_tpu.cli import build_parser, config_from_args
from dorpatch_tpu.config import AttackConfig, DefenseConfig, ExperimentConfig


def test_results_path_matches_reference_contract():
    cfg = ExperimentConfig(results_root="results")
    p = results_path(cfg)
    assert p == os.path.join(
        "results",
        "dataset=imagenet_base_arch=resnetv2_targeted=False_attack=DorPatch_"
        "dropout=2_density=0.001_structured=0.001",
        "num_patch=-1_patch_budget=0.12",
    )


def test_results_path_encodes_dual_and_defense_n_patch():
    """Cache-safety: knobs that change what cached artifacts MEAN (dual
    patches, 2-patch certification records) must separate the results
    tree — but only when non-default, keeping the reference byte-compat."""
    import dataclasses

    from dorpatch_tpu.config import AttackConfig, DefenseConfig

    base = ExperimentConfig(results_root="results")
    dual = dataclasses.replace(base, attack=AttackConfig(dual=True))
    np2 = dataclasses.replace(base, defense=DefenseConfig(n_patch=2))
    assert "dual=True" in results_path(dual)
    assert "defense_n_patch=2" in results_path(np2)
    assert "dual" not in results_path(base)
    assert "defense_n_patch" not in results_path(base)
    assert len({results_path(c) for c in (base, dual, np2)}) == 3


def test_artifact_store_roundtrip(tmp_path):
    store = ArtifactStore(str(tmp_path / "results" / "cfg" / "sub"))
    mask = np.random.default_rng(0).uniform(size=(1, 16, 16, 1)).astype(np.float32)
    pattern = np.random.default_rng(1).uniform(size=(1, 16, 16, 3)).astype(np.float32)

    assert store.load_patch(0) is None
    store.save_patch(0, mask, pattern)
    m2, p2 = store.load_patch(0)
    np.testing.assert_allclose(m2, mask)
    np.testing.assert_allclose(p2, pattern)

    # on-disk format is torch NCHW (reference interchange)
    import torch

    t = torch.load(str(tmp_path / "results" / "cfg" / "sub" / "adv_mask_0.pt"),
                   weights_only=True)
    assert tuple(t.shape) == (1, 1, 16, 16)

    # stage-0 artifacts live in the parent dir, shared across budgets
    assert store.load_stage0(3) is None
    store.save_stage0(3, mask, pattern)
    assert os.path.exists(str(tmp_path / "results" / "cfg" / "adv_mask_3.pt"))
    other = ArtifactStore(str(tmp_path / "results" / "cfg" / "other_budget"))
    assert other.load_stage0(3) is not None

    store.save_pc_records(0, [["rec"]])
    assert store.load_pc_records(0) == [["rec"]]


def test_metrics_math():
    class R:
        def __init__(self, p, c):
            self.predictions = np.asarray(p)
            self.certifications = np.asarray(c)

    y = np.asarray([0, 0, 1, 1])
    preds_clean = np.asarray([0, 0, 1, 0])
    preds_adv = np.asarray([1, 0, 0, 0])
    res = R([1, 0, 1, 0], [True, True, False, True])
    m = metrics.compute_metrics(preds_clean, y, preds_adv, [res])
    assert m["clean_accuracy"] == 75.0
    assert m["robust_accuracy"] == 25.0
    assert m["acc_pc"] == [50.0]                    # preds==y: idx1, idx2
    assert m["certified_acc_pc"] == [25.0]          # certified & correct: idx1
    assert m["certified_asr_pc"] == [50.0]          # certified & wrong: idx0, idx3
    mt = metrics.compute_metrics(
        preds_clean, y, preds_adv, [res], targets=np.asarray([1, 1, 0, 0]))
    assert mt["certified_asr_pc"] == [50.0]         # certified & ==target: idx0, idx3
    assert "clean accuracy: 75.00%" in metrics.report_line(m)


def test_cli_reference_flags():
    args = build_parser().parse_args(
        ["-d", "cifar10", "-ba", "resnetv2", "-t", "--patch_budget", "0.06",
         "-b", "4", "-e", "2.5", "--lr", "0.02", "--dropout", "1",
         "--synthetic", "--max-iterations", "7"])
    cfg = config_from_args(args)
    assert cfg.dataset == "cifar10"
    assert cfg.attack.targeted
    assert cfg.attack.patch_budget == 0.06
    assert cfg.batch_size == 4
    assert cfg.attack.eps == 2.5
    assert cfg.attack.lr == 0.02
    assert cfg.attack.dropout == 1
    assert cfg.synthetic_data
    assert cfg.attack.max_iterations == 7
    assert not cfg.attack.dual and cfg.defense.n_patch == 1  # defaults


def test_cli_dual_and_defense_n_patch_flags():
    """TPU extensions: --dual (the reference's dormant second-occlusion
    branch, live in both backends) and --defense-n-patch (2-patch
    PatchCleanser mask sets) must reach the config dataclasses."""
    args = build_parser().parse_args(
        ["--synthetic", "--dual", "--defense-n-patch", "2"])
    cfg = config_from_args(args)
    assert cfg.attack.dual
    assert cfg.defense.n_patch == 2


@pytest.mark.slow
def test_synthetic_e2e(tmp_path):
    """Tiny full experiment: synthetic cifar10, small victim, 2 batches."""
    from dorpatch_tpu.pipeline import run_experiment

    cfg = ExperimentConfig(
        dataset="cifar10",
        base_arch="resnet18",
        batch_size=2,
        num_batches=2,
        synthetic_data=True,
        img_size=32,
        results_root=str(tmp_path / "results"),
        attack=AttackConfig(
            sampling_size=6, max_iterations=10, sweep_interval=5,
            switch_iteration=5, dropout=1, basic_unit=4, patch_budget=0.15,
        ),
        defense=DefenseConfig(ratios=(0.06,), chunk_size=18),
    )
    m = run_experiment(cfg, verbose=False)
    assert set(m) >= {"clean_accuracy", "robust_accuracy", "acc_pc",
                      "certified_acc_pc", "certified_asr_pc", "report"}
    assert len(m["acc_pc"]) == 1
    # resume path: second run must reuse artifacts (no attack rerun) and give
    # identical metrics
    m2 = run_experiment(cfg, verbose=False)
    assert m2["report"] == m["report"]


def test_unknown_backend_rejected():
    from dorpatch_tpu.pipeline import run_experiment

    with pytest.raises(ValueError):
        run_experiment(ExperimentConfig(backend="mlx"))


@pytest.mark.slow
def test_targeted_cached_rerun_scores_same_targets(tmp_path):
    """Certified-ASR must be scored against the same targets whether the
    patch was just generated or loaded from cache. With a tiny budget the
    stage-0 patch rarely reaches the target, so the reference's re-derivation
    (prediction under the stage-0 patch, `main.py:108-118`) would disagree —
    the recorded-targets file keeps the two evaluations identical."""
    from dorpatch_tpu.pipeline import run_experiment

    cfg = ExperimentConfig(
        dataset="cifar10",
        base_arch="resnet18",
        batch_size=2,
        num_batches=1,
        synthetic_data=True,
        img_size=32,
        results_root=str(tmp_path / "results"),
        attack=AttackConfig(
            targeted=True, sampling_size=4, max_iterations=4,
            sweep_interval=2, switch_iteration=2, dropout=1, basic_unit=4,
            patch_budget=0.15,
        ),
        defense=DefenseConfig(ratios=(0.06,), chunk_size=18),
    )
    m = run_experiment(cfg, verbose=False)
    assert "targets" in m and len(m["targets"]) == m["evaluated_images"]
    m2 = run_experiment(cfg, verbose=False)  # cached patches + records
    assert m2["targets"] == m["targets"]
    assert m2["report"] == m["report"]


def test_data_source_resolution_and_cli_flag():
    from dorpatch_tpu.pipeline import resolved_data_source

    assert resolved_data_source(ExperimentConfig()) == "disk"
    assert resolved_data_source(ExperimentConfig(synthetic_data=True)) == "synthetic"
    assert resolved_data_source(
        ExperimentConfig(data_source="procedural")) == "procedural"
    with pytest.raises(ValueError):
        resolved_data_source(ExperimentConfig(data_source="tfds"))

    args = build_parser().parse_args(
        ["-d", "cifar10", "--data-source", "procedural"])
    assert config_from_args(args).data_source == "procedural"


@pytest.mark.slow
def test_procedural_e2e_uses_genuine_labels(tmp_path):
    """Procedural source: labels come from the generator (NOT the model's
    own predictions), so clean accuracy reflects the victim and the
    correctness filter has teeth — an untrained victim filters almost all
    images out (chance-level survivors)."""
    from dorpatch_tpu.pipeline import run_experiment

    cfg = ExperimentConfig(
        dataset="cifar10",
        base_arch="resnet18",
        batch_size=8,
        num_batches=2,
        data_source="procedural",
        img_size=32,
        results_root=str(tmp_path / "results"),
        attack=AttackConfig(
            sampling_size=4, max_iterations=4, sweep_interval=2,
            switch_iteration=2, dropout=1, basic_unit=4, patch_budget=0.15,
        ),
        defense=DefenseConfig(ratios=(0.06,), chunk_size=18),
    )
    m = run_experiment(cfg, verbose=False)
    # untrained victim on a 10-way task: most images fail the correctness
    # filter; whatever survives was genuinely classified correctly
    assert m["evaluated_images"] <= 8


def test_cli_iteration_schedule_flags():
    """The reference's hardcoded schedule constants (switch at 500, sweep
    every 100, failure-biased sampling from 1000) are CLI-tunable so
    reduced-budget protocol runs keep the schedule's *shape*."""
    args = build_parser().parse_args(
        ["-d", "cifar10", "--max-iterations", "300",
         "--switch-iteration", "150", "--sweep-interval", "75",
         "--failure-sampling-start", "150"])
    cfg = config_from_args(args)
    assert cfg.attack.switch_iteration == 150
    assert cfg.attack.sweep_interval == 75
    assert cfg.attack.failure_sampling_start == 150
    # defaults stay at the reference's constants
    dflt = config_from_args(build_parser().parse_args(["-d", "cifar10"]))
    assert dflt.attack.switch_iteration == 500
    assert dflt.attack.sweep_interval == 100
    assert dflt.attack.failure_sampling_start == 1000
