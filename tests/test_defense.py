"""PatchCleanser certifier tests: randomized decision-logic property tests
against an independent loop-based oracle, plus stub-model end-to-end coverage
of the certified / second-round-recovery / majority branches (SURVEY.md §4)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from dorpatch_tpu import masks as masks_lib
from dorpatch_tpu.config import DefenseConfig
from dorpatch_tpu.defense import (
    PatchCleanser,
    build_defenses,
    double_masking_verdict,
    masked_predictions,
)


# ---------- decision-logic property test ----------

def _oracle(p1, p2, m):
    """Loop-based double-masking decision (documented semantics, independent
    implementation: explicit per-image/per-mask loops)."""
    labels, counts = np.unique(p1, return_counts=True)
    majority = int(labels[np.argmax(counts)])  # smallest label on count ties
    if len(labels) == 1:
        return majority, bool(np.all(p2 == majority))
    pred = majority
    best_idx = -1
    for i in range(m):
        if p1[i] == majority:
            continue
        second = [
            p1[i] if j == i
            else p2[masks_lib.pair_index(m, min(i, j), max(i, j))]
            for j in range(m)
        ]
        if all(s == p1[i] for s in second):
            best_idx = i
    if best_idx >= 0:
        pred = int(p1[best_idx])
    return pred, False


def test_verdict_matches_oracle_randomized():
    m, nc = 6, 4
    p = m * (m - 1) // 2
    rng = np.random.default_rng(0)
    tables_1, tables_2 = [], []
    for _ in range(150):
        tables_1.append(rng.integers(0, nc, m))
        tables_2.append(rng.integers(0, nc, p))
    for _ in range(50):  # skew towards near-unanimity to hit round-1/recovery paths
        base = rng.integers(0, nc)
        t1 = np.full(m, base)
        if rng.random() < 0.7:
            t1[rng.integers(0, m)] = (base + 1) % nc
        t2 = np.full(p, base)
        flips = rng.integers(0, p, rng.integers(0, 3))
        t2[flips] = rng.integers(0, nc, len(flips))
        tables_1.append(t1)
        tables_2.append(t2)

    p1 = jnp.asarray(np.stack(tables_1))
    p2 = jnp.asarray(np.stack(tables_2))
    pred, cert = double_masking_verdict(p1, p2, m, nc)
    pred, cert = np.asarray(pred), np.asarray(cert)
    for b in range(p1.shape[0]):
        want_pred, want_cert = _oracle(np.asarray(p1[b]), np.asarray(p2[b]), m)
        assert pred[b] == want_pred, f"table {b}: {np.asarray(p1[b])}"
        assert cert[b] == want_cert, f"table {b}"


# ---------- masked_predictions scan ----------

def test_masked_predictions_matches_direct():
    spec = masks_lib.geometry(32, 0.12)
    singles, _ = masks_lib.mask_sets(spec)

    def apply_fn(params, x):
        # "class" = bucketized mean brightness -> depends on occlusion pattern
        s = x.mean(axis=(1, 2, 3))
        return jax.nn.one_hot((s * 7).astype(jnp.int32) % 5, 5)

    imgs = jax.random.uniform(jax.random.PRNGKey(0), (3, 32, 32, 3))
    got = np.asarray(masked_predictions(apply_fn, None, imgs, jnp.asarray(singles), chunk_size=7))
    # direct, unchunked
    all_m = masks_lib.rasterize(singles, 32)
    direct = apply_fn(None, masks_lib.apply_masks(imgs, all_m).reshape(-1, 32, 32, 3))
    want = np.asarray(jnp.argmax(direct, -1)).reshape(3, -1)
    np.testing.assert_array_equal(got, want)


def test_masked_predictions_chunk_bound_invariant():
    """`chunk_size` is an upper bound with equalized chunks (padding-free
    where possible); results must not depend on the bound chosen."""
    spec = masks_lib.geometry(32, 0.12)
    singles, doubles = masks_lib.mask_sets(spec)
    k = max(singles.shape[1], doubles.shape[1])
    rects = jnp.asarray(np.concatenate(
        [masks_lib.pad_rects(singles, k), masks_lib.pad_rects(doubles, k)]))

    def apply_fn(params, x):
        s = x.mean(axis=(1, 2, 3))
        return jax.nn.one_hot((s * 7).astype(jnp.int32) % 5, 5)

    imgs = jax.random.uniform(jax.random.PRNGKey(2), (2, 32, 32, 3))
    outs = [
        np.asarray(masked_predictions(apply_fn, None, imgs, rects, chunk_size=c))
        for c in (128, 111, rects.shape[0], 1)
    ]
    for o in outs[1:]:
        np.testing.assert_array_equal(outs[0], o)


def test_masked_predictions_empty_rects():
    """n=0 masks -> empty [B, 0] prediction table, no division by zero."""
    def apply_fn(params, x):
        return jnp.zeros((x.shape[0], 5))

    imgs = jnp.zeros((2, 32, 32, 3))
    rects = jnp.zeros((0, 1, 4), jnp.int32)
    got = masked_predictions(apply_fn, None, imgs, rects, chunk_size=8)
    assert got.shape == (2, 0)


def test_masked_predictions_mesh_chunk_rounding():
    """Under a multi-device mesh the equalized chunk must stay divisible by
    the mask-axis size (keeps the sharded Pallas fill on its fast path) and
    results must match the unmeshed run."""
    from dorpatch_tpu import parallel

    mesh = parallel.make_mesh(data=2, mask=4)
    spec = masks_lib.geometry(32, 0.12)
    singles, doubles = masks_lib.mask_sets(spec)
    k = max(singles.shape[1], doubles.shape[1])
    rects = jnp.asarray(np.concatenate(
        [masks_lib.pad_rects(singles, k), masks_lib.pad_rects(doubles, k)]))

    def apply_fn(params, x):
        s = x.mean(axis=(1, 2, 3))
        return jax.nn.one_hot((s * 7).astype(jnp.int32) % 5, 5)

    imgs = jax.random.uniform(jax.random.PRNGKey(3), (2, 32, 32, 3))
    want = np.asarray(masked_predictions(apply_fn, None, imgs, rects, 128))
    got = np.asarray(masked_predictions(
        apply_fn, None, imgs, rects, 128, mesh=mesh))
    np.testing.assert_array_equal(got, want)


def test_plan_chunks_invariants_randomized():
    """Property test of the chunk planner: coverage, the hard chunk_size
    bound, mesh divisibility whenever the bound admits it, and minimal
    padding (never worse than one quantum per chunk)."""
    from dorpatch_tpu.defense import plan_chunks

    rng = np.random.RandomState(7)
    cases = [(0, 8, 1), (666, 128, 1), (666, 128, 4), (2520, 128, 8),
             (8, 1, 4), (199, 100, 64), (1, 1, 1), (36, 7, 2)]
    cases += [(int(rng.randint(0, 4000)), int(rng.randint(1, 512)),
               int(rng.choice([1, 2, 4, 8, 64]))) for _ in range(300)]
    for n, cs, m in cases:
        n_chunks, chunk = plan_chunks(n, cs, m)
        assert chunk <= cs, (n, cs, m)                       # memory bound
        assert n_chunks * chunk >= n, (n, cs, m)             # coverage
        if n == 0:
            assert n_chunks == 0
            continue
        if cs >= m:
            assert chunk % m == 0, (n, cs, m)                # mesh fast path
        pad = n_chunks * chunk - n
        quantum = m if cs >= m else 1
        assert pad < quantum * n_chunks, (n, cs, m)          # near-minimal pad


# ---------- stub-model end-to-end ----------

@pytest.fixture(scope="module")
def stub_certifier():
    def apply_fn(params, x):
        # class 1 iff the 4x4 region at (20:24, 20:24) is fully visible & bright
        score = x[:, 20:24, 20:24, :].min(axis=(1, 2, 3))
        return jnp.stack([0.9 - score, score - 0.9], axis=-1)

    spec = masks_lib.geometry(64, 0.12)
    return PatchCleanser(apply_fn, spec), spec


def test_clean_image_certified(stub_certifier):
    pc, _ = stub_certifier
    imgs = jnp.full((2, 64, 64, 3), 0.2)
    records = pc.robust_predict(None, imgs, num_classes=2)
    for r in records:
        assert r.prediction == 0
        assert r.certification is True
        assert (r.preds_1 == 0).all() and (r.preds_2 == 0).all()


def test_patched_image_recovered_not_certified(stub_certifier):
    pc, spec = stub_certifier
    img = np.full((1, 64, 64, 3), 0.2, np.float32)
    img[:, 20:24, 20:24, :] = 1.0  # planted "patch" trigger
    records = pc.robust_predict(None, jnp.asarray(img), num_classes=2)
    r = records[0]
    # masks intersecting the trigger occlude it -> minority predicts 0;
    # most masks leave it visible -> majority predicts 1
    n_minority = int((r.preds_1 == 0).sum())
    assert 0 < n_minority < 18, n_minority
    # second-round recovery: occluded-trigger images predict 0 under every
    # second mask -> the defense recovers the true label 0, uncertified
    assert r.prediction == 0
    assert r.certification is False


def test_majority_wins_without_recovery():
    # preds crafted directly: disagreement, and the minority row is broken in
    # the second round -> majority stands
    m = 6
    p1 = np.full((1, m), 3)
    p1[0, 2] = 1  # minority at mask 2
    p2 = np.full((1, m * (m - 1) // 2), 3)
    # minority row 2 sees label 3 somewhere -> not unanimous
    pred, cert = double_masking_verdict(jnp.asarray(p1), jnp.asarray(p2), m, 5)
    assert int(pred[0]) == 3 and not bool(cert[0])


def test_build_defenses_bank():
    bank = build_defenses(lambda p, x: jnp.zeros((x.shape[0], 2)), 224)
    assert len(bank) == 4
    assert [d.spec.patch_ratio for d in bank] == list(DefenseConfig().ratios)
    assert [d.spec.mask_size for d in bank] == [27, 38, 54, 77]


def test_collect_aggregates(stub_certifier):
    pc, _ = stub_certifier
    imgs = jnp.full((3, 64, 64, 3), 0.2)
    records = pc.robust_predict(None, imgs, num_classes=2)
    pc.collect(records)
    assert pc.result.predictions.shape == (3,)
    assert pc.result.certifications.all()
    assert pc.result.predictions_1.shape == (3, 36)
    pc.reset()
    assert pc.result is None
