"""PatchCleanser certifier tests: randomized decision-logic property tests
against an independent loop-based oracle, stub-model end-to-end coverage
of the certified / second-round-recovery / majority branches (SURVEY.md §4),
and pruned-vs-exhaustive parity/forward-count/zero-recompile coverage of
the two-phase double-masking scheduler (`DefenseConfig.prune`)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from dorpatch_tpu import masks as masks_lib
from dorpatch_tpu.config import DefenseConfig
from dorpatch_tpu.defense import (
    UNEVALUATED,
    PatchCleanser,
    build_defenses,
    double_masking_verdict,
    masked_predictions,
)


# ---------- decision-logic property test ----------

def _oracle(p1, p2, m):
    """Loop-based double-masking decision (documented semantics, independent
    implementation: explicit per-image/per-mask loops)."""
    labels, counts = np.unique(p1, return_counts=True)
    majority = int(labels[np.argmax(counts)])  # smallest label on count ties
    if len(labels) == 1:
        return majority, bool(np.all(p2 == majority))
    pred = majority
    best_idx = -1
    for i in range(m):
        if p1[i] == majority:
            continue
        second = [
            p1[i] if j == i
            else p2[masks_lib.pair_index(m, min(i, j), max(i, j))]
            for j in range(m)
        ]
        if all(s == p1[i] for s in second):
            best_idx = i
    if best_idx >= 0:
        pred = int(p1[best_idx])
    return pred, False


def test_verdict_matches_oracle_randomized():
    m, nc = 6, 4
    p = m * (m - 1) // 2
    rng = np.random.default_rng(0)
    tables_1, tables_2 = [], []
    for _ in range(150):
        tables_1.append(rng.integers(0, nc, m))
        tables_2.append(rng.integers(0, nc, p))
    for _ in range(50):  # skew towards near-unanimity to hit round-1/recovery paths
        base = rng.integers(0, nc)
        t1 = np.full(m, base)
        if rng.random() < 0.7:
            t1[rng.integers(0, m)] = (base + 1) % nc
        t2 = np.full(p, base)
        flips = rng.integers(0, p, rng.integers(0, 3))
        t2[flips] = rng.integers(0, nc, len(flips))
        tables_1.append(t1)
        tables_2.append(t2)

    p1 = jnp.asarray(np.stack(tables_1))
    p2 = jnp.asarray(np.stack(tables_2))
    pred, cert = double_masking_verdict(p1, p2, m, nc)
    pred, cert = np.asarray(pred), np.asarray(cert)
    for b in range(p1.shape[0]):
        want_pred, want_cert = _oracle(np.asarray(p1[b]), np.asarray(p2[b]), m)
        assert pred[b] == want_pred, f"table {b}: {np.asarray(p1[b])}"
        assert cert[b] == want_cert, f"table {b}"


# ---------- masked_predictions scan ----------

def test_masked_predictions_matches_direct():
    spec = masks_lib.geometry(32, 0.12)
    singles, _ = masks_lib.mask_sets(spec)

    def apply_fn(params, x):
        # "class" = bucketized mean brightness -> depends on occlusion pattern
        s = x.mean(axis=(1, 2, 3))
        return jax.nn.one_hot((s * 7).astype(jnp.int32) % 5, 5)

    imgs = jax.random.uniform(jax.random.PRNGKey(0), (3, 32, 32, 3))
    got = np.asarray(masked_predictions(apply_fn, None, imgs, jnp.asarray(singles), chunk_size=7))
    # direct, unchunked
    all_m = masks_lib.rasterize(singles, 32)
    direct = apply_fn(None, masks_lib.apply_masks(imgs, all_m).reshape(-1, 32, 32, 3))
    want = np.asarray(jnp.argmax(direct, -1)).reshape(3, -1)
    np.testing.assert_array_equal(got, want)


def test_masked_predictions_chunk_bound_invariant():
    """`chunk_size` is an upper bound with equalized chunks (padding-free
    where possible); results must not depend on the bound chosen."""
    spec = masks_lib.geometry(32, 0.12)
    singles, doubles = masks_lib.mask_sets(spec)
    k = max(singles.shape[1], doubles.shape[1])
    rects = jnp.asarray(np.concatenate(
        [masks_lib.pad_rects(singles, k), masks_lib.pad_rects(doubles, k)]))

    def apply_fn(params, x):
        s = x.mean(axis=(1, 2, 3))
        return jax.nn.one_hot((s * 7).astype(jnp.int32) % 5, 5)

    imgs = jax.random.uniform(jax.random.PRNGKey(2), (2, 32, 32, 3))
    outs = [
        np.asarray(masked_predictions(apply_fn, None, imgs, rects, chunk_size=c))
        for c in (128, 111, rects.shape[0], 1)
    ]
    for o in outs[1:]:
        np.testing.assert_array_equal(outs[0], o)


def test_masked_predictions_empty_rects():
    """n=0 masks -> empty [B, 0] prediction table, no division by zero."""
    def apply_fn(params, x):
        return jnp.zeros((x.shape[0], 5))

    imgs = jnp.zeros((2, 32, 32, 3))
    rects = jnp.zeros((0, 1, 4), jnp.int32)
    got = masked_predictions(apply_fn, None, imgs, rects, chunk_size=8)
    assert got.shape == (2, 0)


def test_masked_predictions_mesh_chunk_rounding():
    """Under a multi-device mesh the equalized chunk must stay divisible by
    the mask-axis size (keeps the sharded Pallas fill on its fast path) and
    results must match the unmeshed run."""
    from dorpatch_tpu import parallel

    mesh = parallel.make_mesh(data=2, mask=4)
    spec = masks_lib.geometry(32, 0.12)
    singles, doubles = masks_lib.mask_sets(spec)
    k = max(singles.shape[1], doubles.shape[1])
    rects = jnp.asarray(np.concatenate(
        [masks_lib.pad_rects(singles, k), masks_lib.pad_rects(doubles, k)]))

    def apply_fn(params, x):
        s = x.mean(axis=(1, 2, 3))
        return jax.nn.one_hot((s * 7).astype(jnp.int32) % 5, 5)

    imgs = jax.random.uniform(jax.random.PRNGKey(3), (2, 32, 32, 3))
    want = np.asarray(masked_predictions(apply_fn, None, imgs, rects, 128))
    got = np.asarray(masked_predictions(
        apply_fn, None, imgs, rects, 128, mesh=mesh))
    np.testing.assert_array_equal(got, want)


def test_plan_chunks_invariants_randomized():
    """Property test of the chunk planner: coverage, the hard chunk_size
    bound, mesh divisibility whenever the bound admits it, and minimal
    padding (never worse than one quantum per chunk)."""
    from dorpatch_tpu.defense import plan_chunks

    rng = np.random.RandomState(7)
    cases = [(0, 8, 1), (666, 128, 1), (666, 128, 4), (2520, 128, 8),
             (8, 1, 4), (199, 100, 64), (1, 1, 1), (36, 7, 2)]
    cases += [(int(rng.randint(0, 4000)), int(rng.randint(1, 512)),
               int(rng.choice([1, 2, 4, 8, 64]))) for _ in range(300)]
    for n, cs, m in cases:
        n_chunks, chunk = plan_chunks(n, cs, m)
        assert chunk <= cs, (n, cs, m)                       # memory bound
        assert n_chunks * chunk >= n, (n, cs, m)             # coverage
        if n == 0:
            assert n_chunks == 0
            continue
        if cs >= m:
            assert chunk % m == 0, (n, cs, m)                # mesh fast path
        pad = n_chunks * chunk - n
        quantum = m if cs >= m else 1
        assert pad < quantum * n_chunks, (n, cs, m)          # near-minimal pad


# ---------- stub-model end-to-end ----------

@pytest.fixture(scope="module")
def stub_certifier():
    def apply_fn(params, x):
        # class 1 iff the 4x4 region at (20:24, 20:24) is fully visible & bright
        score = x[:, 20:24, 20:24, :].min(axis=(1, 2, 3))
        return jnp.stack([0.9 - score, score - 0.9], axis=-1)

    spec = masks_lib.geometry(64, 0.12)
    return PatchCleanser(apply_fn, spec), spec


def test_clean_image_certified(stub_certifier):
    pc, _ = stub_certifier
    imgs = jnp.full((2, 64, 64, 3), 0.2)
    records = pc.robust_predict(None, imgs, num_classes=2)
    for r in records:
        assert r.prediction == 0
        assert r.certification is True
        assert (r.preds_1 == 0).all() and (r.preds_2 == 0).all()


def test_patched_image_recovered_not_certified(stub_certifier):
    pc, spec = stub_certifier
    img = np.full((1, 64, 64, 3), 0.2, np.float32)
    img[:, 20:24, 20:24, :] = 1.0  # planted "patch" trigger
    records = pc.robust_predict(None, jnp.asarray(img), num_classes=2)
    r = records[0]
    # masks intersecting the trigger occlude it -> minority predicts 0;
    # most masks leave it visible -> majority predicts 1
    n_minority = int((r.preds_1 == 0).sum())
    assert 0 < n_minority < 18, n_minority
    # second-round recovery: occluded-trigger images predict 0 under every
    # second mask -> the defense recovers the true label 0, uncertified
    assert r.prediction == 0
    assert r.certification is False


def test_majority_wins_without_recovery():
    # preds crafted directly: disagreement, and the minority row is broken in
    # the second round -> majority stands
    m = 6
    p1 = np.full((1, m), 3)
    p1[0, 2] = 1  # minority at mask 2
    p2 = np.full((1, m * (m - 1) // 2), 3)
    # minority row 2 sees label 3 somewhere -> not unanimous
    pred, cert = double_masking_verdict(jnp.asarray(p1), jnp.asarray(p2), m, 5)
    assert int(pred[0]) == 3 and not bool(cert[0])


def test_build_defenses_bank():
    bank = build_defenses(lambda p, x: jnp.zeros((x.shape[0], 2)), 224)
    assert len(bank) == 4
    assert [d.spec.patch_ratio for d in bank] == list(DefenseConfig().ratios)
    assert [d.spec.mask_size for d in bank] == [27, 38, 54, 77]


def test_collect_aggregates(stub_certifier):
    pc, _ = stub_certifier
    imgs = jnp.full((3, 64, 64, 3), 0.2)
    records = pc.robust_predict(None, imgs, num_classes=2)
    pc.collect(records)
    assert pc.result.predictions.shape == (3,)
    assert pc.result.certifications.all()
    assert pc.result.predictions_1.shape == (3, 36)
    pc.reset()
    assert pc.result is None


# ---------- pruned two-phase scheduling (DefenseConfig.prune) ----------

PRUNE_IMG = 32
PRUNE_CLASSES = 3


def _trigger_stub(params, x):
    """Weightless 3-class trigger detector over the 36-mask family of
    `geometry(32, 0.1)` (stride 4, window 13): each 4x4 trigger sits at an
    offset where every mask either fully covers it or misses it, so the
    masked-prediction tables are exactly constructible. Priority encoding
    (bright beats dark) yields all four verdict classes — see
    `_prune_batch`."""
    t1 = x[:, 4:8, 4:8, :].mean(axis=(1, 2, 3)) > 0.8        # bright, NW
    t2 = x[:, 24:28, 24:28, :].mean(axis=(1, 2, 3)) > 0.8    # bright, SE
    t3 = x[:, 4:8, 24:28, :].mean(axis=(1, 2, 3)) < 0.2      # dark, NE
    cls = jnp.where(t1 | t2, 1, jnp.where(t3, 2, 0))
    return jax.nn.one_hot(cls, PRUNE_CLASSES)


def _prune_batch():
    """Four images, one per verdict class: [0] gray = unanimous certified;
    [1] one bright trigger = first-round disagreement recovered by the
    minority row; [2] two bright triggers no single mask can co-occlude =
    unanimous but a double mask kills the certificate; [3] bright + dark
    trigger = disagreement whose minority row is broken (majority stands)."""
    imgs = np.full((4, PRUNE_IMG, PRUNE_IMG, 3), 0.5, np.float32)
    imgs[1, 4:8, 4:8] = 1.0
    imgs[2, 4:8, 4:8] = 1.0
    imgs[2, 24:28, 24:28] = 1.0
    imgs[3, 4:8, 4:8] = 1.0
    imgs[3, 4:8, 24:28] = 0.0
    return jnp.asarray(imgs)


def _prune_pair(prune="exact"):
    spec = masks_lib.geometry(PRUNE_IMG, 0.1)
    oracle = PatchCleanser(_trigger_stub, spec,
                           DefenseConfig(ratios=(0.1,), prune="off"))
    pruned = PatchCleanser(_trigger_stub, spec,
                           DefenseConfig(ratios=(0.1,), prune=prune))
    return oracle, pruned


@pytest.mark.parametrize("bucket_sizes", [None, (1, 8)])
def test_pruned_parity_all_verdict_classes(bucket_sizes):
    """Pruned verdicts are bit-identical to the exhaustive oracle on a
    batch covering every verdict class, and every second-round entry the
    pruned path DID evaluate matches the exhaustive table."""
    oracle, pruned = _prune_pair()
    x = _prune_batch()
    want = oracle.robust_predict(None, x, PRUNE_CLASSES)
    got = pruned.robust_predict(None, x, PRUNE_CLASSES,
                                bucket_sizes=bucket_sizes)
    # the batch really covers all four classes
    assert [(w.certification,
             bool((w.preds_1 == w.preds_1[0]).all())) for w in want] == \
        [(True, True), (False, False), (False, True), (False, False)]
    assert want[1].prediction != want[3].prediction  # recovered vs majority
    for i, (w, g) in enumerate(zip(want, got)):
        assert (g.prediction, g.certification) == \
            (w.prediction, w.certification), f"image {i}"
        np.testing.assert_array_equal(g.preds_1, w.preds_1)
        evaluated = g.preds_2 != UNEVALUATED
        np.testing.assert_array_equal(g.preds_2[evaluated],
                                      w.preds_2[evaluated])


def test_pruned_forward_counts():
    """Forward accounting: the oracle always charges M + P; pruned
    unanimous images keep the full audit (bit-identical certificates),
    disagreeing images pay M + k*M for their k minority rows."""
    oracle, pruned = _prune_pair()
    x = _prune_batch()
    want = oracle.robust_predict(None, x, PRUNE_CLASSES)
    got = pruned.robust_predict(None, x, PRUNE_CLASSES, bucket_sizes=(1, 8))
    m, p = pruned.num_first, pruned.num_second
    assert all(w.forwards == m + p for w in want)
    for w, g in zip(want, got):
        k = int((w.preds_1 != np.bincount(
            w.preds_1, minlength=PRUNE_CLASSES).argmax()).sum())
        assert g.forwards == (m + p if k == 0 else m + k * m)
        assert g.forwards <= w.forwards


def test_pruned_consensus_unanimous_early_exit():
    """prune="consensus": first-round-unanimous images exit at EXACTLY the
    first-round forward count (36 for this family) with a consensus-only
    certificate; disagreeing images are untouched (same records as
    "exact")."""
    _, exact = _prune_pair("exact")
    _, consensus = _prune_pair("consensus")
    x = _prune_batch()
    we = exact.robust_predict(None, x, PRUNE_CLASSES)
    wc = consensus.robust_predict(None, x, PRUNE_CLASSES)
    m = consensus.num_first
    for i in (0, 2):  # the unanimous images
        assert wc[i].forwards == m
        assert wc[i].certification is True
        assert (wc[i].preds_2 == UNEVALUATED).all()
    # image 2's pair audit fails -> "consensus" certifies what "exact"
    # (and the oracle) refuse: the documented weaker-certificate trade
    assert we[2].certification is False
    for i in (1, 3):  # disagreeing images: identical to "exact"
        assert wc[i].forwards == we[i].forwards
        assert (wc[i].prediction, wc[i].certification) == \
            (we[i].prediction, we[i].certification)


def test_pruned_dense_disagreement_routes_to_pairs():
    """When an image's minority is so large that its rows would cost more
    than the pair table (k*M >= P), the scheduler routes it through the
    pair program: pruning never exceeds the exhaustive forward count."""
    def chaotic(params, x):
        s = x.mean(axis=(1, 2, 3))  # any occlusion flips the class
        return jax.nn.one_hot((s * 997).astype(jnp.int32) % PRUNE_CLASSES,
                              PRUNE_CLASSES)

    spec = masks_lib.geometry(PRUNE_IMG, 0.1)
    oracle = PatchCleanser(chaotic, spec,
                           DefenseConfig(ratios=(0.1,), prune="off"))
    pruned = PatchCleanser(chaotic, spec,
                           DefenseConfig(ratios=(0.1,), prune="exact"))
    rng = np.random.default_rng(5)
    x = jnp.asarray(rng.uniform(0, 1, (2, PRUNE_IMG, PRUNE_IMG, 3))
                    .astype(np.float32))
    want = oracle.robust_predict(None, x, PRUNE_CLASSES)
    got = pruned.robust_predict(None, x, PRUNE_CLASSES, bucket_sizes=(1, 4))
    m, p = pruned.num_first, pruned.num_second
    for w, g in zip(want, got):
        k = int((w.preds_1 != np.bincount(
            w.preds_1, minlength=PRUNE_CLASSES).argmax()).sum())
        assert k * m >= p, "fixture lost its dense disagreement"
        assert (g.prediction, g.certification) == \
            (w.prediction, w.certification)
        assert g.forwards == m + p  # routed through the pair program


def test_pruned_zero_recompile_ragged_sizes():
    """After `warm_pruned`, ragged batch sizes (and the ragged phase-2
    worklists they induce) share the per-bucket compiled programs: trace
    counts are identical before and after traffic, verified under the
    ARMED recompile watchdog (any over-budget retrace raises)."""
    from dorpatch_tpu.analysis.sanitize import Sanitizer

    spec = masks_lib.geometry(PRUNE_IMG, 0.1)
    buckets = (1, 4, 8)
    pc = PatchCleanser(_trigger_stub, spec,
                       DefenseConfig(ratios=(0.1,), prune="exact"),
                       recompile_budget=len(buckets))
    pc.warm_pruned(None, buckets)
    warm = pc.pruned_trace_counts()
    assert warm[f"defense.phase1.r{spec.patch_ratio}"] == len(buckets)
    assert warm[f"defense.rows.r{spec.patch_ratio}"] == \
        len(pc.row_bucket_sizes)
    base = _prune_batch()
    with Sanitizer(debug_nans=False, log_compiles=False):
        for n in (1, 2, 3, 4, 5, 8):
            idx = [i % 4 for i in range(n)]  # mixed verdict classes
            recs = pc.robust_predict(None, base[np.asarray(idx)],
                                     PRUNE_CLASSES, bucket_sizes=buckets)
            assert len(recs) == n
    assert pc.pruned_trace_counts() == warm


def test_resolved_prune_validates_and_forces_off():
    pc, _ = _prune_pair()
    assert pc.resolved_prune() == "off"
    assert pc.resolved_prune("exact") == "exact"
    with pytest.raises(ValueError):
        pc.resolved_prune("fast")
    # n_patch != 1 families have no pruned programs: always exhaustive
    multi = PatchCleanser(
        _trigger_stub, masks_lib.geometry(PRUNE_IMG, 0.1, n_patch=2),
        DefenseConfig(ratios=(0.1,), prune="exact"))
    assert multi.resolved_prune() == "off"
