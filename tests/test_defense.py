"""PatchCleanser certifier tests: randomized decision-logic property tests
against an independent loop-based oracle, stub-model end-to-end coverage
of the certified / second-round-recovery / majority branches (SURVEY.md §4),
and pruned-vs-exhaustive parity/forward-count/zero-recompile coverage of
the two-phase double-masking scheduler (`DefenseConfig.prune`)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from dorpatch_tpu import masks as masks_lib
from dorpatch_tpu.config import DefenseConfig
from dorpatch_tpu.defense import (
    UNEVALUATED,
    PatchCleanser,
    build_defenses,
    double_masking_verdict,
    masked_predictions,
)


# ---------- decision-logic property test ----------

def _oracle(p1, p2, m):
    """Loop-based double-masking decision (documented semantics, independent
    implementation: explicit per-image/per-mask loops)."""
    labels, counts = np.unique(p1, return_counts=True)
    majority = int(labels[np.argmax(counts)])  # smallest label on count ties
    if len(labels) == 1:
        return majority, bool(np.all(p2 == majority))
    pred = majority
    best_idx = -1
    for i in range(m):
        if p1[i] == majority:
            continue
        second = [
            p1[i] if j == i
            else p2[masks_lib.pair_index(m, min(i, j), max(i, j))]
            for j in range(m)
        ]
        if all(s == p1[i] for s in second):
            best_idx = i
    if best_idx >= 0:
        pred = int(p1[best_idx])
    return pred, False


def test_verdict_matches_oracle_randomized():
    m, nc = 6, 4
    p = m * (m - 1) // 2
    rng = np.random.default_rng(0)
    tables_1, tables_2 = [], []
    for _ in range(150):
        tables_1.append(rng.integers(0, nc, m))
        tables_2.append(rng.integers(0, nc, p))
    for _ in range(50):  # skew towards near-unanimity to hit round-1/recovery paths
        base = rng.integers(0, nc)
        t1 = np.full(m, base)
        if rng.random() < 0.7:
            t1[rng.integers(0, m)] = (base + 1) % nc
        t2 = np.full(p, base)
        flips = rng.integers(0, p, rng.integers(0, 3))
        t2[flips] = rng.integers(0, nc, len(flips))
        tables_1.append(t1)
        tables_2.append(t2)

    p1 = jnp.asarray(np.stack(tables_1))
    p2 = jnp.asarray(np.stack(tables_2))
    pred, cert = double_masking_verdict(p1, p2, m, nc)
    pred, cert = np.asarray(pred), np.asarray(cert)
    for b in range(p1.shape[0]):
        want_pred, want_cert = _oracle(np.asarray(p1[b]), np.asarray(p2[b]), m)
        assert pred[b] == want_pred, f"table {b}: {np.asarray(p1[b])}"
        assert cert[b] == want_cert, f"table {b}"


# ---------- masked_predictions scan ----------

def test_masked_predictions_matches_direct():
    spec = masks_lib.geometry(32, 0.12)
    singles, _ = masks_lib.mask_sets(spec)

    def apply_fn(params, x):
        # "class" = bucketized mean brightness -> depends on occlusion pattern
        s = x.mean(axis=(1, 2, 3))
        return jax.nn.one_hot((s * 7).astype(jnp.int32) % 5, 5)

    imgs = jax.random.uniform(jax.random.PRNGKey(0), (3, 32, 32, 3))
    got = np.asarray(masked_predictions(apply_fn, None, imgs, jnp.asarray(singles), chunk_size=7))
    # direct, unchunked
    all_m = masks_lib.rasterize(singles, 32)
    direct = apply_fn(None, masks_lib.apply_masks(imgs, all_m).reshape(-1, 32, 32, 3))
    want = np.asarray(jnp.argmax(direct, -1)).reshape(3, -1)
    np.testing.assert_array_equal(got, want)


def test_masked_predictions_chunk_bound_invariant():
    """`chunk_size` is an upper bound with equalized chunks (padding-free
    where possible); results must not depend on the bound chosen."""
    spec = masks_lib.geometry(32, 0.12)
    singles, doubles = masks_lib.mask_sets(spec)
    k = max(singles.shape[1], doubles.shape[1])
    rects = jnp.asarray(np.concatenate(
        [masks_lib.pad_rects(singles, k), masks_lib.pad_rects(doubles, k)]))

    def apply_fn(params, x):
        s = x.mean(axis=(1, 2, 3))
        return jax.nn.one_hot((s * 7).astype(jnp.int32) % 5, 5)

    imgs = jax.random.uniform(jax.random.PRNGKey(2), (2, 32, 32, 3))
    outs = [
        np.asarray(masked_predictions(apply_fn, None, imgs, rects, chunk_size=c))
        for c in (128, 111, rects.shape[0], 1)
    ]
    for o in outs[1:]:
        np.testing.assert_array_equal(outs[0], o)


def test_masked_predictions_empty_rects():
    """n=0 masks -> empty [B, 0] prediction table, no division by zero."""
    def apply_fn(params, x):
        return jnp.zeros((x.shape[0], 5))

    imgs = jnp.zeros((2, 32, 32, 3))
    rects = jnp.zeros((0, 1, 4), jnp.int32)
    got = masked_predictions(apply_fn, None, imgs, rects, chunk_size=8)
    assert got.shape == (2, 0)


def test_masked_predictions_mesh_chunk_rounding():
    """Under a multi-device mesh the equalized chunk must stay divisible by
    the mask-axis size (keeps the sharded Pallas fill on its fast path) and
    results must match the unmeshed run."""
    from dorpatch_tpu import parallel

    mesh = parallel.make_mesh(data=2, mask=4)
    spec = masks_lib.geometry(32, 0.12)
    singles, doubles = masks_lib.mask_sets(spec)
    k = max(singles.shape[1], doubles.shape[1])
    rects = jnp.asarray(np.concatenate(
        [masks_lib.pad_rects(singles, k), masks_lib.pad_rects(doubles, k)]))

    def apply_fn(params, x):
        s = x.mean(axis=(1, 2, 3))
        return jax.nn.one_hot((s * 7).astype(jnp.int32) % 5, 5)

    imgs = jax.random.uniform(jax.random.PRNGKey(3), (2, 32, 32, 3))
    want = np.asarray(masked_predictions(apply_fn, None, imgs, rects, 128))
    got = np.asarray(masked_predictions(
        apply_fn, None, imgs, rects, 128, mesh=mesh))
    np.testing.assert_array_equal(got, want)


def test_plan_chunks_invariants_randomized():
    """Property test of the chunk planner: coverage, the hard chunk_size
    bound, mesh divisibility whenever the bound admits it, and minimal
    padding (never worse than one quantum per chunk)."""
    from dorpatch_tpu.defense import plan_chunks

    rng = np.random.RandomState(7)
    cases = [(0, 8, 1), (666, 128, 1), (666, 128, 4), (2520, 128, 8),
             (8, 1, 4), (199, 100, 64), (1, 1, 1), (36, 7, 2)]
    cases += [(int(rng.randint(0, 4000)), int(rng.randint(1, 512)),
               int(rng.choice([1, 2, 4, 8, 64]))) for _ in range(300)]
    for n, cs, m in cases:
        n_chunks, chunk = plan_chunks(n, cs, m)
        assert chunk <= cs, (n, cs, m)                       # memory bound
        assert n_chunks * chunk >= n, (n, cs, m)             # coverage
        if n == 0:
            assert n_chunks == 0
            continue
        if cs >= m:
            assert chunk % m == 0, (n, cs, m)                # mesh fast path
        pad = n_chunks * chunk - n
        quantum = m if cs >= m else 1
        assert pad < quantum * n_chunks, (n, cs, m)          # near-minimal pad


# ---------- stub-model end-to-end ----------

@pytest.fixture(scope="module")
def stub_certifier():
    def apply_fn(params, x):
        # class 1 iff the 4x4 region at (20:24, 20:24) is fully visible & bright
        score = x[:, 20:24, 20:24, :].min(axis=(1, 2, 3))
        return jnp.stack([0.9 - score, score - 0.9], axis=-1)

    spec = masks_lib.geometry(64, 0.12)
    return PatchCleanser(apply_fn, spec), spec


def test_clean_image_certified(stub_certifier):
    pc, _ = stub_certifier
    imgs = jnp.full((2, 64, 64, 3), 0.2)
    records = pc.robust_predict(None, imgs, num_classes=2)
    for r in records:
        assert r.prediction == 0
        assert r.certification is True
        assert (r.preds_1 == 0).all() and (r.preds_2 == 0).all()


def test_patched_image_recovered_not_certified(stub_certifier):
    pc, spec = stub_certifier
    img = np.full((1, 64, 64, 3), 0.2, np.float32)
    img[:, 20:24, 20:24, :] = 1.0  # planted "patch" trigger
    records = pc.robust_predict(None, jnp.asarray(img), num_classes=2)
    r = records[0]
    # masks intersecting the trigger occlude it -> minority predicts 0;
    # most masks leave it visible -> majority predicts 1
    n_minority = int((r.preds_1 == 0).sum())
    assert 0 < n_minority < 18, n_minority
    # second-round recovery: occluded-trigger images predict 0 under every
    # second mask -> the defense recovers the true label 0, uncertified
    assert r.prediction == 0
    assert r.certification is False


def test_majority_wins_without_recovery():
    # preds crafted directly: disagreement, and the minority row is broken in
    # the second round -> majority stands
    m = 6
    p1 = np.full((1, m), 3)
    p1[0, 2] = 1  # minority at mask 2
    p2 = np.full((1, m * (m - 1) // 2), 3)
    # minority row 2 sees label 3 somewhere -> not unanimous
    pred, cert = double_masking_verdict(jnp.asarray(p1), jnp.asarray(p2), m, 5)
    assert int(pred[0]) == 3 and not bool(cert[0])


def test_build_defenses_bank():
    bank = build_defenses(lambda p, x: jnp.zeros((x.shape[0], 2)), 224)
    assert len(bank) == 4
    assert [d.spec.patch_ratio for d in bank] == list(DefenseConfig().ratios)
    assert [d.spec.mask_size for d in bank] == [27, 38, 54, 77]


def test_collect_aggregates(stub_certifier):
    pc, _ = stub_certifier
    imgs = jnp.full((3, 64, 64, 3), 0.2)
    records = pc.robust_predict(None, imgs, num_classes=2)
    pc.collect(records)
    assert pc.result.predictions.shape == (3,)
    assert pc.result.certifications.all()
    assert pc.result.predictions_1.shape == (3, 36)
    pc.reset()
    assert pc.result is None


# ---------- pruned two-phase scheduling (DefenseConfig.prune) ----------

PRUNE_IMG = 32
PRUNE_CLASSES = 3


def _trigger_stub(params, x):
    """Weightless 3-class trigger detector over the 36-mask family of
    `geometry(32, 0.1)` (stride 4, window 13): each 4x4 trigger sits at an
    offset where every mask either fully covers it or misses it, so the
    masked-prediction tables are exactly constructible. Priority encoding
    (bright beats dark) yields all four verdict classes — see
    `_prune_batch`."""
    t1 = x[:, 4:8, 4:8, :].mean(axis=(1, 2, 3)) > 0.8        # bright, NW
    t2 = x[:, 24:28, 24:28, :].mean(axis=(1, 2, 3)) > 0.8    # bright, SE
    t3 = x[:, 4:8, 24:28, :].mean(axis=(1, 2, 3)) < 0.2      # dark, NE
    cls = jnp.where(t1 | t2, 1, jnp.where(t3, 2, 0))
    return jax.nn.one_hot(cls, PRUNE_CLASSES)


def _prune_batch():
    """Four images, one per verdict class: [0] gray = unanimous certified;
    [1] one bright trigger = first-round disagreement recovered by the
    minority row; [2] two bright triggers no single mask can co-occlude =
    unanimous but a double mask kills the certificate; [3] bright + dark
    trigger = disagreement whose minority row is broken (majority stands)."""
    imgs = np.full((4, PRUNE_IMG, PRUNE_IMG, 3), 0.5, np.float32)
    imgs[1, 4:8, 4:8] = 1.0
    imgs[2, 4:8, 4:8] = 1.0
    imgs[2, 24:28, 24:28] = 1.0
    imgs[3, 4:8, 4:8] = 1.0
    imgs[3, 4:8, 24:28] = 0.0
    return jnp.asarray(imgs)


def _prune_pair(prune="exact"):
    spec = masks_lib.geometry(PRUNE_IMG, 0.1)
    oracle = PatchCleanser(_trigger_stub, spec,
                           DefenseConfig(ratios=(0.1,), prune="off"))
    pruned = PatchCleanser(_trigger_stub, spec,
                           DefenseConfig(ratios=(0.1,), prune=prune))
    return oracle, pruned


@pytest.mark.parametrize("bucket_sizes", [None, (1, 8)])
def test_pruned_parity_all_verdict_classes(bucket_sizes):
    """Pruned verdicts are bit-identical to the exhaustive oracle on a
    batch covering every verdict class, and every second-round entry the
    pruned path DID evaluate matches the exhaustive table."""
    oracle, pruned = _prune_pair()
    x = _prune_batch()
    want = oracle.robust_predict(None, x, PRUNE_CLASSES)
    got = pruned.robust_predict(None, x, PRUNE_CLASSES,
                                bucket_sizes=bucket_sizes)
    # the batch really covers all four classes
    assert [(w.certification,
             bool((w.preds_1 == w.preds_1[0]).all())) for w in want] == \
        [(True, True), (False, False), (False, True), (False, False)]
    assert want[1].prediction != want[3].prediction  # recovered vs majority
    for i, (w, g) in enumerate(zip(want, got)):
        assert (g.prediction, g.certification) == \
            (w.prediction, w.certification), f"image {i}"
        np.testing.assert_array_equal(g.preds_1, w.preds_1)
        evaluated = g.preds_2 != UNEVALUATED
        np.testing.assert_array_equal(g.preds_2[evaluated],
                                      w.preds_2[evaluated])


def test_pruned_forward_counts():
    """Forward accounting: the oracle always charges M + P; pruned
    unanimous images keep the full audit (bit-identical certificates),
    disagreeing images pay M + k*M for their k minority rows."""
    oracle, pruned = _prune_pair()
    x = _prune_batch()
    want = oracle.robust_predict(None, x, PRUNE_CLASSES)
    got = pruned.robust_predict(None, x, PRUNE_CLASSES, bucket_sizes=(1, 8))
    m, p = pruned.num_first, pruned.num_second
    assert all(w.forwards == m + p for w in want)
    for w, g in zip(want, got):
        k = int((w.preds_1 != np.bincount(
            w.preds_1, minlength=PRUNE_CLASSES).argmax()).sum())
        assert g.forwards == (m + p if k == 0 else m + k * m)
        assert g.forwards <= w.forwards


def test_pruned_consensus_unanimous_early_exit():
    """prune="consensus": first-round-unanimous images exit at EXACTLY the
    first-round forward count (36 for this family) with a consensus-only
    certificate; disagreeing images are untouched (same records as
    "exact")."""
    _, exact = _prune_pair("exact")
    _, consensus = _prune_pair("consensus")
    x = _prune_batch()
    we = exact.robust_predict(None, x, PRUNE_CLASSES)
    wc = consensus.robust_predict(None, x, PRUNE_CLASSES)
    m = consensus.num_first
    for i in (0, 2):  # the unanimous images
        assert wc[i].forwards == m
        assert wc[i].certification is True
        assert (wc[i].preds_2 == UNEVALUATED).all()
    # image 2's pair audit fails -> "consensus" certifies what "exact"
    # (and the oracle) refuse: the documented weaker-certificate trade
    assert we[2].certification is False
    for i in (1, 3):  # disagreeing images: identical to "exact"
        assert wc[i].forwards == we[i].forwards
        assert (wc[i].prediction, wc[i].certification) == \
            (we[i].prediction, we[i].certification)


def test_pruned_dense_disagreement_routes_to_pairs():
    """When an image's minority is so large that its rows would cost more
    than the pair table (k*M >= P), the scheduler routes it through the
    pair program: pruning never exceeds the exhaustive forward count."""
    def chaotic(params, x):
        s = x.mean(axis=(1, 2, 3))  # any occlusion flips the class
        return jax.nn.one_hot((s * 997).astype(jnp.int32) % PRUNE_CLASSES,
                              PRUNE_CLASSES)

    spec = masks_lib.geometry(PRUNE_IMG, 0.1)
    oracle = PatchCleanser(chaotic, spec,
                           DefenseConfig(ratios=(0.1,), prune="off"))
    pruned = PatchCleanser(chaotic, spec,
                           DefenseConfig(ratios=(0.1,), prune="exact"))
    rng = np.random.default_rng(5)
    x = jnp.asarray(rng.uniform(0, 1, (2, PRUNE_IMG, PRUNE_IMG, 3))
                    .astype(np.float32))
    want = oracle.robust_predict(None, x, PRUNE_CLASSES)
    got = pruned.robust_predict(None, x, PRUNE_CLASSES, bucket_sizes=(1, 4))
    m, p = pruned.num_first, pruned.num_second
    for w, g in zip(want, got):
        k = int((w.preds_1 != np.bincount(
            w.preds_1, minlength=PRUNE_CLASSES).argmax()).sum())
        assert k * m >= p, "fixture lost its dense disagreement"
        assert (g.prediction, g.certification) == \
            (w.prediction, w.certification)
        assert g.forwards == m + p  # routed through the pair program


def test_pruned_zero_recompile_ragged_sizes():
    """After `warm_pruned`, ragged batch sizes (and the ragged phase-2
    worklists they induce) share the per-bucket compiled programs: trace
    counts are identical before and after traffic, verified under the
    ARMED recompile watchdog (any over-budget retrace raises)."""
    from dorpatch_tpu.analysis.sanitize import Sanitizer

    spec = masks_lib.geometry(PRUNE_IMG, 0.1)
    buckets = (1, 4, 8)
    pc = PatchCleanser(_trigger_stub, spec,
                       DefenseConfig(ratios=(0.1,), prune="exact"),
                       recompile_budget=len(buckets))
    pc.warm_pruned(None, buckets)
    warm = pc.pruned_trace_counts()
    assert warm[f"defense.phase1.r{spec.patch_ratio}"] == len(buckets)
    assert warm[f"defense.rows.r{spec.patch_ratio}"] == \
        len(pc.row_bucket_sizes)
    base = _prune_batch()
    with Sanitizer(debug_nans=False, log_compiles=False):
        for n in (1, 2, 3, 4, 5, 8):
            idx = [i % 4 for i in range(n)]  # mixed verdict classes
            recs = pc.robust_predict(None, base[np.asarray(idx)],
                                     PRUNE_CLASSES, bucket_sizes=buckets)
            assert len(recs) == n
    assert pc.pruned_trace_counts() == warm


def test_resolved_prune_validates_and_forces_off():
    pc, _ = _prune_pair()
    assert pc.resolved_prune() == "off"
    assert pc.resolved_prune("exact") == "exact"
    with pytest.raises(ValueError):
        pc.resolved_prune("fast")
    # n_patch != 1 families have no pruned programs: always exhaustive
    multi = PatchCleanser(
        _trigger_stub, masks_lib.geometry(PRUNE_IMG, 0.1, n_patch=2),
        DefenseConfig(ratios=(0.1,), prune="exact"))
    assert multi.resolved_prune() == "off"


# ---------- bf16 certify bank (DefenseConfig.compute_dtype) ----------


def _dtype_pair(prune="exact", margin=0.5):
    """An f32 defense and its bf16 twin over the same mask family. The
    bf16 side sweeps at compute_dtype="bfloat16" and escalates any image
    whose evaluated margins land within `margin` of the argmax boundary
    through the f32 exhaustive program — the correctness law under test."""
    spec = masks_lib.geometry(PRUNE_IMG, 0.1)
    f32 = PatchCleanser(_trigger_stub, spec,
                        DefenseConfig(ratios=(0.1,), prune=prune))
    b16 = PatchCleanser(_trigger_stub, spec,
                        DefenseConfig(ratios=(0.1,), prune=prune,
                                      compute_dtype="bfloat16",
                                      incremental_margin=margin))
    return f32, b16


@pytest.mark.parametrize("prune", ["exact", "consensus"])
def test_bf16_verdict_parity_all_classes(prune):
    """Verdict parity across all four verdict classes, twice over: the
    margin=inf bank (EVERY image escalates, so the f32 exhaustive oracle
    decides everything — parity must be bit-exact by construction) and the
    default-margin bank (this fixture's one-hot margins sit at 1.0, far
    from the boundary, so bf16 decides unescalated and must still agree)."""
    x = _prune_batch()
    f32, _ = _dtype_pair(prune)
    want = f32.robust_predict(None, x, PRUNE_CLASSES)
    # the batch really covers all four classes (same check as the f32 test)
    assert [(w.certification,
             bool((w.preds_1 == w.preds_1[0]).all())) for w in want] == \
        ([(True, True), (False, False), (False, True), (False, False)]
         if prune == "exact" else
         [(True, True), (False, False), (True, True), (False, False)])
    for margin in (float("inf"), 0.5):
        _, b16 = _dtype_pair(prune, margin)
        got = b16.robust_predict(None, x, PRUNE_CLASSES)
        for i, (w, g) in enumerate(zip(want, got)):
            assert (g.prediction, g.certification) == \
                (w.prediction, w.certification), \
                f"image {i}, margin {margin}"
    # the inf bank really escalated every image
    assert b16.last_min_margin is not None


def test_bf16_escalation_margin_tracked():
    """The sweep records the per-image minimum margin that drives
    escalation: one-hot logits put every evaluated entry at margin 1.0."""
    _, b16 = _dtype_pair("exact", margin=float("inf"))
    b16.robust_predict(None, _prune_batch(), PRUNE_CLASSES)
    mm = np.asarray(b16.last_min_margin)
    assert mm.shape == (4,)
    np.testing.assert_allclose(mm, 1.0, atol=1e-3)


def test_bf16_zero_recompile_ragged_sizes():
    """The bf16 bank keeps the f32 bank's compile discipline: after
    `warm_pruned`, ragged batch sizes share the per-bucket programs with
    trace counts frozen, under the ARMED recompile watchdog. The warmed
    names carry the `.bf16` tag (DP300/DP301 track the banks separately)."""
    from dorpatch_tpu.analysis.sanitize import Sanitizer

    spec = masks_lib.geometry(PRUNE_IMG, 0.1)
    buckets = (1, 4, 8)
    pc = PatchCleanser(_trigger_stub, spec,
                       DefenseConfig(ratios=(0.1,), prune="exact",
                                     compute_dtype="bfloat16"),
                       recompile_budget=len(buckets))
    pc.warm_pruned(None, buckets, num_classes=PRUNE_CLASSES)
    warm = pc.pruned_trace_counts()
    assert warm[f"defense.phase1.bf16.r{spec.patch_ratio}"] == len(buckets)
    assert warm[f"defense.rows.bf16.r{spec.patch_ratio}"] == \
        len(pc.row_bucket_sizes)
    base = _prune_batch()
    with Sanitizer(debug_nans=False, log_compiles=False):
        for n in (1, 2, 3, 4, 5, 8):
            idx = [i % 4 for i in range(n)]  # mixed verdict classes
            recs = pc.robust_predict(None, base[np.asarray(idx)],
                                     PRUNE_CLASSES, bucket_sizes=buckets)
            assert len(recs) == n
    assert pc.pruned_trace_counts() == warm


def test_bf16_rejects_unknown_dtype():
    spec = masks_lib.geometry(PRUNE_IMG, 0.1)
    with pytest.raises(ValueError):
        PatchCleanser(_trigger_stub, spec,
                      DefenseConfig(ratios=(0.1,), compute_dtype="fp8"))


# ---------- mask-aware incremental forwards (DefenseConfig.incremental) ----------

INCR_IMG = 32
INCR_CLASSES = 3
INCR_AXIS = 3    # 3x3 family (M=9, P=36): the full machinery at unit-test cost


@pytest.fixture(scope="module")
def tiny_vit():
    """Small real ViT victim + its token engine (the incremental path only
    exists for real model families; stub apply_fns resolve to "off")."""
    from dorpatch_tpu.models.registry import incremental_engine
    from dorpatch_tpu.models.vit import ViT

    module = ViT(num_classes=INCR_CLASSES, patch_size=4, dim=32, depth=2,
                 num_heads=2, img_size=(INCR_IMG, INCR_IMG))
    params = module.init(jax.random.PRNGKey(7),
                         jnp.zeros((1, INCR_IMG, INCR_IMG, 3)))

    def apply_fn(p, x):
        return module.apply(p, (x - 0.5) / 0.5)

    return params, apply_fn, incremental_engine("cifar_vit", module, INCR_IMG)


@pytest.fixture(scope="module")
def tiny_conv():
    from dorpatch_tpu.models.registry import incremental_engine
    from dorpatch_tpu.models.small import CifarResNet18

    module = CifarResNet18(num_classes=INCR_CLASSES)
    params = module.init(jax.random.PRNGKey(8),
                         jnp.zeros((1, INCR_IMG, INCR_IMG, 3)))

    def apply_fn(p, x):
        return module.apply(p, (x - 0.5) / 0.5)

    return params, apply_fn, incremental_engine(
        "cifar_resnet18", module, INCR_IMG)


@pytest.fixture(scope="module")
def tiny_mixer():
    from dorpatch_tpu.models.registry import incremental_engine
    from dorpatch_tpu.models.resmlp import ResMLP

    module = ResMLP(num_classes=INCR_CLASSES, patch_size=4, dim=32,
                    depth=2, img_size=INCR_IMG)
    params = module.init(jax.random.PRNGKey(9),
                         jnp.zeros((1, INCR_IMG, INCR_IMG, 3)))

    def apply_fn(p, x):
        return module.apply(p, (x - 0.5) / 0.5)

    return params, apply_fn, incremental_engine("cifar_resmlp", module,
                                                INCR_IMG)


def _incr_pair(apply_fn, engine, ratio, incremental="auto",
               margin=0.5, num_axis=INCR_AXIS, recompile_budget=None):
    spec = masks_lib.geometry(INCR_IMG, ratio, num_mask_per_axis=num_axis)
    oracle = PatchCleanser(apply_fn, spec,
                           DefenseConfig(ratios=(ratio,), prune="off",
                                         num_mask_per_axis=num_axis))
    incr = PatchCleanser(
        apply_fn, spec,
        DefenseConfig(ratios=(ratio,), prune="exact",
                      num_mask_per_axis=num_axis,
                      incremental=incremental, incremental_margin=margin),
        incremental_engine=engine, recompile_budget=recompile_budget)
    return oracle, incr


def _incr_batch():
    rng = np.random.default_rng(11)
    imgs = rng.uniform(0, 1, (4, INCR_IMG, INCR_IMG, 3)).astype(np.float32)
    imgs[0] = 0.5            # gray: provably first-round unanimous
    imgs[1, :6, :6, :] = 1.0  # bright corner: disagreement inducer
    return jnp.asarray(imgs)


@pytest.mark.parametrize("ratio", [0.06, 0.15])  # default + non-default radius
def test_token_tables_parity_within_tolerance(tiny_vit, ratio):
    """The token-pruned tables agree with the exhaustive oracle's on the
    overwhelming majority of entries even on this random-init fixture (the
    documented worst case: drift is comparable to random-init logit
    margins; trained victims sit far above). n_patch=1 only — the pruned
    path's precondition."""
    params, apply_fn, engine = tiny_vit
    oracle, incr = _incr_pair(apply_fn, engine, ratio)
    x = _incr_batch()
    want = np.asarray(masked_predictions(
        apply_fn, params, x, oracle._rects, 64))
    fam = incr._incr_family
    p1, m1 = jax.jit(fam.phase1)(params, x)
    p2, m2 = jax.jit(fam.pairs)(params, x)
    got = np.concatenate([np.asarray(p1), np.asarray(p2)], axis=1)
    margins = np.concatenate([np.asarray(m1), np.asarray(m2)], axis=1)
    assert got.shape == want.shape
    agree = (got == want).mean()
    assert agree >= 0.85, f"entry agreement {agree:.3f} below tolerance"
    assert (margins > 0).all()
    # fractional forward-equivalents: strictly cheaper than full forwards,
    # first-round fraction matching the static token coverage
    assert 0 < fam.fe_first < incr.num_first
    assert 0 < fam.fe_pairs < incr.num_second


@pytest.mark.parametrize("ratio", [0.06, 0.15])
def test_token_exact_verdicts_bit_identical(tiny_vit, ratio):
    """"token-exact" with an infinite margin escalates every image through
    the exhaustive program: verdicts AND tables bit-identical to the
    oracle, with the incremental cost added on top of the full sweep in
    the accounting."""
    params, apply_fn, engine = tiny_vit
    oracle, incr = _incr_pair(apply_fn, engine, ratio,
                              incremental="token-exact",
                              margin=float("inf"))
    x = _incr_batch()
    want = oracle.robust_predict(params, x, INCR_CLASSES)
    got = incr.robust_predict(params, x, INCR_CLASSES, bucket_sizes=(1, 4))
    full = oracle.num_forwards_exhaustive
    for i, (w, g) in enumerate(zip(want, got)):
        assert (g.prediction, g.certification) == \
            (w.prediction, w.certification), f"image {i}"
        np.testing.assert_array_equal(g.preds_1, w.preds_1)
        np.testing.assert_array_equal(g.preds_2, w.preds_2)
        assert g.forwards > full       # incremental entries + the re-run
        assert g.forward_equivalents > full
        assert w.forward_equivalents == full


def test_token_fe_strictly_below_forwards(tiny_vit):
    """Plain "token": every record's forward_equivalents credits the
    dirty-token fraction — strictly below the evaluated entry count."""
    params, apply_fn, engine = tiny_vit
    _, incr = _incr_pair(apply_fn, engine, 0.1, incremental="token")
    assert incr.resolved_incremental() == "token"
    got = incr.robust_predict(params, _incr_batch(), INCR_CLASSES,
                              bucket_sizes=(1, 4))
    for g in got:
        assert 0 < g.forward_equivalents < g.forwards
    assert incr.first_round_forward_equivalents < incr.num_first


def test_mixer_exact_verdicts_bit_identical(tiny_mixer):
    """The ResMLP mixer engine rides the same margin-gated contract as the
    token engine: "mixer-exact" with an infinite margin escalates every
    image — verdicts AND tables bit-identical to the exhaustive oracle."""
    params, apply_fn, engine = tiny_mixer
    assert engine.kind == "mixer"
    oracle, incr = _incr_pair(apply_fn, engine, 0.1,
                              incremental="mixer-exact",
                              margin=float("inf"))
    assert incr.resolved_incremental() == "mixer-exact"
    assert incr.resolved_incremental("auto") == "mixer-exact"
    x = _incr_batch()
    want = oracle.robust_predict(params, x, INCR_CLASSES)
    got = incr.robust_predict(params, x, INCR_CLASSES, bucket_sizes=(1, 4))
    for i, (w, g) in enumerate(zip(want, got)):
        assert (g.prediction, g.certification) == \
            (w.prediction, w.certification), f"image {i}"
        np.testing.assert_array_equal(g.preds_1, w.preds_1)
        np.testing.assert_array_equal(g.preds_2, w.preds_2)


def test_mixer_fe_strictly_below_forwards(tiny_mixer):
    """Plain "mixer": forward_equivalents credits the dirty-row fraction
    of each evaluated entry — strictly below the entry count."""
    params, apply_fn, engine = tiny_mixer
    _, incr = _incr_pair(apply_fn, engine, 0.1, incremental="mixer")
    assert incr.resolved_incremental() == "mixer"
    got = incr.robust_predict(params, _incr_batch(), INCR_CLASSES,
                              bucket_sizes=(1, 4))
    for g in got:
        assert 0 < g.forward_equivalents < g.forwards
    assert incr.first_round_forward_equivalents < incr.num_first


@pytest.mark.parametrize("ratio", [0.06, 0.15])
def test_stem_fold_exact_parity(tiny_conv, ratio):
    """The conv masked-stem fold is algebraically exact: the folded
    first-round table equals apply_masks + full forward, and pruned
    verdicts stay bit-identical to the oracle. fe == forwards (the fold is
    conservatively credited full forwards — it saves stem recompute and
    masked-image HBM, not trunk FLOPs)."""
    params, apply_fn, engine = tiny_conv
    oracle, incr = _incr_pair(apply_fn, engine, ratio)
    assert incr.resolved_incremental() == "stem"
    x = _incr_batch()
    spec = masks_lib.geometry(INCR_IMG, ratio, num_mask_per_axis=INCR_AXIS)
    singles, _ = masks_lib.mask_sets(spec)
    want_p1 = np.asarray(masked_predictions(
        apply_fn, params, x, jnp.asarray(singles), 64))
    p1, _m = jax.jit(incr._incr_family.phase1)(params, x)
    np.testing.assert_array_equal(np.asarray(p1), want_p1)
    want = oracle.robust_predict(params, x, INCR_CLASSES)
    got = incr.robust_predict(params, x, INCR_CLASSES, bucket_sizes=(1, 4))
    for i, (w, g) in enumerate(zip(want, got)):
        assert (g.prediction, g.certification) == \
            (w.prediction, w.certification), f"image {i}"
        np.testing.assert_array_equal(g.preds_1, w.preds_1)
        ev = g.preds_2 != UNEVALUATED
        np.testing.assert_array_equal(g.preds_2[ev], w.preds_2[ev])
        assert g.forward_equivalents == g.forwards


@pytest.mark.parametrize("incremental", ["token", "token-exact"])
def test_incremental_zero_recompile_ragged(tiny_vit, incremental):
    """After `warm_pruned`, ragged batch sizes through the incremental
    programs (and token-exact's escalation program) share the per-bucket
    compiled traces — identical counts before and after traffic, under the
    ARMED recompile watchdog."""
    from dorpatch_tpu.analysis.sanitize import Sanitizer

    params, apply_fn, engine = tiny_vit
    buckets = (1, 2, 4)
    _, incr = _incr_pair(apply_fn, engine, 0.1, incremental=incremental,
                         margin=0.05, recompile_budget=len(buckets))
    incr.warm_pruned(params, buckets, num_classes=INCR_CLASSES)
    warm = incr.pruned_trace_counts()
    mode = incr.resolved_incremental()
    assert f"defense.phase1.token.r0.1" in warm
    if incremental == "token-exact":
        assert warm["defense.predict.r0.1"] == len(buckets)
    base = _incr_batch()
    with Sanitizer(debug_nans=False, log_compiles=False):
        for n in (1, 2, 3, 4):
            recs = incr.robust_predict(params, base[np.asarray(
                [i % 4 for i in range(n)])], INCR_CLASSES,
                bucket_sizes=buckets)
            assert len(recs) == n
    assert incr.pruned_trace_counts() == warm


def test_resolved_incremental_validation(tiny_vit, tiny_conv):
    params, apply_fn, engine = tiny_vit
    cparams, capply, cengine = tiny_conv
    oracle, incr = _incr_pair(apply_fn, engine, 0.1)
    # no engine -> off (every stub certifier in this file)
    assert oracle.resolved_incremental() == "off"
    # auto preserves the verdict contract: token families escalate
    assert incr.resolved_incremental() == "token-exact"
    assert incr.resolved_incremental("token") == "token"
    assert incr.resolved_incremental("token-exact") == "token-exact"
    # family mismatch is a config error, not a silent fallback
    with pytest.raises(ValueError):
        incr.resolved_incremental("stem")
    _, cincr = _incr_pair(capply, cengine, 0.1)
    with pytest.raises(ValueError):
        cincr.resolved_incremental("token")
    with pytest.raises(ValueError):
        incr.resolved_incremental("fast")
    # the incremental path rides the pruned schedule: prune=off kills it
    assert incr.resolved_incremental(prune="off") == "off"
    # config.incremental="off" builds no programs: explicit requests stay off
    spec = masks_lib.geometry(INCR_IMG, 0.1, num_mask_per_axis=INCR_AXIS)
    off = PatchCleanser(apply_fn, spec,
                        DefenseConfig(ratios=(0.1,), prune="exact",
                                      num_mask_per_axis=INCR_AXIS,
                                      incremental="off"),
                        incremental_engine=engine)
    assert off.resolved_incremental() == "off"
    assert off.resolved_incremental("token") == "off"
    # n_patch != 1 has no pruned programs at all -> off
    multi = PatchCleanser(
        apply_fn, masks_lib.geometry(INCR_IMG, 0.1, n_patch=2,
                                     num_mask_per_axis=INCR_AXIS),
        DefenseConfig(ratios=(0.1,), prune="exact", n_patch=2,
                      num_mask_per_axis=INCR_AXIS),
        incremental_engine=engine)
    assert multi.resolved_incremental() == "off"


# ---------- sharded pruned certification (the mesh fast path) ----------
#
# The meshed two-phase schedule (phase 1 sharded over the data axis, one
# host sync of the [B, 36] table, phase-2 worklists planned PER SHARD and
# dispatched as fixed [S * bucket] SPMD waves — defense._schedule_mesh)
# must be invisible in the verdicts: bit-identical records, bit-identical
# forwards accounting, to the single-chip pruned oracle, regardless of how
# the worklists skew across shards.

MESH_DATA, MESH_MASK = 4, 2


def _mesh_prune_pair(prune="exact", chunk_size=64):
    from dorpatch_tpu import parallel

    spec = masks_lib.geometry(PRUNE_IMG, 0.1)
    cfg = DefenseConfig(ratios=(0.1,), prune=prune, chunk_size=chunk_size)
    oracle = PatchCleanser(_trigger_stub, spec, cfg)
    mesh = parallel.make_mesh(MESH_DATA, MESH_MASK)
    sharded = parallel.make_sharded_defenses(
        _trigger_stub, PRUNE_IMG, mesh, cfg)[0]
    return oracle, sharded, mesh


def test_sharded_pruned_parity_all_verdict_classes():
    """Meshed pruned verdicts, tables, and forwards are bit-identical to
    the single-chip pruned oracle on the batch covering every verdict
    class (one image per data shard: the fully-sharded placement)."""
    from dorpatch_tpu import parallel

    oracle, sharded, mesh = _mesh_prune_pair()
    assert sharded.resolved_prune() == "exact"
    x = _prune_batch()
    want = oracle.robust_predict(None, x, PRUNE_CLASSES)
    got = sharded.robust_predict(
        None, parallel.place_batch_auto(mesh, x), PRUNE_CLASSES)
    # the batch really covers all four classes (same check as the
    # single-chip parity test: the fixture must not silently degrade)
    assert [(w.certification,
             bool((w.preds_1 == w.preds_1[0]).all())) for w in want] == \
        [(True, True), (False, False), (False, True), (False, False)]
    for i, (w, g) in enumerate(zip(want, got)):
        assert (g.prediction, g.certification) == \
            (w.prediction, w.certification), f"image {i}"
        np.testing.assert_array_equal(g.preds_1, w.preds_1)
        np.testing.assert_array_equal(g.preds_2, w.preds_2)
        assert g.forwards == w.forwards, f"image {i}"
        assert g.forward_equivalents == w.forward_equivalents


def test_sharded_pruned_forward_counts_law():
    """The forwards law survives sharding: unanimous images keep the full
    M + P audit, disagreeing images pay M + k*M for their k minority rows
    — counted per image, independent of which shard ran the rows."""
    from dorpatch_tpu import parallel

    oracle, sharded, mesh = _mesh_prune_pair()
    x = _prune_batch()
    want = oracle.robust_predict(None, x, PRUNE_CLASSES)
    got = sharded.robust_predict(
        None, parallel.place_batch_auto(mesh, x), PRUNE_CLASSES)
    m, p = sharded.num_first, sharded.num_second
    for w, g in zip(want, got):
        k = int((w.preds_1 != np.bincount(
            w.preds_1, minlength=PRUNE_CLASSES).argmax()).sum())
        assert g.forwards == (m + p if k == 0 else m + k * m)
        assert g.forwards == w.forwards


def test_sharded_pruned_skewed_worklists():
    """Worklist skew is a placement detail, not a semantics one. Data-axis
    ownership is contiguous (np.array_split), so a batch whose only
    disagreeing images are 0..1 puts EVERY phase-2 row on shard 0 while
    shards 1..3 plan empty worklists (consensus mode: unanimous images
    have no phase 2 at all) — verdicts and forwards must still match the
    single-chip consensus oracle. A 2-image batch (n < S: trailing shards
    own zero images, placement falls back to replicated rows) must too."""
    from dorpatch_tpu import parallel

    oracle, sharded, mesh = _mesh_prune_pair("consensus")
    base = np.asarray(_prune_batch())
    # images 0,1 = the disagreement classes (recovered / broken-minority);
    # images 2..7 gray -> consensus-certified with zero phase-2 entries
    skew = np.full((8, PRUNE_IMG, PRUNE_IMG, 3), 0.5, np.float32)
    skew[0], skew[1] = base[1], base[3]
    x = jnp.asarray(skew)
    want = oracle.robust_predict(None, x, PRUNE_CLASSES)
    # the fixture really is skewed: all disagreement lives on shard 0
    disagree = [i for i, w in enumerate(want)
                if not (w.preds_1 == w.preds_1[0]).all()]
    assert disagree == [0, 1]
    got = sharded.robust_predict(
        None, parallel.place_batch_auto(mesh, x), PRUNE_CLASSES)
    m = sharded.num_first
    for i, (w, g) in enumerate(zip(want, got)):
        assert (g.prediction, g.certification) == \
            (w.prediction, w.certification), f"image {i}"
        np.testing.assert_array_equal(g.preds_2, w.preds_2)
        assert g.forwards == w.forwards, f"image {i}"
        if i >= 2:
            assert g.forwards == m  # consensus early exit, no phase 2

    # n=2 < S=4: shards 2,3 own no images at all
    small = jnp.asarray(skew[:2])
    want2 = oracle.robust_predict(None, small, PRUNE_CLASSES)
    got2 = sharded.robust_predict(
        None, parallel.place_batch_auto(mesh, small), PRUNE_CLASSES)
    for w, g in zip(want2, got2):
        assert (g.prediction, g.certification) == \
            (w.prediction, w.certification)
        assert g.forwards == w.forwards


def test_sharded_pruned_zero_recompile_ragged_worklists():
    """After `warm_pruned` on the mesh, traffic at the warmed batch
    buckets with arbitrary verdict-class mixes (and thus ragged per-shard
    phase-2 worklists) shares the per-rung compiled programs: trace counts
    identical before and after, under the ARMED recompile watchdog."""
    from dorpatch_tpu import parallel
    from dorpatch_tpu.analysis.sanitize import Sanitizer

    spec = masks_lib.geometry(PRUNE_IMG, 0.1)
    buckets = (4, 8)
    mesh = parallel.make_mesh(MESH_DATA, MESH_MASK)
    pc = parallel.make_sharded_defenses(
        _trigger_stub, PRUNE_IMG, mesh,
        DefenseConfig(ratios=(0.1,), prune="exact", chunk_size=8),
        recompile_budget=len(buckets))[0]
    pc.warm_pruned(None, buckets)
    warm = pc.pruned_trace_counts()
    r = spec.patch_ratio
    assert warm[f"defense.phase1.mesh.r{r}"] == len(buckets)
    # meshes dispatch the pair audit at wave shapes over the row ladder
    assert warm[f"defense.rows.mesh.r{r}"] == len(pc.row_bucket_sizes)
    assert warm[f"defense.pairs.mesh.r{r}"] == len(pc.row_bucket_sizes)
    base = np.asarray(_prune_batch())
    with Sanitizer(debug_nans=False, log_compiles=False):
        for n, shift in ((4, 0), (8, 1), (4, 2), (8, 3)):
            idx = np.asarray([(i + shift) % 4 for i in range(n)])
            recs = pc.robust_predict(
                None, parallel.place_batch_auto(mesh, jnp.asarray(base[idx])),
                PRUNE_CLASSES)
            assert len(recs) == n
    assert pc.pruned_trace_counts() == warm


@pytest.mark.parametrize("family", ["vit", "conv"])
def test_sharded_incremental_parity(tiny_vit, tiny_conv, family):
    """Incremental engines ride the shard-local schedule: the meshed
    token-pruned ViT (token-exact) and stem-folded conv certifiers are
    record-for-record identical — verdicts, forwards, and fractional
    forward-equivalents — to their single-chip incremental counterparts."""
    from dorpatch_tpu import parallel

    params, apply_fn, engine = tiny_vit if family == "vit" else tiny_conv
    mode = "token-exact" if family == "vit" else "stem"
    _, incr = _incr_pair(apply_fn, engine, 0.1)
    mesh = parallel.make_mesh(MESH_DATA, MESH_MASK)
    spec = masks_lib.geometry(INCR_IMG, 0.1, num_mask_per_axis=INCR_AXIS)
    sh = PatchCleanser(
        parallel.shard_apply_fn(apply_fn, mesh), spec,
        DefenseConfig(ratios=(0.1,), prune="exact",
                      num_mask_per_axis=INCR_AXIS),
        mesh=mesh, incremental_engine=engine)
    assert sh.resolved_incremental() == mode
    x = _incr_batch()
    want = incr.robust_predict(params, x, INCR_CLASSES, bucket_sizes=(1, 4))
    got = sh.robust_predict(params, parallel.place_batch_auto(mesh, x),
                            INCR_CLASSES)
    for i, (w, g) in enumerate(zip(want, got)):
        assert (g.prediction, g.certification) == \
            (w.prediction, w.certification), f"image {i}"
        np.testing.assert_array_equal(g.preds_1, w.preds_1)
        assert g.forwards == w.forwards, f"image {i}"
        assert abs(g.forward_equivalents - w.forward_equivalents) < 1e-9


def test_sharded_prune_downgrade_event_once(tmp_path):
    """n_patch != 1 families still have no pruned programs on the mesh:
    resolved_prune downgrades to "off" and emits the
    `defense.prune_downgrade` observe event exactly once per certifier."""
    import json

    from dorpatch_tpu import observe, parallel

    mesh = parallel.make_mesh(MESH_DATA, MESH_MASK)
    spec = masks_lib.geometry(PRUNE_IMG, 0.1, n_patch=2)
    pc = PatchCleanser(
        parallel.shard_apply_fn(_trigger_stub, mesh), spec,
        DefenseConfig(ratios=(0.1,), prune="exact", n_patch=2), mesh=mesh)
    log = tmp_path / "events.jsonl"
    with observe.EventLog(str(log), run_id="downgrade-test") as el, \
            observe.active(el):
        assert pc.resolved_prune() == "off"
        assert pc.resolved_prune() == "off"  # second resolve: no re-emit
    events = [json.loads(l) for l in log.read_text().splitlines()]
    hits = [e for e in events
            if e.get("name") == "defense.prune_downgrade"]
    assert len(hits) == 1
