"""The typed metric registry (observe/metrics.py): percentile parity with
the shared nearest-rank helper, concurrent-increment exactness, atomic
snapshot dumps under fault, exposition round-trips, and trace-id
propagation through a failover re-dispatch.
"""

import json
import os
import threading
import time

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from dorpatch_tpu.config import DefenseConfig, ServeConfig
from dorpatch_tpu.observe import (
    MetricRegistry,
    labeled_values,
    nearest_rank_percentile,
    parse_exposition,
)
from dorpatch_tpu.observe import report as report_mod
from dorpatch_tpu.serve.service import CertifiedInferenceService
from dorpatch_tpu.serve.types import PredictResult

IMG = 32
N_CLASSES = 5


def stub_apply(params, x):
    s = x.mean(axis=(1, 2, 3))
    return jax.nn.one_hot((s * 7).astype(jnp.int32) % N_CLASSES, N_CLASSES)


# ---------- registry surface ----------

def test_counter_gauge_histogram_basics():
    m = MetricRegistry()
    c = m.counter("req_total", help="requests")
    c.inc(status="ok")
    c.inc(2, status="ok")
    c.inc(status="error")
    assert m.value("req_total", status="ok") == 3
    assert m.value("req_total", status="error") == 1
    assert c.total() == 4
    with pytest.raises(ValueError):
        c.inc(-1, status="ok")

    g = m.gauge("depth")
    g.set(7)
    assert m.value("depth") == 7
    g.set_function(lambda: 13, kind="computed")
    assert m.value("depth", kind="computed") == 13

    h = m.histogram("lat_ms")
    h.observe(3.0)
    assert m.value("lat_ms") == 1  # histograms read as their count


def test_registry_names_are_typed_and_idempotent():
    m = MetricRegistry()
    c = m.counter("x_total")
    assert m.counter("x_total") is c
    with pytest.raises(TypeError):
        m.gauge("x_total")


def test_histogram_percentile_matches_nearest_rank_on_random_data():
    """The satellite contract: registry percentiles and every other
    surface (/stats, loadgen, report CLI) answer from the SAME
    nearest-rank formula — no interpolation drift."""
    rng = np.random.default_rng(42)
    m = MetricRegistry()
    h = m.histogram("lat_ms")
    samples = rng.lognormal(mean=3.0, sigma=1.2, size=1000).tolist()
    for v in samples:
        h.observe(v, path="predict")
    for q in (0.0, 0.25, 0.5, 0.9, 0.95, 0.99, 1.0):
        want = nearest_rank_percentile(sorted(samples), q)
        got = m.percentile("lat_ms", q, path="predict")
        assert got == pytest.approx(want), q
    assert m.percentile("lat_ms", 0.5, path="missing") is None
    assert m.percentile("nope", 0.5) is None


def test_concurrent_increment_exactness_8_threads():
    """8 threads x 10k increments land exactly — the one-lock-per-registry
    contract (a lost update here forks the fleet's books)."""
    m = MetricRegistry()
    c = m.counter("hits_total")
    h = m.histogram("obs_ms", buckets=(1.0, 10.0))
    n_threads, n_iter = 8, 10_000
    start = threading.Barrier(n_threads)

    def worker(tid):
        start.wait()
        for i in range(n_iter):
            c.inc(thread=str(tid % 2))
            h.observe(float(i % 7))

    threads = [threading.Thread(target=worker, args=(t,))
               for t in range(n_threads)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert c.total() == n_threads * n_iter
    assert m.value("hits_total", thread="0") == n_threads * n_iter / 2
    assert m.value("obs_ms") == n_threads * n_iter


def test_snapshot_dump_atomic_under_write_fault(tmp_path, monkeypatch):
    """ENOSPC-style failure mid-dump: dump() returns False, never raises,
    and the PREVIOUS snapshot file survives intact (tmp + os.replace)."""
    m = MetricRegistry()
    m.counter("a_total").inc(5)
    path = str(tmp_path / "metrics.json")
    assert m.dump(path) is True
    first = json.load(open(path))

    m.counter("a_total").inc(1)

    def enospc(fd):
        raise OSError(28, "No space left on device")

    monkeypatch.setattr("dorpatch_tpu.observe.metrics.os.fsync", enospc)
    assert m.dump(path) is False  # degraded, not raised
    monkeypatch.undo()
    assert json.load(open(path)) == first  # prior snapshot intact
    assert not [f for f in os.listdir(tmp_path) if ".tmp." in f]

    assert m.dump(path) is True  # recovery on the next healthy dump
    assert labeled_values(json.load(open(path)), "a_total", "") == {}
    assert json.load(open(path))["metrics"]["a_total"]["series"][0]["value"] \
        == 6


def test_render_text_parse_exposition_round_trip():
    m = MetricRegistry()
    m.counter("req_total", help="requests").inc(3, status="ok")
    m.counter("req_total").inc(status="o\"dd,\nlabel")
    m.gauge("depth").set(2.5)
    m.histogram("lat_ms", buckets=(1.0, 10.0)).observe(0.5)
    text = m.render_text()
    assert "# TYPE req_total counter" in text
    parsed = parse_exposition(text)
    assert parsed["req_total"][(("status", "ok"),)] == 3
    assert parsed["req_total"][(("status", 'o"dd,\nlabel'),)] == 1
    assert parsed["depth"][()] == 2.5
    assert parsed["lat_ms_count"][()] == 1


# ---------- trace-id propagation through failover ----------

def test_trace_ids_survive_failover_redispatch(tmp_path):
    """Chaos wedges replica 0 mid-batch: the re-dispatched requests'
    trace ids must still reach a terminal record — the fleet report joins
    serve.admit markers to downstream telemetry with ZERO orphans, and
    the registry's books still reconcile exactly-once."""
    rd = str(tmp_path / "serve")
    svc = CertifiedInferenceService(
        stub_apply, None, num_classes=N_CLASSES, img_size=IMG,
        serve_cfg=ServeConfig(max_batch=2, bucket_sizes=(1, 2),
                              deadline_ms=10000.0, max_queue_depth=64,
                              replicas=2, max_restarts=2,
                              restart_backoff_base=0.2,
                              restart_backoff_cap=1.0,
                              replica_stale_s=0.4, chaos="wedge_dispatch"),
        defense_cfg=DefenseConfig(ratios=(0.1,), chunk_size=64),
        result_dir=rd)
    rng = np.random.default_rng(9)
    images = rng.uniform(0.0, 1.0, (6, IMG, IMG, 3)).astype(np.float32)
    tids = [f"trace{i:04d}" for i in range(len(images))]
    results = [None] * len(images)
    with svc:
        def fire(i):
            results[i] = svc.predict(images[i], deadline_ms=10000.0,
                                     trace_id=tids[i])

        threads = [threading.Thread(target=fire, args=(i,))
                   for i in range(len(images))]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert all(isinstance(r, PredictResult) for r in results), \
            [getattr(r, "status", r) for r in results]
        assert int(svc.metrics.value("serve_failover_redispatched_total")) \
            >= 1
        # let the supervisor restart the wedged replica so stop() joins
        # cleanly instead of waiting out the drain timeout
        deadline = time.time() + 60.0
        while time.time() < deadline:
            snap = {r["replica"]: r for r in svc.stats()["replicas"]}
            if (snap.get(0, {}).get("state") == "healthy"
                    and snap[0].get("generation", 0) >= 1):
                break
            time.sleep(0.2)

    events = [json.loads(line) for line in open(f"{rd}/events.jsonl")]
    opened = {e["trace"] for e in events
              if e.get("name") == "serve.admit" and e.get("opens_trace")}
    assert opened == set(tids)  # client-minted ids arrived verbatim
    closed = set()
    for e in events:
        if e.get("opens_trace"):
            continue
        if isinstance(e.get("trace"), str):
            closed.add(e["trace"])
        if isinstance(e.get("traces"), list):
            closed.update(t for t in e["traces"] if isinstance(t, str))
    assert opened <= closed, sorted(opened - closed)

    # the fleet summarizer reaches the same verdict from disk alone
    fleet = report_mod.summarize_fleet_dirs([rd])
    assert fleet["traces"]["orphans"] == []
    assert fleet["requests"]["server_by_status"].get("ok") == len(images)
