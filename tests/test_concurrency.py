"""The concurrency analysis tier: DP500-DP504 fixtures, scoping, noqa /
ALLOWLIST semantics, CLI exit codes, the shipped tree staying clean, and
the runtime lockwatch (order inversions, hold budgets, Sanitizer arming).

Static fixtures live in `tests/fixtures/analysis/`; the DP5xx rules are
scoped to the threaded packages, so fixtures lint through `logical_path`
overrides (in-process) or a tmp tree shaped like `dorpatch_tpu/serve/`
(CLI/subprocess)."""

import json
import pathlib
import subprocess
import sys
import threading

import pytest

from dorpatch_tpu import observe
from dorpatch_tpu.analysis import analyze_file, analyze_paths, analyze_source
from dorpatch_tpu.analysis import concurrency as cc
from dorpatch_tpu.analysis import lockwatch as lw
from dorpatch_tpu.analysis.cli import main as cli_main

REPO = pathlib.Path(__file__).resolve().parents[1]
FIXTURES = REPO / "tests" / "fixtures" / "analysis"

RULE_IDS = cc.CONCURRENCY_RULE_IDS


def run_fixture(name: str, rule_id: str):
    """Lint one fixture as if it lived at dorpatch_tpu/serve/<name> (the
    DP5xx rules are scoped to the threaded packages), keeping only the
    rule under test."""
    findings = analyze_file(FIXTURES / name,
                            logical_path=f"dorpatch_tpu/serve/{name}")
    return [f for f in findings if f.rule_id == rule_id]


def planted_tree(tmp_path, *fixture_names):
    """A tmp checkout shaped like a real package so the CLI modes see the
    fixtures in concurrency scope."""
    pkg = tmp_path / "dorpatch_tpu" / "serve"
    pkg.mkdir(parents=True, exist_ok=True)
    for name in fixture_names:
        (pkg / name).write_text(
            (FIXTURES / name).read_text(encoding="utf-8"), encoding="utf-8")
    return tmp_path


# ---------- per-rule positives / negatives ----------

@pytest.mark.parametrize("rule_id", RULE_IDS)
def test_rule_positive_fixture_fires(rule_id):
    found = run_fixture(f"{rule_id.lower()}_pos.py", rule_id)
    assert found, f"{rule_id} did not fire on its positive fixture"
    assert all(f.rule_id == rule_id for f in found)
    assert all(f.line > 0 for f in found)


@pytest.mark.parametrize("rule_id", RULE_IDS)
def test_rule_negative_fixture_clean(rule_id):
    found = run_fixture(f"{rule_id.lower()}_neg.py", rule_id)
    assert not found, [f.render() for f in found]


def test_dp500_counts_each_mutation_kind():
    found = run_fixture("dp500_pos.py", "DP500")
    assert len(found) == 3, [f.render() for f in found]
    msgs = " | ".join(f.message for f in found)
    assert "Pool._items" in msgs and "Pool._count" in msgs
    assert "guarded-by: self._lock" in msgs


def test_dp501_reports_cycle_and_canonical_order():
    found = run_fixture("dp501_pos.py", "DP501")
    assert len(found) == 1, [f.render() for f in found]
    msg = found[0].message
    assert "cycle" in msg
    assert "_alock < _block" in msg  # canonical = alphabetical


def test_dp502_catches_each_blocking_kind():
    found = run_fixture("dp502_pos.py", "DP502")
    msgs = " | ".join(f.message for f in found)
    for kind in ("time.sleep()", "self._queue.get() without a timeout",
                 "self._thread.join()", "self._cond.wait() without a"):
        assert kind in msgs, f"missing {kind}: {msgs}"


def test_dp503_catches_each_lifecycle_kind():
    found = run_fixture("dp503_pos.py", "DP503")
    msgs = " | ".join(f.message for f in found)
    assert "never joined on a Runner stop()/close() path" in msgs
    assert "before guarded attribute(s) _state" in msgs
    assert "anonymous non-daemon thread" in msgs
    assert "start()ed in run_local() but never joined there" in msgs


def test_dp504_counts_and_message():
    found = run_fixture("dp504_pos.py", "DP504")
    assert len(found) == 2, [f.render() for f in found]
    assert all("time.monotonic()" in f.message for f in found)


# ---------- path scoping ----------

@pytest.mark.parametrize("logical", [
    "dorpatch_tpu/pipeline.py",      # package file outside the threaded dirs
    "tools/serve/loadgen.py",        # tools tree is never package scope
    "tests/serve/test_worker.py",    # test tree exempt
])
def test_dp5xx_scoped_to_threaded_packages(logical):
    findings = analyze_file(FIXTURES / "dp500_pos.py", logical_path=logical)
    assert not [f for f in findings if f.rule_id.startswith("DP5")]


@pytest.mark.parametrize("logical", [
    "dorpatch_tpu/farm/queue.py",
    "dorpatch_tpu/observe/metrics.py",
    "dorpatch_tpu/recert/scheduler.py",
    "dorpatch_tpu/backoff.py",
    "dorpatch_tpu/chaos.py",
])
def test_dp5xx_fires_across_threaded_packages(logical):
    findings = analyze_file(FIXTURES / "dp500_pos.py", logical_path=logical)
    assert [f.rule_id for f in findings if f.rule_id == "DP500"] \
        == ["DP500"] * 3


# ---------- suppression: noqa + ALLOWLIST ----------

def test_noqa_suppresses_dp502():
    src = ("import threading\n"
           "import time\n"
           "LOCK = threading.Lock()\n"
           "def hold():\n"
           "    with LOCK:\n"
           "        time.sleep(1.0)  # noqa: DP502\n")
    found = analyze_source(src, logical_path="dorpatch_tpu/serve/x.py",
                           select=["DP502"])
    assert found == []


def test_noqa_wrong_code_does_not_suppress():
    src = ("import threading\n"
           "import time\n"
           "LOCK = threading.Lock()\n"
           "def hold():\n"
           "    with LOCK:\n"
           "        time.sleep(1.0)  # noqa: DP501\n")
    found = analyze_source(src, logical_path="dorpatch_tpu/serve/x.py",
                           select=["DP502"])
    assert [f.rule_id for f in found] == ["DP502"]


def test_allowlist_grants_one_rule_for_matching_files(monkeypatch):
    monkeypatch.setitem(cc.ALLOWLIST, "dorpatch_tpu/serve/dp500_*.py",
                        {"DP500": "test grant"})
    assert cc.allowlisted("DP500", "dorpatch_tpu/serve/dp500_pos.py") \
        == "test grant"
    assert cc.allowlisted("DP501", "dorpatch_tpu/serve/dp500_pos.py") is None
    assert cc.allowlisted("DP500", "dorpatch_tpu/farm/dp500_pos.py") is None
    assert run_fixture("dp500_pos.py", "DP500") == []
    # other rules still run on the allowlisted file
    assert run_fixture("dp501_pos.py", "DP501")


# ---------- the shipped tree ----------

def test_shipped_tree_is_concurrency_clean():
    """The acceptance gate: every DP5xx finding in the shipped tree was
    either fixed or suppressed with a reasoned `# noqa`."""
    findings = analyze_paths([REPO / "dorpatch_tpu", REPO / "tools"],
                             select=list(RULE_IDS))
    assert findings == [], "\n".join(f.render() for f in findings)


def test_shipped_tree_guard_annotations_exist():
    """The DP500 contract is only as good as its annotations: the threaded
    hot spots must actually declare their guarded state."""
    annotated = []
    for rel in ("serve/pool.py", "serve/batcher.py", "farm/queue.py",
                "observe/metrics.py", "observe/heartbeat.py",
                "recert/scheduler.py"):
        src = (REPO / "dorpatch_tpu" / rel).read_text(encoding="utf-8")
        if cc._guard_annotations(src):
            annotated.append(rel)
    assert len(annotated) == 6, f"missing guarded-by annotations: {annotated}"


# ---------- CLI ----------

def test_cli_concurrency_exit_one_on_planted_tree(tmp_path, capsys):
    tree = planted_tree(tmp_path, "dp501_pos.py", "dp502_pos.py")
    rc = cli_main(["--concurrency", str(tree)])
    out = capsys.readouterr()
    assert rc == 1
    assert "DP501" in out.out and "DP502" in out.out
    assert "finding(s)" in out.err


def test_cli_concurrency_exit_zero_on_shipped_tree():
    assert cli_main(["--concurrency"]) == 0


def test_cli_default_lint_gate_includes_dp5xx(tmp_path, capsys):
    """DP5xx rides the default lint gate too — run_tests.sh catches a
    regression even without the dedicated --concurrency pass."""
    tree = planted_tree(tmp_path, "dp501_pos.py")
    rc = cli_main([str(tree)])
    assert rc == 1
    assert "DP501" in capsys.readouterr().out


def test_cli_concurrency_select_narrows(tmp_path, capsys):
    tree = planted_tree(tmp_path, "dp501_pos.py", "dp502_pos.py")
    rc = cli_main(["--concurrency", str(tree), "--select", "DP502"])
    out = capsys.readouterr().out
    assert rc == 1 and "DP502" in out and "DP501" not in out


def test_cli_concurrency_json_format(tmp_path, capsys):
    tree = planted_tree(tmp_path, "dp501_pos.py")
    rc = cli_main(["--concurrency", str(tree), "--format", "json"])
    lines = capsys.readouterr().out.strip().splitlines()
    assert rc == 1 and lines
    recs = [json.loads(line) for line in lines]
    assert all(r["rule"].startswith("DP50") for r in recs)
    assert all(set(r) == {"rule", "path", "line", "col", "message",
                          "fixable"} for r in recs)


def test_cli_concurrency_usage_errors(capsys):
    assert cli_main(["--concurrency", "--trace"]) == 2
    assert cli_main(["--concurrency", "--baseline", "check"]) == 2
    assert cli_main(["--concurrency", "--fix"]) == 2
    # a trace-rule ID under --concurrency would run zero rules: loud exit
    assert cli_main(["--concurrency", "--select", "DP201"]) == 2


def test_cli_list_rules_includes_dp5xx(capsys):
    assert cli_main(["--list-rules"]) == 0
    out = capsys.readouterr().out
    for rid in RULE_IDS:
        assert rid in out


def test_module_entry_point_concurrency_gate(tmp_path):
    """`python -m dorpatch_tpu.analysis --concurrency` — the run_tests.sh
    gate — exits 0 on the shipped tree and 1 on a planted tree. Stdlib
    AST only: the subprocess never initializes a jax backend."""
    ok = subprocess.run(
        [sys.executable, "-m", "dorpatch_tpu.analysis", "--concurrency",
         "dorpatch_tpu", "tools"], cwd=REPO, capture_output=True, text=True)
    assert ok.returncode == 0, ok.stdout + ok.stderr
    tree = planted_tree(tmp_path, "dp501_pos.py")
    bad = subprocess.run(
        [sys.executable, "-m", "dorpatch_tpu.analysis", "--concurrency",
         str(tree)], cwd=REPO, capture_output=True, text=True)
    assert bad.returncode == 1
    assert "DP501" in bad.stdout


# ---------- static graph export ----------

def test_static_lock_graph_merges_package_edges(tmp_path):
    tree = planted_tree(tmp_path, "dp501_neg.py")
    graph = cc.static_lock_graph([tree])
    assert graph.get("_alock") == {"_block"}


def test_static_lock_graph_on_shipped_package_runs():
    graph = cc.static_lock_graph()
    assert isinstance(graph, dict)  # shipped tree avoids nesting: may be {}


# ---------- runtime lockwatch ----------

def test_lockwatch_order_inversion_raises_before_acquire():
    watch = lw.LockWatch()
    a, b = watch.lock("a"), watch.lock("b")
    with a:
        with b:
            pass
    with pytest.raises(lw.LockOrderViolation) as exc:
        with b:
            with a:
                pass
    assert "closes a cycle" in str(exc.value)
    # the raise happened BEFORE the raw acquire: nothing left stranded
    assert a._raw.acquire(blocking=False)
    a._raw.release()
    assert watch.violations == 1
    assert watch.held_by_current_thread() == ()


def test_lockwatch_inversion_across_threads():
    watch = lw.LockWatch()
    a, b = watch.lock("a"), watch.lock("b")
    with a:
        with b:
            pass
    errors = []

    def invert():
        try:
            with b:
                with a:
                    pass
        except lw.LockOrderViolation as e:
            errors.append(e)

    t = threading.Thread(target=invert)
    t.start()
    t.join()
    assert len(errors) == 1


def test_lockwatch_consistent_order_is_silent():
    watch = lw.LockWatch()
    a, b = watch.lock("a"), watch.lock("b")
    for _ in range(3):
        with a:
            with b:
                pass
    assert watch.violations == 0
    assert watch.observed_edges() == {"a": {"b"}}


def test_lockwatch_rlock_reentry_is_not_an_edge():
    watch = lw.LockWatch()
    r = watch.rlock("r")
    with r:
        with r:
            pass
    assert watch.violations == 0
    assert watch.observed_edges() == {}


def test_lockwatch_static_graph_seeds_the_order():
    """An inversion of a *source-committed* order is caught on its first
    runtime execution — before the opposite runtime path ever ran."""
    watch = lw.LockWatch(static_graph={"_alock": {"_block"}})
    a, b = watch.lock("_alock"), watch.lock("_block")
    with pytest.raises(lw.LockOrderViolation):
        with b:
            with a:
                pass


def test_lockwatch_hold_budget_raises_after_release():
    clock = [0.0]
    watch = lw.LockWatch(hold_budget_s=0.5, clock=lambda: clock[0])
    c = watch.lock("c")
    with pytest.raises(lw.LockHoldBudgetExceeded):
        with c:
            clock[0] += 1.0
    # the raise happened AFTER the raw release: nothing left stranded
    assert c._raw.acquire(blocking=False)
    c._raw.release()


def test_lockwatch_events_land_in_the_active_log(tmp_path):
    path = tmp_path / "events.jsonl"
    watch = lw.LockWatch()
    a, b = watch.lock("a"), watch.lock("b")
    with observe.EventLog(str(path)):
        with a:
            with b:
                pass
        with pytest.raises(lw.LockOrderViolation):
            with b:
                with a:
                    pass
    events = [json.loads(line) for line in
              path.read_text(encoding="utf-8").splitlines()]
    order = [e for e in events if e.get("name") == "sanitize.lock_order"]
    assert len(order) == 1
    assert order[0]["lock"] == "a" and order[0]["held"] == ["b"]


def test_lockwatch_hold_event_lands_in_the_active_log(tmp_path):
    path = tmp_path / "events.jsonl"
    clock = [0.0]
    watch = lw.LockWatch(hold_budget_s=0.25, clock=lambda: clock[0])
    c = watch.lock("c")
    with observe.EventLog(str(path)):
        with pytest.raises(lw.LockHoldBudgetExceeded):
            with c:
                clock[0] += 1.0
    events = [json.loads(line) for line in
              path.read_text(encoding="utf-8").splitlines()]
    held = [e for e in events if e.get("name") == "sanitize.lock_held"]
    assert len(held) == 1
    assert held[0]["lock"] == "c" and held[0]["budget_s"] == 0.25


def test_watched_lock_factory_degrades_without_a_watch():
    assert lw.active_watch() is None
    bare = lw.watched_lock("x")
    assert not isinstance(bare, lw.WatchedLock)
    watch = lw.LockWatch()
    prev = lw.set_active_watch(watch)
    try:
        wrapped = lw.watched_lock("x")
        assert isinstance(wrapped, lw.WatchedLock)
        with wrapped:
            pass
    finally:
        lw.set_active_watch(prev)
    assert lw.active_watch() is None


def test_sanitizer_arms_and_restores_the_lockwatch():
    from dorpatch_tpu.analysis.sanitize import Sanitizer

    assert lw.active_watch() is None
    san = Sanitizer(debug_nans=False, log_compiles=False,
                    recompile_budgets=False, lock_order=True)
    assert san.lock_watch is not None
    with san:
        assert lw.active_watch() is san.lock_watch
        # locks built through the factory are watched while armed
        assert isinstance(lw.watched_lock("y"), lw.WatchedLock)
    assert lw.active_watch() is None


def test_sanitizer_lock_order_off_means_no_watch():
    from dorpatch_tpu.analysis.sanitize import Sanitizer

    san = Sanitizer(debug_nans=False, log_compiles=False,
                    recompile_budgets=False, lock_order=False)
    assert san.lock_watch is None
    with san:
        assert lw.active_watch() is None


def test_sanitizer_catches_inversion_of_the_static_graph(tmp_path):
    """End to end: --sanitize arms a watch seeded with the shipped static
    graph; a runtime inversion of a planted source order raises. The
    static side comes from an explicit graph here because the shipped
    tree deliberately has no nested lock acquisitions left."""
    from dorpatch_tpu.analysis.sanitize import Sanitizer

    san = Sanitizer(debug_nans=False, log_compiles=False,
                    recompile_budgets=False, lock_order=True)
    san.lock_watch = lw.LockWatch(static_graph={"_alock": {"_block"}})
    with san:
        watch = lw.active_watch()
        a = watch.lock("_alock")
        b = watch.lock("_block")
        with pytest.raises(lw.LockOrderViolation):
            with b:
                with a:
                    pass
