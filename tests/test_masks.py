"""Golden tests for mask geometry (SURVEY.md §2 geometry table)."""

import numpy as np
import pytest

from dorpatch_tpu import masks


# Expected geometry at 224, n_patch=1 (SURVEY.md §2: mask-window geometry row).
GOLDEN_224 = {
    0.015: (27, 33, 59),
    0.03: (38, 32, 69),
    0.06: (54, 29, 82),
    0.12: (77, 25, 101),
}


def _slice_rasterize(rects, img_size):
    """Independent oracle: build boolean masks by slice-assignment."""
    rects = np.asarray(rects)
    out = np.ones((rects.shape[0], img_size, img_size), dtype=bool)
    for n in range(rects.shape[0]):
        for r0, r1, c0, c1 in rects[n]:
            out[n, r0:r1, c0:c1] = False
    return out


@pytest.mark.parametrize("ratio", sorted(GOLDEN_224))
def test_geometry_golden(ratio):
    spec = masks.geometry(224, ratio, n_patch=1)
    mask_size, stride, window = GOLDEN_224[ratio]
    assert spec.mask_size == mask_size
    assert spec.stride == stride
    assert spec.window_size == window
    assert spec.num_mask_per_axis == 6


def test_set_counts():
    spec = masks.geometry(224, 0.03, n_patch=1)
    singles, doubles = masks.mask_sets(spec)
    assert singles.shape == (36, 1, 4)
    assert doubles.shape == (630, 2, 4)  # C(36,2)

    spec2 = masks.geometry(224, 0.03, n_patch=2)
    pairs, triples = masks.mask_sets(spec2)
    assert pairs.shape == (630, 2, 4)
    assert triples.shape == (36 * 630, 3, 4)


def test_universe_counts():
    uni2 = masks.dropout_universe(224, dropout=2)
    assert uni2.shape == (2520, 2, 4)  # 4 ratios x 630
    uni1 = masks.dropout_universe(224, dropout=1)
    assert uni1.shape == (144, 1, 4)
    uni0 = masks.dropout_universe(224, dropout=0)
    assert uni0.shape == (1, 1, 4)
    assert np.asarray(masks.rasterize(uni0, 224)).all()  # identity mask
    with pytest.raises(ValueError):
        masks.dropout_universe(224, dropout=3)
    with pytest.raises(ValueError):
        masks.pad_rects(np.zeros((4, 2, 4), np.int32), 1)


def test_rects_cover_image():
    """R-covering property: strides place 6 windows spanning the image."""
    for ratio in GOLDEN_224:
        spec = masks.geometry(224, ratio)
        rects = masks.first_order_rects(spec)
        assert rects.shape == (36, 4)
        # Last window reaches the image edge.
        assert rects[:, 1].max() == 224
        assert rects[:, 3].max() == 224
        # Consecutive windows overlap by at least mask_size - 1 so every
        # mask_size x mask_size patch is fully covered by some window.
        starts = sorted(set(rects[:, 0].tolist()))
        for a, b in zip(starts, starts[1:]):
            assert b - a <= spec.window_size - spec.mask_size + 1


def test_rasterize_matches_slicing_oracle():
    spec = masks.geometry(96, 0.06)
    singles, doubles = masks.mask_sets(spec)
    got_s = np.asarray(masks.rasterize(singles, 96))
    np.testing.assert_array_equal(got_s, _slice_rasterize(singles, 96))
    got_d = np.asarray(masks.rasterize(doubles[:50], 96))
    np.testing.assert_array_equal(got_d, _slice_rasterize(doubles[:50], 96))


def test_double_mask_is_product_of_singles():
    """Pair masks equal the elementwise AND of their constituent singles
    (reference builds them as products, PatchCleanser.py:23-24)."""
    spec = masks.geometry(64, 0.06)
    basic = masks.first_order_rects(spec)
    single_m = np.asarray(masks.rasterize(basic[:, None, :], 64))
    _, doubles = masks.mask_sets(spec)
    pair_m = np.asarray(masks.rasterize(doubles, 64))
    ii, jj = np.triu_indices(36, k=1)
    np.testing.assert_array_equal(pair_m, single_m[ii] & single_m[jj])


def test_pair_index():
    n = 36
    ii, jj = np.triu_indices(n, k=1)
    idx = masks.pair_index(n, ii, jj)
    np.testing.assert_array_equal(idx, np.arange(len(ii)))


def test_second_round_table_indices():
    """Row i of the combined-table grid is first-round mask i's second-round
    mask set: the single mask itself on the diagonal (masking idempotence),
    the {i, j} pair at `n + pair_index` off it — symmetric by construction."""
    n = 6
    grid = masks.second_round_table_indices(n)
    assert grid.shape == (n, n)
    np.testing.assert_array_equal(grid, grid.T)
    np.testing.assert_array_equal(np.diag(grid), np.arange(n))
    ii, jj = np.triu_indices(n, k=1)
    np.testing.assert_array_equal(
        grid[ii, jj], n + masks.pair_index(n, ii, jj))
    # every combined-table index lands in [0, n + C(n,2))
    assert grid.min() == 0 and grid.max() == n + n * (n - 1) // 2 - 1


def test_pad_rects_is_noop_on_mask():
    spec = masks.geometry(64, 0.06)
    singles, _ = masks.mask_sets(spec)
    padded = masks.pad_rects(singles, 3)
    assert padded.shape == (36, 3, 4)
    np.testing.assert_array_equal(
        np.asarray(masks.rasterize(padded, 64)),
        np.asarray(masks.rasterize(singles, 64)),
    )


def test_apply_masks_fill():
    import jax.numpy as jnp

    spec = masks.geometry(32, 0.12)
    singles, _ = masks.mask_sets(spec)
    m = masks.rasterize(singles[:4], 32)
    imgs = jnp.ones((2, 32, 32, 3)) * 0.25
    out = np.asarray(masks.apply_masks(imgs, m, fill=0.5))
    assert out.shape == (2, 4, 32, 32, 3)
    mn = np.asarray(m)
    assert np.allclose(out[:, :, :, :, 0][:, mn], 0.25)
    assert np.allclose(out[:, :, :, :, 0][:, ~mn], 0.5)


# ---------- ViT patch-token coverage (incremental certify path) ----------


def test_rect_token_coverage_edge_straddling():
    """A rectangle whose edge straddles a patch boundary covers BOTH
    straddled tokens; boundary-aligned rectangles cover exactly their
    cells; empty (0,0,0,0) rows cover nothing."""
    img, p = 32, 4  # 8x8 token grid
    rects = np.array([
        [[3, 9, 0, 4]],     # rows straddle cells 0-2, cols exactly cell 0
        [[4, 8, 4, 8]],     # exactly token (1, 1)
        [[0, 1, 31, 32]],   # one pixel in the far corner token (0, 7)
        [[0, 0, 0, 0]],     # empty
    ], np.int32)
    cov = masks.rect_token_coverage(rects, img, p)
    assert cov.shape == (4, 64)
    assert sorted(np.nonzero(cov[0])[0]) == [0, 8, 16]   # (0..2, 0)
    assert sorted(np.nonzero(cov[1])[0]) == [9]          # (1, 1)
    assert sorted(np.nonzero(cov[2])[0]) == [7]          # (0, 7)
    assert not cov[3].any()


def test_rect_token_coverage_pair_union():
    """K=2 rows cover the union of their rectangles' tokens — the pair
    masks' coverage the incremental pair table uses."""
    img, p = 32, 4
    a = np.array([[[0, 4, 0, 4]]], np.int32)
    b = np.array([[[28, 32, 28, 32]]], np.int32)
    pair = np.array([[[0, 4, 0, 4], [28, 32, 28, 32]]], np.int32)
    cov = masks.rect_token_coverage(pair, img, p)
    want = (masks.rect_token_coverage(a, img, p)
            | masks.rect_token_coverage(b, img, p))
    np.testing.assert_array_equal(cov, want)
    assert sorted(np.nonzero(cov[0])[0]) == [0, 63]


@pytest.mark.parametrize("ratio", [0.06, 0.12])
def test_token_coverage_matches_rasterize_oracle(ratio):
    """token_coverage(spec, p) == brute force: token t is covered iff the
    rasterized mask occludes any pixel of t's patch window (includes the
    edge-straddling first-round windows the geometry produces)."""
    img, p = 32, 4
    spec = masks.geometry(img, ratio)
    cov = masks.token_coverage(spec, p)
    rects = masks.first_order_rects(spec)
    occluded = ~_slice_rasterize(rects[:, None, :], img)  # [M, H, W]
    g = img // p
    want = occluded.reshape(-1, g, p, g, p).any(axis=(2, 4)).reshape(-1, g * g)
    np.testing.assert_array_equal(cov, want)
    # every first-round window really does straddle cell boundaries
    assert (cov.sum(axis=1) > 1).any()


def test_token_coverage_rejects_bad_patch():
    with pytest.raises(ValueError):
        masks.token_coverage(masks.geometry(32, 0.06), 5)
