"""Victim-training subsystem (dorpatch_tpu/train.py) + procedural dataset:
mechanics, determinism, and the export->registry.get_model checkpoint
round-trip. The accuracy evidence for the flagship victim is the committed
training report (FLAGSHIP.md), not CI."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from dorpatch_tpu import data as data_lib


def test_procedural_arrays_deterministic_and_split_disjoint():
    x1, y1 = data_lib.procedural_arrays("cifar10", 20, 32, seed=7, split="train")
    x2, y2 = data_lib.procedural_arrays("cifar10", 20, 32, seed=7, split="train")
    xt, _ = data_lib.procedural_arrays("cifar10", 20, 32, seed=7, split="test")
    assert np.array_equal(x1, x2) and np.array_equal(y1, y2)
    assert not np.array_equal(x1, xt)
    assert x1.shape == (200, 32, 32, 3) and x1.dtype == np.float32
    assert x1.min() >= 0.0 and x1.max() <= 1.0
    # balanced labels, all classes present
    assert np.bincount(y1, minlength=10).tolist() == [20] * 10


def test_procedural_labels_are_generative_not_random():
    """Class identity must be visible in the pixels: images of the same
    class correlate more with their own class mean than with other class
    means (a linear signal a net can learn from)."""
    x, y = data_lib.procedural_arrays("cifar10", 40, 32, seed=3, split="train")
    flat = x.reshape(len(x), -1) - x.mean()
    means = np.stack([flat[y == c].mean(axis=0) for c in range(10)])
    sims = flat @ means.T  # [N, 10]
    nearest = sims.argmax(axis=1)
    acc = (nearest == y).mean()
    assert acc > 0.5, f"nearest-class-mean acc {acc:.2f}: labels look random"


def test_procedural_batches_cover_split():
    batches = list(data_lib.procedural_batches(
        "cifar10", 64, 32, seed=5, split="test", n_per_class=10))
    n = sum(len(b[1]) for b in batches)
    assert n == 100
    assert all(b[0].shape[1:] == (32, 32, 3) for b in batches)


@pytest.mark.slow
def test_train_step_learns_and_checkpoint_round_trips(tmp_path):
    """Two tiny epochs must beat the loss of step 0 (mechanics, not
    accuracy), and the exported .pth must load through the standard
    registry.get_model path with identical logits."""
    from dorpatch_tpu.models import registry
    from dorpatch_tpu.train import TrainConfig, save_victim_checkpoint, train_victim

    cfg = TrainConfig(n_per_class_train=24, n_per_class_test=8, epochs=2,
                      batch_size=48, warmup_steps=2, seed=1)
    params, report = train_victim(cfg, log=lambda *a: None)
    assert report["steps"] == 2 * (240 // 48)
    assert 0.0 <= report["test_acc"] <= 1.0

    path = save_victim_checkpoint(params, str(tmp_path), "cifar10")
    victim = registry.get_model("cifar10", "resnet18", model_dir=str(tmp_path),
                                img_size=32)
    assert victim.from_checkpoint

    x = jax.random.uniform(jax.random.PRNGKey(0), (2, 32, 32, 3))
    from dorpatch_tpu.models.small import CifarResNet18

    want = CifarResNet18(num_classes=10).apply(params, (x - 0.5) / 0.5)
    got = victim.apply(victim.params, x)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-5, atol=1e-5)
    assert path.endswith("cifar_resnet18_cutout2_128_cifar10.pth")


@pytest.mark.slow
def test_train_vit_family_and_checkpoint_round_trips(tmp_path):
    """The second trainable family: cifar_vit trains through the same jitted
    step and exports a .pth that registry.get_model('cifar_vit') loads with
    identical logits (trained-victim parity beyond the conv family)."""
    from dorpatch_tpu.models import registry
    from dorpatch_tpu.models.vit import vit_cifar
    from dorpatch_tpu.train import TrainConfig, save_victim_checkpoint, train_victim

    cfg = TrainConfig(arch="cifar_vit", n_per_class_train=24,
                      n_per_class_test=8, epochs=1, batch_size=48,
                      warmup_steps=2, seed=1)
    params, report = train_victim(cfg, log=lambda *a: None)
    assert report["steps"] == 240 // 48
    assert 0.0 <= report["test_acc"] <= 1.0

    path = save_victim_checkpoint(params, str(tmp_path), "cifar10",
                                  arch="cifar_vit")
    assert path.endswith("cifar_vit_cutout2_128_cifar10.pth")
    victim = registry.get_model("cifar10", "cifar_vit",
                                model_dir=str(tmp_path), img_size=32)
    assert victim.from_checkpoint

    x = jax.random.uniform(jax.random.PRNGKey(0), (2, 32, 32, 3))
    want = vit_cifar(10).apply(params, (x - 0.5) / 0.5)
    got = victim.apply(victim.params, x)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-5, atol=1e-5)


def test_train_victim_rejects_untrainable_arch():
    from dorpatch_tpu.train import TrainConfig, train_victim

    with pytest.raises(ValueError, match="not trainable offline"):
        train_victim(TrainConfig(arch="resnetv2"), log=lambda *a: None)


def _write_cifar10_batch(path, n, seed, label_key=b"labels"):
    import pickle

    rng = np.random.default_rng(seed)
    d = {b"data": rng.integers(0, 256, (n, 3 * 32 * 32), dtype=np.uint8),
         label_key: rng.integers(0, 10, n).tolist()}
    with open(path, "wb") as f:
        pickle.dump(d, f)


def test_cifar_train_split_loads_data_batches(tmp_path):
    """split="train" reads data_batch_1..5 (the reference's train=True path,
    utils.py:81-102), tolerating missing batch files; split="test" is
    unchanged. training_arrays feeds this to train.py as float [0,1]."""
    base = tmp_path / "cifar10" / "cifar-10-batches-py"
    base.mkdir(parents=True)
    _write_cifar10_batch(base / "data_batch_1", 6, seed=1)
    _write_cifar10_batch(base / "data_batch_3", 4, seed=2)  # gap: no batch 2
    _write_cifar10_batch(base / "test_batch", 5, seed=3)

    imgs, labels = data_lib._load_cifar(str(tmp_path), "cifar10", split="train")
    assert imgs.shape == (10, 32, 32, 3) and labels.shape == (10,)
    te_imgs, _ = data_lib._load_cifar(str(tmp_path), "cifar10", split="test")
    assert te_imgs.shape == (5, 32, 32, 3)

    x, y = data_lib.training_arrays("cifar10", "disk", str(tmp_path),
                                    split="train")
    assert x.dtype == np.float32 and x.shape == (10, 32, 32, 3)
    assert 0.0 <= x.min() and x.max() <= 1.0
    assert np.array_equal(
        x, imgs.astype(np.float32) / 255.0) and np.array_equal(y, labels)

    with pytest.raises(FileNotFoundError, match="data_batch"):
        data_lib._load_cifar(str(tmp_path / "empty"), "cifar10", split="train")


def test_train_victim_rejects_sub_batch_dataset():
    """A partial dataset smaller than one batch must fail with the cause,
    not an empty-stack error deep in the epoch loop."""
    from dorpatch_tpu.train import TrainConfig, train_victim

    cfg = TrainConfig(n_per_class_train=1, batch_size=128)
    with pytest.raises(ValueError, match="not enough data"):
        train_victim(cfg, log=lambda *a: None)


def test_training_arrays_disk_guards():
    with pytest.raises(ValueError, match="cifar only"):
        data_lib.training_arrays("imagenet", "disk")
    with pytest.raises(ValueError, match="native-32px"):
        data_lib.training_arrays("cifar10", "disk", img_size=224)
    with pytest.raises(ValueError, match="unknown training data source"):
        data_lib.training_arrays("cifar10", "nope")


@pytest.mark.slow
def test_train_victim_consumes_disk_cifar(tmp_path):
    """train.py --data-source disk: one tiny epoch over fabricated CIFAR
    batches runs end-to-end (mechanics; real accuracy needs real data)."""
    from dorpatch_tpu.train import TrainConfig, train_victim

    base = tmp_path / "cifar10" / "cifar-10-batches-py"
    base.mkdir(parents=True)
    _write_cifar10_batch(base / "data_batch_1", 96, seed=11)
    _write_cifar10_batch(base / "test_batch", 32, seed=12)

    cfg = TrainConfig(epochs=1, batch_size=48, warmup_steps=2, seed=1,
                      data_source="disk", data_dir=str(tmp_path))
    params, report = train_victim(cfg, log=lambda *a: None)
    assert report["steps"] == 96 // 48
    assert report["n_train"] == 96 and report["n_test"] == 32


def test_procedural_rejects_unlearnable_class_counts():
    """>20 classes would collapse neighboring orientation buckets into the
    angle jitter (and imagenet would allocate ~60 GB): refuse loudly."""
    with pytest.raises(ValueError, match="procedural"):
        data_lib.procedural_arrays("cifar100", 2, 32)
    with pytest.raises(ValueError, match="procedural"):
        data_lib.procedural_arrays("imagenet", 2, 224)


def test_train_telemetry_unwinds_on_failure(tmp_path):
    """A failing train run must not leak the process-global active EventLog
    or the heartbeat daemon: later runs in the same process would write
    their spans into the stale telemetry dir."""
    from dorpatch_tpu import observe
    from dorpatch_tpu.train import TrainConfig, train_victim

    cfg = TrainConfig(data_source="disk", data_dir=str(tmp_path / "missing"))
    with pytest.raises(Exception):
        train_victim(cfg, log=lambda *a: None,
                     telemetry_dir=str(tmp_path / "telemetry"))
    assert observe.active_event_log() is None
    # the manifest was written before the failure: the dir still explains
    # what was attempted
    assert (tmp_path / "telemetry" / "run.json").exists()
