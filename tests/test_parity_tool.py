"""tools/parity_flagship.py mechanics: artifact staging must hand the torch
oracle the jax patches but never the jax certification records, and the
parity table arithmetic must be exact. The real evidence artifact
(artifacts/PARITY_r05.json) comes from running the tool after the
chip-validation flagship; these tests pin the logic it relies on."""

import importlib.util
import os

_spec = importlib.util.spec_from_file_location(
    "parity_flagship",
    os.path.join(os.path.dirname(__file__), "..", "tools",
                 "parity_flagship.py"))
parity = importlib.util.module_from_spec(_spec)
_spec.loader.exec_module(parity)


def test_stage_oracle_root_excludes_pc_cache(tmp_path):
    jax_root = tmp_path / "jax" / "cfg=1" / "sub=2"
    jax_root.mkdir(parents=True)
    for name in ("adv_mask_0.pt", "adv_pattern_0.pt", "targets_0.pt",
                 "adv_PC_0.pt", "adv_mask_1.pt"):
        (jax_root / name).write_bytes(b"x")
    (jax_root / "summary.json").write_text("{}")

    oracle = tmp_path / "oracle"
    n = parity.stage_oracle_root(str(tmp_path / "jax"), str(oracle))
    assert n == 4
    staged = sorted(os.path.basename(p) for p in
                    (oracle / "cfg=1" / "sub=2").iterdir())
    # the PC record cache must NOT cross over: the torch oracle would load
    # it and re-score jax's own certifications instead of recomputing
    assert staged == ["adv_mask_0.pt", "adv_mask_1.pt", "adv_pattern_0.pt",
                      "targets_0.pt"]


def test_parity_rows_and_delta():
    jax_m = {"evaluated_images": 16, "clean_accuracy": 100.0,
             "robust_accuracy": 6.25,
             "acc_pc": [10.0, 20.0, 30.0, 40.0],
             "certified_acc_pc": [1.0, 2.0, 3.0, 4.0],
             "certified_asr_pc": [50.0, 60.0, 70.0, 80.0]}
    torch_m = {"evaluated_images": 16, "clean_accuracy": 100.0,
               "robust_accuracy": 6.25,
               "acc_pc": [10.0, 20.0, 30.0, 40.0],
               "certified_acc_pc": [1.0, 2.0, 3.0, 4.0],
               "certified_asr_pc": [50.0, 60.0, 70.0, 81.5]}
    rows = parity.parity_rows(jax_m, torch_m)
    by_metric = {r["metric"]: r for r in rows}
    assert by_metric["evaluated_images"]["delta"] == 0
    assert by_metric["certified_asr_pc@12%"]["delta"] == -1.5
    assert by_metric["certified_asr_pc@1.5%"]["delta"] == 0.0
    # the tool's parity gate keys off certified_asr rows only
    asr_deltas = [abs(r["delta"]) for r in rows
                  if r["metric"].startswith("certified_asr")]
    assert max(asr_deltas) == 1.5


def test_flagship_config_matches_chip_validation_step8():
    """The oracle must score the SAME protocol chip_validation step 8 ran:
    drift here silently breaks the 'same seeds and images' premise."""
    cfg = parity.flagship_config("/tmp/x", "torch")
    assert (cfg.dataset, cfg.base_arch, cfg.img_size) == ("cifar10",
                                                          "resnet18", 32)
    assert (cfg.batch_size, cfg.num_batches) == (8, 2)
    assert cfg.data_source == "procedural"
    assert cfg.attack.sampling_size == 128
    assert cfg.attack.max_iterations == 600
    assert cfg.seed == 1234  # both runs inherit the default seed
    assert cfg.model_dir.endswith(os.path.join("artifacts", "victim_r05"))
