"""tools/parity_flagship.py mechanics: artifact staging must hand the torch
oracle the jax patches but never the jax certification records, and the
parity table arithmetic must be exact. The real evidence artifact
(artifacts/PARITY_r05.json) comes from running the tool after the
chip-validation flagship; these tests pin the logic it relies on."""

import importlib.util
import os

_spec = importlib.util.spec_from_file_location(
    "parity_flagship",
    os.path.join(os.path.dirname(__file__), "..", "tools",
                 "parity_flagship.py"))
parity = importlib.util.module_from_spec(_spec)
_spec.loader.exec_module(parity)


def test_derived_roots_are_per_jax_root():
    """Oracle/torch staging roots must be derived from the jax tree, not
    shared constants: the r05 vit-family parity run rmtree'd the conv
    run's staged oracle evidence through the old shared default."""
    assert parity.derived_roots("artifacts/flagship_vit_r05") == (
        "artifacts/flagship_vit_r05_oracle", "artifacts/flagship_vit_r05_torch")
    # trailing slash and dot segments must not nest roots inside the tree
    assert parity.derived_roots("artifacts/a/") == parity.derived_roots(
        "artifacts/a/.")
    a = parity.derived_roots("artifacts/a/")
    b = parity.derived_roots("artifacts/b")
    assert a[0] != b[0] and a[1] != b[1]


def test_stage_oracle_root_excludes_pc_cache(tmp_path):
    jax_root = tmp_path / "jax" / "cfg=1" / "sub=2"
    jax_root.mkdir(parents=True)
    for name in ("adv_mask_0.pt", "adv_pattern_0.pt", "targets_0.pt",
                 "adv_PC_0.pt", "adv_mask_1.pt"):
        (jax_root / name).write_bytes(b"x")
    (jax_root / "summary.json").write_text("{}")

    oracle = tmp_path / "oracle"
    n = parity.stage_oracle_root(str(tmp_path / "jax"), str(oracle))
    assert n == 4
    staged = sorted(os.path.basename(p) for p in
                    (oracle / "cfg=1" / "sub=2").iterdir())
    # the PC record cache must NOT cross over: the torch oracle would load
    # it and re-score jax's own certifications instead of recomputing
    assert staged == ["adv_mask_0.pt", "adv_mask_1.pt", "adv_pattern_0.pt",
                      "targets_0.pt"]


def test_parity_rows_and_delta():
    jax_m = {"evaluated_images": 16, "clean_accuracy": 100.0,
             "robust_accuracy": 6.25,
             "acc_pc": [10.0, 20.0, 30.0, 40.0],
             "certified_acc_pc": [1.0, 2.0, 3.0, 4.0],
             "certified_asr_pc": [50.0, 60.0, 70.0, 80.0]}
    torch_m = {"evaluated_images": 16, "clean_accuracy": 100.0,
               "robust_accuracy": 6.25,
               "acc_pc": [10.0, 20.0, 30.0, 40.0],
               "certified_acc_pc": [1.0, 2.0, 3.0, 4.0],
               "certified_asr_pc": [50.0, 60.0, 70.0, 81.5]}
    rows = parity.parity_rows(jax_m, torch_m)
    by_metric = {r["metric"]: r for r in rows}
    assert by_metric["evaluated_images"]["delta"] == 0
    assert by_metric["certified_asr_pc@12%"]["delta"] == -1.5
    assert by_metric["certified_asr_pc@1.5%"]["delta"] == 0.0
    # the tool's parity gate keys off certified_asr rows only
    asr_deltas = [abs(r["delta"]) for r in rows
                  if r["metric"].startswith("certified_asr")]
    assert max(asr_deltas) == 1.5


def test_flagship_config_reconstructs_from_recorded_config(tmp_path):
    """A jax tree carrying the r05 config.json record must drive the oracle
    at the SAME scale it ran (e.g. a CPU-scaled hedge), not the hardcoded
    step-8 flags — with backend/results_root flipped and fp32 forced."""
    import json

    from dorpatch_tpu.config import AttackConfig, ExperimentConfig, config_to_dict

    ran = ExperimentConfig(
        dataset="cifar10", base_arch="resnet18", img_size=32, batch_size=4,
        num_batches=1, data_source="procedural", seed=77,
        model_dir="/victims/x", results_root=str(tmp_path / "jaxroot"),
        attack=AttackConfig(sampling_size=16, max_iterations=150,
                            compute_dtype="bfloat16"))
    sub = tmp_path / "jaxroot" / "cfg" / "sub"
    sub.mkdir(parents=True)
    cfg_path = sub / "config.json"
    cfg_path.write_text(json.dumps(config_to_dict(ran)))

    cfg = parity.flagship_config(str(tmp_path / "oracle"), "torch",
                                 config_path=str(cfg_path))
    assert cfg.backend == "torch"
    assert cfg.results_root == str(tmp_path / "oracle")
    assert cfg.seed == 77 and cfg.batch_size == 4 and cfg.num_batches == 1
    assert cfg.attack.sampling_size == 16
    assert cfg.attack.max_iterations == 150
    assert cfg.attack.compute_dtype == "float32"  # oracle is fp32
    assert cfg.model_dir == "/victims/x"
    # explicit --model-dir still wins over the record
    cfg2 = parity.flagship_config(str(tmp_path / "oracle"), "torch",
                                  model_dir="/other",
                                  config_path=str(cfg_path))
    assert cfg2.model_dir == "/other"
    # a missing record falls back to the step-8 flags, not a crash
    cfg3 = parity.flagship_config(str(tmp_path / "oracle"), "torch",
                                  config_path=str(sub / "absent.json"))
    assert cfg3.attack.max_iterations == 600


def test_config_record_round_trip():
    from dorpatch_tpu.config import (AttackConfig, DefenseConfig,
                                     ExperimentConfig, config_from_dict,
                                     config_to_dict)

    cfg = ExperimentConfig(
        dataset="cifar100", img_size=64, seed=9,
        attack=AttackConfig(dropout_sizes=(0.03, 0.12), targeted=True),
        defense=DefenseConfig(ratios=(0.06,), n_patch=2))
    back = config_from_dict(config_to_dict(cfg))
    assert back == cfg  # frozen dataclasses: full structural equality
    import pytest

    with pytest.raises(ValueError, match="unknown"):
        config_from_dict({**config_to_dict(cfg), "not_a_knob": 1})


def test_flagship_config_matches_chip_validation_step8():
    """The oracle must score the SAME protocol chip_validation step 8 ran:
    drift here silently breaks the 'same seeds and images' premise."""
    cfg = parity.flagship_config("/tmp/x", "torch")
    assert (cfg.dataset, cfg.base_arch, cfg.img_size) == ("cifar10",
                                                          "resnet18", 32)
    assert (cfg.batch_size, cfg.num_batches) == (8, 2)
    assert cfg.data_source == "procedural"
    assert cfg.attack.sampling_size == 128
    assert cfg.attack.max_iterations == 600
    assert cfg.seed == 1234  # both runs inherit the default seed
    assert cfg.model_dir.endswith(os.path.join("artifacts", "victim_r05"))
