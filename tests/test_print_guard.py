"""Lint guard: no bare `print(` calls in `dorpatch_tpu/` outside `observe/`.

Multi-process output must stay attributable — anonymous prints from N SPMD
processes interleave uselessly. Everything routes through `observe.log()`
(process-index + elapsed-time prefix); `observe/` itself implements that
sink and the report CLI's stdout, so it is the one allowed exception.

Token-based (not regex) so comments/docstrings mentioning print( and
`log=print`-style references don't false-positive: only a NAME token
`print` immediately followed by `(` and not preceded by `.` counts.
"""

import io
import pathlib
import tokenize

PKG = pathlib.Path(__file__).resolve().parents[1] / "dorpatch_tpu"


def bare_print_calls(path: pathlib.Path):
    toks = list(tokenize.tokenize(io.BytesIO(path.read_bytes()).readline))
    lines = []
    for i, t in enumerate(toks):
        if t.type != tokenize.NAME or t.string != "print":
            continue
        nxt = toks[i + 1] if i + 1 < len(toks) else None
        prev = toks[i - 1] if i > 0 else None
        if nxt is not None and nxt.type == tokenize.OP and nxt.string == "(" \
                and not (prev is not None and prev.type == tokenize.OP
                         and prev.string == "."):
            lines.append(t.start[0])
    return lines


def test_no_bare_print_outside_observe():
    offenders = {}
    for path in sorted(PKG.rglob("*.py")):
        if "observe" in path.relative_to(PKG).parts:
            continue
        lines = bare_print_calls(path)
        if lines:
            offenders[str(path.relative_to(PKG))] = lines
    assert not offenders, (
        "bare print( calls found — route them through observe.log() so "
        f"multi-process output stays attributable: {offenders}")


def test_guard_detects_prints(tmp_path):
    """The guard itself must actually catch a bare print (and only that)."""
    p = tmp_path / "x.py"
    p.write_text(
        "# print( in a comment is fine\n"
        's = "print(also fine)"\n'
        "log = print  # referencing the callable is fine\n"
        "import sys\n"
        "sys.stdout.write('x')\n"
        "print('caught')\n"
    )
    assert bare_print_calls(p) == [6]
