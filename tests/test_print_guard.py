"""Lint guard: no bare `print(` calls in `dorpatch_tpu/` outside `observe/`.

Multi-process output must stay attributable — anonymous prints from N SPMD
processes interleave uselessly; everything routes through `observe.log()`.

Since PR 2 the check IS rule DP101 of the analysis engine
(`dorpatch_tpu.analysis.rules_output.BarePrintRule`); this file is a thin
wrapper kept at its historical path so the invariant stays visible as its
own test. The engine's AST pass preserves the old tokenize pass's
semantics: comments, strings, `log = print` references and method calls
named print don't count — only a real `print(...)` call expression does.
"""

import pathlib

from dorpatch_tpu.analysis import analyze_file, analyze_paths

PKG = pathlib.Path(__file__).resolve().parents[1] / "dorpatch_tpu"


def bare_print_findings(path, logical_path=None):
    return analyze_file(path, logical_path=logical_path, select=["DP101"])


def test_no_bare_print_outside_observe():
    offenders = [f.render()
                 for f in analyze_paths([PKG], select=["DP101"])]
    assert not offenders, (
        "bare print( calls found — route them through observe.log() so "
        f"multi-process output stays attributable: {offenders}")


def test_guard_detects_prints(tmp_path):
    """The rule must catch a bare print (and only that) — the old tokenize
    guard's self-test, now through the engine."""
    p = tmp_path / "x.py"
    p.write_text(
        "# print( in a comment is fine\n"
        's = "print(also fine)"\n'
        "log = print  # referencing the callable is fine\n"
        "import sys\n"
        "sys.stdout.write('x')\n"
        "print('caught')\n"
    )
    found = bare_print_findings(p, logical_path="dorpatch_tpu/x.py")
    assert [f.line for f in found] == [6]
