"""tools/chip_validation.py resume + guard mechanics. These guard real
device-grant time: a re-run must not re-spend completed steps, a timed-out
step plus a dead tunnel must stop the sequence, and step 8 must never run
against a missing/stale victim checkpoint. All subprocess spawns are
stubbed — no jax, no device."""

import importlib.util
import json
import os
import sys

_spec = importlib.util.spec_from_file_location(
    "chip_validation",
    os.path.join(os.path.dirname(__file__), "..", "tools",
                 "chip_validation.py"))
cv = importlib.util.module_from_spec(_spec)
_spec.loader.exec_module(cv)


def _run_main(monkeypatch, tmp_path, argv, existing=None):
    out = tmp_path / "cv.json"
    if existing is not None:
        out.write_text(json.dumps(existing))
    monkeypatch.setattr(sys, "argv",
                        ["chip_validation.py", "--out", str(out)] + argv)
    rc = cv.main()
    return rc, json.loads(out.read_text()) if out.exists() else None


def test_resume_skips_parsed_steps(monkeypatch, tmp_path):
    calls = []
    monkeypatch.setitem(
        cv.STEPS, "1_gn_microbench",
        lambda t: (lambda res: {"fresh": True},
                   calls.append("1") or {"rc": 0, "seconds": 1.0,
                                         "stdout": "", "stderr": ""}))
    existing = {"1_gn_microbench": {"parsed": {"gn_fwd_only": 1.0},
                                    "rc": 0, "seconds": 5.0}}
    rc, results = _run_main(monkeypatch, tmp_path, ["--only", "1"],
                            existing=existing)
    assert rc == 0 and calls == []  # completed step not re-spent
    assert results["1_gn_microbench"]["parsed"] == {"gn_fwd_only": 1.0}

    rc, results = _run_main(monkeypatch, tmp_path,
                            ["--only", "1", "--redo", "1"], existing=existing)
    assert rc == 0 and calls == ["1"]  # --redo forces the re-run
    assert results["1_gn_microbench"]["parsed"] == {"fresh": True}


def test_failed_step_is_retried_on_resume(monkeypatch, tmp_path):
    """Only steps with a parsed result are skipped: a timeout/crash row
    (parsed=None) re-runs, which is the whole point of resuming."""
    calls = []
    monkeypatch.setitem(
        cv.STEPS, "1_gn_microbench",
        lambda t: (lambda res: {"ok": 1},
                   calls.append("1") or {"rc": 0, "seconds": 1.0,
                                         "stdout": "", "stderr": ""}))
    existing = {"1_gn_microbench": {"parsed": None, "rc": None,
                                    "error": "timeout after 2700s"}}
    rc, results = _run_main(monkeypatch, tmp_path, ["--only", "1"],
                            existing=existing)
    assert rc == 0 and calls == ["1"]
    assert results["1_gn_microbench"]["parsed"] == {"ok": 1}


def test_timeout_plus_dead_tunnel_circuit_breaks(monkeypatch, tmp_path):
    ran = []

    def timed_out_step(t):
        ran.append("2")
        return (cv.parse_bench,
                {"rc": None, "seconds": float(t), "stdout": "", "stderr": "",
                 "error": f"timeout after {t}s"})

    monkeypatch.setitem(cv.STEPS, "2_attack_auto_gn", timed_out_step)
    monkeypatch.setitem(
        cv.STEPS, "4_certify",
        lambda t: (cv.parse_bench,
                   ran.append("4") or {"rc": 0, "seconds": 1.0,
                                       "stdout": "{}", "stderr": ""}))
    monkeypatch.setattr(cv, "probe_tunnel", lambda timeout_s=180: False)
    rc, results = _run_main(monkeypatch, tmp_path, ["--only", "2,4"])
    assert rc == 3            # stopped resumably...
    assert ran == ["2"]       # ...before burning step 4's deadline
    assert "timeout" in results["2_attack_auto_gn"]["error"]
    assert "4_certify" not in results


def test_timeout_with_live_tunnel_continues(monkeypatch, tmp_path):
    ran = []

    def timed_out_step(t):
        ran.append("2")
        return (cv.parse_bench,
                {"rc": None, "seconds": float(t), "stdout": "", "stderr": "",
                 "error": f"timeout after {t}s"})

    monkeypatch.setitem(cv.STEPS, "2_attack_auto_gn", timed_out_step)
    monkeypatch.setitem(
        cv.STEPS, "4_certify",
        lambda t: (lambda res: {"ips": 2.0},
                   ran.append("4") or {"rc": 0, "seconds": 1.0,
                                       "stdout": "", "stderr": ""}))
    monkeypatch.setattr(cv, "probe_tunnel", lambda timeout_s=180: True)
    rc, results = _run_main(monkeypatch, tmp_path, ["--only", "2,4"])
    assert rc == 0 and ran == ["2", "4"]  # one wedged step, sequence goes on
    assert results["4_certify"]["parsed"] == {"ips": 2.0}


def test_parsers_reject_silent_cpu_fallback():
    """A child that silently landed on the jax CPU backend (dead plugin
    registration) must not be banked as an on-chip result: profile_gn and
    the pipeline announce `backend: <name>`, train.py reports
    `'backend': '<name>'` — all three parsers key off it."""
    gn_ok = ("backend: axon\n"
             "[gn] fwd-only scan  12.5 ms/iter\n"
             "[fused] fwd-only scan  9.1 ms/iter\n")
    assert cv.parse_profile_gn({"rc": 0, "stdout": gn_ok}) == {
        "gn_fwd-only": 12.5, "fused_fwd-only": 9.1}
    gn_cpu = gn_ok.replace("backend: axon", "backend: cpu")
    assert cv.parse_profile_gn({"rc": 0, "stdout": gn_cpu}) is None

    train_ok = ("epoch 12/12: train_acc=0.99 (300s)\n"
                "saved /x/v.pth; report={'test_acc': 0.97, "
                "'backend': 'axon'}\n")
    assert cv.parse_train({"rc": 0, "stdout": train_ok}) is not None
    train_cpu = train_ok.replace("'axon'", "'cpu'")
    assert cv.parse_train({"rc": 0, "stdout": train_cpu}) is None
    # train.py now routes output through observe.log, which prefixes
    # `[pN +T.Ts]` — the parser must still find the saved-line marker
    train_prefixed = ("[p0 +300.1s] epoch 12/12: train_acc=0.99 (300s)\n"
                      "[p0 +301.0s] saved /x/v.pth; report={'test_acc': "
                      "0.97, 'backend': 'axon'}\n")
    assert cv.parse_train({"rc": 0, "stdout": train_prefixed}) is not None

    flag_ok = ("backend: axon (1 devices)\n"
               "clean accuracy: 97.00%, ... certified_ASR@PC:0.00%\n")
    assert cv.parse_flagship({"rc": 0, "stdout": flag_ok}) is not None
    flag_cpu = flag_ok.replace("backend: axon", "backend: cpu")
    assert cv.parse_flagship({"rc": 0, "stdout": flag_cpu}) is None


def test_cpu_marker_survives_stdout_truncation():
    """The flagship child prints `backend: cpu` ONCE at the start, then
    ~18 KB of metrics echoes: run() records the marker from the FULL
    stdout before keeping only the 4 KB tail, and ran_on_cpu prefers
    that record over re-scanning the (truncated) tail."""
    noise = "x" * 120 + "\n"
    long_out = "backend: cpu (1 devices)\n" + noise * 200 + \
        "clean accuracy: 97.00%, ... certified_ASR@PC:0.00%\n"
    assert "backend: cpu" not in long_out[-4000:]  # truncation would hide it
    res = {"rc": 0, "cpu_backend": ("backend: cpu" in long_out),
           "stdout": long_out[-4000:]}
    assert cv.ran_on_cpu(res)
    assert cv.parse_flagship(res) is None
    # and run() itself records the flag: exercise it via a real child
    r = cv.run([sys.executable, "-c",
                "print('backend: cpu (8 devices)'); print('y' * 9000)"],
               {}, 60)
    assert r["cpu_backend"] is True and len(r["stdout"]) <= 4000
    r2 = cv.run([sys.executable, "-c",
                 "print('backend: axon (1 devices)'); print('y' * 9000)"],
                {}, 60)
    assert r2["cpu_backend"] is False


def test_is_on_chip_result_rejects_unmarked_cpu_backend_rows():
    """bench rows now carry the child's jax backend: a row from a child
    that silently landed on CPU (no fallback marker, plugin registered but
    device gone) must not be banked either."""
    assert cv.is_on_chip_result({"value": 50.0, "backend": "axon"})
    assert not cv.is_on_chip_result({"value": 0.7, "backend": "cpu"})
    assert not cv.is_on_chip_result({"value": 0.7, "fallback": "cpu"})
    assert not cv.is_on_chip_result(None)
    # pre-r05 rows without the key keep working (skippable when parsed)
    assert cv.is_on_chip_result({"value": 50.0})


def test_parse_bench_rejects_error_rows():
    """bench.py delivers rc=0 error rows by design ('benchmark could not
    run'); banking one as a parsed result would mark the step done and the
    resume would never retry it (found by driving the real tool against
    the dead tunnel)."""
    err_row = ('{"metric": "patch-opt images/sec", "value": 0.0, '
               '"unit": "images/sec", "vs_baseline": 0.0, '
               '"error": "benchmark could not run"}')
    assert cv.parse_bench({"rc": 0, "stdout": err_row}) is None
    ok_row = '{"metric": "m", "value": 5.0, "vs_baseline": 2.0}'
    assert cv.parse_bench({"rc": 0, "stdout": ok_row})["value"] == 5.0


def test_resume_does_not_bank_cpu_fallback_rows(monkeypatch, tmp_path):
    """A CPU-fallback bench row is a liveness artifact: once the tunnel
    holds, the unattended watcher must re-run that step for the on-chip
    number instead of skipping it as 'already done'."""
    calls = []
    monkeypatch.setitem(
        cv.STEPS, "2_attack_auto_gn",
        lambda t: (lambda res: {"value": 99.0},
                   calls.append("2") or {"rc": 0, "seconds": 1.0,
                                         "stdout": "", "stderr": ""}))
    existing = {"2_attack_auto_gn": {
        "parsed": {"value": 0.7, "fallback": "cpu", "comparable": False},
        "rc": 0, "seconds": 300.0}}
    rc, results = _run_main(monkeypatch, tmp_path, ["--only", "2"],
                            existing=existing)
    assert rc == 0 and calls == ["2"]
    assert results["2_attack_auto_gn"]["parsed"] == {"value": 99.0}


def test_flagship_guard_requires_trained_checkpoint(monkeypatch, tmp_path):
    rc, results = _run_main(monkeypatch, tmp_path, ["--only", "8"])
    assert rc == 0
    assert "skipped" in results["8_flagship_trained"]["error"]
