"""Attack-sweep farm: queue/lease protocol, failure taxonomy, chaos, report.

Fast tests drive the farm with stub runners (no model build, no compile);
the slow test proves crash-resume parity end-to-end on the real attack.
"""

import json
import os
import threading
import time

import pytest

from dorpatch_tpu.config import AttackConfig, ExperimentConfig
from dorpatch_tpu.farm.chaos import (
    Chaos, SimulatedPreemption, fault_seed, parse_faults)
from dorpatch_tpu.farm.queue import (
    JobQueue, expand_grid, job_slug, retry_delay)
from dorpatch_tpu.farm.report import format_fleet_report, summarize_fleet
from dorpatch_tpu.farm.worker import (
    FarmWorker, apply_overrides, classify_failure, job_config)

SPEC = {
    "base": {"dataset": "cifar10", "base_arch": "resnet18", "img_size": 32,
             "batch_size": 2, "synthetic_data": True},
    "axes": {"attack.patch_budget": [0.06, 0.12], "attack.dropout": [1, 2]},
    "max_attempts": 3,
}


class FakeClock:
    def __init__(self, now=1000.0):
        self.now = now

    def __call__(self):
        return self.now


def _worker(farm_dir, runner, **kw):
    kw.setdefault("lease_ttl", 5.0)
    kw.setdefault("poll_interval", 0.02)
    kw.setdefault("heartbeat_interval", 0.2)
    kw.setdefault("backoff_base", 0.05)
    kw.setdefault("backoff_cap", 0.2)
    return FarmWorker(str(farm_dir), runner=runner, **kw)


# ---------------- grid expansion / slugs / backoff ----------------


def test_expand_grid_deterministic_sorted_order():
    axes = {"b": [1, 2], "a": [0.1, 0.2]}
    grid = expand_grid(axes)
    assert grid == [
        {"a": 0.1, "b": 1}, {"a": 0.1, "b": 2},
        {"a": 0.2, "b": 1}, {"a": 0.2, "b": 2},
    ]
    assert expand_grid({}) == [{}]
    assert expand_grid(dict(reversed(list(axes.items())))) == grid


def test_job_slug_filesystem_safe():
    slug = job_slug({"attack.patch_budget": 0.06, "base_arch": "res/net 18"})
    assert "/" not in slug and " " not in slug
    assert "patch_budget=0.06" in slug
    assert len(job_slug({"k": "x" * 500})) <= 80


def test_retry_delay_deterministic_exponential_capped():
    assert retry_delay("j", 1) == retry_delay("j", 1)
    assert retry_delay("j", 1) != retry_delay("other", 1)
    base1 = retry_delay("j", 1, base=2.0, cap=300.0, jitter=0.0)
    base3 = retry_delay("j", 3, base=2.0, cap=300.0, jitter=0.0)
    assert base1 == 2.0 and base3 == 8.0
    assert retry_delay("j", 50, base=2.0, cap=300.0, jitter=0.0) == 300.0
    jittered = retry_delay("j", 1, base=2.0, jitter=0.25)
    assert 2.0 <= jittered <= 2.5


# ---------------- submit / job state ----------------


def test_submit_expands_grid_and_is_idempotent(tmp_path):
    jq = JobQueue(str(tmp_path / "farm"))
    ids = jq.submit_spec(SPEC)
    assert len(ids) == 4 and ids == sorted(ids)
    job = jq.read_job(ids[0])
    assert job["state"] == "pending" and job["attempts"] == 0
    assert job["params"] == {"attack.dropout": 1,
                             "attack.patch_budget": 0.06}

    # resubmitting must not reset live state
    jq.mark_running(dict(job), "w1")
    ids2 = jq.submit_spec(SPEC)
    assert ids2 == ids
    assert jq.read_job(ids[0])["state"] == "running"
    assert jq.read_job(ids[0])["attempts"] == 1


def test_counts_and_drained(tmp_path):
    jq = JobQueue(str(tmp_path / "farm"))
    ids = jq.submit_spec(SPEC)
    c = jq.counts()
    assert c["total"] == 4 and c["pending"] == 4
    assert not jq.drained(c)
    for job_id in ids:
        job = jq.read_job(job_id)
        jq.mark_running(job, "w")
        jq.mark_done(job, {"rows": 1})
    c = jq.counts()
    assert c["done"] == 4 and jq.drained(c)


def test_job_config_applies_base_and_axes(tmp_path):
    jq = JobQueue(str(tmp_path / "farm"))
    ids = jq.submit_spec(SPEC)
    cfg = job_config(jq.read_job(ids[-1]))
    assert cfg.dataset == "cifar10" and cfg.synthetic_data
    assert cfg.attack.patch_budget == 0.12 and cfg.attack.dropout == 2


def test_apply_overrides_dotted_and_unknown():
    cfg = ExperimentConfig()
    out = apply_overrides(cfg, {"attack.patch_budget": 0.3, "batch_size": 7,
                                "attack.dropout_sizes": [0.05, 0.1]})
    assert out.attack.patch_budget == 0.3 and out.batch_size == 7
    assert out.attack.dropout_sizes == (0.05, 0.1)
    with pytest.raises(TypeError):
        apply_overrides(cfg, {"attack.no_such_field": 1})
    with pytest.raises(ValueError):
        apply_overrides(cfg, {"attack.lr.inner": 1})


# ---------------- lease protocol ----------------


def test_lease_claim_is_exclusive_and_releasable(tmp_path):
    jq = JobQueue(str(tmp_path / "farm"))
    (job_id,) = jq.submit_spec({"axes": {}, "max_attempts": 1})
    assert jq.try_claim_lease(job_id, "wA", ttl=60.0)
    assert not jq.try_claim_lease(job_id, "wB", ttl=60.0)
    assert jq.owns_lease(job_id, "wA") and not jq.owns_lease(job_id, "wB")
    assert jq.renew_lease(job_id, "wA", 60.0)
    assert not jq.renew_lease(job_id, "wB", 60.0)
    jq.release_lease(job_id, "wB")  # not the owner: must be a no-op
    assert jq.owns_lease(job_id, "wA")
    jq.release_lease(job_id, "wA")
    assert jq.read_lease(job_id) is None


def test_stale_lease_reclaimed_via_heartbeat(tmp_path):
    clock = FakeClock(1000.0)
    jq = JobQueue(str(tmp_path / "farm"), clock=clock)
    (job_id,) = jq.submit_spec({"axes": {}, "max_attempts": 3})
    hb = tmp_path / "hb.jsonl"
    hb.write_text(json.dumps({"ts": 1000.0, "seq": 0, "phase": "x"}) + "\n")
    job = jq.claim("wA", ttl=10.0, heartbeat_path=str(hb))
    assert job is not None and job["state"] == "leased"
    jq.mark_running(job, "wA")

    # within TTL and still beating: the lease holds
    clock.now = 1005.0
    assert jq.claim("wB", ttl=10.0) is None

    # a fresh beat extends liveness even past the original expires_ts
    clock.now = 1012.0
    hb.write_text(hb.read_text()
                  + json.dumps({"ts": 1011.0, "seq": 1, "phase": "x"}) + "\n")
    assert jq.claim("wB", ttl=10.0) is None

    # beats stop: the lease goes stale and wB reclaims, reclaim counted
    clock.now = 1030.0
    reclaimed = jq.claim("wB", ttl=10.0)
    assert reclaimed is not None and reclaimed["id"] == job_id
    assert reclaimed["worker"] == "wB" and reclaimed["reclaims"] == 1


def test_stale_lease_without_heartbeat_uses_expires_ts(tmp_path):
    clock = FakeClock(1000.0)
    jq = JobQueue(str(tmp_path / "farm"), clock=clock)
    (job_id,) = jq.submit_spec({"axes": {}, "max_attempts": 3})
    assert jq.claim("wA", ttl=10.0) is not None
    clock.now = 1009.0
    assert jq.claim("wB", ttl=10.0) is None
    clock.now = 1011.0
    reclaimed = jq.claim("wB", ttl=10.0)
    assert reclaimed is not None and reclaimed["worker"] == "wB"


def test_lease_skew_stale_heartbeat_beats_future_expires_ts(tmp_path):
    """Clock skew: a renewal stamped by a skewed clock pushes `expires_ts`
    far into the future while the worker's heartbeat — its actual liveness
    witness — has stopped. The heartbeat must win: the job is reclaimable
    even though the fallback says the lease is alive."""
    clock = FakeClock(1000.0)
    jq = JobQueue(str(tmp_path / "farm"), clock=clock)
    (job_id,) = jq.submit_spec({"axes": {}, "max_attempts": 3})
    hb = tmp_path / "hb.jsonl"
    hb.write_text(json.dumps({"ts": 1000.0, "seq": 0, "phase": "x"}) + "\n")
    assert jq.claim("wA", ttl=10.0, heartbeat_path=str(hb)) is not None
    assert jq.renew_lease(job_id, "wA", 10_000.0)  # expires_ts ~ 11000
    clock.now = 1020.0  # beats stopped at ts=1000: 20s > TTL
    reclaimed = jq.claim("wB", ttl=10.0)
    assert reclaimed is not None and reclaimed["worker"] == "wB"


def test_lease_skew_future_heartbeat_beats_lapsed_expires_ts(tmp_path):
    """Clock skew the other way: the worker's clock runs ahead, so its beat
    timestamps sit in the queue clock's future while the queue-stamped
    `expires_ts` lapses. A worker that is demonstrably still beating must
    not lose its lease to the fallback disagreeing."""
    clock = FakeClock(1000.0)
    jq = JobQueue(str(tmp_path / "farm"), clock=clock)
    (job_id,) = jq.submit_spec({"axes": {}, "max_attempts": 3})
    hb = tmp_path / "hb.jsonl"
    hb.write_text(json.dumps({"ts": 1000.0, "seq": 0, "phase": "x"}) + "\n")
    assert jq.claim("wA", ttl=10.0, heartbeat_path=str(hb)) is not None
    # expires_ts=1010 has lapsed, but the skewed-ahead worker beat at
    # ts=1018 — (now - ts) <= ttl stays true, the lease holds
    clock.now = 1015.0
    hb.write_text(hb.read_text()
                  + json.dumps({"ts": 1018.0, "seq": 1, "phase": "x"}) + "\n")
    assert jq.claim("wB", ttl=10.0) is None
    # once the worker truly stops, staleness follows the heartbeat
    clock.now = 1040.0
    reclaimed = jq.claim("wB", ttl=10.0)
    assert reclaimed is not None and reclaimed["id"] == job_id


def test_lease_seq_advance_keeps_behind_skewed_worker_alive(tmp_path):
    """Monotonic beat `seq` is the skew-proof liveness witness: a worker
    whose clock runs BEHIND writes beat timestamps that look stale, but
    as long as the queue observes the seq advancing between checks the
    lease holds — and once the seq freezes, staleness follows the queue's
    own clock."""
    clock = FakeClock(1000.0)
    jq = JobQueue(str(tmp_path / "farm"), clock=clock)
    (job_id,) = jq.submit_spec({"axes": {}, "max_attempts": 3})
    hb = tmp_path / "hb.jsonl"
    hb.write_text(json.dumps({"ts": 995.0, "seq": 0, "phase": "x"}) + "\n")
    assert jq.claim("wA", ttl=10.0, heartbeat_path=str(hb)) is not None
    clock.now = 1005.0  # first observation: ts fallback, 10s <= ttl
    assert jq.claim("wB", ttl=10.0) is None
    # the worker's slow clock stamps ts=1000 while the queue reads 1012:
    # the ts check alone would reclaim (12s > ttl), but seq advanced
    clock.now = 1012.0
    hb.write_text(hb.read_text()
                  + json.dumps({"ts": 1000.0, "seq": 1, "phase": "x"}) + "\n")
    assert jq.claim("wB", ttl=10.0) is None
    clock.now = 1020.0
    hb.write_text(hb.read_text()
                  + json.dumps({"ts": 1005.0, "seq": 2, "phase": "x"}) + "\n")
    assert jq.claim("wB", ttl=10.0) is None
    # beats stop: the frozen seq goes stale on the QUEUE's clock
    clock.now = 1035.0
    reclaimed = jq.claim("wB", ttl=10.0)
    assert reclaimed is not None and reclaimed["id"] == job_id
    assert reclaimed["worker"] == "wB"


def test_lease_frozen_seq_with_future_ts_goes_stale(tmp_path):
    """A worker whose clock ran AHEAD leaves its last beat timestamp in
    the queue's future; when it wedges, the `(now - ts) <= ttl` fallback
    would keep the corpse alive until the skew wears off. The frozen seq
    must win: no advance for a local TTL means reclaim, wall clocks be
    damned."""
    clock = FakeClock(1000.0)
    jq = JobQueue(str(tmp_path / "farm"), clock=clock)
    (job_id,) = jq.submit_spec({"axes": {}, "max_attempts": 3})
    hb = tmp_path / "hb.jsonl"
    hb.write_text(json.dumps({"ts": 1000.0, "seq": 0, "phase": "x"}) + "\n")
    assert jq.claim("wA", ttl=10.0, heartbeat_path=str(hb)) is not None
    clock.now = 1005.0
    assert jq.claim("wB", ttl=10.0) is None
    # last beat before the wedge, stamped by a 12s-fast clock
    hb.write_text(hb.read_text()
                  + json.dumps({"ts": 1020.0, "seq": 1, "phase": "x"}) + "\n")
    clock.now = 1010.0  # seq advanced since the last check: alive
    assert jq.claim("wB", ttl=10.0) is None
    # seq frozen for > ttl on the queue's clock, yet ts=1020 still reads
    # "5s ago" at now=1025 — the seq verdict must override it
    clock.now = 1025.0
    reclaimed = jq.claim("wB", ttl=10.0)
    assert reclaimed is not None and reclaimed["id"] == job_id
    assert reclaimed["worker"] == "wB"


def test_corrupt_lease_is_reclaimable(tmp_path):
    jq = JobQueue(str(tmp_path / "farm"))
    (job_id,) = jq.submit_spec({"axes": {}, "max_attempts": 1})
    with open(jq.lease_path(job_id), "w") as fh:
        fh.write('{"worker": "wA", "expi')  # truncated mid-write
    assert jq.try_claim_lease(job_id, "wB", ttl=60.0)
    assert jq.owns_lease(job_id, "wB")


def test_concurrent_claims_are_disjoint(tmp_path):
    jq = JobQueue(str(tmp_path / "farm"))
    ids = jq.submit_spec(SPEC)
    claimed, lock = [], threading.Lock()
    barrier = threading.Barrier(4)

    def contend(worker_id):
        q = JobQueue(str(tmp_path / "farm"))
        barrier.wait()
        while True:
            job = q.claim(worker_id, ttl=60.0)
            if job is None:
                return
            with lock:
                claimed.append((worker_id, job["id"]))

    threads = [threading.Thread(target=contend, args=(f"w{i}",))
               for i in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert sorted(j for _, j in claimed) == ids  # every job exactly once


# ---------------- retry / quarantine state machine ----------------


def test_failed_respects_backoff_then_exhausts(tmp_path):
    clock = FakeClock(1000.0)
    jq = JobQueue(str(tmp_path / "farm"), clock=clock)
    (job_id,) = jq.submit_spec({"axes": {}, "max_attempts": 2})
    job = jq.claim("wA", ttl=60.0)
    job = jq.mark_running(job, "wA")
    jq.mark_failed(job, {"kind": "io"}, next_retry_ts=1050.0)
    jq.release_lease(job_id, "wA")

    assert jq.counts()["failed_retryable"] == 1
    assert jq.claim("wB", ttl=60.0) is None  # backoff not yet elapsed
    clock.now = 1051.0
    job = jq.claim("wB", ttl=60.0)
    assert job is not None
    job = jq.mark_running(job, "wB")
    assert job["attempts"] == 2
    jq.mark_failed(job, {"kind": "io"}, next_retry_ts=1100.0)
    jq.release_lease(job_id, "wB")

    c = jq.counts()
    assert c["failed_exhausted"] == 1 and jq.drained(c)
    clock.now = 2000.0
    assert jq.claim("wC", ttl=60.0) is None  # exhausted is terminal


def test_classify_failure_taxonomy():
    assert classify_failure(SimulatedPreemption("x")) == ("preemption", True)
    assert classify_failure(MemoryError()) == ("oom", True)
    assert classify_failure(OSError(28, "no space")) == ("io", True)
    assert classify_failure(FloatingPointError("nan")) == ("nan", False)
    for exc in (TypeError("t"), ValueError("v"), KeyError("k"),
                AttributeError("a"), IndexError("i")):
        kind, transient = classify_failure(exc)
        assert (kind, transient) == ("trace", False)
    assert classify_failure(RuntimeError("???")) == ("unknown", True)

    class RecompileBudgetExceeded(RuntimeError):
        pass

    assert classify_failure(RecompileBudgetExceeded()) == ("recompile", False)


def test_worker_quarantines_deterministic_failure(tmp_path):
    jq = JobQueue(str(tmp_path / "farm"))
    (job_id,) = jq.submit_spec({"axes": {}, "max_attempts": 3})

    def bad_runner(job, ctx):
        raise ValueError("shape mismatch in trace")

    summary = _worker(tmp_path / "farm", bad_runner).run()
    assert summary["quarantined"] == 1 and summary["done"] == 0
    job = jq.read_job(job_id)
    assert job["state"] == "quarantined"
    assert job["attempts"] == 1  # no retries burned on a deterministic bug
    failure = job["failures"][-1]
    assert failure["kind"] == "trace" and not failure["transient"]
    assert "ValueError" in failure["error"]
    assert "bad_runner" in failure["traceback"]
    assert jq.read_lease(job_id) is None


def test_worker_retries_transient_until_exhausted(tmp_path):
    jq = JobQueue(str(tmp_path / "farm"))
    (job_id,) = jq.submit_spec({"axes": {}, "max_attempts": 2})

    def flaky_runner(job, ctx):
        raise OSError(28, "disk full")

    summary = _worker(tmp_path / "farm", flaky_runner).run()
    assert summary["failed"] == 2
    job = jq.read_job(job_id)
    assert job["state"] == "failed" and job["exhausted"]
    assert job["attempts"] == 2 and len(job["failures"]) == 2


def test_worker_two_workers_drain_disjointly(tmp_path):
    jq = JobQueue(str(tmp_path / "farm"))
    ids = jq.submit_spec(SPEC)
    ran, lock = [], threading.Lock()

    def runner(job, ctx):
        with lock:
            ran.append(job["id"])
        return {"rows": 1}

    workers = [_worker(tmp_path / "farm", runner, worker_id=w)
               for w in ("wA", "wB")]
    threads = [threading.Thread(target=w.run) for w in workers]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert sorted(ran) == ids  # each job ran exactly once
    c = jq.counts()
    assert c["done"] == 4 and jq.drained(c)


# ---------------- chaos harness ----------------


def test_fault_seed_deterministic_and_fault_specific():
    assert fault_seed("0001-x", "crash_block") == fault_seed("0001-x",
                                                            "crash_block")
    assert fault_seed("0001-x", "crash_block") != fault_seed("0002-y",
                                                             "crash_block")
    assert fault_seed("0001-x", "crash_block") != fault_seed("0001-x",
                                                             "ckpt_raise")


def test_parse_faults_rejects_unknown():
    assert parse_faults("crash_block, ckpt_raise") == ("crash_block",
                                                       "ckpt_raise")
    with pytest.raises(ValueError, match="unknown chaos fault"):
        parse_faults("crash_block,typo_fault")


def test_chaos_fires_exactly_once_across_instances(tmp_path):
    d = str(tmp_path / "job")
    c1 = Chaos(("ckpt_raise",), "0000", d)
    assert c1.fire_once("ckpt_raise")
    assert not c1.fire_once("ckpt_raise")
    # a fresh instance (post-SIGKILL restart) sees the persisted marker
    c2 = Chaos(("ckpt_raise",), "0000", d)
    assert c2.fired("ckpt_raise")
    assert not c2.fire_once("ckpt_raise")
    # faults not armed never fire
    assert not c1.fire_once("enospc_events")


def test_chaos_crash_raise_at_seeded_block(tmp_path):
    job_id = "0000"
    c = Chaos(("crash_block",), job_id, str(tmp_path / "job"),
              crash_mode="raise")
    ordinal = c.crash_block_ordinal()
    for n in range(ordinal):
        c.on_block(0, n)  # below the ordinal: no fault
    with pytest.raises(SimulatedPreemption):
        c.on_block(0, ordinal)
    # replay after "restart": marker persists, no second crash
    c2 = Chaos(("crash_block",), job_id, str(tmp_path / "job"),
               crash_mode="raise")
    for n in range(5):
        c2.on_block(0, n)


def test_chaos_ckpt_proxy_raises_enospc_once(tmp_path):
    class FakeCk:
        def __init__(self):
            self.saves = []

        def save(self, *a):
            self.saves.append(a)

        def latest_step_info(self):
            return None

    c = Chaos(("ckpt_raise",), "0000", str(tmp_path / "job"))
    inner = FakeCk()
    proxy = c.wrap_checkpointer(inner)
    errors = 0
    for i in range(4):
        try:
            proxy.save(0, i, "state")
        except OSError as e:
            assert e.errno == 28
            errors += 1
    assert errors == 1 and len(inner.saves) == 3
    assert proxy.latest_step_info() is None  # delegation intact
    # once fired, wrapping is a pass-through (retry attempt saves cleanly)
    assert c.wrap_checkpointer(inner) is inner


def test_worker_chaos_crash_raise_then_retry_to_done(tmp_path):
    jq = JobQueue(str(tmp_path / "farm"))
    (job_id,) = jq.submit_spec({"axes": {}, "max_attempts": 3})

    def runner(job, ctx):
        for i in range(4):
            ctx.on_block_end(0, i, {})
        return {"rows": 1}

    summary = _worker(tmp_path / "farm", runner, chaos="crash_block",
                      crash_mode="raise").run()
    assert summary["failed"] == 1 and summary["done"] == 1
    job = jq.read_job(job_id)
    assert job["state"] == "done" and job["attempts"] == 2
    assert job["failures"][0]["kind"] == "preemption"
    assert os.path.exists(os.path.join(jq.job_dir(job_id),
                                       "chaos_crash_block.fired"))


def test_worker_chaos_enospc_events_never_fatal(tmp_path):
    jq = JobQueue(str(tmp_path / "farm"))
    (job_id,) = jq.submit_spec({"axes": {}, "max_attempts": 2})

    def runner(job, ctx):
        from dorpatch_tpu import observe
        for i in range(12):  # well past any seeded write budget
            with observe.span("farm.step", i=i):
                pass
        return {"rows": 1}

    summary = _worker(tmp_path / "farm", runner,
                      chaos="enospc_events").run()
    assert summary["done"] == 1  # telemetry loss is never fatal
    assert jq.read_job(job_id)["state"] == "done"


def test_worker_wedge_heartbeat_job_reclaimed_by_healthy_worker(tmp_path):
    """A live-zombie worker (heartbeat wedged, process alive) must lose its
    lease to a contender and abandon the job without committing state; a
    healthy worker then drains it."""
    farm = tmp_path / "farm"
    jq = JobQueue(str(farm))
    (job_id,) = jq.submit_spec({"axes": {}, "max_attempts": 3})
    contender = JobQueue(str(farm))

    def runner(job, ctx):
        ctx.on_block_end(0, 0, {})  # wedge fires: beats freeze here
        time.sleep(0.5)             # let the frozen heartbeat go stale
        stolen = contender.claim("wThief", ttl=0.2)
        assert stolen is not None and stolen["worker"] == "wThief"
        ctx.on_block_end(0, 1, {})  # renewal sees the thief -> LeaseLost
        raise AssertionError("unreachable: lease loss must abort the job")

    wedged = _worker(farm, runner, worker_id="wZ", chaos="wedge_heartbeat",
                     lease_ttl=0.3, heartbeat_interval=0.05)
    summary_z = wedged.run()
    assert summary_z["abandoned"] == 1 and summary_z.get("wedged") is True
    # the abandoned commit left the thief's record intact
    assert jq.read_job(job_id)["worker"] == "wThief"

    time.sleep(0.3)  # the thief never beats; its lease expires by ttl

    def ok_runner(job, ctx):
        return {"rows": 1}

    healthy = _worker(farm, ok_runner, worker_id="wH", lease_ttl=5.0)
    healthy.run()
    job = jq.read_job(job_id)
    assert job["state"] == "done" and job["worker"] == "wH"
    assert job["reclaims"] >= 1


# ---------------- fleet report ----------------


def _run_fleet(tmp_path):
    farm = str(tmp_path / "farm")
    jq = JobQueue(farm)
    jq.submit_spec(SPEC)

    def runner(job, ctx):
        for i in range(3):  # block boundaries: lets crash_block chaos fire
            ctx.on_block_end(0, i, {})
        if job["params"]["attack.dropout"] == 2 \
                and job["params"]["attack.patch_budget"] == 0.12:
            raise ValueError("bad grid point")
        from dorpatch_tpu.sweep import append_row
        append_row(ctx.result_dir, {
            "patch_budget": job["params"]["attack.patch_budget"],
            "density": 0.0, "structured": 1e-3,
            "robust_accuracy": 50.0, "certified_asr_pc": 25.0,
            "asr": 50.0, "point": 0,
        })
        return {"rows": 1}

    _worker(tmp_path / "farm", runner, chaos="crash_block",
            crash_mode="raise").run()
    return farm, jq


def test_summarize_fleet_accounting(tmp_path):
    farm, jq = _run_fleet(tmp_path)
    fleet = summarize_fleet(farm)
    assert fleet["counts"]["done"] == 3
    assert fleet["counts"]["quarantined"] == 1
    assert fleet["retries"] >= 1  # at least the chaos-crashed job retried
    assert fleet["failures_by_kind"].get("trace") == 1
    assert fleet["failures_by_kind"].get("preemption", 0) >= 1
    assert len(fleet["quarantined"]) == 1
    assert "ValueError" in fleet["quarantined"][0]["error"]
    assert len(fleet["points"]) == 3
    done = [j for j in fleet["jobs"] if j["state"] == "done"]
    assert all(j["run_ids"] for j in done)  # manifest chain present
    crashed = [j for j in fleet["jobs"] if j["attempts"] > 1]
    assert crashed and len(crashed[0]["run_ids"]) == crashed[0]["attempts"]
    assert summarize_fleet(str(tmp_path)) is None  # not a farm dir


def test_format_fleet_report_sections(tmp_path):
    farm, _ = _run_fleet(tmp_path)
    text = format_fleet_report(summarize_fleet(farm))
    assert "= DorPatch attack-sweep farm report =" in text
    assert "-- farm --" in text and "-- jobs --" in text
    assert "-- robust accuracy --" in text
    assert "quarantined" in text and "robust acc 50.0%" in text


def test_fleet_report_torn_rows_render_as_holes(tmp_path):
    """Torn / partial `rows.jsonl` files (a worker killed mid-append, a
    truncated copy) must degrade to explicit HOLE lines in the report, not
    a parse error — and a done job whose rows vanished entirely is a hole
    too."""
    farm, jq = _run_fleet(tmp_path)
    done = [j for j in jq.job_ids() if jq.read_job(j)["state"] == "done"]
    torn_dir = os.path.join(jq.job_dir(done[0]), "results")
    with open(os.path.join(torn_dir, "rows.jsonl"), "a") as fh:
        fh.write('{"patch_budget": 0.06, "robust_acc')  # killed mid-append
    missing_dir = os.path.join(jq.job_dir(done[1]), "results")
    os.remove(os.path.join(missing_dir, "rows.jsonl"))

    fleet = summarize_fleet(farm)  # must not raise
    by_id = {j["id"]: j for j in fleet["jobs"]}
    assert by_id[done[0]]["torn_rows"] == 1
    assert by_id[done[1]]["rows"] == 0

    text = format_fleet_report(fleet)
    assert "1 torn" in text
    assert "HOLE" in text and done[1] in text


def test_report_cli_dispatches_on_farm_dir(tmp_path, capsys):
    from dorpatch_tpu.observe import report as report_cli

    farm, _ = _run_fleet(tmp_path)
    assert report_cli.main([farm]) == 0
    out = capsys.readouterr().out
    assert "-- farm --" in out and "attempts histogram" in out
    assert report_cli.main([farm, "--json"]) == 0
    fleet = json.loads(capsys.readouterr().out)
    assert fleet["counts"]["done"] == 3


def test_farm_cli_submit_status_roundtrip(tmp_path, capsys):
    from dorpatch_tpu.farm.__main__ import main as farm_main

    spec_path = tmp_path / "spec.json"
    spec_path.write_text(json.dumps(SPEC))
    farm = str(tmp_path / "farm")
    assert farm_main(["submit", farm, "--spec", str(spec_path)]) == 0
    assert farm_main(["status", farm]) == 0
    out = capsys.readouterr().out
    assert '"jobs": 4' in out and '"pending": 4' in out


# ---------------- end-to-end crash-resume parity (real attack) ----------------


@pytest.mark.slow
def test_farm_crash_resume_bit_identical_to_uninterrupted(tmp_path):
    """Acceptance: under chaos (crash at a seeded block boundary), a farm
    completes the job with final patch artifacts identical to an
    uninterrupted run, and the retry resumed from the carry checkpoint
    rather than restarting."""
    import numpy as np

    from dorpatch_tpu.sweep import run_sweep

    attack = AttackConfig(
        sampling_size=4, max_iterations=4, sweep_interval=2,
        switch_iteration=2, dropout=1, dropout_sizes=(0.06,), basic_unit=4,
    )
    cfg = ExperimentConfig(
        dataset="cifar10", base_arch="resnet18", img_size=32, batch_size=2,
        synthetic_data=True, attack=attack,
    )

    # uninterrupted oracle, artifacts on disk
    control_dir = str(tmp_path / "control")
    run_sweep(cfg, patch_budgets=(0.1,), densities=(0.0,),
              structureds=(1e-3,), defense_ratio=0.06, verbose=False,
              result_dir=control_dir)

    farm = str(tmp_path / "farm")
    jq = JobQueue(farm)
    (job_id,) = jq.submit_spec({
        "base": {"dataset": "cifar10", "base_arch": "resnet18",
                 "img_size": 32, "batch_size": 2, "synthetic_data": True,
                 "attack": {"sampling_size": 4, "max_iterations": 4,
                            "sweep_interval": 2, "switch_iteration": 2,
                            "dropout": 1, "dropout_sizes": [0.06],
                            "basic_unit": 4}},
        "axes": {"attack.patch_budget": [0.1]},
        "sweep": {"densities": [0.0], "structureds": [1e-3],
                  "defense_ratio": 0.06},
        "max_attempts": 3,
    })
    worker = FarmWorker(farm, worker_id="wE", lease_ttl=30.0,
                        poll_interval=0.05, heartbeat_interval=0.5,
                        backoff_base=0.05, backoff_cap=0.2,
                        chaos="crash_block", crash_mode="raise")
    summary = worker.run()
    assert summary["done"] == 1 and summary["failed"] == 1

    job = jq.read_job(job_id)
    assert job["state"] == "done" and job["attempts"] == 2
    assert job["result"]["rows"] == 1
    # the retry resumed from the crashed attempt's carry snapshot
    assert job["result"]["resumed_points"] == 1

    result_dir = os.path.join(jq.job_dir(job_id), "results")
    for name in ("point_000_mask.npy", "point_000_pattern.npy"):
        got = np.load(os.path.join(result_dir, name))
        want = np.load(os.path.join(control_dir, name))
        np.testing.assert_array_equal(got, want)

    fleet = summarize_fleet(farm)
    assert fleet["retries"] == 1
    text = format_fleet_report(fleet)
    assert "resumed" in text
