"""The sharding & collectives auditor (analysis/comms.py, DP600-DP603):
the static comm-cost model (bound-axis pricing, axis_index_groups holes,
scan trip multipliers), per-rule positive/negative fixture programs
including the production masked-fill mesh wrapper as the DP603 shard-local
proof, the comm vector folded into the baseline tier (a planted collective
regression must trip DP301 NAMING the collective), the `--baseline update`
topology refusal, the shipped tree staying clean, suppression semantics,
and the CLI `--comms` / `--format sarif` exit-code contract."""

import json
import pathlib
import sys

import pytest

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from dorpatch_tpu.analysis import baseline, comms, program
from dorpatch_tpu.analysis.cli import main as cli_main
from dorpatch_tpu.analysis.entrypoints import EntryPoint, abstractify

REPO = pathlib.Path(__file__).resolve().parents[1]
FIXTURES = REPO / "tests" / "fixtures" / "analysis"
sys.path.insert(0, str(FIXTURES))

import comms_programs  # noqa: E402  (fixture module, see path insert)


def rule_ids(findings):
    return sorted({f.rule_id for f in findings})


def traced(ep):
    ctx, errs = program.trace_entrypoint(ep)
    assert ctx is not None, errs
    return ctx


# ---------- the comm-cost model ----------

def test_comm_cost_prices_bound_axis_psum():
    """operand bytes x axis size, on the PER-SHARD aval: (n, 4) f32 over
    an n-device data axis is a (1, 4) shard — 16 bytes x n participants."""
    cc = comms.comm_cost(traced(comms_programs.priced_psum()).jaxpr)
    n = jax.device_count()
    assert cc["comm_bytes"] == 16 * n
    assert cc["by_collective"] == {"psum": 16 * n}
    assert not cc["unpriced"]


def test_comm_cost_grouped_psum_is_unpriced():
    cc = comms.comm_cost(traced(comms_programs.grouped_psum()).jaxpr)
    assert cc["unpriced"], "axis_index_groups must leave a pricing hole"
    assert any("axis_index_groups" in why for _, why in cc["unpriced"])


def test_comm_cost_scan_multiplies_by_trip_count():
    from jax.experimental.shard_map import shard_map
    from jax.sharding import PartitionSpec as P

    mesh = comms_programs._mesh1d()

    def body(x):
        def step(c, _):
            return c + lax.psum(c, "data"), None

        y, _ = lax.scan(step, x, None, length=5)
        return y

    ep = EntryPoint(
        name="fx.scan_psum",
        fn=jax.jit(shard_map(body, mesh=mesh, in_specs=P("data"),
                             out_specs=P("data"), check_rep=False)),
        args=(abstractify(jnp.zeros((jax.device_count(), 4))),))
    cc = comms.comm_cost(traced(ep).jaxpr)
    assert cc["comm_bytes"] == 5 * 16 * jax.device_count()


def test_comm_cost_zero_for_collective_free_program():
    ep = EntryPoint(name="fx.none", fn=jax.jit(lambda x: x * 2.0),
                    args=(abstractify(jnp.zeros((4,))),))
    cc = comms.comm_cost(traced(ep).jaxpr)
    assert cc == {"comm_bytes": 0, "by_collective": {}, "unpriced": []}


# ---------- per-rule positives / negatives ----------

@pytest.mark.parametrize("rule_id", sorted(comms_programs.PER_RULE))
def test_comms_rule_positive_fires(rule_id):
    positives, _ = comms_programs.PER_RULE[rule_id]
    for pos in positives:
        findings = comms.audit_entrypoint(pos())
        assert rule_id in rule_ids(findings), \
            f"{rule_id} did not fire on {pos.__name__}: " \
            f"{[f.render() for f in findings]}"


@pytest.mark.parametrize("rule_id", sorted(comms_programs.PER_RULE))
def test_comms_rule_negative_clean(rule_id):
    _, neg = comms_programs.PER_RULE[rule_id]
    findings = comms.audit_entrypoint(neg())
    assert rule_id not in rule_ids(findings), \
        f"false positive on {neg.__name__}: " \
        f"{[f.render() for f in findings]}"


def test_dp603_production_mesh_wrapper_is_the_clean_proof():
    """The whole point of DP603: `ops.masked_fill` under its shard_map
    wrapper — forward kernel per shard, backward kernel feeding (not fed
    by) the mask-axis psum — audits completely clean."""
    assert not comms.audit_entrypoint(comms_programs.shard_local_kernel())


def test_dp603_names_the_kernel():
    (f,) = [f for f in comms.audit_entrypoint(
        comms_programs.bare_kernel_under_mesh())
        if f.rule_id == "DP603"]
    assert "pallas" in f.message.lower() or "kernel" in f.message.lower()


# ---------- suppression ----------

def test_comms_allowlist_glob_suppresses():
    findings = comms.audit_entrypoint(
        comms_programs.grouped_psum(),
        allow={"fx.grouped_*": {"DP600": "fixture"}})
    assert "DP600" not in rule_ids(findings)


# ---------- the baseline fold: comm vectors + DP301 ----------

def _psum_ep():
    from jax.experimental.shard_map import shard_map
    from jax.sharding import PartitionSpec as P

    mesh = comms_programs._mesh1d()
    fn = jax.jit(shard_map(lambda x: lax.psum(x, "data"), mesh=mesh,
                           in_specs=P("data"), out_specs=P(),
                           check_rep=False))
    return EntryPoint(
        name="fx.comm_reg", fn=fn,
        args=(abstractify(jnp.zeros((jax.device_count(), 256))),))


def test_baseline_entry_carries_comm_vector():
    entry, errs = baseline.snapshot_entrypoint(_psum_ep(), compiled=False)
    assert not errs
    n = jax.device_count()
    assert entry["cost"]["comm_bytes"] == 1024 * n
    assert entry["comm"] == {"psum": 1024 * n}


def test_planted_comm_regression_trips_dp301_naming_collective():
    """The acceptance planted regression: halve the BASELINE's psum bytes
    (equivalently, the live program doubled its collective traffic) — the
    existing DP301 gate must fire on the comm_bytes metric and name psum
    as the dominant regressing collective."""
    ep = _psum_ep()
    data, errs = baseline.build_baseline([ep], compiled=False)
    assert not errs
    doctored = json.loads(json.dumps(data))
    entry = doctored["entries"]["fx.comm_reg"]
    entry["cost"]["comm_bytes"] = entry["cost"]["comm_bytes"] / 2
    entry["comm"]["psum"] = entry["comm"]["psum"] / 2
    findings = baseline.check_entrypoints([ep], doctored, compiled=False)
    f301 = [f for f in findings if f.rule_id == "DP301"]
    assert f301, [f.render() for f in findings]
    assert "comm bytes" in f301[0].message
    assert "psum" in f301[0].message


def test_shipped_baselines_carry_comm_vectors():
    """Every shipped entry has the comm_bytes cost; the one production
    collective (the sharded masked-fill gradient's mask-axis psum) is
    priced nonzero; the meshed kernel-tier programs price to ZERO — that
    zero IS the recorded shard-locality claim."""
    data = baseline.load_baseline()
    assert data is not None
    entries = data["entries"]
    missing = [n for n, e in entries.items()
               if "comm_bytes" not in e.get("cost", {})]
    assert not missing, missing
    grad = entries["ops.masked_fill.sharded_grad"]
    assert grad["cost"]["comm_bytes"] > 0
    assert set(grad["comm"]) == {"psum"}
    for kname in ("stem", "token"):
        e = entries[f"ops.kernel_tier.{kname}.phase1.kernel.mesh"]
        assert e["cost"]["comm_bytes"] == 0
        assert e["comm"] == {}


# ---------- --baseline update topology refusal ----------

def test_baseline_update_refuses_unenumerable_mesh_entries(
        tmp_path, monkeypatch, capsys):
    """A baseline holding .mesh entries must NOT be regenerable on a
    single-device host: update would silently drop the mesh program bank
    (and its comm vectors) and turn the gate vacuous. Usage error, file
    untouched, actionable message."""
    original = json.dumps({"entries": {
        "ops.kernel_tier.stem.phase1.kernel.mesh": {
            "fingerprint": "x", "cost": {"comm_bytes": 0}}}})
    path = tmp_path / "baselines.json"
    path.write_text(original, encoding="utf-8")
    monkeypatch.setattr(jax, "device_count", lambda: 1)
    rc = cli_main(["--baseline", "update", "--baseline-file", str(path),
                   "--entrypoints", "baseline_programs:clean_entrypoints"])
    err = capsys.readouterr().err
    assert rc == 2
    assert path.read_text(encoding="utf-8") == original
    assert "NOT written" in err
    assert "xla_force_host_platform_device_count" in err
    assert "ops.kernel_tier.stem.phase1.kernel.mesh" in err


# ---------- the shipped tree stays clean ----------

@pytest.mark.slow
def test_shipped_tree_comms_clean():
    """Covered in CI by the run_tests.sh `--comms` gate (same audit over
    the same registry); marked slow like its trace/baseline siblings."""
    findings = comms.audit_production()
    assert not findings, "\n".join(f.render() for f in findings)


# ---------- CLI ----------

def test_cli_comms_exit_codes(capsys):
    rc = cli_main(["--comms", "--entrypoints",
                   "comms_programs:clean_entrypoints"])
    assert rc == 0
    assert "clean" in capsys.readouterr().err
    rc = cli_main(["--comms", "--entrypoints",
                   "comms_programs:bad_entrypoints", "--format", "json"])
    assert rc == 1
    out = capsys.readouterr().out.strip().splitlines()
    rules = {json.loads(line)["rule"] for line in out if line}
    assert rules == {"DP600", "DP601", "DP602", "DP603"}


def test_cli_comms_select(capsys):
    rc = cli_main(["--comms", "--select", "DP601", "--entrypoints",
                   "comms_programs:bad_entrypoints"])
    assert rc == 1
    out = capsys.readouterr().out
    assert "DP601" in out and "DP600" not in out
    assert cli_main(["--comms", "--select", "DP600,DP601,DP602,DP603",
                     "--entrypoints",
                     "comms_programs:clean_entrypoints"]) == 0
    # cross-wing IDs stay a loud usage error, not a vacuous pass
    assert cli_main(["--comms", "--select", "DP301", "--entrypoints",
                     "comms_programs:clean_entrypoints"]) == 2


def test_cli_sarif_output(capsys):
    rc = cli_main(["--comms", "--entrypoints",
                   "comms_programs:bad_entrypoints", "--format", "sarif"])
    assert rc == 1
    doc = json.loads(capsys.readouterr().out)
    assert doc["version"] == "2.1.0"
    assert "sarif-schema-2.1.0" in doc["$schema"]
    run = doc["runs"][0]
    rules = run["tool"]["driver"]["rules"]
    assert {r["id"] for r in rules} == {"DP600", "DP601", "DP602", "DP603"}
    assert run["results"], "positive fixtures must produce results"
    for res in run["results"]:
        assert rules[res["ruleIndex"]]["id"] == res["ruleId"]
        loc = res["locations"][0]["physicalLocation"]
        assert loc["region"]["startLine"] >= 1
        assert res["level"] == "error"


def test_cli_sarif_clean_is_empty_results(capsys):
    rc = cli_main(["--comms", "--entrypoints",
                   "comms_programs:clean_entrypoints", "--format", "sarif"])
    assert rc == 0
    doc = json.loads(capsys.readouterr().out)
    assert doc["runs"][0]["results"] == []


def test_cli_sarif_serves_other_modes_too(capsys, tmp_path):
    """One shared serializer: the lint wing emits the same SARIF shape."""
    bad = tmp_path / "bad.py"
    bad.write_text("import jax\n\n\ndef f(x):\n    return jax.jit(x)\n",
                   encoding="utf-8")
    rc = cli_main([str(tmp_path), "--format", "sarif"])
    out = capsys.readouterr().out
    doc = json.loads(out)
    assert doc["version"] == "2.1.0"
    assert rc in (0, 1)
