"""Fused GroupNorm+ReLU kernel (`ops/fused_gn.py`): parity vs flax.

The kernel replaces flax `GroupNorm(dtype=f32)` + ReLU + cast inside the
ResNetV2 victim, so its forward AND custom-vjp backward must match the flax
composition on every path the attack differentiates (input images) and the
paths training would need (scale/bias). Pallas runs in interpreter mode on
CPU; numerics are identical to the compiled kernel up to reduction order.
"""

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dorpatch_tpu.ops import fused_gn


def _flax_gn_relu(x, scale, bias, num_groups):
    """The exact composition GroupNormRelu(impl="flax") computes."""
    gn = nn.GroupNorm(num_groups=num_groups, epsilon=1e-5, dtype=jnp.float32)
    y = gn.apply({"params": {"scale": scale, "bias": bias}}, x)
    return nn.relu(y).astype(x.dtype)


def _rand(key, shape, dtype):
    return jax.random.normal(key, shape, jnp.float32).astype(dtype)


@pytest.mark.parametrize("dtype,atol", [(jnp.float32, 1e-5), (jnp.bfloat16, 0.02)])
@pytest.mark.parametrize("impl", ["interpret", "jnp"])
def test_forward_matches_flax(dtype, atol, impl):
    k = jax.random.PRNGKey(0)
    x = _rand(k, (2, 6, 5, 64), dtype)
    scale = _rand(jax.random.PRNGKey(1), (64,), jnp.float32) * 0.5 + 1.0
    bias = _rand(jax.random.PRNGKey(2), (64,), jnp.float32) * 0.1
    want = _flax_gn_relu(x, scale, bias, 32)
    got = fused_gn.gn_relu(x, scale, bias, 32, impl=impl)
    assert got.dtype == x.dtype
    np.testing.assert_allclose(np.asarray(got, np.float32),
                               np.asarray(want, np.float32), atol=atol)


@pytest.mark.parametrize("impl", ["interpret", "jnp"])
def test_grads_match_flax(impl):
    """d/d(x, scale, bias) of a weighted sum of outputs: the custom VJP
    (ReLU gate + group-statistic backward) against flax autodiff."""
    k = jax.random.PRNGKey(3)
    x = _rand(k, (2, 4, 4, 64), jnp.float32)
    scale = _rand(jax.random.PRNGKey(4), (64,), jnp.float32) * 0.5 + 1.0
    bias = _rand(jax.random.PRNGKey(5), (64,), jnp.float32) * 0.1
    w = _rand(jax.random.PRNGKey(6), (2, 4, 4, 64), jnp.float32)

    def loss(fn):
        return lambda x, s, b: jnp.sum(fn(x, s, b) * w)

    want = jax.grad(loss(lambda x, s, b: _flax_gn_relu(x, s, b, 32)),
                    argnums=(0, 1, 2))(x, scale, bias)
    got = jax.grad(loss(lambda x, s, b: fused_gn.gn_relu(x, s, b, 32, impl=impl)),
                   argnums=(0, 1, 2))(x, scale, bias)
    for g, wnt in zip(got, want):
        np.testing.assert_allclose(np.asarray(g), np.asarray(wnt),
                                   atol=1e-4, rtol=1e-4)


def test_input_grad_bf16():
    """The attack differentiates w.r.t. bf16 images: cotangent dtype must
    follow the primal and values must track the flax path."""
    x = _rand(jax.random.PRNGKey(7), (2, 4, 4, 64), jnp.bfloat16)
    scale = jnp.ones((64,), jnp.float32)
    bias = jnp.zeros((64,), jnp.float32)

    def loss(fn):
        return lambda x: jnp.sum(fn(x).astype(jnp.float32) ** 2)

    want = jax.grad(loss(lambda x: _flax_gn_relu(x, scale, bias, 32)))(x)
    got = jax.grad(loss(
        lambda x: fused_gn.gn_relu(x, scale, bias, 32, impl="interpret")))(x)
    assert got.dtype == jnp.bfloat16
    np.testing.assert_allclose(np.asarray(got, np.float32),
                               np.asarray(want, np.float32), atol=0.05)


def test_model_level_parity_and_param_tree():
    """`GroupNormRelu(impl="interpret")` inside the full ResNetV2 block
    structure: identical param tree (checkpoint compatibility) and matching
    logits + input grads vs the flax impl."""
    from dorpatch_tpu.models.resnetv2 import ResNetV2

    x = jax.random.uniform(jax.random.PRNGKey(8), (2, 32, 32, 3))
    flax_model = ResNetV2(num_classes=7, layers=(1, 1), gn_impl="flax")
    fused_model = ResNetV2(num_classes=7, layers=(1, 1), gn_impl="interpret")
    params = flax_model.init(jax.random.PRNGKey(9), x)

    flat_a = jax.tree_util.tree_structure(params)
    flat_b = jax.tree_util.tree_structure(
        fused_model.init(jax.random.PRNGKey(9), x))
    assert flat_a == flat_b

    la = flax_model.apply(params, x)
    lb = fused_model.apply(params, x)
    np.testing.assert_allclose(np.asarray(la), np.asarray(lb), atol=1e-4)

    ga = jax.grad(lambda x: flax_model.apply(params, x).sum())(x)
    gb = jax.grad(lambda x: fused_model.apply(params, x).sum())(x)
    np.testing.assert_allclose(np.asarray(ga), np.asarray(gb),
                               atol=1e-3, rtol=1e-3)


def test_gn_preserve_dtype_matches_flax():
    """`gn_preserve_dtype` (f32 statistics, input-dtype normalize): values
    track flax GroupNorm at each dtype's resolution, output keeps the
    input dtype — the contract the bf16 certify bank's victims rely on."""
    k = jax.random.PRNGKey(41)
    scale = _rand(jax.random.PRNGKey(42), (64,), jnp.float32) * 0.5 + 1.0
    bias = _rand(jax.random.PRNGKey(43), (64,), jnp.float32) * 0.1
    for dtype, atol in ((jnp.float32, 1e-5), (jnp.bfloat16, 0.06)):
        x = _rand(k, (2, 6, 5, 64), dtype)
        want = nn.GroupNorm(num_groups=32, epsilon=1e-5).apply(
            {"params": {"scale": scale, "bias": bias}},
            x.astype(jnp.float32))
        got = fused_gn.gn_preserve_dtype(x, scale, bias, 32, eps=1e-5)
        assert got.dtype == dtype
        np.testing.assert_allclose(np.asarray(got, np.float32),
                                   np.asarray(want), atol=atol)


def test_gn_preserve_dtype_no_big_f32_slabs():
    """The point of the function: at bf16 the jitted jaxpr materializes NO
    large f32 tensor outside the statistics reduction (flax's GroupNorm
    runs the whole normalize chain in f32 — the DP208 leak this replaces).
    Every full-slab f32 equation output must be a declared upcast or feed
    only reductions."""
    x = jnp.zeros((4, 16, 16, 64), jnp.bfloat16)
    scale = jnp.ones((64,), jnp.float32)
    bias = jnp.zeros((64,), jnp.float32)
    jaxpr = jax.make_jaxpr(
        lambda x: fused_gn.gn_preserve_dtype(x, scale, bias, 8))(x).jaxpr
    slab = x.size
    reducers = {"reduce_sum", "reduce_max"}
    consumers = {}
    for eqn in jaxpr.eqns:
        for v in eqn.invars:
            if hasattr(v, "aval"):
                consumers.setdefault(id(v), []).append(eqn.primitive.name)
    for eqn in jaxpr.eqns:
        if eqn.primitive.name == "convert_element_type":
            continue
        for out in eqn.outvars:
            if out.aval.dtype == jnp.float32 and out.aval.size >= slab:
                used_by = consumers.get(id(out), [])
                assert used_by and all(u in reducers for u in used_by), (
                    f"{eqn.primitive.name} materializes a full f32 slab "
                    f"consumed by {used_by}")


def test_groupnorm8_f32_bit_identical_to_flax():
    """`GroupNorm8` must be invisible at f32: same param tree as an inline
    `nn.GroupNorm(8)` and bit-identical output (the functional apply runs
    flax's own code — the seed's checkpoints and numerics are preserved)."""
    from dorpatch_tpu.models.small import GroupNorm8

    x = jax.random.normal(jax.random.PRNGKey(51), (2, 8, 8, 64))
    ref = nn.GroupNorm(num_groups=8)
    ours = GroupNorm8()
    p_ref = ref.init(jax.random.PRNGKey(0), x)
    p_ours = ours.init(jax.random.PRNGKey(0), x)
    assert jax.tree_util.tree_structure(p_ref) \
        == jax.tree_util.tree_structure(p_ours)
    np.testing.assert_array_equal(np.asarray(ref.apply(p_ref, x)),
                                  np.asarray(ours.apply(p_ref, x)))
    # and at bf16 it swaps to the dtype-preserving path
    yb = ours.apply(p_ref, x.astype(jnp.bfloat16))
    assert yb.dtype == jnp.bfloat16


def test_auto_dispatch_gates():
    """"auto" picks Pallas only on a single-device TPU backend AND when the
    *backward's* live set (streamed x/dy/dx blocks, double-buffered, plus
    f32 in-kernel slab temporaries — ADVICE r03) fits the VMEM budget, so
    risky geometries fall back to XLA instead of failing Mosaic on-chip."""
    from dorpatch_tpu.ops import _backend

    # this test env is CPU -> never Pallas
    assert fused_gn.auto_pallas() is False
    assert fused_gn.auto_pallas((8, 56, 56, 256)) is False

    orig = _backend.is_tpu_backend
    _backend.is_tpu_backend = lambda: True
    try:
        on_tpu = fused_gn.auto_pallas()
        # device_count is 8 in this suite (virtual mesh) -> still False
        assert on_tpu == (jax.device_count() == 1)
        import unittest.mock as mock

        with mock.patch.object(jax, "device_count", return_value=1):
            # 200k-elem slabs fit untiled at any dtype
            assert fused_gn.auto_pallas((8, 56, 56, 64), jnp.float32)
            assert fused_gn.auto_pallas((8, 14, 14, 1024), jnp.bfloat16)
            # 401k-elem slabs: bf16 untiled; f32 via the tiled backward
            assert fused_gn.auto_pallas((8, 56, 56, 128), jnp.bfloat16)
            assert fused_gn.auto_pallas((8, 56, 56, 128), jnp.float32)
            # largest RN50 slab (803k elems): admitted at every dtype via
            # the tiled plans (bf16: fwd whole-slab + bwd 2 tiles; f32:
            # fwd 2 tiles + bwd 4 tiles)
            assert fused_gn.auto_pallas((8, 56, 56, 256), jnp.bfloat16)
            assert fused_gn.auto_pallas((8, 56, 56, 256), jnp.float32)
            assert fused_gn.auto_pallas((8, 96, 96, 256))  # 9 MB slab, tiled
            # pathological HW factorization (97^2: no aligned divisor):
            # no feasible plan at any dtype -> XLA path
            assert not fused_gn.auto_pallas((8, 97, 97, 1024), jnp.float32)
    finally:
        _backend.is_tpu_backend = orig


def test_vmem_estimates_and_bwd_plan():
    """The admission formulas: double-buffered streamed blocks at the input
    dtype + f32 in-kernel temporaries; the plan tiles HW on Mosaic-aligned
    row boundaries only when the untiled live set busts the budget."""
    elems = 56 * 56 * 128
    assert fused_gn._bwd_vmem_bytes(elems, 2) == elems * (6 * 2 + 16)
    assert fused_gn._bwd_vmem_bytes(elems, 4) == elems * (6 * 4 + 16)
    assert fused_gn._fwd_vmem_bytes(elems, 2) == elems * (4 * 2 + 8)
    # small slabs: whole-slab kernel
    assert fused_gn._bwd_plan(56 * 56, 64, 4) == 1
    # largest RN50 slab at bf16: 2 tiles of 1568 rows (16-row aligned)
    assert fused_gn._bwd_plan(56 * 56, 256, 2) == 2
    # f32 401k slab: tiled too (8-row alignment admits t=2)
    assert fused_gn._bwd_plan(56 * 56, 128, 4) == 2
    # f32 largest slab: forward needs 2 tiles, backward 4
    assert fused_gn._fwd_plan(56 * 56, 256, 4) == 2
    assert fused_gn._bwd_plan(56 * 56, 256, 4) == 4
    # big slab with pathological factorization (97^2 rows: the only
    # divisor <= 256 is 97, not sublane-aligned): no feasible plan
    assert fused_gn._bwd_plan(97 * 97, 1024, 4) is None
    assert fused_gn._fwd_plan(97 * 97, 1024, 4) is None


@pytest.mark.parametrize("dtype,tol", [(jnp.float32, 1e-5), (jnp.bfloat16, 0.02)])
def test_tiled_forward_matches_reference(dtype, tol):
    """`_pallas_fwd_tiled` (two-pass group sums + tiled normalize) against
    the jnp reference, plus its mean/rstd residuals against the untiled
    kernel (the custom VJP consumes them)."""
    k = jax.random.PRNGKey(21)
    n, h, w, c, g = 2, 8, 8, 64, 32
    x = _rand(k, (n, h, w, c), dtype)
    scale = _rand(jax.random.PRNGKey(22), (c,), jnp.float32) * 0.5 + 1.0
    bias = _rand(jax.random.PRNGKey(23), (c,), jnp.float32) * 0.1

    want = fused_gn.gn_relu_reference(x, scale, bias, g)
    y, mean, rstd = fused_gn._pallas_fwd_tiled(x, scale, bias, g, 1e-5,
                                               tiles=4, interpret=True)
    np.testing.assert_allclose(np.asarray(y, np.float32),
                               np.asarray(want, np.float32), atol=tol)
    assert y.dtype == x.dtype
    _, mean_u, rstd_u = fused_gn._pallas_fwd(x, scale, bias, g, 1e-5, True)
    np.testing.assert_allclose(np.asarray(mean), np.asarray(mean_u), atol=1e-5)
    np.testing.assert_allclose(np.asarray(rstd), np.asarray(rstd_u), atol=1e-4)


@pytest.mark.parametrize("dtype,tol", [(jnp.float32, 1e-4), (jnp.bfloat16, 0.05)])
def test_tiled_backward_matches_reference(dtype, tol):
    """`_pallas_bwd_tiled` (two-pass, HW-tiled) against autodiff of the jnp
    reference: dx, dscale, dbias. Run in interpreter mode on a shape whose
    plan would NOT tile (so this exercises the tiled math itself via direct
    call, independent of the admission logic)."""
    k = jax.random.PRNGKey(11)
    n, h, w, c, g = 2, 8, 8, 64, 32
    x = _rand(k, (n, h, w, c), dtype)
    scale = _rand(jax.random.PRNGKey(12), (c,), jnp.float32) * 0.5 + 1.0
    bias = _rand(jax.random.PRNGKey(13), (c,), jnp.float32) * 0.1
    dy = _rand(jax.random.PRNGKey(14), (n, h, w, c), dtype)

    def ref_loss(x, s, b):
        return jnp.sum(
            fused_gn.gn_relu_reference(x, s, b, g).astype(jnp.float32)
            * dy.astype(jnp.float32))

    want = jax.grad(ref_loss, argnums=(0, 1, 2))(x, scale, bias)

    _, mean, rstd = fused_gn._pallas_fwd(x, scale, bias, g, 1e-5, True)
    got = fused_gn._pallas_bwd_tiled(x, dy, scale, bias, mean, rstd, g,
                                     tiles=4, interpret=True)
    for a, b, name in zip(got, want, ("dx", "dscale", "dbias")):
        np.testing.assert_allclose(
            np.asarray(a, np.float32), np.asarray(b, np.float32),
            atol=tol, rtol=tol, err_msg=name)
    assert got[0].dtype == x.dtype


def test_bwd_dispatch_picks_tiled_plan(monkeypatch):
    """`_pallas_bwd` routes through the tiled path when the plan says so."""
    calls = {}
    orig = fused_gn._pallas_bwd_tiled

    def spy(*args, **kw):
        calls["tiles"] = kw.get("tiles") or args[7]
        return orig(*args, **kw)

    monkeypatch.setattr(fused_gn, "_pallas_bwd_tiled", spy)
    monkeypatch.setattr(fused_gn, "_bwd_plan", lambda hw, c, i: 4)
    n, h, w, c, g = 1, 8, 8, 64, 32
    x = _rand(jax.random.PRNGKey(0), (n, h, w, c), jnp.float32)
    dy = jnp.ones_like(x)
    scale, bias = jnp.ones((c,)), jnp.zeros((c,))
    _, mean, rstd = fused_gn._pallas_fwd(x, scale, bias, g, 1e-5, True)
    fused_gn._pallas_bwd(x, dy, scale, bias, mean, rstd, g, True)
    assert calls["tiles"] == 4


def test_forward_and_grad_match_torch_oracle():
    """Cross-framework oracle: torch.nn.GroupNorm(32, eps=1e-5) + ReLU.
    Catches any systematic error shared by the kernel and its flax twin
    (the timm victims this kernel serves are converted torch models)."""
    import torch

    x = np.random.RandomState(0).randn(2, 5, 6, 64).astype(np.float32)
    scale = np.linspace(0.5, 1.5, 64, dtype=np.float32)
    bias = np.linspace(-0.2, 0.2, 64, dtype=np.float32)

    xt = torch.tensor(np.moveaxis(x, -1, 1), requires_grad=True)  # NCHW
    gn = torch.nn.GroupNorm(32, 64, eps=1e-5)
    with torch.no_grad():
        gn.weight.copy_(torch.tensor(scale))
        gn.bias.copy_(torch.tensor(bias))
    yt = torch.relu(gn(xt))
    yt.sum().backward()
    want_y = np.moveaxis(yt.detach().numpy(), 1, -1)
    want_gx = np.moveaxis(xt.grad.numpy(), 1, -1)

    got_y = fused_gn.gn_relu(jnp.asarray(x), jnp.asarray(scale),
                             jnp.asarray(bias), 32, impl="interpret")
    got_gx = jax.grad(lambda x: jnp.sum(fused_gn.gn_relu(
        x, jnp.asarray(scale), jnp.asarray(bias), 32, impl="interpret")))(
        jnp.asarray(x))
    np.testing.assert_allclose(np.asarray(got_y), want_y, atol=1e-4)
    np.testing.assert_allclose(np.asarray(got_gx), want_gx, atol=1e-4)


def test_attack_step_composition_with_remat_policies():
    """The production composition: the kernel's custom VJP under jax.grad,
    inside the attack's jitted scan block, wrapped by jax.checkpoint with
    each remat policy. Asserts the block runs and the stepped state matches
    the flax-GN victim's (same params, same rng)."""
    from dorpatch_tpu import losses
    from dorpatch_tpu import masks as masks_lib
    from dorpatch_tpu.attack import DorPatch
    from dorpatch_tpu.config import AttackConfig
    from dorpatch_tpu.models.resnetv2 import ResNetV2

    img, b = 16, 1
    x = jax.random.uniform(jax.random.PRNGKey(0), (b, img, img, 3))
    flax_model = ResNetV2(num_classes=4, layers=(1,), gn_impl="flax")
    params = flax_model.init(jax.random.PRNGKey(1), x)
    fused_model = ResNetV2(num_classes=4, layers=(1,), gn_impl="interpret")

    universe = jnp.asarray(masks_lib.dropout_universe(img, 1, [0.12]))
    y = jnp.zeros((b,), jnp.int32)
    lv = jnp.mean(losses.local_variance(x)[0], axis=-1)

    patterns = []
    for model, policy in ((flax_model, "full"), (fused_model, "full"),
                          (fused_model, "conv")):
        cfg = AttackConfig(sampling_size=2, max_iterations=2, remat="on",
                           remat_policy=policy)
        apply_fn = lambda p, xx, m=model: m.apply(params, xx)
        atk = DorPatch(apply_fn, None, 4, cfg)  # remat=None: follow cfg
        state = atk._init_state(jax.random.PRNGKey(2), x, y, False,
                                universe.shape[0])
        block = atk._get_block(0, img, 2)
        out = block(state, x, lv, universe)
        patterns.append(np.asarray(out.adv_pattern, np.float32))
    np.testing.assert_allclose(patterns[1], patterns[0], atol=2e-2)
    np.testing.assert_allclose(patterns[2], patterns[1], atol=2e-2)


def test_invalid_args():
    x = jnp.zeros((1, 2, 2, 48))
    with pytest.raises(ValueError):
        fused_gn.gn_relu(x, jnp.ones((48,)), jnp.zeros((48,)), 32)
    x = jnp.zeros((1, 2, 2, 64))
    with pytest.raises(ValueError):
        fused_gn.gn_relu(x, jnp.ones((64,)), jnp.zeros((64,)), 32, impl="bogus")


def test_interpret_bypasses_vmem_plan():
    """impl="interpret" must work even on slabs with NO feasible VMEM plan
    (the interpreter has no VMEM): hw=97*97 has no sublane-aligned tiling,
    so auto_pallas rejects it, but an explicit interpreter call computes."""
    n, h, w, c, g = 1, 97, 97, 64, 32
    assert fused_gn._bwd_plan(h * w, c, 4) is None
    x = _rand(jax.random.PRNGKey(31), (n, h, w, c), jnp.float32)
    scale, bias = jnp.ones((c,)), jnp.zeros((c,))
    want = fused_gn.gn_relu_reference(x, scale, bias, g)
    got, vjp = jax.vjp(
        lambda x: fused_gn.gn_relu(x, scale, bias, g, impl="interpret"), x)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=1e-5)
    (dx,) = vjp(jnp.ones_like(got))
    dx_ref = jax.grad(lambda x: jnp.sum(
        fused_gn.gn_relu_reference(x, scale, bias, g)))(x)
    np.testing.assert_allclose(np.asarray(dx), np.asarray(dx_ref),
                               atol=1e-4, rtol=1e-4)
