"""Offline report CLI (`python -m dorpatch_tpu.observe.report`) against the
checked-in fixture results dir, plus the tier-1 acceptance run: a tiny
single-process CPU experiment must leave the full telemetry contract behind
(run.json, events.jsonl with >=95%-coverage nested spans, heartbeats) and
the report must render it without error."""

import json
import os

import pytest

from dorpatch_tpu.observe import report

FIXTURE = os.path.join(os.path.dirname(__file__), "fixtures", "report_run")


def test_report_cli_renders_fixture(capsys):
    assert report.main([FIXTURE]) == 0
    out = capsys.readouterr().out
    # per-phase breakdown + coverage
    assert "phase breakdown" in out and "span coverage" in out
    assert "batch" in out and "setup" in out
    # compile-vs-run accounting
    assert "compile time:" in out
    assert "attack.block.stage0.steps5" in out
    # throughput incl. MFU through the shared StepTimer.summary path
    assert "steps/sec" in out and "images/sec" in out
    assert "mfu: 0.0327" in out
    # heartbeat stall detection: proc 1's 6s gap inside a bcast is flagged
    assert "** STALL **" in out
    assert "run/batch/artifact_io/bcast" in out
    # resumed-run grouping by run_id
    assert "2 attempt(s)" in out
    assert "beef00000001" in out and "beef00000002" in out


def test_report_json_mode_summary(capsys):
    assert report.main([FIXTURE, "--json"]) == 0
    s = json.loads(capsys.readouterr().out)
    assert s["run_id"] == "beef00000002"
    assert s["attempts"] == ["beef00000001", "beef00000002"]
    assert s["run_seconds"] == 20.3
    assert s["coverage"] >= 0.95
    phases = {p["name"]: p for p in s["phases"]}
    assert phases["batch"]["count"] == 2 and phases["batch"]["pct"] > 50
    assert s["compile"]["total_s"] == pytest.approx(4.4)
    assert s["attack"]["steps"] == 40  # latest attempt only (run_id-grouped)
    assert s["attack"]["images_generated"] == 4
    assert s["certify"]["images_per_sec"] > 0
    assert s["mfu"]["mfu"] == pytest.approx(0.0327, abs=1e-4)
    assert s["peak_device_bytes"] == 3_900_000_000
    stalls = {h["file"]: h["stalled"] for h in s["heartbeats"]}
    assert stalls == {"heartbeat_0.jsonl": False, "heartbeat_1.jsonl": True}
    assert s["metrics_records"]["by_attempt"] == {"beef00000001": 8,
                                                  "beef00000002": 8}


def test_report_rejects_missing_or_empty_dir(tmp_path, capsys):
    assert report.main([str(tmp_path / "nope")]) == 2
    empty = tmp_path / "empty"
    empty.mkdir()
    assert report.main([str(empty)]) == 2
    assert "no telemetry" in capsys.readouterr().out


def test_report_flags_open_spans_as_hang(tmp_path, capsys):
    """A run that died mid-collective leaves begin records with no close —
    the report must surface them instead of pretending the run finished."""
    with open(tmp_path / "events.jsonl", "w") as fh:
        for i, (kind, name, path, depth) in enumerate([
                ("begin", "run", "run", 0),
                ("begin", "batch", "run/batch", 1),
                ("begin", "artifact_io", "run/batch/artifact_io", 2)]):
            fh.write(json.dumps({"ts": 100.0 + i, "seq": i, "proc": 0,
                                 "run_id": "r1", "kind": kind, "name": name,
                                 "path": path, "depth": depth}) + "\n")
    assert report.main([str(tmp_path)]) == 0
    out = capsys.readouterr().out
    assert "run span never closed" in out
    assert "OPEN" in out and "run/batch/artifact_io" in out


# ~155 s on the single CI core: the full attack+certify pipeline with
# live telemetry. The report CLI smoke in run_tests.sh plus the
# fixture-driven tests above keep the content covered in tier-1.
@pytest.mark.slow
def test_telemetry_e2e_single_process_cpu(tmp_path, capsys):
    """ISSUE acceptance: a single-process CPU run (synthetic data, small
    victim) produces run.json, events.jsonl with nested spans covering >=95%
    of wall time, and heartbeat files; the report CLI renders the directory
    without error."""
    from dorpatch_tpu.artifacts import results_path
    from dorpatch_tpu.config import (AttackConfig, DefenseConfig,
                                     ExperimentConfig)
    from dorpatch_tpu.pipeline import run_experiment

    cfg = ExperimentConfig(
        dataset="cifar10",
        base_arch="resnet18",
        batch_size=2,
        num_batches=1,
        synthetic_data=True,
        img_size=32,
        results_root=str(tmp_path / "results"),
        heartbeat_interval=0.1,
        attack=AttackConfig(
            sampling_size=4, max_iterations=4, sweep_interval=2,
            switch_iteration=2, dropout=1, basic_unit=4, patch_budget=0.15,
        ),
        defense=DefenseConfig(ratios=(0.06,), chunk_size=18),
    )
    m = run_experiment(cfg, verbose=False)
    rd = results_path(cfg)

    # run manifest: self-describing results dir
    manifest = json.load(open(os.path.join(rd, "run.json")))
    assert manifest["run_id"] and manifest["backend"] == "cpu"
    assert manifest["config"]["attack"]["max_iterations"] == 4
    assert manifest["process_count"] == 1

    # events: nested spans with the documented names, >=95% coverage
    events = [json.loads(l) for l in open(os.path.join(rd, "events.jsonl"))]
    assert [e["seq"] for e in events] == list(range(len(events)))
    spans = [e for e in events if e["kind"] == "span"]
    names = {s["name"] for s in spans}
    assert {"run", "setup", "batch", "attack.stage0", "attack.stage1",
            "certify", "artifact_io", "finalize"} <= names
    run_dur = [s for s in spans if s["name"] == "run"][-1]["dur_s"]
    covered = sum(s["dur_s"] for s in spans if s["depth"] == 1)
    assert covered / run_dur >= 0.95
    compiles = [e for e in events if e["kind"] == "compile"]
    assert any(c["name"].startswith("attack.block") for c in compiles)
    blocks = [e for e in events if e["kind"] == "block"]
    assert len(blocks) >= 2  # 4 iterations / sweep_interval 2 per stage

    # heartbeats: at least the immediate beat + the exit beat
    beats = [json.loads(l)
             for l in open(os.path.join(rd, "heartbeat_0.jsonl"))]
    assert len(beats) >= 2 and beats[-1]["phase"] == "exit"
    assert all(b["run_id"] == manifest["run_id"] for b in beats)

    # metrics records are run_id-stamped for attempt grouping
    mrecs = [json.loads(l) for l in open(os.path.join(rd, "metrics.jsonl"))]
    assert mrecs and all(r["run_id"] == manifest["run_id"] for r in mrecs)

    # the offline report renders it without error
    capsys.readouterr()
    assert report.main([rd]) == 0
    out = capsys.readouterr().out
    assert "phase breakdown" in out and "steps/sec" in out \
        and "compile time:" in out
    assert m["evaluated_images"] >= 1
