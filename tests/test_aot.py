"""The AOT executable store (dorpatch_tpu/aot/): store round-trips,
zero-trace warm boot through the dispatcher, strict vs auto miss handling,
robustness against corrupt blobs and torn manifests, static-argnum
dispatch, gc, the farm's lazy first-call resolver, `aot verify` (DP305),
and the call-signature discriminator."""

import json
import os

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from dorpatch_tpu import observe
from dorpatch_tpu.aot.boot import (
    AotBootError,
    AotDispatcher,
    FirstCallAotResolver,
    call_signature,
    warm_boot,
)
from dorpatch_tpu.aot.store import MANIFEST, ExecutableStore, open_readonly
from dorpatch_tpu.config import AotConfig


def fresh_program(budget=8):
    """A new jitted toy program behind its own first-call timer. A fresh
    closure per call: jax.jit shares its trace cache across wrappers of the
    same function object, so trace-count assertions need distinct victims."""

    def f(x):
        return x * 2.0 + 1.0

    return observe.timed_first_call(jax.jit(f), "aot.test.double",
                                    recompile_budget=budget)


def abstract_arg(shape=(4,)):
    return jax.ShapeDtypeStruct(shape, np.dtype(np.float32))


def boot(timer, store_dir, mode, name="aot.test.double", shape=(4,)):
    return warm_boot([(name, timer, (abstract_arg(shape),))],
                     AotConfig(cache_dir=store_dir, mode=mode))


def test_build_then_hit_round_trip(tmp_path):
    store = str(tmp_path / "store")
    first = fresh_program()
    stats = boot(first, store, "auto")
    assert stats["builds"] == 1 and stats["hits"] == 0
    assert os.path.exists(os.path.join(store, MANIFEST))

    second = fresh_program()
    stats = boot(second, store, "auto")
    assert stats["hits"] == 1 and stats["misses"] == 0
    assert stats["builds"] == 0
    # the installed executable answers correctly with ZERO traces on the
    # fallback jit — the mechanical zero-trace proof
    out = second(jnp.arange(4, dtype=jnp.float32))
    np.testing.assert_allclose(np.asarray(out), [1.0, 3.0, 5.0, 7.0])
    assert int(second.__wrapped__.fallback._cache_size()) == 0


def test_strict_miss_on_empty_store_raises(tmp_path):
    with pytest.raises(AotBootError):
        boot(fresh_program(), str(tmp_path / "empty"), "strict")


def test_strict_hit_boots_clean(tmp_path):
    store = str(tmp_path / "store")
    boot(fresh_program(), store, "auto")
    timer = fresh_program()
    stats = boot(timer, store, "strict")
    assert stats["hits"] == 1 and stats["misses"] == 0
    assert isinstance(timer.__wrapped__, AotDispatcher)


def test_corrupt_blob_rebuilds_never_crashes(tmp_path):
    store_dir = str(tmp_path / "store")
    boot(fresh_program(), store_dir, "auto")
    manifest = json.load(open(os.path.join(store_dir, MANIFEST)))
    [(name, entry)] = manifest["entries"].items()
    blob = os.path.join(store_dir, entry["payload"])
    with open(blob, "wb") as fh:
        fh.write(b"\x00garbage payload\x00")

    timer = fresh_program()
    stats = boot(timer, store_dir, "auto")
    assert stats["miss_reasons"] == {"corrupt": 1}
    assert stats["builds"] == 1
    # the rewritten entry is valid again
    stats = boot(fresh_program(), store_dir, "auto")
    assert stats["hits"] == 1 and stats["misses"] == 0


def test_torn_manifest_acts_as_empty_store(tmp_path):
    store_dir = str(tmp_path / "store")
    os.makedirs(store_dir)
    with open(os.path.join(store_dir, MANIFEST), "w") as fh:
        fh.write('{"entries": {"trunc')
    stats = boot(fresh_program(), store_dir, "auto")
    assert stats["miss_reasons"] == {"absent": 1}
    assert stats["builds"] == 1
    # and the rewrite produced a readable manifest
    assert ExecutableStore(store_dir).entries()


def test_fingerprint_mismatch_rewrites_entry(tmp_path):
    store_dir = str(tmp_path / "store")
    boot(fresh_program(), store_dir, "auto")
    mpath = os.path.join(store_dir, MANIFEST)
    manifest = json.load(open(mpath))
    [name] = manifest["entries"]
    live_fp = manifest["entries"][name]["fingerprint"]
    manifest["entries"][name]["fingerprint"] = "0" * 16
    json.dump(manifest, open(mpath, "w"))

    stats = boot(fresh_program(), store_dir, "auto")
    assert stats["miss_reasons"] == {"fingerprint": 1}
    rewritten = json.load(open(mpath))["entries"][name]["fingerprint"]
    assert rewritten == live_fp


def test_static_argnum_dispatch(tmp_path):
    store_dir = str(tmp_path / "store")

    def make():
        def g(x, n):
            return x * float(n)

        return observe.timed_first_call(
            jax.jit(g, static_argnums=(1,)), "aot.test.static",
            recompile_budget=4)

    programs = [("aot.test.static", make(), (abstract_arg(), 3))]
    stats = warm_boot(programs, AotConfig(cache_dir=store_dir, mode="auto"))
    assert stats["builds"] == 1

    timer = make()
    stats = warm_boot([("aot.test.static", timer, (abstract_arg(), 3))],
                      AotConfig(cache_dir=store_dir, mode="strict"))
    assert stats["hits"] == 1
    out = timer(jnp.ones(4, dtype=jnp.float32), 3)
    np.testing.assert_allclose(np.asarray(out), 3.0)
    assert int(timer.__wrapped__.fallback._cache_size()) == 0
    # a DIFFERENT static value is a different program: signature miss,
    # falls back to the jit (which traces), never the wrong executable
    out = timer(jnp.ones(4, dtype=jnp.float32), 5)
    np.testing.assert_allclose(np.asarray(out), 5.0)
    assert int(timer.__wrapped__.fallback._cache_size()) == 1


def test_gc_removes_departed_entries(tmp_path):
    store_dir = str(tmp_path / "store")
    boot(fresh_program(), store_dir, "auto")
    store = ExecutableStore(store_dir)
    [name] = store.entries()
    blob = os.path.join(store_dir, store.entries()[name]["payload"])
    assert os.path.exists(blob)
    removed = store.gc({})  # baselines.json no longer records the program
    store.save()
    assert removed == [name]
    assert not ExecutableStore(store_dir).entries()
    assert not os.path.exists(blob)


def test_gc_keeps_matching_entries(tmp_path):
    store_dir = str(tmp_path / "store")
    boot(fresh_program(), store_dir, "auto")
    store = ExecutableStore(store_dir)
    [name] = store.entries()
    fp = store.entries()[name]["fingerprint"]
    assert store.gc({name: {"fingerprint": fp}}) == []
    assert list(store.entries()) == [name]


def test_first_call_resolver_hit_is_read_only(tmp_path):
    store_dir = str(tmp_path / "store")
    boot(fresh_program(), store_dir, "auto")
    manifest_bytes = open(os.path.join(store_dir, MANIFEST), "rb").read()

    resolver = FirstCallAotResolver(open_readonly(store_dir))
    prev = observe.aot_resolver()
    observe.set_aot_resolver(resolver)
    try:
        timer = fresh_program()
        out = timer(jnp.arange(4, dtype=jnp.float32))
    finally:
        observe.set_aot_resolver(prev)
    np.testing.assert_allclose(np.asarray(out), [1.0, 3.0, 5.0, 7.0])
    assert resolver.stats["hits"] == 1 and resolver.stats["misses"] == 0
    assert isinstance(timer.__wrapped__, AotDispatcher)
    assert int(timer.__wrapped__.fallback._cache_size()) == 0
    # never writes: the shared store's manifest is byte-identical
    assert open(os.path.join(store_dir, MANIFEST), "rb").read() \
        == manifest_bytes


def test_first_call_resolver_miss_compiles_normally(tmp_path):
    resolver = FirstCallAotResolver(
        open_readonly(str(tmp_path / "missing-store")))
    prev = observe.aot_resolver()
    observe.set_aot_resolver(resolver)
    try:
        timer = fresh_program()
        out = timer(jnp.arange(4, dtype=jnp.float32))
    finally:
        observe.set_aot_resolver(prev)
    np.testing.assert_allclose(np.asarray(out), [1.0, 3.0, 5.0, 7.0])
    assert resolver.stats["misses"] == 1
    assert int(timer.__wrapped__.fallback._cache_size()) == 1


def test_verify_against_flags_drift_and_absence(tmp_path):
    store_dir = str(tmp_path / "store")
    boot(fresh_program(), store_dir, "auto")
    store = ExecutableStore(store_dir)
    [name] = store.entries()
    entry = store.entries()[name]
    clean_baseline = {"entries": {name: {
        "fingerprint": entry["fingerprint"],
        "interface": {"sha": entry["interface_sha"]},
    }}}
    assert store.verify_against(clean_baseline, allow={}) == []

    drifted = {"entries": {name: {"fingerprint": "0" * 16,
                                  "interface": {"sha": "x"}},
                           "other.program": {"fingerprint": "1" * 16}}}
    findings = store.verify_against(drifted, allow={})
    assert findings and all(f.rule_id == "DP305" for f in findings)
    msgs = "\n".join(f.message for f in findings)
    assert f"[{name}]" in msgs          # fingerprint drift
    assert "[other.program]" in msgs    # baselined, no store entry

    # allowlist is the DP305 suppression channel (manifest.json has no
    # source line for a noqa)
    allow = {name: {"DP305": "known"}, "other.program": {"DP305": "known"}}
    assert store.verify_against(drifted, allow=allow) == []


def test_call_signature_discriminates():
    a4 = jnp.zeros((4,), jnp.float32)
    a8 = jnp.zeros((8,), jnp.float32)
    base = call_signature((a4,), {})
    assert call_signature((abstract_arg((4,)),), {}) == base  # abstract==live
    assert call_signature((a8,), {}) != base                  # shape
    assert call_signature((a4.astype(jnp.int32),), {}) != base  # dtype
    assert call_signature((a4, 3), {}) != call_signature((a4, 5), {})  # static
    assert call_signature((a4,), {"k": a4}) != base           # structure
