"""Fleet gateway: membership hysteresis, exactly-once routing, canary
rollback, autoscale signals, and the HTTP surface.

Everything here is jax-free and socket-local: backends are stub HTTP
servers (`_StubServe`) whose behavior each test scripts — answer, hang
up mid-request, fail health probes, or serve a planted DP400 robustness
verdict. Membership is stepped deterministically via the registry's
public `probe_cycle()` (no prober thread) wherever timing would
otherwise matter."""

import dataclasses
import json
import pathlib
import threading
import time
import urllib.error
import urllib.request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import pytest

from dorpatch_tpu.config import ExperimentConfig, GatewayConfig
from dorpatch_tpu.gateway import (
    Backend,
    BackendRegistry,
    Gateway,
    GatewayFrontend,
    RollingDeploy,
    Router,
)
from dorpatch_tpu.gateway.membership import (
    DEGRADED,
    DRAINING,
    EJECTED,
    HEALTHY,
    JOINING,
)

REPO = pathlib.Path(__file__).resolve().parents[1]

OK_VERDICT = {"status": "ok", "generation": 1, "worst_margin": 0.2,
              "findings_by_rule": {}, "cells": {}}
DP400_VERDICT = {"status": "failed", "generation": 2, "worst_margin": -0.5,
                 "findings_by_rule": {"DP400": ["planted regression"]},
                 "cells": {}}


def _cfg(**kw) -> GatewayConfig:
    """Test-speed gateway knobs; override per test."""
    # probe_interval_s is LONG: started gateways run exactly one probe
    # sweep at boot and tests then drive membership deterministically
    # (forced states or manual probe_cycle()), never racing the prober
    base = dict(probe_interval_s=60.0, probe_timeout_s=2.0,
                fail_threshold=3, ok_threshold=2, inflight_cap=4,
                dispatch_retries=1, dispatch_timeout_s=5.0,
                canary_steps=(1.0,), canary_hold_s=0.0,
                autoscale_cooldown_s=1e9)
    base.update(kw)
    return GatewayConfig(**base)


# ---------------------------------------------------------------- stubs


class _StubServe:
    """A scriptable stand-in for one `python -m dorpatch_tpu.serve`
    process: /healthz /stats /robustness /predict with per-instance
    mutable behavior and exact served counters."""

    def __init__(self):
        stub = self

        class Handler(BaseHTTPRequestHandler):
            def _json(self, code, payload):
                body = json.dumps(payload).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):
                if self.path == "/healthz":
                    code = stub.healthz_code
                    self._json(code, {"status": "ok" if code == 200
                                      else "unhealthy"})
                elif self.path == "/stats":
                    self._json(200, dict(stub.stats))
                elif self.path == "/robustness":
                    code, verdict = stub.robustness
                    self._json(code, verdict)
                else:
                    self._json(404, {"status": "error"})

            def do_POST(self):
                n = int(self.headers.get("Content-Length", "0"))
                self.rfile.read(n)
                with stub.lock:
                    stub.predicts += 1
                    stub.trace_ids.append(
                        self.headers.get("X-Trace-Id", ""))
                if stub.predict_mode == "die":
                    # hang up before any status line: the gateway sees a
                    # connection-level failure (safe to re-dispatch)
                    self.connection.close()
                    return
                if stub.predict_delay:
                    time.sleep(stub.predict_delay)
                with stub.lock:
                    stub.answers += 1
                self._json(200, {"status": "ok", "label": 1,
                                 "certified": True})

            def log_message(self, fmt, *args):
                pass

        self.lock = threading.Lock()
        self.predicts = 0     # requests that REACHED this backend
        self.answers = 0      # requests this backend actually answered
        self.trace_ids = []
        self.healthz_code = 200
        self.stats = {"occupancy": 0.1, "reject_rate": 0.0,
                      "queue_depth": 0, "warm": True}
        self.robustness = (200, OK_VERDICT)
        self.predict_mode = "ok"
        self.predict_delay = 0.0
        self._httpd = ThreadingHTTPServer(("127.0.0.1", 0), Handler)
        self._httpd.daemon_threads = True
        self.port = self._httpd.server_address[1]
        self.url = f"http://127.0.0.1:{self.port}"
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, daemon=True)
        self._thread.start()

    def close(self):
        self._httpd.shutdown()
        self._httpd.server_close()
        self._thread.join(timeout=10.0)


@pytest.fixture
def stub():
    s = _StubServe()
    yield s
    s.close()


@pytest.fixture
def stub2():
    s = _StubServe()
    yield s
    s.close()


def _force_state(registry: BackendRegistry, name: str, state: str) -> None:
    registry.set_state(name, state, reason="test")


def _gateway(backends, tmp_path=None, **cfg_kw) -> Gateway:
    cfg = _cfg(backends=tuple(b.url for b in backends), **cfg_kw)
    return Gateway(cfg, result_dir=str(tmp_path) if tmp_path else "")


# ------------------------------------------------- membership hysteresis


def _scripted_registry(cfg, results):
    """Registry over one fake backend whose probe results are scripted:
    each probe_cycle consumes one (ok, stats, robust_ok, err) tuple."""
    b = Backend("http://127.0.0.1:1")
    transitions = []
    reg = BackendRegistry(
        [b], cfg,
        on_transition=lambda *args: transitions.append(args))
    script = iter(results)
    reg._collect = lambda backend: next(script)
    return b, reg, transitions


OK = (True, {"occupancy": 0.5, "reject_rate": 0.0,
             "queue_depth": 1, "warm": True}, True, "")
FAIL = (False, None, True, "ConnectionRefusedError: x")
OK_NOT_ROBUST = (True, None, False, "")


def test_membership_joining_to_healthy_needs_ok_threshold():
    b, reg, transitions = _scripted_registry(_cfg(ok_threshold=2), [OK, OK])
    reg.probe_cycle()
    assert b.snapshot()["state"] == JOINING  # one ok is not admission
    reg.probe_cycle()
    snap = b.snapshot()
    assert snap["state"] == HEALTHY
    assert snap["occupancy"] == 0.5 and snap["warm"]
    assert (b.name, JOINING, HEALTHY, "probe_ok") in transitions


def test_membership_ejects_after_consecutive_failures_only():
    # fail/ok alternation never reaches fail_threshold=3 consecutively
    b, reg, _ = _scripted_registry(
        _cfg(fail_threshold=3, ok_threshold=2),
        [FAIL, FAIL, OK, FAIL, FAIL, FAIL])
    for _ in range(5):
        reg.probe_cycle()
    assert b.snapshot()["state"] == JOINING
    reg.probe_cycle()  # third consecutive failure
    assert b.snapshot()["state"] == EJECTED
    assert b.snapshot()["last_error"].startswith("ConnectionRefusedError")


def test_membership_readmission_hysteresis_defeats_flapping():
    cfg = _cfg(fail_threshold=2, ok_threshold=2)
    # ejected, then strict ok/fail flapping: never re-admits
    b, reg, _ = _scripted_registry(
        cfg, [FAIL, FAIL, OK, FAIL, OK, FAIL, OK, OK])
    reg.probe_cycle()
    reg.probe_cycle()
    assert b.snapshot()["state"] == EJECTED
    for expect in (JOINING, JOINING, JOINING, JOINING):
        reg.probe_cycle()
        state = b.snapshot()["state"]
        assert state in (expect, EJECTED)  # flapping: joining<->ejected
        assert state != HEALTHY
    # two CONSECUTIVE oks finally re-admit
    reg.probe_cycle()
    reg.probe_cycle()
    assert b.snapshot()["state"] == HEALTHY


def test_membership_robustness_degrades_and_recovers():
    b, reg, transitions = _scripted_registry(
        _cfg(ok_threshold=1), [OK, OK_NOT_ROBUST, OK])
    reg.probe_cycle()
    assert b.snapshot()["state"] == HEALTHY
    reg.probe_cycle()
    assert b.snapshot()["state"] == DEGRADED
    reg.probe_cycle()
    assert b.snapshot()["state"] == HEALTHY
    assert (b.name, HEALTHY, DEGRADED, "robustness") in transitions


def test_membership_draining_is_never_left_automatically():
    b, reg, _ = _scripted_registry(_cfg(ok_threshold=1), [OK, OK])
    reg.probe_cycle()
    reg.set_state(b.name, DRAINING, reason="test drain")
    reg.probe_cycle()  # a good probe must NOT resurrect a draining backend
    assert b.snapshot()["state"] == DRAINING
    assert reg.routable() == []


def test_live_probe_cycle_against_stub(stub):
    """End-to-end probe over real sockets: /healthz + /stats + /robustness
    feed state and load signals."""
    cfg = _cfg(backends=(stub.url,), ok_threshold=1)
    reg = BackendRegistry([Backend(stub.url)], cfg)
    reg.probe_cycle()
    snap = reg.backends()[0].snapshot()
    assert snap["state"] == HEALTHY
    assert snap["occupancy"] == 0.1 and snap["warm"]
    stub.healthz_code = 503
    for _ in range(cfg.fail_threshold):
        reg.probe_cycle()
    assert reg.backends()[0].snapshot()["state"] == EJECTED


def test_wedge_probe_chaos_ejects_then_readmits(stub, tmp_path):
    """Chaos ``wedge_probe`` forces the next N probes of backend index 0
    to fail before any socket is touched: the healthy stub must be
    ejected, and once the wedge exhausts, real probes must walk it back
    through joining to healthy."""
    gw = _gateway([stub], tmp_path=tmp_path, chaos="wedge_probe",
                  ok_threshold=2)
    chaos = gw.chaos
    assert chaos is not None
    wedged = chaos.wedge_probe_failures()
    assert wedged >= gw.cfg.fail_threshold
    for _ in range(gw.cfg.fail_threshold):
        gw.registry.probe_cycle()
    assert gw.registry.backends()[0].snapshot()["state"] == EJECTED
    assert (tmp_path / "chaos_wedge_probe.fired").exists()
    # drain the rest of the wedge, then recovery hysteresis kicks in
    for _ in range(wedged - gw.cfg.fail_threshold):
        gw.registry.probe_cycle()
    states = []
    for _ in range(gw.cfg.ok_threshold + 1):
        gw.registry.probe_cycle()
        states.append(gw.registry.backends()[0].snapshot()["state"])
    assert JOINING in states and states[-1] == HEALTHY


# ------------------------------------------------------- routing


def test_route_retries_on_survivor_exactly_once(stub, stub2):
    """A backend hanging up mid-request (SIGKILL shape) is retried on a
    backend the request has NOT touched; the client sees exactly one
    answer and the survivor serves it exactly once."""
    stub.predict_mode = "die"
    gw = _gateway([stub, stub2])
    for s in (stub, stub2):
        _force_state(gw.registry, f"127.0.0.1:{s.port}", HEALTHY)
    # pin the dead backend first so the retry leg is deterministic
    results = []
    for _ in range(8):
        r = gw.handle_predict(b"{}", trace_id="t-retry")
        results.append(r)
    assert all(r.payload["status"] == "ok" for r in results)
    # every request answered exactly once, all by the survivor
    assert stub.answers == 0
    assert stub2.answers == 8
    retried = [r for r in results if r.retries]
    assert retried, "the dead backend was never even attempted"
    for r in retried:
        assert r.backend == f"127.0.0.1:{stub2.port}"
        assert len(r.attempted) == 2 and len(set(r.attempted)) == 2
    # books: one terminal status per request, retries counted separately
    assert gw.metrics.value("gateway_requests_total", status="ok") == 8
    assert gw.metrics.value("gateway_backend_responses_total",
                            backend=f"127.0.0.1:{stub2.port}",
                            status="ok") == 8
    assert gw.metrics.value("gateway_retries_total") == len(retried)


def test_route_all_ejected_is_typed_fleet_503(stub):
    gw = _gateway([stub])
    _force_state(gw.registry, f"127.0.0.1:{stub.port}", EJECTED)
    r = gw.handle_predict(b"{}", trace_id="t-eject")
    assert r.code == 503
    assert r.payload["status"] == "overloaded"
    assert r.payload["scope"] == "fleet"
    assert r.payload["routable"] == 0 and r.payload["backends"] == 1
    assert stub.predicts == 0  # nothing was dispatched anywhere
    assert gw.metrics.value("gateway_requests_total",
                            status="overloaded") == 1


def test_route_inflight_cap_admission(stub):
    gw = _gateway([stub], inflight_cap=1)
    name = f"127.0.0.1:{stub.port}"
    _force_state(gw.registry, name, HEALTHY)
    b = gw.registry.get(name)
    assert b.begin_dispatch(1)  # occupy the only slot
    r = gw.handle_predict(b"{}", trace_id="t-cap")
    assert r.code == 503 and r.payload["status"] == "overloaded"
    b.end_dispatch()
    r = gw.handle_predict(b"{}", trace_id="t-cap2")
    assert r.payload["status"] == "ok"


def test_route_timeout_is_never_retried(stub, stub2):
    """A dispatch timeout must NOT re-dispatch (the backend may still
    answer): typed deadline_exceeded, second backend untouched."""
    stub.predict_delay = 1.0
    gw = _gateway([stub], dispatch_timeout_s=0.2, dispatch_retries=3)
    _force_state(gw.registry, f"127.0.0.1:{stub.port}", HEALTHY)
    r = gw.handle_predict(b"{}", trace_id="t-slow")
    assert r.code == 504
    assert r.payload["status"] == "deadline_exceeded"
    assert r.retries == 0 and len(r.attempted) == 1
    assert stub2.predicts == 0


def test_route_connection_failures_exhausted_is_internal_error():
    cfg = _cfg(backends=("http://127.0.0.1:9",), dispatch_retries=2)
    reg = BackendRegistry([Backend("http://127.0.0.1:9")], cfg)
    _force_state(reg, "127.0.0.1:9", HEALTHY)
    r = Router(reg, cfg).route(b"{}", "t-conn")
    assert r.code == 500
    assert r.payload["status"] == "internal_error"
    assert "127.0.0.1:9" in r.payload["reason"]


def test_router_prefers_healthy_over_degraded(stub, stub2):
    gw = _gateway([stub, stub2])
    _force_state(gw.registry, f"127.0.0.1:{stub.port}", DEGRADED)
    _force_state(gw.registry, f"127.0.0.1:{stub2.port}", HEALTHY)
    for i in range(6):
        r = gw.handle_predict(b"{}", trace_id=f"t-{i}")
        assert r.backend == f"127.0.0.1:{stub2.port}"
    assert stub.predicts == 0
    # the degraded backend is still a last resort
    _force_state(gw.registry, f"127.0.0.1:{stub2.port}", EJECTED)
    r = gw.handle_predict(b"{}", trace_id="t-last")
    assert r.backend == f"127.0.0.1:{stub.port}"


# ------------------------------------------------------- rolling deploy


def test_deploy_promotes_clean_canary(stub, stub2, tmp_path):
    gw = _gateway([stub], tmp_path=tmp_path, canary_steps=(0.5, 1.0))
    stable = f"127.0.0.1:{stub.port}"
    canary = f"127.0.0.1:{stub2.port}"
    with gw:
        _force_state(gw.registry, stable, HEALTHY)
        gw.add_backend(stub2.url)  # weight 0: no traffic until the deploy
        _force_state(gw.registry, canary, HEALTHY)
        out = RollingDeploy(gw, [canary], hold_s=0.0).run(warm_timeout_s=5)
    assert out["outcome"] == "promoted"
    snaps = {s["name"]: s for s in
             [b.snapshot() for b in gw.registry.backends()]}
    assert snaps[canary]["weight"] == 1.0
    assert snaps[stable]["state"] == DRAINING
    assert snaps[stable]["weight"] == 0.0
    events = [json.loads(line) for line in
              (tmp_path / "events.jsonl").read_text().splitlines()]
    names = [e["name"] for e in events]
    assert "gateway.deploy.begin" in names
    assert "gateway.deploy.step" in names
    assert "gateway.deploy.complete" in names
    assert "gateway.rollback" not in names


def test_deploy_rolls_back_on_planted_dp400(stub, stub2, tmp_path):
    """A DP400 finding in the canary's robustness verdict rolls the fleet
    back: canary drained, stable restored, typed event + counter."""
    stub2.robustness = (503, DP400_VERDICT)
    gw = _gateway([stub], tmp_path=tmp_path, canary_steps=(0.1, 1.0))
    stable = f"127.0.0.1:{stub.port}"
    canary = f"127.0.0.1:{stub2.port}"
    with gw:
        _force_state(gw.registry, stable, HEALTHY)
        gw.add_backend(stub2.url)
        _force_state(gw.registry, canary, HEALTHY)
        out = RollingDeploy(gw, [canary], hold_s=0.0).run(warm_timeout_s=5)
    assert out["outcome"] == "rolled_back"
    assert "DP400" in out["reason"]
    assert out["step"] == 0.1  # the FIRST step's gate caught it
    assert any(f.startswith("DP400:") for f in out["findings"])
    snaps = {s["name"]: s for s in
             [b.snapshot() for b in gw.registry.backends()]}
    assert snaps[canary]["state"] == DRAINING
    assert snaps[canary]["weight"] == 0.0
    assert snaps[stable]["weight"] == 1.0
    assert gw.metrics.value("gateway_rollbacks_total") == 1
    events = [json.loads(line) for line in
              (tmp_path / "events.jsonl").read_text().splitlines()]
    rb = [e for e in events if e["name"] == "gateway.rollback"]
    assert len(rb) == 1 and "DP400" in rb[0]["reason"]
    # the dumped registry carries the rollback for the fleet report
    metrics = json.loads((tmp_path / "metrics.json").read_text())
    assert "gateway_rollbacks_total" in metrics["metrics"]


def test_deploy_rolls_back_on_unreachable_canary(stub, tmp_path):
    gw = _gateway([stub], tmp_path=tmp_path)
    stable = f"127.0.0.1:{stub.port}"
    with gw:
        _force_state(gw.registry, stable, HEALTHY)
        gw.add_backend("http://127.0.0.1:9")  # nothing listens there
        out = RollingDeploy(gw, ["127.0.0.1:9"],
                            hold_s=0.0).run(warm_timeout_s=0.3)
    assert out["outcome"] == "rolled_back"
    assert "never became healthy" in out["reason"]


def test_poison_canary_chaos_forces_rollback(stub, stub2, tmp_path):
    """chaos=poison_canary flips ONE healthy canary verdict to a failing
    DP400 — the rollback machinery proves itself without a bad model."""
    gw = _gateway([stub], tmp_path=tmp_path, chaos="poison_canary")
    stable = f"127.0.0.1:{stub.port}"
    canary = f"127.0.0.1:{stub2.port}"
    with gw:
        _force_state(gw.registry, stable, HEALTHY)
        gw.add_backend(stub2.url)
        _force_state(gw.registry, canary, HEALTHY)
        out = RollingDeploy(gw, [canary], hold_s=0.0).run(warm_timeout_s=5)
        assert out["outcome"] == "rolled_back"
        assert "DP400" in out["reason"]
        # the fault fires once: a second deploy of the same canary passes
        _force_state(gw.registry, canary, HEALTHY)
        out2 = RollingDeploy(gw, [canary], hold_s=0.0).run(warm_timeout_s=5)
    assert out2["outcome"] == "promoted"
    assert gw.metrics.value("gateway_rollbacks_total") == 1


# ------------------------------------------------------- autoscale


def test_autoscale_signals_and_cooldown(tmp_path):
    from dorpatch_tpu import observe
    from dorpatch_tpu.gateway.autoscale import Autoscaler

    events = []
    metrics = observe.MetricRegistry()
    scaler = Autoscaler(
        _cfg(autoscale_window_s=60.0, autoscale_high_occupancy=0.8,
             autoscale_low_occupancy=0.2, autoscale_high_reject=0.01,
             autoscale_cooldown_s=1e9),
        metrics, lambda name, **a: events.append((name, a)))
    assert scaler.observe(0.95, 0.0, routable=2) == "up"
    assert scaler.observe(0.95, 0.0, routable=2) is None  # cooldown
    assert metrics.value("gateway_autoscale_events_total",
                         direction="up") == 1
    assert metrics.value("gateway_autoscale_recommendation") == 1.0
    assert events[0][0] == "gateway.autoscale"
    assert events[0][1]["direction"] == "up"

    # reject pressure alone also recommends up
    m2 = observe.MetricRegistry()
    s2 = Autoscaler(_cfg(autoscale_cooldown_s=0.0), m2,
                    lambda name, **a: None)
    assert s2.observe(0.5, 0.5, routable=2) == "up"

    # idle fleet with zero rejects recommends down
    m3 = observe.MetricRegistry()
    s3 = Autoscaler(_cfg(autoscale_cooldown_s=0.0,
                         autoscale_low_occupancy=0.2), m3,
                    lambda name, **a: None)
    assert s3.observe(0.05, 0.0, routable=3) == "down"
    assert m3.value("gateway_autoscale_recommendation") == -1.0
    # mid-band is steady: gauges update, no event
    assert s3.observe(0.5, 0.0, routable=3) is None
    assert m3.value("gateway_fleet_occupancy_mean") == pytest.approx(0.275)


# ------------------------------------------------------- HTTP surface


def test_gateway_http_surface_and_trace_forwarding(stub, tmp_path):
    gw = _gateway([stub], tmp_path=tmp_path)
    name = f"127.0.0.1:{stub.port}"
    with gw, GatewayFrontend(gw, port=0) as fe:
        base = f"http://127.0.0.1:{fe.port}"
        # nothing routable yet: gateway healthz is 503 but answers
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(base + "/healthz", timeout=10)
        assert ei.value.code == 503
        assert json.loads(ei.value.read())["status"] == "unhealthy"
        _force_state(gw.registry, name, HEALTHY)
        with urllib.request.urlopen(base + "/healthz", timeout=10) as r:
            h = json.loads(r.read())
        assert h["status"] == "ok" and h["routable"] == 1

        req = urllib.request.Request(
            base + "/predict", data=b'{"deadline_ms": 1000}',
            headers={"Content-Type": "application/json",
                     "X-Trace-Id": "trace-abc"})
        with urllib.request.urlopen(req, timeout=30) as r:
            assert r.headers["X-Trace-Id"] == "trace-abc"
            body = json.loads(r.read())
        assert body["status"] == "ok"
        assert body["trace_id"] == "trace-abc"
        assert body["gateway"]["backend"] == name
        assert body["gateway"]["retries"] == 0
        # the SAME id reached the backend: client->gateway->backend joins
        assert stub.trace_ids == ["trace-abc"]

        with urllib.request.urlopen(base + "/stats", timeout=10) as r:
            stats = json.loads(r.read())
        assert stats["role"] == "gateway"
        assert stats["requests"] == {"ok": 1}
        assert [b["name"] for b in stats["backends"]] == [name]
        with urllib.request.urlopen(base + "/metrics", timeout=10) as r:
            text = r.read().decode()
        assert 'gateway_requests_total{status="ok"} 1' in text
    # the admit/terminal pair landed in the gateway's OWN event log
    events = [json.loads(line) for line in
              (tmp_path / "events.jsonl").read_text().splitlines()]
    admits = [e for e in events if e["name"] == "gateway.admit"]
    terminals = [e for e in events if e["name"] == "gateway.request"]
    assert len(admits) == len(terminals) == 1
    assert admits[0]["trace"] == terminals[0]["trace"] == "trace-abc"
    run = json.loads((tmp_path / "run.json").read_text())
    assert run["kind"] == "gateway"


def test_gateway_http_rejects_bad_bodies(stub):
    gw = _gateway([stub])
    with gw, GatewayFrontend(gw, port=0) as fe:
        req = urllib.request.Request(
            f"http://127.0.0.1:{fe.port}/predict", data=b"[1, 2]",
            headers={"Content-Type": "application/json"})
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(req, timeout=10)
        assert ei.value.code == 400
    assert stub.predicts == 0


# ------------------------------------------------------- config / CLI


def test_gateway_config_cli_roundtrip():
    from dorpatch_tpu.cli import build_parser, config_from_args

    args = build_parser().parse_args([
        "--gateway-backends", "http://a:1,http://b:2",
        "--gateway-port", "9100",
        "--gateway-probe-interval", "0.5",
        "--gateway-fail-threshold", "5",
        "--gateway-ok-threshold", "3",
        "--gateway-inflight-cap", "8",
        "--gateway-canary-steps", "0.25,1.0",
        "--gateway-canary-hold", "0.5",
    ])
    cfg = config_from_args(args)
    gw = cfg.gateway
    assert gw.backends == ("http://a:1", "http://b:2")
    assert gw.port == 9100
    assert gw.probe_interval_s == 0.5
    assert gw.fail_threshold == 5 and gw.ok_threshold == 3
    assert gw.inflight_cap == 8
    assert gw.canary_steps == (0.25, 1.0)
    assert gw.canary_hold_s == 0.5


def test_gateway_config_dict_roundtrip():
    from dorpatch_tpu.config import config_from_dict, config_to_dict

    cfg = ExperimentConfig(gateway=GatewayConfig(
        backends=("http://x:1",), inflight_cap=7, canary_steps=(0.2, 1.0)))
    wire = json.loads(json.dumps(config_to_dict(cfg)))
    back = config_from_dict(wire)
    assert back.gateway == cfg.gateway
    with pytest.raises(ValueError):
        wire["gateway"]["not_a_knob"] = 1
        config_from_dict(wire)


def test_gateway_package_is_jax_free():
    """The gateway must boot without jax: routing needs sockets, not an
    accelerator. Checked in a clean interpreter (this process already
    imported jax via conftest)."""
    import subprocess
    import sys

    code = ("import sys; import dorpatch_tpu.gateway; "
            "sys.exit(1 if 'jax' in sys.modules else 0)")
    proc = subprocess.run([sys.executable, "-c", code], cwd=str(REPO),
                          capture_output=True, text=True, timeout=120)
    assert proc.returncode == 0, proc.stderr


def test_gateway_package_passes_concurrency_lint():
    """gateway/ is inside the DP5xx audit scope and must lint clean."""
    from dorpatch_tpu.analysis import analyze_paths
    from dorpatch_tpu.analysis.concurrency import (
        CONCURRENCY_RULE_IDS,
        in_concurrency_scope,
    )
    from dorpatch_tpu.analysis.engine import FileContext

    src = REPO / "dorpatch_tpu" / "gateway" / "membership.py"
    ctx = FileContext(path=str(src), source=src.read_text(encoding="utf-8"))
    assert in_concurrency_scope(ctx)
    findings = [f for f in analyze_paths([REPO / "dorpatch_tpu" / "gateway"])
                if f.rule_id in CONCURRENCY_RULE_IDS]
    assert findings == [], [str(f) for f in findings]


def test_gateway_config_frozen():
    cfg = _cfg()
    with pytest.raises(dataclasses.FrozenInstanceError):
        cfg.inflight_cap = 99
