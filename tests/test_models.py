"""Model parity tests: flax ResNetV2 vs the torch oracle via checkpoint
conversion (SURVEY.md §4 parity strategy; logits must agree to ~1e-4)."""

import numpy as np
import pytest
import torch

import jax
import jax.numpy as jnp

from dorpatch_tpu.backends.torch_models import ResNetV2Torch, Normalized, create_torch_model
from dorpatch_tpu.models.convert import convert_resnetv2
from dorpatch_tpu.models.resnetv2 import ResNetV2


@pytest.fixture(scope="module")
def small_pair():
    """Torch oracle (random weights) + converted flax params, tiny config."""
    torch.manual_seed(0)
    tm = ResNetV2Torch(num_classes=7, layers=(1, 1), width=1).eval()
    sd = {k: v for k, v in tm.state_dict().items()}
    params = convert_resnetv2(sd, layers=(1, 1))
    fm = ResNetV2(num_classes=7, layers=(1, 1))
    return tm, fm, params


def _logits_pair(tm, fm, params, x_nchw):
    with torch.no_grad():
        want = tm(torch.tensor(x_nchw)).numpy()
    got = np.asarray(fm.apply(params, jnp.asarray(x_nchw.transpose(0, 2, 3, 1))))
    return got, want


def test_resnetv2_parity_small(small_pair):
    tm, fm, params = small_pair
    x = np.random.default_rng(1).normal(size=(2, 3, 64, 64)).astype(np.float32)
    got, want = _logits_pair(tm, fm, params, x)
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-4)


def test_resnetv2_parity_odd_size(small_pair):
    """Odd spatial size exercises the asymmetric TF-SAME padding paths."""
    tm, fm, params = small_pair
    x = np.random.default_rng(2).normal(size=(1, 3, 57, 57)).astype(np.float32)
    got, want = _logits_pair(tm, fm, params, x)
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-4)


@pytest.mark.slow
def test_resnetv2_50_parity_full():
    """Full 50-layer config at 224px (the real victim geometry)."""
    torch.manual_seed(0)
    tm = ResNetV2Torch(num_classes=10).eval()
    params = convert_resnetv2(tm.state_dict())
    fm = ResNetV2(num_classes=10)
    x = np.random.default_rng(3).normal(size=(1, 3, 224, 224)).astype(np.float32) * 0.5 + 0.5
    got, want = _logits_pair(tm, fm, params, x)
    np.testing.assert_allclose(got, want, rtol=1e-3, atol=2e-4)


def test_normalized_wrapper_matches_manual(small_pair):
    tm, fm, params = small_pair
    x01 = np.random.default_rng(4).uniform(size=(1, 3, 64, 64)).astype(np.float32)
    with torch.no_grad():
        want = Normalized(tm)(torch.tensor(x01)).numpy()
        manual = tm(torch.tensor((x01 - 0.5) / 0.5)).numpy()
    np.testing.assert_allclose(want, manual, rtol=1e-6)
    got = np.asarray(
        fm.apply(params, (jnp.asarray(x01.transpose(0, 2, 3, 1)) - 0.5) / 0.5))
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-4)


def test_state_dict_keys_are_timm_shaped():
    """Checkpoint-compat contract: keys look like timm resnetv2 keys."""
    tm = ResNetV2Torch(num_classes=5, layers=(1, 1))
    keys = set(tm.state_dict().keys())
    assert "stem.conv.weight" in keys
    assert "stages.0.blocks.0.norm1.weight" in keys
    assert "stages.0.blocks.0.conv2.weight" in keys
    assert "stages.0.blocks.0.downsample.conv.weight" in keys
    assert "stages.1.blocks.0.downsample.conv.weight" in keys
    assert "norm.weight" in keys and "norm.bias" in keys
    assert "head.fc.weight" in keys and "head.fc.bias" in keys
    # no extra buffers / unexpected params
    assert all(".num_batches_tracked" not in k for k in keys)


def test_factory_rejects_unknown_arch():
    from dorpatch_tpu.models import resolve_arch

    assert resolve_arch("resnetv2") == "resnetv2_50x1_bit_distilled"
    assert resolve_arch("vit") == "vit_base_patch16_224"
    with pytest.raises(ValueError):
        resolve_arch("densenet")


def test_grads_flow_through_flax_model(small_pair):
    _, fm, params = small_pair
    x = jnp.ones((1, 64, 64, 3)) * 0.3

    def loss(x):
        return fm.apply(params, x).sum()

    g = jax.grad(loss)(x)
    assert np.isfinite(np.asarray(g)).all()
    assert float(jnp.abs(g).sum()) > 0


# ---------- ViT / ResMLP parity ----------

def test_vit_parity_tiny():
    from dorpatch_tpu.backends.torch_models import ViTTorch
    from dorpatch_tpu.models.convert import convert_vit
    from dorpatch_tpu.models.vit import ViT

    torch.manual_seed(1)
    tm = ViTTorch(num_classes=5, dim=32, depth=2, heads=4, patch=8, img=32).eval()
    params = convert_vit(tm.state_dict(), depth=2, num_heads=4)
    fm = ViT(num_classes=5, patch_size=8, dim=32, depth=2, num_heads=4, img_size=(32, 32))
    x = np.random.default_rng(5).normal(size=(2, 3, 32, 32)).astype(np.float32)
    got, want = _logits_pair(tm, fm, params, x)
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-4)


@pytest.mark.slow
def test_vit_base_parity_full():
    from dorpatch_tpu.backends.torch_models import ViTTorch
    from dorpatch_tpu.models.convert import convert_vit
    from dorpatch_tpu.models.vit import ViT

    torch.manual_seed(2)
    tm = ViTTorch(num_classes=10).eval()
    params = convert_vit(tm.state_dict())
    fm = ViT(num_classes=10)
    x = np.random.default_rng(6).normal(size=(1, 3, 224, 224)).astype(np.float32)
    got, want = _logits_pair(tm, fm, params, x)
    np.testing.assert_allclose(got, want, rtol=1e-3, atol=5e-4)


def test_vit_export_round_trips_through_torch_twin():
    """export_vit (flax -> timm-shaped .pth state_dict) must load into the
    torch twin strict=True with logits parity, and convert_vit must invert
    it exactly — the cifar_vit trained-victim export path."""
    from dorpatch_tpu.backends.torch_models import create_torch_model
    from dorpatch_tpu.models.convert import convert_vit, export_vit
    from dorpatch_tpu.models.vit import CIFAR_VIT, vit_cifar

    fm = vit_cifar(10)
    params = fm.init(jax.random.PRNGKey(3), jnp.zeros((1, 32, 32, 3)))
    sd = export_vit(params)
    tm = create_torch_model("cifar_vit", 10).eval()
    tm.load_state_dict({k: torch.as_tensor(v) for k, v in sd.items()},
                       strict=True)
    x = np.random.default_rng(8).normal(size=(2, 3, 32, 32)).astype(np.float32)
    got, want = _logits_pair(tm, fm, params, x)
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-4)

    p2 = convert_vit(sd, depth=CIFAR_VIT["depth"],
                     num_heads=CIFAR_VIT["num_heads"])
    for a, b in zip(jax.tree_util.tree_leaves(params),
                    jax.tree_util.tree_leaves(p2)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_resmlp_parity_tiny():
    from dorpatch_tpu.backends.torch_models import ResMLPTorch
    from dorpatch_tpu.models.convert import convert_resmlp
    from dorpatch_tpu.models.resmlp import ResMLP

    torch.manual_seed(3)
    tm = ResMLPTorch(num_classes=5, dim=48, depth=3, patch=8, img=32).eval()
    params = convert_resmlp(tm.state_dict(), depth=3)
    fm = ResMLP(num_classes=5, patch_size=8, dim=48, depth=3, img_size=32)
    x = np.random.default_rng(7).normal(size=(2, 3, 32, 32)).astype(np.float32)
    got, want = _logits_pair(tm, fm, params, x)
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-4)


def test_cifar_resnet18_forward_and_grad():
    from dorpatch_tpu.models.small import CifarResNet18

    m = CifarResNet18()
    params = m.init(jax.random.PRNGKey(0), jnp.zeros((1, 32, 32, 3)))
    x = jnp.ones((2, 32, 32, 3)) * 0.5
    logits = m.apply(params, x)
    assert logits.shape == (2, 10)
    g = jax.grad(lambda x: m.apply(params, x).sum())(x)
    assert np.isfinite(np.asarray(g)).all()


# ---------- vendored timm-0.6.7 key contract (models/timm_keys.py) ----------

class _TrackingSD(dict):
    """State-dict stub shaped exactly like the vendored contract, recording
    which keys the converter reads (`in`-probes don't count as reads)."""

    def __init__(self, contract):
        super().__init__({k: np.zeros(s, np.float32)
                          for k, s in contract.items()})
        self.read = set()

    def __getitem__(self, k):
        self.read.add(k)
        return super().__getitem__(k)


_CONTRACT_CASES = [
    ("resnetv2_50x1_bit_distilled", 10, 224),
    ("vit_base_patch16_224", 10, 224),
    ("resmlp_24_distilled_224", 10, 224),
    ("cifar_resnet18", 10, 32),
    ("cifar_vit", 10, 32),
]


@pytest.mark.parametrize("timm_name,n_classes,img", _CONTRACT_CASES)
def test_converter_consumes_exact_timm_contract(timm_name, n_classes, img):
    """Each converter must read EVERY key of the vendored timm-0.6.7
    contract and NOTHING else — a timm naming drift (e.g. mlp_mixer's
    `stem` vs ViT's `patch_embed`, caught r04) fails here instead of
    KeyError-ing on the first real checkpoint."""
    from dorpatch_tpu.models import registry, timm_keys

    contract = timm_keys.state_dict_contract(timm_name, n_classes)
    sd = _TrackingSD(contract)
    registry._convert(timm_name, sd)
    assert sd.read == set(contract), (
        f"unread contract keys: {sorted(set(contract) - sd.read)[:5]}; "
        f"reads outside contract would have KeyError'd")


@pytest.mark.parametrize("timm_name,n_classes,img", _CONTRACT_CASES)
def test_converted_contract_matches_flax_init_shapes(timm_name, n_classes, img):
    """Converting a contract-shaped state_dict yields exactly the param
    tree the flax model initializes (structure AND leaf shapes) — checked
    via eval_shape, no weights materialized through the model."""
    from dorpatch_tpu.models import registry, timm_keys

    contract = timm_keys.state_dict_contract(timm_name, n_classes)
    converted = registry._convert(
        timm_name, {k: np.zeros(s, np.float32) for k, s in contract.items()})
    model = registry._build_flax(timm_name, n_classes)
    want = jax.tree_util.tree_map(
        lambda x: x.shape,
        jax.eval_shape(model.init, jax.random.PRNGKey(0),
                       jnp.zeros((1, img, img, 3), jnp.float32)))
    got = jax.tree_util.tree_map(lambda x: np.asarray(x).shape, converted)
    assert want == got


def test_verify_keys_reports_drift(tmp_path):
    """`models/verify.py --keys-only` flags missing/unexpected/shape-drifted
    keys against the contract (the drift alarm for a future timm re-pin)."""
    import torch as _torch

    from dorpatch_tpu.models import timm_keys
    from dorpatch_tpu.models.verify import verify_keys

    contract = timm_keys.state_dict_contract("resmlp_24_distilled_224", 10)
    sd = {k: _torch.zeros(s) for k, s in contract.items()}
    del sd["stem.proj.bias"]                        # missing
    sd["patch_embed.proj.weight"] = _torch.zeros(1)  # unexpected (old name)
    sd["norm.alpha"] = _torch.zeros(384)             # shape drift
    p = tmp_path / "resmlp_24_distilled_224_cutout2_128_cifar10.pth"
    _torch.save({"state_dict": sd}, str(p))
    report = verify_keys(str(p), "resmlp", "cifar10")
    assert report["missing"] == ["stem.proj.bias"]
    assert report["unexpected"] == ["patch_embed.proj.weight"]
    assert len(report["shape_drift"]) == 1 and "norm.alpha" in report["shape_drift"][0]


@pytest.mark.parametrize("arch,timm_name", [
    ("resnetv2", "resnetv2_50x1_bit_distilled"),
    ("vit", "vit_base_patch16_224"),
    ("resmlp", "resmlp_24_distilled_224"),
    ("resnet18", "cifar_resnet18"),
    ("cifar_vit", "cifar_vit"),
])
def test_torch_twin_state_dict_equals_contract(arch, timm_name):
    """The torch twins must carry EXACTLY the vendored contract's keys and
    shapes — twin==contract here, twin==flax in the parity tests, so
    contract==flax transitively, with no real checkpoint needed."""
    from dorpatch_tpu.models import timm_keys

    tm = create_torch_model(arch, 10)
    sd = {k: tuple(v.shape) for k, v in tm.state_dict().items()}
    contract = {k: tuple(s)
                for k, s in timm_keys.state_dict_contract(timm_name, 10).items()}
    assert sd == contract
