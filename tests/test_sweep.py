"""Sweep driver + seed/device utils."""

import jax
import numpy as np
import pytest

from dorpatch_tpu import utils
from dorpatch_tpu.config import AttackConfig, ExperimentConfig
from dorpatch_tpu.sweep import main as sweep_main, run_sweep


def test_set_global_seed_reproducible():
    k1 = utils.set_global_seed(7)
    a = np.random.uniform(size=3)
    k2 = utils.set_global_seed(7)
    b = np.random.uniform(size=3)
    np.testing.assert_array_equal(a, b)
    np.testing.assert_array_equal(np.asarray(k1), np.asarray(k2))


def test_enable_compilation_cache(tmp_path, monkeypatch):
    """The persistent-cache helper must honor the env override, create the
    dir, and leave jax pointed at it (tunnel recompiles cost minutes; every
    entry point calls this)."""
    prev = jax.config.jax_compilation_cache_dir
    try:
        target = tmp_path / "xla_cache"
        monkeypatch.setenv("JAX_COMPILATION_CACHE_DIR", str(target))
        got = utils.enable_compilation_cache()
        assert got == str(target) and target.is_dir()
        assert jax.config.jax_compilation_cache_dir == str(target)
        explicit = tmp_path / "explicit"
        assert utils.enable_compilation_cache(str(explicit)) == str(explicit)
        assert explicit.is_dir()
    finally:
        jax.config.update("jax_compilation_cache_dir", prev)


def test_select_device():
    prev = jax.config.jax_default_device
    try:
        dev = utils.select_device("0")
        assert dev is jax.devices()[0]
        assert utils.select_device("not-a-number") is None
        assert utils.select_device(str(len(jax.devices()) + 5)) is None
    finally:
        jax.config.update("jax_default_device", prev)


@pytest.mark.slow
def test_run_sweep_grid_rows():
    attack = AttackConfig(
        sampling_size=4, max_iterations=4, sweep_interval=2,
        switch_iteration=2, dropout=1, dropout_sizes=(0.06,), basic_unit=4,
    )
    cfg = ExperimentConfig(
        dataset="cifar10", base_arch="resnet18", img_size=32, batch_size=2,
        synthetic_data=True, attack=attack,
    )
    rows = run_sweep(cfg, patch_budgets=(0.1, 0.2), densities=(0.0,),
                     structureds=(1e-3,), defense_ratio=0.06, verbose=False)
    assert len(rows) == 2
    assert [r["patch_budget"] for r in rows] == [0.1, 0.2]
    for r in rows:
        assert 0.0 <= r["asr"] <= 100.0
        assert 0.0 <= r["certified_asr_pc"] <= 100.0
        assert r["robust_accuracy"] + r["asr"] == pytest.approx(100.0)
        assert r["images"] >= 1 and r["seconds"] > 0
        assert np.isfinite(r["mean_l2"])


@pytest.mark.slow
def test_sweep_cli_smoke(capsys):
    rows = sweep_main([
        "--synthetic", "--dataset", "cifar10", "--base_arch", "resnet18",
        "--img-size", "32", "-b", "2", "--max-iterations", "2",
        "--sampling-size", "4", "--basic-unit", "4",
        "--patch-budgets", "0.1", "--densities", "0.0",
    ])
    assert len(rows) == 1
    out = capsys.readouterr().out
    assert '"sweep_points": 1' in out
