"""Sweep driver + seed/device utils."""

import jax
import numpy as np
import pytest

from dorpatch_tpu import utils
from dorpatch_tpu.config import AttackConfig, ExperimentConfig
from dorpatch_tpu.sweep import main as sweep_main, run_sweep


def test_set_global_seed_reproducible():
    k1 = utils.set_global_seed(7)
    a = np.random.uniform(size=3)
    k2 = utils.set_global_seed(7)
    b = np.random.uniform(size=3)
    np.testing.assert_array_equal(a, b)
    np.testing.assert_array_equal(np.asarray(k1), np.asarray(k2))


def test_enable_compilation_cache(tmp_path, monkeypatch):
    """The persistent-cache helper must honor the env override, create the
    dir, and leave jax pointed at it (tunnel recompiles cost minutes; every
    entry point calls this)."""
    prev = jax.config.jax_compilation_cache_dir
    try:
        target = tmp_path / "xla_cache"
        monkeypatch.setenv("JAX_COMPILATION_CACHE_DIR", str(target))
        got = utils.enable_compilation_cache()
        assert got == str(target) and target.is_dir()
        assert jax.config.jax_compilation_cache_dir == str(target)
        explicit = tmp_path / "explicit"
        assert utils.enable_compilation_cache(str(explicit)) == str(explicit)
        assert explicit.is_dir()
    finally:
        jax.config.update("jax_compilation_cache_dir", prev)


def test_select_device():
    prev = jax.config.jax_default_device
    try:
        dev = utils.select_device("0")
        assert dev is jax.devices()[0]
        assert utils.select_device("not-a-number") is None
        assert utils.select_device(str(len(jax.devices()) + 5)) is None
    finally:
        jax.config.update("jax_default_device", prev)


@pytest.mark.slow
def test_run_sweep_grid_rows():
    attack = AttackConfig(
        sampling_size=4, max_iterations=4, sweep_interval=2,
        switch_iteration=2, dropout=1, dropout_sizes=(0.06,), basic_unit=4,
    )
    cfg = ExperimentConfig(
        dataset="cifar10", base_arch="resnet18", img_size=32, batch_size=2,
        synthetic_data=True, attack=attack,
    )
    rows = run_sweep(cfg, patch_budgets=(0.1, 0.2), densities=(0.0,),
                     structureds=(1e-3,), defense_ratio=0.06, verbose=False)
    assert len(rows) == 2
    assert [r["patch_budget"] for r in rows] == [0.1, 0.2]
    for r in rows:
        assert 0.0 <= r["asr"] <= 100.0
        assert 0.0 <= r["certified_asr_pc"] <= 100.0
        assert r["robust_accuracy"] + r["asr"] == pytest.approx(100.0)
        assert r["images"] >= 1 and r["seconds"] > 0
        assert np.isfinite(r["mean_l2"])


@pytest.mark.slow
def test_sweep_cli_smoke(capsys):
    rows = sweep_main([
        "--synthetic", "--dataset", "cifar10", "--base_arch", "resnet18",
        "--img-size", "32", "-b", "2", "--max-iterations", "2",
        "--sampling-size", "4", "--basic-unit", "4",
        "--patch-budgets", "0.1", "--densities", "0.0",
    ])
    assert len(rows) == 1
    out = capsys.readouterr().out
    assert '"sweep_points": 1' in out


def test_rows_jsonl_roundtrip_and_truncation(tmp_path):
    """Incremental row persistence: appended rows survive a round-trip with
    keys stable across JSON float formatting, and a final line truncated by
    a kill is dropped (that point simply re-runs)."""
    from dorpatch_tpu.sweep import (
        ROWS_NAME, append_row, load_recorded_rows, row_key)

    d = str(tmp_path / "res")
    assert load_recorded_rows(d) == {}  # missing file: nothing recorded
    row_a = {"patch_budget": 0.1, "density": 0.0, "structured": 1e-3,
             "robust_accuracy": 42.0, "point": 0}
    row_b = {"patch_budget": 0.2, "density": 0.0, "structured": 1e-3,
             "robust_accuracy": 17.0, "point": 1}
    append_row(d, row_a)
    append_row(d, row_b)
    recorded = load_recorded_rows(d)
    assert recorded[row_key(0.1, 0.0, 1e-3)] == row_a
    assert recorded[row_key(0.2, 0.0, 1e-3)] == row_b

    # torn final line (killed mid-append): earlier rows still load
    with open(tmp_path / "res" / ROWS_NAME, "a") as fh:
        fh.write('{"patch_budget": 0.3, "den')
    recorded = load_recorded_rows(d)
    assert len(recorded) == 2
    assert row_key(0.3, 0.0, 1e-3) not in recorded

    # rows missing grid keys (e.g. foreign jsonl) are ignored, not fatal
    with open(tmp_path / "res" / ROWS_NAME, "a") as fh:
        fh.write('\n{"unrelated": true}\n')
    assert len(load_recorded_rows(d)) == 2


def test_row_key_stable_across_json_roundtrip():
    import json as _json

    from dorpatch_tpu.sweep import GRID_KEYS, row_key

    row = dict(zip(GRID_KEYS, (0.1, 1e-3, 3.3333333333333335e-05)))
    back = _json.loads(_json.dumps(row))
    assert row_key(*(back[k] for k in GRID_KEYS)) == \
        row_key(*(row[k] for k in GRID_KEYS))
