"""Runtime sanitizer wing (`analysis/sanitize.py`): the recompile-budget
watchdog trips on shape-unstable jits and stays quiet on the real attack
step; log_compiles routes into observe events; flags restore on exit."""

import json
import os

import pytest

import jax
import jax.numpy as jnp

from dorpatch_tpu import observe
from dorpatch_tpu.analysis.sanitize import (
    RecompileBudgetExceeded,
    RecompileWatchdog,
    Sanitizer,
)
from dorpatch_tpu.attack import DorPatch
from dorpatch_tpu.config import AttackConfig


def _tiny_attack(cfg, **kw):
    def apply_fn(params, x):
        s = x.mean(axis=(1, 2))  # [B,3]
        return jnp.stack(
            [s[:, 0], s[:, 1], s[:, 2], s.sum(-1) / 3.0], axis=-1) * 10

    kw.setdefault("remat", False)
    return DorPatch(apply_fn, None, 4, cfg, **kw)


def test_watchdog_trips_on_shape_unstable_jit():
    f = observe.timed_first_call(jax.jit(lambda x: x * 2), "unstable",
                                 recompile_budget=1)
    with Sanitizer(debug_nans=False, log_compiles=False):
        f(jnp.ones((4,)))            # first trace: within budget
        f(jnp.ones((4,)))            # same bucket: fine
        with pytest.raises(RecompileBudgetExceeded):
            f(jnp.ones((5,)))        # new shape bucket: over budget


def test_watchdog_counts_buckets_not_calls():
    f = observe.timed_first_call(jax.jit(lambda x: x + 1), "stable",
                                 recompile_budget=2)
    with Sanitizer(debug_nans=False, log_compiles=False):
        for _ in range(5):
            f(jnp.ones((3,)))
        f(jnp.ones((4,)))            # second bucket: exactly at budget
    assert int(f(jnp.ones((3,)))[0]) == 2


def test_watchdog_quiet_on_real_attack_step():
    """The DorPatch blocks are recompile-stable by design (one trace per
    (stage, n_steps) program at fixed batch): a full tiny generate under the
    armed watchdog at budget 1 must not trip. debug_nans stays on — the
    attack's carry math (inf loss_best sentinels included) must be NaN-free."""
    cfg = AttackConfig(sampling_size=4, max_iterations=4, sweep_interval=2,
                       switch_iteration=2, dropout=1, dropout_sizes=(0.25,),
                       basic_unit=4, patch_budget=0.15)
    atk = _tiny_attack(cfg, recompile_budget=1)
    x = jax.random.uniform(jax.random.PRNGKey(2), (1, 16, 16, 3)) * 0.2
    with Sanitizer(log_compiles=False):
        res = atk.generate(x, key=jax.random.PRNGKey(3))
    assert res.adv_mask.shape == (1, 16, 16, 1)


def test_watchdog_skips_unjitted_callables():
    f = observe.timed_first_call(lambda x: x, "plain", recompile_budget=1)
    with Sanitizer(debug_nans=False, log_compiles=False):
        assert f(1) == 1 and f(2) == 2  # no _cache_size: never trips


def test_retrace_within_budget_is_event_not_error(tmp_path):
    elog = observe.EventLog(str(tmp_path / "events.jsonl"))
    f = observe.timed_first_call(jax.jit(lambda x: x * 3), "bucketed",
                                 recompile_budget=4)
    with observe.active(elog), Sanitizer(debug_nans=False,
                                         log_compiles=False):
        f(jnp.ones((2,)))
        f(jnp.ones((3,)))
    elog.close()
    recs = [json.loads(l) for l in open(tmp_path / "events.jsonl")]
    retraces = [r for r in recs if r.get("name") == "sanitize.retrace"]
    assert len(retraces) == 1
    assert retraces[0]["traces"] == 2 and retraces[0]["budget"] == 4


def test_log_compiles_routed_into_events(tmp_path):
    elog = observe.EventLog(str(tmp_path / "events.jsonl"))
    with observe.active(elog), Sanitizer(debug_nans=False,
                                         recompile_budgets=False):
        jax.jit(lambda x: x - 7)(jnp.ones((2,)))
    elog.close()
    recs = [json.loads(l) for l in open(tmp_path / "events.jsonl")]
    compiles = [r for r in recs if r.get("name") == "jax.log_compiles"]
    assert compiles, recs
    assert all(r["message"].startswith("Compiling") for r in compiles)
    # armed/disarmed is visible in the stream
    assert any(r.get("name") == "sanitize.enabled" for r in recs)


def test_debug_nans_raises_at_producing_op():
    with Sanitizer(log_compiles=False, recompile_budgets=False):
        with pytest.raises(FloatingPointError):
            jax.block_until_ready(
                jax.jit(lambda x: jnp.log(x))(jnp.asarray(-1.0)))


def test_sanitizer_restores_global_state():
    prev_nans = bool(jax.config.jax_debug_nans)
    prev_logs = bool(jax.config.jax_log_compiles)
    with Sanitizer():
        assert jax.config.jax_debug_nans
        assert jax.config.jax_log_compiles
        assert observe.recompile_guard() is not None
    assert bool(jax.config.jax_debug_nans) == prev_nans
    assert bool(jax.config.jax_log_compiles) == prev_logs
    assert observe.recompile_guard() is None


def test_budget_declaration_is_inert_without_sanitizer():
    f = observe.timed_first_call(jax.jit(lambda x: x * 2), "inert",
                                 recompile_budget=1)
    f(jnp.ones((4,)))
    f(jnp.ones((5,)))  # over budget, but no guard armed: no error
    assert observe.recompile_guard() is None


def test_watchdog_unit_without_jax_objects():
    class FakeJit:
        def __init__(self):
            self.n = 0

        def _cache_size(self):
            return self.n

    wd = RecompileWatchdog()
    fake = FakeJit()
    fake.n = 1
    wd.after_call("e", fake, 2)
    fake.n = 2
    wd.after_call("e", fake, 2)
    fake.n = 3
    with pytest.raises(RecompileBudgetExceeded):
        wd.after_call("e", fake, 2)


@pytest.mark.slow
def test_pipeline_e2e_under_sanitize(tmp_path):
    """The full tiny synthetic experiment runs clean under --sanitize: no
    NaNs, every jitted entry point within its declared recompile budget,
    and the sanitizer's events land in the run's events.jsonl."""
    from dorpatch_tpu.config import (AttackConfig, DefenseConfig,
                                     ExperimentConfig)
    from dorpatch_tpu.pipeline import run_experiment

    cfg = ExperimentConfig(
        dataset="cifar10",
        base_arch="resnet18",
        batch_size=2,
        num_batches=1,
        synthetic_data=True,
        sanitize=True,
        img_size=32,
        results_root=str(tmp_path / "results"),
        attack=AttackConfig(
            sampling_size=6, max_iterations=4, sweep_interval=2,
            switch_iteration=2, dropout=1, basic_unit=4, patch_budget=0.15,
        ),
        defense=DefenseConfig(ratios=(0.06,), chunk_size=18),
    )
    m = run_experiment(cfg, verbose=False)
    assert "report" in m
    # sanitizer state unwound
    assert not jax.config.jax_debug_nans
    assert observe.recompile_guard() is None
    # its events are in the telemetry stream
    results = next(p for p, _, fs in os.walk(tmp_path)
                   if "events.jsonl" in fs)
    recs = [json.loads(l) for l in open(os.path.join(results,
                                                     "events.jsonl"))]
    names = {r.get("name") for r in recs if r["kind"] == "event"}
    assert "sanitize.enabled" in names
    assert "jax.log_compiles" in names


def test_pipeline_flag_plumbed():
    """--sanitize reaches ExperimentConfig (the pipeline enters Sanitizer
    when set)."""
    from dorpatch_tpu.cli import build_parser, config_from_args

    args = build_parser().parse_args(["--sanitize", "--synthetic"])
    assert config_from_args(args).sanitize is True
    args = build_parser().parse_args(["--synthetic"])
    assert config_from_args(args).sanitize is False
