"""DP106 positives: imports nothing ever touches."""

import json                      # <- DP106 (line 3)
import os.path                   # <- DP106 (line 4): binds `os`, unused
from typing import List, Optional  # <- DP106 x2 (line 5)

VALUE = 1
