"""DP105 positives: jit entry points invisible to the telemetry layer."""

from functools import partial

import jax

step = jax.jit(lambda x: x * 2)       # <- DP105 (line 7): bare assignment


@jax.jit
def decorated(x):                     # <- DP105 (decorator line 10)
    return x + 1


@partial(jax.jit, static_argnums=0)
def decorated_partial(n, x):          # <- DP105 (decorator line 15)
    return x * n


def immediate(model, key, dummy):
    return jax.jit(model.init)(key, dummy)   # <- DP105 (line 21)
