"""Fixture jit programs for the DP2xx program auditor: one deliberately
broken builder per rule, plus clean twins. Imported by
`tests/test_program_audit.py` and by the analysis CLI's `--entrypoints`
override (exit-code tests run `python -m dorpatch_tpu.analysis --trace
--entrypoints trace_programs:bad_entrypoints` with this directory on
PYTHONPATH).

The `weak_carry` builder is the regression fixture for the PR 2 seed bug:
a `jnp.full` init without an explicit dtype is weak-typed, the program's
strong-typed output re-traces every host-level iteration — the exact
`loss_best`/`lr` defect the recompile watchdog caught at runtime, now
pinned at trace time.
"""

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from dorpatch_tpu.analysis.entrypoints import EntryPoint, abstractify

_BIG_HOST_CONST = np.arange(100_000, dtype=np.float32)  # 391 KiB


def _ep(name, fn, *args):
    return EntryPoint(name=name, fn=fn,
                      args=tuple(abstractify(a) for a in args))


def scan_carry():
    """DP201 via trace failure: int32 carry init, float carry out — the
    scan cannot unify the types, and no device ever runs."""

    @jax.jit
    def program(x):
        def body(c, _):
            return c + 0.5, None

        c, _ = lax.scan(body, jnp.asarray(0, jnp.int32), None, length=3)
        return x + c

    return _ep("fx.scan_carry", program, jnp.zeros((2,)))


def weak_carry():
    """DP201 via the boundary check: weak-typed carry init (the PR 2 bug
    class — `jnp.full` with a python scalar), strong-typed carry out."""

    @jax.jit
    def step(state):
        return state * jnp.asarray(2.0, jnp.float32)

    init = jnp.full((4,), jnp.inf)  # no dtype => weak f32, deliberately
    assert init.weak_type
    return _ep("fx.weak_carry", step, init)


def stable_carry():
    """Clean twin of weak_carry: explicit dtype, aval-stable boundary."""

    @jax.jit
    def step(state):
        return state * jnp.asarray(2.0, jnp.float32)

    return _ep("fx.stable_carry", step, jnp.full((4,), jnp.inf, jnp.float32))


def weak_output():
    """DP202: a python-scalar-derived (weak) float escapes the boundary."""

    @jax.jit
    def program(x):
        return x.sum(), jnp.full((2,), 3.0)

    return _ep("fx.weak_output", program, jnp.zeros((4,)))


def host_const():
    """DP203: a 391 KiB host numpy array baked into the program."""

    @jax.jit
    def program(x):
        return x + jnp.asarray(_BIG_HOST_CONST).sum()

    return _ep("fx.host_const", program, jnp.zeros((2,)))


def device_const():
    """Clean twin of host_const: the same bytes as a closed-over DEVICE
    array are a shared buffer, not program bloat (the params idiom)."""
    dev = jnp.asarray(_BIG_HOST_CONST)

    @jax.jit
    def program(x):
        return x + dev.sum()

    return _ep("fx.device_const", program, jnp.zeros((2,)))


def dead_matmul():
    """DP204: a matmul whose result reaches no output."""

    @jax.jit
    def program(x, w):
        _unused = x @ w
        return x.sum()

    return _ep("fx.dead_matmul", program, jnp.zeros((4, 4)),
               jnp.zeros((4, 4)))


def _mesh():
    from jax.sharding import Mesh

    return Mesh(np.asarray(jax.devices()).reshape(-1), ("data",))


def unbound_axis():
    """DP205: shard_map body psum over an axis its mesh does not bind."""
    from jax.experimental.shard_map import shard_map
    from jax.sharding import PartitionSpec as P

    mesh = _mesh()
    program = jax.jit(shard_map(lambda x: lax.psum(x, "model"), mesh=mesh,
                                in_specs=P("data"), out_specs=P()))
    return _ep("fx.unbound_axis", program,
               jnp.zeros((jax.device_count(),)))


def bound_axis():
    """Clean twin of unbound_axis: psum over the bound mesh axis."""
    from jax.experimental.shard_map import shard_map
    from jax.sharding import PartitionSpec as P

    mesh = _mesh()
    program = jax.jit(shard_map(lambda x: lax.psum(x, "data"), mesh=mesh,
                                in_specs=P("data"), out_specs=P()))
    return _ep("fx.bound_axis", program, jnp.zeros((jax.device_count(),)))


def dead_donation():
    """DP206: a donated argument no output can reuse."""
    program = jax.jit(lambda x, y: y.sum(), donate_argnums=0)
    return _ep("fx.dead_donation", program, jnp.zeros((4,)),
               jnp.zeros((3,)))


def live_donation():
    """Clean twin of dead_donation: the output reuses the donated buffer."""
    program = jax.jit(lambda x: x + 1.0, donate_argnums=0)
    return _ep("fx.live_donation", program, jnp.zeros((4,)))


def silent_upcast():
    """DP208: inside a declared-bf16 program (`.bf16` in the name), a bf16
    slab meets an f32 constant and promotion silently lands the product in
    f32 — the exact leak flax's GroupNorm introduced in the bf16 bank.
    The slab is deliberately above the rule's readout-size exemption."""

    @jax.jit
    def program(x):
        h = x.astype(jnp.bfloat16)
        return h * jnp.asarray(2.0, jnp.float32)  # bf16 x f32 -> silent f32

    return _ep("fx.phase1.bf16.upcast", program, jnp.zeros((128, 128)))


def explicit_upcasts():
    """Clean twin of silent_upcast: the same bank-tagged program, but every
    f32 landing is declared — the readout goes through an explicit
    `astype` (convert_element_type) and the matmul requests its f32
    accumulator via `preferred_element_type`."""

    @jax.jit
    def program(x, w):
        h = x.astype(jnp.bfloat16)
        # f32 stats that reduce straight back down (the E[x^2] idiom)
        hf = h.astype(jnp.float32)
        mean = jnp.mean(hf * hf)
        acc = lax.dot(h, w.astype(jnp.bfloat16),
                      preferred_element_type=jnp.float32)
        logits = acc.astype(jnp.bfloat16) * jnp.asarray(0.5, jnp.bfloat16)
        return logits.astype(jnp.float32), mean  # the visible f32 readout

    return _ep("fx.phase1.bf16.clean", program, jnp.zeros((128, 128)),
               jnp.zeros((128, 128)))


def untagged_upcast():
    """Clean by scope: identical math to silent_upcast but the program is
    not declared bf16 (no `.bf16` tag), so DP208 stays out of it — mixed
    precision outside the certified banks is the attack's business."""

    @jax.jit
    def program(x):
        h = x.astype(jnp.bfloat16)
        return h * jnp.asarray(2.0, jnp.float32)

    return _ep("fx.phase1.mixed", program, jnp.zeros((4, 4)))


#: rule id -> (positive builder, clean twin)
PER_RULE = {
    "DP201": (weak_carry, stable_carry),
    "DP202": (weak_output, stable_carry),
    "DP203": (host_const, device_const),
    "DP204": (dead_matmul, None),
    "DP205": (unbound_axis, bound_axis),
    "DP206": (dead_donation, live_donation),
    "DP208": (silent_upcast, explicit_upcasts),
}


def bad_entrypoints():
    """--entrypoints payload: every positive fixture (CLI must exit 1)."""
    return [scan_carry(), weak_carry(), weak_output(), host_const(),
            dead_matmul(), unbound_axis(), dead_donation(), silent_upcast()]


def clean_entrypoints():
    """--entrypoints payload: only clean programs (CLI must exit 0)."""
    return [stable_carry(), device_const(), bound_axis(), live_donation(),
            explicit_upcasts(), untagged_upcast()]
