"""DP502 positives: blocking calls inside `with <lock>` bodies."""
import queue
import threading
import time


class Worker:
    def __init__(self):
        self._lock = threading.Lock()
        self._cond = threading.Condition()
        self._queue = queue.Queue()
        self._thread = threading.Thread(target=self._run, daemon=True)

    def _run(self):
        with self._lock:
            time.sleep(1.0)  # sleep under lock
            item = self._queue.get()  # untimed queue get under lock
            self._thread.join()  # thread join under lock
            return item

    def park(self):
        with self._cond:
            self._cond.wait()  # untimed Condition.wait
