"""DP104 negative: seeds flowing from config/args, not literals."""

import jax

from dorpatch_tpu import utils


def init_state(seed):
    return jax.random.PRNGKey(seed)


def fallback_key():
    return utils.global_key()
