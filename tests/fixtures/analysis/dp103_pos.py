"""DP103 positives: key fed to two consumers with no split between."""

import jax


def double_use(key):
    a = jax.random.uniform(key, (4,))
    b = jax.random.normal(key, (4,))   # <- DP103 (line 8)
    return a + b


def reuse_after_branchless_use(key, flag):
    if flag:
        x = jax.random.uniform(key, (2,))
    else:
        x = jax.random.normal(key, (2,))  # separate paths: both fine
    y = jax.random.bernoulli(key)         # <- DP103 (line 17): used on every path
    return x, y
