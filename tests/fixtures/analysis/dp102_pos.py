"""DP102 positives: host syncs inside jit / loop-body contexts."""

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np


@jax.jit
def step(x):
    v = x.mean().item()          # <- DP102: .item() (line 12)
    h = np.asarray(x)            # <- DP102: np.asarray (line 13)
    s = float(x.sum())           # <- DP102: float() on traced (line 14)
    return v + h.sum() + s


@partial(jax.jit, static_argnums=0)
def step2(n, x):
    return jax.device_get(x) * n  # <- DP102: device_get (line 19)


def outer(xs):
    def body(carry, x):
        x.block_until_ready()    # <- DP102: sync in scan body (line 24)
        return carry, x

    return jax.lax.scan(body, 0.0, xs)


def fori(x):
    def body_fun(i, acc):
        return acc + int(x[i])   # <- DP102: int() on traced (line 32)

    return jax.lax.fori_loop(0, 3, body_fun, jnp.float32(0))
