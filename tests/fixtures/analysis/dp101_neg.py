"""DP101 negative: every legal shape the old tokenize guard allowed."""

import sys

from dorpatch_tpu import observe

# print( in a comment is fine
S = "print(also fine in a string)"
log = print  # referencing the callable is fine


def report(x):
    observe.log(f"loss: {x}")
    sys.stdout.write("raw\n")
    return x
