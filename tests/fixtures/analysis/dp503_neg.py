"""DP503 negatives: daemon threads, joined threads, state-before-start."""
import threading


class Runner:
    def __init__(self):
        self._lock = threading.Lock()
        self._state = "idle"  # guarded-by: self._lock
        self._worker = threading.Thread(target=self._run, daemon=True)
        self._worker.start()  # daemon: exempt; _state already assigned
        self._sweeper = threading.Thread(target=self._run)
        self._sweeper.start()

    def _run(self):
        pass

    def stop(self):
        self._sweeper.join()  # lifecycle method joins the non-daemon


def run_local():
    t = threading.Thread(target=max)
    t.start()
    t.join()  # started and joined in the same function
