"""DP501 positive: a planted ABBA lock-order cycle."""
import threading


class Transfer:
    def __init__(self):
        self._alock = threading.Lock()
        self._block = threading.Lock()

    def forward(self):
        with self._alock:
            with self._block:
                pass

    def backward(self):
        with self._block:
            with self._alock:  # inverted: closes the cycle
                pass
