"""DP500 positives: guarded attributes mutated outside their lock."""
import threading


class Pool:
    def __init__(self):
        self._lock = threading.Lock()
        self._items = []  # guarded-by: self._lock
        self._count = 0  # guarded-by: self._lock

    def add(self, item):
        self._items.append(item)  # mutator call, lock not held
        self._count += 1  # augmented assign, lock not held

    def reset(self, other_lock):
        with other_lock:
            self._items.clear()  # the WRONG lock is held
