"""DP102 negatives: static uses inside jit, host syncs outside any trace."""

import jax
import jax.numpy as jnp
import numpy as np


@jax.jit
def step(x):
    b = int(x.shape[0])      # static under trace: fine
    n = float(len(x.shape))  # static: fine
    k = int(3)               # constant: fine
    return x * b * n * k


def host_driver(x):
    # not a jit context: host syncs are the whole point here
    y = jax.device_get(x)
    z = float(np.asarray(y).mean())
    jnp.asarray(x).block_until_ready()
    return z + x.mean().item()
