"""DP101 positive: bare print in (logical) package code."""


def report(x):
    print("loss:", x)  # <- DP101 (line 5)
    return x
