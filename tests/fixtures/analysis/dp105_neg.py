"""DP105 negatives: every wrapped shape used in the real tree."""

from functools import partial

import jax

from dorpatch_tpu import observe

# direct wrap of the jit call
step = observe.timed_first_call(jax.jit(lambda x: x * 2), "step",
                                recompile_budget=1)


@partial(jax.jit, static_argnums=())
def run_block(state):
    return state


# wrap-by-name after a decorated def (the attack.py idiom)
run_block = observe.timed_first_call(run_block, "block")


@jax.jit
def eval_step(x):
    return x + 1


eval_step = observe.timed_first_call(eval_step, "eval", recompile_budget=2)
