"""DP104 positive: hard-coded PRNGKey literal outside utils.py/tests."""

import jax


def init_state():
    return jax.random.PRNGKey(0)   # <- DP104 (line 7)
