"""Every violation here carries a suppression — the file must lint clean."""

import json  # noqa
import os    # noqa: DP106 — kept for interactive debugging
import sys   # noqa: F401 (flake8-alias for DP106)

import jax


def report(x):
    print("loss:", x)  # noqa: DP101 — fixture demonstrates suppression
    return x


def seeded():
    return jax.random.PRNGKey(0)  # noqa: DP104 — fixture fixed seed


def double(key):
    a = jax.random.uniform(key, (2,))
    b = jax.random.normal(key, (2,))  # noqa: DP103 — deliberate correlation
    return a + b


step = jax.jit(lambda x: x)  # noqa: DP105 — fixture, telemetry not wanted
