"""DP502 negatives: timed waits under locks, blocking calls outside."""
import os
import queue
import threading
import time


class Worker:
    def __init__(self):
        self._lock = threading.Lock()
        self._cond = threading.Condition()
        self._queue = queue.Queue()

    def _run(self):
        with self._lock:
            item = self._queue.get(timeout=1.0)  # timed: bounded stall
        time.sleep(0.1)  # outside the lock
        return item

    def park(self):
        with self._cond:
            self._cond.wait(0.5)  # timed wait: bounded stall

    def render(self, parts, name):
        with self._lock:
            text = ", ".join(parts)  # str.join is pure
            return os.path.join(text, name)  # os.path.join is pure
