"""DP504 negatives: monotonic liveness; wall time without a liveness
bound."""
import time


class Lease:
    def __init__(self, ttl):
        self.ttl = float(ttl)
        self._last = time.monotonic()

    def expired(self):
        return time.monotonic() - self._last > self.ttl  # monotonic: fine

    def wall_stamp_is_sane(self):
        started = time.time()
        return started > 0  # wall, but no liveness word in the compare
