"""DP503 positives: unjoined non-daemon threads; a start() in __init__
before guarded state is assigned."""
import threading


class Runner:
    def __init__(self):
        self._lock = threading.Lock()
        self._worker = threading.Thread(target=self._run)  # never joined
        self._worker.start()  # starts before _state exists
        self._state = "idle"  # guarded-by: self._lock

    def _run(self):
        pass

    def stop(self):
        pass  # no join: process exit hangs on _worker


def fire_and_forget():
    threading.Thread(target=max).start()  # no reference left to join


def run_local():
    t = threading.Thread(target=max)
    t.start()  # local thread started, never joined here
    return t.name
