"""DP504 positives: wall-clock values compared against liveness bounds."""
import time


class Lease:
    def __init__(self, ttl, clock=time.time):
        self.ttl = float(ttl)
        self._clock = clock  # wall by default: tainted rebind
        self._last = 0.0

    def expired(self):
        return self._clock() - self._last > self.ttl  # wall vs ttl

    def before(self, deadline):
        now = time.time()
        return now < deadline  # tainted local vs deadline
