"""DP501 negative: both paths acquire in the same (canonical) order."""
import threading


class Transfer:
    def __init__(self):
        self._alock = threading.Lock()
        self._block = threading.Lock()

    def forward(self):
        with self._alock:
            with self._block:
                pass

    def backward(self):
        with self._alock:
            with self._block:
                pass
