"""DP500 negatives: every mutation under the declared lock; reads and
__init__ assignments are exempt."""
import threading


class Pool:
    def __init__(self):
        self._lock = threading.Lock()
        self._items = []  # guarded-by: self._lock
        self._count = 0  # guarded-by: self._lock
        self._free = []  # no annotation: unguarded by contract

    def add(self, item):
        with self._lock:
            self._items.append(item)
            self._count += 1

    def snapshot(self):
        with self._lock:
            return list(self._items), self._count

    def peek_len(self):
        return len(self._items)  # a read, not a mutation

    def recycle(self, item):
        self._free.append(item)  # unannotated attr: out of contract
