"""DP107 positives: host syncs in a serve/ worker loop outside the
designated marshalling function (linted as dorpatch_tpu/serve/worker.py)."""

import jax
import numpy as np

WARM_TABLE = np.asarray(jax.device_put([1.0]))  # <- DP107 (line 7):
#    module-level np.asarray sync


def run_batch(programs, params, x):
    logits = programs.clean(params, x)
    logits.block_until_ready()            # <- DP107: sync in worker
    table = jax.device_get(logits)        # <- DP107: device_get
    table2 = np.asarray(logits)           # <- DP107: np.asarray sync
    return table, table2


def worker_loop(batcher, programs, params):
    while True:
        batch = batcher.next_batch()
        if batch is None:
            return
        preds = run_batch(programs, params, batch)
        score = preds[0].mean().item()    # <- DP107: .item()
        batcher.report(score)
