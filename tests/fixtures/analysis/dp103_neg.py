"""DP103 negatives: split-before-reuse discipline, rebinding, fold_in."""

import jax
from jax import random as jr


def disciplined(key):
    k1, k2 = jax.random.split(key)
    a = jax.random.uniform(k1, (4,))
    b = jax.random.normal(k2, (4,))
    return a + b


def rebind(key):
    x = jr.uniform(key, (2,))
    key = jr.fold_in(key, 1)       # fold_in derives; not a consumer
    key, sub = jr.split(key)
    y = jr.uniform(sub, (2,))
    return x + y


def loop_carry(key, n):
    total = 0.0
    for i in range(n):
        key, sub = jax.random.split(key)
        total = total + jax.random.uniform(sub)
    return total
