"""DP106 negatives: used imports, __all__ exports, `as`-re-exports."""

from __future__ import annotations

import json
import os.path
from typing import List
from typing import Optional as Optional  # explicit re-export: exempt

from dorpatch_tpu.analysis.engine import Finding  # exported via __all__

__all__ = ["Finding", "dumps_path"]


def dumps_path(paths: List[str]) -> str:
    return json.dumps([os.path.basename(p) for p in paths])
