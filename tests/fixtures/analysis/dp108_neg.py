"""DP108 negatives: accounting through the registry, local loop
bookkeeping, and reasoned control-state escapes (linted as
dorpatch_tpu/serve/worker.py)."""

from dorpatch_tpu import observe


class Batcher:
    def __init__(self, metrics: observe.MetricRegistry):
        self.metrics = metrics
        self.restarts = 0

    def account(self, reqs, status):
        # the sanctioned spelling: one registry, one set of books
        self.metrics.counter("serve_requests_total").inc(
            len(reqs), status=status)

    def drain(self, batches):
        done = 0                          # plain local: loop bookkeeping
        counts = {}
        for batch in batches:
            done += 1
            counts[batch.status] = counts.get(batch.status, 0)
            counts[batch.status] += 1     # Name-rooted subscript: local dict
        return done, counts

    def restart(self):
        # genuine control state (supervisor decision input, not telemetry)
        self.restarts += 1  # noqa: DP108 — restart budget, not a metric
