"""Fixture entry points for the program-baseline tier (DP300-DP304).

Cheap, dependency-free jit programs exercising the fingerprint and cost
machinery: a reference program, a local-rename twin (identical canonical
jaxpr), a literal-change twin (different fingerprint), a planted +~20%
FLOPs regression twin (extra matmul — DP301 with `dot_general` dominant),
and a donation twin (interface drift with an unchanged body — DP304).

`clean_entrypoints` / `regressed_entrypoints` are `--entrypoints`
loaders for CLI-level tests: both register the same names, so checking
`regressed` against a baseline built from `clean` yields pure rule
findings with no DP302 set-drift noise.
"""

import jax
import jax.numpy as jnp

from dorpatch_tpu.analysis.entrypoints import EntryPoint, abstractify

_X = abstractify(jnp.zeros((8, 16), jnp.float32))
_W = abstractify(jnp.zeros((16, 16), jnp.float32))


@jax.jit
def _ref(x, w):
    y = jnp.tanh(x @ w)
    return y.sum(axis=-1)


@jax.jit
def _renamed(a, b):  # same program, different python locals/arg names
    hidden = jnp.tanh(a @ b)
    return hidden.sum(axis=-1)


@jax.jit
def _literal(x, w):  # one literal changed: 2.0 scale on the activation
    y = jnp.tanh(x @ w) * 2.0
    return y.sum(axis=-1)


@jax.jit
def _regressed(x, w):  # planted regression: a second matmul (~+100% flops)
    y = jnp.tanh((x @ w) @ w)
    return y.sum(axis=-1)


def _step(x, w):
    return x + w, w


_carry = jax.jit(_step)
_carry_donated = jax.jit(_step, donate_argnums=(0,))


def ref_entrypoint(name="fx.base.ref"):
    return EntryPoint(name=name, fn=_ref, args=(_X, _W))


def renamed_entrypoint(name="fx.base.ref"):
    return EntryPoint(name=name, fn=_renamed, args=(_X, _W))


def literal_entrypoint(name="fx.base.ref"):
    return EntryPoint(name=name, fn=_literal, args=(_X, _W))


def regressed_entrypoint(name="fx.base.ref"):
    return EntryPoint(name=name, fn=_regressed, args=(_X, _W))


def carry_entrypoint(name="fx.base.carry"):
    return EntryPoint(name=name, fn=_carry, args=(_X, _X))


def carry_donated_entrypoint(name="fx.base.carry"):
    return EntryPoint(name=name, fn=_carry_donated, args=(_X, _X))


def clean_entrypoints():
    """--entrypoints loader: the reference program set."""
    return [ref_entrypoint(), carry_entrypoint()]


def regressed_entrypoints():
    """--entrypoints loader: same names, one planted +~100% FLOPs
    regression (DP301) and one donation flip (DP304)."""
    return [regressed_entrypoint(), carry_donated_entrypoint()]


def shrunk_entrypoints():
    """--entrypoints loader: a strict subset of `clean_entrypoints` — the
    shape of a single-device regeneration that silently loses the mesh
    tier's entries (the `--allow-remove` guard's target)."""
    return [ref_entrypoint()]
