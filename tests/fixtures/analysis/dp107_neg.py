"""DP107 negatives: syncs live only in the designated marshal_response
function; the worker stays dispatch-only (linted as
dorpatch_tpu/serve/worker.py)."""

import jax
import numpy as np


def marshal_response(reqs, logits):
    # the ONE sanctioned host-sync point: materialize + deadline-check here
    table = jax.device_get(logits)
    return [(r, int(t.argmax()), float(t.max().item())) for r, t in
            zip(reqs, table)]


def run_batch(programs, params, reqs, x):
    # dispatch-only: H2D transfer and jit calls never block on results
    logits = programs.clean(params, jax.device_put(np.stack(x)))
    return marshal_response(reqs, logits)


def worker_loop(batcher, programs, params):
    while True:
        batch = batcher.next_batch()
        if batch is None:
            return
        for resp in run_batch(programs, params, batch, [r.image for r in batch]):
            resp[0].resolve(resp)
