"""DP108 positives: hand-rolled counter/gauge state mutated in a serve/
worker path instead of the observe.metrics registry (linted as
dorpatch_tpu/serve/worker.py)."""


class Batcher:
    def __init__(self):
        self.completed = 0
        self.depth = 0
        self._counts = {}

    def account(self, reqs, status):
        self.completed += 1               # <- DP108: shadow counter
        self._counts[status] += 1         # <- DP108: attr-rooted tally dict
        self.depth -= len(reqs)           # <- DP108: shadow gauge
        return self.completed
