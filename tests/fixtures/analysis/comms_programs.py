"""Fixture jit programs for the DP6xx sharding & collectives auditor: one
deliberately broken builder per rule plus clean twins. Imported by
`tests/test_comms_audit.py` and by the analysis CLI's `--entrypoints`
override (exit-code tests run `python -m dorpatch_tpu.analysis --comms
--entrypoints comms_programs:bad_entrypoints` with this directory on
PYTHONPATH). Every builder needs a multi-device host (the test gate's
8-device virtual CPU mesh).

The DP603 clean twin is deliberately the *production* pattern, not a toy:
`ops.masked_fill` under its mesh wrapper — the Pallas forward inside
`shard_map`, the backward kernel whose *output* feeds the mask-axis
`psum`. That is the shard-local proof the rule exists to certify; the
positive twins plant the two ways the proof fails (a bare `pallas_call`
under the mesh, and a collective result feeding kernel operands).
"""

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.shard_map import shard_map
from jax.sharding import Mesh, NamedSharding
from jax.sharding import PartitionSpec as P

from dorpatch_tpu import ops
from dorpatch_tpu.analysis.entrypoints import EntryPoint, abstractify


def _ep(name, fn, *args):
    return EntryPoint(name=name, fn=fn,
                      args=tuple(abstractify(a) for a in args))


def _mesh1d():
    return Mesh(np.asarray(jax.devices()).reshape(-1), ("data",))


def _mesh2d():
    return Mesh(np.asarray(jax.devices()).reshape(1, -1), ("data", "mask"))


_SM_KW = {"check_rep": False}


def grouped_psum():
    """DP600: a psum partitioned by axis_index_groups — the mesh-axis
    product does not price its groups, so the comm vector has a hole."""
    mesh = _mesh1d()
    groups = [list(range(jax.device_count() // 2)),
              list(range(jax.device_count() // 2, jax.device_count()))]
    program = jax.jit(shard_map(
        lambda x: lax.psum(x, "data", axis_index_groups=groups),
        mesh=mesh, in_specs=P("data"), out_specs=P("data"), **_SM_KW))
    return _ep("fx.grouped_psum", program,
               jnp.zeros((jax.device_count(), 4)))


def priced_psum():
    """Clean twin of grouped_psum: a plain bound-axis psum prices exactly
    (operand bytes x axis size) and fires nothing."""
    mesh = _mesh1d()
    program = jax.jit(shard_map(
        lambda x: lax.psum(x, "data"), mesh=mesh,
        in_specs=P("data"), out_specs=P(), **_SM_KW))
    return _ep("fx.priced_psum", program, jnp.zeros((jax.device_count(), 4)))


def replicated_operand():
    """DP601: a 512 KiB shard_map operand fully replicated (P()) although
    the size-8 data axis divides its leading dim."""
    mesh = _mesh1d()
    program = jax.jit(shard_map(
        lambda big, y: y + big.sum(), mesh=mesh,
        in_specs=(P(), P("data")), out_specs=P("data"), **_SM_KW))
    big = jnp.zeros((jax.device_count(), 16384), jnp.float32)  # 512 KiB
    return _ep("fx.replicated_operand", program, big,
               jnp.zeros((jax.device_count(), 4)))


def sharded_operand():
    """Clean twin of replicated_operand: the large tensor shards P(data);
    the small replicated scale tensor stays under the byte threshold (the
    intended weight-replication idiom)."""
    mesh = _mesh1d()
    program = jax.jit(shard_map(
        lambda big, s: big * s, mesh=mesh,
        in_specs=(P("data"), P()), out_specs=P("data"), **_SM_KW))
    big = jnp.zeros((jax.device_count(), 16384), jnp.float32)
    return _ep("fx.sharded_operand", program, big,
               jnp.zeros((16384,), jnp.float32))


def chained_reshard():
    """DP602: one value pinned to P(data) and immediately re-pinned to a
    different placement — an implicit reshard at dispatch."""
    mesh = _mesh2d()

    @jax.jit
    def program(x):
        y = lax.with_sharding_constraint(x, NamedSharding(mesh, P("data")))
        return lax.with_sharding_constraint(
            y, NamedSharding(mesh, P(None, "mask")))

    return _ep("fx.chained_reshard", program, jnp.zeros((8, 8)))


def single_pin():
    """Clean twin of chained_reshard: one placement per value."""
    mesh = _mesh2d()

    @jax.jit
    def program(x):
        y = lax.with_sharding_constraint(x, NamedSharding(mesh, P("data")))
        return y * 2.0

    return _ep("fx.single_pin", program, jnp.zeros((8, 8)))


def _add_one_kernel(x_ref, o_ref):
    o_ref[...] = x_ref[...] + 1.0


def _pallas_add_one(x):
    return pl.pallas_call(
        _add_one_kernel,
        out_shape=jax.ShapeDtypeStruct(x.shape, x.dtype),
        interpret=True)(x)


def bare_kernel_under_mesh():
    """DP603 (a): a mesh program (it contains a shard_map) whose
    pallas_call sits OUTSIDE the shard_map — a custom call GSPMD cannot
    partition."""
    mesh = _mesh1d()
    reduce_ = shard_map(lambda x: lax.psum(x, "data"), mesh=mesh,
                        in_specs=P("data"), out_specs=P(), **_SM_KW)

    @jax.jit
    def program(x):
        y = _pallas_add_one(x)  # bare: not under the shard_map
        return reduce_(y)

    return _ep("fx.bare_kernel_under_mesh", program, jnp.zeros((8, 8)))


def collective_fed_kernel():
    """DP603 (b): inside the shard_map, a psum result flows into the
    kernel's operands — the kernel consumes cross-shard data, so the
    shard-local proof fails."""
    mesh = _mesh1d()

    def body(x):
        g = lax.psum(x, "data")
        return _pallas_add_one(g)

    program = jax.jit(shard_map(body, mesh=mesh, in_specs=P("data"),
                                out_specs=P("data"), **_SM_KW))
    return _ep("fx.collective_fed_kernel", program, jnp.zeros((8, 8)))


def shard_local_kernel():
    """DP603 clean proof, production pattern: `ops.masked_fill` under its
    mesh wrapper — forward kernel per shard inside shard_map, backward
    kernel whose OUTPUT feeds the mask-axis psum. Nothing crosses shards
    before a kernel runs."""
    mesh = _mesh2d()
    n_masks = int(mesh.shape["mask"])

    @jax.jit
    def program(imgs, rects):
        def total(im):
            return ops.masked_fill(im, rects, 0.5, "interpret",
                                   mesh=mesh).sum()

        return jax.value_and_grad(total)(imgs)

    return _ep("fx.shard_local_kernel", program,
               jnp.zeros((2, 16, 16, 3)),
               jnp.zeros((n_masks, 1, 4), jnp.int32))


#: rule id -> (positive builder(s), clean twin)
PER_RULE = {
    "DP600": ((grouped_psum,), priced_psum),
    "DP601": ((replicated_operand,), sharded_operand),
    "DP602": ((chained_reshard,), single_pin),
    "DP603": ((bare_kernel_under_mesh, collective_fed_kernel),
              shard_local_kernel),
}


def bad_entrypoints():
    """--entrypoints payload: every positive fixture (CLI must exit 1)."""
    return [grouped_psum(), replicated_operand(), chained_reshard(),
            bare_kernel_under_mesh(), collective_fed_kernel()]


def clean_entrypoints():
    """--entrypoints payload: only clean programs (CLI must exit 0)."""
    return [priced_psum(), sharded_operand(), single_pin(),
            shard_local_kernel()]
