"""Pallas fused rasterize+fill vs the jnp reference path.

Runs the kernel in interpreter mode (tests force the CPU platform, see
conftest.py); the lowered TPU path shares the same kernel body.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dorpatch_tpu import masks as masks_lib
from dorpatch_tpu.ops import masked_fill, masked_fill_reference


def _random_rects(key, n_mask, n_rect, img_size):
    k1, k2 = jax.random.split(key)
    lo = jax.random.randint(k1, (n_mask, n_rect, 2), 0, img_size)
    ext = jax.random.randint(k2, (n_mask, n_rect, 2), 0, img_size // 2)
    return jnp.stack(
        [lo[..., 0], jnp.minimum(lo[..., 0] + ext[..., 0], img_size),
         lo[..., 1], jnp.minimum(lo[..., 1] + ext[..., 1], img_size)], axis=-1
    ).astype(jnp.int32)


@pytest.mark.parametrize("n_rect", [1, 2])
def test_pallas_forward_matches_reference(n_rect):
    key = jax.random.PRNGKey(0)
    imgs = jax.random.uniform(key, (2, 16, 16, 3))
    rects = _random_rects(jax.random.PRNGKey(1), 5, n_rect, 16)
    out_ref = masked_fill_reference(imgs, rects, 0.5)
    out_pl = masked_fill(imgs, rects, 0.5, use_pallas="interpret")
    np.testing.assert_array_equal(np.asarray(out_pl), np.asarray(out_ref))


def test_pallas_handles_zero_area_padding_rects():
    imgs = jax.random.uniform(jax.random.PRNGKey(0), (1, 8, 8, 3))
    rects = jnp.zeros((3, 2, 4), jnp.int32)  # pad_rects-style no-op rows
    out = masked_fill(imgs, rects, 0.5, use_pallas="interpret")
    np.testing.assert_array_equal(
        np.asarray(out), np.broadcast_to(imgs[:, None], out.shape))


def test_pallas_gradient_matches_reference():
    imgs = jax.random.uniform(jax.random.PRNGKey(2), (2, 12, 12, 3))
    rects = _random_rects(jax.random.PRNGKey(3), 4, 2, 12)

    def loss_ref(x):
        return jnp.sum(jnp.sin(masked_fill_reference(x, rects, 0.5)))

    def loss_pl(x):
        return jnp.sum(jnp.sin(masked_fill(x, rects, 0.5, use_pallas="interpret")))

    g_ref = jax.grad(loss_ref)(imgs)
    g_pl = jax.grad(loss_pl)(imgs)
    np.testing.assert_allclose(np.asarray(g_pl), np.asarray(g_ref), atol=1e-6)


def test_pallas_matches_real_mask_geometry():
    """The actual PatchCleanser mask families rasterize identically."""
    img_size = 32
    spec = masks_lib.geometry(img_size, 0.06, 1)
    singles, doubles = masks_lib.mask_sets(spec)
    rects = jnp.asarray(masks_lib.pad_rects(doubles, 2))
    imgs = jax.random.uniform(jax.random.PRNGKey(4), (1, img_size, img_size, 3))
    out_ref = masked_fill_reference(imgs, rects, 0.5)
    out_pl = masked_fill(imgs, rects, 0.5, use_pallas="interpret")
    np.testing.assert_array_equal(np.asarray(out_pl), np.asarray(out_ref))


@pytest.mark.slow
def test_attack_step_pallas_matches_reference_path():
    """A jitted attack block produces the same trajectory with the fused
    Pallas kernel (interpret mode) as with the jnp reference path."""
    from dorpatch_tpu.attack import DorPatch
    from dorpatch_tpu.config import AttackConfig

    def apply_fn(params, x):
        s = x.mean(axis=(1, 2))
        return jnp.stack([s[:, 0], s[:, 1], s[:, 2], s.sum(-1)], -1) * 10

    def run(use_pallas):
        cfg = AttackConfig(sampling_size=4, dropout=1, basic_unit=4,
                           dropout_sizes=(0.06,), use_pallas=use_pallas)
        atk = DorPatch(apply_fn, None, 4, cfg, remat=False)
        x = jax.random.uniform(jax.random.PRNGKey(7), (1, 16, 16, 3))
        universe = jnp.asarray(masks_lib.dropout_universe(16, 1, (0.06,)))
        lv = jnp.mean(jax.grad(lambda z: jnp.sum(z))(x), -1)  # placeholder stats
        state = atk._init_state(jax.random.PRNGKey(8), x,
                                jnp.zeros((1,), jnp.int32), False,
                                universe.shape[0])
        block = atk._get_block(1, 16, 3)
        return block(state, x, lv, universe)

    s_ref = run("off")
    s_pl = run("interpret")
    np.testing.assert_allclose(np.asarray(s_pl.adv_pattern),
                               np.asarray(s_ref.adv_pattern), atol=1e-6)
    np.testing.assert_allclose(np.asarray(s_pl.metrics),
                               np.asarray(s_ref.metrics), rtol=1e-5, atol=1e-6)


def test_masked_fill_auto_dispatch_off_tpu():
    """On CPU, auto resolves to the reference path (no pallas lowering)."""
    imgs = jnp.full((1, 8, 8, 3), 0.25)
    rects = jnp.asarray([[[0, 4, 0, 4]]], jnp.int32)
    out = masked_fill(imgs, rects, 0.5)  # auto
    assert np.asarray(out)[0, 0, 0, 0, 0] == 0.5
    assert np.asarray(out)[0, 0, 7, 7, 0] == 0.25
