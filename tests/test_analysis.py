"""The static-analysis engine: per-rule positive/negative fixtures,
suppression syntax, CLI exit codes, and the shipped tree staying clean.

Fixtures live in `tests/fixtures/analysis/`; path-scoped rules (DP101 is
package-only, DP104 exempts utils.py/tests) are exercised through the
engine's `logical_path` override so the fixtures can live here while
linting as if they were package files.
"""

import pathlib
import subprocess
import sys

import pytest

from dorpatch_tpu.analysis import (
    Finding,
    all_rules,
    analyze_file,
    analyze_paths,
    analyze_source,
)
from dorpatch_tpu.analysis.cli import main as cli_main

REPO = pathlib.Path(__file__).resolve().parents[1]
FIXTURES = REPO / "tests" / "fixtures" / "analysis"

RULE_IDS = ("DP101", "DP102", "DP103", "DP104", "DP105", "DP106", "DP107",
            "DP108")


def run_fixture(name: str, rule_id: str):
    """Lint one fixture as if it lived at dorpatch_tpu/<name>, keeping only
    the rule under test (fixtures legitimately trip other rules: e.g. the
    DP102 positives use undecorated prints of their own). DP107/DP108
    fixtures lint as serve/ files (those rules are scoped to the serving
    subpackages)."""
    logical = (f"dorpatch_tpu/serve/{name}"
               if name.startswith(("dp107", "dp108"))
               else f"dorpatch_tpu/{name}")
    findings = analyze_file(FIXTURES / name, logical_path=logical)
    return [f for f in findings if f.rule_id == rule_id]


# ---------- per-rule positives / negatives ----------

@pytest.mark.parametrize("rule_id", RULE_IDS)
def test_rule_positive_fixture_fires(rule_id):
    found = run_fixture(f"{rule_id.lower()}_pos.py", rule_id)
    assert found, f"{rule_id} did not fire on its positive fixture"
    assert all(f.rule_id == rule_id for f in found)
    assert all(f.line > 0 for f in found)


@pytest.mark.parametrize("rule_id", RULE_IDS)
def test_rule_negative_fixture_clean(rule_id):
    found = run_fixture(f"{rule_id.lower()}_neg.py", rule_id)
    assert not found, f"false positives: {found}"


def test_dp101_expected_lines():
    (f,) = run_fixture("dp101_pos.py", "DP101")
    assert f.line == 5


def test_dp102_catches_each_sync_kind():
    found = run_fixture("dp102_pos.py", "DP102")
    msgs = " | ".join(f.message for f in found)
    for kind in (".item()", "numpy.asarray", "float()", "jax.device_get",
                 "block_until_ready", "int()"):
        assert kind in msgs, f"missing {kind}: {msgs}"


def test_dp106_counts_and_fixable():
    found = run_fixture("dp106_pos.py", "DP106")
    assert len(found) == 4  # json, os.path, List, Optional
    assert all(f.fixable for f in found)


# ---------- path scoping ----------

def test_dp101_exempt_inside_observe():
    findings = analyze_file(FIXTURES / "dp101_pos.py",
                            logical_path="dorpatch_tpu/observe/x.py")
    assert not [f for f in findings if f.rule_id == "DP101"]


def test_dp101_exempt_outside_package():
    findings = analyze_file(FIXTURES / "dp101_pos.py",
                            logical_path="tools/x.py")
    assert not [f for f in findings if f.rule_id == "DP101"]


@pytest.mark.parametrize("logical", ["dorpatch_tpu/utils.py",
                                     "tests/seeded.py"])
def test_dp104_exemptions(logical):
    findings = analyze_file(FIXTURES / "dp104_pos.py", logical_path=logical)
    assert not [f for f in findings if f.rule_id == "DP104"]


def test_dp107_catches_each_sync_kind():
    found = run_fixture("dp107_pos.py", "DP107")
    msgs = " | ".join(f.message for f in found)
    for kind in (".item()", "block_until_ready", "jax.device_get",
                 "numpy.asarray"):
        assert kind in msgs, f"missing {kind}: {msgs}"
    # module-level statements are scanned too (line 7's np.asarray)
    assert any(f.line == 7 for f in found), [f.line for f in found]


@pytest.mark.parametrize("logical", [
    "dorpatch_tpu/pipeline.py",      # outside serve/: DP107 does not apply
    "tools/serve/loadgen.py",        # tools tree is never package scope
    "tests/serve/test_worker.py",    # test tree exempt
])
def test_dp107_scoped_to_serve_subpackage(logical):
    findings = analyze_file(FIXTURES / "dp107_pos.py", logical_path=logical)
    assert not [f for f in findings if f.rule_id == "DP107"]


def test_dp108_counts_each_mutation_kind():
    found = run_fixture("dp108_pos.py", "DP108")
    assert len(found) == 3, [f.render() for f in found]
    msgs = " | ".join(f.message for f in found)
    assert "<obj>.completed +=" in msgs
    assert "<obj>._counts[...] +=" in msgs
    assert "<obj>.depth -=" in msgs


@pytest.mark.parametrize("logical", [
    "dorpatch_tpu/farm/worker.py",   # farm/ is in scope too
    "dorpatch_tpu/serve/pool.py",
])
def test_dp108_fires_across_serving_subpackages(logical):
    findings = analyze_file(FIXTURES / "dp108_pos.py", logical_path=logical)
    assert [f.rule_id for f in findings if f.rule_id == "DP108"] \
        == ["DP108"] * 3


@pytest.mark.parametrize("logical", [
    "dorpatch_tpu/attack.py",        # outside serve//farm/: not counters'
    "tools/serve/loadgen.py",        # tools tree is never package scope
    "tests/serve/test_worker.py",    # test tree exempt
])
def test_dp108_scoped_to_serving_subpackages(logical):
    findings = analyze_file(FIXTURES / "dp108_pos.py", logical_path=logical)
    assert not [f for f in findings if f.rule_id == "DP108"]


def test_dp108_name_rooted_subscript_exempt():
    """`counts[k] += 1` on a plain local stays exempt — only attribute
    state (`self.x += 1`, `self.d[k] += 1`) is published accounting."""
    src = ("def drain(batches):\n"
           "    counts = {}\n"
           "    for b in batches:\n"
           "        counts[b.status] = counts.get(b.status, 0)\n"
           "        counts[b.status] += 1\n"
           "    return counts\n")
    assert analyze_source(src, logical_path="dorpatch_tpu/farm/queue.py",
                          select=["DP108"]) == []


def test_dp107_nested_def_inside_marshal_is_exempt():
    """Each def is judged by its own name: marshal_response's own body
    (incl. comprehensions) is exempt, but a helper nested inside it does
    NOT inherit the exemption."""
    src = ("import jax\n"
           "def marshal_response(logits):\n"
           "    vals = [v.item() for v in jax.device_get(logits)]\n"
           "    def helper(x):\n"
           "        return x.item()\n"
           "    return vals, helper\n")
    found = analyze_source(src, logical_path="dorpatch_tpu/serve/service.py",
                           select=["DP107"])
    assert len(found) == 1 and found[0].line == 5


# ---------- suppression syntax ----------

def test_suppressed_fixture_fully_clean():
    findings = analyze_file(FIXTURES / "suppressed.py",
                            logical_path="dorpatch_tpu/suppressed.py")
    assert findings == []


def test_noqa_wrong_code_does_not_suppress():
    src = "import jax\nk = jax.random.PRNGKey(7)  # noqa: DP101\n"
    findings = analyze_source(src, logical_path="dorpatch_tpu/x.py")
    assert [f.rule_id for f in findings] == ["DP104"]


def test_blanket_noqa_suppresses_everything_on_the_line():
    src = "import jax\nk = jax.random.PRNGKey(7)  # noqa\n"
    findings = analyze_source(src, logical_path="dorpatch_tpu/x.py")
    assert findings == []


def test_syntax_error_is_a_dp000_finding():
    findings = analyze_source("def broken(:\n")
    assert len(findings) == 1 and findings[0].rule_id == "DP000"


def test_dp103_lambda_body_is_not_an_inline_use():
    """A lambda's draws happen at call time: defining a closure over a key
    after using it is not reuse at the definition site (regression:
    double-scanning lambda bodies as inline expressions)."""
    src = ("import jax\n"
           "def f(key):\n"
           "    x = jax.random.uniform(key, (2,))\n"
           "    sampler = lambda: jax.random.normal(key, (2,))\n"
           "    return x, sampler\n")
    findings = analyze_source(src, logical_path="dorpatch_tpu/x.py",
                              select=["DP103"])
    assert findings == []


def test_dp103_loop_and_with_targets_rebind():
    """`for key in split(...)` and `with ... as key` rebind the name —
    fresh value, fresh state (regression: loop/with targets never reset)."""
    src = ("import jax\n"
           "def f(master, ctx):\n"
           "    x = jax.random.uniform(master, (2,))\n"
           "    for master in jax.random.split(master, 4):\n"
           "        y = jax.random.normal(master, (2,))\n"
           "    with ctx() as master:\n"
           "        z = jax.random.gumbel(master, (2,))\n"
           "    return x, y, z\n")
    assert analyze_source(src, logical_path="dorpatch_tpu/x.py",
                          select=["DP103"]) == []


def test_dp103_split_in_both_branches_resets():
    """A key re-derived on EVERY path of an if/else is fresh afterwards
    (regression: branch merge unioned into the pre-branch state)."""
    src = ("import jax\n"
           "def f(key, c):\n"
           "    a = jax.random.uniform(key, (2,))\n"
           "    if c:\n"
           "        key, _ = jax.random.split(key)\n"
           "    else:\n"
           "        key, _ = jax.random.split(key)\n"
           "    return a + jax.random.normal(key, (2,))\n")
    assert analyze_source(src, logical_path="dorpatch_tpu/x.py",
                          select=["DP103"]) == []


def test_dp103_loop_invariant_key_is_reuse():
    """A loop-invariant key consumed every iteration draws fully correlated
    samples — the rule's core target (regression: single body walk)."""
    src = ("import jax\n"
           "def f(key):\n"
           "    out = []\n"
           "    for i in range(3):\n"
           "        out.append(jax.random.normal(key, (2,)))\n"
           "    return out\n")
    found = analyze_source(src, logical_path="dorpatch_tpu/x.py",
                           select=["DP103"])
    assert len(found) == 1 and found[0].line == 5


def test_dp103_unbound_split_does_not_refresh():
    """`use(key); jax.random.split(key); use(key)` still consumes the same
    key twice — only REBINDING refreshes (regression: any split call
    discarded the name's consumed state)."""
    src = ("import jax\n"
           "def f(key):\n"
           "    a = jax.random.normal(key, (2,))\n"
           "    sub = jax.random.split(key, 2)\n"
           "    b = jax.random.normal(key, (2,))\n"
           "    return a, b, sub\n")
    found = analyze_source(src, logical_path="dorpatch_tpu/x.py",
                           select=["DP103"])
    assert len(found) == 1 and found[0].line == 5


def test_dp101_checkout_named_dorpatch_tpu_keeps_tools_exempt():
    """A checkout directory named dorpatch_tpu must not pull tools/ into
    package scope (regression: any-component in_package)."""
    found = analyze_source(
        "print('x')\n",
        logical_path="home/u/dorpatch_tpu/tools/profile_calls.py",
        select=["DP101"])
    assert found == []
    found = analyze_source(
        "print('x')\n",
        logical_path="home/u/dorpatch_tpu/dorpatch_tpu/attack.py",
        select=["DP101"])
    assert [f.rule_id for f in found] == ["DP101"]


def test_dp106_quoted_string_annotation_counts_as_use():
    src = ('import numpy as np\n'
           'def f(x: "np.ndarray") -> "np.ndarray":\n'
           '    return x\n')
    assert analyze_source(src, select=["DP106"]) == []


def test_non_ascii_source_lints_under_any_locale(tmp_path):
    p = tmp_path / "uni.py"
    p.write_bytes("import jax\nk = jax.numpy.ones(3)  # комментарий ✓\n"
                  .encode("utf-8"))
    assert analyze_file(p) == []


def test_noqa_codes_case_insensitive_not_blanket():
    """`# noqa: dp104` suppresses DP104 only — it must not widen to a
    blanket suppression of other rules on the line (regression)."""
    src = ("import jax\n"
           "def f(k):\n"
           "    a = jax.random.uniform(k, (2,))\n"
           "    return a + jax.random.normal(jax.random.PRNGKey(0), (2,)) "
           "+ jax.random.normal(k, (2,))  # noqa: dp104\n")
    found = analyze_source(src, logical_path="dorpatch_tpu/x.py")
    assert [f.rule_id for f in found] == ["DP103"]  # DP104 gone, DP103 kept


def test_dp104_only_package_root_utils_exempt():
    seed_src = "import jax\nk = jax.random.PRNGKey(0)\n"
    assert analyze_source(seed_src, logical_path="dorpatch_tpu/utils.py",
                          select=["DP104"]) == []
    found = analyze_source(seed_src,
                           logical_path="dorpatch_tpu/models/utils.py",
                           select=["DP104"])
    assert [f.rule_id for f in found] == ["DP104"]


def test_cli_default_paths_work_from_any_cwd(tmp_path, monkeypatch):
    """The installed `dorpatch-lint` entry point lints the package even when
    invoked outside the checkout (regression: cwd-relative defaults)."""
    monkeypatch.chdir(tmp_path)
    from dorpatch_tpu.analysis.cli import default_paths

    paths = default_paths()
    assert paths and all(pathlib.Path(p).exists() for p in paths)
    assert cli_main([]) == 0  # shipped tree is clean from anywhere


def test_path_scoping_anchored_after_package_component():
    """A checkout prefix containing `tests`/`observe` must not disable the
    path-scoped rules for package files (regression: any-component match)."""
    seed_src = "import jax\nk = jax.random.PRNGKey(0)\n"
    # absolute-ish prefix .../tests/repo/dorpatch_tpu/attack.py: NOT exempt
    found = analyze_source(
        seed_src, logical_path="data/tests/repo/dorpatch_tpu/attack.py",
        select=["DP104"])
    assert [f.rule_id for f in found] == ["DP104"]
    # a prefix dir named observe must not silence DP101 package-wide
    found = analyze_source(
        "print('x')\n",
        logical_path="home/observe/repo/dorpatch_tpu/attack.py",
        select=["DP101"])
    assert [f.rule_id for f in found] == ["DP101"]
    # the real observe/ subpackage stays exempt
    found = analyze_source(
        "print('x')\n",
        logical_path="data/tests/repo/dorpatch_tpu/observe/console.py",
        select=["DP101"])
    assert found == []


# ---------- engine surface ----------

def test_rule_registry_stable_ids():
    # the AST wing = the DP1xx rules plus the DP5xx concurrency tier (which
    # rides the default lint gate; tests/test_concurrency.py owns its details)
    from dorpatch_tpu.analysis.concurrency import CONCURRENCY_RULE_IDS
    rules = all_rules()
    assert [r.id for r in rules] == list(RULE_IDS) + list(CONCURRENCY_RULE_IDS)
    assert all(r.description for r in rules)
    # fixable-offense listing contract: DP106 is the mechanical one
    assert [r.id for r in rules if r.fixable] == ["DP106"]


def test_finding_render_format():
    f = Finding(path="a/b.py", line=3, col=7, rule_id="DP104", message="m")
    assert f.render() == "a/b.py:3:7: DP104 m"


def test_shipped_tree_is_clean():
    """The acceptance gate: the package and tools lint clean — every
    violation was either fixed or suppressed with a reason."""
    findings = analyze_paths([REPO / "dorpatch_tpu", REPO / "tools"])
    assert findings == [], "\n".join(f.render() for f in findings)


# ---------- CLI ----------

def test_cli_exit_nonzero_on_fixtures(capsys):
    # the CLI sees the fixtures at their real tests/ path, so only the
    # path-independent rules fire here (DP101/DP104 scoping is covered by
    # the logical_path tests above) — still a guaranteed non-zero exit
    rc = cli_main([str(FIXTURES)])
    out = capsys.readouterr()
    assert rc == 1
    # listing contract: rule ID + file:line per finding
    assert "DP106" in out.out
    assert "dp106_pos.py:3:" in out.out
    assert "DP103" in out.out and "DP105" in out.out
    assert "finding(s)" in out.err


def test_cli_exit_zero_on_clean_file(tmp_path, capsys):
    p = tmp_path / "clean.py"
    p.write_text("VALUE = 1\n")
    assert cli_main([str(p)]) == 0
    assert capsys.readouterr().out == ""


def test_cli_select_and_fixable(tmp_path, capsys):
    p = tmp_path / "f.py"
    p.write_text("import json\nimport jax\nk = jax.random.PRNGKey(3)\n")
    rc = cli_main([str(p), "--select", "DP106"])
    out = capsys.readouterr().out
    assert rc == 1 and "DP106" in out and "DP104" not in out

    rc = cli_main([str(p), "--fixable"])
    out = capsys.readouterr().out
    assert rc == 1 and "DP106" in out and "DP104" not in out


def test_cli_unknown_rule_is_usage_error(capsys):
    assert cli_main(["--select", "DP999"]) == 2


def test_cli_list_rules(capsys):
    assert cli_main(["--list-rules"]) == 0
    out = capsys.readouterr().out
    for rid in RULE_IDS:
        assert rid in out


def test_module_entry_point_gate():
    """`python -m dorpatch_tpu.analysis dorpatch_tpu tools` — the exact
    run_tests.sh gate — exits 0 on the shipped tree; pointing it at the
    seeded fixtures exits 1. The lint path calls no jax API, so the
    subprocess never initializes a backend."""
    ok = subprocess.run(
        [sys.executable, "-m", "dorpatch_tpu.analysis", "dorpatch_tpu",
         "tools"], cwd=REPO, capture_output=True, text=True)
    assert ok.returncode == 0, ok.stdout + ok.stderr
    bad = subprocess.run(
        [sys.executable, "-m", "dorpatch_tpu.analysis",
         "tests/fixtures/analysis"], cwd=REPO, capture_output=True, text=True)
    assert bad.returncode == 1
    assert "DP10" in bad.stdout


# ---------- --format json (machine-readable findings) ----------

def test_cli_json_format(tmp_path, capsys):
    import json as json_lib

    p = tmp_path / "f.py"
    p.write_text("import json\nimport jax\nk = jax.random.PRNGKey(3)\n")
    rc = cli_main([str(p), "--format", "json"])
    out = capsys.readouterr().out.strip().splitlines()
    assert rc == 1 and len(out) == 2  # DP106 + DP104, one object per line
    recs = [json_lib.loads(line) for line in out]
    assert {r["rule"] for r in recs} == {"DP104", "DP106"}
    for r in recs:
        assert r["path"] == str(p)
        assert set(r) == {"rule", "path", "line", "col", "message",
                          "fixable"}
    (dp106,) = [r for r in recs if r["rule"] == "DP106"]
    assert dp106["line"] == 1 and dp106["fixable"] is True


def test_cli_json_clean_emits_nothing(tmp_path, capsys):
    p = tmp_path / "clean.py"
    p.write_text("VALUE = 1\n")
    assert cli_main([str(p), "--format", "json"]) == 0
    assert capsys.readouterr().out == ""


# ---------- DP106 --fix (mechanical rewriter) ----------

def _fix_tree(tmp_path):
    """A little tree seeded with every fixable shape: the DP106 fixture,
    a multi-alias single line, and a parenthesized multi-line import."""
    tree = tmp_path / "tree"
    tree.mkdir()
    (tree / "a.py").write_text(
        (FIXTURES / "dp106_pos.py").read_text(encoding="utf-8"),
        encoding="utf-8")
    (tree / "b.py").write_text(
        "import os, sys\n"
        "from typing import (\n"
        "    Dict,\n"
        "    List,\n"
        "    Optional,\n"
        ")\n"
        "from pathlib import Path  # noqa: DP106 — deliberate re-export\n"
        "def f(d: Dict[str, int]) -> Optional[int]:\n"
        "    return sys.getsizeof(d)\n",
        encoding="utf-8")
    return tree


def test_fix_then_relint_zero_dp106(tmp_path, capsys):
    tree = _fix_tree(tmp_path)
    assert cli_main([str(tree), "--fix"]) == 0
    assert "removed" in capsys.readouterr().err
    findings = analyze_paths([tree], select=["DP106"])
    assert findings == [], "\n".join(f.render() for f in findings)
    # used aliases and the noqa'd line survive
    b = (tree / "b.py").read_text(encoding="utf-8")
    assert "import sys" in b and "Dict, Optional" in b
    assert "from pathlib import Path  # noqa" in b
    assert "List" not in b and "import os" not in b
    a = (tree / "a.py").read_text(encoding="utf-8")
    assert "import json" not in a and "VALUE = 1" in a


def test_fix_idempotent(tmp_path, capsys):
    """fix twice == fix once, byte for byte (the acceptance criterion)."""
    tree = _fix_tree(tmp_path)
    assert cli_main([str(tree), "--fix"]) == 0
    once = {p.name: p.read_text(encoding="utf-8")
            for p in sorted(tree.glob("*.py"))}
    assert cli_main([str(tree), "--fix"]) == 0
    assert "nothing to fix" in capsys.readouterr().err
    twice = {p.name: p.read_text(encoding="utf-8")
             for p in sorted(tree.glob("*.py"))}
    assert once == twice


def test_fix_diff_dry_run(tmp_path, capsys):
    tree = _fix_tree(tmp_path)
    before = (tree / "a.py").read_text(encoding="utf-8")
    assert cli_main([str(tree), "--fix", "--diff"]) == 0
    out = capsys.readouterr()
    assert "-import json" in out.out and "would remove" in out.err
    assert (tree / "a.py").read_text(encoding="utf-8") == before  # unwritten


def test_fix_leaves_compound_statements_alone(tmp_path):
    """`import os; x = 1` shares its line with another statement — line
    surgery would clobber the neighbor, so the finding is left standing."""
    from dorpatch_tpu.analysis.fix import fix_source

    src = "import os; X = 1\n"
    fixed, n = fix_source(src, "t.py")
    assert n == 0 and fixed == src


def test_diff_without_fix_is_usage_error():
    assert cli_main(["--diff"]) == 2


def test_fix_plus_trace_is_usage_error():
    """`dorpatch-audit --fix` must not silently run the fixer instead of
    the audit the user asked for."""
    assert cli_main(["--trace", "--fix"]) == 2


def test_cross_wing_select_is_usage_error(tmp_path, capsys):
    """A trace-rule ID without --trace (or vice versa) would run ZERO
    rules and pass vacuously — it must exit 2, as a miswired CI gate
    should fail loudly (regression)."""
    p = tmp_path / "f.py"
    p.write_text("import json\n")
    assert cli_main([str(p), "--select", "DP201"]) == 2
    assert "add --trace" in capsys.readouterr().err
    assert cli_main(["--trace", "--select", "DP106"]) == 2
    assert "drop --trace" in capsys.readouterr().err


def test_fix_emptied_block_gets_pass(tmp_path):
    """Removing the only statement(s) of an indented block must leave
    `pass`, not a SyntaxError (regression: sole-statement function body /
    TYPE_CHECKING block)."""
    import ast as ast_mod

    from dorpatch_tpu.analysis.fix import fix_source

    src = ("def f():\n"
           "    import os\n"
           "\n"
           "if True:\n"
           "    import json\n"
           "    import sys\n"
           "\n"
           "VALUE = 1\n")
    fixed, n = fix_source(src, "t.py")
    assert n == 3
    ast_mod.parse(fixed)  # still valid Python
    assert "import" not in fixed
    assert fixed.count("    pass\n") == 2
    # idempotent: the pass-filled result re-lints clean
    fixed2, n2 = fix_source(fixed, "t.py")
    assert n2 == 0 and fixed2 == fixed
